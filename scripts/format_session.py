"""Format a chip_session log (JSON lines) into BASELINE.md-ready rows.

scripts/chip_session.sh appends one JSON line per measurement; this
groups them into markdown tables (training / serving / ablation /
variance) so transcription into BASELINE.md during a short tunnel
window is mechanical.

Usage: python scripts/format_session.py [chip_session_r5.log]
"""

import json
import sys


def main(path):
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rows.append(json.loads(line))
            except ValueError:
                continue
    if not rows:
        sys.exit(f"no JSON lines in {path}")

    def table(title, keep, cols):
        sel = [r for r in rows if keep(r)]
        if not sel:
            return
        print(f"\n### {title}\n")
        print("| " + " | ".join(cols) + " |")
        print("|" + "---|" * len(cols))
        for r in sel:
            print("| " + " | ".join(
                str(r.get(c, "")) for c in cols) + " |")

    table("Errors (fix before transcribing)",
          lambda r: "error" in r, ["metric", "error"])
    table("Training (bench_suite)",
          lambda r: r.get("unit") in ("samples/sec/chip",
                                      "tokens/sec/chip")
          and "step_ms" in r,
          ["metric", "value", "unit", "step_ms", "mfu"])
    table("Serving (bench_serving)",
          lambda r: "ms_per_token" in r and "ttft_p50_ms" not in r,
          ["metric", "value", "ms_per_token", "bw_util",
           "bw_util_measured", "batch"])
    table("Engine under load",
          lambda r: "ttft_p50_ms" in r,
          ["metric", "value", "offered_rps", "achieved_rps",
           "ms_per_request", "ttft_p50_ms", "ttft_p99_ms",
           "tpot_p50_ms", "tpot_p99_ms", "ttft_granularity_ms"])
    table("Ablations",
          lambda r: str(r.get("metric", "")).startswith("ablate_"),
          ["metric", "value", "unit"] + sorted(
              {k for r in rows
               if str(r.get("metric", "")).startswith("ablate_")
               for k in r if k not in ("metric", "value", "unit")}))
    table("Variance (n runs per config)",
          lambda r: "iqr_pct" in r,
          ["metric", "median", "min", "max", "iqr_pct", "spread_pct"])


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "chip_session_r5.log")
