#!/usr/bin/env python
"""Render an obs trace (JSONL) as a human-readable run report.

Phase breakdown (span totals/means/percentiles and share of the run's
wall span), latency histograms (bucket-interpolated p50/p95/p99),
counters/gauges, and the point-event timeline (chaos faults,
supervisor attempts, admission rejects) — reconstructed entirely from
one trace file written by ``distkeras_tpu.obs`` (docs/observability.md).

Usage::

    python scripts/obs_report.py run.jsonl
    python scripts/obs_report.py new.jsonl --compare base.jsonl
    python scripts/obs_report.py run.jsonl --json   # the report dict
    python scripts/obs_report.py --merge host0.jsonl host1.jsonl ...
    python scripts/obs_report.py serve.jsonl --request 3
    python scripts/obs_report.py router.jsonl replica*.jsonl --request 7

``--compare BASE`` prints a regression diff of NEW (the positional
trace) against BASE instead of the full report — per-phase total/mean
deltas, latency percentile deltas, counter drift.

``--request ID`` renders ONE serving request's waterfall instead:
submit -> queue wait -> admission (chunked-prefill spans included) ->
per-step token emissions with inter-token gaps -> finish, filtered
from the round-11 per-request ``request_id`` trace propagation.  With
SEVERAL traces (round 13) the records are wall-clock aligned first
and the waterfall follows a fleet-wide router id across processes:
the routing decision, any re-route hop, and each replica's engine
stages render as one story.

``--merge`` takes SEVERAL per-host traces (a multi-host run writes one
file per host per attempt) and renders ONE cross-host event timeline,
wall-clock aligned through each trace's meta anchor and tagged with
run id + host — how a coordinated cluster restart's fault/recovery
sequence reads as a single story (``--json`` emits it as one JSON
object per line, machine-readable; scripts/chaos_suite.py --cluster
prints exactly this).

Pure host-side file parsing: no jax import, safe anywhere.
"""

import argparse
import importlib
import json
import os
import sys
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_report_module():
    """Import distkeras_tpu.obs.report WITHOUT executing the package
    root's ``__init__`` (which imports jax/keras and the whole
    framework): register stub parent packages whose ``__path__``
    points at the real directories, then import the stdlib-only obs
    submodules through them.  Keeps this script runnable on a host
    with no jax installed — it only parses JSONL files."""
    for name, path in (
            ("distkeras_tpu", os.path.join(REPO, "distkeras_tpu")),
            ("distkeras_tpu.obs",
             os.path.join(REPO, "distkeras_tpu", "obs")),
            # obs/metrics.py (and friends) import the lock wrappers
            # from utils.locks — stdlib-only, but the utils package
            # root is NOT (it pulls the framework), so it gets a stub
            # parent too.
            ("distkeras_tpu.utils",
             os.path.join(REPO, "distkeras_tpu", "utils"))):
        if name not in sys.modules:
            mod = types.ModuleType(name)
            mod.__path__ = [path]
            sys.modules[name] = mod
    return importlib.import_module("distkeras_tpu.obs.report")


def main(argv):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", nargs="+",
                    help="obs JSONL trace(s); several only with --merge")
    ap.add_argument("--compare", metavar="BASE",
                    help="diff TRACE against this earlier trace "
                         "instead of printing the full report")
    ap.add_argument("--merge", action="store_true",
                    help="merge per-host traces into one cross-host "
                         "event timeline (wall-clock aligned)")
    ap.add_argument("--request", type=int, metavar="ID", default=None,
                    help="render one serving request's waterfall "
                         "(submit/admit/chunks/emits/finish) instead "
                         "of the full report")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON instead of text "
                         "(with --merge: one timeline entry per line)")
    ap.add_argument("--max-events", type=int, default=None,
                    help="timeline rows to print "
                         "(default 60; 200 with --merge)")
    args = ap.parse_args(argv)

    report = _load_report_module()

    if args.merge:
        rep = report.merge_traces(args.trace)
        if args.json:
            for e in rep["timeline"]:
                print(json.dumps(e, default=str))
        else:
            print(report.render_merged(
                rep, max_events=args.max_events
                if args.max_events is not None else 200))
        return 0
    if len(args.trace) != 1 and args.request is None:
        ap.error("several traces need --merge or --request")
    if args.request is not None:
        # Several traces: the cross-process fleet case (a routed
        # request's story spans the router's trace and each replica's)
        # — records are wall-clock aligned before the waterfall.
        records = report.merged_records(args.trace)
        wf = report.request_waterfall(records, args.request)
        if args.json:
            print(json.dumps(wf, indent=1, default=str))
        else:
            print(report.render_waterfall(wf))
        return 0 if wf.get("found") else 1
    rep = report.load_report(args.trace[0])
    if args.compare:
        base = report.load_report(args.compare)
        if args.json:
            print(json.dumps({"base": base, "new": rep}, indent=1,
                             default=str))
        else:
            print(report.render_compare(base, rep))
        return 0
    if args.json:
        print(json.dumps(rep, indent=1, default=str))
    else:
        print(report.render_report(
            rep, max_events=args.max_events
            if args.max_events is not None else 60))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
