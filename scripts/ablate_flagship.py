"""Flagship-MFU ablation: where do the missing percent go?

docs/perf_transformer.md attributes the long-config residual (~21% of
step time) to unfused elementwise/optimizer/CE-head bandwidth without
per-component numbers.  This script measures each candidate in
isolation on the current accelerator so the next optimization lands on
evidence, not attribution folklore:

- ``optimizer``: adamw update alone on the flagship param tree (m/v
  read-modify-write is pure HBM traffic; its share of the step bounds
  what any optimizer fusion could win).
- ``qkv``: the 3-einsum split QKV projection vs ONE fused
  ``[D, (H+2*KV)*K]`` einsum over the same weights (x is read once
  instead of three times; one MXU launch instead of three).  Forward
  and forward+backward.
- ``ce_head``: the vocab head fwd+bwd at the long-config shapes,
  unchunked vs ce_chunks=8 (the chunked scan trades logits
  materialization for serialization; the crossover is shape-dependent).
- ``trunk_vs_full``: full train step vs the same step with the CE head
  replaced by a mean over hidden states — the head's true share of the
  step, measured rather than modeled.

Prints one JSON line per measurement.  Results land in
docs/perf_transformer.md's ablation table.

Usage: python scripts/ablate_flagship.py [name ...]
"""

import json
import os
import sys
import time

os.environ.setdefault("KERAS_BACKEND", "jax")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _long_cfg():
    from distkeras_tpu.models import transformer as tfm

    return tfm.TransformerConfig(
        vocab_size=32768, d_model=1024, n_heads=8, n_layers=8, d_ff=4096,
        max_len=4097, dtype="bfloat16", remat=True)


def _time(fn, *args, iters=20, warmup=3):
    import jax

    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def ablate_optimizer(iters=20):
    import jax
    import optax
    from distkeras_tpu.models import transformer as tfm

    cfg = _long_cfg()
    params = tfm.init_params(jax.random.key(0), cfg)
    opt = optax.adamw(3e-4)
    opt_state = opt.init(params)
    grads = jax.tree.map(lambda p: p.astype(p.dtype), params)  # stand-in

    @jax.jit
    def apply(params, opt_state, grads):
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    dt = _time(apply, params, opt_state, grads, iters=iters)
    n = sum(int(x.size) for x in jax.tree.leaves(params))
    return {"metric": "ablate_optimizer_only", "value": round(dt * 1e3, 3),
            "unit": "ms", "params": n}


def ablate_qkv(b=8, s=4096, iters=20):
    import jax
    import jax.numpy as jnp
    import numpy as np

    cfg = _long_cfg()
    d = cfg.d_model
    h, kv, hd = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    rng = np.random.default_rng(0)
    x = jax.device_put(rng.normal(0, 1, (b, s, d)).astype(np.float32)
                       .astype(jnp.bfloat16))
    wq = jax.device_put(rng.normal(0, 0.02, (d, h, hd))
                        .astype(np.float32).astype(jnp.bfloat16))
    wk = jax.device_put(rng.normal(0, 0.02, (d, kv, hd))
                        .astype(np.float32).astype(jnp.bfloat16))
    wv = jax.device_put(rng.normal(0, 0.02, (d, kv, hd))
                        .astype(np.float32).astype(jnp.bfloat16))
    # Pre-fused layout (what a fused_qkv param layout would store).
    wf = jax.device_put(np.concatenate(
        [np.asarray(wq.reshape(d, -1), np.float32),
         np.asarray(wk.reshape(d, -1), np.float32),
         np.asarray(wv.reshape(d, -1), np.float32)], axis=1)
        .astype(jnp.bfloat16))

    def split(x, wq, wk, wv):
        q = jnp.einsum("bsd,dhk->bshk", x, wq)
        k = jnp.einsum("bsd,dhk->bshk", x, wk)
        v = jnp.einsum("bsd,dhk->bshk", x, wv)
        return q.sum() + k.sum() + v.sum()

    def fused(x, wf):
        qkv = jnp.einsum("bsd,de->bse", x, wf)
        q = qkv[..., :h * hd].reshape(b, s, h, hd)
        k = qkv[..., h * hd:(h + kv) * hd].reshape(b, s, kv, hd)
        v = qkv[..., (h + kv) * hd:].reshape(b, s, kv, hd)
        return q.sum() + k.sum() + v.sum()

    out = {"metric": "ablate_qkv_projection", "unit": "ms",
           "shape": f"b{b} s{s} d{d} h{h} kv{kv}"}
    out["split_fwd"] = round(_time(jax.jit(split), x, wq, wk, wv, iters=iters) * 1e3, 3)
    out["fused_fwd"] = round(_time(jax.jit(fused), x, wf, iters=iters) * 1e3, 3)
    out["split_fwdbwd"] = round(_time(
        jax.jit(jax.grad(split, argnums=(1, 2, 3))), x, wq, wk, wv,
        iters=iters) * 1e3, 3)
    out["fused_fwdbwd"] = round(_time(
        jax.jit(jax.grad(fused, argnums=1)), x, wf, iters=iters)
        * 1e3, 3)
    out["value"] = round(out["split_fwdbwd"] / out["fused_fwdbwd"], 3)
    return out


def ablate_ce_head(b=8, s=4096, iters=20):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from distkeras_tpu.models import transformer as tfm

    cfg = _long_cfg()
    rng = np.random.default_rng(0)
    hidden = jax.device_put(rng.normal(0, 1, (b, s, cfg.d_model))
                            .astype(np.float32).astype(jnp.bfloat16))
    emb = jax.device_put(rng.normal(0, 0.02, (cfg.vocab_size, cfg.d_model))
                         .astype(np.float32).astype(jnp.bfloat16))
    targets = jax.device_put(rng.integers(
        0, cfg.vocab_size, (b, s)).astype(np.int32))

    def head_loss(emb, hidden, chunks):
        if chunks > 1:
            nll, _ = tfm.chunked_softmax_xent(hidden, emb, targets,
                                              chunks)
            return nll
        logits = jnp.einsum("bsd,vd->bsv", hidden, emb).astype(
            jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(
            logp, targets[..., None], axis=-1).mean()

    out = {"metric": "ablate_ce_head", "unit": "ms",
           "shape": f"b{b} s{s} v{cfg.vocab_size}"}
    for chunks in (0, 4, 8, 16):
        f = jax.jit(jax.grad(
            lambda e, h, c=chunks: head_loss(e, h, c)))
        out[f"chunks{chunks}_fwdbwd"] = round(
            _time(f, emb, hidden, iters=iters) * 1e3, 3)
    out["value"] = out["chunks8_fwdbwd"]
    return out


def ablate_trunk_vs_full(b=8, s=4096, iters=10):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from distkeras_tpu.models import transformer as tfm

    cfg = _long_cfg()
    params = tfm.init_params(jax.random.key(0), cfg)
    opt = optax.adamw(3e-4)
    rng = np.random.default_rng(0)
    tokens = jax.device_put(rng.integers(
        0, cfg.vocab_size, (b, s + 1)).astype(np.int32))

    full = jax.jit(tfm.make_train_step(cfg, opt), donate_argnums=0)

    def trunk_loss(params, toks, cfg_, attention_fn=None, apply_fn=None,
                   dropout_rng=None, hidden_fn=None, segment_ids=None):
        hid, aux = tfm.apply_hidden(params, toks[:, :-1], cfg_,
                                    attention_fn)
        return jnp.mean(hid.astype(jnp.float32) ** 2) + aux

    trunk = jax.jit(tfm.make_train_step(cfg, opt, loss_fn=trunk_loss),
                    donate_argnums=0)

    def run(step):
        carry = (tfm.init_params(jax.random.key(0), cfg),)
        carry = (carry[0], opt.init(carry[0]))
        for _ in range(3):
            carry, loss = step(carry, tokens)
        float(loss)
        t0 = time.perf_counter()
        n = iters
        for _ in range(n):
            carry, loss = step(carry, tokens)
        float(loss)
        return (time.perf_counter() - t0) / n

    t_full, t_trunk = run(full), run(trunk)
    return {"metric": "ablate_trunk_vs_full", "unit": "ms",
            "full_ms": round(t_full * 1e3, 2),
            "trunk_only_ms": round(t_trunk * 1e3, 2),
            "head_share": round(1 - t_trunk / t_full, 4),
            "value": round(t_full * 1e3, 2)}


ABLATIONS = {
    "optimizer": ablate_optimizer,
    "qkv": ablate_qkv,
    "ce_head": ablate_ce_head,
    "trunk_vs_full": ablate_trunk_vs_full,
}


def main(names):
    import jax

    unknown = set(names) - set(ABLATIONS)
    if unknown:
        sys.exit(f"unknown ablation(s) {sorted(unknown)}; "
                 f"choose from {sorted(ABLATIONS)}")
    print(f"# backend={jax.default_backend()} device={jax.devices()[0]}",
          file=sys.stderr)
    for name in names or ABLATIONS:
        try:
            print(json.dumps(ABLATIONS[name]()))
        except Exception as e:
            print(json.dumps({"metric": name, "error": repr(e)[:200]}))


if __name__ == "__main__":
    main(sys.argv[1:])
