#!/usr/bin/env python
"""Chaos suite: run the full fault matrix against the resilience
subsystem on CPU and report a pass/fail table.

The deterministic, seedable end-to-end exercise of every failure mode
the subsystem claims to survive (docs/resilience.md):

- kill-at-step-N (exception and SIGTERM) under a Supervisor -> final
  parameters allclose to an uninterrupted run, resumed loss trajectory
  bit-for-bit;
- checkpoint-save faults -> retried by the Supervisor;
- serving deadlines -> expired requests never occupy a lane, running
  lanes evict with structured timeouts;
- bounded-queue backpressure -> QueueFull past capacity, queue drains
  as lanes free;
- speculative draft fault -> fallback decode completes every request
  (greedy: exact solo-generate parity);
- drain-then-shutdown -> no request is silently dropped.

The whole matrix runs under an obs telemetry session
(docs/observability.md): every injected fault, Supervisor attempt and
backoff lands in a JSONL event trace, and the suite ends with a
machine-readable **fault/recovery timeline** (one JSON object per
line) reconstructed from that trace — no log parsing.  ``--trace``
keeps the trace file for ``scripts/obs_report.py``.

``--cluster`` runs the MULTI-HOST ladder instead (PR 5): two OS
processes join one jax.distributed runtime and train under per-host
Supervisors wrapped by cluster drivers; chaos then kills one host
mid-training (``kill``), wedges its heartbeat writer (``stall``), or
partitions it (``drop``) — the survivor's collective watchdog fires
within the configured window, both hosts tear down and re-init
jax.distributed under a new cluster epoch, training resumes from the
cluster-consistent checkpoint, and the final weights must be
bit-for-bit identical to an uninterrupted two-host run.  Every
attempt's obs trace is merged (obs_report --merge machinery) into ONE
cross-host fault/recovery timeline, printed as JSON lines.

Round 13 adds the SERVING leg of ``--cluster``: ``serve_kill`` runs
two engine-replica processes (PagedBatcher behind an EngineEndpoint,
heartbeats + federation-published telemetry, lock sanitizer on) under
a cache-aware Router in the suite process, SIGKILLs one replica
mid-stream, and asserts drain-and-reroute completes every accepted
request, the dead replica's series drop out of ``/metrics/cluster``
and return after its restart, the merged timeline shows the re-route
hop, and every lock report is clean.

Round 17 adds ``serve_kill_prefill``, the DISAGGREGATED serving leg:
a role-labeled fleet (a ``prefill``-specialized and a ``decode``-
specialized replica process) serves 2-block prompts through the
prefill->ship->adopt hop, and chaos SIGKILLs the PREFILL replica
mid-transfer.  The router must fall back to plain routing (zero lost
requests), the decode replica's refcounted slab must drain to empty
once the unpins relay (shipped blocks leak nothing), the hop must
resume after the coordinated restart, and every lock ledger must be
clean.

Round 16 adds the ASYNC-TIER legs (docs/async.md): ``async_stall``
wedges a simulated host's heartbeat writer mid-training under the
bounded-staleness plane and asserts the fleet slows by less than tau
round-lengths (watchdog eviction, survivors at full quota — never a
full stall), printing the EpochStore/heartbeat membership audit
trail; ``async_kill_push`` kills a host at the ``cluster.push`` probe
and asserts the in-flight delta dropped cleanly with no torn merge
(pushes == merges == center version).

Round 20 adds the TRAIN→SERVE legs (docs/serving_guide.md):
``train_kill_push`` SIGKILLs the trainer process between a snapshot
version's bucket writes and its atomic manifest rename — the serving
fleet must keep serving the last complete version, the torn snapshot
must be refused (even when the version pointer names it), and the
canary tick must abort cleanly with zero lost requests;
``canary_bad_push`` publishes NaN weights with valid checksums — the
canary's logit-drift probe must trip, the fleet must roll back to the
promoted version (straddling requests all finish, tokens bit-identical
post-rollback), and the rejected version must be quarantined.

Usage: python scripts/chaos_suite.py [--seed N] [--kill-rounds 3,7,12]
                                     [--trace chaos.jsonl]
       python scripts/chaos_suite.py --cluster [--scenarios kill,stall]
       python scripts/chaos_suite.py --cluster --scenarios serve_kill
"""

import argparse
import os
import sys
import tempfile
import threading

os.environ.setdefault("KERAS_BACKEND", "jax")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import distkeras_tpu as dk
from distkeras_tpu.models import transformer as tfm
from distkeras_tpu.models.generate import generate
from distkeras_tpu.resilience import (FaultPlan, QueueFull, Supervisor,
                                       chaos)
from distkeras_tpu.serving import ContinuousBatcher, SpeculativeBatcher

CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=32)
DRAFT = tfm.TransformerConfig(vocab_size=64, d_model=16, n_heads=2,
                              n_layers=1, d_ff=32, max_len=32)


def _mlp_data(seed):
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tests"))
    from helpers import make_blobs, make_mlp

    x, y = make_blobs(n=128, seed=seed)
    return make_mlp, dk.Dataset.from_arrays(x, y)


COMMON = dict(loss="sparse_categorical_crossentropy",
              worker_optimizer="sgd", learning_rate=0.05,
              batch_size=16, num_epoch=2)  # 16 rounds


def check_kill_resume(seed, kill_round, via_signal):
    make_mlp, ds = _mlp_data(seed)
    straight = dk.SingleTrainer(make_mlp(), **COMMON)
    ref = straight.train(ds)
    ref_w = [np.asarray(w) for w in ref.get_weights()]
    with tempfile.TemporaryDirectory() as d:
        t = dk.SingleTrainer(make_mlp(), checkpoint_dir=os.path.join(d, "c"),
                             checkpoint_every=1, checkpoint_backend="pickle",
                             **COMMON)
        sup = Supervisor(t, max_retries=2, backoff=0.0, max_backoff=0.0,
                         jitter=0.0, seed=seed)
        plan = FaultPlan(seed)
        if via_signal:
            plan.preempt("train.round", at=kill_round, via_signal=True)
        else:
            plan.fail("train.round", at=kill_round)
        with plan:
            out = sup.run(ds)
        for a, b in zip(ref_w, [np.asarray(w) for w in out.get_weights()]):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
        # Exception kill dies BEFORE round N commits -> resume replays
        # round N; graceful SIGTERM checkpoints round N synchronously
        # before raising -> resume continues at N + 1.
        resume_at = kill_round if via_signal else kill_round - 1
        assert t.history == straight.history[resume_at:], \
            "resumed loss trajectory diverged from the straight run"
        assert len(sup.attempts) == 2 and sup.attempts[-1].outcome == "ok"


def check_checkpoint_fault_retry(seed):
    make_mlp, ds = _mlp_data(seed)
    with tempfile.TemporaryDirectory() as d:
        t = dk.SingleTrainer(make_mlp(), checkpoint_dir=os.path.join(d, "c"),
                             checkpoint_every=1, checkpoint_backend="pickle",
                             **COMMON)
        sup = Supervisor(t, max_retries=2, backoff=0.0, max_backoff=0.0,
                         jitter=0.0, seed=seed)
        with FaultPlan(seed).fail("checkpoint.save", at=5):
            sup.run(ds)
        assert sup.attempts[0].outcome == "fault"
        assert sup.attempts[-1].outcome == "ok"


def check_serving_deadlines(seed):
    rng = np.random.default_rng(seed)
    params = tfm.init_params(jax.random.key(seed), CFG)
    t = [0.0]
    eng = ContinuousBatcher(params, CFG, lanes=2, max_queue=2,
                            clock=lambda: t[0])
    rid = eng.enqueue(rng.integers(0, 64, (4,)), 5, ttl=0.0)
    res = eng.take(rid)
    assert res.timed_out and eng.free_lanes() == [0, 1], \
        "expired request occupied a lane"
    lane = eng.submit(rng.integers(0, 64, (4,)).astype(np.int32), 10,
                      ttl=5.0)
    assert lane is not None
    eng.step()
    t[0] = 6.0
    eng.step()
    (res,) = eng.results().values()
    assert res.timed_out and len(res.generated) >= 1
    assert len(eng.free_lanes()) == 2, "timed-out lane was not evicted"


def check_backpressure(seed):
    rng = np.random.default_rng(seed)
    params = tfm.init_params(jax.random.key(seed), CFG)
    eng = ContinuousBatcher(params, CFG, lanes=1, max_queue=1)
    r1 = eng.enqueue(rng.integers(0, 64, (3,)), 3)
    r2 = eng.enqueue(rng.integers(0, 64, (3,)), 3)  # queued
    try:
        eng.enqueue(rng.integers(0, 64, (3,)), 3)
        raise AssertionError("queue overflow did not raise QueueFull")
    except QueueFull:
        pass
    res = eng.shutdown()
    assert res[r1].ok and res[r2].ok, "queued request lost"


def check_heartbeat_fault_kinds(seed):
    """The cluster fault kinds, single-process: a ``drop`` rule
    (partition) suppresses beats until peers see the host stale; beats
    flow again when the plan lifts."""
    import tempfile as _tf

    from distkeras_tpu.resilience.health import (HealthMonitor,
                                                  HeartbeatWriter,
                                                  read_beat)

    d = _tf.mkdtemp(prefix="chaos_hb_")
    w = HeartbeatWriter(d, host=1, interval=0.05)
    mon = HealthMonitor(d, host=0, num_hosts=2, window=60.0, grace=0.0)
    with FaultPlan(seed).drop("cluster.heartbeat", times=None):
        w.beat_once()
    assert read_beat(d, 1) is None, "partitioned beat was published"
    assert mon.stale_peers() == [1], "partitioned host not stale"
    w.beat_once()
    assert read_beat(d, 1)["host"] == 1, "beats did not resume"
    assert mon.stale_peers() == [], "fresh beat still read as stale"


def check_draft_fault_fallback(seed):
    rng = np.random.default_rng(seed)
    tp = tfm.init_params(jax.random.key(seed), CFG)
    dp = tfm.init_params(jax.random.key(seed + 9), DRAFT)
    prompt = rng.integers(0, 64, (5,)).astype(np.int32)
    eng = SpeculativeBatcher(tp, dp, CFG, DRAFT, lanes=2, n_draft=3)
    lane = eng.submit(prompt, 8)
    eng.step()
    with FaultPlan(seed).fail("serving.draft"):
        eng.step()
    assert eng.degraded, "draft fault did not degrade the engine"
    while lane in eng.running():
        eng.step()
    np.testing.assert_array_equal(
        eng.drain(lane), np.asarray(generate(tp, prompt[None], CFG, 8))[0])


# --------------------------------------------------- multi-host ladder
#
# The child below is ONE program started identically on every host of
# the cluster (deploy.py's SPMD model): join the epoch's
# jax.distributed runtime under a ClusterMember (heartbeats out,
# collective watchdog in), train the shared tiny LM under a per-host
# Supervisor with a SHARED orbax checkpoint store, and let chaos kill/
# stall/partition host 1 during epoch 0 only.  Epoch 1 must resume
# from the cluster-consistent step and finish; host 0 then writes the
# final weights for the bit-for-bit comparison.

CLUSTER_CHILD = '''
import os, sys
os.environ["KERAS_BACKEND"] = "jax"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
# Every cluster child runs under the LOCK SANITIZER (round 12): all
# engine/obs/resilience locks are instrumented, and the child emits a
# per-host locks.report event into its trace — the ladder fails on
# any recorded violation.  Must be set before distkeras imports.
os.environ.setdefault("DKT_LOCK_SANITIZER", "1")
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})

# Join the EPOCH-STAMPED runtime before anything touches a device
# (jax.distributed.initialize must precede the first computation, and
# importing the framework runs keras backend init): coordinator port =
# base + epoch, so a stale epoch's half-dead runtime cannot be
# rejoined.  Until the member starts beating below, liveness is the
# drivers' job (their launch grace covers import + join).
host = int(os.environ["DKT_CLUSTER_HOST"])
epoch = int(os.environ["DKT_CLUSTER_EPOCH"])
try:  # gloo: cross-process CPU collectives (mesh.enable_cpu_collectives)
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except Exception:
    pass
jax.distributed.initialize(
    "localhost:%d" % (int(os.environ["DKT_CLUSTER_BASE_PORT"]) + epoch),
    num_processes={nhosts}, process_id=host)

from distkeras_tpu import obs
from distkeras_tpu.resilience import FaultPlan, Supervisor, cluster

member = cluster.member_from_env()
trace = os.path.join({tracedir!r}, f"host{{host}}.e{{epoch}}.jsonl")
# Live telemetry plane (round 11): every host serves /metrics etc. on
# an ephemeral port, published into the coord dir's telemetry/ ledger
# via the DKT_CLUSTER_* env contract, so /metrics/cluster on ANY host
# federates the fleet; the rolling SLO rule makes the ladder double as
# a latency-regression canary (a breach event in any host's trace
# fails the ladder unless expected).
obs.enable(trace_path=trace, serve_port=0,
           slo_rules=[obs.SloRule("train.step_s", percentile=0.99,
                                  threshold=60.0, window_s=30.0)],
           slo_tick_s=0.25)
obs.event("cluster.child", host=host, epoch=epoch, phase="start")
member.start()
assert jax.process_count() == {nhosts}, jax.process_count()

import numpy as np
import distkeras_tpu as dk
from distkeras_tpu.models.transformer import TransformerConfig

rng = np.random.default_rng({seed})
tokens = rng.integers(0, 64, (64, 17)).astype(np.int32)
cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=2,
                        d_ff=64, max_len=17)
t = dk.LMTrainer(cfg, optimizer="sgd", learning_rate=0.05, batch_size=16,
                 num_epoch={num_epoch}, checkpoint_dir={ckdir!r},
                 checkpoint_every=1)
sup = Supervisor(t, max_retries=1, backoff=0.0, max_backoff=0.0,
                 jitter=0.0)

plan = None
spec = os.environ.get("DKT_CHAOS", "")
if spec and epoch == 0:
    kind, site, at = spec.split(":")
    plan = FaultPlan({seed})
    if kind == "kill":
        plan.kill(site, at=int(at))
    elif kind == "stall":
        plan.delay(site, seconds=3600.0, at=int(at))
    elif kind == "drop":
        plan.drop(site, at=None, times=None)
    else:
        raise ValueError(f"unknown chaos kind {{kind}}")
    plan.__enter__()

params = sup.run(tokens[host::{nhosts}])
obs.event("cluster.child", host=host, epoch=epoch, phase="trained",
          rounds=len(t.history))
from distkeras_tpu.utils import locks as _locks
_rep = _locks.lock_report()
obs.event("locks.report", host=host, epoch=epoch, **_rep)
assert not _rep["violations"], (
    "lock sanitizer violations on host %d:\\n" % host
    + "\\n".join(v.format() for v in _locks.violations()))
if host == 0:
    flat = {{"/".join(map(str, p)): np.asarray(v)
            for p, v in jax.tree_util.tree_flatten_with_path(params)[0]}}
    np.savez({out!r}, losses=np.asarray(t.history), **flat)
member.complete()
obs.disable()
print("HOST", host, "epoch", epoch, "DONE", flush=True)
'''


def _free_port():
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


# ------------------------------------------------ router serve ladder
#
# The round-13 serving leg of --cluster: TWO engine-replica processes
# (each: a PagedBatcher behind an EngineEndpoint, heartbeats, the
# live telemetry server federation-published, lock sanitizer on), a
# cache-aware Router in THIS process streaming requests at them, and
# a SIGKILL of replica 1 mid-stream.  Drain-and-reroute must complete
# every accepted request, the dead replica's series must drop out of
# /metrics/cluster and return after the restart, the merged timeline
# must show the re-route hop, and every lock report must be clean.

ROUTER_CHILD = '''
import os, sys, time
os.environ["KERAS_BACKEND"] = "jax"
os.environ.setdefault("DKT_LOCK_SANITIZER", "1")
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})

host = int(os.environ["DKT_CLUSTER_HOST"])
from distkeras_tpu import obs
from distkeras_tpu.resilience.health import HeartbeatWriter

trace = os.path.join({tracedir!r},
                     "replica%d.%d.jsonl" % (host, os.getpid()))
# serve_port=0: /metrics etc on an ephemeral port, published into the
# coord dir's telemetry/ ledger via the DKT_CLUSTER_* env — what the
# federation scraper proves drops and returns across the kill.
obs.enable(trace_path=trace, serve_port=0)
hb = HeartbeatWriter(os.path.join(os.environ["DKT_CLUSTER_DIR"], "hb"),
                     host, interval=0.2).start()

import numpy as np
from distkeras_tpu.models import transformer as tfm
from distkeras_tpu.serving import PagedBatcher
from distkeras_tpu.serving.router import EngineEndpoint

cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=128,
                            rope=True)
params = tfm.init_params(jax.random.key({seed}), cfg)
eng = PagedBatcher(params, cfg, lanes=2, block=8, n_blocks=33,
                   max_queue=16, prompt_buckets=(16,))
# Fixed port (parent-chosen): a restarted replica binds the SAME
# address, so the router's handle revives on the next health probe.
# DKT_SERVE_ROLE labels the endpoint for the round-17 disaggregated
# leg (prefill/decode split); unset = generalist (serve_kill).
role = os.environ.get("DKT_SERVE_ROLE") or None
ep = EngineEndpoint(eng, port=int(os.environ["DKT_SERVE_PORT"]),
                    role=role)
ep.start(step=True)
obs.event("router_child", host=host, phase="serving", port=ep.port,
          role=role or "generalist")
print("REPLICA", host, "UP", ep.port, flush=True)
stop = os.path.join(os.environ["DKT_CLUSTER_DIR"], "stop%d" % host)
while not os.path.exists(stop):
    time.sleep(0.1)
ep.stop()
# Refcounted-block leak ledger: with every request taken and every
# unpin relayed, an idle paged engine holds ZERO blocks (resident
# stem hashes are content-addressed bookkeeping, not held blocks).
_st = eng.allocator.stats()
obs.event("serving.allocator", host=host, role=role or "generalist",
          **_st)
if os.environ.get("DKT_ASSERT_IDLE_ALLOC"):
    assert _st["used"] == 0, "leaked KV blocks at exit: %r" % (_st,)
from distkeras_tpu.utils import locks as _locks
_rep = _locks.lock_report()
obs.event("locks.report", host=host, **_rep)
assert not _rep["violations"], (
    "lock sanitizer violations on replica %d:\\n" % host
    + "\\n".join(v.format() for v in _locks.violations()))
hb.mark_done()
obs.disable()
print("REPLICA", host, "DONE", flush=True)
'''


def run_router_kill_scenario(seed, workdir, n_req=12, kill_after=4):
    """The kill-a-replica-mid-stream leg.  Returns the number of
    failed assertions (0 = green), printing the same PASS/FAIL +
    timeline blocks as the training scenarios."""
    import glob
    import json
    import urllib.request

    import numpy as np

    from distkeras_tpu import obs
    from distkeras_tpu.obs.report import merge_traces
    from distkeras_tpu.serving.router import HttpReplica, Router
    from distkeras_tpu.utils import locks

    print("== cluster scenario: serve_kill (router drain-and-reroute)"
          " ==", flush=True)
    base = os.path.join(workdir, "serve_kill")
    coord = os.path.join(base, "coord")
    tracedir = os.path.join(base, "traces")
    os.makedirs(tracedir, exist_ok=True)
    os.makedirs(coord, exist_ok=True)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(base, "replica.py")
    with open(script, "w", encoding="utf-8") as f:
        f.write(ROUTER_CHILD.format(repo=repo, tracedir=tracedir,
                                    seed=seed))
    ports = [_free_port(), _free_port()]

    def launch(h):
        import subprocess

        env = {**os.environ,
               "DKT_CLUSTER_DIR": coord,
               "DKT_CLUSTER_HOST": str(h),
               "DKT_CLUSTER_NHOSTS": "2",
               "DKT_CLUSTER_WINDOW": "2.0",
               "DKT_SERVE_PORT": str(ports[h])}
        return subprocess.Popen([sys.executable, script], env=env)

    def wait_port(h, deadline):
        import time as _time

        while True:
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{ports[h]}/healthz",
                    timeout=1.0).read()
                return
            except Exception:  # noqa: BLE001 — still starting
                assert _time.time() < deadline, \
                    f"replica {h} never came up on port {ports[h]}"
                _time.sleep(0.2)

    import time as _time

    locks.enable_sanitizer()
    children = [launch(0), launch(1)]
    scraper = _FederationScraper(coord)
    scraper.start()
    rng = np.random.default_rng(seed)
    router_trace = os.path.join(tracedir, "router.jsonl")
    failures = 0
    sess = None
    try:
        wait_port(0, _time.time() + 180)
        wait_port(1, _time.time() + 180)
        sess = obs.enable(trace_path=router_trace)
        router = Router(
            [HttpReplica("host0", f"127.0.0.1:{ports[0]}"),
             HttpReplica("host1", f"127.0.0.1:{ports[1]}")],
            policy="least_loaded", health_interval=0.3)
        stem = rng.integers(0, 64, (8,)).astype(np.int32)
        prompts = [np.concatenate(
            [stem, rng.integers(0, 64, (4,)).astype(np.int32)])
            for _ in range(n_req)]

        def serve_wave(wave_rids, deadline):
            done = set()
            while len(done) < len(wave_rids):
                assert _time.time() < deadline, (
                    f"serve_kill stalled: {len(done)}/"
                    f"{len(wave_rids)} done, "
                    f"up={router.replicas_up()}")
                router.pump()
                for r in wave_rids:
                    if r not in done and router.poll(r) is not None:
                        done.add(r)
                _time.sleep(0.05)

        # Wave 1: short requests, both replicas serving (also warms
        # every program outside the kill window).
        first = [router.enqueue(p, 8) for p in prompts[:kill_after]]
        serve_wave(first, _time.time() + 180)
        # Wave 2: LONG decodes, and the SIGKILL lands immediately
        # after their acceptance — the victim is guaranteed to hold
        # accepted, unfinished requests when it dies (enqueue is
        # synchronous: an id returned means the replica accepted).
        rest = [router.enqueue(p, 100) for p in prompts[kill_after:]]
        on_victim = sum(
            1 for r in rest
            if router._requests[r].replica == "host1")
        children[1].kill()
        children[1].wait(timeout=30)
        print(f"  killed replica 1 holding {on_victim} accepted "
              "request(s)", flush=True)
        assert on_victim >= 1, (
            "least-loaded spread put nothing on the victim — the "
            "kill exercised no reroute")
        serve_wave(rest, _time.time() + 300)
        rids = first + rest
        results = {r: router.take(r) for r in rids}
        lost = [r for r, v in results.items() if not v.ok]
        assert not lost, (
            f"accepted requests lost across the kill: "
            f"{[(r, results[r].status) for r in lost]}")
        snap = sess.registry.snapshot()
        n_reroutes = sum(
            s.get("value", 0) for s in
            snap.get("router.reroutes", {}).get("series", []))
        assert n_reroutes >= 1, \
            "the kill produced no drain-and-reroute"
        # Coordinated-restart half: the SAME address comes back and
        # the router's handle revives on a health probe.
        children[1] = launch(1)
        wait_port(1, _time.time() + 180)
        deadline = _time.time() + 60
        while "host1" not in router.replicas_up():
            assert _time.time() < deadline, \
                "restarted replica never rejoined the router"
            router.pump()
            _time.sleep(0.1)
        extra = router.enqueue(prompts[0], 4)
        deadline = _time.time() + 120
        while router.poll(extra) is None:
            assert _time.time() < deadline, \
                "post-restart request never finished"
            router.pump()
            _time.sleep(0.05)
        assert router.take(extra).ok
        print(f"  PASS  cluster/serve_kill: {n_req} streamed + 1 "
              f"post-restart request ok, {int(n_reroutes)} "
              "reroute(s), replica rejoined", flush=True)
    except Exception as e:  # noqa: BLE001 — report the ladder
        failures += 1
        print(f"  FAIL  cluster/serve_kill: {type(e).__name__}: {e}")
    finally:
        if sess is not None:
            obs.disable()
        for h in (0, 1):
            with open(os.path.join(coord, f"stop{h}"), "w"):
                pass
        for c in children:
            try:
                c.wait(timeout=60)
            except Exception:  # noqa: BLE001 — force it down
                c.kill()
        samples = scraper.stop()

    # Federation: both hosts seen, the killed one's series drop out,
    # then return after the restart.
    hosts_seen = [up for _, up in samples]
    try:
        both = next(i for i, up in enumerate(hosts_seen)
                    if up >= {0, 1})
        gone = next(i for i in range(both, len(hosts_seen))
                    if 0 in hosts_seen[i] and 1 not in hosts_seen[i])
        assert any(up >= {0, 1} for up in hosts_seen[gone:]), (
            "killed replica's series never returned to "
            "/metrics/cluster")
    except (StopIteration, AssertionError) as e:
        failures += 1
        print(f"  FAIL  cluster/serve_kill federation: "
              f"{type(e).__name__}: {e} (samples: {hosts_seen[:30]})")

    # Merged cross-process timeline: the re-route hop must be visible,
    # and every completing process must report a clean lock ledger.
    traces = sorted(glob.glob(os.path.join(tracedir, "*.jsonl")))
    merged = merge_traces(traces)
    print("--- cross-process serve timeline (serve_kill, JSONL) ---")
    for e in merged["timeline"]:
        if e["name"].startswith(("router", "locks", "serving.finish")):
            print(json.dumps({"t": round(e["t"], 4),
                              "host": e["host"], "event": e["name"],
                              **e["fields"]}))
    if not any(e["name"] == "router.reroute"
               for e in merged["timeline"]):
        failures += 1
        print("  FAIL  cluster/serve_kill: no router.reroute hop in "
              "the merged timeline")
    reports = [e for e in merged["timeline"]
               if e["name"] == "locks.report"]
    hosts_reported = {e["fields"].get("host") for e in reports}
    if not hosts_reported >= {0, 1}:
        failures += 1
        print(f"  FAIL  cluster/serve_kill: lock report missing for "
              f"replica(s) {sorted({0, 1} - hosts_reported)}")
    bad = [e for e in reports if e["fields"].get("violations")]
    if bad:
        failures += 1
        print("  FAIL  cluster/serve_kill: lock sanitizer "
              "violation(s) in replica report(s)")
    if locks.violation_count():
        failures += 1
        print("  FAIL  cluster/serve_kill: router-process lock "
              "sanitizer violations:")
        for v in locks.violations():
            print("  VIOLATION " + v.format())
    return failures


def run_router_prefill_kill_scenario(seed, workdir, n_wave1=4,
                                     n_wave2=6):
    """The round-17 disaggregated leg: a role-labeled fleet (host0 =
    ``decode``-specialized, host1 = ``prefill``-specialized) serving
    2-block prompts through the prefill->ship->adopt hop, and a
    SIGKILL of the PREFILL replica mid-transfer.  The router must fall
    back to plain routing (every accepted request completes on the
    decode replica — zero lost), the refcounted shipped blocks must
    leak NOTHING on the decode side (allocator drains to empty once
    the unpins relay), the hop must resume after the coordinated
    restart, and every lock ledger must be clean.  Returns the number
    of failed assertions (0 = green)."""
    import glob
    import json
    import threading
    import urllib.request

    import numpy as np

    from distkeras_tpu import obs
    from distkeras_tpu.obs.report import merge_traces
    from distkeras_tpu.serving.router import HttpReplica, Router
    from distkeras_tpu.utils import locks

    print("== cluster scenario: serve_kill_prefill (disaggregated "
          "hop under prefill death) ==", flush=True)
    base = os.path.join(workdir, "serve_kill_prefill")
    coord = os.path.join(base, "coord")
    tracedir = os.path.join(base, "traces")
    os.makedirs(tracedir, exist_ok=True)
    os.makedirs(coord, exist_ok=True)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(base, "replica.py")
    with open(script, "w", encoding="utf-8") as f:
        f.write(ROUTER_CHILD.format(repo=repo, tracedir=tracedir,
                                    seed=seed))
    ports = [_free_port(), _free_port()]
    roles = ["decode", "prefill"]

    def launch(h):
        import subprocess

        env = {**os.environ,
               "DKT_CLUSTER_DIR": coord,
               "DKT_CLUSTER_HOST": str(h),
               "DKT_CLUSTER_NHOSTS": "2",
               "DKT_CLUSTER_WINDOW": "2.0",
               "DKT_SERVE_PORT": str(ports[h]),
               "DKT_SERVE_ROLE": roles[h],
               "DKT_ASSERT_IDLE_ALLOC": "1"}
        return subprocess.Popen([sys.executable, script], env=env)

    def wait_port(h, deadline):
        import time as _time

        while True:
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{ports[h]}/healthz",
                    timeout=1.0).read()
                return
            except Exception:  # noqa: BLE001 — still starting
                assert _time.time() < deadline, \
                    f"replica {h} never came up on port {ports[h]}"
                _time.sleep(0.2)

    import time as _time

    locks.enable_sanitizer()
    children = [launch(0), launch(1)]
    rng = np.random.default_rng(seed)
    router_trace = os.path.join(tracedir, "router.jsonl")
    failures = 0
    sess = None
    try:
        wait_port(0, _time.time() + 180)
        wait_port(1, _time.time() + 180)
        sess = obs.enable(trace_path=router_trace)
        dec = HttpReplica("host0", f"127.0.0.1:{ports[0]}",
                          role="decode")
        router = Router(
            [dec, HttpReplica("host1", f"127.0.0.1:{ports[1]}",
                              role="prefill")],
            policy="affinity", health_interval=0.3,
            residency_interval=0.2)
        router.pump()  # first residency refresh: the disagg planner
        # keys on the block geometry the tables now advertise.
        # 2-block prompts (the child engines run block=8, bucket 16):
        # a UNIQUE first block + a shared 1-block tail.  The planner
        # gates on the full-block stems of ``prompt[:-1]`` — one
        # block here, always fresh — so EVERY request takes the
        # ship->adopt hop (a shared first block would warm-skip all
        # but the first request per stem).
        stem = rng.integers(0, 64, (8,)).astype(np.int32)
        n_req = n_wave1 + n_wave2
        prompts = [np.concatenate(
            [rng.integers(0, 64, (8,)).astype(np.int32), stem])
            for _ in range(n_req)]

        def counter(name):
            snap = sess.registry.snapshot()
            return sum(s.get("value", 0) for s in
                       snap.get(name, {}).get("series", []))

        def serve_wave(wave_rids, deadline):
            done = set()
            while len(done) < len(wave_rids):
                assert _time.time() < deadline, (
                    f"serve_kill_prefill stalled: {len(done)}/"
                    f"{len(wave_rids)} done, "
                    f"up={router.replicas_up()}")
                router.pump()
                for r in wave_rids:
                    if r not in done and router.poll(r) is not None:
                        done.add(r)
                _time.sleep(0.05)

        # Wave 1: the healthy hop — prefill builds, ships, decode
        # adopts (also warms every program outside the kill window).
        first = [router.enqueue(p, 8) for p in prompts[:n_wave1]]
        serve_wave(first, _time.time() + 180)
        hops = counter("router.disagg_requests")
        assert hops >= 1, (
            "no request took the prefill->decode hop before the "
            "kill — the scenario exercised nothing")
        # Wave 2: LONG decodes with the SIGKILL racing the hop.  The
        # killer thread fires mid-enqueue (the hop runs synchronously
        # in the enqueue caller), and the enqueues after the kill land
        # before any health probe marks the victim down — those hops
        # fail at the prefill/transfer stage and MUST fall back to
        # plain routing, never surface to the caller.
        killer = threading.Thread(
            target=lambda: (_time.sleep(0.05), children[1].kill()),
            daemon=True)
        killer.start()
        rest = [router.enqueue(p, 100) for p in prompts[n_wave1:]]
        killer.join()
        children[1].wait(timeout=30)
        print("  killed prefill replica mid-transfer "
              f"({int(counter('router.disagg_fallbacks'))} hop "
              "fallback(s) at kill time)", flush=True)
        serve_wave(rest, _time.time() + 300)
        rids = first + rest
        results = {r: router.take(r) for r in rids}
        lost = [r for r, v in results.items() if not v.ok]
        assert not lost, (
            f"accepted requests lost across the prefill kill: "
            f"{[(r, results[r].status) for r in lost]}")
        fallbacks = counter("router.disagg_fallbacks")
        assert fallbacks >= 1, (
            "the prefill kill produced no hop fallback — nothing "
            "was mid-transfer")
        # Coordinated restart: the prefill replica returns on the
        # SAME address and the hop must RESUME (fresh stem, so the
        # warm-skip gate cannot hide a dead hop).
        children[1] = launch(1)
        wait_port(1, _time.time() + 180)
        deadline = _time.time() + 60
        while "host1" not in router.replicas_up():
            assert _time.time() < deadline, \
                "restarted prefill replica never rejoined the router"
            router.pump()
            _time.sleep(0.1)
        stem2 = rng.integers(0, 64, (8,)).astype(np.int32)
        extra = router.enqueue(np.concatenate(
            [stem2, rng.integers(0, 64, (8,)).astype(np.int32)]), 8)
        serve_wave([extra], _time.time() + 120)
        assert router.take(extra).ok
        assert counter("router.disagg_requests") > hops, (
            "the hop never resumed after the prefill restart")
        # Leak check: once every unpin has relayed, the decode
        # replica's refcounted slab must drain to empty — shipped
        # blocks pinned for adoption leak NOTHING across the chaos.
        capacity = 32          # the child's n_blocks=33 minus trash
        deadline = _time.time() + 60
        while True:
            free = dec.residency().get("kv_blocks_free")
            if free == capacity:
                break
            assert _time.time() < deadline, (
                f"decode replica still holds blocks after drain: "
                f"free={free}, expected {capacity}")
            router.pump()
            _time.sleep(0.1)
        print(f"  PASS  cluster/serve_kill_prefill: {n_req} + 1 "
              f"post-restart ok, {int(hops)} hop(s) pre-kill, "
              f"{int(fallbacks)} fallback(s), decode slab drained "
              f"to {capacity}/{capacity} free", flush=True)
    except Exception as e:  # noqa: BLE001 — report the ladder
        failures += 1
        print(f"  FAIL  cluster/serve_kill_prefill: "
              f"{type(e).__name__}: {e}")
    finally:
        if sess is not None:
            obs.disable()
        for h in (0, 1):
            with open(os.path.join(coord, f"stop{h}"), "w"):
                pass
        for c in children:
            try:
                c.wait(timeout=60)
            except Exception:  # noqa: BLE001 — force it down
                c.kill()

    # Merged cross-process timeline: the block-transfer hop and the
    # fallback must both be visible, the allocator ledgers empty, and
    # every lock report clean.
    traces = sorted(glob.glob(os.path.join(tracedir, "*.jsonl")))
    merged = merge_traces(traces)
    print("--- cross-process serve timeline (serve_kill_prefill, "
          "JSONL) ---")
    for e in merged["timeline"]:
        if e["name"].startswith(("router", "locks",
                                 "serving.allocator")):
            print(json.dumps({"t": round(e["t"], 4),
                              "host": e["host"], "event": e["name"],
                              **e["fields"]}))
    for name, what in (("router.block_transfer",
                        "no block-transfer hop"),
                       ("router.disagg_fallback",
                        "no hop fallback")):
        if not any(e["name"] == name for e in merged["timeline"]):
            failures += 1
            print(f"  FAIL  cluster/serve_kill_prefill: {what} in "
                  "the merged timeline")
    leaks = [e for e in merged["timeline"]
             if e["name"] == "serving.allocator"
             and e["fields"].get("used")]
    if leaks:
        failures += 1
        print("  FAIL  cluster/serve_kill_prefill: block leak in "
              f"exit ledger(s): {[e['fields'] for e in leaks]}")
    reports = [e for e in merged["timeline"]
               if e["name"] == "locks.report"]
    hosts_reported = {e["fields"].get("host") for e in reports}
    if not hosts_reported >= {0, 1}:
        failures += 1
        print(f"  FAIL  cluster/serve_kill_prefill: lock report "
              f"missing for replica(s) "
              f"{sorted({0, 1} - hosts_reported)}")
    bad = [e for e in reports if e["fields"].get("violations")]
    if bad:
        failures += 1
        print("  FAIL  cluster/serve_kill_prefill: lock sanitizer "
              "violation(s) in replica report(s)")
    if locks.violation_count():
        failures += 1
        print("  FAIL  cluster/serve_kill_prefill: router-process "
              "lock sanitizer violations:")
        for v in locks.violations():
            print("  VIOLATION " + v.format())
    return failures


def run_autoscale_spike_scenario(seed, workdir, ticks=14, spike_at=3,
                                 spike_len=6):
    """The round-19 autoscaling leg: one active replica (host0) plus
    two warm-pool replicas (host1, host2) behind the Autoscaler, a
    flash-spike trace driving the router hot, and a SIGKILL of the
    FIRST warm-pool replica exactly as the scale-up reaches for it.
    The join must abort cleanly (no route-table entry ever exists for
    the dead replica), the spike must be absorbed by the surviving
    warm replica, every accepted request must complete (zero lost),
    and the fleet must scale back down losslessly once the spike
    drains.  Returns the number of failed assertions (0 = green)."""
    import glob
    import json
    import urllib.request

    import numpy as np

    from distkeras_tpu import obs
    from distkeras_tpu.obs.report import merge_traces
    from distkeras_tpu.serving.autoscale import (Autoscaler,
                                                 AutoscalePolicy,
                                                 WarmPool)
    from distkeras_tpu.serving.router import HttpReplica, Router
    from distkeras_tpu.serving.traffic import TraceReplay
    from distkeras_tpu.utils import locks

    print("== cluster scenario: autoscale_spike (warm-pool scale-up "
          "under join-time death) ==", flush=True)
    base = os.path.join(workdir, "autoscale_spike")
    coord = os.path.join(base, "coord")
    tracedir = os.path.join(base, "traces")
    os.makedirs(tracedir, exist_ok=True)
    os.makedirs(coord, exist_ok=True)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(base, "replica.py")
    with open(script, "w", encoding="utf-8") as f:
        f.write(ROUTER_CHILD.format(repo=repo, tracedir=tracedir,
                                    seed=seed))
    ports = [_free_port(), _free_port(), _free_port()]

    def launch(h):
        import subprocess

        env = {**os.environ,
               "DKT_CLUSTER_DIR": coord,
               "DKT_CLUSTER_HOST": str(h),
               "DKT_CLUSTER_NHOSTS": "3",
               "DKT_CLUSTER_WINDOW": "2.0",
               "DKT_SERVE_PORT": str(ports[h])}
        return subprocess.Popen([sys.executable, script], env=env)

    def wait_port(h, deadline):
        import time as _time

        while True:
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{ports[h]}/healthz",
                    timeout=1.0).read()
                return
            except Exception:  # noqa: BLE001 — still starting
                assert _time.time() < deadline, \
                    f"replica {h} never came up on port {ports[h]}"
                _time.sleep(0.2)

    import time as _time

    locks.enable_sanitizer()
    children = [launch(0), launch(1), launch(2)]
    router_trace = os.path.join(tracedir, "router.jsonl")
    failures = 0
    sess = None
    try:
        for h in range(3):
            wait_port(h, _time.time() + 180)
        sess = obs.enable(trace_path=router_trace)
        # host0 serves from the start; host1/host2 sit pre-compiled in
        # the warm pool with NO route-table entry until a scale-up
        # health-gates them in.
        # residency_interval=0.2: every pump refreshes the cached
        # queue_depth/lanes_busy the autoscaler's utilization signal
        # reads — without it the tiny engines drain each tick's
        # arrivals before the 2s default refresh ever sees them hot.
        router = Router(
            [HttpReplica("host0", f"127.0.0.1:{ports[0]}")],
            policy="least_loaded", health_interval=0.3,
            residency_interval=0.2)
        pool = WarmPool([
            HttpReplica("host1", f"127.0.0.1:{ports[1]}"),
            HttpReplica("host2", f"127.0.0.1:{ports[2]}")])
        asc = Autoscaler(router, pool, policy=AutoscalePolicy(
            min_replicas=1, max_replicas=2, up_threshold=0.9,
            down_threshold=0.2, up_after=1, down_after=3,
            cooldown_ticks=1))
        # Long decodes (max_new=16) at a rate one 2-lane replica
        # cannot drain inside a tick: the spike piles queue depth the
        # refreshed residency makes visible, driving utilization past
        # the scale-up threshold.  Pre-spike the trickle stays under
        # it, so the FIRST scale-up lands inside the spike — after
        # host1 is dead.
        trace = TraceReplay("spike", seed=seed, base_rate=0.3,
                            spike_at=spike_at, spike_len=spike_len,
                            spike_rate=20.0, max_new=(4, 8))
        # SIGKILL the FIFO head of the warm pool before the spike can
        # reach for it: the scale-up's join health gate must race the
        # death — abort cleanly, admit the survivor.
        children[1].kill()
        children[1].wait(timeout=30)
        print("  killed warm-pool replica 1 ahead of the join",
              flush=True)
        rids, retry = [], []
        for t in range(ticks):
            arrivals = (retry
                        + [trace.prompt(r, stem_len=8, tail_len=4,
                                        vocab=64) for r in
                           trace.requests_at(t)])
            retry = []
            for p in arrivals:
                try:
                    rids.append(router.enqueue(np.asarray(
                        p, np.int32), 16))
                except Exception:  # noqa: BLE001 — backpressure
                    retry.append(p)
            router.pump()
            asc.tick()
            _time.sleep(0.15)
        ups = [d for d in asc.decisions if d["action"] == "up"]
        assert ups, "the flash spike never triggered a scale-up"
        assert all(d["replica"] == "host2" for d in ups), (
            f"a dead warm-pool replica was admitted: {ups}")
        snap = router.fleet_snapshot()
        assert "host1" not in snap["replicas"], (
            "SIGKILLed warm-pool replica holds a route-table entry")
        assert "host2" in router.replicas_up(), (
            "surviving warm replica never joined the fleet")
        # Zero lost: every accepted request completes across the
        # aborted join and the scale-up.
        deadline = _time.time() + 300
        done = {}
        while len(done) < len(rids):
            assert _time.time() < deadline, (
                f"autoscale_spike stalled: {len(done)}/{len(rids)} "
                f"done, up={router.replicas_up()}")
            router.pump()
            for r in rids:
                if r not in done and router.poll(r) is not None:
                    done[r] = router.take(r)
            _time.sleep(0.05)
        lost = [r for r, v in done.items() if not v.ok]
        assert not lost, (
            f"requests lost across the spike: "
            f"{[(r, done[r].status) for r in lost]}")
        reg = sess.registry.snapshot()

        def _total(name):
            return sum(s.get("value", 0) for s in
                       reg.get(name, {}).get("series", []))

        assert _total("autoscale.join_aborts") >= 1, (
            "the killed warm-pool replica produced no join abort")
        # Spike drained: the idle fleet scales back down to the
        # envelope floor, pooling the retired still-warm handle.
        deadline = _time.time() + 60
        while len(router.replicas_up()) > 1:
            assert _time.time() < deadline, (
                "fleet never scaled back down after the spike "
                f"(up={router.replicas_up()})")
            router.pump()
            asc.tick()
            _time.sleep(0.2)
        assert len(pool) >= 1, \
            "retired replica handle was not returned to the warm pool"
        print(f"  PASS  cluster/autoscale_spike: {len(rids)} "
              f"request(s) ok across the spike, scale-up to "
              f"{ups[0]['replica']} after "
              f"{int(_total('autoscale.join_aborts'))} join "
              "abort(s), fleet back at the floor", flush=True)
    except Exception as e:  # noqa: BLE001 — report the ladder
        failures += 1
        print(f"  FAIL  cluster/autoscale_spike: "
              f"{type(e).__name__}: {e}")
    finally:
        if sess is not None:
            obs.disable()
        for h in (0, 1, 2):
            with open(os.path.join(coord, f"stop{h}"), "w"):
                pass
        for c in children:
            try:
                c.wait(timeout=60)
            except Exception:  # noqa: BLE001 — force it down
                c.kill()

    # Merged cross-process timeline: the scaling decisions and the
    # join abort must be visible, and the surviving replicas must
    # report clean lock ledgers (host1 died mid-join — no report).
    traces = sorted(glob.glob(os.path.join(tracedir, "*.jsonl")))
    merged = merge_traces(traces)
    print("--- cross-process autoscale timeline (autoscale_spike, "
          "JSONL) ---")
    for e in merged["timeline"]:
        if e["name"].startswith(("autoscale", "router.reroute",
                                 "locks")):
            print(json.dumps({"t": round(e["t"], 4),
                              "host": e["host"], "event": e["name"],
                              **e["fields"]}))
    decisions = [e for e in merged["timeline"]
                 if e["name"] == "autoscale.decision"]
    if not any(e["fields"].get("action") == "up" for e in decisions):
        failures += 1
        print("  FAIL  cluster/autoscale_spike: no scale-up decision "
              "in the merged timeline")
    if not any(e["fields"].get("action") == "abort"
               for e in decisions):
        failures += 1
        print("  FAIL  cluster/autoscale_spike: no join-abort "
              "decision in the merged timeline")
    reports = [e for e in merged["timeline"]
               if e["name"] == "locks.report"]
    hosts_reported = {e["fields"].get("host") for e in reports}
    if not hosts_reported >= {0, 2}:
        failures += 1
        print(f"  FAIL  cluster/autoscale_spike: lock report missing "
              f"for replica(s) {sorted({0, 2} - hosts_reported)}")
    bad = [e for e in reports if e["fields"].get("violations")]
    if bad:
        failures += 1
        print("  FAIL  cluster/autoscale_spike: lock sanitizer "
              "violation(s) in replica report(s)")
    if locks.violation_count():
        failures += 1
        print("  FAIL  cluster/autoscale_spike: router-process lock "
              "sanitizer violations:")
        for v in locks.violations():
            print("  VIOLATION " + v.format())
    return failures


# SLO breach classes (metric names) the cluster ladder tolerates.
# Empty on purpose: the in-child rule (train.step_s p99 < 60s over a
# 30s window) is generous enough that ANY breach means a real latency
# pathology — the ladder is a latency-regression canary, not just a
# recovery proof.
EXPECTED_BREACH_METRICS: frozenset = frozenset()


class _FederationScraper(threading.Thread):
    """Poll host 0's published telemetry address and scrape its
    ``/metrics/cluster`` while a cluster scenario runs; each sample
    records which hosts' series were present — how the ladder proves a
    killed host's series disappear and return across the coordinated
    restart."""

    def __init__(self, coord_dir: str, poll: float = 0.2):
        super().__init__(name="chaos-federation-scrape", daemon=True)
        self.coord_dir = coord_dir
        self.poll = poll
        self.samples: list = []   # (wall_t, frozenset(hosts up))
        # NOT _stop: threading.Thread owns a private _stop method.
        self._halt = threading.Event()

    def _scrape_once(self):
        import json as _json
        import urllib.request

        addr_path = os.path.join(self.coord_dir, "telemetry",
                                 "host0.addr")
        try:
            with open(addr_path, encoding="utf-8") as f:
                addr = _json.load(f)["addr"]
            with urllib.request.urlopen(
                    f"http://{addr}/metrics/cluster",
                    timeout=2.0) as resp:
                text = resp.read().decode("utf-8")
        except Exception:  # noqa: BLE001 — between epochs: no server
            return None
        up = set()
        for line in text.splitlines():
            if line.startswith("cluster_scrape_up{"):
                name, _, value = line.rpartition(" ")
                if value.strip().startswith("1"):
                    host = name.split('host="', 1)[1].split('"', 1)[0]
                    up.add(int(host))
        return frozenset(up)

    def run(self):
        import time as _time

        while not self._halt.wait(self.poll):
            up = self._scrape_once()
            if up is not None:
                self.samples.append((_time.time(), up))

    def stop(self) -> list:
        self._halt.set()
        self.join(timeout=5.0)
        return self.samples


def run_cluster_scenario(scenario, seed, workdir, window=2.0,
                         attempt_timeout=240.0, num_epoch=2,
                         kill_round=5):
    """One coordinated-restart scenario on 2 local hosts; returns
    (summaries, out_npz_path, trace_paths, federation_samples) —
    federation samples are the scraped ``/metrics/cluster`` host sets
    (round 11).  ``scenario`` None = no chaos (the uninterrupted
    reference run)."""
    import glob

    from distkeras_tpu.resilience.cluster import run_cluster_local

    name = scenario or "reference"
    base = os.path.join(workdir, name)
    coord = os.path.join(base, "coord")
    ckdir = os.path.join(base, "ckpt")
    tracedir = os.path.join(base, "traces")
    out = os.path.join(base, "host0.npz")
    os.makedirs(tracedir, exist_ok=True)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(base, "child.py")
    with open(script, "w", encoding="utf-8") as f:
        f.write(CLUSTER_CHILD.format(repo=repo, nhosts=2, seed=seed,
                                     ckdir=ckdir, out=out,
                                     tracedir=tracedir,
                                     num_epoch=num_epoch))
    per_host_env = {}
    if scenario == "kill":
        per_host_env = {1: {"DKT_CHAOS": f"kill:train.round:{kill_round}"}}
    elif scenario == "stall":
        per_host_env = {1: {"DKT_CHAOS": "stall:cluster.heartbeat:6"}}
    elif scenario == "drop":
        per_host_env = {1: {"DKT_CHAOS": "drop:cluster.heartbeat:0"}}
    elif scenario is not None:
        raise ValueError(f"unknown cluster scenario {scenario!r}")
    scraper = _FederationScraper(coord)
    scraper.start()
    try:
        summaries = run_cluster_local(
            [sys.executable, script], num_hosts=2, coord_dir=coord,
            per_host_env=per_host_env, base_port=_free_port(),
            checkpoint_dirs=[ckdir], window=window, poll=0.2,
            heartbeat_interval=0.4, grace=90.0, max_restarts=2,
            attempt_timeout=attempt_timeout)
    finally:
        samples = scraper.stop()
    return summaries, out, sorted(glob.glob(
        os.path.join(tracedir, "*.jsonl"))), samples


def run_async_scenarios(scenarios, seed, workdir):
    """The round-16 async-tier legs of ``--cluster`` (docs/async.md).
    Like ``serve_kill`` these run in-process — the hosts are simulated
    islands under a seeded virtual-time clock, so the legs are
    deterministic and fast while still exercising the real
    ``AsyncPlane`` membership/merge machinery and the real
    ``cluster.push``/``cluster.merge`` probe sites:

    * ``async_stall`` — a wedged-heartbeat straggler must slow the
      fleet by < tau round-lengths (watchdog eviction), never a full
      stall, with survivors completing their full quotas.
    * ``async_kill_push`` — a host killed mid-push must leave no torn
      merge: the in-flight delta is dropped cleanly
      (pushes == merges == center version) and the fleet drains.

    Returns the number of failed legs."""
    import json
    import shutil

    import numpy as np

    from distkeras_tpu.parallel.async_tier import AsyncSchedule
    from distkeras_tpu.resilience import chaos

    def blob_ds(n=256):
        import distkeras_tpu as dk

        rng = np.random.default_rng(seed)
        centers = rng.normal(0, 4.0, (4, 16))
        labels = rng.integers(0, 4, n)
        feats = (centers[labels]
                 + rng.normal(0, 0.5, (n, 16))).astype(np.float32)
        return dk.Dataset({"features": feats,
                           "label": labels.astype(np.int64)})

    def trainer(schedule, coord=None, tau=2):
        import keras

        import distkeras_tpu as dk

        keras.utils.set_random_seed(0)
        model = keras.Sequential([
            keras.Input((16,)),
            keras.layers.Dense(32, activation="relu"),
            keras.layers.Dense(4)])
        return dk.AsyncDP(model, hosts=3, tau=tau, schedule=schedule,
                          beat_window=1.5, coord_dir=coord,
                          loss="sparse_categorical_crossentropy",
                          worker_optimizer="sgd", learning_rate=0.05,
                          batch_size=2, num_epoch=2,
                          communication_window=2, seed=11)

    def audit_trail(coord):
        """The on-disk membership evidence the plane left behind:
        EpochStore generations + per-host heartbeat files."""
        epochs = sorted(os.listdir(os.path.join(coord, "epochs")))
        beats = {}
        for f in sorted(os.listdir(os.path.join(coord, "beats"))):
            with open(os.path.join(coord, "beats", f)) as fh:
                beats[f] = json.load(fh)
        return epochs, beats

    failures = 0
    if "async_stall" in scenarios:
        print("== cluster scenario: async_stall (bounded-staleness "
              "straggler) ==", flush=True)
        coord = os.path.join(workdir, "async_stall", "coord")
        shutil.rmtree(coord, ignore_errors=True)
        os.makedirs(coord)
        try:
            tau, ds = 2, blob_ds()
            t0 = trainer(AsyncSchedule(seed=3), tau=tau)
            t0.train(ds)
            t1 = trainer(AsyncSchedule(seed=3).stall(1, 2, 50.0),
                         coord=coord, tau=tau)
            t1.train(ds)
            m0 = t0.async_report["makespan"]
            m1 = t1.async_report["makespan"]
            assert m1 - m0 < tau * 1.0, (
                f"fleet slowed by {m1 - m0:.2f} virtual seconds — more "
                f"than tau={tau} round-lengths (full-stall behaviour)")
            assert t1.async_report["evicted"] == [1], (
                f"watchdog did not evict the wedged host: "
                f"{t1.async_report['evicted']}")
            for h in (0, 2):
                assert (t1.async_report["rounds"][h]
                        == t0.async_report["rounds"][h]), (
                    f"survivor {h} lost rounds to the straggler")
            epochs, beats = audit_trail(coord)
            assert len(epochs) >= 2, (
                f"eviction did not bump the membership epoch: {epochs}")
            print(f"  PASS  cluster/async_stall: 50s wedge cost the "
                  f"fleet {m1 - m0:.2f} virtual s (< tau={tau} "
                  f"rounds), host 1 evicted, survivors at full quota")
            print("--- membership audit trail (async_stall) ---")
            print(f"  epochs: {epochs}")
            for f, b in beats.items():
                print(f"  beat {f}: " + json.dumps(b))
        except Exception as e:  # noqa: BLE001 — report the ladder
            failures += 1
            print(f"  FAIL  cluster/async_stall: "
                  f"{type(e).__name__}: {e}")
    if "async_kill_push" in scenarios:
        print("== cluster scenario: async_kill_push (host loss "
              "mid-delta-publish) ==", flush=True)
        try:
            ds = blob_ds()
            t = trainer(AsyncSchedule(seed=3))
            with chaos.FaultPlan(seed=0).fail("cluster.push",
                                              at=5) as plan:
                t.train(ds)
            r = t.async_report
            assert plan.events == [("cluster.push", 5, "fail")], (
                f"probe never fired: {plan.events}")
            assert len(r["evicted"]) == 1, (
                f"killed host not evicted: {r['evicted']}")
            assert r["pushes"] == r["merges"] == r["version"], (
                f"torn merge: pushes={r['pushes']} merges={r['merges']} "
                f"version={r['version']}")
            assert r["members_final"] == [], (
                f"fleet did not drain: {r['members_final']}")
            print(f"  PASS  cluster/async_kill_push: push 5 died "
                  f"pre-publish, host {r['evicted'][0]} evicted, "
                  f"{r['merges']} merges == {r['pushes']} pushes "
                  f"(no torn merge), fleet drained")
        except Exception as e:  # noqa: BLE001 — report the ladder
            failures += 1
            print(f"  FAIL  cluster/async_kill_push: "
                  f"{type(e).__name__}: {e}")
    return failures


# ------------------------------------------- live weight push ladder
#
# The round-20 train→serve legs of --cluster: the trainer publishes
# versioned fusion-bucket snapshots (serving/publish.py) and a
# CanaryController pushes them across a hot_swap serving fleet.
# ``train_kill_push`` SIGKILLs the TRAINER process between a version's
# bucket writes and its atomic manifest rename (the publish.commit
# probe) and asserts the serving side never adopts the torn snapshot;
# ``canary_bad_push`` publishes a poisoned (NaN) version with VALID
# checksums — transport is healthy, the weights are not — and asserts
# the canary's logit-drift gate rolls the fleet back with zero lost
# requests.

TRAINER_PUSH_CHILD = '''
import os, sys
os.environ["KERAS_BACKEND"] = "jax"
os.environ.setdefault("DKT_LOCK_SANITIZER", "1")
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})

import numpy as np
import distkeras_tpu as dk
from distkeras_tpu.models.transformer import TransformerConfig
from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh
from distkeras_tpu.resilience import FaultPlan
from distkeras_tpu.serving.publish import SnapshotPublisher

rng = np.random.default_rng({seed})
tokens = rng.integers(0, 64, (64, 17)).astype(np.int32)
cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=2,
                        d_ff=64, max_len=32)
t = dk.LMTrainer(cfg, optimizer="sgd", learning_rate=0.05, batch_size=16,
                 num_epoch=2, mesh=make_mesh(MeshSpec(data=1)),
                 seed={seed})
t.attach_publisher(SnapshotPublisher({snapdir!r}), every=1)
with FaultPlan({seed}).kill("publish.commit", at={kill_at}):
    t.train(tokens)
print("CHILD DONE (kill never fired)", flush=True)
'''


def _push_fleet(seed):
    """Two hot_swap engines behind a Router plus the canary plumbing —
    the serving half both push legs share."""
    from distkeras_tpu.serving.canary import CanaryController
    from distkeras_tpu.serving.router import InProcessReplica, Router

    params = tfm.init_params(jax.random.key(seed), CFG)
    engines = [ContinuousBatcher(params, CFG, lanes=2, hot_swap=True)
               for _ in range(2)]
    router = Router([InProcessReplica(f"r{i}", e)
                     for i, e in enumerate(engines)])
    template = jax.eval_shape(
        lambda: tfm.init_params(jax.random.key(seed), CFG))
    return engines, router, template, CanaryController


def _push_wave(router, n=4, max_new=6):
    """Serve one wave of greedy requests to completion; a request that
    fails to finish raises out of drain — completing IS the
    zero-lost-requests assertion."""
    rids = [router.enqueue([1 + i, 2, 3], max_new) for i in range(n)]
    out = []
    for r in rids:
        res = router.drain(r)
        toks = res["tokens"] if isinstance(res, dict) else res.tokens
        out.append(tuple(int(t) for t in toks))
    return out


def run_train_kill_push_scenario(seed, workdir, kill_at=2):
    """SIGKILL the trainer between bucket writes and the manifest
    rename of version ``kill_at``: the serving fleet must keep serving
    the last complete version, the torn snapshot must never be
    adopted, and the canary tick must abort cleanly."""
    from distkeras_tpu.serving.publish import (SnapshotCorrupt,
                                               SnapshotReader)
    from distkeras_tpu.utils import locks

    print("== cluster scenario: train_kill_push (trainer SIGKILL "
          "mid-publish) ==", flush=True)
    try:
        import subprocess

        snapdir = os.path.join(workdir, "push_snaps")
        os.makedirs(snapdir, exist_ok=True)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        script = os.path.join(workdir, "train_push_child.py")
        with open(script, "w") as f:
            f.write(TRAINER_PUSH_CHILD.format(
                repo=repo, seed=seed, snapdir=snapdir, kill_at=kill_at))
        proc = subprocess.run([sys.executable, script],
                              capture_output=True, text=True, timeout=600)
        assert proc.returncode == 137, (
            f"trainer child exited {proc.returncode}, expected 137 "
            f"(SIGKILL-equivalent)\n{proc.stdout[-400:]}"
            f"\n{proc.stderr[-800:]}")
        torn = os.path.join(snapdir, f"v{kill_at:08d}")
        assert os.path.isdir(torn), "kill fired before bucket writes"
        assert not os.path.exists(os.path.join(torn, "MANIFEST.json")), (
            "manifest present: the kill did not land mid-publish")

        engines, router, template, CanaryController = _push_fleet(seed)
        reader = SnapshotReader(snapdir)
        ctl = CanaryController(router, reader, CFG, template)
        base_viol = locks.violation_count()
        _push_wave(router)                       # serve on init params
        # LATEST never advanced past the last COMPLETE publish.
        assert reader.latest_version() == kill_at - 1, (
            reader.latest_version())
        rec = ctl.poll()
        assert rec is not None and rec["action"] == "promote", rec
        assert all(e.param_version == kill_at - 1 for e in engines)
        served = _push_wave(router)              # serve on pushed v1
        # A direct read of the torn version must refuse, loudly.
        try:
            reader.load(kill_at, template)
            raise AssertionError("torn snapshot adopted")
        except SnapshotCorrupt:
            pass
        # Worst case: the version pointer itself names the torn
        # version (simulated pointer corruption).  The canary tick
        # must abort — never a partial adoption, never a crash.
        with open(os.path.join(snapdir, "LATEST"), "w") as f:
            f.write(str(kill_at))
        rec2 = ctl.poll()
        assert rec2 is not None and rec2["action"] == "abort", rec2
        assert all(e.param_version == kill_at - 1 for e in engines)
        after = _push_wave(router)
        assert after == served, "tokens drifted across the abort"
        assert locks.violation_count() == base_viol, (
            "lock sanitizer violations during the push leg")
        print(f"  PASS  cluster/train_kill_push: trainer died at "
              f"publish.commit v{kill_at} (rc 137), torn snapshot "
              f"refused, fleet stayed on v{kill_at - 1}, canary tick "
              f"aborted cleanly, zero lost requests")
        return 0
    except Exception as e:  # noqa: BLE001 — report the ladder
        print(f"  FAIL  cluster/train_kill_push: "
              f"{type(e).__name__}: {e}")
        return 1


def run_canary_bad_push_scenario(seed, workdir):
    """Publish a poisoned (NaN) version with valid checksums: the
    drift probe must trip, the fleet must roll back to the promoted
    version with zero lost requests, and the rejected version must be
    quarantined (pushed once, never re-pushed)."""
    from distkeras_tpu.serving.publish import (SnapshotPublisher,
                                               SnapshotReader)
    from distkeras_tpu.utils import locks

    print("== cluster scenario: canary_bad_push (NaN weights, valid "
          "checksums) ==", flush=True)
    try:
        snapdir = os.path.join(workdir, "canary_snaps")
        os.makedirs(snapdir, exist_ok=True)
        engines, router, template, CanaryController = _push_fleet(seed)
        pub = SnapshotPublisher(snapdir)
        reader = SnapshotReader(snapdir)
        ctl = CanaryController(router, reader, CFG, template)
        base_viol = locks.violation_count()

        good = jax.tree.map(
            np.asarray, tfm.init_params(jax.random.key(seed + 1), CFG))
        pub.publish(good, 1)
        rec = ctl.poll()
        assert rec is not None and rec["action"] == "promote", rec
        served = _push_wave(router)
        # In-flight requests straddle the bad push: enqueue, partially
        # decode, push, then drain — every request must still finish.
        straddlers = [router.enqueue([9 + i, 8, 7], 6) for i in range(3)]
        for _ in range(2):
            router.step()
        bad = jax.tree.map(
            lambda a: np.full_like(np.asarray(a), np.nan), good)
        pub.publish(bad, 2)                  # checksums are VALID
        rec2 = ctl.poll()
        assert rec2 is not None and rec2["action"] == "rollback", rec2
        assert rec2["reason"] == "drift" and rec2["drift"] == float(
            "inf"), rec2
        assert all(e.param_version == 1 for e in engines), (
            [e.param_version for e in engines])
        for r in straddlers:                 # zero lost requests
            router.drain(r)
        after = _push_wave(router)
        assert after == served, (
            "rollback did not restore bit-identical serving")
        assert ctl.poll() is None, "rejected version re-pushed"
        assert locks.violation_count() == base_viol, (
            "lock sanitizer violations during the canary leg")
        print("  PASS  cluster/canary_bad_push: drift probe tripped "
              "(inf), fleet rolled back to v1, straddling requests "
              "all finished, tokens bit-identical post-rollback, "
              "rejected v2 quarantined")
        return 0
    except Exception as e:  # noqa: BLE001 — report the ladder
        print(f"  FAIL  cluster/canary_bad_push: "
              f"{type(e).__name__}: {e}")
        return 1


def run_cluster_ladder(scenarios, seed, workdir):
    """The --cluster entry: reference run + one chaos run per
    training scenario (bit-for-bit weight comparison, merged
    cross-host timeline), plus the round-13 ``serve_kill`` router leg
    (kill-a-replica-mid-stream).  Returns the number of failures."""
    import json

    import numpy as np

    from distkeras_tpu.obs.report import merge_traces

    failures = 0
    scenarios = list(scenarios)
    async_legs = [s for s in scenarios
                  if s in ("async_stall", "async_kill_push")]
    if async_legs:
        scenarios = [s for s in scenarios if s not in async_legs]
        failures += run_async_scenarios(async_legs, seed, workdir)
    if "serve_kill" in scenarios:
        scenarios.remove("serve_kill")
        failures += run_router_kill_scenario(seed, workdir)
    if "serve_kill_prefill" in scenarios:
        scenarios.remove("serve_kill_prefill")
        failures += run_router_prefill_kill_scenario(seed, workdir)
    if "autoscale_spike" in scenarios:
        scenarios.remove("autoscale_spike")
        failures += run_autoscale_spike_scenario(seed, workdir)
    if "train_kill_push" in scenarios:
        scenarios.remove("train_kill_push")
        failures += run_train_kill_push_scenario(seed, workdir)
    if "canary_bad_push" in scenarios:
        scenarios.remove("canary_bad_push")
        failures += run_canary_bad_push_scenario(seed, workdir)
    if not scenarios:
        return failures

    print("== cluster ladder: uninterrupted 2-host reference ==",
          flush=True)
    _, ref_out, _, _ = run_cluster_scenario(None, seed, workdir)
    ref = np.load(ref_out)

    for scenario in scenarios:
        print(f"== cluster scenario: {scenario} ==", flush=True)
        try:
            summaries, out, traces, samples = run_cluster_scenario(
                scenario, seed, workdir)
            assert all(s["epochs"] >= 2 for s in summaries), (
                f"no coordinated restart happened: {summaries}")
            got = np.load(out)
            mismatch = [k for k in ref.files if k != "losses"
                        and not np.array_equal(got[k], ref[k])]
            assert not mismatch, (
                f"resumed weights differ from the uninterrupted run: "
                f"{mismatch}")
            # Federation (round 11): /metrics/cluster must have served
            # BOTH hosts' series host=-labeled at some point, and on a
            # host kill the dead host's series must visibly drop out
            # and return across the coordinated restart.
            hosts_seen = [up for _, up in samples]
            assert any(up >= {0, 1} for up in hosts_seen), (
                f"/metrics/cluster never federated both hosts "
                f"(samples: {hosts_seen[:20]})")
            if scenario == "kill":
                both = next(i for i, up in enumerate(hosts_seen)
                            if up >= {0, 1})
                gone = next((i for i in range(both, len(hosts_seen))
                             if 0 in hosts_seen[i]
                             and 1 not in hosts_seen[i]), None)
                assert gone is not None, (
                    "killed host's series never dropped out of "
                    "/metrics/cluster")
                assert any(up >= {0, 1}
                           for up in hosts_seen[gone:]), (
                    "killed host's series never returned after the "
                    "coordinated restart")
            print(f"  PASS  cluster/{scenario}: restart under epoch "
                  f"{summaries[0]['epochs'] - 1}, weights bit-for-bit, "
                  f"{len(samples)} federation scrape(s)")
        except Exception as e:  # noqa: BLE001 — report the ladder
            failures += 1
            print(f"  FAIL  cluster/{scenario}: "
                  f"{type(e).__name__}: {e}")
            continue
        # Machine-readable cross-host fault/recovery timeline,
        # assembled by the obs_report --merge machinery.
        merged = merge_traces(traces)
        print(f"--- cross-host fault/recovery timeline "
              f"({scenario}, JSONL) ---")
        for e in merged["timeline"]:
            print(json.dumps({"t": round(e["t"], 4), "host": e["host"],
                              "run": e["run"], "event": e["name"],
                              **e["fields"]}))
        # Per-host SLO/breach timeline (round 11): the in-child
        # rolling SLO rule turns the ladder into a latency-regression
        # canary — a breach class outside EXPECTED_BREACH_METRICS
        # fails the scenario.
        breaches = [e for e in merged["timeline"]
                    if e["name"] == "slo.breach"]
        print(f"--- per-host SLO/breach timeline ({scenario}) ---")
        if not breaches:
            print("  (no SLO breaches)")
        for e in breaches:
            print(f"  +{e['t']:.3f}s host {e['host']} BREACH "
                  + json.dumps(e["fields"]))
        unexpected = [e for e in breaches
                      if e["fields"].get("metric")
                      not in EXPECTED_BREACH_METRICS]
        if unexpected:
            failures += 1
            print(f"  FAIL  cluster/{scenario}: {len(unexpected)} "
                  f"unexpected SLO breach(es) — latency regressed "
                  f"under chaos (classes: "
                  f"{sorted({e['fields'].get('metric') for e in unexpected})})")
        # Per-host lock-sanitizer report (round 12): every completing
        # child emits one; a recorded violation anywhere in the
        # ladder — any host, any epoch — fails the scenario.  (A
        # chaos-killed epoch-0 child dies before reporting; the
        # coordinated restart's completing attempt must still report
        # for BOTH hosts.)
        reports = [e for e in merged["timeline"]
                   if e["name"] == "locks.report"]
        print(f"--- per-host lock sanitizer report ({scenario}) ---")
        for e in reports:
            print(f"  host {e['host']}: " + json.dumps(e["fields"]))
        hosts_reported = {e["fields"].get("host") for e in reports}
        if not hosts_reported >= {0, 1}:
            failures += 1
            print(f"  FAIL  cluster/{scenario}: lock report missing "
                  f"for host(s) {sorted({0, 1} - hosts_reported)}")
        bad = [e for e in reports if e["fields"].get("violations")]
        if bad:
            failures += 1
            print(f"  FAIL  cluster/{scenario}: lock sanitizer "
                  f"violation(s) recorded on host(s) "
                  f"{sorted({e['fields'].get('host') for e in bad})}")
    return failures


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--kill-rounds", default="3,7,12",
                    help="comma-separated rounds for the kill matrix")
    ap.add_argument("--trace", default=None,
                    help="write the obs event trace here (default: a "
                         "temp file, deleted after the timeline prints)")
    ap.add_argument("--cluster", action="store_true",
                    help="run the multi-host coordinated-restart "
                         "ladder instead of the single-host matrix")
    ap.add_argument("--scenarios",
                    default="kill,stall,drop,serve_kill,"
                            "serve_kill_prefill,autoscale_spike,"
                            "async_stall,async_kill_push,"
                            "train_kill_push,canary_bad_push",
                    help="--cluster fault kinds to run "
                         "(kill = host loss, stall = wedged heartbeat "
                         "writer, drop = partition, serve_kill = "
                         "kill-a-serving-replica-mid-stream under the "
                         "router, autoscale_spike = flash-spike "
                         "scale-up with a warm-pool replica SIGKILLed "
                         "mid-join, async_stall = bounded-staleness "
                         "straggler in the async tier, async_kill_push "
                         "= host loss mid-delta-publish, "
                         "train_kill_push = trainer SIGKILL mid-weight-"
                         "publish, canary_bad_push = poisoned weight "
                         "push rolled back by the canary gate)")
    ap.add_argument("--workdir", default=None,
                    help="--cluster scratch dir (default: a temp dir, "
                         "kept on failure)")
    args = ap.parse_args()

    if args.cluster:
        workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_cluster_")
        failures = run_cluster_ladder(
            [s for s in args.scenarios.split(",") if s], args.seed,
            workdir)
        if failures:
            print(f"cluster ladder: {failures} scenario(s) FAILED "
                  f"(artifacts kept at {workdir})")
            return 1
        print("cluster ladder: all scenarios passed")
        if not args.workdir:
            import shutil

            shutil.rmtree(workdir, ignore_errors=True)
        return 0
    kills = [int(r) for r in args.kill_rounds.split(",")]

    matrix = []
    for r in kills:
        matrix.append((f"kill@round{r}/exception",
                       lambda r=r: check_kill_resume(args.seed, r, False)))
    matrix.append((f"kill@round{kills[0]}/sigterm",
                   lambda: check_kill_resume(args.seed, kills[0], True)))
    matrix += [
        ("checkpoint-save-fault", lambda: check_checkpoint_fault_retry(
            args.seed)),
        ("cluster-heartbeat-partition",
         lambda: check_heartbeat_fault_kinds(args.seed)),
        ("serving-deadlines", lambda: check_serving_deadlines(args.seed)),
        ("queue-backpressure", lambda: check_backpressure(args.seed)),
        ("draft-fault-fallback", lambda: check_draft_fault_fallback(
            args.seed)),
    ]

    import json

    from distkeras_tpu import obs
    from distkeras_tpu.obs.trace import read_trace
    from distkeras_tpu.utils import locks

    # The single-host matrix runs under the lock sanitizer too: every
    # engine/obs lock the checks construct from here on is
    # instrumented, and a recorded violation fails the suite.
    locks.enable_sanitizer()
    trace_path = args.trace or os.path.join(
        tempfile.mkdtemp(prefix="chaos_obs_"), "chaos.jsonl")
    failures = 0
    with obs.session(trace_path=trace_path):
        for name, fn in matrix:
            obs.event("chaos_suite.check", check=name, status="start")
            try:
                fn()
                print(f"  PASS  {name}")
                obs.event("chaos_suite.check", check=name, status="pass")
            except Exception as e:  # noqa: BLE001 — report the matrix
                failures += 1
                print(f"  FAIL  {name}: {type(e).__name__}: {e}")
                obs.event("chaos_suite.check", check=name,
                          status="fail", error=repr(e)[:200])
            assert chaos.active_plan() is None, "a FaultPlan leaked"
        obs.event("locks.report", **locks.lock_report())
    print(f"{len(matrix) - failures}/{len(matrix)} chaos checks passed")
    print("--- lock sanitizer report ---")
    print(f"  {json.dumps(locks.lock_report())}")
    if locks.violation_count():
        failures += 1
        for v in locks.violations():
            print("  VIOLATION " + v.format())

    # Machine-readable fault/recovery timeline, straight off the obs
    # event trace: injected faults (chaos.fault), Supervisor attempts/
    # backoffs (supervisor.*), preemption checkpoints and engine
    # degradation — one JSON object per line, time-ordered.
    records = [r for r in read_trace(trace_path)
               if r.get("kind") == "event"]
    t0 = min((r["t"] for r in records), default=0.0)
    print("--- fault/recovery timeline (JSONL) ---")
    for r in sorted(records, key=lambda r: r["t"]):
        print(json.dumps({"t": round(r["t"] - t0, 4),
                          "event": r["name"], **r.get("fields", {})}))
    if args.trace:
        print(f"--- obs trace kept at {args.trace} "
              "(render: scripts/obs_report.py) ---")
    else:
        import shutil

        shutil.rmtree(os.path.dirname(trace_path), ignore_errors=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
