#!/usr/bin/env python
"""Chaos suite: run the full fault matrix against the resilience
subsystem on CPU and report a pass/fail table.

The deterministic, seedable end-to-end exercise of every failure mode
the subsystem claims to survive (docs/resilience.md):

- kill-at-step-N (exception and SIGTERM) under a Supervisor -> final
  parameters allclose to an uninterrupted run, resumed loss trajectory
  bit-for-bit;
- checkpoint-save faults -> retried by the Supervisor;
- serving deadlines -> expired requests never occupy a lane, running
  lanes evict with structured timeouts;
- bounded-queue backpressure -> QueueFull past capacity, queue drains
  as lanes free;
- speculative draft fault -> fallback decode completes every request
  (greedy: exact solo-generate parity);
- drain-then-shutdown -> no request is silently dropped.

The whole matrix runs under an obs telemetry session
(docs/observability.md): every injected fault, Supervisor attempt and
backoff lands in a JSONL event trace, and the suite ends with a
machine-readable **fault/recovery timeline** (one JSON object per
line) reconstructed from that trace — no log parsing.  ``--trace``
keeps the trace file for ``scripts/obs_report.py``.

Usage: python scripts/chaos_suite.py [--seed N] [--kill-rounds 3,7,12]
                                     [--trace chaos.jsonl]
"""

import argparse
import os
import sys
import tempfile

os.environ.setdefault("KERAS_BACKEND", "jax")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import distkeras_tpu as dk
from distkeras_tpu.models import transformer as tfm
from distkeras_tpu.models.generate import generate
from distkeras_tpu.resilience import (FaultPlan, QueueFull, Supervisor,
                                       chaos)
from distkeras_tpu.serving import ContinuousBatcher, SpeculativeBatcher

CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=32)
DRAFT = tfm.TransformerConfig(vocab_size=64, d_model=16, n_heads=2,
                              n_layers=1, d_ff=32, max_len=32)


def _mlp_data(seed):
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tests"))
    from helpers import make_blobs, make_mlp

    x, y = make_blobs(n=128, seed=seed)
    return make_mlp, dk.Dataset.from_arrays(x, y)


COMMON = dict(loss="sparse_categorical_crossentropy",
              worker_optimizer="sgd", learning_rate=0.05,
              batch_size=16, num_epoch=2)  # 16 rounds


def check_kill_resume(seed, kill_round, via_signal):
    make_mlp, ds = _mlp_data(seed)
    straight = dk.SingleTrainer(make_mlp(), **COMMON)
    ref = straight.train(ds)
    ref_w = [np.asarray(w) for w in ref.get_weights()]
    with tempfile.TemporaryDirectory() as d:
        t = dk.SingleTrainer(make_mlp(), checkpoint_dir=os.path.join(d, "c"),
                             checkpoint_every=1, checkpoint_backend="pickle",
                             **COMMON)
        sup = Supervisor(t, max_retries=2, backoff=0.0, max_backoff=0.0,
                         jitter=0.0, seed=seed)
        plan = FaultPlan(seed)
        if via_signal:
            plan.preempt("train.round", at=kill_round, via_signal=True)
        else:
            plan.fail("train.round", at=kill_round)
        with plan:
            out = sup.run(ds)
        for a, b in zip(ref_w, [np.asarray(w) for w in out.get_weights()]):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
        # Exception kill dies BEFORE round N commits -> resume replays
        # round N; graceful SIGTERM checkpoints round N synchronously
        # before raising -> resume continues at N + 1.
        resume_at = kill_round if via_signal else kill_round - 1
        assert t.history == straight.history[resume_at:], \
            "resumed loss trajectory diverged from the straight run"
        assert len(sup.attempts) == 2 and sup.attempts[-1].outcome == "ok"


def check_checkpoint_fault_retry(seed):
    make_mlp, ds = _mlp_data(seed)
    with tempfile.TemporaryDirectory() as d:
        t = dk.SingleTrainer(make_mlp(), checkpoint_dir=os.path.join(d, "c"),
                             checkpoint_every=1, checkpoint_backend="pickle",
                             **COMMON)
        sup = Supervisor(t, max_retries=2, backoff=0.0, max_backoff=0.0,
                         jitter=0.0, seed=seed)
        with FaultPlan(seed).fail("checkpoint.save", at=5):
            sup.run(ds)
        assert sup.attempts[0].outcome == "fault"
        assert sup.attempts[-1].outcome == "ok"


def check_serving_deadlines(seed):
    rng = np.random.default_rng(seed)
    params = tfm.init_params(jax.random.key(seed), CFG)
    t = [0.0]
    eng = ContinuousBatcher(params, CFG, lanes=2, max_queue=2,
                            clock=lambda: t[0])
    rid = eng.enqueue(rng.integers(0, 64, (4,)), 5, ttl=0.0)
    res = eng.take(rid)
    assert res.timed_out and eng.free_lanes() == [0, 1], \
        "expired request occupied a lane"
    lane = eng.submit(rng.integers(0, 64, (4,)).astype(np.int32), 10,
                      ttl=5.0)
    assert lane is not None
    eng.step()
    t[0] = 6.0
    eng.step()
    (res,) = eng.results().values()
    assert res.timed_out and len(res.generated) >= 1
    assert len(eng.free_lanes()) == 2, "timed-out lane was not evicted"


def check_backpressure(seed):
    rng = np.random.default_rng(seed)
    params = tfm.init_params(jax.random.key(seed), CFG)
    eng = ContinuousBatcher(params, CFG, lanes=1, max_queue=1)
    r1 = eng.enqueue(rng.integers(0, 64, (3,)), 3)
    r2 = eng.enqueue(rng.integers(0, 64, (3,)), 3)  # queued
    try:
        eng.enqueue(rng.integers(0, 64, (3,)), 3)
        raise AssertionError("queue overflow did not raise QueueFull")
    except QueueFull:
        pass
    res = eng.shutdown()
    assert res[r1].ok and res[r2].ok, "queued request lost"


def check_draft_fault_fallback(seed):
    rng = np.random.default_rng(seed)
    tp = tfm.init_params(jax.random.key(seed), CFG)
    dp = tfm.init_params(jax.random.key(seed + 9), DRAFT)
    prompt = rng.integers(0, 64, (5,)).astype(np.int32)
    eng = SpeculativeBatcher(tp, dp, CFG, DRAFT, lanes=2, n_draft=3)
    lane = eng.submit(prompt, 8)
    eng.step()
    with FaultPlan(seed).fail("serving.draft"):
        eng.step()
    assert eng.degraded, "draft fault did not degrade the engine"
    while lane in eng.running():
        eng.step()
    np.testing.assert_array_equal(
        eng.drain(lane), np.asarray(generate(tp, prompt[None], CFG, 8))[0])


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--kill-rounds", default="3,7,12",
                    help="comma-separated rounds for the kill matrix")
    ap.add_argument("--trace", default=None,
                    help="write the obs event trace here (default: a "
                         "temp file, deleted after the timeline prints)")
    args = ap.parse_args()
    kills = [int(r) for r in args.kill_rounds.split(",")]

    matrix = []
    for r in kills:
        matrix.append((f"kill@round{r}/exception",
                       lambda r=r: check_kill_resume(args.seed, r, False)))
    matrix.append((f"kill@round{kills[0]}/sigterm",
                   lambda: check_kill_resume(args.seed, kills[0], True)))
    matrix += [
        ("checkpoint-save-fault", lambda: check_checkpoint_fault_retry(
            args.seed)),
        ("serving-deadlines", lambda: check_serving_deadlines(args.seed)),
        ("queue-backpressure", lambda: check_backpressure(args.seed)),
        ("draft-fault-fallback", lambda: check_draft_fault_fallback(
            args.seed)),
    ]

    import json

    from distkeras_tpu import obs
    from distkeras_tpu.obs.trace import read_trace

    trace_path = args.trace or os.path.join(
        tempfile.mkdtemp(prefix="chaos_obs_"), "chaos.jsonl")
    failures = 0
    with obs.session(trace_path=trace_path):
        for name, fn in matrix:
            obs.event("chaos_suite.check", check=name, status="start")
            try:
                fn()
                print(f"  PASS  {name}")
                obs.event("chaos_suite.check", check=name, status="pass")
            except Exception as e:  # noqa: BLE001 — report the matrix
                failures += 1
                print(f"  FAIL  {name}: {type(e).__name__}: {e}")
                obs.event("chaos_suite.check", check=name,
                          status="fail", error=repr(e)[:200])
            assert chaos.active_plan() is None, "a FaultPlan leaked"
    print(f"{len(matrix) - failures}/{len(matrix)} chaos checks passed")

    # Machine-readable fault/recovery timeline, straight off the obs
    # event trace: injected faults (chaos.fault), Supervisor attempts/
    # backoffs (supervisor.*), preemption checkpoints and engine
    # degradation — one JSON object per line, time-ordered.
    records = [r for r in read_trace(trace_path)
               if r.get("kind") == "event"]
    t0 = min((r["t"] for r in records), default=0.0)
    print("--- fault/recovery timeline (JSONL) ---")
    for r in sorted(records, key=lambda r: r["t"]):
        print(json.dumps({"t": round(r["t"] - t0, 4),
                          "event": r["name"], **r.get("fields", {})}))
    if args.trace:
        print(f"--- obs trace kept at {args.trace} "
              "(render: scripts/obs_report.py) ---")
    else:
        import shutil

        shutil.rmtree(os.path.dirname(trace_path), ignore_errors=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
