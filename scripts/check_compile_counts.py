"""Recompile guard: assert jit compile counts against a checked-in budget.

Silent shape-bucket regressions — a trainer that starts recompiling per
round because a batch shape stopped being static, a serving engine
whose admission path grows an extra program per prompt length — show up
as *throughput* losses long after the PR that caused them.  This guard
catches them at review time: it runs a fixed small session of each
subsystem (a plain and a ZeRO-1 ``ADAG`` round loop, an ``LMTrainer``
run, and a ``ContinuousBatcher`` serve session with two prompt buckets)
on the deterministic 8-device CPU mesh, counts actual backend compiles
via ``jax.monitoring``'s ``/jax/core/compile/backend_compile_duration``
event, and compares against ``scripts/compile_budget.json``.

Usage::

    python scripts/check_compile_counts.py           # check (rc=1 over budget)
    python scripts/check_compile_counts.py --update  # rewrite the budget

A session exceeding its budget fails; a session compiling *less* than
budget prints a note (ratchet the budget down with ``--update``).
Budgets are exact for this container's pinned jax; across jax upgrades
re-record with ``--update`` and review the diff.

NOTE: sessions run sequentially in ONE process, so later sessions'
budgets are *deltas on a warm jit cache* (e.g. ``lm_zero1`` measures
2, not ~20, because ``lm_trainer`` already compiled the shared
programs) — deterministic for the fixed SESSIONS order, but editing,
reordering, or inserting a session shifts every later budget.  After
any such change re-record with ``--update`` and review the whole
diff, not just the session you touched.
"""

import json
import os
import sys

# Deterministic substrate BEFORE jax initializes: the same 8-device CPU
# mesh the test suite uses (tests/conftest.py), so budgets are stable
# regardless of what accelerator is attached.
os.environ["KERAS_BACKEND"] = "jax"
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BUDGET_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "compile_budget.json")

_COMPILES = {"n": 0}


def _install_counter():
    import jax.monitoring

    def on_duration(event, duration, **kw):
        if event == "/jax/core/compile/backend_compile_duration":
            _COMPILES["n"] += 1

    jax.monitoring.register_event_duration_secs_listener(on_duration)


class _count:
    """Context manager: number of backend compiles inside the block."""

    def __enter__(self):
        self.start = _COMPILES["n"]
        return self

    def __exit__(self, *exc):
        self.n = _COMPILES["n"] - self.start


def session_adag(zero1: bool = False, device_data: bool = False,
                 rounds: int = 4, **opts):
    """Two ADAG rounds; every round after the first must hit the cache
    (one accum-step program; shapes are static by construction).
    ``device_data`` exercises the HBM-staged indexed path instead —
    its per-round traffic is one index block, so extra programs mean
    the staged plane regressed.  ``opts`` select exchange-layer
    variants (adasum / local-SGD): their shard_map merges must compile
    into the ONE step program, never per round."""
    import numpy as np

    import distkeras_tpu as dk

    rng = np.random.default_rng(0)
    x = rng.normal(size=(128, 8)).astype(np.float32)
    y = rng.integers(0, 4, 128).astype(np.int32)
    ds = dk.Dataset({"features": x, "label": y})
    import keras

    model = keras.Sequential([keras.layers.Input((8,)),
                              keras.layers.Dense(16, activation="relu"),
                              keras.layers.Dense(4)])
    t = dk.ADAG(model, loss="sparse_categorical_crossentropy",
                worker_optimizer="adam", learning_rate=0.05,
                batch_size=4, num_epoch=2, communication_window=2,
                zero1=zero1, device_data=device_data, **opts)
    t.train(ds)
    assert len(t.history) == rounds, t.history


def session_lm(zero1: bool = False, device_data: bool = False, **opts):
    """Four LMTrainer optimizer steps, one compiled step program
    (zero1: the sharded update must not add per-round programs;
    device_data: nor must the staged-stream gather; int8-EF: nor must
    the codec's residual carry)."""
    import numpy as np

    import distkeras_tpu as dk
    from distkeras_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=16)
    rows = np.random.default_rng(0).integers(
        0, 64, (32, 17)).astype(np.int32)
    t = dk.LMTrainer(cfg, learning_rate=1e-2, batch_size=8, num_epoch=1,
                     zero1=zero1, device_data=device_data, **opts)
    t.train(rows)
    assert len(t.history) == 4, t.history


def session_serving():
    """ContinuousBatcher session touching two prompt buckets: expected
    programs = one admission per touched bucket + the decode step
    (+ cache init).  A third bucket's worth of compiles appearing here
    means admission bucketing regressed."""
    import jax
    import numpy as np

    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.serving import ContinuousBatcher

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32, rope=True)
    params = tfm.init_params(jax.random.key(0), cfg)
    eng = ContinuousBatcher(params, cfg, lanes=2, prompt_buckets=(8, 16))
    rng = np.random.default_rng(0)
    lanes = [eng.submit(rng.integers(0, 64, (5,)).astype(np.int32), 6),
             eng.submit(rng.integers(0, 64, (12,)).astype(np.int32), 6)]
    for lane in lanes:
        while lane in eng.running():
            eng.step()
        eng.drain(lane)
    # Same-bucket re-admission must be compile-free.
    lane = eng.submit(rng.integers(0, 64, (7,)).astype(np.int32), 4)
    while lane in eng.running():
        eng.step()
    eng.drain(lane)


def session_speculative():
    """SpeculativeBatcher session: expected programs = target+draft
    admission (one bucket) + the fused draft/verify step; a second
    request in the same bucket must be compile-free."""
    import jax
    import numpy as np

    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.serving import SpeculativeBatcher

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32)
    draft = tfm.TransformerConfig(vocab_size=64, d_model=16, n_heads=2,
                                  n_layers=1, d_ff=32, max_len=32)
    eng = SpeculativeBatcher(
        tfm.init_params(jax.random.key(0), cfg),
        tfm.init_params(jax.random.key(1), draft),
        cfg, draft, lanes=2, n_draft=2, prompt_buckets=(8,))
    rng = np.random.default_rng(0)
    for _ in range(2):  # same bucket twice: re-admission compile-free
        lane = eng.submit(rng.integers(0, 64, (5,)).astype(np.int32), 6)
        while lane in eng.running():
            eng.step()
        eng.drain(lane)


def session_serving_elastic():
    """Elastic ContinuousBatcher session: EVERY program — each tier's
    decode step, each (tier, bucket) admission, the inter-tier resize
    gathers — compiles at construction; the overload -> step-up ->
    drain -> step-down cycle afterwards must be COMPILE-FREE (asserted
    here, not just budgeted: a post-construction compile means a tier
    program was missed and some request paid a recompile)."""
    import jax
    import numpy as np

    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.serving import ContinuousBatcher

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32,
                                rope=True)
    params = tfm.init_params(jax.random.key(0), cfg)
    eng = ContinuousBatcher(params, cfg, lane_tiers=(1, 2), max_queue=1,
                            scale_up_after=1, scale_down_after=2,
                            prompt_buckets=(8,))
    built = _COMPILES["n"]
    rng = np.random.default_rng(0)
    rids = [eng.enqueue(rng.integers(0, 64, (5,)).astype(np.int32), 6)
            for _ in range(3)]
    assert eng.lanes == 2, eng.lanes          # stepped up under load
    while any(eng.poll(r) is None for r in rids):
        eng.step()
    for _ in range(3):
        eng.step()                            # drained + idle: back down
    assert eng.lanes == 1, eng.lanes
    assert all(eng.take(r).ok for r in rids)
    serve_compiles = _COMPILES["n"] - built
    assert serve_compiles == 0, (
        f"elastic serve phase compiled {serve_compiles} program(s); "
        "tier compiles must all happen at construction")


def session_serving_chunked():
    """Chunked-prefill ContinuousBatcher session: every admission
    program (seeded + continuation per bucket) and the declared step
    window compile at CONSTRUCTION; the serve phase — a long prompt
    admitting in chunks interleaved with a short lane decoding — must
    be COMPILE-FREE (asserted, not just budgeted: a compile here means
    some chunk shape was missed and a request paid it)."""
    import jax
    import numpy as np

    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.serving import ContinuousBatcher

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32,
                                rope=True)
    params = tfm.init_params(jax.random.key(0), cfg)
    eng = ContinuousBatcher(params, cfg, lanes=2, prefill_chunk=8,
                            prompt_buckets=(8,))
    built = _COMPILES["n"]
    rng = np.random.default_rng(0)
    short = eng.submit(rng.integers(0, 64, (4,)).astype(np.int32), 8)
    eng.step()
    long_ = eng.submit(rng.integers(0, 64, (21,)).astype(np.int32), 4)
    for lane in (long_, short):
        while lane in eng.running():
            eng.step()
        eng.drain(lane)
    serve = _COMPILES["n"] - built
    assert serve == 0, (
        f"chunked serve phase compiled {serve} program(s); chunk "
        "programs must all compile at construction")


def session_serving_prefix_pool():
    """PrefixPool ContinuousBatcher session: pool construction + puts
    + engine construction compile everything (the pool's slab write,
    the pooled admission gathers, the reseed, the step window); the
    serve phase — two requests reusing pooled prefixes plus a plain
    request — must be COMPILE-FREE, proving prefix reuse runs zero
    prefill work and zero fresh programs."""
    import jax
    import numpy as np

    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.models.generate import prefill
    from distkeras_tpu.serving import ContinuousBatcher, PrefixPool

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32,
                                rope=True)
    params = tfm.init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(0)
    pool = PrefixPool(cfg, slots=2)
    for n in (6, 10):
        pref = rng.integers(0, 64, (1, n)).astype(np.int32)
        cache, _ = prefill(params, pref, cfg, last_logits=False)
        pool.put(cache, n)
    eng = ContinuousBatcher(params, cfg, lanes=2, prefix_pool=pool,
                            prompt_buckets=(8,))
    built = _COMPILES["n"]
    pids = pool.ids()
    tail = rng.integers(0, 64, (4,)).astype(np.int32)
    lanes = [eng.submit(tail, 4, prefix_id=pids[0]),
             eng.submit(tail, 4, prefix_id=pids[1])]
    for lane in lanes:
        while lane in eng.running():
            eng.step()
        eng.drain(lane)
    plain = eng.submit(tail, 4)
    while plain in eng.running():
        eng.step()
    eng.drain(plain)
    serve = _COMPILES["n"] - built
    assert serve == 0, (
        f"prefix-pool serve phase compiled {serve} program(s); the "
        "pooled gather must ride the construction-compiled admission")


def session_spec_prefix():
    """SpeculativeBatcher + prefix pool: admission/step programs
    compile lazily on the FIRST request cycle (the recorded budget);
    the second cycle — reusing the pooled prefix AND a fresh plain
    request — must be compile-free."""
    import jax
    import numpy as np

    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.models.generate import prefill
    from distkeras_tpu.serving import PrefixPool, SpeculativeBatcher

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32)
    draft = tfm.TransformerConfig(vocab_size=64, d_model=16, n_heads=2,
                                  n_layers=1, d_ff=32, max_len=32)
    params = tfm.init_params(jax.random.key(0), cfg)
    dparams = tfm.init_params(jax.random.key(1), draft)
    rng = np.random.default_rng(0)
    pref = rng.integers(0, 64, (1, 6)).astype(np.int32)
    tc, _ = prefill(params, pref, cfg, last_logits=False)
    dc, _ = prefill(dparams, pref, draft, last_logits=False)
    pool = PrefixPool(cfg, slots=1, draft_cfg=draft)
    pid = pool.put((tc, dc), 6, last_token=int(pref[0, -1]))
    eng = SpeculativeBatcher(params, dparams, cfg, draft, lanes=2,
                             n_draft=2, prompt_buckets=(8,),
                             prefix_pool=pool)
    tail = rng.integers(0, 64, (4,)).astype(np.int32)

    def cycle():
        lanes = [eng.submit(tail, 4, prefix_id=pid),
                 eng.submit(tail, 4)]
        for lane in lanes:
            while lane in eng.running():
                eng.step()
            eng.drain(lane)

    cycle()                       # warm: buckets + step compile here
    warm = _COMPILES["n"]
    cycle()                       # steady state: prefix reuse is free
    serve = _COMPILES["n"] - warm
    assert serve == 0, (
        f"speculative prefix reuse compiled {serve} program(s) in "
        "steady state; re-admission must hit the warm jit caches")


def session_obs_live():
    """Live telemetry plane (round 11): a ContinuousBatcher serve
    session with a running TelemetryServer + SLO ticker, scraped
    mid-decode.  The server/ticker are stdlib threads that only READ
    the registry, so the live phase — decode steps interleaved with
    /metrics and /metrics/cluster scrapes, /healthz probes, and
    explicit SLO ticks — must add ZERO compiled programs (asserted
    here; the recorded budget is the engine's own warm-up).

    Round 12: the whole session runs under the LOCK SANITIZER
    (utils/locks.py) — every engine/registry/SLO lock is instrumented
    from construction on — asserting both that the sanitizer itself
    is jax-free (zero extra programs: the budget is unchanged from
    the un-sanitized recording) and that the live plane's lock
    discipline is violation-free under real scrape traffic."""
    from distkeras_tpu.utils import locks

    was_enabled = locks.sanitizer_enabled()
    locks.enable_sanitizer()
    try:
        _session_obs_live_sanitized()
    finally:
        # Restore, don't blindly disable: a later session must not
        # silently run un-sanitized when the environment asked for
        # DKT_LOCK_SANITIZER process-wide, and an assertion failure
        # above must not leave state dependent on the failure path.
        if not was_enabled:
            locks.disable_sanitizer()


def _session_obs_live_sanitized():
    import urllib.request

    import jax
    import numpy as np

    from distkeras_tpu import obs
    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.serving import ContinuousBatcher
    from distkeras_tpu.utils import locks

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32,
                                rope=True)
    params = tfm.init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(0)
    eng = ContinuousBatcher(params, cfg, lanes=2, max_queue=4,
                            prompt_buckets=(8,))
    # Warm every program OUTSIDE the live phase.
    rid = eng.enqueue(rng.integers(0, 64, (5,)).astype(np.int32), 4)
    while eng.poll(rid) is None:
        eng.step()
    eng.take(rid)
    rules = [obs.SloRule("serving.request_s", percentile=0.99,
                         threshold=60.0, window_s=10.0)]
    with obs.session(serve_port=0, slo_rules=rules) as sess:
        live = _COMPILES["n"]
        url = sess.server.url
        rids = [eng.enqueue(rng.integers(0, 64, (5,)).astype(np.int32),
                            6) for _ in range(3)]
        while any(eng.poll(r) is None for r in rids):
            eng.step()
            urllib.request.urlopen(url + "/metrics", timeout=5).read()
            sess.slo.tick()
        urllib.request.urlopen(url + "/metrics/cluster",
                               timeout=5).read()
        urllib.request.urlopen(url + "/healthz", timeout=5).read()
        assert all(eng.take(r).ok for r in rids)
        live_compiles = _COMPILES["n"] - live
        assert live_compiles == 0, (
            f"live telemetry phase compiled {live_compiles} "
            "program(s); the scrape server and SLO ticker must only "
            "READ the registry (sanitizer enabled: utils/locks.py "
            "must stay jax-free)")
    vs = locks.violations()
    assert not vs, "lock sanitizer violations in the live session:\n" \
        + "\n".join(v.format() for v in vs)


def session_serving_paged():
    """Paged-KV ContinuousBatcher session (round 12): EVERY program —
    the page-table-gather step windows, one admission program per
    bucket, the CoW block copy / row fork — compiles at construction;
    the serve phase (a plain admission, a stem-SHARING admission that
    refcounts the first request's prompt blocks, decode, drain, and a
    re-admission) must be COMPILE-FREE (asserted: a compile here means
    some paged program shape was missed and a request paid it)."""
    import jax
    import numpy as np

    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.serving import PagedBatcher

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32,
                                rope=True)
    params = tfm.init_params(jax.random.key(0), cfg)
    eng = PagedBatcher(params, cfg, lanes=2, block=8, n_blocks=9,
                       prompt_buckets=(8,))
    built = _COMPILES["n"]
    rng = np.random.default_rng(0)
    stem = rng.integers(0, 64, (8,)).astype(np.int32)
    tails = rng.integers(0, 64, (2, 4)).astype(np.int32)
    lanes = [eng.submit(np.concatenate([stem, tails[0]]), 6),
             eng.submit(np.concatenate([stem, tails[1]]), 6)]
    assert eng.allocator.stats()["shared"] >= 1  # the stem hash hit
    for lane in lanes:
        while lane in eng.running():
            eng.step()
        eng.drain(lane)
    again = eng.submit(rng.integers(0, 64, (5,)).astype(np.int32), 4)
    while again in eng.running():
        eng.step()
    eng.drain(again)
    serve = _COMPILES["n"] - built
    assert serve == 0, (
        f"paged serve phase compiled {serve} program(s); every paged "
        "program must compile at construction")


def session_serving_paged_cow():
    """Paged CoW session: forking a mid-decode lane (share full
    blocks, copy the divergent tail block) and decoding both branches
    must ride the construction-compiled block-copy/row-fork programs —
    the fork path itself is asserted compile-free."""
    import jax
    import numpy as np

    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.serving import PagedBatcher

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32,
                                rope=True)
    params = tfm.init_params(jax.random.key(0), cfg)
    eng = PagedBatcher(params, cfg, lanes=3, block=8, n_blocks=13,
                       prompt_buckets=(8,))
    built = _COMPILES["n"]
    rng = np.random.default_rng(0)
    src = eng.submit(rng.integers(0, 64, (6,)).astype(np.int32), 10)
    for _ in range(3):
        eng.step()
    alt = (eng._lane_state[src].tokens[-1] + 1) % 64
    fork = eng.fork(src, token=alt)
    assert fork is not None
    for lane in (src, fork):
        while lane in eng.running():
            eng.step()
        eng.drain(lane)
    serve = _COMPILES["n"] - built
    assert serve == 0, (
        f"paged CoW serve phase compiled {serve} program(s); the fork "
        "must ride the construction-compiled block-copy/row-fork "
        "programs")


def session_serving_router():
    """Fleet router session (round 13): TWO in-process paged replicas
    behind a cache-aware Router.  Engine construction compiles
    everything (the recorded budget — a warm-cache delta after the
    serving_paged sessions); the ROUTE-AND-SERVE phase — affinity
    scoring, stem-shared and fresh admissions through the router,
    drain-and-reroute off a drained replica, residency refresh — is
    asserted to compile ZERO programs: the router is jax-free host
    bookkeeping, and a routing decision must never trigger device
    work."""
    import jax
    import numpy as np

    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.serving import (InProcessReplica, PagedBatcher,
                                       Router)

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32,
                                rope=True)
    params = tfm.init_params(jax.random.key(0), cfg)
    engines = [PagedBatcher(params, cfg, lanes=2, block=8, n_blocks=17,
                            max_queue=4, prompt_buckets=(8,))
               for _ in range(2)]
    built = _COMPILES["n"]
    router = Router([InProcessReplica(f"r{i}", e)
                     for i, e in enumerate(engines)])
    rng = np.random.default_rng(0)
    stem = rng.integers(0, 64, (8,)).astype(np.int32)
    rids = [router.enqueue(np.concatenate(
        [stem, rng.integers(0, 64, (4,)).astype(np.int32)]), 5)
        for _ in range(3)]
    rids.append(router.enqueue(
        rng.integers(0, 64, (5,)).astype(np.int32), 5))
    while any(router.poll(r) is None for r in rids):
        router.step()
    assert all(router.take(r).status == "ok" for r in rids)
    assert sum(e.stem_hit_blocks for e in engines) >= 2, \
        "affinity routing never hit a resident stem"
    # Drain-and-reroute rides the same warm programs.
    busy = router.replicas_up()[0]
    more = [router.enqueue(np.concatenate(
        [stem, rng.integers(0, 64, (4,)).astype(np.int32)]), 5)
        for _ in range(2)]
    router.drain_replica(busy)
    while any(router.poll(r) is None for r in more):
        router.step()
    assert all(router.take(r).status == "ok" for r in more)
    router.refresh_residency()
    serve = _COMPILES["n"] - built
    assert serve == 0, (
        f"router route-and-serve phase compiled {serve} program(s); "
        "routing is host bookkeeping — a routing decision must never "
        "trigger device work")


def session_serving_sharded():
    """Pod-sharded ContinuousBatcher (round 14): ONE engine replica
    spans the 8-CPU mesh (data=4, model=2) under ``serving_plan()`` —
    params TP-sharded, the KV cache's kv-heads dim sharded over
    ``model``, GSPMD's per-token collectives compiled in.  EVERY
    program compiles at construction (the recorded budget); the serve
    phase — two admissions in different buckets, interleaved decode,
    drain, and a same-bucket re-admission — is ASSERTED compile-free:
    a compile here means some sharded program shape (or a committed-
    array placement mismatch between warm-up and live state) was
    missed and a request paid it."""
    import jax
    import numpy as np

    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh
    from distkeras_tpu.parallel.sharding import serving_plan
    from distkeras_tpu.serving import ContinuousBatcher

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32,
                                rope=True)
    params = tfm.init_params(jax.random.key(0), cfg)
    mesh = make_mesh(MeshSpec(data=4, model=2))
    eng = ContinuousBatcher(params, cfg, lanes=2,
                            prompt_buckets=(8, 16),
                            plan=serving_plan(), mesh=mesh)
    built = _COMPILES["n"]
    rng = np.random.default_rng(0)
    lanes = [eng.submit(rng.integers(0, 64, (5,)).astype(np.int32), 6),
             eng.submit(rng.integers(0, 64, (12,)).astype(np.int32), 6)]
    for lane in lanes:
        while lane in eng.running():
            eng.step()
        eng.drain(lane)
    again = eng.submit(rng.integers(0, 64, (7,)).astype(np.int32), 4)
    while again in eng.running():
        eng.step()
    eng.drain(again)
    serve = _COMPILES["n"] - built
    assert serve == 0, (
        f"sharded serve phase compiled {serve} program(s); every "
        "sharded program must compile at construction and live state "
        "placement must match the warm-up's")


def session_serving_sharded_paged():
    """Pod-sharded PagedBatcher: the block slab's kv-heads dim shards
    over ``model`` exactly like the monolithic cache; stem-sharing
    admission, decode growth, drain, and re-admission on the sharded
    slab are ASSERTED compile-free after construction (the page-table
    pushes are transfers, never compiles — replicated placement is
    pinned by the warm-up)."""
    import jax
    import numpy as np

    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh
    from distkeras_tpu.parallel.sharding import serving_plan
    from distkeras_tpu.serving import PagedBatcher

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32,
                                rope=True)
    params = tfm.init_params(jax.random.key(0), cfg)
    mesh = make_mesh(MeshSpec(data=4, model=2))
    eng = PagedBatcher(params, cfg, lanes=2, block=8, n_blocks=9,
                       prompt_buckets=(8,), plan=serving_plan(),
                       mesh=mesh)
    built = _COMPILES["n"]
    rng = np.random.default_rng(0)
    stem = rng.integers(0, 64, (8,)).astype(np.int32)
    tails = rng.integers(0, 64, (2, 4)).astype(np.int32)
    lanes = [eng.submit(np.concatenate([stem, tails[0]]), 6),
             eng.submit(np.concatenate([stem, tails[1]]), 6)]
    assert eng.allocator.stats()["shared"] >= 1  # sharing still works
    for lane in lanes:
        while lane in eng.running():
            eng.step()
        eng.drain(lane)
    again = eng.submit(rng.integers(0, 64, (5,)).astype(np.int32), 4)
    while again in eng.running():
        eng.step()
    eng.drain(again)
    serve = _COMPILES["n"] - built
    assert serve == 0, (
        f"sharded paged serve phase compiled {serve} program(s); "
        "paging must compose with the sharded slab at zero "
        "steady-state compiles")


# NOTE: new sessions append at the END — inserting one mid-dict would
# shift every later session's warm-cache delta budget (module
# docstring).
def session_async(hosts: int = 2, batch_size: int = 4, rounds: int = 4,
                  **opts):
    """AsyncDP rounds across ``hosts`` simulated hosts: every host
    shares the ONE compiled intra-host accumulation step, and the
    plane's encode/merge kernels (int8 EF, adasum tree) compile once
    each — fleet size must never scale the program count (the
    ``async_tree`` session's warm-cache delta over ``adag_async``
    pins exactly that)."""
    import numpy as np

    import distkeras_tpu as dk

    rng = np.random.default_rng(0)
    x = rng.normal(size=(128, 8)).astype(np.float32)
    y = rng.integers(0, 4, 128).astype(np.int32)
    ds = dk.Dataset({"features": x, "label": y})
    import keras

    model = keras.Sequential([keras.layers.Input((8,)),
                              keras.layers.Dense(16, activation="relu"),
                              keras.layers.Dense(4)])
    t = dk.AsyncDP(model, loss="sparse_categorical_crossentropy",
                   worker_optimizer="adam", learning_rate=0.05,
                   batch_size=batch_size, num_epoch=2,
                   communication_window=2, hosts=hosts, **opts)
    t.train(ds)
    assert len(t.history) == rounds, t.history
    assert t.async_report["version"] == rounds, t.async_report


def session_serving_sharded_elastic():
    """lane_tiers x plan (round 17): a POD-SHARDED elastic engine —
    monolithic and paged — compiles every tier's sharded programs and
    the inter-tier resize gathers at construction; the serve phase
    INCLUDING a tier move up and back down is ASSERTED compile-free.
    A compile here means a tier's sharded program (or the paged
    engine's rows-only resize) was missed at warm-up and a live
    resize paid it."""
    import jax
    import numpy as np

    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh
    from distkeras_tpu.parallel.sharding import serving_plan
    from distkeras_tpu.serving import ContinuousBatcher, PagedBatcher

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32,
                                rope=True)
    params = tfm.init_params(jax.random.key(0), cfg)
    mesh = make_mesh(MeshSpec(data=4, model=2))
    rng = np.random.default_rng(0)
    for kind in ("cb", "paged"):
        kw = dict(lane_tiers=(1, 2), max_queue=1, scale_up_after=1,
                  scale_down_after=2, prompt_buckets=(8,),
                  plan=serving_plan(), mesh=mesh)
        if kind == "cb":
            eng = ContinuousBatcher(params, cfg, **kw)
        else:
            eng = PagedBatcher(params, cfg, block=8, **kw)
        built = _COMPILES["n"]
        rids = [eng.enqueue(rng.integers(0, 64, (5,)).astype(np.int32),
                            6) for _ in range(3)]
        assert eng.lanes == 2, eng.lanes      # stepped up under load
        while any(eng.poll(r) is None for r in rids):
            eng.step()
        for _ in range(3):
            eng.step()                        # drained: back down
        assert eng.lanes == 1, eng.lanes
        assert all(eng.take(r).ok for r in rids)
        serve = _COMPILES["n"] - built
        assert serve == 0, (
            f"sharded elastic ({kind}) serve phase compiled {serve} "
            "program(s); every tier's sharded programs and the resize "
            "gathers must compile at construction")


def session_serving_disagg():
    """Disaggregated prefill/decode (round 17): a prefill engine
    exports a prompt's KV blocks through the wire codec, a decode
    engine adopts them by page-table splice, and decode runs on the
    adopted stem.  The whole export -> ship -> import -> decode path
    is ASSERTED compile-free after construction — the extract/adopt
    block programs warm at construction with template blocks placed
    exactly like live wire payloads, so adoption never compiles."""
    import jax
    import numpy as np

    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.serving import (PagedBatcher, decode_shipment,
                                       encode_shipment)

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32,
                                rope=True)
    params = tfm.init_params(jax.random.key(0), cfg)
    pre = PagedBatcher(params, cfg, lanes=2, block=8,
                       prompt_buckets=(8, 16))
    dec = PagedBatcher(params, cfg, lanes=2, block=8,
                       prompt_buckets=(8, 16))
    built = _COMPILES["n"]
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, 64, (19,)).astype(np.int32)
    ship = pre.export_blocks(prompt)
    assert ship is not None and len(ship.hashes) == 2
    imported = dec.import_blocks(decode_shipment(encode_shipment(ship)))
    assert imported is not None and imported["blocks"] == 2
    lane = dec.submit(prompt, 6)
    while lane in dec.running():
        dec.step()
    dec.drain(lane)
    dec.unpin_prefix(imported["prefix_id"])
    serve = _COMPILES["n"] - built
    assert serve == 0, (
        f"disagg export/import/decode compiled {serve} program(s); "
        "block extract/adopt must warm at construction and the "
        "adopted stem must decode on the existing admission programs")


def session_spec_sharded():
    """Pod-sharded SpeculativeBatcher (round 17): the target model
    shards per the plan, the draft replicates, and _warm_sharded
    compiles every serve-phase program — the step, both per-bucket
    admissions, the host lane-slot scatters — at construction; the
    admit/decode/drain/re-admit phase is ASSERTED compile-free."""
    import jax
    import numpy as np

    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh
    from distkeras_tpu.parallel.sharding import serving_plan
    from distkeras_tpu.serving import SpeculativeBatcher

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32,
                                rope=True)
    draft_cfg = tfm.TransformerConfig(vocab_size=64, d_model=16,
                                      n_heads=2, n_layers=1, d_ff=32,
                                      max_len=32, rope=True)
    params = tfm.init_params(jax.random.key(0), cfg)
    draft = tfm.init_params(jax.random.key(8), draft_cfg)
    mesh = make_mesh(MeshSpec(data=4, model=2))
    eng = SpeculativeBatcher(params, draft, cfg, draft_cfg, lanes=2,
                             n_draft=3, prompt_buckets=(8, 16),
                             plan=serving_plan(), mesh=mesh)
    built = _COMPILES["n"]
    rng = np.random.default_rng(0)
    lanes = [eng.submit(rng.integers(1, 64, (5,)).astype(np.int32), 6),
             eng.submit(rng.integers(1, 64, (12,)).astype(np.int32), 6)]
    for lane in lanes:
        while lane in eng.running():
            eng.step()
        eng.drain(lane)
    again = eng.submit(rng.integers(1, 64, (7,)).astype(np.int32), 4)
    while again in eng.running():
        eng.step()
    eng.drain(again)
    serve = _COMPILES["n"] - built
    assert serve == 0, (
        f"sharded speculative serve phase compiled {serve} "
        "program(s); every program must warm at construction "
        "(_warm_sharded) with live-matching placements")


def session_serving_autoscale():
    """Autoscaling control plane (round 19): ONE active paged replica
    plus a pre-compiled warm-pool replica behind the Autoscaler.
    Engine construction compiles everything (the recorded budget — a
    warm-cache delta after the serving_router session, which runs the
    identical geometry); the entire ELASTIC phase — saturate, the
    health-gated warm-pool join, serving on the freshly joined
    replica, lossless drain-and-retire scale-down, and serving after
    the shrink — is asserted to compile ZERO programs: a scale-up is
    a route-table insert of an already-warm engine, never a compile,
    and a scale-down is the router's existing drain-and-reroute."""
    import jax
    import numpy as np

    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.serving import (Autoscaler, AutoscalePolicy,
                                       InProcessReplica, PagedBatcher,
                                       Router, WarmPool)

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32,
                                rope=True)
    params = tfm.init_params(jax.random.key(0), cfg)
    engines = [PagedBatcher(params, cfg, lanes=2, block=8, n_blocks=17,
                            max_queue=4, prompt_buckets=(8,))
               for _ in range(2)]
    built = _COMPILES["n"]
    router = Router([InProcessReplica("r0", engines[0])])
    pool = WarmPool([InProcessReplica("w0", engines[1])])
    asc = Autoscaler(router, pool, policy=AutoscalePolicy(
        min_replicas=1, max_replicas=2, up_after=1, down_after=1,
        cooldown_ticks=0))
    rng = np.random.default_rng(0)
    stem = rng.integers(0, 64, (8,)).astype(np.int32)
    # Saturate past r0's bounded queue: the spillover backlog votes
    # hot and the next tick admits w0 from the warm pool.
    rids = [router.enqueue(np.concatenate(
        [stem, rng.integers(0, 64, (4,)).astype(np.int32)]), 5)
        for _ in range(6)]
    rec = asc.tick()
    assert rec["action"] == "up" and rec["replica"] == "w0", \
        f"saturated fleet did not scale up: {rec}"
    while any(router.poll(r) is None for r in rids):
        router.step()
        router.pump()
    assert all(router.take(r).status == "ok" for r in rids)
    # Idle fleet scales back down; the retire is the router's
    # drain-and-reroute, and the handle returns to the pool warm.
    rec = asc.tick()
    assert rec["action"] == "down", f"idle fleet held: {rec}"
    assert len(router.replicas_up()) == 1 and len(pool) == 1
    after = router.enqueue(np.concatenate(
        [stem, rng.integers(0, 64, (4,)).astype(np.int32)]), 5)
    while router.poll(after) is None:
        router.step()
    assert router.take(after).status == "ok"
    serve = _COMPILES["n"] - built
    assert serve == 0, (
        f"autoscale join/retire cycle compiled {serve} program(s); a "
        "warm-pool join must be a route-table insert of an already-"
        "warm engine and a retire the existing drain-and-reroute — "
        "never device work")


def session_serving_weight_push():
    """Live weight push (round 20): two hot_swap ContinuousBatchers
    behind a Router with the publish→canary plumbing.  Construction
    compiles everything — the hot_swap step/admission programs take
    params as an explicit jit argument, and the canary's logit-drift
    probe is compiled and warmed in the controller's constructor (the
    recorded budget).  The entire PUSH phase — serve, a promoted
    rollout (canary swap, drift probe, fleet-wide swap), serving the
    new version, a rejected NaN push rolled back, and serving after
    the rollback — is asserted to compile ZERO programs: a weight
    swap is a host-side rebind that reproduces the live placement,
    never a recompile."""
    import tempfile

    import jax
    import numpy as np

    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.serving import (ContinuousBatcher,
                                       InProcessReplica, Router)
    from distkeras_tpu.serving.canary import CanaryController
    from distkeras_tpu.serving.publish import (SnapshotPublisher,
                                               SnapshotReader)

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32,
                                rope=True)
    params = tfm.init_params(jax.random.key(0), cfg)
    engines = [ContinuousBatcher(params, cfg, lanes=2, hot_swap=True)
               for _ in range(2)]
    router = Router([InProcessReplica(f"r{i}", e)
                     for i, e in enumerate(engines)])
    root = tempfile.mkdtemp()
    pub = SnapshotPublisher(root)
    template = jax.eval_shape(
        lambda: tfm.init_params(jax.random.key(0), cfg))
    ctl = CanaryController(router, SnapshotReader(root), cfg, template)
    built = _COMPILES["n"]

    def serve():
        rids = [router.enqueue([1 + i, 2, 3], 5) for i in range(3)]
        for r in rids:
            router.drain(r)

    serve()
    good = jax.tree.map(np.asarray,
                        tfm.init_params(jax.random.key(1), cfg))
    pub.publish(good, 1)
    rec = ctl.poll()
    assert rec["action"] == "promote", f"good push not promoted: {rec}"
    serve()
    bad = jax.tree.map(lambda a: np.full_like(a, np.nan), good)
    pub.publish(bad, 2)
    rec = ctl.poll()
    assert rec["action"] == "rollback", f"NaN push not rejected: {rec}"
    serve()
    swap = _COMPILES["n"] - built
    assert swap == 0, (
        f"weight push cycle compiled {swap} program(s); a live swap "
        "must rebind the params argument under the live placement — "
        "a compile here means the swapped tree re-keyed the jit "
        "cache (committedness or layout drift)")


SESSIONS = {
    "adag": lambda: session_adag(),
    "adag_zero1": lambda: session_adag(zero1=True),
    "adag_device_data": lambda: session_adag(device_data=True),
    "adag_adasum": lambda: session_adag(merge_rule="adasum"),
    # sync_every=2 consumes 2x the rows per round: 2 rounds total.
    "adag_localsgd": lambda: session_adag(sync_every=2, rounds=2),
    "lm_trainer": lambda: session_lm(),
    "lm_zero1": lambda: session_lm(zero1=True),
    "lm_device_data": lambda: session_lm(device_data=True),
    "lm_int8ef": lambda: session_lm(compress="int8"),
    "serving": session_serving,
    "speculative": session_speculative,
    "serving_elastic": session_serving_elastic,
    "serving_chunked": session_serving_chunked,
    "serving_prefix_pool": session_serving_prefix_pool,
    "spec_prefix": session_spec_prefix,
    "obs_live": session_obs_live,
    # ZeRO-2/3 (docs/zero1.md): the in-scan scattered accumulator and
    # the gather-on-use view carry must each stay ONE step program —
    # an extra program here means a stage started recompiling per
    # round (e.g. the view layout stopped being trace-stable).  The
    # codec-rules session pins the per-bucket (topk + int8) exchange
    # to one program likewise.
    "adag_zero2": lambda: session_adag(zero=2),
    "lm_zero3": lambda: session_lm(zero=3),
    "lm_codec_rules": lambda: session_lm(
        compress=(("emb", "topk"), (".*", "int8"))),
    # Paged KV (round 12): construction compiles everything — gather
    # steps, per-bucket block-scatter admission, CoW block copy + row
    # fork — and both serve phases are ASSERTED compile-free inside
    # the session (the budget is the construction warm-up only).
    "serving_paged": session_serving_paged,
    "serving_paged_cow": session_serving_paged_cow,
    # Fleet router (round 13): engine construction is the budget; the
    # route-and-serve phase over 2 in-process replicas is ASSERTED
    # zero-compile inside the session (the router is jax-free).
    "serving_router": session_serving_router,
    # Pod-sharded serving (round 14): construction compiles every
    # sharded program (params TP-placed, KV heads sharded over
    # ``model``, GSPMD collectives in the step); both serve phases are
    # ASSERTED compile-free inside the session — the acceptance bar
    # for "one router replica is a whole mesh".
    "serving_sharded": session_serving_sharded,
    "serving_sharded_paged": session_serving_sharded_paged,
    # Async tier (docs/async.md): 2 hosts on the int8 wire, then a
    # 4-host adasum aggregation tree — the tree session rides the
    # 2-host session's cache, so its delta is the marginal cost of
    # growing the fleet (must be ~zero new programs, or the plane
    # started recompiling per host).
    "adag_async": lambda: session_async(
        hosts=2, tau=2, async_merge="adasum", async_compress="int8"),
    "async_tree": lambda: session_async(
        hosts=4, batch_size=2, rounds=8, tau=2, fanout=2,
        async_merge="adasum", async_compress="int8"),
    # Round 17: elastic tiers compose with plan= (both engine
    # families — serve phase incl. a live tier move asserted
    # zero-compile), and the disaggregated block-transfer path
    # (export -> wire -> adopt -> decode) is likewise asserted
    # compile-free after construction.
    "serving_sharded_elastic": session_serving_sharded_elastic,
    "serving_disagg": session_serving_disagg,
    "spec_sharded": session_spec_sharded,
    # Round 19: the autoscaler's warm-pool join + scale-down cycle is
    # ASSERTED zero-compile inside the session (appended LAST so every
    # earlier warm-cache budget delta is unchanged).
    "serving_autoscale": session_serving_autoscale,
    # Round 20: the train→serve weight push — swap + serve phases are
    # ASSERTED zero-compile inside the session (appended LAST so every
    # earlier warm-cache budget delta is unchanged).
    "serving_weight_push": session_serving_weight_push,
}


def main(argv):
    update = "--update" in argv
    _install_counter()

    measured = {}
    for name, fn in SESSIONS.items():
        with _count() as c:
            fn()
        measured[name] = c.n
        print(f"{name}: {c.n} compiles", file=sys.stderr)

    if update:
        with open(BUDGET_PATH, "w") as f:
            json.dump({"comment": "backend compiles per session on the "
                                  "8-device CPU mesh; re-record with "
                                  "--update on jax upgrades",
                       "budgets": measured}, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {BUDGET_PATH}: {measured}")
        return 0

    try:
        with open(BUDGET_PATH) as f:
            budgets = json.load(f)["budgets"]
    except (OSError, ValueError, KeyError):
        print(f"no readable budget at {BUDGET_PATH}; run with --update "
              "to record one", file=sys.stderr)
        return 1

    rc = 0
    for name, n in measured.items():
        budget = budgets.get(name)
        if budget is None:
            print(f"FAIL {name}: no budget recorded (run --update)")
            rc = 1
        elif n > budget:
            print(f"FAIL {name}: {n} compiles > budget {budget} — a "
                  "shape bucket regressed (something recompiles per "
                  "round/request)")
            rc = 1
        elif n < budget:
            print(f"ok   {name}: {n} compiles (budget {budget} is stale "
                  "— consider --update to ratchet down)")
        else:
            print(f"ok   {name}: {n} compiles == budget")
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
