"""Full benchmark suite on the current accelerator (one JSON line per
config; bench.py stays the single-headline driver).

Methodology (same as bench.py): bf16 compute policy, jitted train step
with donated state, device-resident synthetic data, warmup, then a
timed run whose barrier is a device->host float() through the step
dependency chain (the axon relay's block_until_ready returns early).

Usage: python scripts/bench_suite.py [config ...]
Configs: mnist_mlp cifar_cnn higgs_mlp imdb_lstm resnet50 transformer
"""

import json
import os
import sys
import time

os.environ.setdefault("KERAS_BACKEND", "jax")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure_keras(build, shape, classes, batch, iters, warmup=10,
                  int_input=False, vocab=None, scan_steps=1):
    """``scan_steps`` > 1 uses the multi-step scan path
    (SingleTrainer(steps_per_call=...)): several optimizer updates per
    XLA call, amortizing host dispatch for small models."""
    import jax
    import numpy as np
    from distkeras_tpu.models.adapter import ModelAdapter

    model = build()
    adapter = ModelAdapter(model, loss=(
        "binary_crossentropy" if classes == 1
        else "sparse_categorical_crossentropy"),
        optimizer="sgd", learning_rate=0.01)
    state = adapter.init_state()
    if scan_steps > 1:
        step = jax.jit(adapter.make_multi_train_step(scan_steps),
                       donate_argnums=0)
        lead = (scan_steps, batch)
    else:
        step = jax.jit(adapter.make_train_step(), donate_argnums=0)
        lead = (batch,)

    rng = np.random.default_rng(0)
    if int_input:
        x = jax.device_put(rng.integers(0, vocab, (*lead, *shape))
                           .astype(np.int32))
    else:
        x = jax.device_put(rng.normal(size=(*lead, *shape))
                           .astype(np.float32))
    y = jax.device_put(rng.integers(0, max(classes, 2), lead)
                       .astype(np.float32 if classes == 1 else np.int64))

    for _ in range(warmup):
        state, loss = step(state, x, y)
    float(np.asarray(loss).ravel()[-1])  # device->host: the true barrier
    t0 = time.perf_counter()
    for _ in range(iters):
        state, loss = step(state, x, y)
    float(np.asarray(loss).ravel()[-1])
    dt = time.perf_counter() - t0
    steps = iters * scan_steps
    return batch * steps / dt, dt / steps


def bench_mnist_mlp():
    import keras
    from distkeras_tpu.models.zoo import mnist_mlp

    keras.mixed_precision.set_global_policy("mixed_bfloat16")
    return measure_keras(lambda: mnist_mlp(seed=0), (784,), 10,
                         batch=4096, iters=60, scan_steps=8)


def bench_cifar_cnn():
    import keras
    from distkeras_tpu.models.zoo import cifar_cnn

    keras.mixed_precision.set_global_policy("mixed_bfloat16")
    return measure_keras(lambda: cifar_cnn(seed=0), (32, 32, 3), 10,
                         batch=1024, iters=300)


def bench_higgs_mlp():
    import keras
    from distkeras_tpu.models.zoo import higgs_mlp

    keras.mixed_precision.set_global_policy("mixed_bfloat16")
    return measure_keras(lambda: higgs_mlp(seed=0), (28,), 2,
                         batch=4096, iters=60, scan_steps=8)


def bench_imdb_lstm():
    import keras
    from distkeras_tpu.models.zoo import imdb_lstm

    keras.mixed_precision.set_global_policy("mixed_bfloat16")
    return measure_keras(
        lambda: imdb_lstm(vocab_size=20000, maxlen=128, seed=0), (128,), 1,
        batch=512, iters=100, int_input=True, vocab=20000)


def bench_resnet50():
    import keras
    from distkeras_tpu.models.zoo import resnet50

    keras.mixed_precision.set_global_policy("mixed_bfloat16")
    return measure_keras(lambda: resnet50(seed=0), (224, 224, 3), 1000,
                         batch=128, iters=50, warmup=5)


def bench_transformer():
    """Flagship LM: tokens/sec with the Pallas flash-attention path."""
    import jax
    import numpy as np
    import optax
    from distkeras_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(
        vocab_size=32768, d_model=512, n_heads=4, n_layers=4, d_ff=2048,
        max_len=1025, dtype="bfloat16")
    params = tfm.init_params(jax.random.key(0), cfg)
    opt = optax.adamw(3e-4)
    step = jax.jit(tfm.make_train_step(cfg, opt), donate_argnums=0)
    carry = (params, opt.init(params))

    batch, seq = 8, 1024
    rng = np.random.default_rng(0)
    tokens = jax.device_put(
        rng.integers(0, cfg.vocab_size, (batch, seq + 1)).astype(np.int32))
    for _ in range(5):
        carry, loss = step(carry, tokens)
    float(loss)
    iters = 50
    t0 = time.perf_counter()
    for _ in range(iters):
        carry, loss = step(carry, tokens)
    float(loss)
    dt = time.perf_counter() - t0
    return batch * seq * iters / dt, dt / iters


BENCHES = {
    "mnist_mlp": (bench_mnist_mlp, "samples/sec/chip"),
    "cifar_cnn": (bench_cifar_cnn, "samples/sec/chip"),
    "higgs_mlp": (bench_higgs_mlp, "samples/sec/chip"),
    "imdb_lstm": (bench_imdb_lstm, "samples/sec/chip"),
    "resnet50": (bench_resnet50, "samples/sec/chip"),
    "transformer": (bench_transformer, "tokens/sec/chip"),
}


def main(names):
    import jax

    unknown = set(names) - set(BENCHES)
    if unknown:
        sys.exit(f"unknown config(s) {sorted(unknown)}; "
                 f"choose from {sorted(BENCHES)}")
    print(f"# backend={jax.default_backend()} device={jax.devices()[0]}",
          file=sys.stderr)
    for name in names or BENCHES:
        fn, unit = BENCHES[name]
        try:
            rate, step_s = fn()
        except Exception as e:  # keep the suite going; record the failure
            print(json.dumps({"metric": name, "error": repr(e)[:200]}))
            continue
        print(json.dumps({
            "metric": name, "value": round(rate, 1), "unit": unit,
            "step_ms": round(step_s * 1e3, 2),
        }))


if __name__ == "__main__":
    main(sys.argv[1:])
