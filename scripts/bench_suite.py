"""Full benchmark suite on the current accelerator (one JSON line per
config; bench.py stays the single-headline driver).

Methodology (same as bench.py): bf16 compute policy, jitted train step
with donated state, device-resident synthetic data, warmup, then a
timed run whose barrier is a device->host float() through the step
dependency chain (the axon relay's block_until_ready returns early).

Every line reports ``mfu``: flops from the compiled program's own
cost_analysis (not an analytic estimate) against the chip's bf16 peak;
scan-path configs take FLOPs from the single-step program because
cost_analysis counts a lax.scan body once, not times the trip count.

Two configs exercise the input pipeline end-to-end instead of
device-resident synthetic data (docs/perf_input_pipeline.md):
``cifar_cnn_hostdata`` streams host uint8 windows through the native
row gather + DeviceFeed + multi-step scan with on-device normalization;
``cifar_cnn_resident`` stages the uint8 dataset in HBM once and gathers
minibatches on device from host-sent index blocks.

Usage: python scripts/bench_suite.py [config ...]
Configs: see BENCHES at the bottom of this file (python
scripts/bench_suite.py bogus lists them) — training configs for every
zoo model + the transformer at short/long/windowed/chunked-CE/remat
variants, decode throughput (prefill + int8), and the end-to-end input
pipeline pair.
"""

import json
import os
import sys
import time

os.environ.setdefault("KERAS_BACKEND", "jax")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Peak bf16 TFLOP/s per chip, keyed on jax device_kind.  MFU is reported
# only for known accelerators (it is meaningless on the CPU fallback).
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v4": 275e12,
    "TPU v5p": 459e12,
}


def peak_flops():
    import jax

    return PEAK_FLOPS.get(jax.devices()[0].device_kind)


# Machine-readable record of the most recent GREEN measurement per
# config, at the repo root next to BENCH_r0N.json.  bench.py embeds it
# (clearly labeled as a prior measurement) in its error line when the
# accelerator tunnel is down at the driver's capture time — two rounds
# running, the headline artifact recorded null while same-day green
# numbers existed only in BASELINE.md prose.
LAST_GREEN_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "BENCH_LAST_GREEN.json")


def update_last_green(line: dict, path: str = LAST_GREEN_PATH,
                      device: str | None = None) -> None:
    """Merge one green result line into BENCH_LAST_GREEN.json.

    Layout: {"entries": {metric: {...line, measured_utc, device}},
    "updated_utc": ...}.  Best-effort — a read-only checkout or a
    corrupt file must never fail a measurement run.  NO jax calls in
    here: this helper must stay callable (and instant) while the
    accelerator tunnel is down; callers that just measured pass their
    device kind."""
    try:
        try:
            with open(path) as f:
                rec = json.load(f)
            if (not isinstance(rec, dict)
                    or not isinstance(rec.get("entries"), dict)):
                rec = {"entries": {}}
        except (OSError, ValueError):
            rec = {"entries": {}}
        stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        entry = dict(line)
        entry["measured_utc"] = stamp
        if device is not None:
            entry["device"] = device
        rec["entries"][str(line.get("metric"))] = entry
        rec["updated_utc"] = stamp
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except OSError:
        pass


def read_last_green(metric: str | None = None,
                    path: str = LAST_GREEN_PATH):
    """The recorded last-green entry for ``metric`` (or the whole
    record), or None if absent/unreadable."""
    try:
        with open(path) as f:
            rec = json.load(f)
        return rec["entries"].get(metric) if metric else rec
    except (OSError, ValueError, KeyError, AttributeError):
        return None


def compiled_flops(jitted, *args) -> float:
    """FLOPs of one call, from the compiled executable's cost model."""
    try:
        return float(jitted.lower(*args).compile()
                     .cost_analysis().get("flops", 0.0))
    except Exception:
        return 0.0


def measure_keras(build, shape, classes, batch, iters, warmup=10,
                  int_input=False, vocab=None, scan_steps=1):
    """``scan_steps`` > 1 uses the multi-step scan path
    (SingleTrainer(steps_per_call=...)): several optimizer updates per
    XLA call, amortizing host dispatch for small models."""
    import jax
    import numpy as np
    from distkeras_tpu.models.adapter import ModelAdapter

    model = build()
    adapter = ModelAdapter(model, loss=(
        "binary_crossentropy" if classes == 1
        else "sparse_categorical_crossentropy"),
        optimizer="sgd", learning_rate=0.01)
    state = adapter.init_state()
    if scan_steps > 1:
        step = jax.jit(adapter.make_multi_train_step(scan_steps),
                       donate_argnums=0)
        lead = (scan_steps, batch)
    else:
        step = jax.jit(adapter.make_train_step(), donate_argnums=0)
        lead = (batch,)

    rng = np.random.default_rng(0)
    if int_input:
        x = jax.device_put(rng.integers(0, vocab, (*lead, *shape))
                           .astype(np.int32))
    else:
        x = jax.device_put(rng.normal(size=(*lead, *shape))
                           .astype(np.float32))
    y = jax.device_put(rng.integers(0, max(classes, 2), lead)
                       .astype(np.float32 if classes == 1 else np.int64))

    # FLOPs from the *single-step* program: XLA's cost_analysis counts a
    # lax.scan body once, not times the trip count, so analyzing the
    # scanned program and dividing by scan_steps would undercount ~8x.
    if scan_steps > 1:
        one = jax.jit(adapter.make_train_step())
        step_flops = compiled_flops(one, state, x[0], y[0])
    else:
        step_flops = compiled_flops(step, state, x, y)
    for _ in range(warmup):
        state, loss = step(state, x, y)
    float(np.asarray(loss).ravel()[-1])  # device->host: the true barrier
    t0 = time.perf_counter()
    for _ in range(iters):
        state, loss = step(state, x, y)
    float(np.asarray(loss).ravel()[-1])
    dt = time.perf_counter() - t0
    steps = iters * scan_steps
    return batch * steps / dt, dt / steps, step_flops


def bench_mnist_mlp():
    import keras
    from distkeras_tpu.models.zoo import mnist_mlp

    keras.mixed_precision.set_global_policy("mixed_bfloat16")
    return measure_keras(lambda: mnist_mlp(seed=0), (784,), 10,
                         batch=4096, iters=60, scan_steps=8)


def bench_cifar_cnn():
    import keras
    from distkeras_tpu.models.zoo import cifar_cnn

    keras.mixed_precision.set_global_policy("mixed_bfloat16")
    return measure_keras(lambda: cifar_cnn(seed=0), (32, 32, 3), 10,
                         batch=1024, iters=300)


def bench_higgs_mlp():
    import keras
    from distkeras_tpu.models.zoo import higgs_mlp

    keras.mixed_precision.set_global_policy("mixed_bfloat16")
    return measure_keras(lambda: higgs_mlp(seed=0), (28,), 2,
                         batch=4096, iters=60, scan_steps=8)


def bench_imdb_lstm():
    """FusedLSTM path (models/rnn.py): input projection hoisted out of
    the recurrence into one MXU matmul."""
    import keras
    from distkeras_tpu.models.zoo import imdb_lstm

    keras.mixed_precision.set_global_policy("mixed_bfloat16")
    return measure_keras(
        lambda: imdb_lstm(vocab_size=20000, maxlen=128, seed=0), (128,), 1,
        batch=512, iters=100, int_input=True, vocab=20000)


def bench_imdb_lstm_keras():
    """Ablation baseline: the stock keras.layers.LSTM recurrence."""
    import keras
    from distkeras_tpu.models.zoo import imdb_lstm

    keras.mixed_precision.set_global_policy("mixed_bfloat16")
    return measure_keras(
        lambda: imdb_lstm(vocab_size=20000, maxlen=128, seed=0,
                          fused=False), (128,), 1,
        batch=512, iters=100, int_input=True, vocab=20000)


def bench_resnet50():
    import keras
    from distkeras_tpu.models.zoo import resnet50

    keras.mixed_precision.set_global_policy("mixed_bfloat16")
    return measure_keras(lambda: resnet50(seed=0), (224, 224, 3), 1000,
                         batch=128, iters=50, warmup=5)


def _measure_lm(cfg, batch, seq, iters, warmup=5, attention_fn=None,
                flops_cfg=None):
    """``flops_cfg``: config whose compiled program supplies the FLOPs
    count — a ce_chunks config hides the head matmuls inside a lax.scan
    whose body cost_analysis counts once, so its MFU must come from the
    numerically-identical unchunked program."""
    import jax
    import numpy as np
    import optax
    from distkeras_tpu.models import transformer as tfm

    params = tfm.init_params(jax.random.key(0), cfg)
    opt = optax.adamw(3e-4)
    step = jax.jit(tfm.make_train_step(cfg, opt, attention_fn=attention_fn),
                   donate_argnums=0)
    carry = (params, opt.init(params))

    rng = np.random.default_rng(0)
    tokens = jax.device_put(
        rng.integers(0, cfg.vocab_size, (batch, seq + 1)).astype(np.int32))
    if flops_cfg is not None:
        fstep = jax.jit(tfm.make_train_step(flops_cfg, opt,
                                            attention_fn=attention_fn))
        step_flops = compiled_flops(fstep, carry, tokens)
    else:
        step_flops = compiled_flops(step, carry, tokens)
    for _ in range(warmup):
        carry, loss = step(carry, tokens)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        carry, loss = step(carry, tokens)
    float(loss)
    dt = time.perf_counter() - t0
    return batch * seq * iters / dt, dt / iters, step_flops


def bench_transformer():
    """Flagship LM, short-sequence config (head-dominated at seq 1024)."""
    from distkeras_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(
        vocab_size=32768, d_model=512, n_heads=4, n_layers=4, d_ff=2048,
        max_len=1025, dtype="bfloat16")
    return _measure_lm(cfg, batch=8, seq=1024, iters=50)


def bench_transformer_fusedce():
    """Same head-dominated config with the chunked vocab-head CE
    (ce_chunks=8): the [8, 1024, 32k] f32 logits (~1 GB) never
    materialize — the delta vs ``transformer`` is pure head HBM
    traffic."""
    from distkeras_tpu.models import transformer as tfm

    import dataclasses

    cfg = tfm.TransformerConfig(
        vocab_size=32768, d_model=512, n_heads=4, n_layers=4, d_ff=2048,
        max_len=1025, dtype="bfloat16", ce_chunks=8)
    return _measure_lm(cfg, batch=8, seq=1024, iters=50,
                       flops_cfg=dataclasses.replace(cfg, ce_chunks=0))


def _d1024_cfg(**kw):
    from distkeras_tpu.models import transformer as tfm

    # Dense d1024 L8 at seq 1024: the direct comparison row for the MoE
    # and LoRA configs below (same trunk; transformer_long differs in
    # seq length and remat, so it can't serve as their baseline).
    return tfm.TransformerConfig(
        vocab_size=32768, d_model=1024, n_heads=8, n_layers=8, d_ff=4096,
        max_len=1025, dtype="bfloat16", **kw)


def bench_transformer_d1024(batch=8, seq=1024, iters=30):
    """Dense-FFN baseline row for the MoE/LoRA family (d1024 L8 s1024).
    (batch/seq/iters overridable so CPU smoke tests can shrink them.)"""
    return _measure_lm(_d1024_cfg(), batch=batch, seq=seq, iters=iters)


def bench_transformer_moe(top_k):
    """Mixture-of-experts training: 8 experts over the d1024 L8 trunk,
    capacity_factor 1.25 (Switch top-1 / renormalized top-2).  The
    capacity einsum dispatch is all-to-all-shaped even on one chip, so
    step time vs the dense row IS the routing+dispatch overhead; MFU
    comes from the compiled program's own cost_analysis (it counts the
    dispatch/combine einsums — hardware MFU, not active-param MFU)."""
    def run(batch=8, seq=1024, iters=30):
        cfg = _d1024_cfg(num_experts=8, moe_top_k=top_k,
                         capacity_factor=1.25)
        rate, step_s, flops = _measure_lm(cfg, batch=batch, seq=seq,
                                          iters=iters)
        return rate, step_s, flops, {
            "num_experts": 8, "moe_top_k": top_k,
            "capacity_factor": 1.25,
            "dense_baseline": "transformer_d1024"}
    return run


def bench_lora_finetune(batch=8, seq=1024, iters=30):
    """LoRA fine-tune step throughput on the d1024 L8 row (rank 8,
    wq/wv): the forward is byte-identical to full fine-tuning (merge
    inside the step), so the delta vs ``transformer_d1024`` isolates
    what LoRA saves — backward skips the base's gradient paths and the
    optimizer touches ~1000x fewer moments."""
    import jax
    import numpy as np
    import optax
    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.models.lora import (LoRAConfig, lora_init,
                                           lora_mask, make_lora_loss)

    cfg = _d1024_cfg()
    lcfg = LoRAConfig(rank=8, alpha=16.0, targets=("wq", "wv"))
    base = tfm.init_params(jax.random.key(0), cfg)
    adapters = lora_init(jax.random.key(1), cfg, lcfg)
    opt = optax.masked(optax.adamw(3e-4), lora_mask)
    step = jax.jit(
        tfm.make_train_step(cfg, opt, loss_fn=make_lora_loss(cfg, lcfg)),
        donate_argnums=0)
    packed = (adapters, base)
    carry = (packed, opt.init(packed))
    rng = np.random.default_rng(0)
    tokens = jax.device_put(
        rng.integers(0, cfg.vocab_size, (batch, seq + 1)).astype(np.int32))
    step_flops = compiled_flops(step, carry, tokens)
    for _ in range(5):
        carry, loss = step(carry, tokens)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        carry, loss = step(carry, tokens)
    float(loss)
    dt = time.perf_counter() - t0
    n_adapter = sum(int(np.prod(np.shape(a))) for a in
                    jax.tree.leaves(adapters))
    return batch * seq * iters / dt, dt / iters, step_flops, {
        "lora_rank": 8, "lora_targets": "wq,wv",
        "adapter_params": n_adapter,
        "dense_baseline": "transformer_d1024"}


def _long_cfg():
    from distkeras_tpu.models import transformer as tfm

    # Attention-dominated: at seq 4096 / d_model 1024 the S^2 term is
    # ~2x the matmul term per layer, and the 32k-vocab head is <10% of
    # the step.  remat keeps activations in budget at this depth.
    return tfm.TransformerConfig(
        vocab_size=32768, d_model=1024, n_heads=8, n_layers=8, d_ff=4096,
        max_len=4097, dtype="bfloat16", remat=True)


def bench_transformer_long():
    """Long-context LM on the Pallas flash-attention path."""
    return _measure_lm(_long_cfg(), batch=8, seq=4096, iters=20)


def bench_transformer_long_rope():
    """Long config with rotary positions + grouped-query attention (the
    modern long-context layout; rope/GQA cost vs the learned-table MHA
    baseline is the interesting delta)."""
    import dataclasses

    return _measure_lm(
        dataclasses.replace(_long_cfg(), rope=True, n_kv_heads=2),
        batch=8, seq=4096, iters=20)


def bench_transformer_long_window():
    """Long config with sliding-window attention (window 1024 at seq
    4096): the kernels skip blocks beyond the lookback, so the S^2
    attention term drops ~4x."""
    import dataclasses

    return _measure_lm(
        dataclasses.replace(_long_cfg(), attention_window=1024),
        batch=8, seq=4096, iters=20)


def bench_transformer_long_rematdots():
    """Long config with selective remat (policy='dots': matmul outputs
    saved, elementwise recomputed) — the middle point between full
    remat and no remat."""
    import dataclasses

    return _measure_lm(
        dataclasses.replace(_long_cfg(), remat_policy="dots"),
        batch=8, seq=4096, iters=20)


def bench_transformer_long_noremat():
    """Same config without per-block rematerialization (fits at this
    size; remat trades ~13% step time for O(1)-block activations)."""
    import dataclasses

    return _measure_lm(dataclasses.replace(_long_cfg(), remat=False),
                       batch=8, seq=4096, iters=20)


def bench_transformer_long_xla():
    """Same config on the blockwise-jnp XLA fallback (no Pallas).

    batch 4: the fallback's backward (re-run forward under jax.vjp)
    fails to compile at batch 8 on a 16 GB chip — itself part of the
    comparison; tokens/sec is batch-normalized.
    """
    from distkeras_tpu.ops.attention import blockwise_attention

    return _measure_lm(
        _long_cfg(), batch=4, seq=4096, iters=20,
        attention_fn=lambda q, k, v: blockwise_attention(q, k, v, causal=True))


def bench_generate_decode():
    """KV-cached greedy decode on the flagship config: sustained decode
    tokens/s (batch x new tokens / wall), plus the prefill win — wall
    time of the one-forward prompt fill vs teacher-forcing the prompt
    through the cached step (``prefill_speedup`` in the extras)."""
    import jax
    import numpy as np
    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.models.generate import generate

    cfg = tfm.TransformerConfig(
        vocab_size=32768, d_model=512, n_heads=4, n_layers=4, d_ff=2048,
        max_len=1025, dtype="bfloat16")
    params = tfm.init_params(jax.random.key(0), cfg)
    batch, p_len, new = 8, 512, 512
    prompt = jax.device_put(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (batch, p_len)).astype(np.int32))

    gen = jax.jit(lambda pp, pr: generate(pp, pr, cfg, new))
    seq = jax.jit(lambda pp, pr: generate(pp, pr, cfg, new,
                                          use_prefill=False))
    int(np.asarray(gen(params, prompt))[0, -1])  # compile + barrier
    iters = 3
    t0 = time.perf_counter()
    for _ in range(iters):
        out = gen(params, prompt)
    int(np.asarray(out)[0, -1])
    dt_pre = (time.perf_counter() - t0) / iters

    int(np.asarray(seq(params, prompt))[0, -1])
    t0 = time.perf_counter()
    out = seq(params, prompt)
    int(np.asarray(out)[0, -1])
    dt_seq = time.perf_counter() - t0

    # Decode rate from the prefill path; per-token step time likewise.
    rate = batch * new / dt_pre
    extras = {"prefill_speedup": round(dt_seq / dt_pre, 2),
              "prompt_len": p_len, "new_tokens": new}
    return rate, dt_pre / new, 0.0, extras


def bench_generate_decode_int8():
    """Same decode workload with int8-quantized weights (models/quant):
    the sequential loop is weight-bandwidth-bound, so halving the
    weight bytes vs bf16 is the lever.  Short prompt (the int8 path is
    sequential-only; its regime is generation-heavy serving)."""
    import jax
    import numpy as np
    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.models.generate import generate
    from distkeras_tpu.models.quant import quantize_params

    cfg = tfm.TransformerConfig(
        vocab_size=32768, d_model=512, n_heads=4, n_layers=4, d_ff=2048,
        max_len=1025, dtype="bfloat16")
    qparams = quantize_params(tfm.init_params(jax.random.key(0), cfg))
    batch, p_len, new = 8, 16, 512
    prompt = jax.device_put(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (batch, p_len)).astype(np.int32))

    gen = jax.jit(lambda pp, pr: generate(pp, pr, cfg, new))
    int(np.asarray(gen(qparams, prompt))[0, -1])
    iters = 3
    t0 = time.perf_counter()
    for _ in range(iters):
        out = gen(qparams, prompt)
    int(np.asarray(out)[0, -1])
    dt = (time.perf_counter() - t0) / iters
    return batch * new / dt, dt / new, 0.0, {"prompt_len": p_len,
                                             "new_tokens": new}


def bench_cifar_cnn_hostdata():
    """End-to-end input pipeline: host uint8 rows -> native gather ->
    DeviceFeed (async h2d, uint8 on the wire) -> multi-step scan with
    on-device normalization.

    The honest counterpart of ``cifar_cnn`` (device-resident synthetic
    data): same model and batch, but every batch starts as host uint8
    rows the way training data does (SURVEY.md §7.3 #4).  Three design
    rules keep the link, not the software, as the only limit:
    uint8 on the wire (4x fewer bytes; ModelAdapter ``preprocess``
    normalizes on device), windows of ``scan`` steps per XLA call
    (execution/transfer interleaving carries a fixed per-dispatch cost
    on remote-attached devices), and DeviceFeed lookahead so the next
    window streams under the current scan.  The JSON line reports
    ``h2d_mbytes_per_s`` (achieved wire rate) next to ``mfu`` — when the
    achieved rate saturates the measured link bandwidth, the gap to the
    synthetic number is transport physics, not pipeline overhead.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import keras
    from distkeras_tpu import native
    from distkeras_tpu.data.prefetch import DeviceFeed
    from distkeras_tpu.models.adapter import ModelAdapter
    from distkeras_tpu.models.zoo import cifar_cnn

    keras.mixed_precision.set_global_policy("mixed_bfloat16")
    batch, scan, windows, warmup = 1024, 8, 24, 3
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (50_000, 32, 32, 3)).astype(np.uint8)
    labels = rng.integers(0, 10, 50_000).astype(np.int32)

    adapter = ModelAdapter(
        cifar_cnn(seed=0), loss="sparse_categorical_crossentropy",
        optimizer="sgd", learning_rate=0.01,
        preprocess=lambda x: x.astype(jnp.bfloat16) * (1 / 255.0))
    state = adapter.init_state()
    step = jax.jit(adapter.make_multi_train_step(scan), donate_argnums=0)

    def window_batches(n):
        order, i = rng.permutation(len(images)), 0
        rows = scan * batch
        for _ in range(n):
            if i + rows > len(order):
                order, i = rng.permutation(len(images)), 0
            idx = order[i:i + rows]
            i += rows
            x = native.gather_rows(images, idx).reshape(
                scan, batch, *images.shape[1:])
            y = native.gather_rows(labels, idx).reshape(scan, batch)
            yield x, y

    x0, y0 = next(iter(window_batches(1)))
    wire_bytes = x0.nbytes + y0.nbytes
    x0d, y0d = jax.device_put((x0, y0))
    # Single-step program for FLOPs (scan bodies are counted once by
    # cost_analysis, see measure_keras).
    one = jax.jit(adapter.make_train_step())
    step_flops = compiled_flops(one, state, x0d[0], y0d[0])
    for x, y in DeviceFeed(window_batches(warmup), depth=2):
        state, loss = step(state, x, y)
    float(np.asarray(loss).ravel()[-1])
    t0 = time.perf_counter()
    for x, y in DeviceFeed(window_batches(windows), depth=2):
        state, loss = step(state, x, y)
    float(np.asarray(loss).ravel()[-1])
    dt = time.perf_counter() - t0
    steps = windows * scan
    extra = {"h2d_mbytes_per_s": round(wire_bytes * windows / dt / 1e6, 1)}
    return batch * steps / dt, dt / steps, step_flops, extra


def bench_cifar_cnn_resident():
    """End-to-end with a device-resident dataset: the uint8 training set
    is staged in HBM once, and each multi-step call gathers its
    minibatches on device from a host-sent int32 index block
    (SingleTrainer(device_data=True) path).

    This is the TPU-native answer for any dataset that fits HBM: after
    staging, ~4 bytes/sample/epoch cross the host link, so throughput
    tracks the synthetic number regardless of link quality — compare
    ``cifar_cnn_hostdata``, which streams every pixel and is bounded by
    the link.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import keras
    from distkeras_tpu.models.adapter import ModelAdapter
    from distkeras_tpu.models.zoo import cifar_cnn

    keras.mixed_precision.set_global_policy("mixed_bfloat16")
    batch, scan, windows, warmup = 1024, 8, 24, 3
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (50_000, 32, 32, 3)).astype(np.uint8)
    labels = rng.integers(0, 10, 50_000).astype(np.int32)

    adapter = ModelAdapter(
        cifar_cnn(seed=0), loss="sparse_categorical_crossentropy",
        optimizer="sgd", learning_rate=0.01,
        preprocess=lambda x: x.astype(jnp.bfloat16) * (1 / 255.0))
    state = adapter.init_state()
    step = jax.jit(adapter.make_indexed_train_step(scan), donate_argnums=0)
    X, Y = jax.device_put((images, labels))

    def idx_blocks(n):
        order, i = rng.permutation(len(images)), 0
        rows = scan * batch
        for _ in range(n):
            if i + rows > len(order):
                order, i = rng.permutation(len(images)), 0
            block = order[i:i + rows].astype(np.int32).reshape(scan, batch)
            i += rows
            yield block

    i0 = next(iter(idx_blocks(1)))
    one = jax.jit(adapter.make_train_step())
    step_flops = compiled_flops(
        one, state, jnp.take(X, i0[0], axis=0), jnp.take(Y, i0[0], axis=0))
    for idx in idx_blocks(warmup):
        state, loss = step(state, X, Y, idx)
    float(np.asarray(loss).ravel()[-1])
    t0 = time.perf_counter()
    for idx in idx_blocks(windows):
        state, loss = step(state, X, Y, idx)
    float(np.asarray(loss).ravel()[-1])
    dt = time.perf_counter() - t0
    steps = windows * scan
    return batch * steps / dt, dt / steps, step_flops


def bench_zero1_update(batch_unused=None, iters=30):
    """The weight-update phase in isolation: replicated update vs the
    ZeRO-1 sharded update (docs/zero1.md), over a data axis spanning
    every visible device.

    Training-step benchmarks hide the update behind the forward/backward;
    this one feeds a fixed synthetic gradient of the flagship short
    transformer config to adamw directly, so the measured wall is
    exactly exchange + update math — the thing ZeRO-1 shards.  Reports
    per-device optimizer-state bytes for both layouts (from the sharded
    state's addressable shards — the ~num_workers x memory claim as a
    measured number) and the update-time pair.  On a single-device
    backend the two paths coincide (ratio ~1): the win needs a real
    data axis.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.parallel.collectives import (zero1_optimizer,
                                                    zero1_state_shardings)
    from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_dev = len(jax.devices())
    mesh = make_mesh(MeshSpec(data=n_dev))
    cfg = tfm.TransformerConfig(
        vocab_size=32768, d_model=512, n_heads=4, n_layers=4, d_ff=2048,
        max_len=1025, dtype="bfloat16")
    params = tfm.init_params(jax.random.key(0), cfg)
    grads = jax.tree.map(lambda p: p * 1e-3, params)
    opt = optax.adamw(3e-4)
    rep = NamedSharding(mesh, P())
    params = jax.device_put(params, jax.tree.map(lambda _: rep, params))
    grads = jax.device_put(grads, jax.tree.map(lambda _: rep, grads))

    def bytes_per_device(state):
        return sum(l.addressable_shards[0].data.nbytes
                   for l in jax.tree.leaves(state)
                   if hasattr(l, "addressable_shards"))

    def measure(optimizer, state_shardings):
        state = jax.jit(optimizer.init,
                        out_shardings=state_shardings)(params)
        per_dev = bytes_per_device(state)

        def upd(g, s, p):
            u, s2 = optimizer.update(g, s, p)
            return optax.apply_updates(p, u), s2

        psh = jax.tree.map(lambda _: rep, params)
        step = jax.jit(upd, donate_argnums=(1, 2),
                       in_shardings=(psh, state_shardings, psh),
                       out_shardings=(psh, state_shardings))
        # The step donates its params operand; work on a copy so the
        # shared tree survives for the other layout's measurement.
        p = jax.tree.map(jnp.copy, params)
        for _ in range(3):
            p, state = step(grads, state, p)
        jax.block_until_ready(p)
        t0 = time.perf_counter()
        for _ in range(iters):
            p, state = step(grads, state, p)
        jax.block_until_ready(p)
        return (time.perf_counter() - t0) / iters, per_dev

    # Replicated baseline: every state leaf whole on every device.
    opt_shapes = jax.eval_shape(opt.init, params)
    rep_sh = jax.tree.map(lambda _: rep, opt_shapes)
    rep_s, rep_bytes = measure(opt, rep_sh)

    z = zero1_optimizer(opt, mesh)
    z_sh = zero1_state_shardings(params, jax.eval_shape(z.init, params),
                                 mesh)
    z_s, z_bytes = measure(z, z_sh)

    n_params = sum(int(np.prod(np.shape(l)))
                   for l in jax.tree.leaves(params))
    return 1.0 / z_s, z_s, 0.0, {
        "n_devices": n_dev, "n_params": n_params,
        "update_ms_replicated": round(rep_s * 1e3, 3),
        "update_ms_zero1": round(z_s * 1e3, 3),
        "update_speedup": round(rep_s / z_s, 3),
        "opt_bytes_per_device_replicated": rep_bytes,
        "opt_bytes_per_device_zero1": z_bytes,
        "opt_memory_ratio": round(rep_bytes / max(z_bytes, 1), 2),
    }


def bench_zero_stages(iters=10, batch=8, seq=256, d_model=256,
                      n_layers=4, vocab=8192):
    """The ZeRO stage ladder, side by side (docs/zero1.md): for
    replicated DP and stages 1/2/3, ONE real ``LMTrainer`` train-step
    program (built through the trainer's own ``_build_carry_and_step``,
    so the measured program is exactly what users train) on a data
    axis spanning every visible device.  Reports per stage:

    * ``step_ms_*`` — steady-state wall of the full train step (the
      stage-3 row is where the gather-on-use overhead shows: the
      per-use parameter all-gathers ride inside the step);
    * ``state_bytes_per_device_*`` — persistent params+optimizer bytes
      per device from ADDRESSABLE SHARDS (the acceptance's ~n x memory
      claim as a measured number: stage 1 shards the moments, stage 3
      params+moments both).

    Model dims overridable so CPU smoke tests can shrink them; the
    default is a flagship-short config sized to make the update and
    gather phases visible.  On a single-device backend every stage
    coincides (ratio ~1): the ladder needs a real data axis."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh
    from distkeras_tpu.trainers.lm import LMTrainer

    n_dev = len(jax.devices())
    mesh = make_mesh(MeshSpec(data=n_dev))
    cfg = tfm.TransformerConfig(
        vocab_size=vocab, d_model=d_model, n_heads=4,
        n_layers=n_layers, d_ff=4 * d_model, max_len=seq + 1)
    rows = np.random.default_rng(0).integers(
        0, vocab, (batch, seq + 1)).astype(np.int32)
    extras = {"n_devices": n_dev}
    walls = {}
    for stage in (0, 1, 2, 3):
        t = LMTrainer(cfg, learning_rate=3e-4, batch_size=batch,
                      mesh=mesh, **({"zero": stage} if stage else {}))
        params = t.init_params()
        (carry_p, opt_state, _psh, _osh, step, step_sh,
         _tok) = t._build_carry_and_step(params)
        carry = (carry_p, opt_state)
        tok = jax.device_put(rows, step_sh)
        per_dev = sum(l.addressable_shards[0].data.nbytes
                      for l in jax.tree.leaves(carry)
                      if hasattr(l, "addressable_shards"))
        for _ in range(2):
            carry, loss = step(carry, tok, None, None)
        jax.block_until_ready(loss)
        t0 = time.perf_counter()
        for _ in range(iters):
            carry, loss = step(carry, tok, None, None)
        jax.block_until_ready(loss)
        wall = (time.perf_counter() - t0) / iters
        key = f"stage{stage}" if stage else "dp"
        walls[key] = wall
        extras[f"step_ms_{key}"] = round(wall * 1e3, 3)
        extras[f"state_bytes_per_device_{key}"] = per_dev
    extras["state_memory_ratio_stage3"] = round(
        extras["state_bytes_per_device_dp"]
        / max(extras["state_bytes_per_device_stage3"], 1), 2)
    tokens_per_step = batch * seq
    return (tokens_per_step / walls["stage3"] / n_dev,
            walls["stage3"], 0.0, extras)


def bench_lowcomm_convergence(**opts):
    """Convergence-vs-baseline row for one gradient-exchange variant
    (docs/lowcomm.md): train the toy LM twice on the same seeded rows —
    replicated-DP baseline, then the variant — and report both final
    losses against the DECLARED tolerance (the same bound
    tests/test_exchange.py::TOL_LOSS enforces; the row makes the margin
    visible, the test makes it binding).  Wire-bytes/collective-count
    claims live in the compiled census (scripts/comm_budget.json), not
    here — this row is the convergence half of the lowcomm contract.
    """
    def run(batch=16, seq=16, n_rows=128, epochs=2, tol=0.05):
        import jax
        import numpy as np
        from distkeras_tpu.models import transformer as tfm
        from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh
        from distkeras_tpu.trainers.lm import LMTrainer

        cfg = tfm.TransformerConfig(vocab_size=64, d_model=32,
                                    n_heads=2, n_layers=2, d_ff=64,
                                    max_len=seq + 1)
        rows = np.random.default_rng(0).integers(
            0, cfg.vocab_size, (n_rows, seq + 1)).astype(np.int32)
        mesh = make_mesh(MeshSpec(data=len(jax.devices())))

        def train(**kw):
            t = LMTrainer(cfg, learning_rate=1e-2, batch_size=batch,
                          num_epoch=epochs, mesh=mesh, **kw)
            t0 = time.perf_counter()
            t.train(rows)
            return t, time.perf_counter() - t0

        base, _ = train()
        t, wall = train(**opts)
        steps = len(t.history)
        delta = abs(t.history[-1] - base.history[-1])
        # One row == one sync round; under local-SGD a round carries
        # sync_every optimizer steps' worth of tokens.
        tokens = n_rows * seq * epochs
        return tokens / wall, wall / steps, 0.0, {
            **opts,
            "final_loss": round(t.history[-1], 5),
            "baseline_loss": round(base.history[-1], 5),
            "loss_delta": round(delta, 5),
            "tolerance": tol,
            "within_tolerance": bool(delta <= tol),
            "rounds": steps, "baseline_rounds": len(base.history)}
    return run


def bench_lowcomm_update(iters=10, d_model=512, n_layers=4,
                         vocab=32768):
    """The gradient-exchange + update path in isolation, per variant
    (docs/lowcomm.md): feed a fixed synthetic STACKED per-replica
    gradient of the flagship short transformer config through
    ``exchange_optimizer`` for each merge rule / codec, so the measured
    wall is exactly merge collectives + inner update — the thing the
    exchange layer changes.  Reports per-variant update time and the
    analytic per-step gradient wire bytes (``exchange.wire_bytes`` —
    the same formula the obs gauges carry; the compiled census pins the
    claim), so the ~4x int8-EF byte reduction and its CPU-mesh cost
    show up side by side.  (Model dims overridable so CPU smoke tests
    can shrink them; the flagship default is chip-sized.)"""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.parallel import exchange as ex
    from distkeras_tpu.parallel.collectives import Zero1Layout
    from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_dev = len(jax.devices())
    mesh = make_mesh(MeshSpec(data=n_dev))
    cfg = tfm.TransformerConfig(
        vocab_size=vocab, d_model=d_model, n_heads=4,
        n_layers=n_layers, d_ff=4 * d_model, max_len=1025,
        dtype="bfloat16")
    params = tfm.init_params(jax.random.key(0), cfg)
    rep = NamedSharding(mesh, P())
    stk = NamedSharding(mesh, P("data"))
    params = jax.device_put(params, jax.tree.map(lambda _: rep, params))
    # Per-replica contributions: the mean over the leading axis equals
    # the replicated-baseline gradient, so every variant does real work.
    stacked = jax.device_put(
        jax.tree.map(lambda p: jnp.broadcast_to(
            (p * 1e-3)[None], (n_dev,) + p.shape), params),
        jax.tree.map(lambda _: stk, params))
    layout = Zero1Layout.for_tree(
        jax.tree.map(lambda p: jax.ShapeDtypeStruct(p.shape, p.dtype),
                     params), n_dev, ex.ExchangeConfig().bucket_mb)

    def measure(config, zero1=False):
        opt = ex.exchange_optimizer(optax.adamw(3e-4), mesh, config,
                                    zero1=zero1)
        osh = ex.exchange_state_shardings(
            params, jax.eval_shape(opt.init, params), mesh, zero1=zero1)
        state = jax.jit(opt.init, out_shardings=osh)(params)

        def upd(g, s, p):
            u, s2 = opt.update(g, s, p)
            return optax.apply_updates(p, u), s2

        psh = jax.tree.map(lambda _: rep, params)
        gsh = jax.tree.map(lambda _: stk, params)
        step = jax.jit(upd, donate_argnums=(1, 2),
                       in_shardings=(gsh, osh, psh),
                       out_shardings=(psh, osh))
        p = jax.tree.map(jnp.copy, params)
        for _ in range(3):
            p, state = step(stacked, state, p)
        jax.block_until_ready(p)
        t0 = time.perf_counter()
        for _ in range(iters):
            p, state = step(stacked, state, p)
        jax.block_until_ready(p)
        return (time.perf_counter() - t0) / iters

    variants = {
        "mean": (ex.ExchangeConfig(), False),
        "adasum": (ex.ExchangeConfig(merge_rule="adasum"), False),
        "int8ef": (ex.ExchangeConfig(compress="int8"), False),
        "topk": (ex.ExchangeConfig(compress="topk", topk_frac=0.01),
                 False),
        "zero1_int8ef": (ex.ExchangeConfig(compress="int8"), True),
    }
    extras = {"n_devices": n_dev}
    walls = {}
    for name, (config, zero1) in variants.items():
        walls[name] = measure(config, zero1)
        f32_b, wire_b = ex.wire_bytes(layout, config, zero1)
        extras[f"update_ms_{name}"] = round(walls[name] * 1e3, 3)
        extras[f"grad_wire_bytes_{name}"] = wire_b
        if name == "mean":
            extras["grad_f32_bytes"] = f32_b
        else:
            extras[f"compression_{name}"] = round(f32_b / max(wire_b, 1),
                                                  2)
    return 1.0 / walls["int8ef"], walls["int8ef"], 0.0, extras


def bench_async_convergence(**opts):
    """Convergence-vs-ADAG row for the bounded-staleness async tier
    (docs/async.md): train the same seeded MLP on the same blob rows
    twice — synchronous ADAG baseline, then ``AsyncDP`` under the
    given staleness/merge config with a deterministic virtual-time
    schedule — and report both final losses against the DECLARED
    tolerance (the bound tests/test_async_tier.py::
    test_converges_within_tol_of_adag enforces; the row makes the
    margin visible, the test makes it binding).  The int8 cross-host
    wire claim lives in the compiled census (asyncdp_wire/* in
    scripts/comm_budget.json), not here — this row is the convergence
    half of the async contract."""
    def run(n_rows=256, epochs=2, tol=0.05):
        import keras
        import numpy as np

        import distkeras_tpu as dk
        from distkeras_tpu.parallel.async_tier import AsyncSchedule

        rng = np.random.default_rng(0)
        centers = rng.normal(0, 4.0, (4, 16))
        labels = rng.integers(0, 4, n_rows)
        feats = (centers[labels]
                 + rng.normal(0, 0.5, (n_rows, 16))).astype(np.float32)
        ds = dk.Dataset({"features": feats,
                         "label": labels.astype(np.int64)})

        def mlp():
            keras.utils.set_random_seed(0)
            return keras.Sequential([
                keras.Input((16,)),
                keras.layers.Dense(32, activation="relu"),
                keras.layers.Dense(4)])

        kw = dict(loss="sparse_categorical_crossentropy",
                  worker_optimizer="sgd", learning_rate=0.05,
                  batch_size=2, num_epoch=epochs,
                  communication_window=2, seed=11)
        base = dk.ADAG(mlp(), **kw)
        base.train(ds)
        t = dk.AsyncDP(mlp(), hosts=2, beat_window=1.5,
                       schedule=AsyncSchedule(seed=3), **kw, **opts)
        t0 = time.perf_counter()
        t.train(ds)
        wall = time.perf_counter() - t0
        rounds = len(t.history)
        delta = abs(t.history[-1] - base.history[-1])
        rep = t.async_report
        return n_rows * epochs / wall, wall / rounds, 0.0, {
            **opts,
            "final_loss": round(t.history[-1], 5),
            "baseline_loss": round(base.history[-1], 5),
            "loss_delta": round(delta, 5),
            "tolerance": tol,
            "within_tolerance": bool(delta <= tol),
            "rounds": rounds, "baseline_rounds": len(base.history),
            "hard_syncs": rep["hard_syncs"],
            "wire_bytes": rep["wire_bytes"]}
    return run


def bench_lm_e2e(device_data):
    """End-to-end ``LMTrainer.train()`` throughput over real host rows,
    streaming vs ``device_data=True`` — the LM flagship's input-plane
    delta (docs/perf_input_pipeline.md round-5).  The per-step
    ``transformer_*`` rows feed ONE pre-staged device batch and so
    cannot see the host link at all; this pair trains on a real row
    set through the public trainer API.

    Timing is a DELTA of two train() calls (``steps`` vs
    ``warm_steps`` rows, same shapes), after one DISCARDED warmup
    call: the warmup absorbs process-level one-time costs (backend
    init, first-compile cache seeding), and whatever per-call cost
    remains — train() builds its jitted step from fresh closures, so
    the compile is re-resolved per call, cached or not — lands
    equally on both measured calls and cancels in the subtraction,
    leaving steady-state step time + the per-row input plane (for
    device_data that includes its share of the bulk staging transfer,
    which is the thing being measured)."""
    def run(batch=8, seq=1024, steps=64, warm_steps=4, cfg=None):
        import numpy as np
        from distkeras_tpu.trainers.lm import LMTrainer

        cfg = cfg or _d1024_cfg()
        rng = np.random.default_rng(0)
        rows = rng.integers(0, cfg.vocab_size,
                            (batch * steps, seq + 1)).astype(np.int32)

        def train_once(n):
            t = LMTrainer(cfg, learning_rate=3e-4, batch_size=batch,
                          num_epoch=1, device_data=device_data)
            t.train(rows[:batch * n])
            return t.training_time

        if steps <= warm_steps:
            raise ValueError(
                f"steps ({steps}) must exceed warm_steps ({warm_steps}) "
                "— the delta IS the measurement")
        train_once(warm_steps)            # discarded: one-time costs
        wall_short = train_once(warm_steps)
        wall_long = train_once(steps)
        d_steps = steps - warm_steps
        wall = wall_long - wall_short
        if wall <= 0:
            raise RuntimeError(
                f"non-positive delta wall ({wall_long:.3f}s - "
                f"{wall_short:.3f}s): per-call compile variance exceeds "
                f"the {d_steps}-step term at these dims — raise steps "
                "(chip dims resolve; toy CPU dims often cannot)")
        return batch * d_steps * seq / wall, wall / d_steps, 0.0, {
            "device_data": device_data, "steps_delta": d_steps,
            "batch": batch, "seq": seq,
            "e2e_wall_long_s": round(wall_long, 3),
            "e2e_wall_short_s": round(wall_short, 3)}
    return run


BENCHES = {
    "mnist_mlp": (bench_mnist_mlp, "samples/sec/chip"),
    "cifar_cnn": (bench_cifar_cnn, "samples/sec/chip"),
    "cifar_cnn_hostdata": (bench_cifar_cnn_hostdata, "samples/sec/chip"),
    "cifar_cnn_resident": (bench_cifar_cnn_resident, "samples/sec/chip"),
    "higgs_mlp": (bench_higgs_mlp, "samples/sec/chip"),
    "imdb_lstm": (bench_imdb_lstm, "samples/sec/chip"),
    "imdb_lstm_keras": (bench_imdb_lstm_keras, "samples/sec/chip"),
    "resnet50": (bench_resnet50, "samples/sec/chip"),
    "transformer": (bench_transformer, "tokens/sec/chip"),
    "transformer_fusedce": (bench_transformer_fusedce, "tokens/sec/chip"),
    "generate_decode": (bench_generate_decode, "tokens/sec/chip"),
    "generate_decode_int8": (bench_generate_decode_int8, "tokens/sec/chip"),
    "transformer_long": (bench_transformer_long, "tokens/sec/chip"),
    "transformer_long_rope": (bench_transformer_long_rope, "tokens/sec/chip"),
    "transformer_long_window": (bench_transformer_long_window,
                                "tokens/sec/chip"),
    "transformer_long_rematdots": (bench_transformer_long_rematdots,
                                   "tokens/sec/chip"),
    "transformer_long_noremat": (bench_transformer_long_noremat,
                                 "tokens/sec/chip"),
    "transformer_long_xla": (bench_transformer_long_xla, "tokens/sec/chip"),
    "transformer_d1024": (bench_transformer_d1024, "tokens/sec/chip"),
    "transformer_moe_top1": (bench_transformer_moe(1), "tokens/sec/chip"),
    "transformer_moe_top2": (bench_transformer_moe(2), "tokens/sec/chip"),
    "lora_finetune": (bench_lora_finetune, "tokens/sec/chip"),
    "lm_e2e_stream": (bench_lm_e2e(False), "tokens/sec/chip"),
    "lm_e2e_device_data": (bench_lm_e2e(True), "tokens/sec/chip"),
    "zero1_update": (bench_zero1_update, "updates/sec"),
    "zero_stages": (bench_zero_stages, "tokens/sec/chip"),
    "lowcomm_adasum": (bench_lowcomm_convergence(merge_rule="adasum"),
                       "tokens/sec/chip"),
    "lowcomm_localsgd4": (bench_lowcomm_convergence(sync_every=4),
                          "tokens/sec/chip"),
    "lowcomm_int8ef": (bench_lowcomm_convergence(compress="int8"),
                       "tokens/sec/chip"),
    "lowcomm_zero1_int8ef": (
        bench_lowcomm_convergence(zero1=True, compress="int8"),
        "tokens/sec/chip"),
    "lowcomm_update": (bench_lowcomm_update, "updates/sec"),
    "async_tau1": (bench_async_convergence(tau=1, async_merge="sum"),
                   "samples/sec"),
    "async_tau4": (bench_async_convergence(tau=4, async_merge="sum"),
                   "samples/sec"),
    "async_adasum": (bench_async_convergence(tau=4, async_merge="adasum",
                                             async_compress="int8"),
                     "samples/sec"),
}


def main(names):
    import jax

    unknown = set(names) - set(BENCHES)
    if unknown:
        sys.exit(f"unknown config(s) {sorted(unknown)}; "
                 f"choose from {sorted(BENCHES)}")
    from distkeras_tpu import obs

    print(f"# backend={jax.default_backend()} device={jax.devices()[0]}",
          file=sys.stderr)
    peak = peak_flops()
    for name in names or BENCHES:
        fn, unit = BENCHES[name]
        # Each config runs under its own obs session (metrics only, no
        # trace file) so the result line carries its telemetry — h2d
        # bytes, prefetch occupancy, zero1 bucket geometry, serving
        # counters — and a perf regression ships its own evidence.
        sess = obs.enable()
        try:
            out = fn()
        except Exception as e:  # keep the suite going; record the failure
            print(json.dumps({"metric": name, "error": repr(e)[:200]}))
            continue
        finally:
            snapshot = sess.registry.compact()
            obs.disable()
        rate, step_s, step_flops = out[:3]
        extra = out[3] if len(out) > 3 else {}
        line = {
            "metric": name, "value": round(rate, 1), "unit": unit,
            "step_ms": round(step_s * 1e3, 2),
            "gflops_per_step": round(step_flops / 1e9, 1),
            **extra,
        }
        if peak and step_flops:
            line["mfu"] = round(step_flops / step_s / peak, 4)
        if snapshot:
            line["obs"] = snapshot
        print(json.dumps(line))
        if jax.default_backend() == "tpu":
            update_last_green(line,
                              device=jax.devices()[0].device_kind)


if __name__ == "__main__":
    main(sys.argv[1:])
