"""Measure the reference-shaped CPU baseline (run once, record in BASELINE.md).

The reference publishes no numbers (BASELINE.md), so the 10x north-star
target is against an "8-executor Spark CPU" baseline we must construct.
Proxy: a single-process Keras ``model.train_on_batch`` loop on CPU —
exactly what each reference worker runs inside its executor
(reference: distkeras/workers.py hot loop) — scaled by 8 for the eight
executors, charging the reference NOTHING for its parameter-server
pickle/TCP overhead (SURVEY.md §3.2); i.e. a *generous* upper bound on
reference throughput.

Usage: python scripts/measure_cpu_baseline.py [mnist_mlp|cifar_cnn]
"""

import os
import sys
import time

os.environ["KERAS_BACKEND"] = "jax"

import numpy as np


def main(which: str = "cifar_cnn"):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import keras

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from distkeras_tpu.models import zoo

    batch = 128
    if which == "mnist_mlp":
        model = zoo.mnist_mlp(seed=0)
        x = np.random.default_rng(0).normal(size=(batch, 784)).astype(np.float32)
    elif which == "cifar_cnn":
        model = zoo.cifar_cnn(seed=0)
        x = np.random.default_rng(0).normal(size=(batch, 32, 32, 3)).astype(np.float32)
    else:
        raise SystemExit(f"unknown model {which}")
    y = np.random.default_rng(1).integers(0, 10, batch)

    model.compile(optimizer="sgd",
                  loss=keras.losses.SparseCategoricalCrossentropy(from_logits=True))
    # Warmup (compile/trace)
    for _ in range(3):
        model.train_on_batch(x, y)
    iters = 30
    t0 = time.perf_counter()
    for _ in range(iters):
        model.train_on_batch(x, y)
    dt = time.perf_counter() - t0
    sps = batch * iters / dt
    print(f"{which}: single-process CPU train_on_batch {sps:.1f} samples/sec")
    print(f"{which}: 8-executor Spark proxy = {8 * sps:.1f} samples/sec")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "cifar_cnn")
