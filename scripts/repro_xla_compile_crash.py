"""Minimal repro hunt for the serial-full-suite XLA-CPU segfault.

Rounds 3 and 4 both saw a serial `pytest tests/ -q` run segfault inside
``backend_compile`` late in the run (~85%, two DIFFERENT victim tests,
each green standalone), while xdist with 4 workers (~110 tests/process)
is reliably green.  Working theory: accumulated per-process XLA-CPU
backend state, not any specific test.  This script is that theory with
the test framework removed: ONE process compiles N structurally
distinct programs (a mix of plain jits and 8-device shard_map/pjit
steps with donation, shaped like the suite's trainers) until it
crashes or hits the cap, reporting the compile count and RSS every
``--report-every`` compiles.

Usage:
    python scripts/repro_xla_compile_crash.py [--cap 1500]
        [--clear-every 0] [--mode mix|plain|mesh]

``--clear-every K`` calls ``jax.clear_caches()`` every K compiles (the
candidate mitigation); ``JAX_ENABLE_COMPILATION_CACHE=0`` in the env
tests the other one.  Crash reporting: run it under a shell that
prints the exit code; rc=139 = the repro fired.  Results land in
docs/xla_cpu_compile_crash.md.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("KERAS_BACKEND", "jax")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import faulthandler

faulthandler.enable()

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_platforms", "cpu")


def rss_mb():
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS"):
                return int(line.split()[1]) // 1024
    return -1


def map_count():
    """Live mmap regions (vs /proc/sys/vm/max_map_count, default
    65530): every live LLVM-JIT'd executable holds several mapped code
    sections, so THIS — not RSS — is the resource a long-lived
    compiling process exhausts."""
    with open("/proc/self/maps") as f:
        return sum(1 for _ in f)


def plain_program(i):
    """A structurally unique small jit: depth/width keyed on i."""
    w = 4 + (i % 7)

    def f(x, y):
        for j in range(2 + i % 3):
            x = jnp.tanh(x @ y) + float(i)
        return x.sum()

    return jax.jit(f), (jnp.ones((w, w)), jnp.ones((w, w)))


def mesh_program(i):
    """An 8-device shard_map train-step-shaped program with donation —
    the suite's dominant compile shape."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2),
                ("data", "model"))
    w = 8 + 2 * (i % 5)
    sh = NamedSharding(mesh, P("data", None))
    rep = NamedSharding(mesh, P())

    def step(params, x):
        def loss(p):
            h = jnp.tanh(x @ p) * (1.0 + i % 4)
            return (h * h).mean()

        g = jax.grad(loss)(params)
        return params - 0.01 * g, loss(params)

    f = jax.jit(step, in_shardings=(rep, sh), out_shardings=(rep, rep),
                donate_argnums=0)
    return f, (jnp.ones((w, w)), jnp.ones((8, w)))


def transformer_program(i):
    """A real repo train-step compile — the suite's dominant shape
    (shard_map-free dp path, donation, remat every 3rd, MoE every
    4th, packed segments every 5th) at a unique tiny size per i."""
    import optax

    from distkeras_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(
        vocab_size=64 + (i % 3) * 8, d_model=16 + 8 * (i % 2),
        n_heads=2, n_layers=1 + i % 2, d_ff=32 + 16 * (i % 3),
        max_len=17, rope=bool(i % 2), remat=(i % 3 == 0),
        **({"num_experts": 2, "capacity_factor": 2.0}
           if i % 4 == 0 else {}))
    params = tfm.init_params(jax.random.key(i), cfg)
    opt = optax.adam(1e-2)
    step = jax.jit(tfm.make_train_step(cfg, opt), donate_argnums=0)
    toks = jnp.ones((4, 17), jnp.int32)
    seg = jnp.ones((4, 17), jnp.int32) if i % 5 == 0 else None
    return step, ((params, opt.init(params)), toks, None, seg)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cap", type=int, default=1500)
    ap.add_argument("--clear-every", type=int, default=0)
    ap.add_argument("--report-every", type=int, default=100)
    ap.add_argument("--mode", default="mix",
                    choices=("mix", "plain", "mesh", "transformer"))
    ap.add_argument("--drop-refs", action="store_true",
                    help="let each compiled executable be GC'd (the "
                    "suite keeps them alive, so default is keep)")
    args = ap.parse_args()

    print(f"pid={os.getpid()} mode={args.mode} cap={args.cap} "
          f"clear_every={args.clear_every} drop_refs={args.drop_refs} "
          f"comp_cache={os.environ.get('JAX_ENABLE_COMPILATION_CACHE')}",
          flush=True)
    keep = []
    for i in range(1, args.cap + 1):
        if args.mode == "transformer":
            f, xs = transformer_program(i)
        elif args.mode == "plain" or (args.mode == "mix" and i % 2):
            f, xs = plain_program(i)
        else:
            f, xs = mesh_program(i)
        out = f(*xs)
        jax.block_until_ready(jax.tree.leaves(out)[0])
        if not args.drop_refs:
            keep.append(f)  # live executables accumulate, like pytest
        if i % args.report_every == 0:
            print(f"compiles={i} rss_mb={rss_mb()} maps={map_count()}",
                  flush=True)
        if args.clear_every and i % args.clear_every == 0:
            jax.clear_caches()
    print(f"SURVIVED {args.cap} compiles, rss_mb={rss_mb()} "
          f"maps={map_count()}", flush=True)


if __name__ == "__main__":
    main()
