"""Graph lint CLI: source lint + IR lint + comm budgets, for CI.

Runs, in order:

1. the **source lint** (analysis/source_lint.py) over ``distkeras_tpu/``,
   plus the **thread-safety lint** (analysis/thread_lint.py) over the
   threaded core modules;
2. the **IR lint** (analysis/ir_lint.py) over the standard trace
   targets (analysis/targets.py) — every trainer family's and serving
   engine's real jitted step on the deterministic 8-device CPU mesh:
   dtype policy, host callbacks, PRNG reuse, donation coverage;
3. the **collective census** of each compiled step against
   ``scripts/comm_budget.json``, plus the ZeRO-1 parity proof
   (RS+AG == the gradient all-reduce it replaces, bytes measured
   from the declared exchange and the DP partner's compiled HLO).

Exit 0 iff there are zero unsuppressed error/warn findings.  Usage::

    python scripts/graph_lint.py                  # full run (CI)
    python scripts/graph_lint.py --source-only    # AST rules only, fast
    python scripts/graph_lint.py --threads        # thread-safety rules only
    python scripts/graph_lint.py --ir-only        # IR rules + budgets
    python scripts/graph_lint.py --update-budgets # re-record the census
    python scripts/graph_lint.py --update-baseline # re-record warn ledger
    python scripts/graph_lint.py -v               # also print censuses

``warn`` findings ratchet through ``scripts/lint_baseline.json``: the
recorded count per (rule, path) stops gating, anything beyond it (or
at a new location) still fails, and ``--update-baseline`` re-records
the ledger — review the diff; counts should only go DOWN.  Errors are
never baselineable.

See docs/graph_lint.md for the rule catalogue and the
``# dkt: ignore[rule]`` suppression syntax.
"""

import argparse
import os
import sys

# Deterministic substrate BEFORE jax initializes — the same 8-device
# CPU mesh the test suite uses, so censuses and budgets are stable no
# matter what accelerator is attached.
os.environ["KERAS_BACKEND"] = "jax"
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BUDGET_PATH = os.path.join(REPO, "scripts", "comm_budget.json")
BASELINE_PATH = os.path.join(REPO, "scripts", "lint_baseline.json")


def run_source(findings):
    from distkeras_tpu.analysis.source_lint import lint_paths

    findings += lint_paths([os.path.join(REPO, "distkeras_tpu")])
    run_threads(findings)


def run_threads(findings):
    from distkeras_tpu.analysis.thread_lint import lint_paths_threads

    findings += lint_paths_threads([os.path.join(REPO, "distkeras_tpu")])


def run_ir(findings, update: bool, verbose: bool):
    from distkeras_tpu.analysis import ir_lint
    from distkeras_tpu.analysis.targets import default_targets

    specs = default_targets()
    censuses, measured = {}, {}
    for spec in specs:
        fs, census = ir_lint.lint_trace(spec)
        findings += fs
        censuses[spec.name] = census
        measured[spec.name] = ir_lint.census_to_budget(census)
        if verbose:
            print(f"-- {spec.name}: "
                  f"{measured[spec.name]['wire_total']} wire B")
            for c in census:
                print(f"     {c.as_json()}")

    for spec in specs:
        if spec.zero1_parity_with:
            findings += ir_lint.check_zero1_parity(
                spec, censuses[spec.zero1_parity_with])

    if update:
        ir_lint.save_budgets(BUDGET_PATH, measured)
        print(f"wrote {BUDGET_PATH} ({len(measured)} targets)")
        return
    try:
        budgets = ir_lint.load_budgets(BUDGET_PATH)
    except (OSError, ValueError, KeyError):
        print(f"no readable budget at {BUDGET_PATH}; run "
              "--update-budgets to record one", file=sys.stderr)
        budgets = {}
    for name, census in censuses.items():
        findings += ir_lint.check_budget(name, census, budgets)


def main(argv):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--source-only", action="store_true")
    ap.add_argument("--ir-only", action="store_true")
    ap.add_argument("--threads", action="store_true",
                    help="thread-safety rules only (analysis/"
                         "thread_lint.py over the threaded core), "
                         "fastest of all")
    ap.add_argument("--update-budgets", action="store_true")
    ap.add_argument("--update-baseline", action="store_true",
                    help="re-record scripts/lint_baseline.json from "
                         "the current warn findings (ratchet ledger)")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    if args.update_baseline and (args.source_only or args.ir_only
                                 or args.threads):
        # The ledger covers BOTH lint layers; re-recording from a
        # half-census would drop the other layer's keys and start
        # failing its previously-baselined warns on the next full run.
        ap.error("--update-baseline needs the full run (drop "
                 "--source-only/--ir-only/--threads)")
    if args.threads and (args.source_only or args.ir_only
                         or args.update_budgets):
        # --threads skips the IR layer entirely: silently accepting a
        # budget re-record (or a conflicting mode) would exit 0
        # having written nothing.
        ap.error("--threads runs the thread-safety rules alone; it "
                 "cannot combine with --source-only/--ir-only/"
                 "--update-budgets")

    from distkeras_tpu.analysis.findings import (apply_baseline,
                                                 format_findings,
                                                 load_baseline,
                                                 save_baseline)

    findings = []
    if args.threads:
        run_threads(findings)
    else:
        if not args.ir_only:
            run_source(findings)
        if not args.source_only:
            run_ir(findings, update=args.update_budgets,
                   verbose=args.verbose)
    if args.update_baseline:
        counts = save_baseline(BASELINE_PATH, findings)
        print(f"wrote {BASELINE_PATH} ({sum(counts.values())} warn "
              f"finding(s) across {len(counts)} key(s))")
        # Fall through: the fresh ledger covers every current warn by
        # construction, but ERROR findings are never baselineable and
        # must still be reported and gate this very invocation.
    findings = apply_baseline(findings, load_baseline(BASELINE_PATH))
    print(format_findings(findings))
    return 1 if any(f.gating for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
