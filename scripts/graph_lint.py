"""Graph lint CLI: source + IR + shard lint + budgets, for CI.

Runs, in order:

1. the **source lint** (analysis/source_lint.py) over ``distkeras_tpu/``,
   plus the **thread-safety lint** (analysis/thread_lint.py) over the
   threaded core modules;
2. the **IR lint** (analysis/ir_lint.py) over the standard trace
   targets (analysis/targets.py) — every trainer family's and serving
   engine's real jitted step on the deterministic 8-device CPU mesh:
   dtype policy, host callbacks, PRNG reuse, donation coverage;
3. the **collective census** of each compiled step against
   ``scripts/comm_budget.json``, plus the ZeRO-1 parity proof
   (RS+AG == the gradient all-reduce it replaces, bytes measured
   from the declared exchange and the DP partner's compiled HLO);
4. the **shard lint** (analysis/shard_lint.py): the plan lint over
   every shipped partition-rule plan (dead/shadowed/duplicate rules,
   axis divisibility, replicated giants) and the compiled-placement
   census of every target — per-tensor shardings + per-device byte
   ledger pinned in ``scripts/shard_budget.json``, resharding
   collectives attributed to declared scopes;
5. the **contract lint** (analysis/contract_lint.py): the telemetry
   census of every emission site against ``scripts/obs_schema.json``,
   consumer + documentation resolution, the wire-protocol cross-check
   between every HTTP server and its in-repo clients, and the
   resource-pairing control-flow analysis over ``serving/``.

Exit 0 iff there are zero unsuppressed error/warn findings.  Usage::

    python scripts/graph_lint.py                  # full run (CI)
    python scripts/graph_lint.py --source-only    # AST rules only, fast
    python scripts/graph_lint.py --threads        # thread-safety rules only
    python scripts/graph_lint.py --ir-only        # IR + shard + budgets
    python scripts/graph_lint.py --shardings      # shard lint only
    python scripts/graph_lint.py --contracts      # contract lint only, fast
    python scripts/graph_lint.py --update-budgets # re-record ALL censuses
    python scripts/graph_lint.py --update-baseline # re-record warn ledger
    python scripts/graph_lint.py -v               # also print censuses

``warn`` findings ratchet through ``scripts/lint_baseline.json``: the
recorded count per (rule, path) stops gating, anything beyond it (or
at a new location) still fails, and ``--update-baseline`` re-records
the ledger — review the diff; counts should only go DOWN.  Errors are
never baselineable.

See docs/graph_lint.md for the rule catalogue and the
``# dkt: ignore[rule]`` suppression syntax.
"""

import argparse
import os
import sys

# Deterministic substrate BEFORE jax initializes — the same 8-device
# CPU mesh the test suite uses, so censuses and budgets are stable no
# matter what accelerator is attached.
os.environ["KERAS_BACKEND"] = "jax"
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BUDGET_PATH = os.path.join(REPO, "scripts", "comm_budget.json")
SHARD_BUDGET_PATH = os.path.join(REPO, "scripts", "shard_budget.json")
BASELINE_PATH = os.path.join(REPO, "scripts", "lint_baseline.json")
OBS_SCHEMA_PATH = os.path.join(REPO, "scripts", "obs_schema.json")


def run_source(findings):
    from distkeras_tpu.analysis.source_lint import lint_paths

    findings += lint_paths([os.path.join(REPO, "distkeras_tpu")])
    run_threads(findings)


def run_threads(findings):
    from distkeras_tpu.analysis.thread_lint import lint_paths_threads

    findings += lint_paths_threads([os.path.join(REPO, "distkeras_tpu")])


def run_contracts(findings, update: bool = False):
    """The contract lint: pure-AST + JSON, no trace, no compile.  With
    ``update`` the census is re-recorded into scripts/obs_schema.json
    BEFORE the check, so the same invocation verifies what it wrote."""
    from distkeras_tpu.analysis import contract_lint

    if update:
        contract_lint.save_obs_schema(
            OBS_SCHEMA_PATH, contract_lint.build_obs_schema(REPO))
        print(f"wrote {OBS_SCHEMA_PATH}")
    findings += contract_lint.lint_repo_contracts(
        REPO, schema_path=OBS_SCHEMA_PATH)


def run_plan_lint(findings):
    """The shard lint's pure-host half: every shipped plan constructor
    against the real trees it places (no trace, no compile)."""
    from distkeras_tpu.analysis import shard_lint

    findings += shard_lint.lint_repo_plans()


def run_ir(findings, update: bool, verbose: bool,
           shardings_only: bool = False):
    """The compile-heavy layer: each standard target is traced and
    compiled ONCE (ir_lint.trace_target) and the artifacts feed the IR
    audits, the collective census, AND the shard lint's placement
    census — the full run never pays a second backend compile.
    ``shardings_only`` skips the IR audits/comm budgets (the
    ``--shardings`` view)."""
    from distkeras_tpu.analysis import ir_lint, shard_lint
    from distkeras_tpu.analysis.targets import default_targets

    specs = default_targets()
    censuses, measured, placements = {}, {}, {}
    for spec in specs:
        art = ir_lint.trace_target(spec)
        if not shardings_only:
            fs, census = ir_lint.lint_trace(spec, artifacts=art)
            findings += fs
            censuses[spec.name] = census
            measured[spec.name] = ir_lint.census_to_budget(census)
        placements[spec.name] = shard_lint.placement_census(spec, art)
        findings += shard_lint.reshard_findings(spec, art.hlo)
        if verbose:
            p = placements[spec.name]
            wire = (f"{measured[spec.name]['wire_total']} wire B, "
                    if not shardings_only else "")
            print(f"-- {spec.name}: {wire}"
                  f"{p['bytes_per_device']} B/device "
                  f"({len(p['tensors'])} tensors, resharding "
                  f"{p['resharding']})")
            if not shardings_only:
                for c in censuses[spec.name]:
                    print(f"     {c.as_json()}")

    if not shardings_only:
        for spec in specs:
            if spec.zero1_parity_with:
                findings += ir_lint.check_zero1_parity(
                    spec, censuses[spec.zero1_parity_with])

    if update:
        ir_lint.save_budgets(BUDGET_PATH, measured)
        print(f"wrote {BUDGET_PATH} ({len(measured)} targets)")
        shard_lint.save_shard_budgets(SHARD_BUDGET_PATH, placements)
        print(f"wrote {SHARD_BUDGET_PATH} ({len(placements)} targets)")
        return
    if not shardings_only:
        try:
            budgets = ir_lint.load_budgets(BUDGET_PATH)
        except (OSError, ValueError, KeyError):
            print(f"no readable budget at {BUDGET_PATH}; run "
                  "--update-budgets to record one", file=sys.stderr)
            budgets = {}
        for name, census in censuses.items():
            findings += ir_lint.check_budget(name, census, budgets)
    try:
        shard_budgets = shard_lint.load_shard_budgets(SHARD_BUDGET_PATH)
    except (OSError, ValueError, KeyError):
        print(f"no readable budget at {SHARD_BUDGET_PATH}; run "
              "--update-budgets to record one", file=sys.stderr)
        shard_budgets = {}
    for name, entry in placements.items():
        findings += shard_lint.check_shard_budget(name, entry,
                                                  shard_budgets)


def main(argv):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--source-only", action="store_true")
    ap.add_argument("--ir-only", action="store_true")
    ap.add_argument("--threads", action="store_true",
                    help="thread-safety rules only (analysis/"
                         "thread_lint.py over the threaded core), "
                         "fastest of all")
    ap.add_argument("--shardings", action="store_true",
                    help="shard lint only (analysis/shard_lint.py): "
                         "the plan lint over every shipped partition "
                         "plan plus the compiled-placement census vs "
                         "scripts/shard_budget.json")
    ap.add_argument("--contracts", action="store_true",
                    help="contract lint only (analysis/"
                         "contract_lint.py): telemetry census vs "
                         "scripts/obs_schema.json, wire-protocol "
                         "cross-check, resource pairing — pure AST, "
                         "no compile")
    ap.add_argument("--update-budgets", action="store_true")
    ap.add_argument("--update-baseline", action="store_true",
                    help="re-record scripts/lint_baseline.json from "
                         "the current warn findings (ratchet ledger)")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    if args.contracts and (args.source_only or args.ir_only
                           or args.threads or args.shardings):
        # Same parity as --threads/--shardings: one mode flag at a
        # time, rejected before any heavy import is paid.
        ap.error("--contracts runs the contract lint alone; it cannot "
                 "combine with --source-only/--ir-only/--threads/"
                 "--shardings")
    if args.update_baseline and (args.source_only or args.ir_only
                                 or args.threads or args.shardings
                                 or args.contracts):
        # The ledger covers EVERY lint layer; re-recording from a
        # half-census would drop the other layers' keys and start
        # failing their previously-baselined warns on the next full run.
        ap.error("--update-baseline needs the full run (drop "
                 "--source-only/--ir-only/--threads/--shardings/"
                 "--contracts)")
    if args.threads and (args.source_only or args.ir_only
                         or args.shardings or args.update_budgets):
        # --threads skips the IR layer entirely: silently accepting a
        # budget re-record (or a conflicting mode) would exit 0
        # having written nothing.
        ap.error("--threads runs the thread-safety rules alone; it "
                 "cannot combine with --source-only/--ir-only/"
                 "--shardings/--update-budgets")
    if args.shardings and (args.source_only or args.ir_only):
        # Same parity as --threads: one mode flag at a time.
        ap.error("--shardings runs the shard lint alone; it cannot "
                 "combine with --source-only/--ir-only")
    if args.shardings and args.update_budgets:
        # --update-budgets re-records comm_budget.json AND
        # shard_budget.json from one compile pass; a --shardings run
        # computes only half and would leave the comm census stale.
        ap.error("--update-budgets re-records both census files from "
                 "the full IR pass; drop --shardings (use --ir-only "
                 "--update-budgets for the compile-heavy layer alone)")
    if args.source_only and args.update_budgets:
        # Symmetric to the --threads/--shardings guards: a source-only
        # run never reaches run_ir, so the re-record would exit 0
        # having written nothing.
        ap.error("--update-budgets needs the IR pass; drop "
                 "--source-only (or use --ir-only --update-budgets)")

    from distkeras_tpu.analysis.findings import (apply_baseline,
                                                 format_findings,
                                                 load_baseline,
                                                 save_baseline)

    findings = []
    if args.threads:
        run_threads(findings)
    elif args.shardings:
        run_plan_lint(findings)
        run_ir(findings, update=False, verbose=args.verbose,
               shardings_only=True)
    elif args.contracts:
        # --contracts --update-budgets re-records obs_schema.json
        # alone; unlike --shardings this leaves nothing stale — the
        # contract census never depends on the compile pass.
        run_contracts(findings, update=args.update_budgets)
    else:
        if not args.ir_only:
            run_source(findings)
            run_contracts(findings, update=args.update_budgets)
        if not args.source_only:
            run_plan_lint(findings)
            run_ir(findings, update=args.update_budgets,
                   verbose=args.verbose)
    if args.update_baseline:
        counts = save_baseline(BASELINE_PATH, findings)
        print(f"wrote {BASELINE_PATH} ({sum(counts.values())} warn "
              f"finding(s) across {len(counts)} key(s))")
        # Fall through: the fresh ledger covers every current warn by
        # construction, but ERROR findings are never baselineable and
        # must still be reported and gate this very invocation.
    findings = apply_baseline(findings, load_baseline(BASELINE_PATH))
    print(format_findings(findings))
    return 1 if any(f.gating for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
