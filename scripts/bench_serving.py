"""Serving benchmark: KV-cached decode through the bandwidth lens.

Decode is HBM-bandwidth-bound, not FLOPs-bound: each generated token
re-reads every matmul weight plus the KV cache at batch sizes far too
small to amortize them, so the right utilization metric is **achieved
bytes/s against the chip's HBM bandwidth**, not MFU (the roofline
argument of docs/perf_resnet50.md applied to inference — decode lives
on the bandwidth-bound side of the ridge).

Per config this prints one JSON line with:

- ``tokens_per_s`` (batch x new_tokens / wall) and ``ms_per_token``
  (per decode step — the user-visible latency between tokens),
- ``bw_util``: modeled HBM traffic per step / (step time x peak HBM
  bandwidth).  Traffic model, intentionally minimal: weight bytes are
  read once per step (batch shares them — that IS batching's win) and
  each batch row reads its cache slots once; activations are noise at
  decode shapes.  ``bw_util`` near 1.0 = the decode loop is running at
  the hardware's bandwidth roofline; the headroom 1 - bw_util is what
  software (fusion, layout, quantization) can still claim.

Workloads: greedy and sampled (top-k=50, temperature 0.8) at batch
1/8/64, bf16 vs int8 weights, rolling-window cache, and beam width 4 —
every serving surface models/generate.py offers.

Usage: python scripts/bench_serving.py [config ...]
(no args = all; unknown name lists the choices).  Results land in
BASELINE.md's Serving section; analysis in docs/perf_serving.md.
"""

import json
import os
import sys
import time

os.environ.setdefault("KERAS_BACKEND", "jax")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Peak HBM GB/s per chip, keyed on jax device_kind (public spec sheets:
# v5e 819, v4 1228, v5p 2765).
PEAK_HBM = {
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v4": 1228e9,
    "TPU v5p": 2765e9,
}


def _cfg(window=None):
    from distkeras_tpu.models import transformer as tfm

    # The flagship serving config (>= d1024 L8 per the round-2 review):
    # 32k vocab, 8 layers, d_model 1024 — ~152M weight params, the tied
    # embedding table is ~22% of weight bytes.
    return tfm.TransformerConfig(
        vocab_size=32768, d_model=1024, n_heads=8, n_layers=8, d_ff=4096,
        max_len=1025, dtype="bfloat16", rope=True,
        attention_window=window)


def weight_bytes(cfg, bytes_per_el=2):
    """Matmul-weight bytes one decode step reads (norm scales ignored:
    <0.01%).  Tied embedding counts once (embed gather touches B rows,
    the unembed reads the full [V, D] table)."""
    d, f, l, v = cfg.d_model, cfg.d_ff, cfg.n_layers, cfg.vocab_size
    attn = 4 * d * d          # wq wk wv wo
    ffn = 2 * d * f
    return (l * (attn + ffn) + v * d) * bytes_per_el


def cache_bytes_per_row(cfg, filled, bytes_per_el=2):
    """KV bytes one decode step reads per batch row.

    Static shapes: the masked attention reads all ``cfg.max_len`` slots
    regardless of how many are filled — that is the real traffic, and
    exactly why the rolling-window config (small max_len ring buffer)
    wins on long generations.  ``filled`` is kept for reporting only.
    """
    del filled
    return 2 * cfg.n_layers * cfg.max_len * cfg.kv_heads * cfg.head_dim \
        * bytes_per_el


def compiled_step_bytes(cfg, params, batch, kv_int8=False, pos=512):
    """``bytes accessed`` of ONE compiled decode step, from the
    executable's own cost model — the self-auditing counterpart to the
    hand-built traffic model (round-3 verdict: bw_util was self-graded;
    this makes the roofline claim checkable against the compiler).
    Abstract lowering only — nothing is allocated."""
    import jax
    import jax.numpy as jnp
    from distkeras_tpu.models.generate import _decode_step, init_cache

    try:
        cache = jax.eval_shape(
            lambda: init_cache(cfg, batch, kv_int8=kv_int8))
        p_sh = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(jnp.shape(a),
                                           jnp.asarray(a).dtype), params)
        toks = jax.ShapeDtypeStruct((batch,), jnp.int32)
        comp = jax.jit(
            lambda p, c, t: _decode_step(p, c, t, pos, cfg)
        ).lower(p_sh, cache, toks).compile()
        ca = comp.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return float(ca.get("bytes accessed", 0.0))
    except Exception as e:
        # Degrade loudly: without this number the roofline claim is
        # back to self-graded (the round-3 weakness), so a broken
        # self-audit must be visible, not silent.
        print(f"# compiled_step_bytes unavailable: {e!r}",
              file=sys.stderr)
        return 0.0


def _measure_decode(cfg, params, batch, new, p_len=64, iters=3,
                    w_bytes=None, seq_steps=None, c_bytes=None,
                    **gen_kw):
    """``seq_steps``: actual decode-step count of the compiled scan.
    Defaults to ``new`` (the prefill path); the quantized tree forces
    the sequential path, which teacher-forces p_len - 1 extra steps —
    callers on that path must pass ``p_len - 1 + new`` or ms_per_token
    and bw_util are biased against it."""
    import jax
    import numpy as np
    from distkeras_tpu.models.generate import generate

    prompt = jax.device_put(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (batch, p_len)).astype(np.int32))
    gen = jax.jit(lambda pp, pr: generate(pp, pr, cfg, new, **gen_kw))
    int(np.asarray(gen(params, prompt))[0, -1])  # compile + barrier
    t0 = time.perf_counter()
    for _ in range(iters):
        out = gen(params, prompt)
    int(np.asarray(out)[0, -1])
    dt = (time.perf_counter() - t0) / iters

    step_s = dt / (seq_steps if seq_steps is not None else new)
    w_bytes = w_bytes if w_bytes is not None else weight_bytes(cfg)
    c_bytes = (c_bytes if c_bytes is not None
               else cache_bytes_per_row(cfg, p_len + new))
    step_bytes = w_bytes + batch * c_bytes
    extras = {"batch": batch, "prompt_len": p_len, "new_tokens": new,
              "step_bytes_mb": round(step_bytes / 1e6, 1)}
    import jax as _j

    peak = PEAK_HBM.get(_j.devices()[0].device_kind)
    if peak:
        extras["bw_util"] = round(step_bytes / step_s / peak, 4)
        meas = compiled_step_bytes(cfg, params, batch,
                                   kv_int8=gen_kw.get("kv_int8", False))
        if meas:
            extras["step_bytes_measured_mb"] = round(meas / 1e6, 1)
            extras["bw_util_measured"] = round(meas / step_s / peak, 4)
    return batch * new / dt, step_s, 0.0, extras


def _params(quant=False, cfg=None):
    import jax
    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.models.quant import quantize_params

    p = tfm.init_params(jax.random.key(0), cfg or _cfg())
    return quantize_params(p) if quant else p


def kv_int8_cache_bytes(cfg):
    """Modeled per-row cache traffic of the int8 KV cache: data bytes
    halve (bytes_per_el=1) and the per-token per-kv-head f32 scales add
    a head_dim/4 x smaller term.  ONE definition for every kv_int8
    bench row."""
    return (cache_bytes_per_row(cfg, None, bytes_per_el=1)
            + 2 * cfg.n_layers * cfg.max_len * cfg.kv_heads * 4)


def bench_kv_int8(batch):
    def run():
        cfg = _cfg()
        return _measure_decode(cfg, _params(), batch, new=512,
                               kv_int8=True,
                               c_bytes=kv_int8_cache_bytes(cfg))
    return run


def bench_gqa4(batch):
    # GQA 4:1 (kv_heads 2 of 8): the cache-byte term drops 4x by
    # architecture. wk/wv shrink too (project to kv_heads only).
    def run():
        import dataclasses

        cfg = dataclasses.replace(_cfg(), n_kv_heads=2)
        d = cfg.d_model
        w_b = weight_bytes(cfg) - 2 * cfg.n_layers * d * (
            d - cfg.kv_heads * cfg.head_dim) * 2
        return _measure_decode(cfg, _params(cfg=cfg), batch, new=512,
                               w_bytes=w_b)
    return run


def bench_greedy(batch):
    def run():
        return _measure_decode(_cfg(), _params(), batch, new=512)
    return run


def bench_sampled(batch):
    def run():
        import jax

        return _measure_decode(_cfg(), _params(), batch, new=512,
                               temperature=0.8, top_k=50,
                               key=jax.random.key(0))
    return run


def bench_int8(batch):
    def run():
        # int8 params force the sequential path (no prefill): short
        # prompt keeps the measured region decode-dominated, and
        # seq_steps counts the p_len-1 teacher-forcing steps the scan
        # really runs so per-step numbers compare fairly vs bf16.
        return _measure_decode(_cfg(), _params(quant=True), batch,
                               new=512, p_len=16, seq_steps=15 + 512,
                               w_bytes=weight_bytes(_cfg(), bytes_per_el=1))
    return run


def bench_rolling_window():
    """Sliding-window serving: window 256 on a 256-slot ring-buffer
    cache, generating PAST the cache size (the rolling-decode path).
    Cache traffic/row drops ~4x vs the full-1025-slot config."""
    import dataclasses

    def run():
        import jax
        from distkeras_tpu.models import transformer as tfm

        cfg = dataclasses.replace(_cfg(window=256), max_len=256)
        params = tfm.init_params(jax.random.key(0), cfg)
        return _measure_decode(cfg, params, batch=8, new=512, p_len=64)
    return run


def bench_rolling_window_kvint8():
    """Rolling ring decode x int8 KV cache (round-5: the composition
    the engine refused through round 4).  Window 256 ring + int8 K/V:
    the cache term drops ~8x vs the full-1025-slot bf16 config (4x
    ring, 2x int8, minus the f32 scale rows)."""
    import dataclasses

    def run():
        import jax
        from distkeras_tpu.models import transformer as tfm

        cfg = dataclasses.replace(_cfg(window=256), max_len=256)
        params = tfm.init_params(jax.random.key(0), cfg)
        return _measure_decode(cfg, params, batch=8, new=512, p_len=64,
                               kv_int8=True,
                               c_bytes=kv_int8_cache_bytes(cfg))
    return run


def bench_beam4(window=None, beam_impl="auto"):
    """Beam-4 decode; ``window`` runs the ring-buffer config (the
    round-4 ancestry extension — compare beam4_windowed vs
    beam4_windowed_physical for what dropping the per-step cache
    gather is worth on a windowed cache)."""
    def run():
        import dataclasses

        import jax
        import numpy as np
        from distkeras_tpu.models.generate import beam_search

        if window is None:
            cfg = _cfg()
            params = _params()
        else:
            # Ring cache sized to the workload (prompt 64 + 256 new =
            # 320 <= 384 slots; beam search never rolls past max_len),
            # so the cache-traffic term shrinks with the ring, not the
            # full 1025-slot table.
            cfg = dataclasses.replace(_cfg(window=window), max_len=384)
            params = _params(cfg=cfg)
        batch, p_len, new, width = 8, 64, 256, 4
        prompt = jax.device_put(np.random.default_rng(0).integers(
            0, cfg.vocab_size, (batch, p_len)).astype(np.int32))
        bs = jax.jit(lambda pp, pr: beam_search(
            pp, pr, cfg, new, beam_width=width,
            beam_impl=beam_impl)[0])
        int(np.asarray(bs(params, prompt))[0, 0, -1])
        iters = 3
        t0 = time.perf_counter()
        for _ in range(iters):
            out = bs(params, prompt)
        int(np.asarray(out)[0, 0, -1])
        dt = (time.perf_counter() - t0) / iters
        step_s = dt / new
        # Beam traffic: weights once, cache read per beam row (B x W
        # rows).  The physical impl ADDITIONALLY gathers the whole
        # beam cache through the parent permutation every step — a
        # full read + write on top of the attention read (the cost
        # ancestry attention removes; modeling it is the point of the
        # windowed ancestry-vs-physical pair).
        cache_rows = batch * width * cache_bytes_per_row(cfg, 0)
        step_bytes = weight_bytes(cfg) + cache_rows
        if beam_impl == "physical":
            step_bytes += 2 * cache_rows
        extras = {"batch": batch, "beam_width": width, "prompt_len": p_len,
                  "new_tokens": new,
                  "step_bytes_mb": round(step_bytes / 1e6, 1)}
        if window is not None:
            extras["attention_window"] = window
            extras["ring_slots"] = cfg.max_len
        if beam_impl != "auto":
            extras["beam_impl"] = beam_impl
        peak = PEAK_HBM.get(jax.devices()[0].device_kind)
        if peak:
            extras["bw_util"] = round(step_bytes / step_s / peak, 4)
        # tokens/s counts kept tokens (batch x new), not beam work.
        return batch * new / dt, step_s, 0.0, extras
    return run


def bench_speculative_int8draft():
    """Self-speculative decode: the int8-quantized tree drafts for its
    own f32 parent.  Quantization preserves ~97% of greedy argmax
    choices, so acceptance is high by construction, draft steps read
    half the weight bytes, and the target pass amortizes its reads
    over n_draft+1 positions — a serving configuration that needs no
    second trained model.  Reports acceptance_rate next to tokens/s;
    compare against decode_greedy_b8 for the speedup."""
    def run():
        import jax
        import numpy as np
        from distkeras_tpu.models.quant import quantize_params
        from distkeras_tpu.models.speculative import speculative_generate

        cfg = _cfg()
        params = _params()
        draft = quantize_params(params)
        batch, p_len, new, k = 8, 64, 512, 3
        prompt = jax.device_put(np.random.default_rng(0).integers(
            0, cfg.vocab_size, (batch, p_len)).astype(np.int32))
        fn = jax.jit(lambda tp, dp, pr: speculative_generate(
            tp, dp, pr, cfg, cfg, new, n_draft=k))
        out, stats = fn(params, draft, prompt)
        int(np.asarray(out)[0, -1])
        iters = 3
        t0 = time.perf_counter()
        for _ in range(iters):
            out, stats = fn(params, draft, prompt)
        int(np.asarray(out)[0, -1])
        dt = (time.perf_counter() - t0) / iters
        extras = {"batch": batch, "prompt_len": p_len, "new_tokens": new,
                  "n_draft": k,
                  "acceptance_rate": round(float(stats["acceptance_rate"]),
                                           4),
                  "target_passes": int(stats["iterations"])}
        return batch * new / dt, dt / new, 0.0, extras
    return run


def bench_moe(batch, top_k=1):
    """MoE decode (8 experts over the flagship trunk, dense-routing
    T=1 path: each row gathers its top-k experts' slabs).  The traffic
    model makes the MoE decode cost structure explicit: expert mats are
    PER-ROW reads (a row's selected expert isn't shared the way the
    dense FFN is), so the per-step bytes are
    ``shared(attn+embed+router) + batch x (cache + k expert slabs)`` —
    the architectural reason MoE decode falls off the dense-FFN
    roofline as batch grows.  Compare against decode_greedy_b{batch}."""
    def run(new=512, p_len=64):
        import dataclasses

        cfg = dataclasses.replace(_cfg(), num_experts=8,
                                  moe_top_k=top_k)
        d, f, l = cfg.d_model, cfg.d_ff, cfg.n_layers
        # Shared per step: attention mats + tied embedding + router.
        w_b = (weight_bytes(cfg) - l * 2 * d * f * 2
               + l * d * cfg.num_experts * 2)
        # Per row per step: selected experts' w1+w2 slabs.
        c_b = (cache_bytes_per_row(cfg, None)
               + l * top_k * 2 * d * f * 2)
        out = _measure_decode(cfg, _params(cfg=cfg), batch, new=new,
                              p_len=p_len, w_bytes=w_b, c_bytes=c_b)
        out[3].update(num_experts=8, moe_top_k=top_k,
                      dense_baseline=f"decode_greedy_b{batch}")
        return out
    return run


def bench_lora_merged_serve():
    """LoRA serving: merge rank-8 wq/wv adapters into the base once
    (lora_merge), then decode the merged tree — the framework's LoRA
    deployment story.  The value is merged-tree decode tokens/s, which
    must sit on the dense row (merging leaves the forward
    byte-identical); ``merge_ms`` is the one-time cost of producing
    the servable tree."""
    def run(new=512):
        import jax
        import numpy as np
        from distkeras_tpu.models.lora import (LoRAConfig, lora_init,
                                               lora_merge)

        cfg = _cfg()
        base = _params()
        lcfg = LoRAConfig(rank=8, alpha=16.0, targets=("wq", "wv"))
        adapters = lora_init(jax.random.key(1), cfg, lcfg)
        # Trained-like adapters: B is zero at init (delta == 0); fill it
        # so the merge adds a real delta (same FLOPs either way, but a
        # zero delta would invite "it benched a no-op" skepticism).
        adapters = jax.tree.map(
            lambda a: a + 0.01 * jax.random.normal(
                jax.random.key(2), a.shape, a.dtype), adapters)
        merge = jax.jit(lambda p, ad: lora_merge(p, ad, cfg, lcfg))
        merged = merge(base, adapters)
        jax.block_until_ready(merged)
        iters = 10
        t0 = time.perf_counter()
        for _ in range(iters):
            merged = merge(base, adapters)
        jax.block_until_ready(merged)
        merge_s = (time.perf_counter() - t0) / iters
        rate, step_s, z, extras = _measure_decode(cfg, merged, 8,
                                                  new=new)
        extras.update(merge_ms=round(merge_s * 1e3, 2), lora_rank=8,
                      lora_targets="wq,wv",
                      dense_baseline="decode_greedy_b8")
        return rate, step_s, z, extras
    return run


def bench_prefix_ttft():
    # Time-to-first-token with a reused 512-token prefix vs prefilling
    # prefix+tail from scratch: the system-prompt serving pattern.
    # Reported value = scratch_ttft / cached_ttft (the reuse speedup);
    # extras carry both absolute latencies.
    def run():
        import jax
        import numpy as np
        from distkeras_tpu.models.generate import generate, prefill

        cfg = _cfg()
        params = _params()
        rng = np.random.default_rng(0)
        prefix = jax.device_put(rng.integers(
            0, cfg.vocab_size, (8, 512)).astype(np.int32))
        tail = jax.device_put(rng.integers(
            0, cfg.vocab_size, (8, 32)).astype(np.int32))
        full = jax.numpy.concatenate([prefix, tail], axis=1)
        cache, _ = jax.jit(
            lambda pp, pr: prefill(pp, pr, cfg, last_logits=False)
        )(params, prefix)
        g_scratch = jax.jit(lambda pp, pr: generate(pp, pr, cfg, 1))
        g_cached = jax.jit(lambda pp, pr, c: generate(
            pp, pr, cfg, 1, prompt_cache=(c, 512)))
        int(np.asarray(g_scratch(params, full))[0, -1])
        int(np.asarray(g_cached(params, tail, cache))[0, -1])
        iters = 10
        t0 = time.perf_counter()
        for _ in range(iters):
            out = g_scratch(params, full)
        int(np.asarray(out)[0, -1])
        scratch = (time.perf_counter() - t0) / iters
        t0 = time.perf_counter()
        for _ in range(iters):
            out = g_cached(params, tail, cache)
        int(np.asarray(out)[0, -1])
        cached = (time.perf_counter() - t0) / iters
        return scratch / cached, cached, 0.0, {
            "scratch_ttft_ms": round(scratch * 1e3, 2),
            "cached_ttft_ms": round(cached * 1e3, 2),
            "prefix_len": 512, "tail_len": 32}
    return run


def bench_engine(kv_int8=False):
    # Continuous-batching engine overhead vs raw generate: 8 full lanes
    # decoding 256 tokens in step(8) windows (one host round-trip per 8
    # tokens/lane).  The value is engine tokens/s; ``raw_tok_s`` in the
    # extras is the same workload through plain generate for the
    # overhead ratio.  ``kv_int8``: int8 KV cache on both sides (the
    # engine regime where cache bytes dominate).
    def run():
        import jax
        import numpy as np
        from distkeras_tpu.models.generate import generate
        from distkeras_tpu.serving import ContinuousBatcher

        cfg = _cfg()
        params = _params()
        rng = np.random.default_rng(0)
        prompts = rng.integers(0, cfg.vocab_size, (8, 64)).astype(np.int32)
        new = 256

        g = jax.jit(lambda pp, pr: generate(pp, pr, cfg, new,
                                            kv_int8=kv_int8))
        int(np.asarray(g(params, prompts))[0, -1])
        t0 = time.perf_counter()
        out = g(params, prompts)
        int(np.asarray(out)[0, -1])
        raw = 8 * new / (time.perf_counter() - t0)

        eng = ContinuousBatcher(params, cfg, lanes=8,
                                kv_int8=kv_int8)
        lanes = [eng.submit(prompts[i], new) for i in range(8)]
        while eng.running():     # warm compile of admit + step(8)
            eng.step(8)
        for lane in lanes:
            eng.drain(lane)
        t0 = time.perf_counter()
        lanes = [eng.submit(prompts[i], new) for i in range(8)]
        while eng.running():
            eng.step(8)
        dt = time.perf_counter() - t0
        for lane in lanes:
            eng.drain(lane)
        tok_s = 8 * new / dt
        return tok_s, dt / new, 0.0, {
            "raw_tok_s": round(raw, 1),
            "engine_overhead": round(raw / tok_s, 3),
            "lanes": 8, "step_window": 8, "new_tokens": new,
            **({"kv_int8": True} if kv_int8 else {})}
    return run


def bench_engine_speculative():
    """SpeculativeBatcher vs ContinuousBatcher on the same greedy
    workload (8 lanes x 256 tokens, d1024 target): each speculative
    round is n_draft cheap draft passes + ONE target chunk, so the win
    is acceptance_rate * n_draft amortized target-weight reads per
    round — the serving regime where plain decode is weight-bound.
    Extras carry the plain-engine rate for the ratio and the measured
    rounds/tokens.  Draft = the int8-quantized target (same trick as
    decode_speculative_int8draft: a REAL high-acceptance draft —
    ~0.93 measured solo — without a second pretrained tree; a random
    small model would have ~zero argmax agreement and measure
    nothing)."""
    def run(n_draft=3, new=256, p_len=64):
        import numpy as np
        from distkeras_tpu.models.quant import quantize_params
        from distkeras_tpu.serving import ContinuousBatcher, \
            SpeculativeBatcher

        cfg = _cfg()
        params = _params()
        dcfg = cfg
        draft = quantize_params(params)
        rng = np.random.default_rng(0)
        prompts = rng.integers(0, cfg.vocab_size,
                               (8, p_len)).astype(np.int32)

        def drive(eng, step_args):
            # Warm-up run on the SAME instance (fresh engines would
            # recompile inside the timed region), then the timed run
            # over reused lanes.
            lanes = [eng.submit(prompts[i], new) for i in range(8)]
            while eng.running():
                eng.step(*step_args)
            for ln in lanes:
                eng.drain(ln)
            t0 = time.perf_counter()
            lanes = [eng.submit(prompts[i], new) for i in range(8)]
            rounds = 0
            while eng.running():
                eng.step(*step_args)
                rounds += 1
            dt = time.perf_counter() - t0
            for ln in lanes:
                eng.drain(ln)
            return 8 * new / dt, rounds, dt

        # Plain baseline at step(n_draft + 1): the same tokens-per-
        # host-round-trip budget as a speculative round, so the ratio
        # isolates speculation from dispatch amortization.
        plain_tok_s, plain_rounds, _ = drive(
            ContinuousBatcher(params, cfg, lanes=8), (n_draft + 1,))
        spec_tok_s, spec_rounds, spec_dt = drive(
            SpeculativeBatcher(params, draft, cfg, dcfg, lanes=8,
                               n_draft=n_draft), ())
        # Second element = per decode-POSITION time (dt / new), the
        # same convention as bench_engine's ms_per_token.
        return spec_tok_s, spec_dt / new, 0.0, {
            "plain_tok_s": round(plain_tok_s, 1),
            "speedup": round(spec_tok_s / plain_tok_s, 3),
            "n_draft": n_draft, "new_tokens": new, "lanes": 8,
            "spec_rounds": spec_rounds, "plain_rounds": plain_rounds}
    return run


def bench_engine_load(lanes, offered_rps):
    """Open-loop Poisson load test of the continuous-batching engine:
    requests arrive at ``offered_rps`` (seeded exponential
    interarrivals), are admitted when a lane frees, and decode in
    step(4) windows.  Reports the latency distribution serving engines
    live by: TTFT (arrival -> first emitted token, queueing included)
    and TPOT (per-token interval after the first) at p50/p99, plus
    achieved token throughput over the makespan.  The value is
    achieved tokens/s; compare TTFT across offered loads and lane
    counts for the saturation curve."""
    def run(n_req=48, p_len=64, new=128, window=4):
        import numpy as np
        from distkeras_tpu.serving import ContinuousBatcher

        cfg = _cfg()
        params = _params()
        rng = np.random.default_rng(0)
        arrivals = np.cumsum(rng.exponential(1.0 / offered_rps, n_req))
        prompts = rng.integers(0, cfg.vocab_size,
                               (n_req, p_len)).astype(np.int32)

        eng = ContinuousBatcher(params, cfg, lanes=lanes)
        # Compile admission (the p_len-1 bucket) and the step window
        # BEFORE the clock starts: first-call XLA compiles are not
        # serving latency.
        warm = eng.submit(prompts[0], new)
        while warm in eng.running():
            eng.step(window)
        eng.drain(warm)

        lane_req: dict[int, int] = {}
        first_t = np.full(n_req, np.nan)
        done_t = np.full(n_req, np.nan)
        tokens_of = np.zeros(n_req, np.int64)
        next_rid = 0
        t0 = time.perf_counter()
        while np.isnan(done_t).any():
            now = time.perf_counter() - t0
            # Admit every request that has arrived, while lanes free.
            while (next_rid < n_req and arrivals[next_rid] <= now
                   and eng.free_lanes()):
                lane = eng.submit(prompts[next_rid], new)
                lane_req[lane] = next_rid
                next_rid += 1
            if not eng.running():
                if next_rid < n_req:
                    # Idle until the next arrival (open-loop clock).
                    time.sleep(max(0.0, arrivals[next_rid]
                                   - (time.perf_counter() - t0)))
                continue
            out = eng.step(window)
            now = time.perf_counter() - t0
            for lane, toks in out.items():
                rid = lane_req[lane]
                if toks and np.isnan(first_t[rid]):
                    first_t[rid] = now
                tokens_of[rid] += len(toks)
            for lane, rid in list(lane_req.items()):
                if lane not in eng.running() and np.isnan(done_t[rid]):
                    done_t[rid] = now
                    eng.drain(lane)
                    del lane_req[lane]
        makespan = float(np.nanmax(done_t))
        total_tokens = int(tokens_of.sum())
        ttft = first_t - arrivals
        tpot = (done_t - first_t) / np.maximum(tokens_of - 1, 1)
        pct = lambda a, q: round(float(np.percentile(a, q)) * 1e3, 1)
        extras = {
            "lanes": lanes, "offered_rps": offered_rps,
            "n_requests": n_req, "prompt_len": p_len,
            "new_tokens": new, "step_window": window,
            "achieved_rps": round(n_req / makespan, 2),
            # Per-request makespan under its own key: NOT a per-token
            # rate (makespan/n_req spans queueing + all decode rounds).
            "ms_per_request": round(makespan / n_req * 1e3, 1),
            "ttft_p50_ms": pct(ttft, 50), "ttft_p99_ms": pct(ttft, 99),
            "tpot_p50_ms": pct(tpot, 50), "tpot_p99_ms": pct(tpot, 99),
            # TTFT/TPOT are observed at step(window) boundaries, so the
            # percentiles are quantized to ~window tokens of decode
            # time; this is the quantum in ms (window x median TPOT).
            "ttft_granularity_ms": round(
                float(np.percentile(tpot, 50)) * 1e3 * window, 1),
        }
        # Second element feeds main()'s ms_per_token: aggregate
        # per-token wall time (1/value), a real per-token rate.
        return total_tokens / makespan, makespan / total_tokens, 0.0, \
            extras
    return run


def bench_engine_load_elastic(tiers, offered_rps):
    """Open-loop Poisson load against an ELASTIC engine (the PR-5
    follow-up): requests go through enqueue/poll (lane ids are
    unstable across tier resizes), QueueFull is retried at the next
    loop tick (the shed-or-retry contract), and the row reports
    achieved throughput + request-latency percentiles plus the tier
    trajectory (the obs snapshot on the row carries
    serving.lanes_tier / serving.resizes — main() attaches it)."""
    def run(n_req=48, p_len=64, new=128, window=4):
        import numpy as np
        from distkeras_tpu.serving import ContinuousBatcher, QueueFull

        cfg = _cfg()
        params = _params()
        rng = np.random.default_rng(0)
        arrivals = np.cumsum(rng.exponential(1.0 / offered_rps, n_req))
        prompts = rng.integers(0, cfg.vocab_size,
                               (n_req, p_len)).astype(np.int32)
        eng = ContinuousBatcher(params, cfg, lane_tiers=tiers,
                                max_queue=4, scale_up_after=2,
                                scale_down_after=8,
                                step_windows=(1, window))
        done_t = np.full(n_req, np.nan)
        rid_of = {}
        next_req = 0
        t0 = time.perf_counter()
        while np.isnan(done_t).any():
            now = time.perf_counter() - t0
            while next_req < n_req and arrivals[next_req] <= now:
                try:
                    rid_of[next_req] = eng.enqueue(prompts[next_req],
                                                   new)
                except QueueFull:
                    break                  # retry at the next tick
                next_req += 1
            if not eng.running() and not eng.queued:
                if next_req < n_req:
                    time.sleep(max(0.0, arrivals[next_req]
                                   - (time.perf_counter() - t0)))
                continue
            eng.step(window)
            now = time.perf_counter() - t0
            for req, rid in rid_of.items():
                if np.isnan(done_t[req]) and eng.poll(rid) is not None:
                    done_t[req] = now
        results = eng.results()
        ok = sum(r.ok for r in results.values())
        makespan = float(np.nanmax(done_t))
        total_tokens = sum(len(r.generated) for r in results.values())
        lat = done_t - arrivals
        pct = lambda a, q: round(float(np.percentile(a, q)) * 1e3, 1)
        extras = {
            "lane_tiers": list(tiers), "offered_rps": offered_rps,
            "n_requests": n_req, "ok": ok, "new_tokens": new,
            "step_window": window, "final_lanes": eng.lanes,
            "tier_epoch": eng.tier_epoch,
            "achieved_rps": round(n_req / makespan, 2),
            "request_p50_ms": pct(lat, 50),
            "request_p99_ms": pct(lat, 99),
        }
        return total_tokens / makespan, makespan / max(total_tokens,
                                                       1), 0.0, extras
    return run


def bench_engine_load_spec(lanes, offered_rps):
    """Open-loop Poisson load against the SpeculativeBatcher (the
    PR-5 follow-up): same arrival process as engine_load_*, draft =
    the int8-quantized target (the high-acceptance self-draft), TTFT/
    TPOT percentiles per offered load.  Each step advances a lane up
    to n_draft + 1 tokens, so TPOT granularity is a speculative
    round, not a token."""
    def run(n_req=48, p_len=64, new=128, n_draft=3):
        import numpy as np
        from distkeras_tpu.models.quant import quantize_params
        from distkeras_tpu.serving import SpeculativeBatcher

        cfg = _cfg()
        params = _params()
        draft = quantize_params(params)
        rng = np.random.default_rng(0)
        arrivals = np.cumsum(rng.exponential(1.0 / offered_rps, n_req))
        prompts = rng.integers(0, cfg.vocab_size,
                               (n_req, p_len)).astype(np.int32)
        eng = SpeculativeBatcher(params, draft, cfg, cfg, lanes=lanes,
                                 n_draft=n_draft)
        warm = eng.submit(prompts[0], new)
        while warm in eng.running():
            eng.step()
        eng.drain(warm)

        lane_req: dict[int, int] = {}
        first_t = np.full(n_req, np.nan)
        done_t = np.full(n_req, np.nan)
        tokens_of = np.zeros(n_req, np.int64)
        next_rid = 0
        t0 = time.perf_counter()
        while np.isnan(done_t).any():
            now = time.perf_counter() - t0
            while (next_rid < n_req and arrivals[next_rid] <= now
                   and eng.free_lanes()):
                lane = eng.submit(prompts[next_rid], new)
                lane_req[lane] = next_rid
                next_rid += 1
            if not eng.running():
                if next_rid < n_req:
                    time.sleep(max(0.0, arrivals[next_rid]
                                   - (time.perf_counter() - t0)))
                continue
            out = eng.step()
            now = time.perf_counter() - t0
            for lane, toks in out.items():
                rid = lane_req[lane]
                if toks and np.isnan(first_t[rid]):
                    first_t[rid] = now
                tokens_of[rid] += len(toks)
            for lane, rid in list(lane_req.items()):
                if lane not in eng.running() and np.isnan(done_t[rid]):
                    done_t[rid] = now
                    eng.drain(lane)
                    del lane_req[lane]
        makespan = float(np.nanmax(done_t))
        total_tokens = int(tokens_of.sum())
        ttft = first_t - arrivals
        tpot = (done_t - first_t) / np.maximum(tokens_of - 1, 1)
        pct = lambda a, q: round(float(np.percentile(a, q)) * 1e3, 1)
        extras = {
            "lanes": lanes, "offered_rps": offered_rps,
            "n_requests": n_req, "prompt_len": p_len,
            "new_tokens": new, "n_draft": n_draft,
            "achieved_rps": round(n_req / makespan, 2),
            "ttft_p50_ms": pct(ttft, 50), "ttft_p99_ms": pct(ttft, 99),
            "tpot_p50_ms": pct(tpot, 50), "tpot_p99_ms": pct(tpot, 99),
            "degraded": eng.degraded,
        }
        return total_tokens / makespan, makespan / total_tokens, 0.0, \
            extras
    return run


def bench_longprompt(prefill_chunk):
    """The chunked-prefill claim, measured: 7 lanes decode steadily
    while ONE long prompt (1024 warm tokens) is admitted mid-flight.
    Reports the decoding lanes' inter-token step gap p50/p99 over the
    run and the gap of the single worst step (monolithic admission:
    the whole 1024-token prefill lands between two steps; chunked:
    bounded by one chunk).  Value = aggregate tokens/s (the chunked
    row pays the same total prefill compute, spread out)."""
    def run(p_short=64, p_long=1017, new=160, long_new=8):
        import numpy as np
        from distkeras_tpu.serving import ContinuousBatcher

        cfg = _cfg()
        params = _params()
        if p_long + long_new > cfg.max_len:
            p_long = cfg.max_len - long_new
        # Self-scale to the config (the bench-contract tests drive
        # this through a tiny model): the chunk is ~1/8 of the cache,
        # capped at the requested width.
        chunk = (None if prefill_chunk is None
                 else min(prefill_chunk, max(1, cfg.max_len // 8)))
        rng = np.random.default_rng(0)
        shorts = rng.integers(0, cfg.vocab_size,
                              (7, p_short)).astype(np.int32)
        long_p = rng.integers(0, cfg.vocab_size,
                              (p_long,)).astype(np.int32)
        eng = ContinuousBatcher(
            params, cfg, lanes=8,
            prompt_buckets=(p_short, chunk or 128, p_long),
            prefill_chunk=chunk)
        lanes = [eng.submit(s, new) for s in shorts]
        for _ in range(4):                    # warm the step program
            eng.step()
        gaps = []
        t0 = time.perf_counter()
        injected = None
        steps = 0
        while any(l in eng.running() for l in lanes):
            if steps == 2:
                injected = eng.submit(long_p, long_new)
            t1 = time.perf_counter()
            eng.step()
            gaps.append(time.perf_counter() - t1)
            steps += 1
        dt = time.perf_counter() - t0
        for lane in lanes:
            eng.drain(lane)
        if injected is not None:
            while injected in eng.running():
                eng.step()
            eng.drain(injected)
        gaps = np.asarray(gaps)
        pct = lambda q: round(float(np.percentile(gaps, q)) * 1e3, 2)
        total = 7 * new
        extras = {
            "lanes": 8, "prompt_len_long": int(p_long),
            "prefill_chunk": chunk, "new_tokens": new,
            "step_gap_p50_ms": pct(50), "step_gap_p99_ms": pct(99),
            "step_gap_max_ms": round(float(gaps.max()) * 1e3, 2),
        }
        return total / dt, dt / total, 0.0, extras
    return run


def bench_prefix_reuse(n_prefixes):
    """The multi-prefix KV pool, measured: ``n_prefixes`` distinct
    512-token prefixes pooled device-side, 32 requests with 32-token
    tails round-robin across them.  Value = pooled tokens/s over the
    full serve; ``noreuse_tok_s`` re-runs the same workload with the
    full prefix+tail prompt re-prefilled per request (the v1
    behavior), so the ratio is what the pool is worth at this prefix
    length.  1/4/16 prefixes sweep the pool-size axis."""
    def run(prefix_len=512, tail_len=32, n_req=32, new=32):
        import jax as _jax
        import numpy as np
        from distkeras_tpu.models.generate import prefill
        from distkeras_tpu.serving import ContinuousBatcher, PrefixPool

        cfg = _cfg()
        params = _params()
        rng = np.random.default_rng(0)
        prefixes = rng.integers(0, cfg.vocab_size,
                                (n_prefixes, prefix_len)
                                ).astype(np.int32)
        tails = rng.integers(0, cfg.vocab_size,
                             (n_req, tail_len)).astype(np.int32)
        pool = PrefixPool(cfg, slots=n_prefixes)
        pf = _jax.jit(lambda pp, pr: prefill(pp, pr, cfg,
                                             last_logits=False)[0])
        pids = []
        for i in range(n_prefixes):
            pids.append(pool.put(pf(params, prefixes[i][None]),
                                 prefix_len))

        def serve(eng, use_pool):
            order = []
            t0 = time.perf_counter()
            done = 0
            nxt = 0
            lane_req = {}
            while done < n_req:
                while nxt < n_req and eng.free_lanes():
                    if use_pool:
                        lane = eng.submit(tails[nxt], new,
                                          prefix_id=pids[nxt
                                                         % n_prefixes])
                    else:
                        full = np.concatenate(
                            [prefixes[nxt % n_prefixes], tails[nxt]])
                        lane = eng.submit(full, new)
                    lane_req[lane] = nxt
                    nxt += 1
                eng.step(4)
                for lane in [l for l in lane_req
                             if l not in eng.running()]:
                    eng.drain(lane)
                    del lane_req[lane]
                    done += 1
            return time.perf_counter() - t0

        pooled_eng = ContinuousBatcher(params, cfg, lanes=8,
                                       prompt_buckets=(tail_len,),
                                       prefix_pool=pool,
                                       step_windows=(1, 4))
        serve(pooled_eng, True)               # warm
        dt_pool = serve(pooled_eng, True)
        plain_eng = ContinuousBatcher(
            params, cfg, lanes=8,
            prompt_buckets=(tail_len, prefix_len + tail_len))
        serve(plain_eng, False)               # warm
        dt_plain = serve(plain_eng, False)
        total = n_req * new
        extras = {
            "n_prefixes": n_prefixes, "prefix_len": prefix_len,
            "tail_len": tail_len, "n_requests": n_req,
            "new_tokens": new,
            "noreuse_tok_s": round(total / dt_plain, 1),
            "reuse_speedup": round(dt_plain / dt_pool, 3),
        }
        return total / dt_pool, dt_pool / total, 0.0, extras
    return run


def _paged_block(max_len, target=None):
    """Largest divisor of ``max_len`` at or under ~max_len/8 — the
    paged rows must self-scale to the config (block must divide
    max_len; the flagship's 1025 has awkward divisors)."""
    cap = target if target is not None else max(1, max_len // 8)
    return next(b for b in range(min(cap, max_len), 0, -1)
                if max_len % b == 0)


def bench_paged_lanes(lane_mult):
    """The lane-count-at-fixed-HBM claim, measured: a monolithic
    engine at ``mono_lanes`` full-``max_len`` rows vs a PagedBatcher
    whose slab holds the SAME block count (same resident KV bytes)
    serving ``lane_mult`` x the lanes — possible because each request
    only touches ~1/lane_mult of max_len, so blocks cover actual
    tokens, not rows.  Both serve the identical request set; value =
    paged tokens/s, extras carry the monolithic rate, both lane
    counts, and the slab geometry.  ``lanes_ratio`` is the headline:
    >= 2 at fixed slab bytes is the acceptance bar."""
    def run(mono_lanes=4, p_len=32, new=None):
        import numpy as np
        from distkeras_tpu.serving import ContinuousBatcher, PagedBatcher

        cfg = _cfg()
        params = _params()
        block = _paged_block(cfg.max_len)
        mb = cfg.max_len // block
        paged_lanes = mono_lanes * lane_mult
        # Each request's whole budget fits 1/lane_mult of a lane row
        # (prompt + generation), so paged_lanes of them fit the slab.
        budget = cfg.max_len // lane_mult
        p_len = min(p_len, max(2, budget // 2))
        if new is None:
            # Slack of one block for roundup, floor of 1 token.
            new = max(1, budget - p_len - block)
        n_req = paged_lanes
        rng = np.random.default_rng(0)
        prompts = rng.integers(0, cfg.vocab_size,
                               (n_req, p_len)).astype(np.int32)

        def serve(eng):
            # ``peak`` is MEASURED concurrency (max simultaneously
            # decoding lanes), not the configured lane count — the
            # >=2x-at-fixed-slab acceptance claim must be falsifiable
            # (a regression that serializes paged admissions shows up
            # here, not hidden behind a constant).
            done, nxt, lane_req, peak = 0, 0, {}, 0
            t0 = time.perf_counter()
            while done < n_req:
                while nxt < n_req and eng.free_lanes():
                    lane = eng.submit(prompts[nxt], new)
                    if lane is None:
                        break
                    lane_req[lane] = nxt
                    nxt += 1
                peak = max(peak, len(eng.running()))
                eng.step()
                for lane in [l for l in lane_req
                             if l not in eng.running()]:
                    eng.drain(lane)
                    del lane_req[lane]
                    done += 1
            return time.perf_counter() - t0, peak

        slab_blocks = mono_lanes * mb   # the fixed HBM budget
        paged = PagedBatcher(params, cfg, lanes=paged_lanes,
                             block=block, n_blocks=slab_blocks + 1,
                             prompt_buckets=(p_len - 1,))
        serve(paged)                    # warm
        dt_paged, peak_paged = serve(paged)
        mono = ContinuousBatcher(params, cfg, lanes=mono_lanes,
                                 prompt_buckets=(p_len - 1,))
        serve(mono)                     # warm
        dt_mono, peak_mono = serve(mono)
        total = n_req * new
        bytes_per_block = (2 * cfg.n_layers * block * cfg.kv_heads
                           * cfg.head_dim * 2)
        extras = {
            "mono_lanes": mono_lanes, "paged_lanes": paged_lanes,
            "peak_lanes_paged": peak_paged,
            "peak_lanes_mono": peak_mono,
            "lanes_ratio": round(peak_paged / max(peak_mono, 1), 2),
            "block": block, "slab_blocks": slab_blocks,
            "slab_mb": round(slab_blocks * bytes_per_block / 1e6, 1),
            "prompt_len": p_len, "new_tokens": new,
            "mono_tok_s": round(total / dt_mono, 1),
            "paged_speedup": round(dt_mono / dt_paged, 3),
        }
        return total / dt_paged, dt_paged / total, 0.0, extras
    return run


def bench_paged_shared_stem(n_req):
    """Cross-request stem sharing, measured: ``n_req`` requests whose
    prompts share one long stem (block-aligned) with distinct tails,
    served on a PagedBatcher — every request past the first hash-hits
    the stem blocks and prefills only its tail.  ``noshare_tok_s``
    re-runs the same shapes with fully DISTINCT stems (every request
    pays the whole prefill); ``blocks_saved`` counts the refcounted
    block hits.  Value = shared-stem tokens/s."""
    def run(stem_len=None, tail_len=16, new=32, lanes=8):
        import numpy as np
        from distkeras_tpu.serving import PagedBatcher

        cfg = _cfg()
        params = _params()
        block = _paged_block(cfg.max_len)
        if stem_len is None:
            stem_len = (cfg.max_len // 2 // block) * block
        stem_len = max(block, (stem_len // block) * block)
        rng = np.random.default_rng(0)
        stem = rng.integers(0, cfg.vocab_size,
                            (stem_len,)).astype(np.int32)
        tails = rng.integers(0, cfg.vocab_size,
                             (n_req, tail_len)).astype(np.int32)
        alt_stems = rng.integers(0, cfg.vocab_size,
                                 (n_req, stem_len)).astype(np.int32)

        def serve(eng, prompts):
            done, nxt, lane_req = 0, 0, {}
            t0 = time.perf_counter()
            while done < n_req:
                while nxt < n_req and eng.free_lanes():
                    lane = eng.submit(prompts[nxt], new)
                    if lane is None:
                        break
                    lane_req[lane] = nxt
                    nxt += 1
                eng.step()
                for lane in [l for l in lane_req
                             if l not in eng.running()]:
                    eng.drain(lane)
                    del lane_req[lane]
                    done += 1
            return time.perf_counter() - t0

        shared_prompts = [np.concatenate([stem, t]) for t in tails]
        distinct_prompts = [np.concatenate([alt_stems[i], tails[i]])
                            for i in range(n_req)]
        mb = cfg.max_len // block
        eng = PagedBatcher(params, cfg, lanes=lanes, block=block,
                           n_blocks=lanes * mb + 1,
                           prompt_buckets=(tail_len, stem_len + tail_len))
        serve(eng, shared_prompts)              # warm
        hits0 = eng.stem_hit_blocks
        dt_shared = serve(eng, shared_prompts)
        hits = eng.stem_hit_blocks - hits0
        dt_plain = serve(eng, distinct_prompts)
        total = n_req * new
        extras = {
            "n_requests": n_req, "stem_len": int(stem_len),
            "tail_len": tail_len, "new_tokens": new, "block": block,
            "blocks_saved": int(hits),
            "noshare_tok_s": round(total / dt_plain, 1),
            "share_speedup": round(dt_plain / dt_shared, 3),
        }
        return total / dt_shared, dt_shared / total, 0.0, extras
    return run


def bench_paged_cow_fork():
    """CoW fork cost vs cache copy, measured: fork a mid-decode lane
    ``iters`` times (page-table share + ONE block copy) and time it
    against the monolithic alternative — copying the lane's whole
    ``max_len`` cache row (the physical beam/spec fork).  Value = the
    copy/fork speedup; extras carry both absolute latencies and the
    byte ratio (block vs max_len row)."""
    def run(p_len=64, warm_steps=4, iters=16):
        import jax as _jax
        import jax.numpy as _jnp
        import numpy as np
        from distkeras_tpu.serving import ContinuousBatcher, PagedBatcher

        cfg = _cfg()
        params = _params()
        block = _paged_block(cfg.max_len)
        mb = cfg.max_len // block
        rng = np.random.default_rng(0)
        prompt = rng.integers(0, cfg.vocab_size,
                              (p_len,)).astype(np.int32)
        eng = PagedBatcher(params, cfg, lanes=2, block=block,
                           n_blocks=2 * mb + 2,
                           prompt_buckets=(p_len - 1,))
        src = eng.submit(prompt, warm_steps + 2)
        for _ in range(warm_steps):
            eng.step()
        alt = int(eng._lane_state[src].tokens[-1])
        f = eng.fork(src, token=alt)            # warm the fork path
        _jax.block_until_ready(eng.cache["k"])
        eng._finish(eng._lane_state[f].request_id, [], "cancelled", 1)
        eng._vacate(f)
        t0 = time.perf_counter()
        for _ in range(iters):
            f = eng.fork(src, token=alt)
            _jax.block_until_ready(eng.cache["k"])
            eng._finish(eng._lane_state[f].request_id, [], "cancelled",
                        1)
            eng._vacate(f)
        fork_s = (time.perf_counter() - t0) / iters

        # The monolithic alternative: physically copy the source
        # lane's whole cache row into the destination lane.
        mono = ContinuousBatcher(params, cfg, lanes=2,
                                 prompt_buckets=(p_len - 1,))
        lane = mono.submit(prompt, warm_steps + 2)
        for _ in range(warm_steps):
            mono.step()

        def copy_lane(cache, src_lane, dst_lane):
            row = _jax.tree.map(
                lambda a: _jax.lax.dynamic_slice_in_dim(
                    a, src_lane, 1, axis=1), cache)
            return _jax.tree.map(
                lambda a, r: _jax.lax.dynamic_update_slice_in_dim(
                    a, r, dst_lane, axis=1), cache, row)
        cp = _jax.jit(copy_lane, donate_argnums=0)
        mono.cache = cp(mono.cache, _jnp.int32(lane), _jnp.int32(1))
        _jax.block_until_ready(mono.cache["k"])
        t0 = time.perf_counter()
        for _ in range(iters):
            mono.cache = cp(mono.cache, _jnp.int32(lane),
                            _jnp.int32(1))
        _jax.block_until_ready(mono.cache["k"])
        copy_s = (time.perf_counter() - t0) / iters
        row_bytes = (2 * cfg.n_layers * cfg.max_len * cfg.kv_heads
                     * cfg.head_dim * 2)
        extras = {
            "fork_ms": round(fork_s * 1e3, 3),
            "cache_copy_ms": round(copy_s * 1e3, 3),
            "block": block,
            "bytes_ratio": round(cfg.max_len / block, 1),
            "lane_cache_mb": round(row_bytes / 1e6, 2),
        }
        return copy_s / fork_s, fork_s, 0.0, extras
    return run


def bench_router_scale(n_replicas):
    """Fleet throughput vs replica count (round 13): ``n_replicas``
    in-process engine replicas at EQUAL per-replica config, each
    stepping on its own driver thread (the fleet shape — XLA releases
    the GIL during execution, so replicas decode concurrently), behind
    the Router's enqueue/poll flow under open-loop Poisson load that
    scales with the replica count.  Value = aggregate tokens/s;
    extras carry achieved rps and TTFT/TPOT p50/p99 read from the obs
    ``serving.ttft_s``/``serving.tpot_s`` histograms (bucket-
    interpolated; the row needs an active obs session for them, which
    main() provides).  Compare router_scale_{1,2,4}: the ≥3x-at-4
    claim is the acceptance bar on hardware where replicas own their
    compute (separate chips/hosts); one shared CPU undercounts it by
    whatever the replicas contend for."""
    def run(n_req=48, p_len=64, new=128, lanes=4,
            per_replica_rps=8.0):
        import numpy as np

        from distkeras_tpu import obs
        from distkeras_tpu.obs.metrics import percentile_from_buckets
        from distkeras_tpu.serving import (ContinuousBatcher,
                                           InProcessReplica, QueueFull,
                                           Router)

        cfg = _cfg()
        params = _params()
        rng = np.random.default_rng(0)
        offered = per_replica_rps * n_replicas
        arrivals = np.cumsum(rng.exponential(1.0 / offered, n_req))
        prompts = rng.integers(0, cfg.vocab_size,
                               (n_req, p_len)).astype(np.int32)
        engines = [ContinuousBatcher(params, cfg, lanes=lanes,
                                     max_queue=n_req,
                                     prompt_buckets=(p_len - 1,))
                   for _ in range(n_replicas)]
        replicas = [InProcessReplica(f"r{i}", e)
                    for i, e in enumerate(engines)]
        # round_robin: the scale row measures capacity, not locality —
        # uniform spread isolates the replica-count axis.
        router = Router(replicas, policy="round_robin")
        for r in replicas:
            r.start()
        try:
            # Warm every replica's programs outside the timed region.
            warm = [router.enqueue(prompts[i % n_req], new)
                    for i in range(n_replicas)]
            while any(router.poll(w) is None for w in warm):
                router.pump()
                time.sleep(0.002)
            for w in warm:
                router.take(w)
            done_t = np.full(n_req, np.nan)
            rid_of: dict[int, int] = {}
            next_req = 0
            t0 = time.perf_counter()
            while np.isnan(done_t).any():
                now = time.perf_counter() - t0
                while next_req < n_req and arrivals[next_req] <= now:
                    try:
                        rid_of[next_req] = router.enqueue(
                            prompts[next_req], new)
                    except QueueFull:
                        break              # retry at the next tick
                    next_req += 1
                router.pump()
                now = time.perf_counter() - t0
                for req, rid in rid_of.items():
                    if np.isnan(done_t[req]) \
                            and router.poll(rid) is not None:
                        done_t[req] = now
                time.sleep(0.0005)
            results = router.results()
        finally:
            for r in replicas:
                r.stop()
        ok = sum(r.ok for r in results.values())
        makespan = float(np.nanmax(done_t))
        total_tokens = sum(len(r.generated)
                           for r in results.values())
        extras = {
            "replicas": n_replicas, "lanes_per_replica": lanes,
            "offered_rps": offered, "n_requests": n_req,
            "prompt_len": p_len, "new_tokens": new, "ok": ok,
            "achieved_rps": round(n_req / makespan, 2),
        }
        sess = obs.active()
        if sess is not None:
            snap = sess.registry.snapshot()
            for name, key in (("serving.ttft_s", "ttft"),
                              ("serving.tpot_s", "tpot")):
                series = [s for s in snap.get(name, {}).get(
                    "series", []) if s.get("count")]
                if series:
                    s = series[0]
                    extras[f"{key}_p50_ms"] = round(
                        percentile_from_buckets(s, 0.50) * 1e3, 1)
                    extras[f"{key}_p99_ms"] = round(
                        percentile_from_buckets(s, 0.99) * 1e3, 1)
        return total_tokens / makespan, makespan / max(total_tokens,
                                                       1), 0.0, extras
    return run


def bench_engine_sharded(tp):
    """Pod-sharded serving (round 14): ONE ContinuousBatcher replica
    spans a ``model=tp`` mesh over the host's devices under
    ``serving_plan()`` — params TP-sharded, KV heads sharded, GSPMD
    per-token collectives compiled in.  The row reports what the
    sharding BUYS and COSTS: per-device param+KV bytes vs the solo
    engine (read from addressable shards — the ~tp× memory claim) and
    TTFT/TPOT vs the solo engine on the identical workload (the
    per-token collective cost; on one CPU host the collectives are
    memcpys, so the latency column is declared-level until a hardware
    session — ROADMAP item 5).  Value = sharded tokens/s."""
    def run(n_req=8, p_len=64, new=64, lanes=4):
        import jax
        import numpy as np

        from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh
        from distkeras_tpu.parallel.sharding import serving_plan
        from distkeras_tpu.serving import ContinuousBatcher

        cfg = _cfg()
        params = _params()
        n_dev = len(jax.devices())
        if n_dev % tp:
            raise RuntimeError(
                f"engine_sharded_tp{tp} needs a device count "
                f"divisible by {tp}, have {n_dev}")
        mesh = make_mesh(MeshSpec(data=n_dev // tp, model=tp))
        rng = np.random.default_rng(0)
        prompts = rng.integers(0, cfg.vocab_size,
                               (n_req, p_len)).astype(np.int32)

        def serve(eng):
            """Serve the full request set; returns (wall, ttft list,
            tpot list) measured at step boundaries."""
            done, nxt, lane_req = 0, 0, {}
            sub_t = {}
            first_t = np.full(n_req, np.nan)
            done_t = np.full(n_req, np.nan)
            toks = np.zeros(n_req, np.int64)
            t0 = time.perf_counter()
            while done < n_req:
                while nxt < n_req and eng.free_lanes():
                    lane = eng.submit(prompts[nxt], new)
                    if lane is None:
                        break
                    lane_req[lane] = nxt
                    sub_t[nxt] = time.perf_counter() - t0
                    nxt += 1
                out = eng.step()
                now = time.perf_counter() - t0
                for lane, emitted in out.items():
                    r = lane_req[lane]
                    if emitted and np.isnan(first_t[r]):
                        first_t[r] = now
                    toks[r] += len(emitted)
                for lane in [l for l in lane_req
                             if l not in eng.running()]:
                    r = lane_req.pop(lane)
                    eng.drain(lane)
                    done_t[r] = now
                    done += 1
            sub = np.asarray([sub_t[i] for i in range(n_req)])
            ttft = first_t - sub
            tpot = (done_t - first_t) / np.maximum(toks - 1, 1)
            return time.perf_counter() - t0, ttft, tpot

        kw = dict(lanes=lanes, prompt_buckets=(p_len - 1,))
        sharded = ContinuousBatcher(params, cfg, plan=serving_plan(),
                                    mesh=mesh, **kw)
        serve(sharded)                         # warm
        dt_sh, ttft_sh, tpot_sh = serve(sharded)
        fp_sh = sharded.memory_footprint()
        solo = ContinuousBatcher(params, cfg, **kw)
        serve(solo)                            # warm
        dt_solo, ttft_solo, tpot_solo = serve(solo)
        fp_solo = solo.memory_footprint()
        total = n_req * new
        # 4 decimals: the contract tests drive this through tiny
        # configs whose per-device KV is ~0.006 MB — 2 decimals would
        # round the tp× ratio away.
        mb = lambda b: round(b / 1e6, 4)
        pct = lambda a, q: round(float(np.percentile(a, q)) * 1e3, 1)
        extras = {
            "tp": tp, "lanes": lanes, "n_requests": n_req,
            "prompt_len": p_len, "new_tokens": new,
            "param_mb_per_device": mb(fp_sh["param_bytes_per_device"]),
            "kv_mb_per_device": mb(fp_sh["kv_bytes_per_device"]),
            "solo_param_mb_per_device":
                mb(fp_solo["param_bytes_per_device"]),
            "solo_kv_mb_per_device":
                mb(fp_solo["kv_bytes_per_device"]),
            "bytes_reduction": round(
                (fp_solo["param_bytes_per_device"]
                 + fp_solo["kv_bytes_per_device"])
                / max(fp_sh["param_bytes_per_device"]
                      + fp_sh["kv_bytes_per_device"], 1), 2),
            "ttft_p50_ms": pct(ttft_sh, 50),
            "tpot_p50_ms": pct(tpot_sh, 50),
            "solo_ttft_p50_ms": pct(ttft_solo, 50),
            "solo_tpot_p50_ms": pct(tpot_solo, 50),
            "solo_tok_s": round(total / dt_solo, 1),
        }
        return total / dt_sh, dt_sh / total, 0.0, extras
    return run


def bench_router_affinity():
    """Cache-aware routing vs round-robin on the SAME trace (round
    13): 2 paged replicas, requests drawn from a handful of shared
    stems in shuffled order.  The affinity policy sends every
    same-stem request to the replica whose blocks are already
    resident (stem_hit_blocks counts the re-prefill work avoided);
    round-robin scatters them, so each replica pays its own prefill.
    Value = affinity-policy tokens/s; extras carry both policies'
    stem-hit totals and throughput — the routing-policy win isolated
    from everything else (same engines-per-run, same request order,
    single-threaded stepping so hits are deterministic)."""
    def run(n_stems=4, reqs_per_stem=8, tail_len=16, new=32, lanes=4,
            n_replicas=2):
        import numpy as np

        from distkeras_tpu.serving import (InProcessReplica,
                                           PagedBatcher, Router)

        cfg = _cfg()
        params = _params()
        block = _paged_block(cfg.max_len)
        mb = cfg.max_len // block
        stem_len = max(block, (cfg.max_len // 2 // block) * block)
        n_req = n_stems * reqs_per_stem
        rng = np.random.default_rng(0)
        stems = rng.integers(0, cfg.vocab_size,
                             (n_stems, stem_len)).astype(np.int32)
        tails = rng.integers(0, cfg.vocab_size,
                             (n_req, tail_len)).astype(np.int32)
        order = rng.permutation(n_req)
        prompts = [np.concatenate([stems[i % n_stems], tails[i]])
                   for i in order]

        def serve(policy):
            engines = [PagedBatcher(
                params, cfg, lanes=lanes, block=block,
                n_blocks=lanes * mb + 1, max_queue=n_req,
                prompt_buckets=(tail_len, stem_len + tail_len))
                for _ in range(n_replicas)]
            router = Router([InProcessReplica(f"r{i}", e)
                             for i, e in enumerate(engines)],
                            policy=policy)
            warm = router.enqueue(prompts[0], new)
            while router.poll(warm) is None:
                router.step()
            router.take(warm)
            hits0 = sum(e.stem_hit_blocks for e in engines)
            t0 = time.perf_counter()
            rids = [router.enqueue(p, new) for p in prompts]
            while any(router.poll(r) is None for r in rids):
                router.step()
            dt = time.perf_counter() - t0
            assert all(router.take(r).ok for r in rids)
            hits = sum(e.stem_hit_blocks for e in engines) - hits0
            return dt, hits

        dt_aff, hits_aff = serve("affinity")
        dt_rr, hits_rr = serve("round_robin")
        total = n_req * new
        extras = {
            "replicas": n_replicas, "n_stems": n_stems,
            "n_requests": n_req, "stem_len": int(stem_len),
            "tail_len": tail_len, "new_tokens": new, "block": block,
            "affinity_hit_blocks": int(hits_aff),
            "round_robin_hit_blocks": int(hits_rr),
            "round_robin_tok_s": round(total / dt_rr, 1),
            "affinity_speedup": round(dt_rr / dt_aff, 3),
        }
        return total / dt_aff, dt_aff / total, 0.0, extras
    return run


def bench_router_disagg():
    """Disaggregated prefill/decode fleet vs the co-resident baseline
    (round 17): the SAME 2-replica paged fleet serves the SAME trace —
    a storm of multi-block-prompt, short-decode requests pounding the
    fleet while a handful of long-decode "victim" requests stream
    tokens through ``Router.stream()`` — once with role labels
    (``prefill``-specialized replica builds each storm prompt's KV
    blocks, ships them, the ``decode`` replica adopts by page-table
    splice) and once role-less (every replica pays its own prefills
    between its own decode steps).  The claim under test: moving
    prefill compute OFF the decode replica keeps the victims' decode
    TPOT flat through the storm.  TPOT here is the ROUTER-LEVEL
    inter-token gap observed by the streaming caller (the user-visible
    latency), p50/p99 pooled across every victim gap; value = the
    baseline-over-disagg p99 ratio (the immunity).  Storm prompts
    share a first block across ``n_stems`` stems with a unique second
    block, so every request takes the ship->adopt hop (the unique
    block defeats the warm-skip residency gate) while repeated stems
    hash-hit on adoption — extras carry the transfer bytes and the
    adoption-hit rate read from the obs counters (needs the active
    obs session main() provides)."""
    def run(n_storm=1000, n_victims=8, storm_new=2, victim_new=96,
            lanes=4, n_stems=None, window=8):
        import threading

        import numpy as np

        from distkeras_tpu import obs
        from distkeras_tpu.serving import (InProcessReplica,
                                           PagedBatcher, QueueFull,
                                           Router)

        cfg = _cfg()
        params = _params()
        block = _paged_block(cfg.max_len)
        mb = cfg.max_len // block
        rng = np.random.default_rng(0)
        if n_stems is None:
            n_stems = max(1, n_storm // 8)
        # stem + unique block + a ONE-TOKEN tail: the disagg planner
        # gates on the full-block stems of prompt[:-1], so the tail
        # makes the unique block count as a stem — every request
        # takes the hop (never warm-skipped), while the shared first
        # block hash-hits on adoption once its stem shipped before.
        stems = rng.integers(0, cfg.vocab_size,
                             (n_stems, block)).astype(np.int32)
        uniq = rng.integers(0, cfg.vocab_size,
                            (n_storm, block + 1)).astype(np.int32)
        storm = [np.concatenate([stems[i % n_stems], uniq[i]])
                 for i in range(n_storm)]
        v_len = block - 1        # sub-block: victims never take the hop
        vics = rng.integers(0, cfg.vocab_size,
                            (n_victims, v_len)).astype(np.int32)
        warm_storm = rng.integers(0, cfg.vocab_size,
                                  (2 * block + 1,)).astype(np.int32)

        def counters():
            sess = obs.active()
            if sess is None:
                return None
            snap = sess.registry.snapshot()

            def val(name):
                return sum(s.get("value", 0) or 0
                           for s in snap.get(name, {}).get("series", []))
            return {n: val(n) for n in (
                "router.transfer_bytes", "router.disagg_requests",
                "router.disagg_warm_skips", "router.disagg_fallbacks",
                "serving.disagg.blocks_in", "serving.disagg.adopt_hits")}

        def serve(disagg):
            roles = ("prefill", "decode") if disagg else (None, None)
            engines = [PagedBatcher(
                params, cfg, lanes=lanes, block=block,
                n_blocks=4 * lanes * mb + 2 * n_stems + 4,
                max_queue=n_storm + n_victims,
                prompt_buckets=(v_len, 2 * block + 1)) for _ in roles]
            # Warm every engine's admission/decode programs and the
            # export/import hop OUTSIDE the timed region (non-elastic
            # paged engines compile lazily).
            for e in engines:
                for p, new in ((warm_storm, storm_new),
                               (vics[0], victim_new)):
                    rid = e.enqueue(p, new)
                    while e.poll(rid) is None:
                        e.step()
                    e.take(rid)
            if disagg:
                ship = engines[0].export_blocks(warm_storm)
                imported = engines[1].import_blocks(ship)
                rid = engines[1].enqueue(warm_storm, storm_new)
                while engines[1].poll(rid) is None:
                    engines[1].step()
                engines[1].take(rid)
                engines[1].unpin_prefix(imported["prefix_id"])
            replicas = [InProcessReplica(f"{r or 'gen'}{i}", e, role=r)
                        for i, (r, e) in enumerate(zip(roles, engines))]
            router = Router(replicas, policy="affinity",
                            residency_interval=0.05)
            for r in replicas:
                r.start()
            try:
                router.pump()   # residency refresh: tables learn the
                # block geometry the disagg planner keys on.
                gaps: list[float] = []
                firsts: list[float] = []

                def stream_victim(i):
                    t0 = time.perf_counter()
                    rid = router.enqueue(vics[i], victim_new)
                    last = None
                    mine = []
                    for _tok in router.stream(rid):
                        now = time.perf_counter()
                        if last is None:
                            firsts.append(now - t0)
                        else:
                            mine.append(now - last)
                        last = now
                    gaps.extend(mine)

                threads = [threading.Thread(target=stream_victim,
                                            args=(i,), daemon=True)
                           for i in range(n_victims)]
                t0 = time.perf_counter()
                for t in threads:
                    t.start()
                # The storm: open loop with a bounded in-flight window
                # (shipped blocks stay pinned until their request
                # decodes — an unbounded burst would just trade hop
                # fallbacks for allocator backpressure).
                rids: dict[int, int] = {}
                inflight: set[int] = set()
                nxt = done = 0
                while done < n_storm:
                    while nxt < n_storm and len(inflight) < window:
                        try:
                            rids[nxt] = router.enqueue(storm[nxt],
                                                       storm_new)
                        except QueueFull:
                            break
                        inflight.add(nxt)
                        nxt += 1
                    router.pump()
                    for i in list(inflight):
                        if router.poll(rids[i]) is not None:
                            inflight.discard(i)
                            done += 1
                    time.sleep(0.0005)
                dt = time.perf_counter() - t0
                for t in threads:
                    t.join()
                ok = sum(router.take(r).ok for r in rids.values())
            finally:
                for r in replicas:
                    r.stop()
            return gaps, firsts, dt, ok

        c0 = counters()
        gaps_d, firsts_d, dt_d, ok_d = serve(True)
        c1 = counters()
        gaps_b, firsts_b, dt_b, ok_b = serve(False)

        pct = lambda a, q: round(
            float(np.percentile(a or [0.0], q)) * 1e3, 2)
        extras = {
            "n_storm": n_storm, "n_victims": n_victims,
            "storm_new": storm_new, "victim_new": victim_new,
            "lanes": lanes, "block": block, "n_stems": n_stems,
            "storm_ok": ok_d, "baseline_storm_ok": ok_b,
            "storm_rps": round(n_storm / dt_d, 1),
            "baseline_storm_rps": round(n_storm / dt_b, 1),
            "tpot_p50_ms": pct(gaps_d, 50),
            "tpot_p99_ms": pct(gaps_d, 99),
            "baseline_tpot_p50_ms": pct(gaps_b, 50),
            "baseline_tpot_p99_ms": pct(gaps_b, 99),
            "ttft_p50_ms": pct(firsts_d, 50),
            "baseline_ttft_p50_ms": pct(firsts_b, 50),
        }
        if c0 is not None:
            d = {k: c1[k] - c0[k] for k in c0}
            blocks_in = d["serving.disagg.blocks_in"]
            extras.update({
                "disagg_requests": int(d["router.disagg_requests"]),
                "warm_skips": int(d["router.disagg_warm_skips"]),
                "fallbacks": int(d["router.disagg_fallbacks"]),
                "transfer_mb": round(
                    d["router.transfer_bytes"] / 1e6, 3),
                "blocks_shipped": int(blocks_in),
                "adoption_hit_rate": round(
                    d["serving.disagg.adopt_hits"]
                    / max(blocks_in, 1), 3),
            })
        p99_d = float(np.percentile(gaps_d or [1e-9], 99))
        p99_b = float(np.percentile(gaps_b or [1e-9], 99))
        return p99_b / max(p99_d, 1e-9), p99_d, 0.0, extras
    return run


def _autoscale_leg(trace, engines, n_start, policy, *, ticks,
                   steps_per_tick, stem_len, tail_len, vocab):
    """One policy leg of the autoscale harness: replay ``trace`` over
    a fleet built from ``engines`` under a VIRTUAL clock — each tick
    injects that tick's arrivals, steps every serving replica
    ``steps_per_tick`` decode steps (service capacity is steps, not
    wall time, so the whole leg is deterministic), and, when
    ``policy`` is an Autoscaler factory, runs one scaling decision.
    ``n_start`` engines begin in the route table; the rest are parked
    in the warm pool (idle = not burning replica-ticks).  Returns
    ``(ttft_ticks, replica_ticks, decisions, lost)`` where
    ``ttft_ticks[(tick, index)]`` is first-token latency in ticks for
    every completed arrival."""
    import numpy as np

    from distkeras_tpu.serving import (InProcessReplica, QueueFull,
                                       Router, WarmPool)

    vclock = [0.0]
    replicas = [InProcessReplica(f"r{i}", e)
                for i, e in enumerate(engines)]
    router = Router(replicas[:n_start], clock=lambda: vclock[0])
    scaler = None
    if policy is not None:
        pool = WarmPool(replicas[n_start:])
        scaler = policy(router, pool)
    arrival: dict = {}     # key -> arrival tick
    first: dict = {}       # key -> first-token tick
    rid_of: dict = {}      # key -> fleet request id
    retry: list = []       # QueueFull'd (key, prompt, max_new)
    replica_ticks = 0

    def inject(tick, items):
        still = []
        for key, prompt, max_new in items:
            try:
                rid_of[key] = router.enqueue(prompt, max_new)
            except QueueFull:
                still.append((key, prompt, max_new))
        del tick
        return still

    def observe_first(tick):
        # First-token detection off the live transcripts (the same
        # read Router.stream relays; chaos_suite reads the same
        # private tables for its timeline assertions).
        for key, rid in rid_of.items():
            if key in first:
                continue
            res = router.poll(rid)
            req = router._requests.get(rid)
            part = None
            if res is not None:
                part = res
            elif req is not None and req.replica is not None:
                m = router._members.get(req.replica)
                if m is not None and req.replica_rid is not None:
                    part = m.replica.partial(req.replica_rid)
            if part is not None and \
                    np.asarray(part.tokens).size > int(part.prompt_len):
                first[key] = tick

    t = 0
    while True:
        draining = t >= ticks
        if not draining:
            vclock[0] = float(t)
            reqs = trace.replay(t)
            items = [((r.tick, r.index),
                      trace.prompt(r, stem_len=stem_len,
                                   tail_len=tail_len, vocab=vocab),
                      r.max_new) for r in reqs]
            for key, _p, _n in items:
                arrival[key] = t
            retry = inject(t, retry + items)
        else:
            vclock[0] = float(t)
            retry = inject(t, retry)
        replica_ticks += len(router.replicas_up())
        for _ in range(steps_per_tick):
            router.step()
        observe_first(t)
        if scaler is not None:
            scaler.tick()
        if draining and not retry \
                and all(router.poll(r) is not None
                        for r in rid_of.values()):
            break
        t += 1
        if t > ticks + 400:
            break  # wedged leg: report what completed as lost
    results = {k: router.poll(rid) for k, rid in rid_of.items()}
    lost = [k for k in arrival
            if k not in rid_of or results.get(k) is None
            or results[k].status != "ok"]
    ttft = {k: first[k] - arrival[k] for k in first}
    decisions = scaler.decisions if scaler is not None else []
    return ttft, replica_ticks, decisions, lost


def bench_autoscale(shape):
    """Policy-vs-policy autoscaling rows (round 19): the SAME
    deterministic :class:`TraceReplay` trace replayed over three
    fleet policies — static at the MINIMUM replica count, static at
    the MAXIMUM, and autoscaled between them by the
    :class:`Autoscaler` (warm-pool scale-up, drain-and-reroute
    scale-down) — under a virtual clock where service capacity is
    decode steps per tick, so every leg (arrivals, queue build-up,
    scaling decisions) is bit-reproducible.  Value = static-min p99
    TTFT over autoscaled p99 TTFT through the hot window (>1 means
    the autoscaler beat the small fleet); extras carry the
    replica-ticks each policy burned (autoscaled must undercut
    static-max — elasticity's cost claim), the scaling-decision
    timeline, and a repeat-run determinism check over the decision
    audit trail."""
    def run(ticks=36, min_replicas=1, max_replicas=4, lanes=2,
            steps_per_tick=4, seed=0, base_rate=2.0, spike_rate=14.0,
            spike_at=10, spike_len=8, peak_rate=10.0, period=32,
            stem_len=8, tail_len=2, max_queue=256):
        import numpy as np

        from distkeras_tpu import obs
        from distkeras_tpu.serving import (AutoscalePolicy, Autoscaler,
                                           ContinuousBatcher,
                                           TraceReplay)

        cfg = _cfg()
        params = _params()

        def trace():
            return TraceReplay(shape, seed=seed, base_rate=base_rate,
                               peak_rate=peak_rate, period=period,
                               spike_at=spike_at, spike_len=spike_len,
                               spike_rate=spike_rate, stems=4,
                               max_new=(3, 5))

        def engines(n):
            return [ContinuousBatcher(
                params, cfg, lanes=lanes, max_queue=max_queue,
                prompt_buckets=(stem_len + tail_len - 1,))
                for _ in range(n)]

        def scaler_factory(router, pool):
            sc = Autoscaler(router, pool, policy=AutoscalePolicy(
                min_replicas=min_replicas, max_replicas=max_replicas,
                up_threshold=0.9, down_threshold=0.3, up_after=1,
                down_after=3, cooldown_ticks=1))
            return sc

        kw = dict(ticks=ticks, steps_per_tick=steps_per_tick,
                  stem_len=stem_len, tail_len=tail_len,
                  vocab=cfg.vocab_size)
        legs = {}
        legs["static_min"] = _autoscale_leg(
            trace(), engines(min_replicas), min_replicas, None, **kw)
        legs["static_max"] = _autoscale_leg(
            trace(), engines(max_replicas), max_replicas, None, **kw)
        legs["autoscaled"] = _autoscale_leg(
            trace(), engines(max_replicas), min_replicas,
            scaler_factory, **kw)
        repeat = _autoscale_leg(
            trace(), engines(max_replicas), min_replicas,
            scaler_factory, **kw)

        if shape == "spike":
            hot = range(spike_at, spike_at + spike_len)
        else:
            hot = range(period // 4, (3 * period) // 4)
        hot = set(hot)

        def hot_p99(leg):
            ttft = [v for (tick, _i), v in leg[0].items()
                    if tick in hot]
            return float(np.percentile(ttft, 99)) if ttft else 0.0

        timeline = [(d["tick"], d["action"], d["replica"])
                    for d in legs["autoscaled"][2]]
        timeline2 = [(d["tick"], d["action"], d["replica"])
                     for d in repeat[2]]
        extras = {
            "shape": shape, "ticks": ticks, "seed": seed,
            "min_replicas": min_replicas,
            "max_replicas": max_replicas,
            "deterministic_timeline": timeline == timeline2,
            "scaling_changes": sum(1 for _, a, _r in timeline
                                   if a in ("up", "down")),
        }
        for name, leg in legs.items():
            extras[f"{name}_ttft_p99_ticks"] = round(hot_p99(leg), 2)
            extras[f"{name}_replica_ticks"] = leg[1]
            extras[f"{name}_lost"] = len(leg[3])
        sess = obs.active()
        if sess is not None:
            snap = sess.registry.snapshot()

            def total(name):
                return int(sum(s["value"] for s in
                               snap.get(name, {}).get("series", [])))
            extras["scale_ups"] = total("autoscale.scale_ups")
            extras["scale_downs"] = total("autoscale.scale_downs")
            extras["offered_requests"] = total("traffic.requests")
        p99_auto = extras["autoscaled_ttft_p99_ticks"]
        p99_min = extras["static_min_ttft_p99_ticks"]
        return (p99_min / max(p99_auto, 1e-9), p99_auto, 0.0, extras)
    return run


def bench_canary_rollout():
    """Live weight push under load (round 20): two hot_swap engines
    behind a Router serve a wave of in-flight requests while a
    :class:`CanaryController` promotes a freshly published snapshot
    mid-stream.  Value = victim-request TPOT p99 with the mid-stream
    push over the no-push baseline's (≈1.0 means a live swap is
    invisible to in-flight decodes — the zero-recompile claim measured
    from the victim's seat).  Extras carry the rollout wall-clock
    (canary swap → drift probe → fleet swap → epoch bump), both TPOT
    p99s, and a per-version token-determinism flag: each leg runs
    twice and must produce bit-identical token streams (the swap lands
    between the same two steps, so same params ⇒ same tokens)."""
    def run(n_req=6, max_new=16, push_after=3, lanes=4, seed=0):
        import time

        import jax
        import numpy as np

        from distkeras_tpu.models import transformer as tfm
        from distkeras_tpu.serving import (ContinuousBatcher,
                                           InProcessReplica, Router)
        from distkeras_tpu.serving.canary import CanaryController

        cfg = _cfg()
        params = _params(cfg=cfg)
        v1 = jax.tree.map(np.asarray,
                          tfm.init_params(jax.random.key(1), cfg))
        template = jax.eval_shape(
            lambda: tfm.init_params(jax.random.key(0), cfg))
        rng = np.random.default_rng(seed)
        prompts = [rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
                   for _ in range(n_req)]

        def leg(push):
            engines = [ContinuousBatcher(params, cfg, lanes=lanes,
                                         hot_swap=True)
                       for _ in range(2)]
            router = Router([InProcessReplica(f"r{i}", e)
                             for i, e in enumerate(engines)])
            ctl = CanaryController(router, None, cfg, template)
            rids = [router.enqueue(p, max_new) for p in prompts]
            gaps, rollout_ms, steps = [], 0.0, 0
            while any(router.poll(r) is None for r in rids):
                if push and steps == push_after:
                    t0 = time.perf_counter()
                    rec = ctl.rollout(1, v1)
                    rollout_ms = (time.perf_counter() - t0) * 1e3
                    assert rec["action"] == "promote", rec
                t0 = time.perf_counter()
                router.step()
                gaps.append(time.perf_counter() - t0)
                steps += 1
            toks = tuple(tuple(int(t) for t in router.take(r).tokens)
                         for r in rids)
            return gaps, rollout_ms, toks

        base_gaps, _, base_toks = leg(push=False)
        _, _, base_toks2 = leg(push=False)
        push_gaps, rollout_ms, push_toks = leg(push=True)
        _, _, push_toks2 = leg(push=True)

        base_p99 = float(np.percentile(base_gaps, 99)) * 1e3
        push_p99 = float(np.percentile(push_gaps, 99)) * 1e3
        deterministic = (base_toks == base_toks2
                         and push_toks == push_toks2)
        extras = {
            "rollout_wallclock_ms": round(rollout_ms, 3),
            "tpot_p99_push_ms": round(push_p99, 3),
            "tpot_p99_baseline_ms": round(base_p99, 3),
            "tokens_deterministic_per_version": deterministic,
            "tokens_changed_at_push": push_toks != base_toks,
            "n_req": n_req, "push_after_steps": push_after,
        }
        ratio = push_p99 / max(base_p99, 1e-9)
        return (ratio, rollout_ms / 1e3, 0.0, extras)
    return run


BENCHES = {
    "decode_greedy_b1": (bench_greedy(1), "tokens/sec/chip"),
    "decode_greedy_b8": (bench_greedy(8), "tokens/sec/chip"),
    "decode_greedy_b64": (bench_greedy(64), "tokens/sec/chip"),
    "decode_sampled_b1": (bench_sampled(1), "tokens/sec/chip"),
    "decode_sampled_b8": (bench_sampled(8), "tokens/sec/chip"),
    "decode_sampled_b64": (bench_sampled(64), "tokens/sec/chip"),
    "decode_int8_b1": (bench_int8(1), "tokens/sec/chip"),
    "decode_int8_b8": (bench_int8(8), "tokens/sec/chip"),
    "decode_int8_b64": (bench_int8(64), "tokens/sec/chip"),
    "prefix_cache_ttft": (bench_prefix_ttft(), "x speedup"),
    "engine_throughput": (bench_engine(), "tokens/sec/chip"),
    "engine_throughput_kvint8": (bench_engine(kv_int8=True),
                                 "tokens/sec/chip"),
    "decode_kv_int8_b8": (bench_kv_int8(8), "tokens/sec/chip"),
    "decode_kv_int8_b64": (bench_kv_int8(64), "tokens/sec/chip"),
    "decode_gqa4_b64": (bench_gqa4(64), "tokens/sec/chip"),
    "decode_rolling_window": (bench_rolling_window(), "tokens/sec/chip"),
    "decode_rolling_window_kvint8": (bench_rolling_window_kvint8(),
                                     "tokens/sec/chip"),
    "beam4": (bench_beam4(), "tokens/sec/chip"),
    "beam4_windowed": (bench_beam4(window=256), "tokens/sec/chip"),
    "beam4_windowed_physical": (bench_beam4(window=256,
                                            beam_impl="physical"),
                                "tokens/sec/chip"),
    "decode_speculative_int8draft": (bench_speculative_int8draft(),
                                     "tokens/sec/chip"),
    "engine_speculative": (bench_engine_speculative(),
                           "tokens/sec/chip"),
    "decode_moe_b8": (bench_moe(8), "tokens/sec/chip"),
    "decode_moe_b64": (bench_moe(64), "tokens/sec/chip"),
    "decode_moe_top2_b8": (bench_moe(8, top_k=2), "tokens/sec/chip"),
    "lora_merged_serve": (bench_lora_merged_serve(), "tokens/sec/chip"),
    # Engine-under-load grid: 3 offered loads x the default 8 lanes,
    # plus the lane-count sweep at the middle load.  Loads bracket the
    # theoretical ceiling, computed chip-level: the engine's aggregate
    # decode rate at 8 full lanes is the measured b8 rate (~8.6k tok/s
    # across ALL lanes), so 128-token requests cap at ~8600/128 ≈ 67
    # req/s minus engine/admission overhead — 8 rps is light, 32
    # moderate, 64 probes saturation (p99 TTFT blows up first).  The
    # ceiling scales with the aggregate tok/s at that lane count, not
    # per-lane: re-derive 4/16-lane loads from the matching batch row.
    "engine_load_8l_low": (bench_engine_load(8, 8.0), "tokens/sec/chip"),
    "engine_load_8l_mid": (bench_engine_load(8, 32.0), "tokens/sec/chip"),
    "engine_load_8l_high": (bench_engine_load(8, 64.0), "tokens/sec/chip"),
    "engine_load_4l_mid": (bench_engine_load(4, 32.0), "tokens/sec/chip"),
    "engine_load_16l_mid": (bench_engine_load(16, 32.0),
                            "tokens/sec/chip"),
    # Round-10 rows.  Elastic + speculative load sweeps (the PR-5
    # follow-up), each row shipping its obs snapshot:
    "engine_load_elastic_mid": (bench_engine_load_elastic((4, 8, 16),
                                                          32.0),
                                "tokens/sec/chip"),
    "engine_load_elastic_high": (bench_engine_load_elastic((4, 8, 16),
                                                           64.0),
                                 "tokens/sec/chip"),
    "engine_load_spec_mid": (bench_engine_load_spec(8, 32.0),
                             "tokens/sec/chip"),
    # Chunked-vs-monolithic long-prompt admission (inter-token gap):
    "engine_longprompt_monolithic": (bench_longprompt(None),
                                     "tokens/sec/chip"),
    "engine_longprompt_chunked": (bench_longprompt(128),
                                  "tokens/sec/chip"),
    # Multi-prefix KV pool reuse at 1/4/16 distinct prefixes:
    "engine_prefix_pool_1": (bench_prefix_reuse(1), "tokens/sec/chip"),
    "engine_prefix_pool_4": (bench_prefix_reuse(4), "tokens/sec/chip"),
    "engine_prefix_pool_16": (bench_prefix_reuse(16),
                              "tokens/sec/chip"),
    # Round-12 paged-KV rows: lane count at fixed slab bytes, shared
    # stems vs re-prefill, and the CoW fork vs a physical cache copy.
    "engine_paged_lanes_at_hbm": (bench_paged_lanes(4),
                                  "tokens/sec/chip"),
    "engine_paged_shared_stem": (bench_paged_shared_stem(16),
                                 "tokens/sec/chip"),
    "engine_paged_cow_fork": (bench_paged_cow_fork(), "x speedup"),
    # Round-13 fleet rows: throughput/latency vs replica count through
    # the Router (equal per-replica config, per-replica step threads),
    # and the cache-aware policy vs round-robin on one trace.
    "router_scale_1": (bench_router_scale(1), "tokens/sec"),
    "router_scale_2": (bench_router_scale(2), "tokens/sec"),
    "router_scale_4": (bench_router_scale(4), "tokens/sec"),
    "router_affinity": (bench_router_affinity(), "tokens/sec"),
    # Round-14 pod-sharded rows: one engine over a model=tp mesh —
    # per-device param+KV bytes and TTFT/TPOT vs the solo engine.
    "engine_sharded_tp2": (bench_engine_sharded(2), "tokens/sec"),
    "engine_sharded_tp4": (bench_engine_sharded(4), "tokens/sec"),
    # Round-17 disaggregated fleet: prefill/decode role split with
    # block shipping vs the co-resident baseline on the same trace —
    # value is the victims' streaming-TPOT p99 immunity ratio.
    "router_disagg": (bench_router_disagg(), "x speedup"),
    # Round 19: policy-vs-policy autoscaling on the deterministic
    # trace-replay harness — static-min vs static-max vs autoscaled
    # on the SAME (seed, tick) trace; value is the p99-TTFT edge over
    # the static-minimum fleet through the hot window.
    "autoscale_spike": (bench_autoscale("spike"),
                        "x ttft vs static-min"),
    "autoscale_diurnal": (bench_autoscale("diurnal"),
                          "x ttft vs static-min"),
    # Round 20: live weight push under load — value is the victim
    # requests' TPOT p99 with a mid-stream canary promote over the
    # no-push baseline's (≈1.0 = the swap is invisible in-flight).
    "canary_rollout": (bench_canary_rollout(),
                       "x no-push tpot p99"),
}


def _probe_with_retries(attempts=3, probe_s=120, backoff_s=60):
    """Device probe that survives a flapping accelerator tunnel (the
    bench.py pattern): each attempt probes from a FRESH subprocess —
    a hung backend init cannot be retried in-process — and only after
    one succeeds does this process initialize its own backend.
    Returns the error string, or None when a device answered."""
    import time as _time

    from distkeras_tpu.utils.misc import probe_device_count_subprocess

    err = "no probe attempt ran"
    for i in range(attempts):
        try:
            probe_device_count_subprocess(deadline_s=probe_s)
            return None
        except Exception as e:  # TimeoutError / RuntimeError from probe
            err = str(e)[:220]
        if i + 1 < attempts:
            _time.sleep(backoff_s)
    return err


def _emit_skips(names, err):
    """One structured ``status: skipped`` line per requested row — an
    environment outage must not read as a repo regression (the same
    poisoned-run hazard bench.py fixed in round 4: rc=1 made the
    driver record a failure while the real numbers lived in prose).
    Each line keeps the one-line contract (null value = no
    measurement) and carries the most recent PRIOR green measurement
    under ``last_green``, clearly labeled."""
    from bench_suite import read_last_green

    for name in names or BENCHES:
        line = {"metric": name, "value": None,
                "unit": BENCHES[name][1], "ms_per_token": None,
                "status": "skipped", "error": err}
        prior = read_last_green(name)
        if prior is not None:
            line["last_green"] = {
                "note": "prior green measurement, NOT this run",
                **prior}
        print(json.dumps(line))


def main(names):
    unknown = set(names) - set(BENCHES)
    if unknown:
        sys.exit(f"unknown config(s) {sorted(unknown)}; "
                 f"choose from {sorted(BENCHES)}")
    err = _probe_with_retries()
    if err is not None:
        _emit_skips(names, err)
        sys.exit(0)
    import jax

    from distkeras_tpu import obs

    print(f"# backend={jax.default_backend()} device={jax.devices()[0]}",
          file=sys.stderr)
    for name in names or BENCHES:
        fn, unit = BENCHES[name]
        # Each config runs under its own obs session (metrics only) so
        # the row ships its serving telemetry — lanes_busy, queue
        # depth, tier resizes, spec accept rate — alongside the
        # number (bench_suite.py's round-10 convention).
        sess = obs.enable()
        try:
            rate, step_s, _, extra = fn()
        except Exception as e:
            print(json.dumps({"metric": name, "error": repr(e)[:200]}))
            continue
        finally:
            snapshot = sess.registry.compact()
            obs.disable()
        line = {
            "metric": name, "value": round(rate, 1), "unit": unit,
            "ms_per_token": round(step_s * 1e3, 3), **extra,
        }
        if snapshot:
            line["obs"] = snapshot
        print(json.dumps(line))


if __name__ == "__main__":
    main(sys.argv[1:])
