"""Sweep Pallas flash-attention block sizes on the long-context config.

The three kernels (fwd, dq, dkv) share one (block_q, block_k) pair via
``flash_attention``'s custom_vjp; the transformer's default lambda uses
(256, 512) without ever having been tuned on hardware.  This sweeps the
pair over the training step of the benchmark long config (seq 4096,
d1024, L8, bf16, remat) and prints one JSON line per point — the
evidence docs/perf_transformer.md's tuning section needs.

Also sweeps the forward-only (inference) kernel separately, since the
optimum can differ when no lse is written and no backward runs.

Usage: python scripts/sweep_attention_blocks.py [--quick]
(--quick: 3 iters instead of 10 — a coarse first pass).
"""

import itertools
import json
import os
import sys
import time

os.environ.setdefault("KERAS_BACKEND", "jax")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


BLOCKS_Q = (128, 256, 512, 1024)
BLOCKS_K = (128, 256, 512, 1024)


def _long_cfg():
    from distkeras_tpu.models import transformer as tfm

    return tfm.TransformerConfig(
        vocab_size=32768, d_model=1024, n_heads=8, n_layers=8, d_ff=4096,
        max_len=4097, dtype="bfloat16", remat=True)


def sweep_train(iters):
    import jax
    import numpy as np
    import optax
    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.ops.attention import flash_attention

    cfg = _long_cfg()
    params = tfm.init_params(jax.random.key(0), cfg)
    opt = optax.adamw(3e-4)
    opt_state = opt.init(params)
    tokens = jax.device_put(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (8, 4097)).astype(np.int32))

    for bq, bk in itertools.product(BLOCKS_Q, BLOCKS_K):
        attn = lambda q, k, v, bq=bq, bk=bk: flash_attention(
            q, k, v, True, block_q=bq, block_k=bk)
        step = jax.jit(tfm.make_train_step(cfg, opt, attention_fn=attn))
        try:
            carry = (params, opt_state)
            for _ in range(3):
                carry, loss = step(carry, tokens)
            float(loss)
            t0 = time.perf_counter()
            for _ in range(iters):
                carry, loss = step(carry, tokens)
            float(loss)
            dt = (time.perf_counter() - t0) / iters
            print(json.dumps({"mode": "train", "block_q": bq, "block_k": bk,
                              "step_ms": round(dt * 1e3, 2),
                              "tokens_per_s": round(8 * 4096 / dt, 1)}))
        except Exception as e:
            print(json.dumps({"mode": "train", "block_q": bq, "block_k": bk,
                              "error": repr(e)[:160]}))


def sweep_fwd(iters):
    import jax
    import numpy as np
    from distkeras_tpu.ops.attention import flash_attention

    rng = np.random.default_rng(0)
    b, s, h, d = 8, 4096, 8, 128
    q = jax.device_put(rng.normal(size=(b, s, h, d)).astype(np.float32)
                       ).astype("bfloat16")
    k = jax.device_put(rng.normal(size=(b, s, h, d)).astype(np.float32)
                       ).astype("bfloat16")
    v = jax.device_put(rng.normal(size=(b, s, h, d)).astype(np.float32)
                       ).astype("bfloat16")
    for bq, bk in itertools.product(BLOCKS_Q, BLOCKS_K):
        fn = jax.jit(lambda q, k, v, bq=bq, bk=bk: flash_attention(
            q, k, v, True, block_q=bq, block_k=bk))
        try:
            fn(q, k, v).block_until_ready()
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn(q, k, v)
            out.block_until_ready()
            float(np.asarray(out[0, 0, 0, 0]))  # relay-safe barrier
            dt = (time.perf_counter() - t0) / iters
            print(json.dumps({"mode": "fwd", "block_q": bq, "block_k": bk,
                              "ms": round(dt * 1e3, 3)}))
        except Exception as e:
            print(json.dumps({"mode": "fwd", "block_q": bq, "block_k": bk,
                              "error": repr(e)[:160]}))


if __name__ == "__main__":
    iters = 3 if "--quick" in sys.argv else 10
    sweep_fwd(iters)
    sweep_train(iters)
