"""Run-to-run variance protocol for the headline benchmarks.

Round-3 left a −21% r2→r3 swing on CIFAR-CNN/MNIST-MLP attributed to
"relay variance" with no variance data (round-3 verdict, weakness 2).
This runs each named config N times IN ONE TUNNEL SESSION and reports
median / min / max / IQR, so BASELINE.md rows can carry spread columns
and cross-round deltas can be judged against measured noise instead of
folklore.

Usage:
    python scripts/variance.py [-n 5] [config ...]
Defaults: n=5 over the headline set (cifar_cnn, mnist_mlp,
cifar_cnn_resident, transformer_long).  Prints one JSON line per
config: {"metric", "runs", "median", "min", "max", "iqr_pct", "unit",
"values"}.
"""

import json
import os
import sys

os.environ.setdefault("KERAS_BACKEND", "jax")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

HEADLINE = ("cifar_cnn", "mnist_mlp", "cifar_cnn_resident",
            "transformer_long")


def main(argv):
    import numpy as np

    from bench_suite import BENCHES

    n = 5
    if argv[:1] == ["-n"]:
        n, argv = int(argv[1]), argv[2:]
    names = argv or list(HEADLINE)
    unknown = set(names) - set(BENCHES)
    if unknown:
        sys.exit(f"unknown config(s) {sorted(unknown)}; "
                 f"choose from {sorted(BENCHES)}")
    import jax

    print(f"# backend={jax.default_backend()} device={jax.devices()[0]} "
          f"n={n}", file=sys.stderr)
    for name in names:
        fn, unit = BENCHES[name]
        vals = []
        for i in range(n):
            try:
                vals.append(float(fn()[0]))
            except Exception as e:
                print(json.dumps({"metric": name, "run": i,
                                  "error": repr(e)[:200]}))
        if not vals:
            continue
        v = np.asarray(vals)
        q1, med, q3 = np.percentile(v, [25, 50, 75])
        print(json.dumps({
            "metric": name, "runs": len(vals),
            "median": round(float(med), 1),
            "min": round(float(v.min()), 1),
            "max": round(float(v.max()), 1),
            "iqr_pct": round(float((q3 - q1) / med * 100), 2),
            "spread_pct": round(
                float((v.max() - v.min()) / med * 100), 2),
            "unit": unit,
            "values": [round(float(x), 1) for x in vals],
        }))


if __name__ == "__main__":
    main(sys.argv[1:])
