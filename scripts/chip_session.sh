#!/bin/bash
# One green-tunnel measurement session, in priority order (round-5
# plan; round-4 backlog front-loaded — see VERDICT.md round-4 item 1).
# Run from the repo root the moment the axon tunnel is up; every stage
# appends JSON lines to chip_session_r5.log so a mid-session tunnel
# drop loses nothing.
set -u
cd "$(dirname "$0")/.."
LOG=chip_session_r5.log
say() { echo "### $(date -u +%H:%M:%S) $*" | tee -a "$LOG"; }

say "stage 0: probe + headline (writes BENCH_LAST_GREEN.json)"
python bench.py 2>>"$LOG" | tee -a "$LOG" || exit 1

say "stage 1: staged round-3 serving configs (TTFT + engine)"
python scripts/bench_serving.py prefix_cache_ttft engine_throughput \
    engine_throughput_kvint8 \
    2>>"$LOG" | tee -a "$LOG"

say "stage 2: MoE + LoRA serving"
python scripts/bench_serving.py decode_moe_b8 decode_moe_b64 \
    decode_moe_top2_b8 lora_merged_serve 2>>"$LOG" | tee -a "$LOG"

say "stage 3: MoE + LoRA training (with the dense baseline row)"
python scripts/bench_suite.py transformer_d1024 transformer_moe_top1 \
    transformer_moe_top2 lora_finetune 2>>"$LOG" | tee -a "$LOG"

say "stage 4: engine under load (TTFT/TPOT p50/p99 grid)"
python scripts/bench_serving.py engine_load_8l_low engine_load_8l_mid \
    engine_load_8l_high engine_load_4l_mid engine_load_16l_mid \
    2>>"$LOG" | tee -a "$LOG"

say "stage 5: flagship MFU ablation"
python scripts/ablate_flagship.py 2>>"$LOG" | tee -a "$LOG"

say "stage 6: variance protocol (headline set, n=5)"
python scripts/variance.py -n 5 2>>"$LOG" | tee -a "$LOG"

say "stage 7: windowed beam (ancestry vs physical on chip)"
python scripts/bench_serving.py beam4 beam4_windowed \
    beam4_windowed_physical decode_rolling_window \
    2>>"$LOG" | tee -a "$LOG"

say "stage 8 (round-5 additions): LM e2e input plane + int8 ring"
python scripts/bench_suite.py lm_e2e_stream lm_e2e_device_data \
    2>>"$LOG" | tee -a "$LOG"
python scripts/bench_serving.py decode_rolling_window_kvint8 \
    engine_speculative \
    2>>"$LOG" | tee -a "$LOG"

say "session complete — transcribe: python scripts/format_session.py $LOG"
