#!/bin/bash
# One green-tunnel measurement session, in priority order (round-6
# loop; round-5 set carried forward).  Run from the repo root the
# moment the axon tunnel is up; every stage appends JSON lines to
# chip_session_r6.log so a mid-session tunnel drop loses nothing.
#
# Hang-proofing (round 6): every stage runs under a hard timeout cap —
# a wedged backend init or flapping tunnel records a TIMEOUT line and
# the session moves on, it can never hang the box.  Stages 0-2 form
# the MINIMUM-EVIDENCE set (~10 min): probe + headline + the
# pre-registered decision rows, so even a session cut short right
# after them leaves a decidable round record.  At close the probe/
# availability record is committed as the round artifact
# (PROBELOG_r6.txt — VERDICT round-5 item 9).
set -u
cd "$(dirname "$0")/.."
LOG=chip_session_r6.log
say() { echo "### $(date -u +%H:%M:%S) $*" | tee -a "$LOG"; }
run() {  # run <minutes> <cmd...> — hard-capped stage; a timeout is a
         # recorded fact, never a hang
  local mins=$1; shift
  timeout -k 30 "$((mins * 60))" "$@" 2>>"$LOG" | tee -a "$LOG"
  local rc=${PIPESTATUS[0]}
  if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
    say "TIMEOUT (${mins}m cap): $*"
  fi
  return "$rc"
}

say "=== minimum-evidence set (~10 min) ==="
say "stage 0: probe + headline (writes BENCH_LAST_GREEN.json)"
run 5 python bench.py || exit 1

say "stage 1: staged round-3 serving configs (TTFT + engine)"
run 3 python scripts/bench_serving.py prefix_cache_ttft engine_throughput \
    engine_throughput_kvint8

say "stage 2: pre-registered engine_speculative decision row"
run 3 python scripts/bench_serving.py engine_speculative
say "=== minimum-evidence set complete; below is extended coverage ==="

say "stage 3: MoE + LoRA serving"
run 8 python scripts/bench_serving.py decode_moe_b8 decode_moe_b64 \
    decode_moe_top2_b8 lora_merged_serve

say "stage 4: MoE + LoRA training (with the dense baseline row)"
run 12 python scripts/bench_suite.py transformer_d1024 transformer_moe_top1 \
    transformer_moe_top2 lora_finetune

say "stage 5: engine under load (TTFT/TPOT p50/p99 grid)"
run 12 python scripts/bench_serving.py engine_load_8l_low engine_load_8l_mid \
    engine_load_8l_high engine_load_4l_mid engine_load_16l_mid

say "stage 6: flagship MFU ablation"
run 15 python scripts/ablate_flagship.py

say "stage 7: variance protocol (headline set, n=5)"
run 15 python scripts/variance.py -n 5

say "stage 8: windowed beam (ancestry vs physical on chip)"
run 8 python scripts/bench_serving.py beam4 beam4_windowed \
    beam4_windowed_physical decode_rolling_window

say "stage 9: LM e2e input plane + int8 ring + async-tier convergence"
run 10 python scripts/bench_suite.py lm_e2e_stream lm_e2e_device_data \
    async_tau1 async_tau4 async_adasum
run 6 python scripts/bench_serving.py decode_rolling_window_kvint8

say "session close: commit probe/availability record as round artifact"
grep -E '^### |"status"' "$LOG" > PROBELOG_r6.txt
git add PROBELOG_r6.txt && git commit -q -m "round 6 chip session: tunnel-availability probe log" -- PROBELOG_r6.txt || say "probe-log commit skipped"

say "session complete — transcribe: python scripts/format_session.py $LOG"
