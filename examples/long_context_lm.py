"""Long-context LM training: past the reference's ceiling.

The reference's longest sequence model is a 128-token LSTM trained
data-parallel only (reference: examples, IMDB config).  This example
trains a causal transformer whose sequence dimension is sharded over
the mesh ``seq`` axis with ring attention, optionally with a Switch-MoE
FFN sharded over ``expert`` — per-device activation memory stays
O(L / seq_parallelism) while the math matches single-device attention
exactly (tests/test_attention.py pins this).

Run ``DKT_EXAMPLE_DEVICES=8 python examples/long_context_lm.py`` for a
data=2 x seq=4 CPU mesh; on a pod slice the same code spans the real
ICI torus.
"""

import numpy as np

from _common import setup_devices


def main(steps: int = 30, seq_len: int = 256):
    devices = setup_devices()
    import jax
    import jax.numpy as jnp
    import optax
    import distkeras_tpu  # noqa: F401
    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh
    from distkeras_tpu.parallel.ring import make_ring_attention
    from distkeras_tpu.parallel.sharding import ShardingPlan

    n = len(devices)
    seq_par = 4 if n % 4 == 0 else (2 if n % 2 == 0 else 1)
    mesh = make_mesh(MeshSpec(data=n // seq_par, seq=seq_par),
                     devices=devices)
    cfg = tfm.TransformerConfig(
        vocab_size=512, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_len=seq_len, num_experts=0)
    params = tfm.init_params(jax.random.key(0), cfg)
    plan = ShardingPlan(rules=tfm.tp_rules())
    params = jax.device_put(params, plan.tree_shardings(mesh, params))
    opt = optax.adam(1e-3)
    ring = make_ring_attention(mesh, causal=True)
    step = jax.jit(tfm.make_train_step(cfg, opt, attention_fn=ring),
                   donate_argnums=0)

    rng = np.random.default_rng(0)
    batch = 4 * int(mesh.shape["data"])
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size,
                                      (batch, seq_len + 1)), jnp.int32)
    carry = (params, opt.init(params))
    for i in range(steps):
        carry, loss = step(carry, tokens)
        if i % 10 == 0 or i == steps - 1:
            print(f"step {i:3d} loss {float(loss):.4f} "
                  f"(mesh data={mesh.shape['data']} seq={seq_par}, "
                  f"global seq len {seq_len})")
    return float(loss)


if __name__ == "__main__":
    main()
