"""Interactive serving: continuous batching + a reused system prompt.

The online-serving surface in one script:

1. train a small rope LM (arithmetic-sequence toy data so outputs are
   checkable),
2. prefill a shared "system prompt" ONCE and fan it out per request
   (`prompt_cache` — exact-parity prefix reuse),
3. run a `ContinuousBatcher`: requests arrive at different times, each
   admitted into a free lane mid-flight while other lanes keep
   decoding; every output equals its solo `generate` run.

The reference has no serving story at all (its ModelPredictor runs the
training forward over a static batch; reference:
distkeras/predictors.py) — this is TPU-first surplus.

Run: python examples/serving_engine.py
(DKT_EXAMPLE_DEVICES=8 forces the CPU mesh.)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import setup_devices  # noqa: E402

devices = setup_devices()

import numpy as np  # noqa: E402

import jax  # noqa: E402

import distkeras_tpu as dk  # noqa: E402
from distkeras_tpu.models import transformer as tfm  # noqa: E402
from distkeras_tpu.models.generate import generate, prefill  # noqa: E402


def main():
    vocab, seq = 128, 64
    cfg = tfm.TransformerConfig(vocab_size=vocab, d_model=128, n_heads=4,
                                n_layers=2, d_ff=256, max_len=seq,
                                rope=True)
    rng = np.random.default_rng(0)
    # Learnable toy language: each row counts up from a random start.
    rows = (np.cumsum(np.ones((256, seq + 1), np.int64), axis=1)
            + rng.integers(0, vocab, (256, 1))) % vocab
    # One-device mesh: this example is about the serving loop, and the
    # forced-CPU multi-device mesh on a small host can deadlock its
    # in-process collectives under the async dispatch of a bigger toy
    # model (the distributed-training examples are workflow.py etc.).
    mesh = dk.make_mesh(dk.MeshSpec(data=1), devices=devices[:1])
    tr = dk.LMTrainer(cfg, learning_rate=5e-3, batch_size=32,
                      num_epoch=6, seed=0, mesh=mesh)
    params = tr.train(rows.astype(np.int32))
    print(f"trained: loss {tr.history[0]:.2f} -> {tr.history[-1]:.2f}")
    # Serving is single-chip: pull the trained tree off the training
    # mesh so the engine's state and the params share one device (on
    # the forced-CPU mesh this also avoids mixing tiny multi-device
    # programs into the host-driven serving loop).
    params = jax.device_get(params)

    # ---- shared system prefix, prefilled once at batch 1 ------------
    prefix = (np.arange(8, dtype=np.int32) + 17) % vocab
    cache, _ = prefill(params, prefix[None], cfg, last_logits=False)
    tail = ((np.arange(4, dtype=np.int32) + prefix[-1] + 1) % vocab)
    out = generate(params, tail[None], cfg, 8,
                   prompt_cache=(cache, len(prefix)))
    print("prefix-cached generation:", np.asarray(out)[0].tolist())

    # ---- continuous batching ----------------------------------------
    eng = dk.ContinuousBatcher(params, cfg, lanes=4)
    starts = rng.integers(0, vocab, (6,))
    requests = [((np.arange(5) + s) % vocab).astype(np.int32)
                for s in starts]
    pending, done = list(enumerate(requests)), {}
    submitted = {}
    tick = 0
    while len(done) < len(requests):
        while pending and eng.free_lanes():
            rid, prompt = pending.pop(0)
            submitted[eng.submit(prompt, 10)] = rid
            print(f"t={tick}: admitted request {rid}")
        eng.step()
        tick += 1
        for lane in list(submitted):
            if lane not in eng.running():
                rid = submitted.pop(lane)
                done[rid] = eng.drain(lane)
                print(f"t={tick}: request {rid} finished")
    ok = 0
    for rid, out in sorted(done.items()):
        expect = (requests[rid][-1] + 1 + np.arange(10)) % vocab
        ok += int((np.asarray(out)[5:] == expect).mean() > 0.9)
    print(f"{ok}/{len(requests)} requests continued their sequence")
    assert ok >= len(requests) - 1   # trained model, not a proof

    # ---- per-request sampling ---------------------------------------
    # One engine, one batch: a greedy request decodes next to a
    # creative one (its own temperature/top_p) — each matches its solo
    # generate() run exactly.
    eng = dk.ContinuousBatcher(params, cfg, lanes=2,
                               per_request_sampling=True)
    prompt = requests[0]
    greedy = eng.submit(prompt, 8)
    key = jax.random.key(42)
    creative = eng.submit(prompt, 8, key=key, temperature=1.2,
                          top_p=0.9)
    while eng.running():
        eng.step()
    g, c = eng.drain(greedy), eng.drain(creative)
    print("greedy  :", np.asarray(g)[5:].tolist())
    print("creative:", np.asarray(c)[5:].tolist())
    ref = generate(params, prompt[None], cfg, 8, temperature=1.2,
                   top_p=0.9, key=key)
    assert (np.asarray(c) == np.asarray(ref)[0]).all()


if __name__ == "__main__":
    main()
