"""MNIST MLP: the reference's canonical first example, TPU-native.

Mirrors the reference MNIST notebook (reference: examples — Dense
500/300/10 MLP, SingleTrainer then a distributed trainer, accuracy via
the predictor/evaluator pipeline).  Run single-chip as-is, or
``DKT_EXAMPLE_DEVICES=8 python examples/mnist_mlp.py`` for an 8-way
data-parallel CPU mesh.
"""

from _common import setup_devices, synthetic_mnist


def main(steps_scale: int = 1):
    devices = setup_devices()
    import distkeras_tpu as dk  # before keras: forces the JAX backend
    from distkeras_tpu.models.zoo import mnist_mlp

    x, y = synthetic_mnist()
    split = len(x) * 3 // 4
    train = dk.Dataset.from_arrays(x[:split], y[:split])
    test = dk.Dataset.from_arrays(x[split:], y[split:])

    results = {}
    for name, trainer in [
        ("SingleTrainer", dk.SingleTrainer(
            mnist_mlp(seed=0), loss="sparse_categorical_crossentropy",
            worker_optimizer="adam", learning_rate=1e-3, batch_size=128,
            num_epoch=2 * steps_scale)),
        ("ADAG", dk.ADAG(
            mnist_mlp(seed=0), loss="sparse_categorical_crossentropy",
            worker_optimizer="adam", learning_rate=1e-3, batch_size=64,
            communication_window=4, num_epoch=2 * steps_scale,
            num_workers=len(devices))),
    ]:
        model = trainer.train(train)
        scored = dk.ModelPredictor(model, output_col="prediction").predict(test)
        scored = dk.LabelIndexTransformer(input_col="prediction").transform(scored)
        acc = dk.AccuracyEvaluator(
            prediction_col="prediction_index").evaluate(scored)
        results[name] = (trainer.training_time, acc)
        print(f"{name:16s} time={trainer.training_time:6.2f}s acc={acc:.4f}")
    return results


if __name__ == "__main__":
    main()
