"""Shared example plumbing: device setup + synthetic datasets.

The reference's examples are Jupyter notebooks against a Spark
`local[N]` master (reference: examples/workflow.ipynb, mnist notebook);
these are scripts against either the real TPU (default) or an N-device
CPU mesh — set ``DKT_EXAMPLE_DEVICES=8`` to force the CPU mesh, the
moral equivalent of `local[8]`.

Datasets are synthetic (this environment has no network): shaped and
sized like the originals, separable enough that every trainer reaches
high accuracy in seconds.
"""

import os
import sys

import numpy as np

# Examples run from a checkout without installation.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def setup_devices():
    """Honor DKT_EXAMPLE_DEVICES before jax initializes; return devices."""
    n = os.environ.get("DKT_EXAMPLE_DEVICES")
    if n:
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + f" --xla_force_host_platform_device_count={n}")
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    return jax.devices()


def synthetic_mnist(n=8192, seed=0):
    """784-dim 10-class data shaped like flattened MNIST."""
    rng = np.random.default_rng(seed)
    protos = rng.normal(0, 1.0, (10, 784))
    y = rng.integers(0, 10, n)
    x = (protos[y] + rng.normal(0, 2.0, (n, 784))).astype(np.float32)
    return x, y.astype(np.int64)


def synthetic_higgs(n=16384, dim=28, seed=0):
    """Tabular binary task shaped like the ATLAS Higgs features, with
    feature scales spread out so MinMaxTransformer matters."""
    rng = np.random.default_rng(seed)
    w = rng.normal(0, 1, (dim,))
    scales = np.exp(rng.normal(0, 1, (dim,)))
    x_raw = rng.normal(0, 1, (n, dim))
    y = (x_raw @ w + 0.3 * rng.normal(0, 1, n) > 0).astype(np.int64)
    return (x_raw * scales).astype(np.float32), y
