"""The full pipeline workflow: every trainer on the Higgs-shaped task.

Mirrors the reference's flagship notebook (reference:
examples/workflow.ipynb — ATLAS Higgs tabular MLP through
normalization, one-hot, every distributed trainer with timing table,
then predictor + label-index + accuracy evaluation).  The Spark
DataFrame stages map to Dataset/transformer ops; the trainer table maps
1:1 (SURVEY.md §7.4 for the async->sync semantics).

``DKT_EXAMPLE_DEVICES=8 python examples/workflow.py`` runs the
distributed trainers over an 8-device CPU mesh (the reference's
`local[8]`).
"""

from _common import setup_devices, synthetic_higgs


def main(steps_scale: int = 1):
    devices = setup_devices()
    import distkeras_tpu as dk
    from distkeras_tpu.models.zoo import higgs_mlp

    x, y = synthetic_higgs()
    split = len(x) * 3 // 4

    # -- pipeline ops (reference workflow: StandardScaler before the
    # trainers — SURVEY.md §3.5).  Fit on train, reuse stats for test.
    scaler = dk.StandardScaleTransformer(input_col="features")
    train = scaler.transform(dk.Dataset.from_arrays(x[:split], y[:split]))
    test = scaler.transform(dk.Dataset.from_arrays(x[split:], y[split:]))

    n = len(devices)
    mk = lambda: higgs_mlp(seed=0)
    common = dict(loss="sparse_categorical_crossentropy",
                  worker_optimizer="adam", learning_rate=1e-3,
                  num_epoch=4 * steps_scale)
    trainers = [
        ("SingleTrainer", dk.SingleTrainer(mk(), batch_size=128, **common)),
        ("ADAG", dk.ADAG(mk(), batch_size=64, communication_window=4,
                         num_workers=n, **common)),
        ("DOWNPOUR", dk.DOWNPOUR(mk(), batch_size=64, communication_window=4,
                                 num_workers=n, **common)),
        ("AEASGD", dk.AEASGD(mk(), batch_size=64, communication_window=8,
                             rho=5.0, num_workers=n, **common)),
        ("EAMSGD", dk.EAMSGD(mk(), batch_size=64, communication_window=8,
                             rho=5.0, momentum=0.9, num_workers=n, **common)),
        ("DynSGD", dk.DynSGD(mk(), batch_size=64, communication_window=4,
                             num_workers=n, **common)),
        ("AveragingTrainer", dk.AveragingTrainer(mk(), batch_size=64,
                                                 num_workers=n, **common)),
    ]

    print(f"{'trainer':18s} {'time (s)':>9s} {'accuracy':>9s}   ({n} workers)")
    results = {}
    for name, trainer in trainers:
        model = trainer.train(train)
        scored = dk.ModelPredictor(model, output_col="prediction").predict(test)
        scored = dk.LabelIndexTransformer(input_col="prediction").transform(scored)
        acc = dk.AccuracyEvaluator(
            prediction_col="prediction_index").evaluate(scored)
        results[name] = (trainer.training_time, acc)
        print(f"{name:18s} {trainer.training_time:9.2f} {acc:9.4f}")
    return results


if __name__ == "__main__":
    main()
