"""Text in, text out: the full LM pipeline on one chip.

Train a byte-level BPE tokenizer on a corpus (here: this repository's
own source files — real text, no download), encode it into LMTrainer
rows, train the transformer with a warmup-cosine schedule, and sample
continuations with nucleus sampling.  The reference has no analogue of
any stage of this (its pipeline starts at pre-vectorized DataFrame
columns, reference: workflow.ipynb); this is the rebuild's flagship
path end to end.

Run: python examples/text_lm.py [--steps N]
"""

import argparse
import glob
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import setup_devices  # noqa: E402

setup_devices()  # DKT_EXAMPLE_DEVICES=N forces the CPU mesh

import distkeras_tpu as dk  # noqa: E402  (forces KERAS_BACKEND=jax)


def load_corpus() -> str:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    files = sorted(glob.glob(os.path.join(root, "distkeras_tpu/**/*.py"),
                             recursive=True))
    return "\n\n".join(open(f).read() for f in files)


def main():
    import jax
    import numpy as np
    import optax
    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.models.generate import generate

    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--vocab", type=int, default=1024)
    args = ap.parse_args()

    corpus = load_corpus()
    print(f"corpus: {len(corpus):,} chars")
    tok = dk.BPETokenizer.train(corpus, vocab_size=args.vocab)
    rows = tok.encode_corpus(corpus, seq_len=args.seq_len)
    sample = corpus[:100000]
    print(f"tokenizer: vocab {tok.vocab_size}, "
          f"{rows.shape[0]:,} rows of {args.seq_len}+1 tokens "
          f"({len(sample) / len(tok.encode(sample)):.2f} chars/token "
          "on a sample)")

    cfg = tfm.TransformerConfig(
        vocab_size=tok.vocab_size, d_model=256, n_heads=4, n_layers=4,
        d_ff=1024, max_len=args.seq_len + 1,
        dtype="bfloat16" if jax.default_backend() == "tpu" else "float32")
    batch = 32
    epochs = max(1, args.steps // max(1, len(rows) // batch))
    sched = optax.warmup_cosine_decay_schedule(0.0, 3e-3, 20,
                                               args.steps, 1e-4)
    trainer = dk.LMTrainer(cfg, optimizer="adamw", learning_rate=sched,
                           batch_size=batch, num_epoch=epochs, shuffle=True,
                           seed=0)
    params = trainer.train(rows)
    print(f"trained {len(trainer.history)} steps in "
          f"{trainer.training_time:.1f}s: loss "
          f"{trainer.history[0]:.3f} -> {trainer.history[-1]:.3f}")

    prompt_text = "def train("
    prompt = np.tile(tok.encode(prompt_text), (2, 1)).astype(np.int32)
    n_new = min(48, cfg.max_len - prompt.shape[1])
    out = generate(params, prompt, cfg, max_new_tokens=n_new,
                   temperature=0.8, top_p=0.95, key=jax.random.key(0))
    for row in np.asarray(out):
        print("sample:", repr(tok.decode(row)))

    # Beam search: the most probable continuation instead of a sample.
    from distkeras_tpu.models.generate import beam_search

    seqs, scores = beam_search(params, prompt[:1], cfg, n_new,
                               beam_width=4)
    print(f"beam ({float(scores[0, 0]):.2f}):",
          repr(tok.decode(np.asarray(seqs[0, 0]))))

    # Ship the artifact; int8-quantize for decode-heavy serving.
    from distkeras_tpu.models.quant import quantize_params

    dk.save_lm("/tmp/text_lm.npz", params, cfg)
    loaded, cfg2 = dk.load_lm("/tmp/text_lm.npz")
    q = quantize_params(jax.tree.map(jax.numpy.asarray, loaded))
    qout = generate(q, prompt[:1], cfg2, max_new_tokens=n_new)
    print("int8 greedy:", repr(tok.decode(np.asarray(qout[0]))))


if __name__ == "__main__":
    main()
