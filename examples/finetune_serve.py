"""Pretrain -> pack -> LoRA-finetune -> serve: the adapt-and-deploy path.

The round-3 serving/adaptation surface in one script:

1. pretrain a small rope transformer on corpus A (packed documents —
   `pack_documents` + segment-masked attention, no padding waste),
2. LoRA-finetune the frozen base on corpus B (adapter-only optimizer
   state; merged servable tree back),
3. serve the merged model three ways and compare tokens/sec:
   plain KV-cached greedy decode, int8 weight-quantized decode, and
   speculative decode with the int8 tree drafting for its f32 parent.

The reference has no analogue of any stage (its largest model is an
IMDB LSTM trained from scratch; reference: examples).

Run: python examples/finetune_serve.py [--steps N]
(DKT_EXAMPLE_DEVICES=8 forces the CPU mesh.)
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import setup_devices  # noqa: E402


def synthetic_docs(rng, n_docs, lo, hi, vocab):
    """Documents as token-id sequences with a doc-level bias so the
    two corpora are distinguishable (finetuning has something to do)."""
    import numpy as np

    docs = []
    for _ in range(n_docs):
        length = int(rng.integers(8, 60))
        base = rng.integers(lo, hi)
        step = rng.integers(1, 5)
        docs.append(((base + step * np.arange(length)) % (hi - lo) + lo
                     ).astype(np.int32).tolist())
    return docs


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60)
    args = ap.parse_args()
    setup_devices()

    import jax
    import numpy as np

    import distkeras_tpu as dk
    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.models.generate import generate
    from distkeras_tpu.models.quant import quantize_params
    from distkeras_tpu.models.speculative import speculative_generate

    rng = np.random.default_rng(0)
    vocab, seq = 128, 64
    cfg = tfm.TransformerConfig(vocab_size=vocab, d_model=64, n_heads=4,
                                n_layers=2, d_ff=256, max_len=256,
                                rope=True)

    # ---- 1. pretrain on packed corpus A --------------------------------
    docs_a = synthetic_docs(rng, 400, 1, 64, vocab)
    rows, segs = dk.pack_documents(docs_a, seq_len=seq)
    n = (len(rows) // 16) * 16
    epochs = max(1, args.steps * 16 // max(n, 1))
    t = dk.LMTrainer(cfg, learning_rate=3e-3, batch_size=16,
                     num_epoch=epochs, shuffle=True, seed=0)
    base = t.train(rows[:n], segments=segs[:n])
    print(f"[pretrain] {len(docs_a)} docs -> {n} packed rows "
          f"(fill {dk.packing_efficiency(segs[:n]):.2f}); "
          f"loss {t.history[0]:.3f} -> {t.history[-1]:.3f}")

    # ---- 2. LoRA-finetune on corpus B ----------------------------------
    docs_b = synthetic_docs(rng, 200, 64, 127, vocab)
    rows_b, segs_b = dk.pack_documents(docs_b, seq_len=seq)
    nb = (len(rows_b) // 16) * 16
    ft = dk.LoRATrainer(cfg, base, lora_rank=8, lora_alpha=16.0,
                        learning_rate=3e-2, batch_size=16, num_epoch=3)
    merged = ft.train(rows_b[:nb], segments=segs_b[:nb])
    n_ad = sum(x.size for x in jax.tree.leaves(ft.adapters))
    n_base = sum(x.size for x in jax.tree.leaves(base))
    nll_base = float(tfm.lm_nll(base, rows_b[:16], cfg,
                                segment_ids=segs_b[:16]))
    nll_ft = float(tfm.lm_nll(merged, rows_b[:16], cfg,
                              segment_ids=segs_b[:16]))
    print(f"[finetune] adapters {n_ad} params ({n_ad / n_base:.1%} of "
          f"base); corpus-B nll {nll_base:.3f} -> {nll_ft:.3f}")

    # ---- 3. serve three ways -------------------------------------------
    prompt = rows_b[:4, :8]
    new = 48

    def bench(name, fn):
        out = fn()  # compile
        t0 = time.perf_counter()
        out = fn()
        toks = np.asarray(out)
        dt = time.perf_counter() - t0
        print(f"[serve] {name:<24} {4 * new / dt:8.1f} tok/s")
        return toks

    g_plain = bench("greedy bf16/f32", lambda: generate(
        merged, prompt, cfg, new))
    q = quantize_params(merged)
    g_int8 = bench("greedy int8", lambda: generate(q, prompt, cfg, new))
    spec_fn = jax.jit(lambda tp, dp, pr: speculative_generate(
        tp, dp, pr, cfg, cfg, new, n_draft=4)[0])
    g_spec = bench("speculative (int8 draft)",
                   lambda: spec_fn(merged, q, prompt))
    agree8 = (g_plain[:, 8:] == g_int8[:, 8:]).mean()
    # Speculative output IS the target's greedy rollout — assert exact
    # equality.  (A float-tie argmax flip between the chunked and
    # per-step programs would cascade autoregressively from that
    # position; none observed on this config — if one ever appears on
    # other hardware, compare per row up to first divergence instead.)
    assert (g_spec == g_plain).all()
    print(f"[serve] int8 token agreement vs f32: {agree8:.2f}; "
          f"speculative == plain greedy: True")


if __name__ == "__main__":
    main()
