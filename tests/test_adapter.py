import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.models.adapter import ModelAdapter
from distkeras_tpu.ops.losses import resolve_loss


def test_train_step_reduces_loss(mlp, blobs):
    x, y = blobs
    adapter = ModelAdapter(mlp, loss="sparse_categorical_crossentropy",
                           optimizer="sgd", learning_rate=0.1)
    state = adapter.init_state()
    step = jax.jit(adapter.make_train_step())
    state, l0 = step(state, x[:128], y[:128])
    for _ in range(30):
        state, loss = step(state, x[:128], y[:128])
    assert float(loss) < float(l0) * 0.7
    assert int(state.step) == 31


def test_train_step_matches_numpy_sgd(blobs):
    """Gradient math check against a hand-rolled numpy softmax-regression step.

    SURVEY.md §4: 'train-step math vs a hand-rolled numpy SGD step'.
    """
    import keras

    keras.utils.set_random_seed(0)
    model = keras.Sequential([keras.Input((16,)), keras.layers.Dense(4)])
    adapter = ModelAdapter(model, loss="sparse_categorical_crossentropy",
                           optimizer="sgd", learning_rate=0.5)
    state = adapter.init_state()
    W0 = np.asarray(state.tv[0]).copy()
    b0 = np.asarray(state.tv[1]).copy()

    x, y = blobs
    xb, yb = x[:64], y[:64]
    step = jax.jit(adapter.make_train_step())
    state, _ = step(state, xb, yb)

    # numpy softmax CE gradient
    logits = xb @ W0 + b0
    e = np.exp(logits - logits.max(axis=1, keepdims=True))
    p = e / e.sum(axis=1, keepdims=True)
    onehot = np.eye(4)[yb]
    dlogits = (p - onehot) / len(xb)
    gW = xb.T @ dlogits
    gb = dlogits.sum(axis=0)

    np.testing.assert_allclose(np.asarray(state.tv[0]), W0 - 0.5 * gW,
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(state.tv[1]), b0 - 0.5 * gb,
                               rtol=1e-4, atol=1e-5)


def test_accum_step_equals_large_batch(mlp, blobs):
    """window-w accumulation == one step on the concatenated batch (SGD)."""
    import keras

    x, y = blobs
    adapter = ModelAdapter(mlp, loss="sparse_categorical_crossentropy",
                           optimizer="sgd", learning_rate=0.1)
    state0 = adapter.init_state()

    astep = jax.jit(adapter.make_accum_train_step(4))
    xs = x[:128].reshape(4, 32, -1)
    ys = y[:128].reshape(4, 32)
    s_accum, _ = astep(state0, xs, ys)

    step = jax.jit(adapter.make_train_step())
    s_big, _ = step(state0, x[:128], y[:128])

    for a, b in zip(s_accum.tv, s_big.tv):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_export_model_round_trip(mlp, blobs):
    x, y = blobs
    adapter = ModelAdapter(mlp, loss="sparse_categorical_crossentropy")
    state = adapter.init_state()
    step = jax.jit(adapter.make_train_step())
    state, _ = step(state, x[:32], y[:32])
    model2 = adapter.export_model(state)
    np.testing.assert_allclose(np.asarray(state.tv[0]),
                               model2.get_weights()[0], rtol=1e-6)


@pytest.mark.parametrize("name", ["categorical_crossentropy",
                                  "sparse_categorical_crossentropy",
                                  "binary_crossentropy", "mse", "mae"])
def test_losses_finite(name):
    loss = resolve_loss(name)
    if name == "categorical_crossentropy":
        y, p = jnp.eye(4)[jnp.array([0, 1])], jnp.ones((2, 4))
    elif name == "sparse_categorical_crossentropy":
        y, p = jnp.array([0, 1]), jnp.ones((2, 4))
    elif name == "binary_crossentropy":
        y, p = jnp.array([0.0, 1.0]), jnp.array([0.3, 2.0])
    else:
        y, p = jnp.array([0.0, 1.0]), jnp.array([0.5, 0.5])
    val = loss(y, p)
    assert jnp.isfinite(val)


def test_unknown_loss_raises():
    with pytest.raises(ValueError):
        resolve_loss("nope")


def test_bce_rank_alignment():
    """(B,) labels vs (B,1) logits must not broadcast to (B,B)."""
    loss = resolve_loss("binary_crossentropy")
    y = jnp.array([0.0, 1.0, 1.0, 0.0])
    logits = jnp.array([[-2.0], [3.0], [1.0], [-1.0]])
    v = float(loss(y, logits))
    v_ref = float(loss(y[:, None], logits))
    assert abs(v - v_ref) < 1e-6
    with pytest.raises(ValueError, match="incompatible"):
        loss(jnp.zeros((3,)), jnp.zeros((4, 2)))
