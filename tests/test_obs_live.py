"""Live telemetry plane (round 11, docs/observability.md "Live
telemetry"): the /metrics scrape server, exposition-format
conformance under a strict mini-parser, the rolling-window SLO
engine, cluster federation, per-request trace propagation and the
request waterfall — plus the round-11 registry satellites (HELP
escaping, wire-name collision detection, compact() min/max).
"""

import json
import os
import re
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu import obs
from distkeras_tpu.models import transformer as tfm
from distkeras_tpu.obs.live import (HeartbeatHealth, TelemetryServer,
                                    merge_expositions)
from distkeras_tpu.obs.metrics import (MetricsRegistry, prom_name,
                                       windowed_percentiles)
from distkeras_tpu.obs.report import render_waterfall, request_waterfall
from distkeras_tpu.obs.slo import SloEngine, SloRule
from distkeras_tpu.obs.trace import read_trace, tail_trace
from distkeras_tpu.resilience.health import write_beat

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=32, rope=True)


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode("utf-8")


# ------------------------------------- strict exposition mini-parser

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_SAMPLE_RE = re.compile(
    rf"^({_NAME})(?:\{{(.*)\}})? (-?(?:[0-9.]+(?:[eE][+-]?[0-9]+)?|"
    r"Inf)|\+Inf|NaN)$")


def _parse_labels(s: str) -> dict:
    """Parse a label body with full escape handling (round-trips the
    writer's backslash/quote/newline escaping)."""
    labels = {}
    i = 0
    while i < len(s):
        j = s.index("=", i)
        key = s[i:j]
        assert re.fullmatch(r"[a-zA-Z_][a-zA-Z0-9_]*", key), key
        assert s[j + 1] == '"', s
        k = j + 2
        val = []
        while True:
            c = s[k]
            if c == "\\":
                val.append({"\\": "\\", '"': '"', "n": "\n"}[s[k + 1]])
                k += 2
            elif c == '"':
                break
            else:
                val.append(c)
                k += 1
        labels[key] = "".join(val)
        k += 1
        if k < len(s):
            assert s[k] == ",", s
            k += 1
        i = k
    return labels


def parse_exposition(text: str) -> dict:
    """Strict Prometheus text-format parser: validates HELP/TYPE
    ordering, sample grammar, histogram `le` monotonicity (cumulative
    counts nondecreasing, +Inf last and == _count), _sum/_count
    presence.  Returns {family: {"type", "help", "samples":
    [(name, labels, value)]}}."""
    fams: dict = {}
    cur = None

    def family_of(name):
        if name in fams:
            return name
        for suf in ("_bucket", "_sum", "_count", "_total"):
            if name.endswith(suf) and name[: -len(suf)] in fams:
                return name[: -len(suf)]
        return name

    for line in text.splitlines():
        assert line == line.rstrip(), f"trailing whitespace: {line!r}"
        if not line:
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):]
            name, _, help_text = rest.partition(" ")
            assert name not in fams, f"duplicate HELP for {name}"
            assert "\n" not in help_text
            fams[name] = {"type": None, "help": help_text, "samples": []}
            cur = name
            continue
        if line.startswith("# TYPE "):
            rest = line[len("# TYPE "):]
            name, _, kind = rest.partition(" ")
            assert kind in ("counter", "gauge", "histogram",
                            "summary", "untyped"), line
            if name in fams:
                assert fams[name]["type"] is None, \
                    f"duplicate TYPE for {name}"
                assert not fams[name]["samples"], \
                    f"TYPE after samples for {name}"
                fams[name]["type"] = kind
            else:
                fams[name] = {"type": kind, "help": None, "samples": []}
            cur = name
            continue
        assert not line.startswith("#"), f"unexpected comment: {line!r}"
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable sample line: {line!r}"
        name, lab, value = m.group(1), m.group(2), m.group(3)
        fam = family_of(name)
        assert fam in fams and fams[fam]["type"] is not None, (
            f"sample {name} before its TYPE line")
        assert fam == cur, (
            f"sample {name} outside its family's block ({fam} != {cur})")
        labels = _parse_labels(lab) if lab else {}
        fams[fam]["samples"].append((name, labels, value))

    # Histogram invariants.
    for fam, info in fams.items():
        if info["type"] != "histogram":
            continue
        series: dict = {}
        sums, counts = set(), {}
        for name, labels, value in info["samples"]:
            rest = tuple(sorted((k, v) for k, v in labels.items()
                                if k != "le"))
            if name == fam + "_bucket":
                series.setdefault(rest, []).append(
                    (labels["le"], float(value)))
            elif name == fam + "_sum":
                sums.add(rest)
            elif name == fam + "_count":
                counts[rest] = float(value)
            else:
                raise AssertionError(f"stray sample {name} in "
                                     f"histogram {fam}")
        assert series, f"histogram {fam} has no buckets"
        for rest, buckets in series.items():
            assert rest in sums, f"{fam} missing _sum for {rest}"
            assert rest in counts, f"{fam} missing _count for {rest}"
            les = [le for le, _ in buckets]
            assert les[-1] == "+Inf", f"{fam}: +Inf bucket not last"
            edges = [float(le) for le in les[:-1]]
            assert edges == sorted(edges), f"{fam}: le not ascending"
            cums = [c for _, c in buckets]
            assert cums == sorted(cums), (
                f"{fam}: cumulative bucket counts decreased: {cums}")
            assert cums[-1] == counts[rest], (
                f"{fam}: +Inf bucket {cums[-1]} != _count "
                f"{counts[rest]}")
    return fams


# -------------------------------------------- registry satellites


def test_exposition_conformance_and_label_roundtrip():
    reg = MetricsRegistry()
    reg.counter("serving.requests", "total requests").inc(
        3, status="ok")
    reg.counter("serving.requests").inc(status='we"ird\\lab\nel')
    reg.gauge("queue.depth", "queued requests").set(2)
    reg.histogram("serving.request_s", "request latency").observe(
        0.03, status="ok")
    reg.histogram("serving.request_s").observe(7.0, status="timeout")
    fams = parse_exposition(reg.render_text())
    assert fams["serving_requests"]["type"] == "counter"
    assert fams["serving_requests"]["help"] == "total requests"
    # Label escaping round-trips through the strict parser.
    weird = [lab for _, lab, _ in fams["serving_requests"]["samples"]]
    assert {"status": 'we"ird\\lab\nel'} in weird
    assert fams["serving_request_s"]["type"] == "histogram"


def test_help_text_newline_is_escaped():
    reg = MetricsRegistry()
    reg.counter("a.b", "line one\nline two \\ slash").inc()
    text = reg.render_text()
    assert "# HELP a_b line one\\nline two \\\\ slash" in text
    # The stream still parses as one record per line.
    parse_exposition(text)


def test_wire_name_collision_raises_at_registration():
    reg = MetricsRegistry()
    reg.counter("serving.queue_depth").inc()
    with pytest.raises(ValueError, match="collides"):
        reg.counter("serving_queue_depth")
    with pytest.raises(ValueError, match="collides"):
        reg.gauge("serving-queue.depth")
    # Re-asking for the same name is still get-or-create.
    reg.counter("serving.queue_depth").inc()
    assert reg.counter("serving.queue_depth").value() == 2
    with pytest.raises(ValueError, match="legal Prometheus name"):
        reg.counter("bad name!")
    assert prom_name("a.b-c") == "a_b_c"


def test_compact_includes_exact_min_max():
    reg = MetricsRegistry()
    h = reg.histogram("lat_s")
    for v in (0.003, 0.4, 11.0):
        h.observe(v)
    c = reg.compact()["lat_s"]
    assert c["min"] == 0.003 and c["max"] == 11.0
    assert c["count"] == 3 and c["p99"] <= 11.0


def test_windowed_percentiles_diff():
    reg = MetricsRegistry()
    h = reg.histogram("lat_s")
    for _ in range(10):
        h.observe(0.01)
    old = reg.snapshot()["lat_s"]["series"][0]
    for _ in range(10):
        h.observe(5.0)
    new = reg.snapshot()["lat_s"]["series"][0]
    cum = windowed_percentiles(new, None)
    win = windowed_percentiles(new, old)
    assert win["count"] == 10 and cum["count"] == 20
    assert win["p50"] > 1.0 > cum["p50"]  # window excludes the old obs
    assert windowed_percentiles(old, old) is None


# ------------------------------------------------------- SLO engine


def test_slo_engine_windows_breaches_and_rearms():
    t = [0.0]
    events = []
    reg = MetricsRegistry()
    hits = []
    eng = SloEngine(
        reg, [SloRule("lat_s", percentile=0.99, threshold=1.0,
                      window_s=10.0)],
        clock=lambda: t[0],
        emit=lambda name, **f: events.append((name, f)))
    # The subscriber queries the engine back — fires with the engine
    # lock RELEASED, so this must not deadlock the tick (round-11
    # review regression).
    eng.subscribe(lambda rule, value: hits.append(
        (rule.metric, value, eng.windowed(rule.metric, 0.5, 10.0))))
    h = reg.histogram("lat_s")
    for _ in range(5):
        h.observe(0.01)
    eng.tick()
    assert not events and not hits
    assert eng.windowed("lat_s", 0.5, 10.0) < 0.1
    # Latency spike -> breach (event + counter + subscriber).
    t[0] = 5.0
    for _ in range(5):
        h.observe(5.0)
    eng.tick()
    assert [n for n, _ in events] == ["slo.breach"]
    assert events[0][1]["metric"] == "lat_s"
    assert events[0][1]["value"] > 1.0
    assert hits and hits[0][0] == "lat_s"
    assert hits[0][2] is not None  # the reentrant windowed() worked
    assert reg.counter("slo.breaches").value(metric="lat_s",
                                             q="p99") == 1
    # Windowed gauges land in the registry (scrapeable).
    assert reg.gauge("slo.windowed").value(metric="lat_s",
                                           q="p99") > 1.0
    # Sustained breach: edge-triggered, no second event.
    t[0] = 6.0
    eng.tick()
    assert len(events) == 1
    # Recovery re-arms...
    t[0] = 20.0
    for _ in range(20):
        h.observe(0.01)
    eng.tick()
    assert len(events) == 1
    # ...so the next spike breaches again.
    t[0] = 21.0
    for _ in range(5):
        h.observe(5.0)
    eng.tick()
    assert len(events) == 2
    assert reg.counter("slo.breaches").value(metric="lat_s",
                                             q="p99") == 2


def test_slo_rule_validation():
    with pytest.raises(ValueError, match="percentile"):
        SloRule("m", percentile=1.5, threshold=1.0)
    with pytest.raises(ValueError, match="threshold"):
        SloRule("m", percentile=0.99, threshold=0.0)
    with pytest.raises(ValueError, match="window_s"):
        SloRule("m", percentile=0.99, threshold=1.0, window_s=-1)


# ------------------------------------------------- telemetry server


def test_server_endpoints_and_trace_tail(tmp_path):
    path = str(tmp_path / "t.jsonl")
    with obs.session(trace_path=path, serve_port=0) as sess:
        obs.count("x.hits", 2, kind="a")
        obs.observe("x.lat_s", 0.02)
        for i in range(8):
            obs.event("marker", i=i)
        url = sess.server.url
        fams = parse_exposition(_get(url + "/metrics"))
        assert ("x_hits", {"kind": "a"}, "2.0") in \
            fams["x_hits"]["samples"]
        snap = json.loads(_get(url + "/snapshot.json"))
        assert snap["x.hits"]["series"][0]["value"] == 2
        # /trace/tail?n= — last N records, newest last.
        lines = _get(url + "/trace/tail?n=3").splitlines()
        recs = [json.loads(l) for l in lines]
        assert len(recs) == 3
        assert [r["fields"]["i"] for r in recs] == [5, 6, 7]
        # Unknown endpoint -> 404.
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(url + "/nope")
        assert ei.value.code == 404
    # Session close stops the server.
    with pytest.raises(Exception):
        _get(url + "/metrics", timeout=2)


def test_healthz_flips_with_heartbeat_freshness(tmp_path):
    t = [100.0]
    hb = str(tmp_path / "hb")
    health = HeartbeatHealth(hb, host=0, window=2.0,
                             clock=lambda: t[0])
    with obs.session(serve_port=0, health=health) as sess:
        url = sess.server.url
        # No beat yet -> 503.
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(url + "/healthz")
        assert ei.value.code == 503
        write_beat(hb, 0, epoch=0, n=1, clock=lambda: t[0])
        body = json.loads(_get(url + "/healthz"))
        assert body["ok"] and body["age_s"] <= 2.0
        t[0] += 10.0            # beat goes stale -> 503
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(url + "/healthz")
        assert ei.value.code == 503
        assert json.loads(ei.value.read())["ok"] is False
        # Terminal done beat: clean completion is healthy forever.
        write_beat(hb, 0, epoch=0, n=2, clock=lambda: t[0], done=True)
        t[0] += 100.0
        assert json.loads(_get(url + "/healthz"))["done"] is True


def test_tail_trace_tolerates_live_torn_write(tmp_path):
    path = str(tmp_path / "x.jsonl")
    with open(path, "w") as f:
        for i in range(100):
            f.write(json.dumps({"kind": "event", "name": "e",
                                "t": i, "fields": {"i": i}}) + "\n")
        f.write('{"kind": "ev')  # live writer mid-flush
    recs = tail_trace(path, 5)
    assert [r["fields"]["i"] for r in recs] == [95, 96, 97, 98, 99]
    assert tail_trace(path, 0) == []
    assert len(tail_trace(path, 1000)) == 100
    evs = tail_trace(path, 10, kinds=("span",))
    assert evs == []


def test_scrape_under_concurrent_writes_no_torn_lines():
    """The satellite stress test: trainer/serving-like threads hammer
    the registry while the server is scraped; every scrape must parse
    under the strict parser (no torn lines) and the loop must finish
    (no deadlock between the scrape snapshot and the registry lock)."""
    reg = MetricsRegistry()
    stop = threading.Event()

    def writer(k):
        while not stop.is_set():
            reg.counter("w.requests").inc(status=f"s{k}")
            reg.histogram("w.lat_s").observe(0.01 * (k + 1), kind=f"k{k}")
            reg.gauge("w.depth").set(k, lane=str(k))

    threads = [threading.Thread(target=writer, args=(k,), daemon=True)
               for k in range(3)]
    with TelemetryServer(reg) as srv:
        for th in threads:
            th.start()
        t0 = time.monotonic()
        try:
            for _ in range(30):
                parse_exposition(_get(srv.url + "/metrics"))
        finally:
            stop.set()
            for th in threads:
                th.join(timeout=5.0)
        assert time.monotonic() - t0 < 60.0, "scrape loop crawled"


# ------------------------------------------------------- federation


def test_cluster_federation_merges_hosts_and_drops_dead_peer(tmp_path):
    cdir = str(tmp_path / "coord")
    r0, r1 = MetricsRegistry(), MetricsRegistry()
    r0.counter("serving.requests").inc(3, status="ok")
    r1.counter("serving.requests").inc(5, status="ok")
    r1.gauge("only.on.one").set(7)
    with TelemetryServer(r0, cluster_dir=cdir, host_id=0) as s0, \
            TelemetryServer(r1, cluster_dir=cdir, host_id=1):
        text = _get(s0.url + "/metrics/cluster")
        fams = parse_exposition(text)
        sam = fams["serving_requests"]["samples"]
        assert ("serving_requests", {"host": "0", "status": "ok"},
                "3.0") in sam
        assert ("serving_requests", {"host": "1", "status": "ok"},
                "5.0") in sam
        up = dict(((lab["host"], v) for _, lab, v in
                   fams["cluster_scrape_up"]["samples"]))
        assert up == {"0": "1", "1": "1"}
        # A published-but-dead peer drops out instead of failing the
        # scrape.
        with open(os.path.join(cdir, "telemetry", "host7.addr"),
                  "w") as f:
            json.dump({"host": 7, "addr": "127.0.0.1:9"}, f)
        fams = parse_exposition(_get(s0.url + "/metrics/cluster"))
        up = dict(((lab["host"], v) for _, lab, v in
                   fams["cluster_scrape_up"]["samples"]))
        assert up["7"] == "0"
        assert not any(lab.get("host") == "7"
                       for _, lab, _ in
                       fams["serving_requests"]["samples"])
    # Clean stop unpublishes.
    assert not os.path.exists(os.path.join(cdir, "telemetry",
                                           "host0.addr"))


def test_merge_expositions_groups_families():
    a = ("# HELP m total\n# TYPE m counter\nm 1.0\n"
         "# TYPE h histogram\nh_bucket{le=\"+Inf\"} 1\nh_sum 0.5\n"
         "h_count 1\n")
    b = "# TYPE m counter\nm{x=\"y\"} 2.0\n"
    merged = merge_expositions({0: a, 1: b, 2: None})
    fams = parse_exposition(merged)
    assert ("m", {"host": "0"}, "1.0") in fams["m"]["samples"]
    assert ("m", {"host": "1", "x": "y"}, "2.0") in fams["m"]["samples"]
    assert ("h_bucket", {"host": "0", "le": "+Inf"}, "1") in \
        fams["h"]["samples"]


# -------------------------------------- the acceptance integration


@pytest.mark.slow
def test_live_plane_end_to_end_engine_healthz_slo_waterfall(tmp_path):
    """The round-11 acceptance test: `obs.session(serve_port=0)` over
    a real ContinuousBatcher workload — /metrics parses clean with
    serving_* series, /healthz flips 200 -> 503 when the heartbeat
    goes stale, an injected latency spike trips the SloRule
    (slo.breach event + subscriber callback), and
    `obs_report.py --request` renders the request's
    submit -> admit -> chunks -> decode waterfall from the trace."""
    import jax

    path = str(tmp_path / "serve.jsonl")
    hb = str(tmp_path / "hb")
    clk = [0.0]
    hclk = [1000.0]
    params = tfm.init_params(jax.random.key(0), CFG)
    rng = np.random.default_rng(0)
    health = HeartbeatHealth(hb, host=0, window=2.0,
                             clock=lambda: hclk[0])
    rules = [SloRule("serving.request_s", percentile=0.95,
                     threshold=1.0, window_s=30.0)]
    hits = []
    with obs.session(trace_path=path, serve_port=0, health=health,
                     slo_rules=rules, slo_tick_s=30.0) as sess:
        sess.slo.subscribe(lambda rule, v: hits.append((rule.metric, v)))
        url = sess.server.url
        eng = dk.ContinuousBatcher(params, CFG, lanes=2, max_queue=4,
                                   prompt_buckets=(8,),
                                   prefill_chunk=8,
                                   clock=lambda: clk[0])
        # A long prompt (chunked admission), a short one, and a third
        # that has to QUEUE behind them (real queue wait).
        long_rid = eng.enqueue(
            rng.integers(0, 64, (20,)).astype(np.int32), 5)
        short_rid = eng.enqueue(
            rng.integers(0, 64, (4,)).astype(np.int32), 5)
        queued_rid = eng.enqueue(
            rng.integers(0, 64, (4,)).astype(np.int32), 5)
        assert eng.queued == 1
        while any(eng.poll(r) is None
                  for r in (long_rid, short_rid, queued_rid)):
            clk[0] += 2.0        # injected latency spike (engine clock)
            eng.step()
        res = {r: eng.take(r) for r in (long_rid, short_rid,
                                        queued_rid)}
        assert all(r.ok for r in res.values())

        # -- /metrics parses clean and carries serving_* series.
        write_beat(hb, 0, epoch=0, n=1, clock=lambda: hclk[0])
        fams = parse_exposition(_get(url + "/metrics"))
        assert any(f.startswith("serving_") for f in fams)
        assert fams["serving_requests"]["type"] == "counter"
        assert fams["serving_request_s"]["type"] == "histogram"
        assert "serving_ttft_s" in fams and "serving_tpot_s" in fams

        # -- /healthz: fresh 200 -> stale 503.
        assert json.loads(_get(url + "/healthz"))["ok"]
        hclk[0] += 30.0
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(url + "/healthz")
        assert ei.value.code == 503

        # -- the spike (every request took seconds of engine clock)
        # trips the rule on the next tick.
        sess.slo.tick()
        assert hits and hits[0][0] == "serving.request_s"
        assert sess.registry.counter("slo.breaches").value(
            metric="serving.request_s", q="p95") >= 1
        fams = parse_exposition(_get(url + "/metrics"))
        assert "slo_windowed" in fams and "slo_breaches" in fams

    # -- the trace carries the full per-request story.
    recs = read_trace(path)
    breach = [r for r in recs if r.get("kind") == "event"
              and r["name"] == "slo.breach"]
    assert breach and breach[0]["fields"]["metric"] == \
        "serving.request_s"

    wf = request_waterfall(recs, queued_rid)
    assert wf["found"] and wf["status"] == "ok"
    assert wf["queue_wait_s"] is not None and wf["queue_wait_s"] >= 0
    assert wf["ttft_s"] > 0 and wf["tokens"] == 5
    assert wf["gaps"] and wf["gaps"]["count"] >= 1
    text = render_waterfall(wf)
    assert "serving.emit" in text and "serving.finish" in text

    # The long prompt's waterfall shows its chunked-prefill admissions.
    wf_long = request_waterfall(recs, long_rid)
    assert wf_long["prefill_chunks"] >= 1
    assert wf_long["prompt_len"] == 20

    # -- the CLI renders the same waterfall.
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_report.py"),
         path, "--request", str(queued_rid)],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert r.returncode == 0, r.stderr
    assert f"request {queued_rid}" in r.stdout
    assert "queue wait" in r.stdout and "serving.finish" in r.stdout
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_report.py"),
         path, "--request", "99999"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert r.returncode == 1


def test_request_waterfall_speculative_and_unknown_id(tmp_path):
    """Per-request propagation covers the speculative engine too, and
    an unknown id reports found=False."""
    import jax

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32)
    draft = tfm.TransformerConfig(vocab_size=64, d_model=16, n_heads=2,
                                  n_layers=1, d_ff=32, max_len=32)
    path = str(tmp_path / "spec.jsonl")
    with obs.session(trace_path=path):
        eng = dk.SpeculativeBatcher(
            tfm.init_params(jax.random.key(0), cfg),
            tfm.init_params(jax.random.key(1), draft),
            cfg, draft, lanes=2, n_draft=2, max_queue=2)
        rid = eng.enqueue(np.arange(4, dtype=np.int32), 6)
        while eng.poll(rid) is None:
            eng.step()
        assert eng.take(rid).ok
    recs = read_trace(path)
    wf = request_waterfall(recs, rid)
    assert wf["found"] and wf["status"] == "ok" and wf["tokens"] == 6
    names = [s["name"] for s in wf["stages"]]
    assert "serving.admit" in names and "serving.finish" in names
    assert not request_waterfall(recs, 12345)["found"]
