"""Test harness: force an 8-device CPU mesh (SURVEY.md §4).

The JAX analogue of the reference testing its socket protocol on Spark
``local[N]``: ``--xla_force_host_platform_device_count=8`` gives eight
CPU devices in one process, so every pjit/shard_map collective path runs
for real without TPU hardware.

The axon sitecustomize imports jax at interpreter start with
JAX_PLATFORMS=axon, so flipping the env var here is too late; instead we
switch the platform through jax.config before any backend is
initialized (verified: works as long as jax.devices() hasn't run yet).
"""

import os

os.environ["KERAS_BACKEND"] = "jax"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
# Run the WHOLE tier-1 suite under the lock-order sanitizer
# (utils/locks.py): every TracedLock/TracedRLock the production code
# constructs is instrumented, lock-order inversions / double-acquires
# / callbacks-under-lock raise at the offending site, and the autouse
# fixture below fails any test that recorded a violation.  Set before
# anything imports distkeras_tpu (the env is read at locks import);
# the driver can override with DKT_LOCK_SANITIZER=0.
os.environ.setdefault("DKT_LOCK_SANITIZER", "1")

import jax

if os.environ.get("DKT_TEST_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

# Re-exported so tests keep importing them from conftest; helpers.py is
# the conftest-free home (subprocess tests import it without triggering
# the env mutation above).
from helpers import make_blobs, make_mlp  # noqa: F401


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 test devices, got {devs}"
    return devs


@pytest.fixture()
def rng():
    return np.random.default_rng(0)


@pytest.fixture()
def blobs():
    return make_blobs()


@pytest.fixture()
def mlp():
    return make_mlp()


# ---------------------------------------------------------------- markers
# Suite gating (SURVEY.md §4 "do better, cheaply"): `pytest -m "not
# slow"` is the fast gate (~4-5 min on one CPU core, >= 1 test per
# subsystem); the full suite (~25 min) stays the merge gate.  The SLOW
# set was measured with `pytest --durations=0` (call time >= 4 s on one
# core); refresh it the same way when tests move.  Deliberate
# exceptions when refreshing: test_sharded_decode::
# test_generate_sampled_tp_sharded_matches_single stays UNmarked even
# though it exceeds the threshold — it is the fast gate's one
# sharded-decode representative (the README promises the gate covers
# every subsystem) — and test_zero1::test_adag_zero1_matches_replicated
# / test_zero1::test_lm_zero1_matches_dp stay UNmarked as the fast
# gate's ZeRO-1 parity representatives for the two trainer families
# (the sharded-update acceptance contract).  MULTIPROCESS tests
# spawn OS subprocesses (multi-host runtime, crash recovery, the driver
# dryrun) — they are also slow, and worth selecting on their own when
# debugging the distributed runtime: `pytest -m multiprocess`.

MULTIPROCESS = {
    "test_checkpoint::test_sigkill_midrun_then_resume_matches_straight",
    "test_deploy::test_four_process_smoke",
    "test_deploy::test_two_process_adag_matches_single_process",
    "test_deploy::test_two_process_checkpoint_save_and_resume",
    "test_deploy::test_two_process_device_data_adag_matches_single",
    "test_deploy::test_two_process_downpour_matches_single_process",
    "test_deploy::test_two_process_eval_dataset_matches_single",
    "test_deploy::test_two_process_lm_trainer_matches_single_process",
    "test_deploy::test_two_process_model_axis_crosses_boundary",
    "test_deploy::test_two_process_packed_training_matches_single",
    "test_zoo_and_entry::test_graft_entry_multichip",
}

SLOW = MULTIPROCESS | {
    "test_serving::test_engine_fuzz_schedule_matches_solo",
    "test_serving::test_per_request_fuzz_schedule_matches_solo",
    "test_serving::test_staggered_admission_and_lane_reuse",
    "test_generate::test_beam_prompt_cache_matches_full_prompt",
    "test_generate::test_beam_ancestry_equals_physical_reorder",
    "test_generate::test_prompt_cache_matches_full_prompt",
    "test_lm_trainer::test_ema_resume_matches_straight_run",
    "test_lora::test_lora_checkpoint_resume_matches_straight",
    "test_lora::test_lora_merged_serves_speculatively",
    "test_lora::test_lora_grad_accum_matches_large_batch",
    "test_lora::test_merged_model_serves",
    "test_lora::test_zero_init_merge_is_identity",
    "test_lora::test_lora_composes_with_tp_mesh_and_segments",
    "test_lora::test_finetune_trains_adapters_and_freezes_base",
    "test_packing::test_packed_forward_equals_separate_docs",
    "test_packing::test_packed_forward_ring_mesh_matches_default",
    "test_packing::test_packed_forward_pipeline_matches_default",
    "test_packing::test_lm_trainer_packed_ring_mesh",
    "test_packing::test_lm_trainer_packed_pipeline_mesh",
    "test_packing::test_remat_composes_with_segments",
    "test_packing::test_pallas_interpret_segments_fwd_bwd",
    "test_packing::test_lm_trainer_packed_tp_fsdp_mesh",
    "test_packing::test_packed_loss_equals_weighted_separate_losses",
    "test_packing::test_lm_trainer_packed_end_to_end",
    "test_packing::test_flash_fallback_segments_grads_match_naive",
    "test_sharded_decode::test_speculative_tp_sharded_matches_single",
    "test_speculative::test_decode_chunk_matches_decode_step",
    "test_speculative::test_eos_matches_generate",
    "test_speculative::test_eos_stops_rows_early",
    "test_speculative::test_decode_chunk_per_row_offsets",
    "test_speculative::test_greedy_matches_generate",
    "test_speculative::test_greedy_rope_gqa_matches_generate",
    "test_speculative::test_greedy_moe_matches_generate",
    "test_speculative::test_nonuniform_acceptance_rows_finish_cleanly",
    "test_speculative::test_perfect_draft_accepts_everything",
    "test_speculative::test_quantized_target_matches_quantized_generate",
    "test_speculative::test_sampled_matches_target_distribution",
    "test_speculative::test_sampled_deterministic_per_key",
    "test_attention::test_flash_attention_window_grads_fallback",
    "test_attention::test_pallas_window_backward_interpret",
    "test_attention::test_pallas_window_banded_grid_asymmetric_blocks",
    "test_eval_hook::test_perplexity_evaluator_matches_trainer_eval",
    "test_fsdp::test_lm_fsdp_checkpoint_resume",
    "test_fsdp::test_lm_fsdp_composes_with_tp",
    "test_fsdp::test_lm_fsdp_matches_dp",
    "test_fsdp::test_lm_fsdp_shards_param_memory",
    "test_generate::test_beam_eos_freezes_score",
    "test_generate::test_beam_frozen_score_is_length_invariant",
    "test_generate::test_beam_length_penalty",
    "test_generate::test_beam_length_penalty_frozen_lengths",
    "test_generate::test_beam_prefill_matches_sequential",
    "test_generate::test_beam_scores_match_rescoring_and_beat_greedy",
    "test_generate::test_beam_search_windowed_cfg",
    "test_generate::test_beam_validation_and_quantized",
    "test_generate::test_beam_width_1_equals_greedy",
    "test_generate::test_cached_decode_matches_full_forward",
    "test_generate::test_generate_greedy_matches_argmax_rollout",
    "test_generate::test_generate_min_p_sampling",
    "test_generate::test_generate_ragged_batch_matches_individual",
    "test_generate::test_generate_rope_greedy_matches_rollout",
    "test_generate::test_generate_sampling_deterministic_per_key",
    "test_generate::test_generate_temperature_needs_key",
    "test_generate::test_generate_tiny_top_p_equals_greedy",
    "test_generate::test_generate_topk1_equals_greedy",
    "test_generate::test_gqa_cache_is_smaller_and_decode_matches",
    "test_generate::test_moe_capacity_vs_dense_divergence_bounded",
    "test_generate::test_prefill_eos_matches_sequential",
    "test_generate::test_prefill_matches_sequential_generate",
    "test_generate::test_prefill_matches_sequential_gqa",
    "test_generate::test_prefill_moe_matches_sequential",
    "test_generate::test_prefill_sampling_matches_sequential",
    "test_generate::test_quantized_decode_matches_f32_greedy",
    "test_generate::test_rolling_decode_long_prompt_sequential_fallback",
    "test_generate::test_rolling_decode_matches_large_cache",
    "test_generate::test_rolling_decode_quantized",
    "test_generate::test_rolling_decode_sampling_and_eos",
    "test_lm_trainer::test_lm_dropout_resume_matches_straight",
    "test_lm_trainer::test_lm_dropout_trains_and_is_reproducible",
    "test_lm_trainer::test_lm_eval_moe_excludes_aux",
    "test_lm_trainer::test_lm_eval_perplexity",
    "test_lm_trainer::test_lm_grad_accum_matches_large_batch",
    "test_lm_trainer::test_lm_grad_clip",
    "test_lm_trainer::test_lm_profile_dir_writes_trace",
    "test_lm_trainer::test_lm_trainer_accepts_optax_optimizers",
    "test_lm_trainer::test_lm_trainer_dp",
    "test_lm_trainer::test_lm_trainer_pp_ep",
    "test_lm_trainer::test_lm_trainer_pp_sp",
    "test_lm_trainer::test_lm_trainer_resume_matches_straight_run",
    "test_lm_trainer::test_lm_trainer_shuffle_deterministic",
    "test_lm_trainer::test_lm_trainer_tp_sp",
    "test_lm_trainer::test_lm_weight_decay_masks_norm_scales",
    "test_pipeline::test_pipelined_moe_aux_flows_into_loss",
    "test_pipeline::test_pipelined_moe_with_seq_axis_aux_consistent",
    "test_pipeline::test_pipelined_ring_attention_matches_single",
    "test_pipeline::test_pipelined_transformer_matches_single",
    "test_pipeline::test_pipelined_transformer_trains",
    "test_remat::test_remat_policy_matches_plain_remat",
    "test_remat::test_transformer_remat_matches_plain",
    "test_rnn::test_matches_keras_last_state",
    "test_rnn::test_serialization_round_trip",
    "test_rnn::test_trains_under_single_trainer",
    "test_schedules::test_schedule_through_lm_trainer",
    "test_serialization::test_save_load_lm_round_trip",
    "test_sharded_decode::test_beam_search_fsdp_scattered_matches_single",
    "test_sharded_decode::test_beam_search_tp_sharded_matches_single",
    "test_sharded_decode::test_generate_greedy_fsdp_scattered_matches_single",
    "test_sharded_decode::test_generate_greedy_tp_sharded_matches_single",
    "test_tokenizer::test_tokenizer_feeds_lm_trainer",
    "test_transformer::test_attention_window_composes_with_moe",
    "test_transformer::test_attention_window_lm_trainer_ring",
    "test_transformer::test_attention_window_matches_manual_mask",
    "test_transformer::test_attention_window_trains",
    "test_transformer::test_chunked_ce_handles_nondivisible_token_count",
    "test_transformer::test_chunked_ce_loss_and_grads_match_full",
    "test_transformer::test_chunked_ce_pipelined_trains_via_lm_trainer",
    "test_transformer::test_chunked_ce_trains",
    "test_transformer::test_dropout_deterministic_per_key_and_off_without_rng",
    "test_transformer::test_dropout_training_learns",
    "test_transformer::test_expert_parallel_matches_single",
    "test_transformer::test_gqa_shapes_and_learning",
    "test_transformer::test_moe_train_step_learns",
    "test_transformer::test_rope_forward_and_learning",
    "test_transformer::test_rope_params_have_no_pos_table",
    "test_transformer::test_rope_trains_past_max_len",
    "test_transformer::test_train_step_learns_copy_task",
    "test_transformer::test_z_loss_chunked_matches_full",
    "test_transformer::test_z_loss_trains_and_shrinks_normalizer",
    "test_zoo_and_entry::test_cifar_cnn_forward",
    "test_zoo_and_entry::test_graft_entry_single",
    "test_zero1::test_lm_zero1_checkpoint_resume",
    "test_zero1::test_lm_zero1_clip_ema_matches_dp",
    "test_zero1::test_lm_zero1_grad_accum_matches_dp",
    # Exchange-layer LM legs: the fast gate keeps the ADAG family's
    # full variant matrix (convergence, determinism, residual
    # diagnostics, pickle checkpoint resume, Supervisor bit-for-bit);
    # the LM spellings — same merge rules on the bigger model, whose
    # ~21-program compiles dominate wall time — run in the merge gate.
    "test_exchange::test_lm_int8ef_converges_and_is_deterministic",
    "test_exchange::test_lm_sync_every_1_and_4_converge",
    "test_exchange::test_lm_adasum_and_zero1_int8_converge",
    "test_exchange::test_lm_int8ef_checkpoint_resume",
    "test_exchange::test_lm_zero1_int8_shards_opt_memory",
    # The 2-process coordinated-restart smoke joins its full-ladder
    # sibling in the merge gate: the fast gate keeps every in-process
    # cluster protocol test (driver restart protocol, flap ladder,
    # watchdog, torn-checkpoint selection), and the tier-1 wall-clock
    # budget goes to the exchange-layer matrix instead of a second
    # spawned-subprocess collective run.
    "test_cluster::test_two_process_kill_one_host_coordinated_restart",
    # Round-11 fast-gate rebalance: the round-10 serving fast path
    # grew the gate past its wall clock (measured 1029 s against the
    # 870 s tier-1 budget on the 8-CPU harness, before this round
    # added anything), so the heaviest SECOND spellings of already-
    # fast-covered contracts move to the merge gate.  What stays fast
    # per subsystem: beam — width-1/scores/eos/prefill/length-penalty/
    # ancestry + the kv_int8 rolling-beam parity; speculative — the
    # whole solo-fn matrix, the rolling batcher parity + draft-fault
    # chaos tests, and the pooled engine parity; chunked prefill —
    # greedy parity + the 1k-prompt interleave bound; device_data —
    # the ADAG family matrix (test_device_data.py); TP decode — the
    # prompt-cache decode test; compile counts — the graph-lint CLI
    # and in-process census/parity stay, the full recorded-session
    # guard subprocess (61 s) runs at merge (and in this round's
    # obs_live work the new session asserts its zero-compile claim
    # in-session, so a regression still fails the guard itself).
    "test_budget_guards::test_compile_count_guard_passes",
    "test_lm_trainer::test_lm_device_data_matches_streaming",
    "test_lm_trainer::test_ema_decay_matches_manual_shadow",
    "test_generate::test_beam_windowed_ancestry_equals_physical",
    "test_generate::test_rolling_beam_matches_large_cache",
    "test_serving::test_speculative_batcher_matches_solo",
    "test_serving::test_speculative_batcher_sampled_matches_solo",
    "test_serving_fastpath::test_chunked_prefill_sampled_and_tail_overlap",
    "test_serving_fastpath::test_elastic_chunked_pool_enqueue",
    "test_sharded_decode::test_beam_prompt_cache_under_tp",
    "test_speculative::test_windowed_small_ring_matches_big_cache_sampled",
    "test_obs_live::test_request_waterfall_speculative_and_unknown_id",
    # Round-12 (ZeRO-2/3): the fast gate keeps one parity test per
    # stage per family (ADAG zero2+zero3, LM zero2+zero3), the
    # per-device-bytes acceptance assertions, the Supervisor
    # bit-for-bit chaos leg (MLP-fast) and the codec-rules exchange;
    # the heavier SECOND spellings of already-covered contracts — the
    # stage-3 checkpoint round-trips (both backends), the
    # clip+EMA/grad_accum/device_data/eval stage-3 variants — run in
    # the merge gate to hold the tier-1 wall clock (the ISSUE's
    # declared escape hatch for exactly these legs).
    "test_zero_stages::test_lm_zero3_checkpoint_resume",
    "test_zero_stages::test_lm_zero3_grad_accum_matches_dp",
    "test_zero_stages::test_lm_zero3_clip_ema_matches_dp",
    "test_zero_stages::test_lm_zero3_device_data_matches_streaming",
    "test_zero_stages::test_lm_zero3_eval_matches_dp",
    # Round-20 rebalance (contract-lint gate): the gate itself is
    # pure-AST and cheap (~5 s for tests/test_contract_lint.py +
    # the schema-equality guard), but the suite had crept to 896 s
    # measured against the 870 s tier-1 wall, so the heaviest SECOND
    # spellings of already-fast-covered contracts move to the merge
    # gate.  What stays fast per subsystem: sharded serving — the
    # residency-digest sharded-vs-solo parity, elastic-cb scaling,
    # FSDP-plan serving, router-over-sharded-replica, prefix-pool and
    # cb-sampled bit-exact legs; paged serving — chunked-prefill /
    # sampled-per-request / CoW-fork / stem-sharing / admission-
    # tolerance parities; disagg — greedy+role-exclusivity, seeded
    # sampling, chunked prefill, export/import refcounts, cross-hop
    # streaming, prefill-failure fallback; prefix pool — the engine
    # parity + zero-prefix-work and speculative-pool greedy legs;
    # bench contract — the paged and load/elastic/spec rows.  The
    # moved tests re-spell those same contracts on a second axis
    # (kv_int8 x prefill-agreement, sampled x sharded-paged,
    # speculative x sharded, staggered-lane x paged, bench rows whose
    # underlying router/disagg paths have dedicated fast tests) and
    # run in the full merge suite.
    "test_serving_sharded::test_sharded_paged_greedy_and_sampled_bit_exact",
    "test_serving_sharded::test_sharded_speculative_greedy_parity",
    "test_serving_paged::test_kv_int8_prefill_engine_agreement",
    "test_serving_paged::test_paged_greedy_parity_staggered_and_lane_reuse",
    "test_serving_fastpath::test_prefix_pool_sampled_kv_int8_and_lane_reuse",
    "test_disagg::test_disagg_parity_kv_int8",
    "test_bench_contract::test_bench_router_affinity_row",
    "test_bench_contract::test_bench_router_disagg_row",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        key = f"{item.module.__name__}::{item.originalname}"
        if key in SLOW:
            item.add_marker(pytest.mark.slow)
        if key in MULTIPROCESS:
            item.add_marker(pytest.mark.multiprocess)


# Every live XLA-CPU executable holds dozens-to-hundreds of LLVM-JIT
# mmap sections, and jax's global caches keep every test's programs
# alive for the whole run — a serial full run used to hit the kernel's
# vm.max_map_count wall (~65k) at ~85% and SIGSEGV inside
# backend_compile (root cause + repro: docs/xla_cpu_compile_crash.md).
# Dropping the caches every 50 tests releases the maps (measured: map
# count pinned flat vs linear growth to the wall) at the price of
# recompiles across the boundary.  The xdist gate (-n 4) never gets
# near the wall; this makes plain serial runs safe too.
_TESTS_PER_CACHE_DROP = 50
_test_tally = {"n": 0}


@pytest.fixture(autouse=True)
def _bound_llvm_jit_maps():
    yield
    _test_tally["n"] += 1
    if _test_tally["n"] % _TESTS_PER_CACHE_DROP == 0:
        jax.clear_caches()


# ------------------------------------------------ concurrency gate
# (round 12)  Two autouse fixtures make thread discipline a tier-1
# property of EVERY test, not just the ones that think about threads:
#
# - _lock_sanitizer_gate: any lock-order violation the runtime
#   sanitizer recorded during the test fails it — even when the
#   raising thread swallowed the exception (SLO ticker, HTTP handler
#   threads catch broadly).  Tests that deliberately provoke
#   violations (tests/test_locks.py positives) opt out with
#   @pytest.mark.expected_lock_violations.
# - _no_thread_leaks: a test must not leave its own background
#   threads running (the PR-8 EADDRINUSE class: a leaked
#   dkt-telemetry thread holds the port for the next test).  All
#   subsystem threads are dkt-named; a gc pass first lets abandoned
#   Prefetcher/engine objects run their __del__ cleanup, then
#   stragglers get a short grace to finish stopping.  Opt out with
#   @pytest.mark.bg_threads for tests that intentionally leave
#   background work (e.g. a deliberately hung device probe).

import sys as _sys


def _locks_module():
    return _sys.modules.get("distkeras_tpu.utils.locks")


@pytest.fixture(autouse=True)
def _lock_sanitizer_gate(request):
    locks = _locks_module()
    before = locks.violation_count() if locks is not None else 0
    yield
    if request.node.get_closest_marker("expected_lock_violations"):
        return
    locks = _locks_module()
    if locks is None:
        return
    new = locks.violations()[before:]
    assert not new, (
        "the lock sanitizer recorded violation(s) during this test:\n"
        + "\n".join(v.format() for v in new))


@pytest.fixture(autouse=True)
def _no_thread_leaks(request):
    import threading as _threading

    before = set(_threading.enumerate())
    yield
    if request.node.get_closest_marker("bg_threads"):
        return

    def leaked():
        return [t for t in _threading.enumerate()
                if t.is_alive() and t not in before
                and t.name.startswith("dkt-")]

    left = leaked()
    if left:
        import gc
        import time as _time

        gc.collect()   # abandoned Prefetcher/session: __del__ stops it
        deadline = _time.monotonic() + 2.0
        while leaked() and _time.monotonic() < deadline:
            _time.sleep(0.05)
        left = leaked()
    assert not left, (
        f"test leaked live background thread(s): "
        f"{sorted(t.name for t in left)} — stop/close them, or mark "
        "the test @pytest.mark.bg_threads if the background work is "
        "intentional")
