"""Test harness: force an 8-device CPU mesh (SURVEY.md §4).

The JAX analogue of the reference testing its socket protocol on Spark
``local[N]``: ``--xla_force_host_platform_device_count=8`` gives eight
CPU devices in one process, so every pjit/shard_map collective path runs
for real without TPU hardware.

The axon sitecustomize imports jax at interpreter start with
JAX_PLATFORMS=axon, so flipping the env var here is too late; instead we
switch the platform through jax.config before any backend is
initialized (verified: works as long as jax.devices() hasn't run yet).
"""

import os

os.environ["KERAS_BACKEND"] = "jax"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import jax

if os.environ.get("DKT_TEST_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 test devices, got {devs}"
    return devs


@pytest.fixture()
def rng():
    return np.random.default_rng(0)


def make_blobs(n=512, dim=16, classes=4, seed=0):
    """Linearly separable gaussian blobs — learnable in a few steps."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(0, 4.0, (classes, dim))
    labels = rng.integers(0, classes, n)
    feats = centers[labels] + rng.normal(0, 0.5, (n, dim))
    return feats.astype(np.float32), labels.astype(np.int64)


@pytest.fixture()
def blobs():
    return make_blobs()


def make_mlp(dim=16, classes=4, hidden=32, seed=0):
    import keras

    keras.utils.set_random_seed(seed)
    return keras.Sequential([
        keras.Input((dim,)),
        keras.layers.Dense(hidden, activation="relu"),
        keras.layers.Dense(classes),
    ])


@pytest.fixture()
def mlp():
    return make_mlp()
