"""Test harness: force an 8-device CPU mesh (SURVEY.md §4).

The JAX analogue of the reference testing its socket protocol on Spark
``local[N]``: ``--xla_force_host_platform_device_count=8`` gives eight
CPU devices in one process, so every pjit/shard_map collective path runs
for real without TPU hardware.

The axon sitecustomize imports jax at interpreter start with
JAX_PLATFORMS=axon, so flipping the env var here is too late; instead we
switch the platform through jax.config before any backend is
initialized (verified: works as long as jax.devices() hasn't run yet).
"""

import os

os.environ["KERAS_BACKEND"] = "jax"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import jax

if os.environ.get("DKT_TEST_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

# Re-exported so tests keep importing them from conftest; helpers.py is
# the conftest-free home (subprocess tests import it without triggering
# the env mutation above).
from helpers import make_blobs, make_mlp  # noqa: F401


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 test devices, got {devs}"
    return devs


@pytest.fixture()
def rng():
    return np.random.default_rng(0)


@pytest.fixture()
def blobs():
    return make_blobs()


@pytest.fixture()
def mlp():
    return make_mlp()
