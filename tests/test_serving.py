"""Continuous batching: every request matches its solo generate() run,
under staggered admission and lane reuse."""

import jax
import numpy as np
import pytest

from distkeras_tpu.models import transformer as tfm
from distkeras_tpu.models.generate import generate
from distkeras_tpu.serving import ContinuousBatcher


CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=32, rope=True)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(jax.random.key(0), CFG)


def run_to_done(eng, lane):
    while lane in eng.running():
        eng.step()
    return eng.drain(lane)


def solo(params, prompt, n, **kw):
    return np.asarray(generate(params, np.asarray(prompt)[None], CFG,
                               n, **kw))[0]


def test_single_request_matches_generate(params, rng):
    eng = ContinuousBatcher(params, CFG, lanes=4)
    prompt = rng.integers(0, 64, (5,)).astype(np.int32)
    lane = eng.submit(prompt, 8)
    out = run_to_done(eng, lane)
    np.testing.assert_array_equal(out, solo(params, prompt, 8))


def test_sampled_request_matches_generate(params, rng):
    eng = ContinuousBatcher(params, CFG, lanes=2, temperature=0.8,
                            top_k=8)
    prompt = rng.integers(0, 64, (4,)).astype(np.int32)
    k = jax.random.key(11)
    lane = eng.submit(prompt, 6, key=k)
    out = run_to_done(eng, lane)
    np.testing.assert_array_equal(
        out, solo(params, prompt, 6, temperature=0.8, top_k=8, key=k))


def test_staggered_admission_and_lane_reuse(params, rng):
    """Requests admitted mid-flight (and into a reused lane) still
    match their solo runs — lanes are independent."""
    eng = ContinuousBatcher(params, CFG, lanes=2)
    pa = rng.integers(0, 64, (6,)).astype(np.int32)
    pb = rng.integers(0, 64, (3,)).astype(np.int32)
    pc = rng.integers(0, 64, (9,)).astype(np.int32)

    la = eng.submit(pa, 10)
    for _ in range(3):
        eng.step()                       # A decodes alone for 3 steps
    lb = eng.submit(pb, 5)               # B admitted mid-flight
    out_a = run_to_done(eng, la)
    out_b = run_to_done(eng, lb)
    lc = eng.submit(pc, 4)               # reuses a freed lane
    out_c = run_to_done(eng, lc)

    np.testing.assert_array_equal(out_a, solo(params, pa, 10))
    np.testing.assert_array_equal(out_b, solo(params, pb, 5))
    np.testing.assert_array_equal(out_c, solo(params, pc, 4))
    assert lc in (la, lb)                # a lane was actually reused


def test_eos_and_one_token_prompt(params, rng):
    eng = ContinuousBatcher(params, CFG, lanes=2, eos_token=7)
    p1 = rng.integers(0, 64, (1,)).astype(np.int32)
    lane = eng.submit(p1, 12)
    out = run_to_done(eng, lane)
    ref = solo(params, p1, 12, eos_token=7)
    # The engine stops at eos; generate() sticky-fills to full length.
    np.testing.assert_array_equal(out, ref[:len(out)])
    if len(out) < len(ref):
        assert out[-1] == 7 and (ref[len(out):] == 7).all()


def test_capacity_and_validation(params, rng):
    eng = ContinuousBatcher(params, CFG, lanes=1)
    p = rng.integers(0, 64, (4,)).astype(np.int32)
    assert eng.submit(p, 4) == 0
    assert eng.submit(p, 4) is None      # full
    with pytest.raises(ValueError, match="still decoding"):
        eng.drain(0)
    run_to_done(eng, 0)
    assert eng.submit(p, 4) == 0              # drained lane is reusable
    with pytest.raises(ValueError, match="max_len"):
        ContinuousBatcher(params, CFG, lanes=1).submit(p, 40)
    with pytest.raises(ValueError, match="key iff"):
        eng.submit(p, 4, key=jax.random.key(0))
    with pytest.raises(ValueError, match="temperature > 0"):
        ContinuousBatcher(params, CFG, top_k=5)


def test_quantized_weights_match_quantized_generate(params, rng):
    """int8 weight trees serve through the engine (the chunk path
    dequantizes per read) and match their solo quantized run."""
    from distkeras_tpu.models.quant import quantize_params

    qp = quantize_params(params)
    eng = ContinuousBatcher(qp, CFG, lanes=2)
    prompt = rng.integers(0, 64, (5,)).astype(np.int32)
    lane = eng.submit(prompt, 6)
    out = run_to_done(eng, lane)
    np.testing.assert_array_equal(out, solo(qp, prompt, 6))


def test_engine_shared_prefix_matches_generate_prompt_cache(params, rng):
    """An engine built over a shared prefilled prefix emits exactly
    what generate(prompt_cache=...) emits per request — including for a
    lane's SECOND occupant (the admission reseed from the prefix)."""
    from distkeras_tpu.models.generate import prefill

    prefix = rng.integers(0, 64, (6,)).astype(np.int32)
    cache, _ = prefill(params, prefix[None], CFG, last_logits=False)
    eng = ContinuousBatcher(params, CFG, lanes=1,
                            prompt_cache=(cache, 6))
    for tail_len in (3, 1):    # second pass reuses lane 0; tail_len 1
        #                          pins the no-admission reseed path
        tail = rng.integers(0, 64, (tail_len,)).astype(np.int32)
        lane = eng.submit(tail, 5)
        out = run_to_done(eng, lane)
        ref = np.asarray(generate(params, tail[None], CFG, 5,
                                  prompt_cache=(cache, 6)))[0]
        np.testing.assert_array_equal(out, ref)
    with pytest.raises(ValueError, match="exceeds"):
        eng.submit(rng.integers(0, 64, (20,)).astype(np.int32), 10)
    with pytest.raises(ValueError, match="batch 1"):
        big = {k: np.repeat(np.asarray(v), 2, axis=1)
               for k, v in cache.items()}
        ContinuousBatcher(params, CFG, prompt_cache=(big, 6))


def test_multi_token_step_matches_single_steps(params, rng):
    """step(n) emits exactly the tokens of n step(1) calls — greedy and
    sampled — including mid-window retirement truncation."""
    for kw in [{}, dict(temperature=0.8, top_k=8)]:
        key = jax.random.key(5) if kw else None
        prompts = [rng.integers(0, 64, (4,)).astype(np.int32)
                   for _ in range(2)]
        outs = {}
        for n in (1, 4):
            eng = ContinuousBatcher(params, CFG, lanes=2,
                                    eos_token=3, **kw)
            lanes = [eng.submit(p, 9, key=key) if kw else
                     eng.submit(p, 9) for p in prompts]
            while eng.running():
                eng.step(n)
            outs[n] = [eng.drain(l) for l in lanes]
        for a, b in zip(outs[1], outs[4]):
            np.testing.assert_array_equal(a, b)


def test_engine_fuzz_schedule_matches_solo(params, rng):
    """Property test: a randomized arrival/length/window schedule over
    few lanes still gives every request exactly its solo generate()
    output (with sticky-eos truncation)."""
    eng = ContinuousBatcher(params, CFG, lanes=3, eos_token=9)
    reqs = []            # (prompt, max_new)
    for _ in range(8):
        p = rng.integers(1, 12)
        reqs.append((rng.integers(0, 64, (p,)).astype(np.int32),
                     int(rng.integers(1, 32 - p))))
    pending = list(range(len(reqs)))
    lane_of, outs = {}, {}
    while len(outs) < len(reqs):
        while pending and eng.free_lanes():
            rid = pending.pop(0)
            lane_of[eng.submit(*reqs[rid])] = rid
        eng.step(int(rng.integers(1, 5)))
        for lane in list(lane_of):
            if lane not in eng.running():
                outs[lane_of.pop(lane)] = eng.drain(lane)
    for rid, (prompt, n) in enumerate(reqs):
        ref = solo(params, prompt, n, eos_token=9)
        out = outs[rid]
        np.testing.assert_array_equal(out, ref[:len(out)])
        # Truncation only ever drops sticky-eos fill.
        if len(out) < len(ref):
            assert out[-1] == 9 and (ref[len(out):] == 9).all()


def test_lane_pos_clamped_and_idle_engine_skips_device(params, rng):
    """Device-side invariants (advisor round-3): (a) per-lane positions
    never advance past max_len - 1 — free/done lanes keep decoding but
    their pos pins at the last slot instead of relying on
    dynamic_update_slice start-clamping; (b) an engine whose lanes are
    all empty/finished returns {} without a device round-trip; (c) a
    lane reused after a long over-decode run still matches solo."""
    eng = ContinuousBatcher(params, CFG, lanes=2)
    pa = rng.integers(0, 64, (4,)).astype(np.int32)
    la = eng.submit(pa, 3)
    # Over-step far past every budget: lane A finishes (done, undrained)
    # while lane B is free; both keep decoding until A retires.
    out = []
    while la in eng.running():
        out.extend(eng.step().get(la, []))
    np.testing.assert_array_equal(
        eng.drain(la), solo(params, pa, 3))
    # Idle engine: no lane can emit -> no device work, state untouched.
    pos_before = np.asarray(eng.pos)
    assert eng.step(4) == {}
    np.testing.assert_array_equal(np.asarray(eng.pos), pos_before)
    # Force many windows with one live lane so the OTHER (free) lane
    # over-decodes; its pos must pin at max_len - 1.
    lb = eng.submit(rng.integers(0, 64, (2,)).astype(np.int32),
                    CFG.max_len - 3)
    while lb in eng.running():
        eng.step(4)
    assert int(np.asarray(eng.pos).max()) <= CFG.max_len - 1
    # Lane 1 was never admitted and over-decoded the whole test: it
    # sits AT the clamp.  Readmit THAT lane (occupy lane 0 first —
    # submit picks the lowest free lane) and require solo parity.
    assert int(np.asarray(eng.pos)[1]) == CFG.max_len - 1
    eng.drain(lb)
    assert eng.submit(rng.integers(0, 64, (2,)).astype(np.int32),
                      2) == 0
    pc = rng.integers(0, 64, (5,)).astype(np.int32)
    lc = eng.submit(pc, 6)
    assert lc == 1
    np.testing.assert_array_equal(run_to_done(eng, lc),
                                  solo(params, pc, 6))


ROLL_CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                 n_layers=2, d_ff=64, max_len=12,
                                 rope=True, attention_window=5)


def test_rolling_engine_matches_rolling_generate(params, rng):
    """Windowed (rope + attention_window) engines run ROLLING lanes:
    each request decodes past max_len on the ring cache and must match
    its solo rolling generate() run exactly — staggered admission,
    lane reuse, and lanes mid-wrap while a fresh lane is admitted."""
    rparams = tfm.init_params(jax.random.key(3), ROLL_CFG)

    def rsolo(prompt, n, **kw):
        return np.asarray(generate(rparams, np.asarray(prompt)[None],
                                   ROLL_CFG, n, **kw))[0]

    eng = ContinuousBatcher(rparams, ROLL_CFG, lanes=2)
    pa = rng.integers(0, 64, (4,)).astype(np.int32)
    pb = rng.integers(0, 64, (6,)).astype(np.int32)
    pc = rng.integers(0, 64, (3,)).astype(np.int32)
    la = eng.submit(pa, 30)              # 4 + 30 = 34 >> 12: wraps
    for _ in range(10):                  # A rolls past the ring alone
        eng.step()
    lb = eng.submit(pb, 20)              # admitted mid-wrap of A
    out_a = run_to_done(eng, la)
    out_b = run_to_done(eng, lb)
    lc = eng.submit(pc, 25)              # reuses a freed, wrapped lane
    out_c = run_to_done(eng, lc)
    np.testing.assert_array_equal(out_a, rsolo(pa, 30))
    np.testing.assert_array_equal(out_b, rsolo(pb, 20))
    np.testing.assert_array_equal(out_c, rsolo(pc, 25))
    assert lc in (la, lb)


def test_rolling_engine_sampled_and_validation(params, rng):
    """Sampled rolling lanes match solo rolling generate with the same
    per-request key; windowed engines without rope are rejected, and
    rolling submit has no total-length cap while the prompt still must
    fit the admission buckets."""
    import dataclasses

    rparams = tfm.init_params(jax.random.key(4), ROLL_CFG)
    eng = ContinuousBatcher(rparams, ROLL_CFG, lanes=2,
                            temperature=0.8, top_k=8)
    p = rng.integers(0, 64, (4,)).astype(np.int32)
    k = jax.random.key(21)
    lane = eng.submit(p, 24, key=k)      # 4 + 24 = 28 > 12
    out = run_to_done(eng, lane)
    ref = np.asarray(generate(rparams, p[None], ROLL_CFG, 24,
                              temperature=0.8, top_k=8, key=k))[0]
    np.testing.assert_array_equal(out, ref)

    norope = dataclasses.replace(ROLL_CFG, rope=False)
    with pytest.raises(ValueError, match="rolling lanes"):
        ContinuousBatcher(tfm.init_params(jax.random.key(0), norope),
                          norope, lanes=1)
    # Prompt must fit the ring (admission chunk cannot wrap).
    with pytest.raises(ValueError, match="admission"):
        eng.submit(rng.integers(0, 64, (20,)).astype(np.int32), 4,
                   key=jax.random.key(1))


def test_kv_int8_engine_matches_sequential_generate(params, rng):
    """kv_int8 engines: every request matches its solo
    generate(kv_int8=True, use_prefill=False) run EXACTLY — both paths
    attend the already-quantized cache position by position (the
    admission chunk writes quantized K/V and its in-chunk attention
    reads them, same as the sequential step loop; prefill() would
    differ by quantization noise).  Staggered admission + lane reuse +
    a sampled request, plus the windowed/prefix validation edges."""
    eng = ContinuousBatcher(params, CFG, lanes=2, kv_int8=True)
    pa = rng.integers(0, 64, (6,)).astype(np.int32)
    pb = rng.integers(0, 64, (3,)).astype(np.int32)
    la = eng.submit(pa, 8)
    for _ in range(3):
        eng.step()
    lb = eng.submit(pb, 6)                  # admitted mid-flight
    out_a = run_to_done(eng, la)
    out_b = run_to_done(eng, lb)
    lc = eng.submit(pb, 4)                  # reused (quantized) lane
    out_c = run_to_done(eng, lc)
    for out, p, n in [(out_a, pa, 8), (out_b, pb, 6), (out_c, pb, 4)]:
        np.testing.assert_array_equal(
            out, solo(params, p, n, kv_int8=True, use_prefill=False))

    seng = ContinuousBatcher(params, CFG, lanes=1, kv_int8=True,
                             temperature=0.8, top_k=8)
    k = jax.random.key(31)
    lane = seng.submit(pa, 6, key=k)
    np.testing.assert_array_equal(
        run_to_done(seng, lane),
        solo(params, pa, 6, kv_int8=True, use_prefill=False,
             temperature=0.8, top_k=8, key=k))

    # Windowed engines take kv_int8 too since round 5 — positive
    # coverage in test_kv_int8_rolling_engine_matches_rolling_generate.
    # Prefix quantization must match the engine cache.
    from distkeras_tpu.models.generate import prefill

    fp_cache, _ = prefill(params, pa[None], CFG, last_logits=False)
    with pytest.raises(ValueError, match="quantization must match"):
        ContinuousBatcher(params, CFG, lanes=1, kv_int8=True,
                          prompt_cache=(fp_cache, 6))


def test_kv_int8_engine_shared_prefix(params, rng):
    """A kv_int8 engine over a kv_int8-prefilled shared prefix matches
    generate(prompt_cache=..., kv_int8=True) per request, including
    the lane-reuse reseed."""
    from distkeras_tpu.models.generate import prefill

    prefix = rng.integers(0, 64, (6,)).astype(np.int32)
    cache, _ = prefill(params, prefix[None], CFG, last_logits=False,
                       kv_int8=True)
    eng = ContinuousBatcher(params, CFG, lanes=1, kv_int8=True,
                            prompt_cache=(cache, 6))
    for tail_len in (3, 1):
        tail = rng.integers(0, 64, (tail_len,)).astype(np.int32)
        lane = eng.submit(tail, 5)
        out = run_to_done(eng, lane)
        ref = np.asarray(generate(params, tail[None], CFG, 5,
                                  prompt_cache=(cache, 6),
                                  kv_int8=True))[0]
        np.testing.assert_array_equal(out, ref)


def test_kv_int8_rolling_engine_matches_rolling_generate(rng):
    """kv_int8 on ROLLING ring lanes (round-5: serving.py's windowed x
    kv_int8 rejection deleted): every request decodes past max_len on
    the int8 ring cache and matches its solo sequential
    generate(kv_int8=True, use_prefill=False) run EXACTLY — admission
    chunk and decode loop both attend the already-quantized cache."""
    rparams = tfm.init_params(jax.random.key(5), ROLL_CFG)
    eng = ContinuousBatcher(rparams, ROLL_CFG, lanes=2, kv_int8=True)
    assert eng.kv_int8 and "k_scale" in eng.cache

    def rsolo(prompt, n):
        return np.asarray(generate(rparams, np.asarray(prompt)[None],
                                   ROLL_CFG, n, kv_int8=True,
                                   use_prefill=False))[0]

    pa = rng.integers(0, 64, (4,)).astype(np.int32)
    pb = rng.integers(0, 64, (6,)).astype(np.int32)
    la = eng.submit(pa, 30)              # 4 + 30 = 34 >> 12: wraps
    for _ in range(8):                   # A rolls ahead alone
        eng.step()
    lb = eng.submit(pb, 20)              # admitted mid-wrap of A
    out_a = run_to_done(eng, la)
    out_b = run_to_done(eng, lb)
    np.testing.assert_array_equal(out_a, rsolo(pa, 30))
    np.testing.assert_array_equal(out_b, rsolo(pb, 20))


def test_per_request_sampling_mixed_lanes(params, rng):
    """per_request_sampling=True: greedy and differently-parameterized
    sampled requests decode in ONE batch, each matching its solo
    generate() run exactly (the vectorized per-lane params select per
    row; no-op rows are bit-exact with the scalar path)."""
    eng = ContinuousBatcher(params, CFG, lanes=4,
                            per_request_sampling=True)
    pa, pb, pc, pd = (rng.integers(0, 64, (5,)).astype(np.int32)
                      for _ in range(4))
    ka, kc, kd = (jax.random.key(i) for i in (41, 42, 43))
    la = eng.submit(pa, 8, key=ka, temperature=0.8)
    lb = eng.submit(pb, 8)                        # greedy default
    lc = eng.submit(pc, 8, key=kc, temperature=1.0, top_p=0.9)
    ld = eng.submit(pd, 8, key=kd, temperature=0.7, min_p=0.2)
    outs = {ln: run_to_done(eng, ln) for ln in (la, lb, lc, ld)}
    np.testing.assert_array_equal(
        outs[la], solo(params, pa, 8, temperature=0.8, key=ka))
    np.testing.assert_array_equal(outs[lb], solo(params, pb, 8))
    np.testing.assert_array_equal(
        outs[lc], solo(params, pc, 8, temperature=1.0, top_p=0.9,
                       key=kc))
    np.testing.assert_array_equal(
        outs[ld], solo(params, pd, 8, temperature=0.7, min_p=0.2,
                       key=kd))
    # Lane reuse flips a sampled lane back to greedy cleanly.
    le = eng.submit(pa, 6)
    np.testing.assert_array_equal(run_to_done(eng, le),
                                  solo(params, pa, 6))


def test_per_request_eos_and_validation(params, rng):
    """Per-request eos_token works on ANY engine (host-side
    bookkeeping); param overrides need per_request_sampling=True and
    keep generate()'s key/filter contracts per request."""
    eng = ContinuousBatcher(params, CFG, lanes=2)
    p = rng.integers(0, 64, (4,)).astype(np.int32)
    base = solo(params, p, 10)
    tok = int(base[len(p) + 2])           # emitted at the 3rd new slot
    lane = eng.submit(p, 10, eos_token=tok)
    out = run_to_done(eng, lane)
    assert out[-1] == tok and len(out) <= len(base)
    np.testing.assert_array_equal(out, base[:len(out)])

    with pytest.raises(ValueError, match="per_request_sampling"):
        eng.submit(p, 4, key=jax.random.key(0), temperature=0.5)
    pr = ContinuousBatcher(params, CFG, lanes=2,
                           per_request_sampling=True)
    with pytest.raises(ValueError, match="iff this request samples"):
        pr.submit(p, 4, temperature=0.5)  # samples but no key
    with pytest.raises(ValueError, match="iff this request samples"):
        pr.submit(p, 4, key=jax.random.key(0))  # greedy with key
    with pytest.raises(ValueError, match="top_p/min_p need"):
        pr.submit(p, 4, top_p=0.9)        # filter on a greedy request
    with pytest.raises(ValueError, match="top_p must be"):
        pr.submit(p, 4, key=jax.random.key(0), temperature=0.5,
                  top_p=1.5)
    # Sampling-default engine: a request can drop to greedy (no key).
    sd = ContinuousBatcher(params, CFG, lanes=2, temperature=0.8,
                           top_k=8, per_request_sampling=True)
    ln = sd.submit(p, 6, temperature=0.0)
    np.testing.assert_array_equal(run_to_done(sd, ln),
                                  solo(params, p, 6))
    # min_p=0.0 is the explicit OFF override for a filtering default.
    fd = ContinuousBatcher(params, CFG, lanes=2, temperature=0.8,
                           min_p=0.3, per_request_sampling=True)
    k2 = jax.random.key(77)
    ln2 = fd.submit(p, 6, key=k2, min_p=0.0)
    np.testing.assert_array_equal(
        run_to_done(fd, ln2),
        solo(params, p, 6, temperature=0.8, key=k2))
    # Bad constructor defaults fail eagerly (the per-request arrays
    # would otherwise sample silent garbage).
    with pytest.raises(ValueError, match="min_p must be"):
        ContinuousBatcher(params, CFG, temperature=0.8, min_p=-0.5,
                          per_request_sampling=True)


def test_per_request_fuzz_schedule_matches_solo(params, rng):
    """Property test: randomized arrivals x random per-request params
    (greedy/temperature/top_p/min_p/eos mixes) on a
    per_request_sampling engine — every request still equals its solo
    generate() run."""
    eng = ContinuousBatcher(params, CFG, lanes=3,
                            per_request_sampling=True)
    reqs = []           # (prompt, n, submit_kw, solo_kw)
    for i in range(8):
        p = rng.integers(1, 10)
        prompt = rng.integers(0, 64, (p,)).astype(np.int32)
        n = int(rng.integers(1, 32 - p))
        kind = i % 4
        if kind == 0:
            sub, sol = {}, {}
        elif kind == 1:
            k = jax.random.key(100 + i)
            sub = dict(key=k, temperature=0.8)
            sol = dict(key=k, temperature=0.8)
        elif kind == 2:
            k = jax.random.key(100 + i)
            sub = dict(key=k, temperature=1.1, top_p=0.85, eos_token=9)
            sol = dict(key=k, temperature=1.1, top_p=0.85, eos_token=9)
        else:
            k = jax.random.key(100 + i)
            sub = dict(key=k, temperature=0.6, min_p=0.25)
            sol = dict(key=k, temperature=0.6, min_p=0.25)
        reqs.append((prompt, n, sub, sol))
    pending = list(range(len(reqs)))
    lane_of, outs = {}, {}
    while len(outs) < len(reqs):
        while pending and eng.free_lanes():
            rid = pending.pop(0)
            prompt, n, sub, _ = reqs[rid]
            lane_of[eng.submit(prompt, n, **sub)] = rid
        eng.step(int(rng.integers(1, 4)))
        for lane in list(lane_of):
            if lane not in eng.running():
                outs[lane_of.pop(lane)] = eng.drain(lane)
    for rid, (prompt, n, _, sol) in enumerate(reqs):
        ref = solo(params, prompt, n, **sol)
        out = outs[rid]
        np.testing.assert_array_equal(out, ref[:len(out)],
                                      err_msg=f"request {rid}")
        if len(out) < len(ref):   # eos truncation: tail is sticky fill
            eos = sol["eos_token"]
            assert out[-1] == eos and (ref[len(out):] == eos).all()


def test_per_request_sampling_on_rolling_lanes(rng):
    """per_request_sampling composes with rolling ring lanes: a greedy
    and a sampled request decode past max_len side by side, each
    matching its solo rolling generate() run."""
    rparams = tfm.init_params(jax.random.key(6), ROLL_CFG)
    eng = ContinuousBatcher(rparams, ROLL_CFG, lanes=2,
                            per_request_sampling=True)
    pa = rng.integers(0, 64, (4,)).astype(np.int32)
    pb = rng.integers(0, 64, (5,)).astype(np.int32)
    kb = jax.random.key(33)
    la = eng.submit(pa, 20)                        # greedy, wraps
    lb = eng.submit(pb, 18, key=kb, temperature=0.9, top_p=0.9)
    out_a = run_to_done(eng, la)
    out_b = run_to_done(eng, lb)
    np.testing.assert_array_equal(
        out_a, np.asarray(generate(rparams, pa[None], ROLL_CFG, 20))[0])
    np.testing.assert_array_equal(
        out_b, np.asarray(generate(rparams, pb[None], ROLL_CFG, 18,
                                   temperature=0.9, top_p=0.9,
                                   key=kb))[0])


# ------------------------------------------------------ SpeculativeBatcher

def test_speculative_batcher_matches_solo(params, rng):
    """Draft-assisted lanes: each request's output is exactly its solo
    greedy speculative_generate run (== generate's greedy rollout),
    under staggered admission and lane reuse, with per-request eos."""
    from distkeras_tpu.models.speculative import speculative_generate
    from distkeras_tpu.serving import SpeculativeBatcher

    draft_cfg = tfm.TransformerConfig(vocab_size=64, d_model=16,
                                      n_heads=2, n_layers=1, d_ff=32,
                                      max_len=32, rope=True)
    draft = tfm.init_params(jax.random.key(9), draft_cfg)
    eng = SpeculativeBatcher(params, draft, CFG, draft_cfg, lanes=2,
                             n_draft=3)
    pa = rng.integers(0, 64, (5,)).astype(np.int32)
    pb = rng.integers(0, 64, (1,)).astype(np.int32)   # 1-token prompt
    pc = rng.integers(0, 64, (7,)).astype(np.int32)

    la = eng.submit(pa, 10)
    eng.step()                            # A advances alone first
    lb = eng.submit(pb, 8)                # admitted mid-flight
    out_a = run_to_done(eng, la)
    out_b = run_to_done(eng, lb)
    lc = eng.submit(pc, 6, eos_token=9)   # reuses a freed lane
    out_c = run_to_done(eng, lc)

    def solo_spec(p, n, **kw):
        out, _ = speculative_generate(params, draft, p[None], CFG,
                                      draft_cfg, n, n_draft=3, **kw)
        return np.asarray(out)[0]

    np.testing.assert_array_equal(out_a, solo_spec(pa, 10))
    np.testing.assert_array_equal(out_b, solo_spec(pb, 8))
    ref_c = solo_spec(pc, 6, eos_token=9)
    np.testing.assert_array_equal(out_c, ref_c[:len(out_c)])
    if len(out_c) < len(ref_c):
        assert out_c[-1] == 9 and (ref_c[len(out_c):] == 9).all()
    assert lc in (la, lb)


def test_speculative_batcher_validation(params, rng):
    import dataclasses

    from distkeras_tpu.serving import SpeculativeBatcher

    draft_cfg = tfm.TransformerConfig(vocab_size=64, d_model=16,
                                      n_heads=2, n_layers=1, d_ff=32,
                                      max_len=32, rope=True)
    draft = tfm.init_params(jax.random.key(9), draft_cfg)
    p = rng.integers(0, 64, (4,)).astype(np.int32)
    with pytest.raises(ValueError, match="full-cache"):
        SpeculativeBatcher(params, draft,
                           dataclasses.replace(CFG, attention_window=8),
                           draft_cfg)
    with pytest.raises(ValueError, match="vocab"):
        SpeculativeBatcher(params, draft, CFG,
                           dataclasses.replace(draft_cfg, vocab_size=32))
    eng = SpeculativeBatcher(params, draft, CFG, draft_cfg, lanes=1,
                             n_draft=3)
    with pytest.raises(ValueError, match="slack"):
        eng.submit(p, 26)                  # 4 + 26 + 3 > 32
    assert eng.submit(p, 8) == 0
    assert eng.submit(p, 8) is None        # full
    with pytest.raises(ValueError, match="still decoding"):
        eng.drain(0)


def test_speculative_batcher_sampled_matches_solo(params, rng):
    """Sampled speculative lanes: per-lane iteration-keyed draws
    replay each request's solo b=1 sampled speculative_generate run
    exactly, regardless of when the lane was admitted."""
    from distkeras_tpu.models.speculative import speculative_generate
    from distkeras_tpu.serving import SpeculativeBatcher

    draft_cfg = tfm.TransformerConfig(vocab_size=64, d_model=16,
                                      n_heads=2, n_layers=1, d_ff=32,
                                      max_len=32, rope=True)
    draft = tfm.init_params(jax.random.key(9), draft_cfg)
    eng = SpeculativeBatcher(params, draft, CFG, draft_cfg, lanes=2,
                             n_draft=3, temperature=0.8)
    pa = rng.integers(0, 64, (5,)).astype(np.int32)
    pb = rng.integers(0, 64, (3,)).astype(np.int32)
    ka, kb = jax.random.key(51), jax.random.key(52)
    la = eng.submit(pa, 10, key=ka)
    eng.step()                            # A ahead by one round
    lb = eng.submit(pb, 8, key=kb)        # admitted mid-flight
    out_a = run_to_done(eng, la)
    out_b = run_to_done(eng, lb)

    def solo(p, n, key):
        out, _ = speculative_generate(params, draft, p[None], CFG,
                                      draft_cfg, n, n_draft=3,
                                      temperature=0.8, key=key)
        return np.asarray(out)[0]

    np.testing.assert_array_equal(out_a, solo(pa, 10, ka))
    np.testing.assert_array_equal(out_b, solo(pb, 8, kb))

    with pytest.raises(ValueError, match="key iff"):
        eng.submit(pa, 4)                 # sampling engine, no key
    greedy = SpeculativeBatcher(params, draft, CFG, draft_cfg,
                                lanes=1, n_draft=2)
    with pytest.raises(ValueError, match="key iff"):
        greedy.submit(pa, 4, key=ka)      # greedy engine with key


def test_speculative_impossible_config_rejected_eagerly(params):
    """Round-6 fix: a n_draft/max_len combination that can never admit
    any request fails at CONSTRUCTION, naming n_draft and max_len —
    not at every submit() with an error blaming the prompt."""
    import dataclasses

    from distkeras_tpu.serving import SpeculativeBatcher

    draft_cfg = tfm.TransformerConfig(vocab_size=64, d_model=16,
                                      n_heads=2, n_layers=1, d_ff=32,
                                      max_len=4, rope=True)
    draft = tfm.init_params(jax.random.key(9), draft_cfg)
    # min(max_len) = 4 <= n_draft + 1 = 5: no request can ever fit.
    with pytest.raises(ValueError, match=r"n_draft=4.*max_len"):
        SpeculativeBatcher(params, draft, CFG, draft_cfg, n_draft=4)
    # The boundary case (cap == 1) constructs and admits a 1-token
    # prompt with one new token.
    ok_draft_cfg = dataclasses.replace(draft_cfg, max_len=6)
    ok_draft = tfm.init_params(jax.random.key(9), ok_draft_cfg)
    eng = SpeculativeBatcher(params, ok_draft, CFG, ok_draft_cfg,
                             n_draft=4, lanes=1)
    assert eng.submit(np.asarray([3], np.int32), 1) == 0


def test_engine_top_p_one_matches_unfiltered_solo(params, rng):
    """Round-6 parity fix: a scalar-path engine built with top_p=1.0
    decodes exactly like solo generate with NO nucleus filter (and
    like generate(top_p=1.0), which now bypasses the mask too)."""
    # The no-op values are legal on every engine mode — scalar sampled,
    # scalar greedy (they turn nothing ON), and per-request (already).
    ContinuousBatcher(params, CFG, lanes=1, temperature=0.9, min_p=0.0)
    ContinuousBatcher(params, CFG, lanes=1, top_p=1.0, min_p=0.0)
    eng = ContinuousBatcher(params, CFG, lanes=1, temperature=0.9,
                            top_p=1.0, prompt_buckets=(8,))
    prompt = rng.integers(0, 64, (5,)).astype(np.int32)
    k = jax.random.key(3)
    lane = eng.submit(prompt, 6, key=k)
    out = run_to_done(eng, lane)
    unfiltered = solo(params, prompt, 6, temperature=0.9, key=k)
    explicit = solo(params, prompt, 6, temperature=0.9, top_p=1.0,
                    key=k)
    np.testing.assert_array_equal(out, unfiltered)
    np.testing.assert_array_equal(out, explicit)
