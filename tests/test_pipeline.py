"""Pipeline parallelism: schedule numerics + differentiability + the
pipelined transformer trunk vs the single-device forward."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distkeras_tpu.models import transformer as tfm
from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh
from distkeras_tpu.parallel.pipeline import make_pipeline


def test_pipeline_matches_sequential(devices, rng):
    """4 affine stages over the pipeline == their sequential composition."""
    mesh = make_mesh(MeshSpec(data=1, pipeline=4), devices=devices[:4])
    w = rng.normal(size=(4, 8, 8)).astype(np.float32) * 0.5
    b = rng.normal(size=(4, 8)).astype(np.float32)
    x = rng.normal(size=(16, 8)).astype(np.float32)

    def stage_fn(p, u):
        return jnp.tanh(u @ p["w"] + p["b"]), jnp.zeros((), jnp.float32)

    pipe = jax.jit(make_pipeline(stage_fn, mesh, microbatches=4))
    out, _ = pipe({"w": jnp.asarray(w), "b": jnp.asarray(b)}, jnp.asarray(x))

    ref = x
    for i in range(4):
        ref = np.tanh(ref @ w[i] + b[i])
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_pipeline_rejects_misstacked_params(devices, rng):
    """Leading axis != n_stages must fail loudly, not drop layers."""
    mesh = make_mesh(MeshSpec(data=1, pipeline=4), devices=devices[:4])
    w = jnp.asarray(rng.normal(size=(8, 8, 8)).astype(np.float32))
    x = jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32))
    pipe = make_pipeline(lambda p, u: (u @ p, jnp.zeros((), jnp.float32)),
                         mesh, microbatches=4)
    with pytest.raises(ValueError, match="n_stages"):
        jax.jit(pipe)(w, x)


def test_pipeline_gradients(devices, rng):
    """grad through the pipeline == grad through sequential composition."""
    mesh = make_mesh(MeshSpec(data=1, pipeline=2), devices=devices[:2])
    w = jnp.asarray(rng.normal(size=(2, 4, 4)).astype(np.float32) * 0.5)
    x = jnp.asarray(rng.normal(size=(8, 4)).astype(np.float32))

    def stage_fn(p, u):
        return jnp.tanh(u @ p), jnp.zeros((), jnp.float32)

    pipe = make_pipeline(stage_fn, mesh, microbatches=4)
    g = jax.jit(jax.grad(lambda w: pipe(w, x)[0].sum()))(w)

    def seq(w):
        u = x
        for i in range(2):
            u = jnp.tanh(u @ w[i])
        return u.sum()

    g_ref = jax.grad(seq)(w)
    np.testing.assert_allclose(g, g_ref, atol=1e-5, rtol=1e-5)


def test_pipelined_transformer_matches_single(devices, rng):
    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=4, d_ff=64, max_len=32)
    mesh = make_mesh(MeshSpec(data=2, pipeline=4), devices=devices)
    params = tfm.init_params(jax.random.key(0), cfg)
    t = jnp.asarray(rng.integers(0, 64, (8, 16)).astype(np.int32))
    ref, _ = tfm.apply(params, t, cfg)
    out, _ = jax.jit(
        lambda p, t: tfm.apply_pipelined(p, t, cfg, mesh, microbatches=4)
    )(params, t)
    np.testing.assert_allclose(out, ref, atol=2e-4, rtol=2e-4)


def test_pipelined_transformer_trains(devices, rng):
    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32)
    mesh = make_mesh(MeshSpec(data=2, pipeline=2), devices=devices[:4])
    params = tfm.init_params(jax.random.key(0), cfg)
    opt = optax.adam(1e-2)
    step = jax.jit(tfm.make_train_step(
        cfg, opt, apply_fn=lambda p, t: tfm.apply_pipelined(
            p, t, cfg, mesh, microbatches=2)))
    carry = (params, opt.init(params))
    t = jnp.asarray(rng.integers(0, 64, (8, 16)).astype(np.int32))
    losses = []
    for _ in range(20):
        carry, loss = step(carry, t)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses[::5]


def test_pipelined_moe_aux_flows_into_loss(devices, rng):
    """The router's load-balancing aux must survive pipelining: stage
    outputs carry (activation, aux) and lm_loss sees nll + aux."""
    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32,
                                num_experts=2, capacity_factor=2.0)
    mesh = make_mesh(MeshSpec(data=2, pipeline=2, expert=2), devices=devices)
    params = tfm.init_params(jax.random.key(0), cfg)
    t = jnp.asarray(rng.integers(0, 64, (8, 17)).astype(np.int32))

    apply_fn = lambda p, tk: tfm.apply_pipelined(p, tk, cfg, mesh,
                                                 microbatches=2)
    logits, aux = jax.jit(apply_fn)(params, t[:, :-1])
    _, ref_aux = tfm.apply(params, t[:, :-1], cfg)
    assert float(aux) > 0
    # Same scale as the un-pipelined forward (capacity is per-microbatch
    # under PP, so routing may drop slightly differently).
    np.testing.assert_allclose(float(aux), float(ref_aux), rtol=0.5)

    loss = jax.jit(lambda p, tk: tfm.lm_loss(p, tk, cfg, apply_fn=apply_fn))(
        params, t)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, t[:, 1:][..., None], axis=-1).mean()
    np.testing.assert_allclose(float(loss), float(nll) + float(aux),
                               rtol=1e-5)


def test_pipelined_ring_attention_matches_single(devices, rng):
    """PP x SP: the pipeline manual over {pipeline, seq} running the
    ring attention body per stage reproduces the plain single-device
    forward — and its gradient."""
    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32)
    mesh = make_mesh(MeshSpec(data=2, pipeline=2, seq=2), devices=devices)
    params = tfm.init_params(jax.random.key(1), cfg)
    t = jnp.asarray(rng.integers(0, 64, (8, 17)).astype(np.int32))
    apply_fn = lambda p, tk: tfm.apply_pipelined(
        p, tk, cfg, mesh, microbatches=2, seq_axis="seq")
    ref, _ = tfm.apply(params, t[:, :-1], cfg)
    out, _ = jax.jit(apply_fn)(params, t[:, :-1])
    np.testing.assert_allclose(out, ref, atol=2e-4, rtol=2e-4)

    g = jax.jit(jax.grad(
        lambda p: tfm.lm_loss(p, t, cfg, apply_fn=apply_fn)))(params)
    g_ref = jax.grad(lambda p: tfm.lm_loss(p, t, cfg))(params)
    for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-3)


def test_pipelined_moe_with_seq_axis_aux_consistent(devices, rng):
    """dp x pp x sp x ep with MoE: per-seq-shard router aux must be
    reduced over seq (not silently claimed replicated) and the loss must
    differentiate."""
    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32,
                                num_experts=2, capacity_factor=4.0)
    mesh = make_mesh(MeshSpec(data=1, pipeline=2, seq=2, expert=2),
                     devices=devices)
    params = tfm.init_params(jax.random.key(0), cfg)
    t = jnp.asarray(rng.integers(0, 64, (8, 17)).astype(np.int32))
    apply_fn = lambda p, tk: tfm.apply_pipelined(
        p, tk, cfg, mesh, microbatches=2, seq_axis="seq")
    _, aux = jax.jit(apply_fn)(params, t[:, :-1])
    _, ref_aux = tfm.apply(params, t[:, :-1], cfg)
    assert float(aux) > 0
    # Routing/capacity is per seq shard under SP, so only same-scale.
    np.testing.assert_allclose(float(aux), float(ref_aux), rtol=0.5)
    g = jax.jit(jax.grad(
        lambda p: tfm.lm_loss(p, t, cfg, apply_fn=apply_fn)))(params)
    assert all(np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(g))
