"""Round-12 paged KV: block-granular cache, per-lane page tables,
content-hash stem sharing, copy-on-write forks.

The exact-parity contract is tests/test_serving.py's: every request's
emitted tokens are bit-identical to the monolithic engine's and to
solo ``generate`` — the block slab, the page-table gather, stem
sharing, and CoW forks must all be invisible in the tokens.  On top of
that: allocator bookkeeping (refcounts, OOM backpressure, no leaked
blocks across any vacation path), pinned prefixes on the one slab,
and the ``kv_int8="prefill"`` tolerance pin.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu import obs
from distkeras_tpu.models import transformer as tfm
from distkeras_tpu.models.generate import (_decode_chunk, generate,
                                           init_cache, prefill)
from distkeras_tpu.serving import (BlockAllocator, ContinuousBatcher,
                                   PagedBatcher, QueueFull)
from distkeras_tpu.serving.paged import (KV_INT8_PREFILL_LOGIT_TOL,
                                         TRASH_BLOCK)

CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=32, rope=True)
BLOCK = 8
MB = CFG.max_len // BLOCK


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(jax.random.key(0), CFG)


def paged(params, lanes=2, n_blocks=None, **kw):
    kw.setdefault("prompt_buckets", (8,))
    if n_blocks is None:
        n_blocks = lanes * MB + 1
    return PagedBatcher(params, CFG, lanes=lanes, block=BLOCK,
                        n_blocks=n_blocks, **kw)


def run_to_done(eng, lane):
    while lane in eng.running():
        eng.step()
    return eng.drain(lane)


def solo(params, prompt, n, **kw):
    return np.asarray(generate(params, np.asarray(prompt)[None], CFG,
                               n, **kw))[0]


def assert_no_leak(eng):
    """Every block is back on the free list and no lane table points
    anywhere but trash — the no-block-leaked invariant."""
    st = eng.allocator.stats()
    assert st["used"] == 0 and st["free"] == st["capacity"], st
    assert (eng._tables_np == TRASH_BLOCK).all()
    assert all(not b for b in eng._lane_blocks)


# ---------------------------------------------------- allocator unit


def test_allocator_refcount_and_residency():
    a = BlockAllocator(n_blocks=5, block=8)   # blocks 1..4 usable
    assert a.capacity == 4
    b1, b2 = a.alloc(), a.alloc()
    assert a.refs_of(b1) == 1
    a.share(b1)
    assert a.refs_of(b1) == 2
    a.register(b1, b"h1")
    assert a.share_by_hash(b"h1") == b1
    assert a.refs_of(b1) == 3
    # Free down to zero: the block moves to the free list but stays
    # hash-resident, so a later request can revive it...
    for _ in range(3):
        a.free(b1)
    assert a.refs_of(b1) == 0
    assert a.stats()["free"] == 3
    assert a.share_by_hash(b"h1") == b1        # revived
    a.free(b1)
    # ...until the free list recycles it: alloc purges the hash.
    got = {a.alloc() for _ in range(4)}
    assert len(got) == 4
    assert a.alloc() is None                   # exhausted, no raise
    assert a.share_by_hash(b"h1") is None      # recycled -> purged
    with pytest.raises(ValueError, match="not live"):
        a.free(99)
    a.free(b2)
    with pytest.raises(ValueError, match="not live"):
        a.free(b2)                             # double free
    with pytest.raises(ValueError, match="not live"):
        a.share(b2)


def test_allocator_register_first_writer_wins():
    a = BlockAllocator(n_blocks=4, block=8)
    b1, b2 = a.alloc(), a.alloc()
    a.register(b1, b"h")
    a.register(b2, b"h")                       # identical content
    assert a.share_by_hash(b"h") == b1
    a.free(b1)
    a.free(b1)                                 # drop the shared ref
    a.free(b2)


# ------------------------------------------------------- parity


def test_paged_greedy_parity_staggered_and_lane_reuse(params, rng):
    """Staggered admission + lane reuse: bit parity with both the
    monolithic engine and solo generate, and zero blocks leaked."""
    pb = paged(params, lanes=2)
    cb = ContinuousBatcher(params, CFG, lanes=2, prompt_buckets=(8,))
    prompts = [rng.integers(0, 64, (n,)).astype(np.int32)
               for n in (5, 12, 7)]
    outs = {}
    lp1, lc1 = pb.submit(prompts[0], 10), cb.submit(prompts[0], 10)
    pb.step(), cb.step()
    lp2, lc2 = pb.submit(prompts[1], 8), cb.submit(prompts[1], 8)
    outs[0] = (run_to_done(pb, lp1), run_to_done(cb, lc1))
    # Lane reuse: the third request lands on a vacated lane whose
    # stale blocks went back to the allocator.
    lp3, lc3 = pb.submit(prompts[2], 9), cb.submit(prompts[2], 9)
    outs[1] = (run_to_done(pb, lp2), run_to_done(cb, lc2))
    outs[2] = (run_to_done(pb, lp3), run_to_done(cb, lc3))
    for i, (op, oc) in outs.items():
        assert np.array_equal(op, oc), f"request {i} diverged"
        n = (10, 8, 9)[i]
        assert np.array_equal(op, solo(params, prompts[i], n))
    assert_no_leak(pb)


def test_paged_sampled_parity_per_request(params, rng):
    """Seeded-sampled parity through the per-request-sampling step —
    greedy and sampled requests mixed in one paged batch."""
    pb = paged(params, lanes=2, per_request_sampling=True,
               temperature=0.0)
    p1 = rng.integers(0, 64, (6,)).astype(np.int32)
    p2 = rng.integers(0, 64, (9,)).astype(np.int32)
    k = jax.random.key(11)
    l1 = pb.submit(p1, 8, key=k, temperature=0.9, top_p=0.9)
    l2 = pb.submit(p2, 8)                      # greedy default
    o1, o2 = run_to_done(pb, l1), run_to_done(pb, l2)
    assert np.array_equal(
        o1, solo(params, p1, 8, temperature=0.9, top_p=0.9, key=k))
    assert np.array_equal(o2, solo(params, p2, 8))
    assert_no_leak(pb)


def test_paged_kv_int8_exact_parity(params, rng):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        pb = paged(params, lanes=2, kv_int8=True)
    p = rng.integers(0, 64, (7,)).astype(np.int32)
    out = run_to_done(pb, pb.submit(p, 9))
    assert np.array_equal(
        out, solo(params, p, 9, kv_int8=True, use_prefill=False))
    assert_no_leak(pb)


def test_paged_chunked_prefill_parity(params, rng):
    """Chunked prefill on the paged slab: the long prompt's chunks
    land one per step while another lane decodes; tokens match the
    monolithic chunked engine (itself pinned to solo runs)."""
    pb = paged(params, lanes=2, prefill_chunk=8,
               prompt_buckets=(8, 16))
    ps = rng.integers(0, 64, (4,)).astype(np.int32)
    pl = rng.integers(0, 64, (22,)).astype(np.int32)
    ls = pb.submit(ps, 12)
    pb.step()
    ll = pb.submit(pl, 6)                      # parks, admits chunked
    assert ll in pb.running()
    assert np.array_equal(run_to_done(pb, ls), solo(params, ps, 12))
    assert np.array_equal(run_to_done(pb, ll), solo(params, pl, 6))
    assert_no_leak(pb)


# -------------------------------------------------- stem sharing


def test_stem_sharing_hit_refcounts_and_parity(params, rng):
    """Two requests sharing a 2-block stem: the second admission
    refcounts the first's blocks (no re-prefill), both match solo
    runs, and vacating one keeps the shared blocks alive for the
    other."""
    pb = paged(params, lanes=2, prompt_buckets=(4, 16))
    stem = rng.integers(0, 64, (16,)).astype(np.int32)
    t1 = rng.integers(0, 64, (3,)).astype(np.int32)
    t2 = rng.integers(0, 64, (3,)).astype(np.int32)
    pr1, pr2 = np.concatenate([stem, t1]), np.concatenate([stem, t2])
    l1 = pb.submit(pr1, 6)
    used_before = pb.allocator.stats()["used"]
    l2 = pb.submit(pr2, 6)
    st = pb.allocator.stats()
    assert st["shared"] == 2, st               # both stem blocks hit
    # The second admission allocated only its tail blocks, not the
    # stem's: 19 warm tokens = 3 blocks, 2 shared -> 1 fresh.
    assert st["used"] == used_before + 1, (used_before, st)
    assert pb._lane_blocks[l1][:2] == pb._lane_blocks[l2][:2]
    o1 = run_to_done(pb, l1)                   # vacates lane 1
    st = pb.allocator.stats()
    assert st["shared"] == 0                   # survivor holds refs 1
    assert all(pb.allocator.refs_of(b) == 1
               for b in pb._lane_blocks[l2])
    o2 = run_to_done(pb, l2)
    assert np.array_equal(o1, solo(params, pr1, 6))
    assert np.array_equal(o2, solo(params, pr2, 6))
    assert_no_leak(pb)
    # Residency outlives the requests: a third shared-stem request
    # revives the freed blocks by hash.
    l3 = pb.submit(pr1, 4)
    assert pb.allocator.stats()["resident_hashes"] >= 2
    assert np.array_equal(run_to_done(pb, l3), solo(params, pr1, 4))


def test_stem_sharing_miss_stays_private(params, rng):
    """Different stems: no hash hit, fully private block sets."""
    pb = paged(params, lanes=2, prompt_buckets=(4, 16))
    a = rng.integers(0, 64, (18,)).astype(np.int32)
    b = rng.integers(0, 64, (18,)).astype(np.int32)
    assert not np.array_equal(a[:BLOCK], b[:BLOCK])
    la, lb = pb.submit(a, 5), pb.submit(b, 5)
    assert pb.allocator.stats()["shared"] == 0
    assert not set(pb._lane_blocks[la]) & set(pb._lane_blocks[lb])
    assert np.array_equal(run_to_done(pb, la), solo(params, a, 5))
    assert np.array_equal(run_to_done(pb, lb), solo(params, b, 5))


def test_stem_sharing_waits_for_chunked_content(params, rng):
    """A chunk-admitting lane's blocks must not hash-hit before their
    content is dispatched: a same-stem request admitted while the
    first is still PARKED shares only the chunks already landed."""
    pb = paged(params, lanes=2, prefill_chunk=8,
               prompt_buckets=(8, 24))
    stem = rng.integers(0, 64, (24,)).astype(np.int32)
    p1 = np.concatenate([stem, rng.integers(0, 64, (1,)).astype(np.int32)])
    l1 = pb.submit(p1, 4)                      # parked: 24 warm = 3 chunks
    assert pb._lane_state[l1].chunks is not None
    # Only chunk 0 (8 tokens = 1 block) has landed -> 1 resident hash.
    p2 = np.concatenate([stem, rng.integers(0, 64, (2,)).astype(np.int32)])
    l2 = pb.submit(p2, 4)
    assert len(pb._lane_blocks[l2]) >= 3
    assert pb.allocator.stats()["shared"] == 1  # just the landed block
    assert np.array_equal(run_to_done(pb, l1), solo(params, p1, 4))
    assert np.array_equal(run_to_done(pb, l2), solo(params, p2, 4))
    assert_no_leak(pb)


def test_stem_hit_unbucketable_span_falls_back(params, rng):
    """Code-review regression: a resident stem hit whose unshared
    span fits NO bucket at the skip offset must fall back to less
    sharing (down to a full re-prefill), never fail a request that
    validated — and the surplus shared refs are handed back."""
    pb = paged(params, lanes=2, prompt_buckets=(8,))  # buckets {8, 32}
    stem = rng.integers(0, 64, (16,)).astype(np.int32)
    first = np.concatenate([stem,
                            rng.integers(0, 64, (2,)).astype(np.int32)])
    run_to_done(pb, pb.submit(first, 4))       # makes the stem resident
    # warm 25: skip=16 -> span 9 at offset 16 (no bucket fits),
    # skip=8 -> span 17 at offset 8 (32 doesn't fit) -> skip=0.
    prompt = np.concatenate([stem,
                             rng.integers(0, 64, (10,)).astype(np.int32)])
    hits0 = pb.stem_hit_blocks
    lane = pb.submit(prompt, 4)
    assert lane is not None
    assert pb.stem_hit_blocks == hits0          # all shares given back
    assert pb.allocator.stats()["shared"] == 0
    assert np.array_equal(run_to_done(pb, lane),
                          solo(params, prompt, 4))
    assert_no_leak(pb)
    # A shareable span that DOES fit still shares (the fallback is
    # not a blanket disable): warm 21 -> span 5 at offset 16, bucket 8
    # fits (16 + 8 <= 32).
    ok = np.concatenate([stem,
                         rng.integers(0, 64, (6,)).astype(np.int32)])
    lane = pb.submit(ok, 4)
    assert pb.stem_hit_blocks == hits0 + 2      # revived by hash
    assert np.array_equal(run_to_done(pb, lane), solo(params, ok, 4))
    assert_no_leak(pb)


def test_growth_window_does_not_overallocate_past_budget(params, rng):
    """Code-review regression: a step window larger than a lane's
    remaining budget must not allocate blocks for the discarded
    garbage positions (that turned window roundup into spurious OOM
    evictions)."""
    pb = paged(params, lanes=2, n_blocks=3, prompt_buckets=(8,),
               step_windows=(1, 8))            # 2 usable blocks
    p1 = rng.integers(0, 64, (8,)).astype(np.int32)
    p2 = rng.integers(0, 64, (8,)).astype(np.int32)
    l1, l2 = pb.submit(p1, 1), pb.submit(p2, 1)
    out = pb.step(8)                           # window >> budget
    assert set(out) == {l1, l2}
    assert np.array_equal(pb.drain(l1), solo(params, p1, 1))
    assert np.array_equal(pb.drain(l2), solo(params, p2, 1))
    assert not pb.results()                    # nobody was evicted
    assert_no_leak(pb)


# ----------------------------------------------- pinned prefixes


def test_pinned_prefix_on_slab_parity_and_residency(params, rng):
    """The pooled-prefix story on the paged slab: pin once, every
    matching prompt hash-hits the pinned blocks (zero prefix prefill
    work — asserted via block identity), parity is exact, and unpin
    releases exactly the pin's references."""
    pb = paged(params, lanes=2, prompt_buckets=(4, 16))
    prefix = rng.integers(0, 64, (17,)).astype(np.int32)  # rounds to 16
    pid = pb.pin_prefix(prefix)
    assert pb.pinned.length_of(pid) == 16
    pinned_blocks = list(pb.pinned.blocks_of(pid))
    assert pb.allocator.stats()["used"] == 2
    tail = rng.integers(0, 64, (4,)).astype(np.int32)
    full = np.concatenate([prefix[:16], tail])
    lane = pb.submit(full, 6)
    # The lane's first two blocks ARE the pinned blocks, refcounted.
    assert pb._lane_blocks[lane][:2] == pinned_blocks
    assert all(pb.allocator.refs_of(b) == 2 for b in pinned_blocks)
    assert np.array_equal(run_to_done(pb, lane), solo(params, full, 6))
    assert all(pb.allocator.refs_of(b) == 1 for b in pinned_blocks)
    pb.unpin_prefix(pid)
    assert pid not in pb.pinned
    assert_no_leak(pb)
    with pytest.raises(KeyError):
        pb.unpin_prefix(pid)


def test_pinned_prefix_validation(params, rng):
    pb = paged(params, lanes=1, prompt_buckets=(8,))
    with pytest.raises(ValueError, match="full block"):
        pb.pin_prefix(rng.integers(0, 64, (BLOCK - 1,)))
    with pytest.raises(ValueError, match="leave room"):
        pb.pin_prefix(rng.integers(0, 64, (CFG.max_len,)))
    tiny = paged(params, lanes=1, n_blocks=2, prompt_buckets=(8,))
    tiny.pin_prefix(rng.integers(0, 64, (BLOCK,)))
    with pytest.raises(RuntimeError, match="no free KV blocks"):
        tiny.pin_prefix(np.arange(BLOCK, dtype=np.int32))


def test_pin_prefix_rolls_back_on_dispatch_fault(params, rng):
    """Code-review regression: a failure AFTER pin_prefix staged its
    blocks (here the admit dispatch) must hand every staged reference
    back — the pin was never published."""
    pb = paged(params, lanes=2, prompt_buckets=(8,))

    def boom(*a, **kw):
        raise RuntimeError("injected pin fault")
    real, pb._admit = pb._admit, boom
    with pytest.raises(RuntimeError, match="injected pin fault"):
        pb.pin_prefix(rng.integers(0, 64, (16,)).astype(np.int32))
    assert len(pb.pinned) == 0
    assert_no_leak(pb)
    pb._admit = real
    pid = pb.pin_prefix(rng.integers(0, 64, (16,)).astype(np.int32))
    assert pb.allocator.stats()["used"] == 2   # engine still healthy
    pb.unpin_prefix(pid)
    assert_no_leak(pb)


# ------------------------------------------------------ CoW forks


def test_cow_fork_beam_parity(params, rng):
    """Beam-style fork: branch on an alternative token mid-decode;
    the source stays bit-exact with its solo run and the branch
    matches the solo run of its forced-token transcript.  Only the
    divergent tail block is fresh — all full blocks are shared."""
    pb = paged(params, lanes=3, prompt_buckets=(8,))
    p = rng.integers(0, 64, (6,)).astype(np.int32)
    src = pb.submit(p, 12)
    for _ in range(4):
        pb.step()
    st = pb._lane_state[src]
    alt = (st.tokens[-1] + 1) % CFG.vocab_size
    frontier = len(st.tokens) - 1
    f = pb.fork(src, token=alt)
    assert f is not None
    shared = pb._lane_blocks[src][:frontier // BLOCK]
    assert pb._lane_blocks[f][:len(shared)] == shared
    assert all(pb.allocator.refs_of(b) == 2 for b in shared)
    o_src, o_f = run_to_done(pb, src), run_to_done(pb, f)
    assert np.array_equal(o_src, solo(params, p, 12))
    forced = np.asarray(o_f[:len(p) + 4])      # prompt + 3 kept + alt
    assert forced[-1] == alt
    assert np.array_equal(o_f, solo(params, forced, 12 - 4))
    assert_no_leak(pb)


def test_cow_fork_speculative_rollback(params, rng):
    """Speculative checkpoint/rollback: fork an exact replica, let
    the source speculate ahead, reject it (evict), and the
    checkpoint lane continues to the solo-run answer."""
    pb = paged(params, lanes=3, prompt_buckets=(8,), clock=lambda: 0.0)
    p = rng.integers(0, 64, (6,)).astype(np.int32)
    src = pb.submit(p, 12)
    for _ in range(3):
        pb.step()
    st = pb._lane_state[src]
    ck = pb.fork(src, token=st.tokens[-1])     # exact replica
    for _ in range(2):                         # "speculate" on src
        pb.step()
    # Reject: evict the speculating lane; its private blocks free,
    # the checkpoint's shared blocks survive.
    used = pb.allocator.stats()["used"]
    st_src = pb._lane_state[src]
    pb._finish(st_src.request_id, st_src.tokens, "cancelled",
               st_src.prompt_len)
    pb._vacate(src)
    assert pb.allocator.stats()["used"] < used
    assert np.array_equal(run_to_done(pb, ck), solo(params, p, 12))
    assert_no_leak(pb)


def test_cow_fork_sampled_key_and_validation(params, rng):
    pb = paged(params, lanes=2, temperature=0.8, prompt_buckets=(8,))
    p = rng.integers(0, 64, (5,)).astype(np.int32)
    src = pb.submit(p, 6, key=jax.random.key(3))
    pb.step()
    f = pb.fork(src, token=pb._lane_state[src].tokens[-1],
                key=jax.random.key(9))
    o_src, o_f = run_to_done(pb, src), run_to_done(pb, f)
    assert np.array_equal(
        o_src, solo(params, p, 6, temperature=0.8,
                    key=jax.random.key(3)))
    # The fork replays the same transcript prefix under ITS key: its
    # continuation is the solo run of that prefix with the new key.
    kept = len(o_f) - 6 + 1                   # prompt + first emitted
    assert np.array_equal(
        o_f, np.asarray(generate(params, np.asarray(o_f[:kept])[None],
                                 CFG, 6 - 1, temperature=0.8,
                                 key=jax.random.key(9)))[0])
    with pytest.raises(ValueError, match="empty"):
        pb.fork(0 if src != 0 else 1, token=1)
    greedy = paged(params, lanes=2, prompt_buckets=(8,))
    g = greedy.submit(p, 4)
    with pytest.raises(ValueError, match="sampling engine"):
        greedy.fork(g, token=1, key=jax.random.key(0))
    with pytest.raises(ValueError, match="outside vocab"):
        greedy.fork(g, token=CFG.vocab_size)


def test_cow_fork_backpressure(params, rng):
    """No free lane -> None; no free block for the tail copy -> None
    with every staged share rolled back."""
    pb = paged(params, lanes=2, n_blocks=4, prompt_buckets=(8,))
    # Budgets fit one block each (no growth pressure in this test).
    p = rng.integers(0, 64, (3,)).astype(np.int32)
    a = pb.submit(p, 5)
    b = pb.submit(rng.integers(0, 64, (6,)).astype(np.int32), 2)
    pb.step()
    assert pb.fork(a, token=1) is None          # lanes full
    run_to_done(pb, b)                          # a still decoding
    # 3 usable blocks: a holds 1 (6 warm tokens) and will have grown;
    # drain the allocator with a pin so the tail copy cannot alloc.
    while pb.allocator.alloc() is not None:
        pass
    st = pb.allocator.stats()
    assert pb.fork(a, token=1) is None
    assert pb.allocator.stats() == st           # rollback exact
    # No result was fabricated for the declined forks.
    assert pb.last_request_id is None


# ------------------------------------- backpressure, OOM, eviction


def test_admission_oom_declines_then_queue_backpressure(params, rng):
    """Allocator exhausted at admission: bare submit declines (no
    lane occupied, nothing leaked), enqueue queues the request and
    admits it once blocks free; past the queue cap, QueueFull."""
    pb = paged(params, lanes=3, n_blocks=3, max_queue=1,
               prompt_buckets=(8,))                  # 2 usable blocks
    # 5-token prompts + 3 new = 8 total: exactly one block each, no
    # growth — admission pressure only.
    p1 = rng.integers(0, 64, (5,)).astype(np.int32)
    p2 = rng.integers(0, 64, (5,)).astype(np.int32)
    p3 = rng.integers(0, 64, (5,)).astype(np.int32)
    l1, l2 = pb.submit(p1, 3), pb.submit(p2, 3)
    assert l1 is not None and l2 is not None
    assert pb.submit(p3, 3) is None              # blocks dry, lane free
    assert len(pb.free_lanes()) == 1
    r3 = pb.enqueue(p3, 3)                       # queues instead
    assert pb.queued == 1
    with pytest.raises(QueueFull):
        pb.enqueue(p3, 3)
    run_to_done(pb, l1)
    run_to_done(pb, l2)                          # frees blocks; pumps
    while pb.poll(r3) is None:
        pb.step()
    res = pb.take(r3)
    assert res.ok
    assert np.array_equal(res.tokens, solo(params, p3, 3))
    assert_no_leak(pb)


def test_growth_oom_evicts_with_structured_error(params, rng):
    """A lane the allocator cannot grow mid-decode is evicted with a
    structured "error" result; its freed blocks let the other lane
    finish exactly."""
    pb = paged(params, lanes=2, n_blocks=4, prompt_buckets=(8,))
    # Two lanes, 3 usable blocks: both will outgrow block 1 and only
    # one second block exists.
    p1 = rng.integers(0, 64, (7,)).astype(np.int32)
    p2 = rng.integers(0, 64, (7,)).astype(np.int32)
    l1 = pb.submit(p1, 12)                     # grows past 8 tokens
    l2 = pb.submit(p2, 12)
    while pb.running():
        pb.step()
    results = pb.results()
    evicted = [r for r in results.values() if r.status == "error"]
    assert len(evicted) == 1
    assert "exhausted" in evicted[0].error
    survivor = l1 if pb._lane_state[l1] is not None else l2
    sp = p1 if survivor == l1 else p2
    assert np.array_equal(pb.drain(survivor), solo(params, sp, 12))
    assert_no_leak(pb)


def test_chaos_eviction_mid_growth_shared_blocks_survive(params, rng):
    """The chaos leg: a deadline-evicted lane mid-growth frees its
    PRIVATE blocks; the stem blocks it shared survive for the other
    lane, whose output stays bit-exact, and nothing leaks."""
    t = {"now": 0.0}
    pb = paged(params, lanes=2, prompt_buckets=(4, 16),
               clock=lambda: t["now"])
    stem = rng.integers(0, 64, (16,)).astype(np.int32)
    pr1 = np.concatenate([stem, rng.integers(0, 64, (3,)).astype(np.int32)])
    pr2 = np.concatenate([stem, rng.integers(0, 64, (3,)).astype(np.int32)])
    l1 = pb.submit(pr1, 10)
    l2 = pb.submit(pr2, 10, ttl=5.0)           # will expire mid-decode
    shared = pb._lane_blocks[l1][:2]
    assert pb._lane_blocks[l2][:2] == shared
    blocks_at_admission = len(pb._lane_blocks[l2])
    for _ in range(7):
        pb.step()                              # both grow past block 2
    assert len(pb._lane_blocks[l2]) > blocks_at_admission  # mid-growth
    victim_private = [b for b in pb._lane_blocks[l2]
                      if b not in shared]
    assert victim_private                      # it DID grow private
    t["now"] = 6.0
    pb.step()                                  # reap evicts l2
    assert pb._lane_state[l2] is None
    for b in victim_private:                   # private blocks freed
        assert pb.allocator.refs_of(b) == 0
    for b in shared:                           # shared survive
        assert pb.allocator.refs_of(b) == 1
    assert np.array_equal(run_to_done(pb, l1),
                          solo(params, pr1, 10))
    assert_no_leak(pb)


def test_abort_admission_releases_staged_blocks(params, rng):
    """A failure AFTER block staging (here: the admit dispatch
    itself) must roll the staged blocks back — no half-admitted lane,
    no leak."""
    pb = paged(params, lanes=2, prompt_buckets=(8,))
    p = rng.integers(0, 64, (9,)).astype(np.int32)

    def boom(*a, **kw):
        raise RuntimeError("injected admit fault")
    real_admit, pb._admit = pb._admit, boom
    with pytest.raises(RuntimeError, match="injected admit fault"):
        pb.submit(p, 4)
    assert_no_leak(pb)
    # Early validation failures (before staging) stay clean too.
    pb._admit = real_admit
    with pytest.raises(ValueError, match="key iff"):
        pb.submit(p, 4, key=jax.random.key(0))  # greedy engine + key
    assert_no_leak(pb)
    out = run_to_done(pb, pb.submit(p, 4))      # engine still healthy
    assert np.array_equal(out, solo(params, p, 4))


def test_shutdown_drains_and_frees(params, rng):
    pb = paged(params, lanes=2, max_queue=2, prompt_buckets=(8,))
    rids = [pb.enqueue(rng.integers(0, 64, (6,)).astype(np.int32), 5)
            for _ in range(4)]
    res = pb.shutdown()
    assert sorted(res) == sorted(rids)
    assert all(r.ok for r in res.values())
    assert_no_leak(pb)


# ------------------------------------------- kv_int8="prefill"


def test_kv_int8_prefill_admission_tolerance(params, rng):
    """The round-12 satellite, pinned: a prefill-BUILT int8 cache
    (full-precision in-chunk attention, quantized once) differs from
    the exact decode-built cache by a real but bounded amount —
    nonzero (it IS a different build) and under
    KV_INT8_PREFILL_LOGIT_TOL on the first decode step's logits."""
    prompt = rng.integers(0, 64, (1, 17)).astype(np.int32)
    warm = jnp.asarray(prompt[:, :-1])
    w = warm.shape[1]
    cache_d = init_cache(CFG, 1, kv_int8=True)
    _, cache_d = _decode_chunk(params, cache_d, warm,
                               jnp.zeros((1,), jnp.int32), CFG,
                               uniform_pos=True)
    cache_p, _ = prefill(params, warm, CFG, last_logits=False,
                         kv_int8=True)
    pos = jnp.full((1,), w, jnp.int32)
    last = jnp.asarray(prompt[:, -1:])
    lg_d, _ = _decode_chunk(params, cache_d, last, pos, CFG)
    lg_p, _ = _decode_chunk(params, cache_p, last, pos, CFG)
    diff = float(jnp.max(jnp.abs(lg_d - lg_p)))
    assert 0.0 < diff < KV_INT8_PREFILL_LOGIT_TOL, diff


def test_kv_int8_prefill_engine_agreement(params, rng):
    """Engine level: kv_int8="prefill" admission serves tokens that
    track the exact decode-built engine closely (measured: identical
    on this seed; the bound leaves headroom) and the decode phase
    after admission stays the same compiled path."""
    p = rng.integers(0, 64, (9,)).astype(np.int32)
    outs = {}
    for mode in (True, "prefill"):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            pb = paged(params, lanes=1, kv_int8=mode)
        outs[mode] = run_to_done(pb, pb.submit(p, 12))
        assert_no_leak(pb)
    agree = np.mean(np.asarray(outs[True]) == np.asarray(outs["prefill"]))
    assert agree >= 0.9, (agree, outs)


def test_kv_int8_prefill_validation(params):
    from distkeras_tpu.models.quant import quantize_params

    with pytest.raises(ValueError, match="full-precision"):
        PagedBatcher(quantize_params(params), CFG, block=BLOCK,
                     kv_int8="prefill")
    with pytest.raises(ValueError, match='kv_int8 must be'):
        PagedBatcher(params, CFG, block=BLOCK, kv_int8="decode")
    # Monolithic engines reject the string too instead of silently
    # truthy-coercing it into plain decode-built int8.
    with pytest.raises(ValueError, match="PagedBatcher"):
        ContinuousBatcher(params, CFG, kv_int8="prefill")


# -------------------------------------------------- validation, obs


def test_paged_constructor_validation(params):
    with pytest.raises(ValueError, match="divide max_len"):
        PagedBatcher(params, CFG, block=5)
    win = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32,
                                rope=True, attention_window=16)
    with pytest.raises(ValueError, match="full-cache"):
        PagedBatcher(params, win, block=8)
    with pytest.raises(ValueError, match="block must be >= 1"):
        PagedBatcher(params, CFG, block=0)
    with pytest.raises(ValueError, match="n_blocks"):
        PagedBatcher(params, CFG, block=8, n_blocks=1)


def test_paged_obs_gauges_and_fork_counter(params, rng):
    """The round-12 observability satellite: kv_blocks_used/free/
    shared gauges and the cow_forks counter flow through the standard
    registry (and therefore /metrics and the cluster federation)."""
    sess = obs.enable()
    try:
        pb = paged(params, lanes=3, prompt_buckets=(4, 16))
        stem = rng.integers(0, 64, (16,)).astype(np.int32)
        l1 = pb.submit(
            np.concatenate([stem,
                            rng.integers(0, 64, (3,)).astype(np.int32)]),
            6)
        l2 = pb.submit(
            np.concatenate([stem,
                            rng.integers(0, 64, (3,)).astype(np.int32)]),
            6)
        f = pb.fork(l1, token=int(pb._lane_state[l1].tokens[-1]))
        reg = sess.registry
        assert reg.gauge("serving.kv_blocks_used").value() > 0
        assert reg.gauge("serving.kv_shared_blocks").value() >= 2
        assert (reg.gauge("serving.kv_blocks_used").value()
                + reg.gauge("serving.kv_blocks_free").value()
                == pb.allocator.capacity)
        assert reg.counter("serving.cow_forks").value() == 1
        assert reg.counter("serving.stem_hit_blocks").value() >= 2
        for lane in (l1, l2, f):
            run_to_done(pb, lane)
        assert reg.gauge("serving.kv_blocks_used").value() == 0
        text = reg.render_text()
        assert "serving_kv_blocks_used" in text
        assert "serving_cow_forks" in text
    finally:
        obs.disable()


def test_paged_zero_steady_state_compiles(params, rng):
    """Construction compiles everything; a full serve cycle —
    admission (stem hit AND miss), decode, fork, drain — compiles
    nothing (the in-repo mirror of the serving_paged* compile-guard
    sessions)."""
    import jax.monitoring

    n = {"c": 0}

    def listener(event, duration, **kw):
        if event == "/jax/core/compile/backend_compile_duration":
            n["c"] += 1
    jax.monitoring.register_event_duration_secs_listener(listener)
    pb = paged(params, lanes=3, prompt_buckets=(8,))
    built = n["c"]
    stem = rng.integers(0, 64, (8,)).astype(np.int32)
    l1 = pb.submit(
        np.concatenate([stem, rng.integers(0, 64, (4,)).astype(np.int32)]), 6)
    l2 = pb.submit(
        np.concatenate([stem, rng.integers(0, 64, (4,)).astype(np.int32)]), 6)
    pb.step()
    f = pb.fork(l1, token=int(pb._lane_state[l1].tokens[-1]))
    for lane in (l1, l2, f):
        run_to_done(pb, lane)
    assert n["c"] == built, f"serve phase compiled {n['c'] - built}"
