"""Byte-level BPE tokenizer: native/python parity, losslessness, and
the LMTrainer packing contract."""

import numpy as np
import pytest

import distkeras_tpu  # noqa: F401  (package import path)
from distkeras_tpu.data.tokenizer import BPETokenizer


CORPUS = (
    "the quick brown fox jumps over the lazy dog. "
    "the quicker brown foxes jump over the lazier dogs. "
    "pack my box with five dozen liquor jugs. "
) * 50


def test_train_encode_decode_roundtrip():
    tok = BPETokenizer.train(CORPUS, vocab_size=400)
    assert 256 < tok.vocab_size <= 400
    ids = tok.encode("the quick brown fox")
    assert ids.dtype == np.int32
    assert len(ids) < len("the quick brown fox")  # merges compress
    assert tok.decode(ids) == "the quick brown fox"


def test_unseen_and_unicode_text_is_lossless():
    tok = BPETokenizer.train(CORPUS, vocab_size=300)
    for text in ["zebra! @#$%", "héllo wörld é中文", ""]:
        assert tok.decode(tok.encode(text)) == text


def test_native_and_python_paths_agree(monkeypatch):
    tok_native = BPETokenizer.train(CORPUS, vocab_size=350)

    import distkeras_tpu.native as native

    monkeypatch.setattr(native, "_bpe_lib", None)
    monkeypatch.setattr(native, "_bpe_tried", True)  # force fallback
    tok_py = BPETokenizer.train(CORPUS, vocab_size=350)
    np.testing.assert_array_equal(tok_native.merges, tok_py.merges)

    text = "the lazy liquor jugs jumped over my box"
    ids_py = tok_py.encode(text)
    assert tok_py.decode(ids_py) == text
    monkeypatch.undo()
    np.testing.assert_array_equal(tok_native.encode(text), ids_py)


def test_save_load_roundtrip(tmp_path):
    tok = BPETokenizer.train(CORPUS, vocab_size=300)
    p = str(tmp_path / "bpe.json")
    tok.save(p)
    tok2 = BPETokenizer.load(p)
    np.testing.assert_array_equal(tok.merges, tok2.merges)
    text = "five dozen foxes"
    np.testing.assert_array_equal(tok.encode(text), tok2.encode(text))


def test_encode_corpus_packs_lm_rows():
    tok = BPETokenizer.train(CORPUS, vocab_size=300)
    rows = tok.encode_corpus(CORPUS, seq_len=16)
    assert rows.shape[1] == 17 and rows.dtype == np.int32
    ids = tok.encode(CORPUS)
    # Consecutive rows overlap by one token (input/target shift).
    np.testing.assert_array_equal(rows[0], ids[:17])
    np.testing.assert_array_equal(rows[1], ids[16:33])


def test_validation():
    with pytest.raises(ValueError, match="vocab_size"):
        BPETokenizer.train("abc", vocab_size=100)
    with pytest.raises(ValueError, match="do not exist"):
        BPETokenizer(np.asarray([[999, 0]], np.int32))
    tok = BPETokenizer.train(CORPUS, vocab_size=300)
    with pytest.raises(ValueError, match="out of range"):
        tok.decode(np.asarray([tok.vocab_size], np.int32))
    with pytest.raises(ValueError, match="needs"):
        tok.encode_corpus("x", seq_len=64)


def test_empty_merge_table_is_raw_bytes():
    tok = BPETokenizer(np.empty((0, 2), np.int32))
    ids = tok.encode("abc")
    np.testing.assert_array_equal(ids, [97, 98, 99])
    assert tok.decode(ids) == "abc"


def test_tokenizer_feeds_lm_trainer(devices):
    import distkeras_tpu as dk
    from distkeras_tpu.models import transformer as tfm

    tok = BPETokenizer.train(CORPUS, vocab_size=300)
    rows = tok.encode_corpus(CORPUS, seq_len=16)
    cfg = tfm.TransformerConfig(vocab_size=tok.vocab_size, d_model=32,
                                n_heads=2, n_layers=2, d_ff=64, max_len=32)
    t = dk.LMTrainer(cfg, learning_rate=1e-2, batch_size=16, num_epoch=2)
    t.train(rows)
    assert t.history[-1] < t.history[0]
