"""Bounded-staleness async tier (parallel/async_tier.py +
trainers/async_dp.py, docs/async.md) on the 8-CPU mesh.

Acceptance contract: seeded virtual-time schedules are replayable —
two runs of the same schedule, INCLUDING a straggler stall and a
mid-training join, produce bit-identical final params; the SSP gate
parks fast hosts only for slow-but-alive laggards and the watchdog
evicts wedged-heartbeat ones, so a stall degrades the fleet by less
than τ rounds instead of stalling it; a host killed mid-push
(``cluster.push`` fault) publishes nothing — its delta drops cleanly
and the merge is atomic under ``cluster.merge`` faults; and AsyncDP
converges within TOL_LOSS of the synchronous ADAG baseline on the
same seeded blobs.  The s8 wire-dtype claim is proved from the
compiled census in tests/test_budget_guards.py (asyncdp_wire target).
"""

import jax.numpy as jnp
import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.parallel.async_tier import (AsyncConfig, AsyncPlane,
                                                AsyncSchedule, VirtualClock,
                                                tree_reduce)
from distkeras_tpu.resilience import chaos

from helpers import make_blobs, make_mlp

TOL_LOSS = 0.05  # same declared bound as tests/test_exchange.py


def _tree(*leaves):
    return [jnp.asarray(l, jnp.float32) for l in leaves]


def _plane(tau=2, merge="adasum", compress=None, fanout=2, window=3.0,
           coord_dir=None, t0=0.0):
    clock = VirtualClock(t0)
    cfg = AsyncConfig(tau=tau, merge_rule=merge, compress=compress,
                      fanout=fanout, beat_window=window)
    center = _tree([0.0, 0.0, 0.0, 0.0], [[0.0, 0.0], [0.0, 0.0]])
    return AsyncPlane(center, cfg, clock, coord_dir=coord_dir), clock


# --------------------------------------------------------- primitives


def test_config_validation():
    with pytest.raises(ValueError, match="tau"):
        AsyncConfig(tau=0)
    with pytest.raises(ValueError, match="merge_rule"):
        AsyncConfig(merge_rule="median")
    with pytest.raises(ValueError, match="compress"):
        AsyncConfig(compress="fp8")
    with pytest.raises(ValueError, match="fanout"):
        AsyncConfig(fanout=1)
    with pytest.raises(ValueError, match="beat_window"):
        AsyncConfig(beat_window=0.0)


def test_virtual_clock_monotone():
    c = VirtualClock()
    c.advance_to(2.5)
    assert c.now() == c() == 2.5
    with pytest.raises(ValueError, match="backwards"):
        c.advance_to(1.0)


def test_schedule_deterministic_and_stallable():
    a, b = AsyncSchedule(seed=5), AsyncSchedule(seed=5)
    durs = [(h, r) for h in range(3) for r in range(1, 5)]
    assert [a.duration(h, r) for h, r in durs] == \
           [b.duration(h, r) for h, r in durs]
    assert a.duration(0, 1) != AsyncSchedule(seed=6).duration(0, 1)
    base = a.duration(1, 2)
    a.stall(1, 2, 10.0)
    assert a.duration(1, 2) == pytest.approx(base + 10.0)
    assert a.stalled(1, 2) and not a.stalled(1, 3)


def test_tree_reduce_adasum_and_sum():
    x = [_tree([1.0, 2.0]), _tree([1.0, 2.0])]
    # Agreeing deltas: adasum == the value (mean of parallel inputs).
    out = tree_reduce([t for t in x], 2, "adasum")
    np.testing.assert_allclose(np.asarray(out[0]), [1.0, 2.0],
                               rtol=1e-6)
    # Orthogonal deltas: the plain sum, under both rules.
    y = [_tree([1.0, 0.0]), _tree([0.0, 2.0])]
    for rule in ("adasum", "sum"):
        out = tree_reduce([t for t in y], 2, rule)
        np.testing.assert_allclose(np.asarray(out[0]), [1.0, 2.0],
                                   rtol=1e-6)
    # Fanout-ary tree over 5 hosts reduces to one delta.
    five = [_tree([float(i), 0.0]) for i in range(5)]
    out = tree_reduce(five, 2, "sum")
    np.testing.assert_allclose(np.asarray(out[0]), [10.0, 0.0],
                               rtol=1e-6)


# ---------------------------------------------------------- the plane


def test_push_merge_updates_center():
    plane, _ = _plane(merge="sum")
    plane.join(0), plane.join(1)
    plane.push(0, _tree([1.0, 0.0, 0.0, 0.0], [[1.0, 0.0], [0.0, 0.0]]))
    plane.push(1, _tree([0.0, 2.0, 0.0, 0.0], [[0.0, 0.0], [0.0, 2.0]]))
    assert plane.version == 2 and plane.merges == 2
    np.testing.assert_allclose(np.asarray(plane.center[0]),
                               [1.0, 2.0, 0.0, 0.0], rtol=1e-6)
    tv, version = plane.pull(0)
    assert version == 2
    # pull returns a copy, never an alias of the center.
    assert tv[0] is not plane.center[0]


def test_int8_wire_error_feedback_converges():
    plane, _ = _plane(merge="sum", compress="int8")
    plane.join(0)
    target = _tree([0.3, -1.7, 0.01, 4.0], [[0.5, 0.0], [0.0, 0.0]])
    # Repeatedly pushing the same delta: the EF residual carries each
    # push's quantization error into the next, so the center tracks
    # n * delta far better than n independent lossy pushes would.
    for _ in range(8):
        plane.push(0, target)
    np.testing.assert_allclose(np.asarray(plane.center[0]),
                               8 * np.asarray(target[0]),
                               rtol=0.02, atol=0.02)
    assert plane.wire_bytes > 0


def test_staleness_gate_and_hard_sync():
    plane, _ = _plane(tau=2)
    plane.join(0), plane.join(1)
    for _ in range(3):
        plane.complete(0)
    ok, lag = plane.may_start(0, 4)       # host 1 at round 0: lag 4 > 2
    assert not ok and lag == [1]
    assert plane.hard_syncs == 1
    ok, lag = plane.may_start(1, 1)       # the laggard itself may run
    assert ok and lag == []


def test_watchdog_evicts_stale_heartbeat_only():
    plane, clock = _plane(tau=1, window=2.0)
    plane.join(0), plane.join(1)
    # Healthy-but-slow member: never stale, no matter the clock.
    clock.advance_to(10.0)
    assert not plane.stale(1)
    plane.freeze_beats(1)
    clock.advance_to(11.0)
    assert not plane.stale(1)            # frozen, but inside window
    clock.advance_to(13.0)
    assert plane.stale(1)                # past the window: evictable
    plane.evict(1, reason="heartbeat_stale")
    assert plane.evicted == [1] and 1 not in plane.members


def test_evict_drops_in_flight_deltas():
    plane, _ = _plane(merge="sum")
    plane.join(0), plane.join(1)
    with chaos.FaultPlan(seed=0).fail("cluster.merge", at=1):
        plane.push(1, _tree([9.0, 9.0, 9.0, 9.0],
                            [[9.0, 9.0], [9.0, 9.0]]))
    assert plane.pending and plane.version == 0
    plane.evict(1, reason="heartbeat_stale")
    assert plane.dropped_deltas == 1 and not plane.pending
    # The dropped delta never reaches the center.
    plane.push(0, _tree([1.0, 0.0, 0.0, 0.0], [[0.0] * 2] * 2))
    np.testing.assert_allclose(np.asarray(plane.center[0]),
                               [1.0, 0.0, 0.0, 0.0], rtol=1e-6)


def test_graceful_leave_refcounts_final_delta():
    plane, _ = _plane(merge="sum")
    plane.join(0), plane.join(1)
    plane.leave(1, final_delta=_tree([0.0, 0.0, 0.0, 5.0],
                                     [[0.0] * 2] * 2))
    assert 1 not in plane.members and plane.version == 1
    np.testing.assert_allclose(np.asarray(plane.center[0]),
                               [0.0, 0.0, 0.0, 5.0], rtol=1e-6)


def test_join_bootstraps_at_fleet_round():
    plane, _ = _plane()
    plane.join(0)
    for _ in range(5):
        plane.complete(0)
    tv, version = plane.join(7)
    assert plane.members[7].round == 5     # cannot trip the bound
    assert version == plane.version


def test_push_fault_is_pre_publish():
    plane, _ = _plane(merge="sum")
    plane.join(0)
    with chaos.FaultPlan(seed=0).fail("cluster.push", at=1):
        with pytest.raises(chaos.FaultInjected):
            plane.push(0, _tree([1.0] * 4, [[1.0] * 2] * 2))
    # Nothing enqueued, nothing counted, center untouched.
    assert plane.pushes == 0 and not plane.pending
    assert plane.version == 0
    np.testing.assert_allclose(np.asarray(plane.center[0]), [0.0] * 4)


def test_merge_fault_is_atomic_and_retries():
    plane, _ = _plane(merge="sum")
    plane.join(0), plane.join(1)
    with chaos.FaultPlan(seed=0).fail("cluster.merge", at=1):
        plane.push(0, _tree([1.0, 0.0, 0.0, 0.0], [[0.0] * 2] * 2))
    assert plane.version == 0 and len(plane.pending) == 1
    # The next push merges BOTH deltas in one wave — nothing torn,
    # nothing lost.
    plane.push(1, _tree([0.0, 1.0, 0.0, 0.0], [[0.0] * 2] * 2))
    assert plane.version == 1 and not plane.pending
    np.testing.assert_allclose(np.asarray(plane.center[0]),
                               [1.0, 1.0, 0.0, 0.0], rtol=1e-6)


def test_membership_rides_cluster_substrate(tmp_path):
    from distkeras_tpu.resilience.cluster import EpochStore
    from distkeras_tpu.resilience.health import read_beat

    d = str(tmp_path / "coord")
    plane, clock = _plane(coord_dir=d)
    plane.join(0)
    plane.complete(0)
    clock.advance_to(1.0)
    plane.join(1)
    # Membership transitions are EpochStore generations ...
    assert EpochStore(d).current() == plane.epoch == 2
    # ... and heartbeats are real beat files stamped with VIRTUAL time.
    beat = read_beat(str(tmp_path / "coord" / "beats"), 1)
    assert beat is not None and beat["t"] == 1.0
    plane.leave(0)
    assert EpochStore(d).current() == 3
    done = read_beat(str(tmp_path / "coord" / "beats"), 0)
    assert done["done"] is True


# ----------------------------------------------------------- AsyncDP


def _blob_ds(n=256, seed=0):
    feats, labels = make_blobs(n=n, seed=seed)
    return dk.Dataset({"features": feats, "label": labels})


def _async_trainer(schedule=None, hosts=2, tau=2, **kw):
    opts = dict(loss="sparse_categorical_crossentropy",
                worker_optimizer="sgd", learning_rate=0.05,
                batch_size=2, num_epoch=2, communication_window=2,
                seed=11)
    opts.update(kw)
    return dk.AsyncDP(make_mlp(), hosts=hosts, tau=tau,
                      schedule=schedule, beat_window=1.5, **opts)


def _weights(trainer, ds):
    return [np.asarray(w) for w in trainer.train(ds).get_weights()]


def test_construction_rejects():
    with pytest.raises(ValueError, match="hosts"):
        dk.AsyncDP(make_mlp(), hosts=0)
    with pytest.raises(ValueError, match="device_data"):
        dk.AsyncDP(make_mlp(), device_data=True)
    with pytest.raises(ValueError, match="merge_rule"):
        dk.AsyncDP(make_mlp(), async_merge="median")
    import keras

    bn = keras.Sequential([keras.Input((16,)),
                           keras.layers.Dense(8),
                           keras.layers.BatchNormalization(),
                           keras.layers.Dense(4)])
    with pytest.raises(ValueError, match="non-trainable"):
        dk.AsyncDP(bn)


def test_determinism_with_straggler_and_join():
    """The flagship harness: the SAME seeded virtual-time schedule —
    heterogeneous durations, one host wedged for 50 virtual seconds
    (watchdog-evicted), one host joining mid-training — replayed
    twice, is bit-identical down to every weight."""
    ds = _blob_ds()

    def sched():
        return (AsyncSchedule(seed=3).stall(1, 2, 50.0)
                .join(5, at_time=2.0))

    t1, t2 = _async_trainer(sched(), hosts=3), _async_trainer(
        sched(), hosts=3)
    w1, w2 = _weights(t1, ds), _weights(t2, ds)
    assert all(np.array_equal(a, b) for a, b in zip(w1, w2))
    assert t1.async_report == t2.async_report
    r = t1.async_report
    assert r["evicted"] == [1]            # the wedged straggler left
    assert 5 in r["rounds"]               # the joiner did real rounds
    assert r["hard_syncs"] >= 1           # the barrier fired en route


def test_straggler_degrades_fleet_less_than_tau():
    """A heartbeat-stalled host slows the fleet by < τ round-lengths
    (detection window + re-gate), never a full stall: the 50-virtual-
    second wedge must NOT show up in the makespan."""
    ds = _blob_ds()
    tau = 2
    t0 = _async_trainer(AsyncSchedule(seed=3), hosts=3, tau=tau)
    _weights(t0, ds)
    t1 = _async_trainer(AsyncSchedule(seed=3).stall(1, 2, 50.0),
                        hosts=3, tau=tau)
    _weights(t1, ds)
    m0 = t0.async_report["makespan"]
    m1 = t1.async_report["makespan"]
    assert t1.async_report["evicted"] == [1]
    assert m1 - m0 < tau * 1.0            # base round length is 1.0
    # Survivors completed their full quotas.
    for h in (0, 2):
        assert t1.async_report["rounds"][h] == t0.async_report["rounds"][h]


def test_host_kill_mid_push_drops_delta_cleanly():
    ds = _blob_ds()
    t = _async_trainer(AsyncSchedule(seed=3), hosts=3)
    with chaos.FaultPlan(seed=0).fail("cluster.push", at=5) as plan:
        _weights(t, ds)
    r = t.async_report
    assert plan.events == [("cluster.push", 5, "fail")]
    assert len(r["evicted"]) == 1         # the killed host left ...
    assert r["pushes"] == r["merges"] == r["version"]  # ... torn-free
    assert r["members_final"] == []       # survivors drained cleanly


def test_merge_fault_mid_training_retries():
    ds = _blob_ds()
    t = _async_trainer(AsyncSchedule(seed=3), hosts=2)
    with chaos.FaultPlan(seed=0).fail("cluster.merge", at=3):
        _weights(t, ds)
    r = t.async_report
    assert r["evicted"] == []
    # One wave deferred and folded into the next push's merge.
    assert r["merges"] == r["pushes"] - 1


def test_converges_within_tol_of_adag():
    """The robustness tier costs < TOL_LOSS of final loss vs the
    synchronous baseline — same model, same seeded blobs, same total
    data."""
    ds = _blob_ds()
    kw = dict(loss="sparse_categorical_crossentropy",
              worker_optimizer="sgd", learning_rate=0.05, batch_size=2,
              num_epoch=2, communication_window=2, seed=11)
    base = dk.ADAG(make_mlp(), **kw)
    base.train(ds)
    for merge in ("adasum", "sum"):
        t = _async_trainer(AsyncSchedule(seed=3), hosts=2,
                           async_merge=merge)
        _weights(t, ds)
        assert abs(t.history[-1] - base.history[-1]) < TOL_LOSS, (
            merge, t.history[-1], base.history[-1])


def test_traced_for_analysis_has_wire_leg():
    t = _async_trainer(AsyncSchedule(seed=3), hosts=2,
                       async_compress="int8")
    specs = t.traced_for_analysis(_blob_ds())
    names = [s.name for s in specs]
    assert any(n.startswith("asyncdp_dp/") for n in names)
    assert "asyncdp_wire/adasum_int8" in names
