"""Speculative decoding: exactness vs generate(), chunk machinery,
acceptance statistics, and the sampled-mode distribution guarantee."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.models import transformer as tfm
from distkeras_tpu.models.generate import (
    _decode_chunk,
    _decode_step,
    generate,
    init_cache,
)
from distkeras_tpu.models.speculative import speculative_generate


# max_len carries the n_draft slack past prompt + new (validated).
CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=32)
DRAFT = tfm.TransformerConfig(vocab_size=64, d_model=16, n_heads=2,
                              n_layers=1, d_ff=32, max_len=32)


def _models(cfg=CFG, draft=DRAFT):
    return (tfm.init_params(jax.random.key(0), cfg),
            tfm.init_params(jax.random.key(9), draft))


def test_decode_chunk_matches_decode_step(rng):
    """The chunked step is the T-token generalization of _decode_step:
    teacher-forcing T tokens through one chunk must give the same
    logits as T sequential steps."""
    params, _ = _models()
    toks = jnp.asarray(rng.integers(0, 64, (3, 9)), jnp.int32)
    cache = init_cache(CFG, 3)
    seq_logits = []
    for pos in range(9):
        lg, cache = _decode_step(params, cache, toks[:, pos], pos, CFG)
        seq_logits.append(lg)
    seq_logits = np.stack(seq_logits, axis=1)

    chunk_logits, _ = _decode_chunk(params, init_cache(CFG, 3), toks,
                                    jnp.zeros((3,), jnp.int32), CFG)
    np.testing.assert_allclose(np.asarray(chunk_logits), seq_logits,
                               atol=2e-4, rtol=2e-4)


def test_decode_chunk_per_row_offsets(rng):
    """Rows at different positions share one chunk call: each row's
    logits equal the same row processed alone at its own offset."""
    params, _ = _models()
    warm = jnp.asarray(rng.integers(0, 64, (2, 6)), jnp.int32)
    cache = init_cache(CFG, 2)
    for pos in range(6):
        _, cache = _decode_step(params, cache, warm[:, pos], pos, CFG)
    toks = jnp.asarray(rng.integers(0, 64, (2, 3)), jnp.int32)
    # Row 0 continues at position 6, row 1 pretends it only consumed 4.
    pos0 = jnp.asarray([6, 4], jnp.int32)
    out, _ = _decode_chunk(params, cache, toks, pos0, CFG)
    for r, start in enumerate(pos0.tolist()):
        solo_cache = init_cache(CFG, 1)
        for pos in range(start):
            _, solo_cache = _decode_step(params, solo_cache,
                                         warm[r:r + 1, pos], pos, CFG)
        solo, _ = _decode_chunk(params, solo_cache, toks[r:r + 1],
                                jnp.asarray([start], jnp.int32), CFG)
        np.testing.assert_allclose(np.asarray(out[r]),
                                   np.asarray(solo[0]),
                                   atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("n_draft", [1, 3, 4])
def test_greedy_matches_generate(rng, n_draft):
    """The exactness guarantee: greedy speculative output == generate's
    greedy rollout, token for token, at any draft quality/width."""
    params, draft = _models()
    prompt = jnp.asarray(rng.integers(1, 64, (4, 5)), jnp.int32)
    ref = np.asarray(generate(params, prompt, CFG, 10))
    out, stats = speculative_generate(params, draft, prompt, CFG, DRAFT,
                                      10, n_draft=n_draft)
    np.testing.assert_array_equal(np.asarray(out), ref)
    assert int(stats["iterations"]) >= 1


def test_greedy_rope_gqa_matches_generate(rng):
    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                                n_kv_heads=2, n_layers=2, d_ff=64,
                                max_len=32, rope=True)
    draft_cfg = dataclasses.replace(cfg, n_layers=1)
    params = tfm.init_params(jax.random.key(1), cfg)
    draft = tfm.init_params(jax.random.key(8), draft_cfg)
    prompt = jnp.asarray(rng.integers(1, 64, (3, 4)), jnp.int32)
    ref = np.asarray(generate(params, prompt, cfg, 9))
    out, _ = speculative_generate(params, draft, prompt, cfg, draft_cfg,
                                  9, n_draft=3)
    np.testing.assert_array_equal(np.asarray(out), ref)


def test_greedy_moe_matches_generate(rng):
    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=1, d_ff=64, max_len=32,
                                num_experts=4, moe_top_k=2,
                                capacity_factor=1.25)
    params = tfm.init_params(jax.random.key(2), cfg)
    _, draft = _models()
    prompt = jnp.asarray(rng.integers(1, 64, (2, 4)), jnp.int32)
    ref = np.asarray(generate(params, prompt, cfg, 8))
    out, _ = speculative_generate(params, draft, prompt, cfg, DRAFT, 8,
                                  n_draft=2)
    np.testing.assert_array_equal(np.asarray(out), ref)


def test_perfect_draft_accepts_everything(rng):
    """Draft == target: every proposal is the target argmax, so the
    acceptance rate is 1 and each target pass advances n_draft + 1
    positions (the best-case iteration count)."""
    params, _ = _models()
    prompt = jnp.asarray(rng.integers(1, 64, (2, 4)), jnp.int32)
    n_new, k = 12, 3
    out, stats = speculative_generate(params, params, prompt, CFG, CFG,
                                      n_new, n_draft=k)
    ref = np.asarray(generate(params, prompt, CFG, n_new))
    np.testing.assert_array_equal(np.asarray(out), ref)
    assert float(stats["acceptance_rate"]) == 1.0
    assert int(stats["iterations"]) == -(-n_new // (k + 1))  # ceil


def test_nonuniform_acceptance_rows_finish_cleanly(rng):
    """Rows finishing at DIFFERENT iterations must keep their final
    token: a done row still executes the loop body and writes its
    window into the scratch region — one scratch column too few and
    dynamic_update_slice clamps the write back onto buf[total-1]
    (regression: int8 self-draft gives ~0.8 acceptance with real
    per-row variance, unlike the perfect/random drafts elsewhere)."""
    from distkeras_tpu.models.quant import quantize_params

    cfg = dataclasses.replace(CFG, max_len=40)
    params = tfm.init_params(jax.random.key(6), cfg)
    draft = quantize_params(params)
    prompt = jnp.asarray(rng.integers(1, 64, (8, 4)), jnp.int32)
    ref = np.asarray(generate(params, prompt, cfg, 20))
    out, stats = speculative_generate(params, draft, prompt, cfg, cfg,
                                      20, n_draft=3)
    np.testing.assert_array_equal(np.asarray(out), ref)
    # The regression needs per-row variance to bite; make sure this
    # config still provides it (acceptance strictly between the
    # uniform extremes).
    assert 0.0 < float(stats["acceptance_rate"]) < 1.0


def test_quantized_target_matches_quantized_generate(rng):
    from distkeras_tpu.models.quant import quantize_params

    params, draft = _models()
    qp = quantize_params(params)
    prompt = jnp.asarray(rng.integers(1, 64, (2, 4)), jnp.int32)
    ref = np.asarray(generate(qp, prompt, CFG, 8))
    out, _ = speculative_generate(qp, draft, prompt, CFG, DRAFT, 8,
                                  n_draft=2)
    np.testing.assert_array_equal(np.asarray(out), ref)


def test_sampled_matches_target_distribution(rng):
    """The speculative-sampling theorem, empirically: with a DIFFERENT
    draft model, the first generated token must still be distributed
    exactly as the target's softmax.  4096 parallel rows, TV distance
    against the analytic target distribution."""
    vocab = 16
    cfg = tfm.TransformerConfig(vocab_size=vocab, d_model=16, n_heads=2,
                                n_layers=1, d_ff=32, max_len=8)
    dcfg = dataclasses.replace(cfg, d_model=8, d_ff=16)
    params = tfm.init_params(jax.random.key(3), cfg)
    draft = tfm.init_params(jax.random.key(4), dcfg)
    temp = 0.9
    b = 4096
    prompt = jnp.full((b, 1), 7, jnp.int32)
    out, _ = speculative_generate(params, draft, prompt, cfg, dcfg, 1,
                                  n_draft=2, temperature=temp,
                                  key=jax.random.key(11))
    samples = np.asarray(out[:, 1])
    emp = np.bincount(samples, minlength=vocab) / b

    logits, _ = tfm.apply(params, prompt[:1], cfg)
    target = np.asarray(jax.nn.softmax(logits[0, 0] / temp))
    tv = 0.5 * np.abs(emp - target).sum()
    assert tv < 0.05, (tv, emp, target)


def test_sampled_deterministic_per_key(rng):
    params, draft = _models()
    prompt = jnp.asarray(rng.integers(1, 64, (2, 4)), jnp.int32)
    kw = dict(n_draft=2, temperature=0.8, key=jax.random.key(5))
    a, _ = speculative_generate(params, draft, prompt, CFG, DRAFT, 6, **kw)
    b, _ = speculative_generate(params, draft, prompt, CFG, DRAFT, 6, **kw)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_jittable(rng):
    params, draft = _models()
    prompt = jnp.asarray(rng.integers(1, 64, (2, 4)), jnp.int32)
    fn = jax.jit(lambda tp, dp, pr: speculative_generate(
        tp, dp, pr, CFG, DRAFT, 8, n_draft=3))
    out, stats = fn(params, draft, prompt)
    ref = np.asarray(generate(params, prompt, CFG, 8))
    np.testing.assert_array_equal(np.asarray(out), ref)


def test_validation_errors(rng):
    params, draft = _models()
    prompt = jnp.asarray(rng.integers(1, 64, (2, 4)), jnp.int32)
    with pytest.raises(ValueError, match="vocab"):
        speculative_generate(params, draft, prompt, CFG,
                             dataclasses.replace(DRAFT, vocab_size=32), 4)
    # Windowed configs are supported since round 5; their own bounds:
    with pytest.raises(ValueError, match="rejected tail"):
        speculative_generate(
            params, draft, prompt,
            dataclasses.replace(CFG, rope=True, attention_window=29,
                                max_len=32),
            DRAFT, 4, n_draft=4)  # 29 + 5 > 32
    with pytest.raises(ValueError, match="rope"):
        speculative_generate(
            params, draft, prompt,
            dataclasses.replace(CFG, attention_window=8, max_len=16),
            DRAFT, 20, n_draft=2)  # rolls past max_len without rope
    with pytest.raises(ValueError, match="slack"):
        speculative_generate(params, draft, prompt, CFG, DRAFT, 26,
                             n_draft=4)  # 4+26+4 > 32
    with pytest.raises(ValueError, match="PRNG"):
        speculative_generate(params, draft, prompt, CFG, DRAFT, 4,
                             temperature=0.5)
    with pytest.raises(ValueError, match="n_draft"):
        speculative_generate(params, draft, prompt, CFG, DRAFT, 4,
                             n_draft=0)


def test_eos_matches_generate(rng):
    """Sticky EOS parity: pick an eos token the model actually emits,
    then speculative greedy must equal generate's sticky-eos output,
    including the filled tail."""
    params, draft = _models()
    prompt = jnp.asarray(rng.integers(1, 64, (4, 5)), jnp.int32)
    plain = np.asarray(generate(params, prompt, CFG, 12))
    # A token emitted mid-generation on row 0 becomes the eos —
    # guaranteed to trigger for at least one row.
    eos = int(plain[0, 5 + 3])
    ref = np.asarray(generate(params, prompt, CFG, 12, eos_token=eos))
    out, stats = speculative_generate(params, draft, prompt, CFG, DRAFT,
                                      12, n_draft=3, eos_token=eos)
    np.testing.assert_array_equal(np.asarray(out), ref)
    assert int(stats["iterations"]) >= 1


def test_eos_stops_rows_early(rng):
    """EOS actually saves target passes: IDENTICAL prompt rows all emit
    the chosen eos as their first generated token, so the whole batch
    must finish in ONE pass (without early exit, 16 tokens at
    n_draft=4 need ceil(16/5) = 4)."""
    params, _ = _models()
    one = rng.integers(1, 64, (1, 4))
    prompt = jnp.asarray(np.repeat(one, 3, axis=0), jnp.int32)
    plain = np.asarray(generate(params, prompt, CFG, 16))
    eos = int(plain[0, 4])  # every row's first generated token
    assert (plain[:, 4] == eos).all()
    out, stats = speculative_generate(params, params, prompt, CFG, CFG,
                                      16, n_draft=4, eos_token=eos)
    ref = np.asarray(generate(params, prompt, CFG, 16, eos_token=eos))
    np.testing.assert_array_equal(np.asarray(out), ref)
    assert int(stats["iterations"]) == 1


def test_eos_validation(rng):
    params, draft = _models()
    prompt = jnp.asarray(rng.integers(1, 64, (2, 4)), jnp.int32)
    with pytest.raises(ValueError, match="eos_token"):
        speculative_generate(params, draft, prompt, CFG, DRAFT, 4,
                             eos_token=64)


def test_speculative_kv_int8_greedy_matches_generate_kv_int8(rng):
    """Speculative decoding with int8 caches on both models emits the
    same tokens as plain kv_int8 generate: quantization is per-token
    deterministic, so the verify-chunk cache and the slab-update cache
    hold identical int8 values."""
    params = tfm.init_params(jax.random.key(0), CFG)
    prompt = jnp.asarray(rng.integers(0, 64, (3, 4)).astype(np.int32))
    ref = np.asarray(generate(params, prompt, CFG, 8, kv_int8=True))
    out, stats = speculative_generate(params, params, prompt, CFG, CFG,
                                      8, n_draft=3, kv_int8=True)
    np.testing.assert_array_equal(np.asarray(out), ref)
    assert float(stats["acceptance_rate"]) > 0.9  # self-draft


# ------------------------------------------------------ windowed / rolling

WIN = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, rope=True,
                            attention_window=6, max_len=16)
WIN_DRAFT = tfm.TransformerConfig(vocab_size=64, d_model=16, n_heads=2,
                                  n_layers=1, d_ff=32, rope=True,
                                  attention_window=6, max_len=16)


def test_windowed_greedy_matches_generate(rng):
    """Speculative decoding on rope + attention_window ring caches
    (round-5): greedy output equals windowed generate()'s, including
    ROLLING past max_len — both models' rings wrap mid-run and the
    verify chunks wrap mid-chunk."""
    params, draft = _models(WIN, WIN_DRAFT)
    prompt = jnp.asarray(rng.integers(1, 64, (2, 5)), jnp.int32)
    out, stats = speculative_generate(params, draft, prompt, WIN,
                                      WIN_DRAFT, 25, n_draft=3)
    ref = generate(params, prompt, WIN, 25)   # 5 + 25 = 30 >> 16
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    assert float(stats["acceptance_rate"]) >= 0.0


def test_windowed_mixed_draft_full_cache(rng):
    """Target on a ring, draft on a full cache (each model's budget is
    checked independently) — still exact vs windowed generate."""
    big_draft = dataclasses.replace(WIN_DRAFT, attention_window=None,
                                    max_len=40)
    params, draft = _models(WIN, big_draft)
    prompt = jnp.asarray(rng.integers(1, 64, (2, 4)), jnp.int32)
    out, _ = speculative_generate(params, draft, prompt, WIN,
                                  big_draft, 20, n_draft=3)
    ref = generate(params, prompt, WIN, 20)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


@pytest.mark.parametrize("kv_int8", [False, True])
def test_windowed_small_ring_matches_big_cache_sampled(rng, kv_int8):
    """Sampled speculative decoding on a wrapping ring reproduces the
    non-wrapping big-cache run EXACTLY (same key -> same logits ->
    same accept/reject draws), with and without the int8 cache."""
    big = dataclasses.replace(WIN, max_len=64)
    big_d = dataclasses.replace(WIN_DRAFT, max_len=64)
    params, draft = _models(big, big_d)
    prompt = jnp.asarray(rng.integers(1, 64, (2, 5)), jnp.int32)
    kw = dict(n_draft=3, temperature=0.8, key=jax.random.key(11),
              kv_int8=kv_int8)
    ref, _ = speculative_generate(params, draft, prompt, big, big_d,
                                  25, **kw)
    out, _ = speculative_generate(params, draft, prompt, WIN,
                                  WIN_DRAFT, 25, **kw)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


# ------------------------- ring-cache-compatible serving fallback (PR 5)


@pytest.mark.chaos
def test_rolling_batcher_draft_fault_fallback_past_max_len(rng):
    """The ROADMAP follow-up closed by PR 5: a SpeculativeBatcher on
    rolling/ring-slot lanes must degrade to plain decode when the
    draft model faults, PRESERVING ring-slot state — the fallback
    inherits the lanes' unbounded positions and wrapped ring slabs
    mid-flight, so greedy parity with solo rolling generate holds past
    max_len through the degradation."""
    from distkeras_tpu.resilience import FaultInjected, FaultPlan
    from distkeras_tpu.serving import SpeculativeBatcher

    params, draft = _models(WIN, WIN_DRAFT)
    eng = SpeculativeBatcher(params, draft, WIN, WIN_DRAFT, lanes=2,
                             n_draft=3)
    pa = np.asarray(rng.integers(1, 64, (5,)), np.int32)
    pb = np.asarray(rng.integers(1, 64, (3,)), np.int32)
    la = eng.submit(pa, 25)          # 5 + 25 = 30 >> max_len=16: wraps
    for _ in range(4):               # healthy speculative rounds first:
        eng.step()                   # lane A's ring is mid-wrap
    lb = eng.submit(pb, 20)          # admitted while A wraps
    with FaultPlan().fail("serving.draft"):
        eng.step()                   # draft faults mid-wrap
    assert eng.degraded
    assert isinstance(eng.degraded_error, FaultInjected)
    while eng.running():
        eng.step()
    np.testing.assert_array_equal(
        eng.drain(la),
        np.asarray(generate(params, pa[None], WIN, 25))[0])
    np.testing.assert_array_equal(
        eng.drain(lb),
        np.asarray(generate(params, pb[None], WIN, 20))[0])
    # A degraded rolling engine still admits fresh wrapping requests.
    lc = eng.submit(pa, 18)
    while lc in eng.running():
        eng.step()
    np.testing.assert_array_equal(
        eng.drain(lc),
        np.asarray(generate(params, pa[None], WIN, 18))[0])


@pytest.mark.slow
def test_rolling_batcher_healthy_matches_solo_and_validates(rng):
    """Healthy rolling speculative lanes match solo rolling
    speculative_generate (== rolling generate, greedy); the engine's
    ring bound and rolling-eligibility checks reject loudly; rolling
    budgets cap only the PROMPT."""
    from distkeras_tpu.serving import SpeculativeBatcher

    params, draft = _models(WIN, WIN_DRAFT)
    eng = SpeculativeBatcher(params, draft, WIN, WIN_DRAFT, lanes=2,
                             n_draft=3)
    p = np.asarray(rng.integers(1, 64, (4,)), np.int32)
    lane = eng.submit(p, 24)         # no total-length cap on the ring
    while lane in eng.running():
        eng.step()
    np.testing.assert_array_equal(
        eng.drain(lane),
        np.asarray(generate(params, p[None], WIN, 24))[0])
    # Prompt must still fit the ring's admission chunk.
    with pytest.raises(ValueError, match="admission bucket"):
        eng.submit(np.asarray(rng.integers(1, 64, (17,)), np.int32), 2)
    # Mixed full/windowed model pairs stay rejected.
    full_draft = dataclasses.replace(WIN_DRAFT, attention_window=None)
    with pytest.raises(ValueError, match="agree"):
        SpeculativeBatcher(params, draft, WIN, full_draft, lanes=1,
                           n_draft=2)
    # The ring bound: window + n_draft + 1 must fit max_len.
    with pytest.raises(ValueError, match="rejected tail"):
        SpeculativeBatcher(params, draft, WIN, WIN_DRAFT, lanes=1,
                           n_draft=12)
    # Windowed without rope has no rolling semantics.
    norope = dataclasses.replace(WIN, rope=False)
    with pytest.raises(ValueError, match="rope"):
        SpeculativeBatcher(params, draft, norope, WIN_DRAFT, lanes=1,
                           n_draft=2)
