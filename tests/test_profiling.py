"""StepTimer + profiler trace smoke (SURVEY.md §5 observability rebuild)."""

import glob

import jax
import jax.numpy as jnp
import pytest

from distkeras_tpu.utils.profiling import StepTimer, annotate, trace


def test_step_timer_rounds():
    timer = StepTimer()
    step = jax.jit(lambda x: x * 2.0)
    x = jnp.ones((128, 128))
    for _ in range(3):
        with timer.round():
            for _ in range(4):
                x = step(x)
                timer.count()
        timer.finalize(x)
    assert timer.total_steps == 12
    assert len(timer.rounds) == 3
    assert timer.total_s > 0
    assert timer.mean_step_s > 0
    assert timer.samples_per_sec(128) > 0
    assert timer.p50_round_s > 0


def test_step_timer_named_phases():
    """Named phase counters: host wall time accumulates per phase
    (the distributed trainers record "h2d" and "step" with these)."""
    timer = StepTimer()
    step = jax.jit(lambda x: x * 2.0)
    x = jnp.ones((64, 64))
    for _ in range(3):
        with timer.phase("h2d"):
            xd = jax.device_put(x)
        with timer.phase("step"):
            xd = step(xd)
    timer.finalize(xd)
    assert set(timer.phases) == {"h2d", "step"}
    assert timer.phase_s("h2d") > 0 and timer.phase_s("step") > 0
    assert timer.phase_s("unknown") == 0.0
    stats = timer.phase_stats()
    assert stats["step"]["calls"] == 3
    assert stats["step"]["mean_s"] == pytest.approx(
        stats["step"]["total_s"] / 3)


def test_step_timer_reset_is_explicit():
    """Regression (PR 4 satellite): stats must not silently blend
    across runs — reset() clears rounds AND phases, and abandons an
    open round instead of recording it."""
    timer = StepTimer()
    with timer.phase("h2d"):
        pass
    with timer.round(4):
        pass
    timer.finalize()
    assert timer.total_steps == 4 and timer.phases
    with timer.round(2):  # left open on purpose
        timer.reset()
    assert timer.rounds == [] and timer.phases == {}
    assert timer.total_steps == 0 and timer.total_s == 0.0
    timer.finalize()  # the abandoned round must not resurface
    assert timer.rounds == []


def test_trainer_resets_timer_per_run():
    """Two train() calls on one trainer: phase stats describe the
    SECOND run only (the trainers call timer.reset() at train())."""
    import distkeras_tpu as dk
    from helpers import make_blobs, make_mlp

    feats, labels = make_blobs(n=128)
    ds = dk.Dataset({"features": feats, "label": labels})
    t = dk.ADAG(make_mlp(), loss="sparse_categorical_crossentropy",
                worker_optimizer="sgd", learning_rate=0.05, batch_size=4,
                num_epoch=1, communication_window=2)
    t.train(ds)
    rounds_per_run = len(t.history)
    first = t.step_timer.phase_stats()["step"]["calls"]
    t.train(ds)
    again = t.step_timer.phase_stats()["step"]["calls"]
    assert first == again == rounds_per_run, (first, again)


def test_trainer_populates_phase_counters():
    """A distributed trainer run leaves "h2d"/"step" populated — the
    input plane is distinguishable from compute without a profiler."""
    import numpy as np

    import distkeras_tpu as dk
    from helpers import make_blobs, make_mlp

    feats, labels = make_blobs(n=256)
    ds = dk.Dataset({"features": feats, "label": labels})
    t = dk.ADAG(make_mlp(), loss="sparse_categorical_crossentropy",
                worker_optimizer="sgd", learning_rate=0.05, batch_size=4,
                num_epoch=1, communication_window=2)
    t.train(ds)
    assert t.step_timer.phase_s("h2d") > 0
    assert t.step_timer.phase_s("step") > 0
    assert t.step_timer.phase_stats()["step"]["calls"] == len(t.history)


def test_trace_writes_profile(tmp_path):
    logdir = str(tmp_path / "prof")
    with trace(logdir):
        with annotate("matmul_region"):
            y = jax.jit(lambda a: a @ a)(jnp.ones((64, 64)))
            jax.block_until_ready(y)
    files = glob.glob(logdir + "/**/*", recursive=True)
    assert any("trace" in f or "xplane" in f for f in files), files
