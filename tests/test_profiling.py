"""StepTimer + profiler trace smoke (SURVEY.md §5 observability rebuild)."""

import glob

import jax
import jax.numpy as jnp

from distkeras_tpu.utils.profiling import StepTimer, annotate, trace


def test_step_timer_rounds():
    timer = StepTimer()
    step = jax.jit(lambda x: x * 2.0)
    x = jnp.ones((128, 128))
    for _ in range(3):
        with timer.round():
            for _ in range(4):
                x = step(x)
                timer.count()
        timer.finalize(x)
    assert timer.total_steps == 12
    assert len(timer.rounds) == 3
    assert timer.total_s > 0
    assert timer.mean_step_s > 0
    assert timer.samples_per_sec(128) > 0
    assert timer.p50_round_s > 0


def test_trace_writes_profile(tmp_path):
    logdir = str(tmp_path / "prof")
    with trace(logdir):
        with annotate("matmul_region"):
            y = jax.jit(lambda a: a @ a)(jnp.ones((64, 64)))
            jax.block_until_ready(y)
    files = glob.glob(logdir + "/**/*", recursive=True)
    assert any("trace" in f or "xplane" in f for f in files), files
