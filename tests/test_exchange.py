"""Gradient-exchange layer (parallel/exchange.py, docs/lowcomm.md):
Adasum merging, local-SGD periodic sync, and error-feedback
compression on the 8-CPU mesh.

Acceptance contract: every variant's final loss lands within the
DECLARED tolerance of the replicated-DP baseline (``TOL_LOSS`` — the
same bound ``bench_suite.py``'s convergence rows report against);
seeded runs are bit-for-bit deterministic; error-feedback residual
state round-trips both checkpoint backends; and the Supervisor's
kill/resume harness stays bit-for-bit under ``sync_every > 1``.  The
wire-bytes and collective-count claims are proved separately from the
compiled census in tests/test_budget_guards.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.models import transformer as tfm
from distkeras_tpu.parallel import collectives as cl
from distkeras_tpu.parallel import exchange as ex
from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh
from distkeras_tpu.resilience import FaultPlan, Supervisor
from jax.sharding import NamedSharding, PartitionSpec as P

from helpers import make_blobs, make_mlp

CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=32)

# The DECLARED convergence tolerance: a lossy exchange (int8/top-k
# quantization, adasum's adaptive weights, local-SGD's stale period)
# is allowed to land within this absolute final-loss distance of the
# replicated-DP baseline on these seeded toy problems.  bench_suite's
# lowcomm_* rows report against the same bound.
TOL_LOSS = 0.05


def lm_tokens(n=128, s=16):
    return np.random.default_rng(0).integers(0, 64, (n, s + 1)).astype(
        np.int32)


# --------------------------------------------------------- primitives


def test_adasum_pair_mean_for_agreeing_sum_for_orthogonal():
    a = jnp.asarray([1.0, 2.0, 3.0, 0.0])
    # Identical inputs: adasum == the value itself (what mean-reduce
    # of agreeing replicas gives) — the "replicas agree" fallback.
    np.testing.assert_allclose(ex.adasum_combine(jnp.stack([a, a])),
                               a, rtol=1e-6)
    # Orthogonal inputs: the plain sum.
    b = jnp.asarray([0.0, 0.0, 0.0, 5.0])
    np.testing.assert_allclose(ex.adasum_combine(jnp.stack([a, b])),
                               a + b, rtol=1e-6)
    # Zero gradients: plain sum (no NaN from the norm division).
    z = jnp.zeros_like(a)
    np.testing.assert_allclose(ex.adasum_combine(jnp.stack([z, a])),
                               a, rtol=1e-6)


def test_adasum_combine_odd_stack():
    a = jnp.asarray([[1.0, 0.0], [0.0, 1.0], [1.0, 1.0]])
    out = ex.adasum_combine(a)
    assert out.shape == (2,) and bool(jnp.all(jnp.isfinite(out)))


def test_adasum_reduce_primitive(devices):
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P("data", None)))
    # Identical addends on every replica -> the addend itself.  Both
    # cases through ONE jit: adasum_reduce builds a fresh shard_map per
    # call, so separate calls would compile the gather tree twice.
    same = jax.device_put(jnp.broadcast_to(x[0], (8, 16)),
                          NamedSharding(mesh, P("data", None)))
    out, out_same = jax.jit(lambda a, b: (cl.adasum_reduce(a, mesh),
                                          cl.adasum_reduce(b, mesh)))(
        xs, same)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(ex.adasum_combine(x)),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out_same),
                               np.asarray(x[0]), rtol=1e-5)
    with pytest.raises(ValueError, match="axis"):
        cl.adasum_reduce(jnp.ones((4, 16)), mesh)


def test_int8_codec_roundtrip_error_bound(rng):
    x = jnp.asarray(rng.normal(size=(8, 64)), jnp.float32)
    q, scale = ex.int8_encode(x)
    assert q.dtype == jnp.int8 and scale.shape == (8, 1)
    err = np.abs(np.asarray(ex.int8_decode(q, scale) - x))
    # Symmetric quantization error is bounded by half a step per row.
    bound = np.asarray(scale)[:, 0:1] * 0.5 + 1e-7
    assert (err <= bound).all()
    # All-zero rows encode exactly.
    qz, sz = ex.int8_encode(jnp.zeros((2, 4)))
    assert not np.asarray(ex.int8_decode(qz, sz)).any()


def test_exchange_config_validation():
    with pytest.raises(ValueError, match="merge_rule"):
        ex.ExchangeConfig(merge_rule="median")
    with pytest.raises(ValueError, match="compress"):
        ex.ExchangeConfig(compress="fp4")
    with pytest.raises(ValueError, match="sync_every"):
        ex.ExchangeConfig(sync_every=0)
    with pytest.raises(ValueError, match="topk_frac"):
        ex.ExchangeConfig(compress="topk", topk_frac=0.0)
    with pytest.raises(ValueError, match="mean"):
        ex.ExchangeConfig(merge_rule="adasum", compress="int8")
    with pytest.raises(ValueError, match="local-SGD"):
        ex.ExchangeConfig(sync_every=2, compress="int8")
    assert ex.ExchangeConfig().is_default
    assert ex.ExchangeConfig(sync_every=4).label() == "localsgd4"
    assert ex.ExchangeConfig(compress="int8").label() == "int8ef"


def test_wire_bytes_ring_model_matches_census_ratios():
    """The analytic wire accounting (exchange.wire_bytes — what the
    obs gauges and bench rows report) uses the census's ring model, so
    its ratios match the compiled truth: int8 ~4x below the f32
    baseline (scales cost the remainder), mean == baseline, adasum
    costs n/2 x MORE (the whole-stack gather), zero1 legs consistent."""
    n = 8
    leaves = [jax.ShapeDtypeStruct((1024, 64), jnp.float32)]
    layout = cl.Zero1Layout.for_tree(leaves, n, 4.0)
    f32, mean_w = ex.wire_bytes(layout, ex.ExchangeConfig())
    assert mean_w == f32 > 0
    _, int8_w = ex.wire_bytes(layout, ex.ExchangeConfig(compress="int8"))
    assert 3.9 <= f32 / int8_w <= 4.0
    z_f32, z_int8 = ex.wire_bytes(layout,
                                  ex.ExchangeConfig(compress="int8"),
                                  zero1=True)
    assert z_f32 == f32 / 2  # one RS leg vs the AR's two
    assert 3.9 <= z_f32 / z_int8 <= 4.0
    _, ada_w = ex.wire_bytes(layout,
                             ex.ExchangeConfig(merge_rule="adasum"))
    assert ada_w == f32 * n / 2  # gather of n stacks vs 2 AR legs
    _, topk_w = ex.wire_bytes(
        layout, ex.ExchangeConfig(compress="topk", topk_frac=0.01))
    assert 0 < topk_w < int8_w


# ----------------------------------------------- ADAG family variants


def _adag(blobs, **kw):
    feats, labels = blobs
    ds = dk.Dataset({"features": feats, "label": labels})
    t = dk.ADAG(make_mlp(), loss="sparse_categorical_crossentropy",
                worker_optimizer="adam", learning_rate=0.05,
                batch_size=8, num_epoch=2, communication_window=4, **kw)
    state = t._fit(ds)
    return t, state


@pytest.fixture(scope="module")
def adag_base(devices):
    """The replicated-DP ADAG baseline on the shared blobs problem —
    one run, shared by every parity/accounting test (make_blobs() is
    deterministic, so this matches the function-scoped ``blobs``)."""
    return _adag(make_blobs())


@pytest.fixture(scope="module")
def lm_base(devices):
    """The replicated-DP LMTrainer baseline on the 8-way data mesh."""
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    return _lm(mesh) + (mesh,)


@pytest.mark.parametrize("opts", [
    {"merge_rule": "adasum"},
    {"compress": "int8"},
    {"compress": "topk", "topk_frac": 0.1},
    {"sync_every": 4},
    {"zero1": True, "compress": "int8"},
])
def test_adag_variant_converges_to_baseline(devices, blobs, adag_base,
                                            opts):
    """Convergence parity: each exchange variant's final loss within
    the declared tolerance of replicated DP on the blobs MLP."""
    if opts.get("sync_every", 1) > 1:
        # One local-SGD round consumes sync_every x the rows: H=4
        # needs 1024 rows for a round (8 batch x 8 workers x 4 window
        # x 4 local rounds) — the shared 512-row fixture is too small.
        blobs = make_blobs(n=1024)
        base, _ = _adag(blobs)
    else:
        base, _ = adag_base
    t, _ = _adag(blobs, **opts)
    assert abs(t.history[-1] - base.history[-1]) <= TOL_LOSS, (
        opts, t.history[-1], base.history[-1])


def test_adag_variants_deterministic(devices, blobs):
    """Seeded determinism: two identical runs, bit-for-bit histories
    (quantization and the adasum tree are deterministic functions of
    the data; the local-SGD leg is covered bit-for-bit by the
    Supervisor harness below, which trains its config twice)."""
    for opts in ({"compress": "int8"}, {"merge_rule": "adasum"}):
        a, _ = _adag(blobs, **opts)
        b, _ = _adag(blobs, **opts)
        assert a.history == b.history, opts


def test_adag_localsgd_round_accounting(devices, blobs, adag_base):
    """sync_every=H consumes H x the rows per round: half the rounds
    at H=2, and the optimizer step counter advances H per round."""
    base, s0 = adag_base
    t, s1 = _adag(blobs, sync_every=2)
    assert len(t.history) == len(base.history) // 2
    assert int(s1.step) == int(s0.step)  # same optimizer steps total


def test_adag_probe_metrics(devices, blobs, adag_base):
    """The opt-in in-graph probe: same losses as the unprobed run (the
    probe only ADDS outputs), finite grad-norm series, recorded into
    obs at end of run.  The compile-budget delta is zero extra
    programs — pinned by scripts/check_compile_counts.py's sessions
    (the probed step is still ONE program)."""
    base, _ = adag_base
    with dk.obs.session() as sess:
        t, _ = _adag(blobs, probe_metrics=True)
    assert t.history == base.history
    assert len(t.probe_history) == len(t.history)
    assert all(np.isfinite(p["grad_norm"]) for p in t.probe_history)
    snap = sess.registry.compact()
    assert any(k.startswith("train.grad_norm") for k in snap)


def test_adag_int8ef_residual_diagnostic(devices, blobs):
    with dk.obs.session() as sess:
        t, state = _adag(blobs, compress="int8")
    assert np.isfinite(t.residual_norm) and t.residual_norm >= 0
    assert any(k.startswith("exchange.residual_norm")
               for k in sess.registry.compact())
    # The residual state rides the optimizer state as an ExchangeState.
    assert ex.residual_norm_of(state.opt_state) is not None


# ------------------------------------------------------- LM variants


def _lm(mesh, **kw):
    t = dk.LMTrainer(CFG, learning_rate=1e-2, batch_size=16,
                     num_epoch=2, mesh=mesh, **kw)
    params = t.train(lm_tokens())
    return t, params


def test_lm_int8ef_converges_and_is_deterministic(lm_base):
    base, _, mesh = lm_base
    a, _ = _lm(mesh, compress="int8")
    b, _ = _lm(mesh, compress="int8")
    assert abs(a.history[-1] - base.history[-1]) <= TOL_LOSS
    assert a.history == b.history


def test_lm_sync_every_1_and_4_converge(lm_base):
    """sync_every=1 IS the synchronous baseline (the default config);
    sync_every=4 runs 1/4 the rounds and lands within tolerance."""
    base, _, mesh = lm_base
    t, _ = _lm(mesh, sync_every=4)
    # sync_every=1 IS the default exchange — the baseline run covers it.
    assert ex.ExchangeConfig(sync_every=1).is_default
    assert base.exchange.is_default
    assert len(t.history) == len(base.history) // 4
    assert abs(t.history[-1] - base.history[-1]) <= TOL_LOSS


def test_lm_adasum_and_zero1_int8_converge(lm_base):
    base, _, mesh = lm_base
    for opts in ({"merge_rule": "adasum"},
                 {"zero1": True, "compress": "int8"}):
        t, _ = _lm(mesh, **opts)
        assert abs(t.history[-1] - base.history[-1]) <= TOL_LOSS, opts


def test_lm_zero1_int8_shards_opt_memory(devices):
    """zero1 x int8: the inner moments still scatter (the memory win
    survives the codec) and the residuals shard over their replica
    axis — nothing replicated that shouldn't be."""
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    t = dk.LMTrainer(CFG, learning_rate=1e-2, batch_size=16, mesh=mesh,
                     zero1=True, compress="int8")
    params = t.init_params()
    opt_shapes = jax.eval_shape(t.optimizer.init, params)
    psh, osh = t._state_shardings(params, opt_shapes)
    opt_state = jax.jit(t.optimizer.init, out_shardings=osh)(params)
    n_param_bytes = sum(
        l.size * l.dtype.itemsize for l in jax.tree.leaves(params))
    inner_state, exs = opt_state
    # The EF residuals are ~1x params/device by construction (each
    # replica's quantization error on its local contribution); the
    # memory claim is about the INNER moments, so exclude them.
    resid_ids = {id(l) for l in jax.tree.leaves(exs)}
    per_dev = sum(
        l.addressable_shards[0].data.nbytes
        for l in jax.tree.leaves(opt_state)
        if hasattr(l, "addressable_shards") and id(l) not in resid_ids)
    # adamw mu+nu ~= 2x params replicated; scattered they must stay
    # far under that figure.
    assert per_dev < 2 * n_param_bytes / 2.0, (per_dev, n_param_bytes)
    for e in exs.e1:
        assert e.sharding.spec == P("data", None, None)
    for e in exs.e2:
        assert e.sharding.spec == P("data", None)


def test_adag_int8ef_checkpoint_resume(devices, tmp_path, blobs):
    """Error-feedback residual state round-trips the pickle backend:
    the resumed ADAG run continues the uninterrupted run's loss
    trajectory bit-for-bit (a dropped/zeroed residual would fork it).
    The LM spelling (both backends) runs in the merge gate."""
    feats, labels = blobs
    ds = dk.Dataset({"features": feats, "label": labels})
    kw = dict(loss="sparse_categorical_crossentropy",
              worker_optimizer="adam", learning_rate=0.05,
              batch_size=8, communication_window=4, compress="int8",
              checkpoint_backend="pickle")
    full = dk.ADAG(make_mlp(), num_epoch=2,
                   **{k: v for k, v in kw.items()
                      if k != "checkpoint_backend"})
    full.train(ds)
    d = str(tmp_path / "ck")
    first = dk.ADAG(make_mlp(), num_epoch=1, checkpoint_dir=d,
                    checkpoint_every=1, **kw)
    first.train(ds)
    resumed = dk.ADAG(make_mlp(), num_epoch=2, checkpoint_dir=d,
                      checkpoint_every=1, resume=True, **kw)
    resumed.train(ds)
    assert resumed.history == full.history[len(first.history):]


@pytest.mark.parametrize("backend", [
    # Both legs run in the merge gate (LM compiles are the fast gate's
    # scarcest budget); tests/conftest.py SLOW carries the demotion.
    # The fast-gate residual-round-trip representative is the ADAG
    # pickle test above.
    "pickle",
    "orbax",
])
def test_lm_int8ef_checkpoint_resume(devices, tmp_path, backend):
    """Error-feedback residual state round-trips both checkpoint
    backends: the resumed run continues the uninterrupted run's loss
    trajectory (a dropped/zeroed residual would fork it)."""
    if backend == "orbax":
        pytest.importorskip("orbax.checkpoint")
    d = str(tmp_path / "ck")
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    data = lm_tokens()
    kw = dict(learning_rate=1e-2, batch_size=16, mesh=mesh,
              compress="int8", checkpoint_backend=backend)
    full = dk.LMTrainer(CFG, num_epoch=2, **{k: v for k, v in kw.items()
                                             if k != "checkpoint_backend"})
    full.train(data)
    first = dk.LMTrainer(CFG, num_epoch=1, checkpoint_dir=d,
                         checkpoint_every=1, **kw)
    first.train(data)
    resumed = dk.LMTrainer(CFG, num_epoch=2, checkpoint_dir=d,
                           checkpoint_every=1, resume=True, **kw)
    resumed.train(data)
    np.testing.assert_allclose(
        resumed.history, full.history[len(first.history):], rtol=1e-5)


@pytest.mark.chaos
def test_adag_localsgd_supervisor_bit_for_bit(devices, tmp_path, blobs):
    """The resilience acceptance harness under sync_every > 1: an
    injected kill mid-run + Supervisor auto-resume reproduces the
    uninterrupted run's loss trajectory bit-for-bit — a sync period is
    a round, so the checkpoint boundary is always a post-merge state."""
    feats, labels = blobs
    ds = dk.Dataset({"features": feats, "label": labels})
    kw = dict(loss="sparse_categorical_crossentropy",
              worker_optimizer="adam", learning_rate=0.05,
              batch_size=8, num_epoch=2, communication_window=4,
              sync_every=2)

    straight = dk.ADAG(make_mlp(), **kw)
    ref = straight.train(ds)

    t = dk.ADAG(make_mlp(), checkpoint_dir=str(tmp_path / "c"),
                checkpoint_every=1, checkpoint_backend="pickle", **kw)
    sup = Supervisor(t, max_retries=2, backoff=0.0, max_backoff=0.0,
                     jitter=0.0)
    with FaultPlan().fail("train.round", at=2):
        out = sup.run(ds)

    assert t.history == straight.history[1:]  # bit-for-bit
    for wr, wo in zip(ref.get_weights(), out.get_weights()):
        np.testing.assert_allclose(wr, wo, rtol=1e-5, atol=1e-6)
    assert [a.outcome for a in sup.attempts] == ["fault", "ok"]


# ----------------------------------------------------------- guards


def test_exchange_rejections(devices):
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    with pytest.raises(ValueError, match="exchange"):
        dk.AEASGD(make_mlp(), merge_rule="adasum")
    with pytest.raises(ValueError, match="exchange"):
        dk.DOWNPOUR(make_mlp(), compress="int8")
    with pytest.raises(ValueError, match="device_data"):
        dk.ADAG(make_mlp(), compress="int8", device_data=True)
    with pytest.raises(ValueError, match="fsdp"):
        dk.ADAG(make_mlp(), compress="int8", fsdp=True)
    with pytest.raises(ValueError, match="int8"):
        dk.ADAG(make_mlp(), zero1=True, merge_rule="adasum")
    with pytest.raises(ValueError, match="int8"):
        dk.LMTrainer(CFG, mesh=mesh, zero1=True, sync_every=2)
    with pytest.raises(ValueError, match="dropout"):
        dk.LMTrainer(tfm.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
            max_len=32, dropout=0.1), mesh=mesh, compress="int8")
    with pytest.raises(ValueError, match="grad_accum"):
        dk.LMTrainer(CFG, mesh=mesh, sync_every=2, grad_accum=2)
    tp = make_mesh(MeshSpec(data=4, model=2), devices=devices)
    with pytest.raises(ValueError, match="data"):
        dk.LMTrainer(CFG, mesh=tp, merge_rule="adasum")
    with pytest.raises(ValueError, match="LoRATrainer"):
        dk.LoRATrainer(CFG, base_params=tfm.init_params(
            jax.random.key(0), CFG), compress="int8")
    with pytest.raises(ValueError, match="segments"):
        t = dk.LMTrainer(CFG, mesh=mesh, compress="int8")
        rows = lm_tokens(32)
        t.train(rows, segments=np.ones_like(rows))
    # BatchNorm carries non-trainable training state: rejected.
    import keras

    keras.utils.set_random_seed(0)
    bn = keras.Sequential([keras.Input((16,)),
                           keras.layers.Dense(8),
                           keras.layers.BatchNormalization(),
                           keras.layers.Dense(4)])
    with pytest.raises(ValueError, match="non-trainable"):
        dk.ADAG(bn, compress="int8")
    # zero1_bucket_mb threads into the exchange layout on BOTH trainer
    # families (under zero1 x int8 the one knob governs both layouts).
    t = dk.ADAG(make_mlp(), zero1=True, compress="int8",
                zero1_bucket_mb=1.0)
    assert t.exchange.bucket_mb == 1.0
    t = dk.LMTrainer(CFG, mesh=mesh, zero1=True, compress="int8",
                     zero1_bucket_mb=1.0)
    assert t.exchange.bucket_mb == 1.0


def test_exports():
    assert dk.ExchangeConfig is ex.ExchangeConfig
    assert dk.exchange_optimizer is ex.exchange_optimizer
    assert dk.exchange is ex
    assert cl.adasum_reduce is not None
