"""Cluster resilience: heartbeats, epochs, coordinated restart, and
cluster-consistent checkpoint selection (PR 5 tentpole).

Layers under test, cheapest first: the health/epoch primitives with
injected clocks (no processes), the restart DRIVER with stdlib-only
child processes (no jax — proves the coordination protocol alone), the
torn-checkpoint consistency rule on both backends, and finally the
real thing: a 2-process jax.distributed training job whose host 1 is
chaos-killed mid-training — the survivor's collective watchdog fires
within the window, both hosts re-init under a new cluster epoch, and
the resumed run lands on the uninterrupted run's weights.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.checkpoint import CheckpointManager
from distkeras_tpu.resilience import cluster
from distkeras_tpu.resilience.chaos import FaultPlan
from distkeras_tpu.resilience.cluster import (ClusterMember, EpochStore,
                                              cluster_consistent_step,
                                              step_is_valid,
                                              trim_to_consistent,
                                              valid_steps)
from distkeras_tpu.resilience.health import (HealthMonitor,
                                             HeartbeatWriter, read_beat,
                                             write_beat)

from conftest import make_blobs, make_mlp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------- epochs


def test_epoch_store_is_monotone(tmp_path):
    store = EpochStore(str(tmp_path))
    assert store.current() == 0
    store.request(1)
    store.request(1)            # idempotent, concurrent-safe
    assert store.current() == 1
    store.request(3)
    store.request(2)            # late lower request cannot regress
    assert store.current() == 3
    with pytest.raises(ValueError):
        store.request(-1)


# ----------------------------------------------------------- heartbeats


def test_heartbeat_staleness_and_done(tmp_path):
    t = [100.0]
    clock = lambda: t[0]
    d = str(tmp_path / "hb")
    write_beat(d, 0, epoch=0, n=1, clock=clock)
    write_beat(d, 1, epoch=0, n=1, clock=clock)
    mon = HealthMonitor(d, host=0, num_hosts=3, window=2.0, grace=5.0,
                        clock=clock)
    assert mon.stale_peers() == []          # host 2 inside grace
    t[0] += 6.0
    # host 1's beat is now 6s old (> window) and host 2 never beat.
    assert mon.stale_peers() == [1, 2]
    write_beat(d, 1, epoch=0, n=2, clock=clock)
    assert mon.stale_peers() == [2]
    # done beat: clean completion is never read as death
    write_beat(d, 2, epoch=0, n=1, clock=clock, done=True)
    t[0] += 100.0
    assert mon.stale_peers() == [1]         # host 2 done; host 1 stale


def test_heartbeat_epoch_filter(tmp_path):
    """A relaunched cluster must not count a dead host's pre-restart
    beats as liveness in the new generation."""
    t = [0.0]
    clock = lambda: t[0]
    d = str(tmp_path / "hb")
    write_beat(d, 1, epoch=0, n=9, clock=clock)
    mon = HealthMonitor(d, host=0, num_hosts=2, window=10.0, grace=1.0,
                        clock=clock)
    assert mon.stale_peers(epoch=0) == []   # fresh beat, right epoch
    t[0] += 2.0
    assert mon.stale_peers(epoch=1) == [1]  # old-epoch beat filtered


@pytest.mark.chaos
def test_chaos_partition_drops_beats(tmp_path):
    """The ``drop`` fault kind: the host keeps running but its beats
    never publish — a partition as peers see it."""
    d = str(tmp_path / "hb")
    w = HeartbeatWriter(d, host=0, interval=0.05)
    with FaultPlan().drop("cluster.heartbeat", times=None):
        w.beat_once()
        w.beat_once()
    assert read_beat(d, 0) is None          # nothing ever published
    w.beat_once()                           # plan gone: beats flow
    assert read_beat(d, 0)["host"] == 0


def test_watchdog_trips_on_stale_peer_and_requests_epoch(tmp_path):
    """The collective-watchdog core: a peer stops beating -> the
    member requests the next epoch and aborts (injected abort — the
    production default is os._exit, the only way out of a wedged
    collective)."""
    coord = str(tmp_path)
    # Peer host 1 beats once, then goes silent.
    write_beat(os.path.join(coord, "hb"), 1, epoch=0, n=1)
    tripped = []
    m = ClusterMember(coord, host=0, num_hosts=2, epoch=0,
                      heartbeat_interval=0.05, window=0.3, poll=0.05,
                      grace=5.0, abort=tripped.append)
    m.start()
    try:
        deadline = time.monotonic() + 5.0
        while not tripped and time.monotonic() < deadline:
            time.sleep(0.05)
    finally:
        m.stop()
    assert tripped and "1" in tripped[0]
    assert m.epochs.current() == 1          # next epoch requested
    assert m.fault_reason is not None


def test_watchdog_trips_on_epoch_advance(tmp_path):
    coord = str(tmp_path)
    tripped = []
    m = ClusterMember(coord, host=0, num_hosts=1, epoch=0,
                      heartbeat_interval=0.05, window=5.0, poll=0.05,
                      abort=tripped.append)
    m.start()
    try:
        m.epochs.request(1)                 # another host moved on
        deadline = time.monotonic() + 5.0
        while not tripped and time.monotonic() < deadline:
            time.sleep(0.05)
    finally:
        m.stop()
    assert tripped and "epoch 1" in tripped[0]


# ------------------------------------- cluster-consistent checkpoints


def _pickle_store(d, steps):
    with CheckpointManager(str(d), backend="pickle",
                           max_to_keep=10) as m:
        for s in steps:
            m.save({"v": np.float32(s)}, step=s, force=True)


def _tear_pickle(d, step):
    """Truncate the step's payload mid-byte: a host that died inside
    save() on a filesystem without atomic rename."""
    p = os.path.join(str(d), str(step), "state.pkl")
    data = open(p, "rb").read()
    with open(p, "wb") as f:
        f.write(data[:max(1, len(data) // 2)])


def test_cluster_consistent_step_skips_torn_pickle(tmp_path):
    a, b = tmp_path / "h0", tmp_path / "h1"
    _pickle_store(a, [2, 4, 6])
    _pickle_store(b, [2, 4, 6])
    assert cluster_consistent_step([str(a), str(b)]) == 6
    _tear_pickle(b, 6)
    assert not step_is_valid(str(b / "6"))
    assert valid_steps(str(b)) == [2, 4]
    # Highest step valid on EVERY host: host 1's torn 6 disqualifies 6.
    assert cluster_consistent_step([str(a), str(b)]) == 4
    # A step only one host committed never wins either.
    _pickle_store(a, [8])
    assert cluster_consistent_step([str(a), str(b)]) == 4
    kept = trim_to_consistent([str(a), str(b)])
    assert kept == 4
    assert valid_steps(str(a)) == [2, 4]
    assert sorted(int(e) for e in os.listdir(str(b))
                  if e.isdigit()) == [2, 4]


def test_cluster_consistent_step_skips_torn_orbax(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    a, b = tmp_path / "h0", tmp_path / "h1"
    for d in (a, b):
        with CheckpointManager(str(d), backend="orbax",
                               async_save=False) as m:
            for s in (1, 2):
                m.save({"v": np.arange(4.0)}, step=s, force=True)
            m.wait_until_finished()
    assert cluster_consistent_step([str(a), str(b)]) == 2
    # Torn orbax step: the committed-by-name dir exists but its
    # payload never landed (crash mid-save without atomic rename).
    for e in os.listdir(str(b / "2")):
        path = b / "2" / e
        if path.is_dir():
            import shutil

            shutil.rmtree(path)
        else:
            path.unlink()
    assert not step_is_valid(str(b / "2"))
    assert cluster_consistent_step([str(a), str(b)]) == 1
    # Shared-store case (multi-host orbax): one real dir, deduped.
    assert cluster_consistent_step([str(a), str(a)]) == 2


def test_trainer_restore_skips_torn_latest(tmp_path):
    """Trainers' restore validation (tentpole satellite): a torn
    latest checkpoint must not crash resume — the trainer falls back
    to the latest VALID step, replays from there, and still lands on
    the uninterrupted run's weights."""
    x, y = make_blobs(n=128)
    ds = dk.Dataset.from_arrays(x, y)
    common = dict(loss="sparse_categorical_crossentropy",
                  worker_optimizer="sgd", learning_rate=0.05,
                  batch_size=16, num_epoch=2)
    ref = dk.SingleTrainer(make_mlp(), **common).train(ds)

    ckdir = str(tmp_path / "c")
    t = dk.SingleTrainer(make_mlp(), checkpoint_dir=ckdir,
                         checkpoint_every=1, checkpoint_backend="pickle",
                         max_checkpoints=100, **common)
    t.train(ds)
    steps = sorted(int(e) for e in os.listdir(ckdir) if e.isdigit())
    _tear_pickle(tmp_path / "c", steps[-1])
    _tear_pickle(tmp_path / "c", steps[-2])

    resumed = dk.SingleTrainer(make_mlp(), checkpoint_dir=ckdir,
                               checkpoint_every=1, resume=True,
                               checkpoint_backend="pickle",
                               max_checkpoints=100, **common)
    with pytest.warns(UserWarning, match="torn/partial"):
        out = resumed.train(ds)
    # Resumed from the last VALID step: replays the torn rounds.
    assert len(resumed.history) == 2
    for wr, wo in zip(ref.get_weights(), out.get_weights()):
        np.testing.assert_allclose(np.asarray(wr), np.asarray(wo),
                                   rtol=1e-5, atol=1e-6)


# ------------------------------------------- driver protocol (no jax)

# A stdlib-only cluster child: imports health/cluster through stub
# parent packages (no jax, no keras — ~0.2 s startup), beats, "works",
# and at epoch 0 host 1 hard-dies mid-work.  Proves the driver
# protocol — detection, epoch bump, barrier, relaunch — in seconds.
DRIVER_CHILD = """
import importlib, os, sys, time, types
for name, path in (("distkeras_tpu", {pkg!r}),
                   ("distkeras_tpu.resilience", {res!r})):
    mod = types.ModuleType(name)
    mod.__path__ = [path]
    sys.modules[name] = mod
cluster = importlib.import_module("distkeras_tpu.resilience.cluster")

member = cluster.member_from_env()
member.start()
if member.epoch == 0 and member.host == 1:
    time.sleep(0.6)
    os._exit(137)                     # hard host loss, no cleanup
time.sleep(2.5)                       # "training"
member.complete()
print("host", member.host, "epoch", member.epoch, "done", flush=True)
"""


@pytest.mark.multiprocess
def test_driver_coordinated_restart_protocol(tmp_path):
    """Two drivers, stdlib children: host 1 dies at epoch 0 -> host
    0's child watchdog aborts (EXIT_RESTART), both drivers meet at the
    epoch-1 barrier and relaunch, epoch 1 completes on both hosts."""
    pkg = os.path.join(REPO, "distkeras_tpu")
    res = os.path.join(pkg, "resilience")
    child = DRIVER_CHILD.format(pkg=pkg, res=res)
    summaries = cluster.run_cluster_local(
        [sys.executable, "-c", child], num_hosts=2,
        coord_dir=str(tmp_path / "coord"), base_port=0,
        window=0.6, poll=0.1, heartbeat_interval=0.15, grace=20.0,
        max_restarts=2, barrier_timeout=30.0, attempt_timeout=60.0)
    for s in summaries:
        assert s["epochs"] == 2, s        # exactly one restart
        assert s["restarts"] == 1, s
    # The dead host's driver recorded the failed attempt; host 0's
    # recorded either the watchdog abort rc or a driver-side kill.
    rcs = [a["rc"] for a in summaries[1]["history"]
           if a["event"] == "attempt"]
    assert rcs[0] == 137 and rcs[-1] == 0


# A stdlib-only flapping child: increments a counter file, dies with
# EXIT_RESTART for the first three runs (after `uptime` seconds of
# "healthy training"), completes on the fourth.  No cluster imports at
# all — with one host the driver protocol needs no member.
FLAP_CHILD = """
import os, sys, time
path, uptime = sys.argv[1], float(sys.argv[2])
n = int(open(path).read()) if os.path.exists(path) else 0
open(path, "w").write(str(n + 1))
if n < 3:
    time.sleep(uptime)
    os._exit(75)
"""


def _flap_driver(tmp_path, uptime, **kw):
    counter = str(tmp_path / "count")
    sup = cluster.ClusterSupervisor(
        str(tmp_path / "coord"), 0, 1,
        [sys.executable, "-c", FLAP_CHILD, counter, str(uptime)],
        poll=0.05, barrier_timeout=10.0, **kw)
    return sup


@pytest.mark.multiprocess
def test_flap_dampening_refunds_restart_budget(tmp_path):
    """The 3-flap ladder (ROADMAP carried follow-up): three healthy-
    then-dead attempts against max_restarts=1.  Without the refund the
    budget burns on flap 2; with ``healthy_uptime`` below each flap's
    uptime, every healthy attempt refunds the budget and the job
    completes with the counter never exceeding 1."""
    sup = _flap_driver(tmp_path, uptime=0.5, max_restarts=1,
                       healthy_uptime=0.2)
    summary = sup.run()
    attempts = [a for a in sup.history if a["event"] == "attempt"]
    refunds = [a for a in sup.history if a["event"] == "refund"]
    assert [a["rc"] for a in attempts] == [75, 75, 75, 0]
    assert len(refunds) == 2            # flaps 2 and 3 were forgiven
    assert summary["restarts"] == 1     # never exceeded the budget
    assert summary["epochs"] == 4


@pytest.mark.multiprocess
def test_flap_ladder_exhausts_without_refund(tmp_path):
    """Same ladder with the refund disabled: the pre-dampening
    behavior — three flaps burn max_restarts=1 and the driver gives
    up — pinned so the refund stays opt-in."""
    sup = _flap_driver(tmp_path, uptime=0.5, max_restarts=1,
                       healthy_uptime=None)
    with pytest.raises(cluster.ClusterGivenUp):
        sup.run()


@pytest.mark.multiprocess
def test_rapid_crash_loop_still_exhausts_with_refund(tmp_path):
    """A genuine crash loop (uptime below ``healthy_uptime``) must
    still exhaust the budget — the refund forgives flaps, not loops."""
    sup = _flap_driver(tmp_path, uptime=0.0, max_restarts=1,
                       healthy_uptime=30.0)
    with pytest.raises(cluster.ClusterGivenUp):
        sup.run()
    assert not [a for a in sup.history if a["event"] == "refund"]


@pytest.mark.multiprocess
def test_hung_child_timeout_kills_never_refund(tmp_path):
    """A deterministically hung child always outlives ``healthy_uptime``,
    so attempt-timeout kills must NOT refund the budget — otherwise the
    supervisor would kill and relaunch the same hang forever and
    ClusterGivenUp would be unreachable."""
    sup = cluster.ClusterSupervisor(
        str(tmp_path / "coord"), 0, 1,
        [sys.executable, "-c", "import time; time.sleep(60)"],
        poll=0.05, barrier_timeout=10.0, max_restarts=1,
        attempt_timeout=0.3, healthy_uptime=0.1)
    with pytest.raises(cluster.ClusterGivenUp):
        sup.run()
    attempts = [a for a in sup.history if a["event"] == "attempt"]
    assert all(a["reason"] == "attempt timeout" for a in attempts)
    assert len(attempts) == 2           # max_restarts=1 bounded it
    assert not [a for a in sup.history if a["event"] == "refund"]


def test_member_from_env_round_trip(tmp_path, monkeypatch):
    monkeypatch.setenv("DKT_CLUSTER_DIR", str(tmp_path))
    monkeypatch.setenv("DKT_CLUSTER_HOST", "1")
    monkeypatch.setenv("DKT_CLUSTER_NHOSTS", "4")
    monkeypatch.setenv("DKT_CLUSTER_EPOCH", "3")
    monkeypatch.setenv("DKT_CLUSTER_BASE_PORT", "9100")
    m = cluster.member_from_env()
    assert (m.host, m.num_hosts, m.epoch) == (1, 4, 3)
    assert m.coordinator_address == "localhost:9103"  # epoch-stamped


# ------------------------------------------------ the real thing (jax)


def _load_chaos_suite():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "chaos_suite", os.path.join(REPO, "scripts", "chaos_suite.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.multihost
@pytest.mark.multiprocess
def test_two_process_kill_one_host_coordinated_restart(tmp_path):
    """The fast-gate smoke (bounded: every layer has a timeout well
    under 120 s): 2 jax.distributed processes train the tiny LM under
    per-host Supervisors; chaos hard-kills host 1 at round 5.  Host
    0 wedges in the next collective, its watchdog detects the missed
    heartbeats inside the window and aborts; both drivers meet at the
    epoch-1 barrier, re-init jax.distributed on the epoch-stamped
    port, resume from the cluster-consistent checkpoint, and finish.
    The resumed weights must match the uninterrupted run (the
    byte-exact 2-process-vs-2-process comparison runs in the slow
    chaos ladder; here the reference is the single-process run over
    the same global batches — identical math, reduction-order
    tolerance)."""
    cs = _load_chaos_suite()
    summaries, out, traces, fed = cs.run_cluster_scenario(
        "kill", 0, str(tmp_path), window=2.0, attempt_timeout=100.0,
        num_epoch=1, kill_round=3)
    for s in summaries:
        assert s["epochs"] == 2 and s["restarts"] == 1, s
    assert os.path.exists(out)
    # Live telemetry plane (round 11): host 0's /metrics/cluster
    # federated BOTH hosts' live servers at some point during the run.
    assert any(up >= {0, 1} for _, up in fed), (
        f"/metrics/cluster never federated both hosts: "
        f"{[sorted(u) for _, u in fed][:20]}")

    # Chaos really killed host 1 (its epoch-0 trace records the
    # injected fault) and BOTH hosts started an epoch-1 attempt (the
    # coordinated restart).  How the survivor noticed is environment-
    # dependent and both paths are by-design: a wedged collective is
    # aborted by the watchdog (cluster.fault event — the stall/drop
    # ladder scenarios and the unit tests pin that path), while this
    # container's gloo fails fast and the Supervisor's re-raise takes
    # the child down for the driver to restart.
    from distkeras_tpu.obs.report import merge_traces

    merged = merge_traces(traces)
    names = [(e["host"], e["name"]) for e in merged["timeline"]]
    assert (1, "chaos.fault") in names
    epoch1 = [(e["host"], e["fields"].get("epoch"))
              for e in merged["timeline"] if e["name"] == "cluster.child"]
    assert (0, 1) in epoch1 and (1, 1) in epoch1

    # Uninterrupted single-process reference over the same global data.
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 64, (64, 17)).astype(np.int32)
    from distkeras_tpu.models.transformer import TransformerConfig

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=17)
    t = dk.LMTrainer(cfg, optimizer="sgd", learning_rate=0.05,
                     batch_size=16, num_epoch=1)
    params = t.train(tokens)
    import jax

    ref = {"/".join(map(str, p)): np.asarray(v) for p, v in
           jax.tree_util.tree_flatten_with_path(params)[0]}
    got = np.load(out)
    # Killed at round 3 with rounds 1-2 committed: the resumed attempt
    # replays rounds 3-4 only.
    np.testing.assert_allclose(got["losses"], np.asarray(t.history)[2:],
                               rtol=1e-4, atol=1e-5)
    for k, v in ref.items():
        np.testing.assert_allclose(got[k], v, rtol=1e-4, atol=1e-5,
                                   err_msg=k)


@pytest.mark.slow
@pytest.mark.multihost
@pytest.mark.multiprocess
def test_chaos_suite_cluster_ladder(tmp_path):
    """`chaos_suite.py --cluster`: the full fault ladder (host-kill,
    heartbeat-stall, partition), each scenario's resumed weights
    BIT-FOR-BIT against an uninterrupted 2-process reference, plus the
    machine-readable cross-host fault/recovery timeline assembled by
    the obs_report --merge machinery."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "chaos_suite.py"),
         "--cluster", "--workdir", str(tmp_path / "w")],
        capture_output=True, text=True, timeout=900,
        env={**os.environ, "PYTHONPATH": REPO})
    assert proc.returncode == 0, proc.stdout[-4000:] + proc.stderr[-2000:]
    assert "all scenarios passed" in proc.stdout
    # The timeline is machine-readable: JSON object lines with host +
    # event fields, containing the injected fault and the watchdog
    # trip.
    lines = [l for l in proc.stdout.splitlines()
             if l.startswith("{")]
    events = [json.loads(l) for l in lines]
    assert any(e["event"] == "chaos.fault" for e in events)
    assert any(e["event"] == "cluster.fault" for e in events)
    assert all("t" in e and "host" in e for e in events)
