"""Shared test fixtures' builders, importable without conftest's
environment mutation (conftest appends XLA_FLAGS at import, which a
subprocess that configured its own device count must not re-run)."""

import numpy as np


def make_blobs(n=512, dim=16, classes=4, seed=0):
    """Linearly separable gaussian blobs — learnable in a few steps."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(0, 4.0, (classes, dim))
    labels = rng.integers(0, classes, n)
    feats = centers[labels] + rng.normal(0, 0.5, (n, dim))
    return feats.astype(np.float32), labels.astype(np.int64)


def make_mlp(dim=16, classes=4, hidden=32, seed=0):
    import keras

    keras.utils.set_random_seed(seed)
    return keras.Sequential([
        keras.Input((dim,)),
        keras.layers.Dense(hidden, activation="relu"),
        keras.layers.Dense(classes),
    ])
