"""Live train→serve weight push (round 20): atomic versioned publish,
zero-recompile hot swap, and the SLO-gated canary rollout.

The contracts pinned here:
- a reader NEVER adopts a torn/corrupt/stale snapshot;
- ``swap_params`` replaces the served weights between steps, version-
  monotone, atomically under the admission lock — per-version tokens
  are bit-identical to a solo ``generate`` run under those params;
- the canary controller promotes a good push fleet-wide and rolls a
  bad one back (NaN drift, chaos fault at the promote probe), always
  under a bumped router epoch;
- the autoscaler's decision timeline is blind to ``param_version``.
"""

import json
import os
import threading

import jax
import numpy as np
import pytest

from distkeras_tpu.models import transformer as tfm
from distkeras_tpu.models.generate import generate
from distkeras_tpu.resilience import chaos
from distkeras_tpu.serving import (CanaryController, ContinuousBatcher,
                                   InProcessReplica, Router,
                                   SnapshotCorrupt, SnapshotPublisher,
                                   SnapshotReader, StaleSnapshot)
from distkeras_tpu.utils import locks


CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=32, rope=True)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(jax.random.key(0), CFG)


@pytest.fixture(scope="module")
def params_v1():
    return tfm.init_params(jax.random.key(1), CFG)


@pytest.fixture(scope="module")
def template():
    return jax.eval_shape(lambda: tfm.init_params(jax.random.key(0), CFG))


def np_tree(tree):
    return jax.tree.map(np.asarray, tree)


def solo(params, prompt, n):
    return np.asarray(generate(params, np.asarray(prompt)[None], CFG,
                               n))[0]


# ------------------------------------------------------------- publish


def test_publish_roundtrip_raw_and_int8(tmp_path, params, template):
    tree = np_tree(params)
    for coding in (None, "int8"):
        root = tmp_path / (coding or "raw")
        SnapshotPublisher(str(root), coding=coding).publish(tree, 3)
        reader = SnapshotReader(str(root))
        assert reader.latest_version() == 3
        version, got = reader.poll(template)
        assert version == 3
        assert (jax.tree_util.tree_structure(got)
                == jax.tree_util.tree_structure(tree))
        for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(tree)):
            assert a.shape == b.shape and a.dtype == b.dtype
            if coding is None:
                np.testing.assert_array_equal(a, b)
            else:
                assert float(np.max(np.abs(
                    np.asarray(a, np.float32)
                    - np.asarray(b, np.float32)))) < 0.1


def test_reader_declines_torn_manifest(tmp_path, params, template):
    root = str(tmp_path)
    SnapshotPublisher(root).publish(np_tree(params), 1)
    # A publish killed between bucket writes and the manifest rename:
    # bucket files exist, MANIFEST.json does not, LATEST still says 1.
    os.makedirs(os.path.join(root, "v00000002"))
    with open(os.path.join(root, "v00000002", "bucket_0000.npz"),
              "wb") as f:
        f.write(b"partial")
    reader = SnapshotReader(root)
    assert reader.latest_version() == 1
    with pytest.raises(SnapshotCorrupt):
        reader.load(2, template)
    # The good version is untouched by the torn sibling.
    assert reader.poll(template)[0] == 1


def test_reader_declines_checksum_mismatch(tmp_path, params, template):
    root = str(tmp_path)
    SnapshotPublisher(root).publish(np_tree(params), 1)
    manifest = os.path.join(root, "v00000001", "MANIFEST.json")
    with open(manifest) as f:
        body = json.load(f)
    # A VALID npz whose payload does not match the manifest checksum
    # (silent disk corruption, not a torn write).
    bucket = os.path.join(root, "v00000001", body["buckets"][0]["file"])
    data = np.load(bucket)["raw"].copy()
    data[0] ^= 0xFF
    np.savez(bucket[:-4], raw=data)
    with pytest.raises(SnapshotCorrupt):
        SnapshotReader(root).load(1, template)


def test_reader_declines_stale_version(tmp_path, params, template):
    root = str(tmp_path)
    pub = SnapshotPublisher(root)
    pub.publish(np_tree(params), 1)
    pub.publish(np_tree(params), 2)
    reader = SnapshotReader(root)
    reader.adopt(2)
    with pytest.raises(StaleSnapshot):
        reader.load(1, template)
    with pytest.raises(StaleSnapshot):
        reader.load(2, template)
    assert reader.poll(template) is None


# ------------------------------------------------------------ hot swap


def test_hot_swap_per_version_parity(params, params_v1, rng):
    """Each param version's tokens are bit-identical to a solo
    generate() run under those params — across swap and rollback."""
    eng = ContinuousBatcher(params, CFG, lanes=2, hot_swap=True)
    prompt = rng.integers(0, 64, (5,)).astype(np.int32)

    def serve():
        lane = eng.submit(prompt, 6)
        while lane in eng.running():
            eng.step()
        return eng.drain(lane)

    np.testing.assert_array_equal(serve(), solo(params, prompt, 6))
    assert eng.param_version == 0
    eng.swap_params(np_tree(params_v1), 1)
    assert eng.param_version == 1
    np.testing.assert_array_equal(serve(), solo(params_v1, prompt, 6))
    # Rollback path: downgrade restores version 0's exact tokens.
    eng.swap_params(np_tree(params), 0, allow_downgrade=True)
    np.testing.assert_array_equal(serve(), solo(params, prompt, 6))


def test_swap_validation(params, params_v1):
    eng = ContinuousBatcher(params, CFG, lanes=2, hot_swap=True)
    eng.swap_params(np_tree(params_v1), 2)
    with pytest.raises(ValueError, match="monotone|<="):
        eng.swap_params(np_tree(params), 2)
    with pytest.raises(ValueError, match="monotone|<="):
        eng.swap_params(np_tree(params), 1)
    bad = {k: v for k, v in np_tree(params).items() if k != "tok_emb"}
    with pytest.raises(ValueError):
        eng.swap_params(bad, 3)
    plain = ContinuousBatcher(params, CFG, lanes=2)
    with pytest.raises(ValueError, match="hot_swap"):
        plain.swap_params(np_tree(params_v1), 1)


def test_hot_swap_rejects_baked_prefix_state(params):
    from distkeras_tpu.serving import PrefixPool

    with pytest.raises(ValueError, match="hot_swap"):
        ContinuousBatcher(params, CFG, lanes=2, hot_swap=True,
                          prefix_pool=PrefixPool(CFG, slots=1))


def test_concurrent_publish_while_swap_atomic(tmp_path, params,
                                              params_v1, template,
                                              rng):
    """A publisher thread and a swap+serve loop race: every serve
    wave's tokens must match exactly one version (never a mix), and
    the lock ledger stays clean."""
    root = str(tmp_path)
    pub = SnapshotPublisher(root)
    reader = SnapshotReader(root)
    eng = ContinuousBatcher(params, CFG, lanes=2, hot_swap=True)
    prompt = rng.integers(0, 64, (5,)).astype(np.int32)
    refs = {0: solo(params, prompt, 6), 1: solo(params_v1, prompt, 6)}
    trees = {1: np_tree(params_v1), 2: np_tree(params)}
    base_viol = locks.violation_count()
    errs = []
    adopted_v1 = threading.Event()

    def publish_loop():
        try:
            pub.publish(trees[1], 1)
            # Hold v2 until the serving side has actually swapped v1
            # in, so the race covers BOTH transitions.
            adopted_v1.wait(timeout=30)
            pub.publish(trees[2], 2)
        except Exception as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    t = threading.Thread(target=publish_loop)
    t.start()
    seen = []
    for _ in range(50):
        nxt = reader.poll(template)
        if nxt is not None:
            version, tree = nxt
            eng.swap_params(tree, version)
            reader.adopt(version)
            if version >= 1:
                adopted_v1.set()
        lane = eng.submit(prompt, 6)
        while lane in eng.running():
            eng.step()
        out = np.asarray(eng.drain(lane))
        matched = [v for v, ref in refs.items()
                   if np.array_equal(out, ref)]
        assert matched, "serve wave matched NO whole version (torn mix)"
        seen.append(matched[0])
        if eng.param_version == 2:
            break
    t.join()
    assert not errs, errs
    assert eng.param_version == 2
    # v2 re-publishes version-0's weights: both references must have
    # been served across the race.
    assert {0, 1} <= set(seen), seen
    assert locks.violation_count() == base_viol


# -------------------------------------------------------------- canary


def fleet(params, n=2):
    engines = [ContinuousBatcher(params, CFG, lanes=2, hot_swap=True)
               for _ in range(n)]
    router = Router([InProcessReplica(f"r{i}", e)
                     for i, e in enumerate(engines)])
    return engines, router


def wave(router, n=3, max_new=5):
    rids = [router.enqueue([1 + i, 2, 3], max_new) for i in range(n)]
    out = []
    for r in rids:
        res = router.drain(r)
        toks = res["tokens"] if isinstance(res, dict) else res.tokens
        out.append(tuple(int(t) for t in toks))
    return out


def test_canary_lifecycle(params, params_v1, template):
    """Promote → NaN rollback → chaos fault at the promote probe →
    quarantine, with per-replica ``param_version`` in the fleet
    snapshot and a clean lock ledger throughout."""
    engines, router = fleet(params)
    ctl = CanaryController(router, None, CFG, template)
    base_viol = locks.violation_count()
    v1 = np_tree(params_v1)

    snap = router.fleet_snapshot()
    assert all(r["param_version"] == 0
               for r in snap["replicas"].values())
    epoch0 = snap["epoch"]

    rec = ctl.rollout(1, v1)
    assert rec["action"] == "promote" and rec["promoted"] == 2
    assert all(e.param_version == 1 for e in engines)
    snap = router.fleet_snapshot()
    assert all(r["param_version"] == 1
               for r in snap["replicas"].values())
    assert snap["epoch"] > epoch0
    served = wave(router)

    bad = jax.tree.map(lambda a: np.full_like(a, np.nan), v1)
    rec = ctl.rollout(2, bad)
    assert rec["action"] == "rollback" and rec["reason"] == "drift"
    assert rec["drift"] == float("inf")
    assert all(e.param_version == 1 for e in engines)
    assert wave(router) == served

    plan = chaos.FaultPlan().fail("canary.promote", at=3)
    with plan:
        with pytest.raises(chaos.FaultInjected):
            ctl.rollout(3, v1)
    assert ("canary.promote", 3, "fail") in plan.events
    assert all(e.param_version == 1 for e in engines)
    assert wave(router) == served
    assert locks.violation_count() == base_viol


def test_canary_poll_quarantines_rejected_version(tmp_path, params,
                                                  params_v1, template):
    root = str(tmp_path)
    pub = SnapshotPublisher(root)
    engines, router = fleet(params)
    ctl = CanaryController(router, SnapshotReader(root), CFG, template)
    pub.publish(np_tree(params_v1), 1)
    assert ctl.poll()["action"] == "promote"
    bad = jax.tree.map(lambda a: np.full_like(a, np.nan),
                       np_tree(params_v1))
    pub.publish(bad, 2)
    assert ctl.poll()["action"] == "rollback"
    assert all(e.param_version == 1 for e in engines)
    # The rejected version is pushed ONCE — the next tick skips it.
    assert ctl.poll() is None


def test_autoscaler_ignores_param_version(params, params_v1):
    """Small fix regression: ``param_version`` rides the fleet
    snapshot, and the scaling-decision timeline is identical whether
    or not a swap lands between ticks."""
    from distkeras_tpu.serving import (AutoscalePolicy, Autoscaler,
                                       WarmPool)

    def run(swap):
        engines, router = fleet(params)
        spare = ContinuousBatcher(params, CFG, lanes=2, hot_swap=True)
        asc = Autoscaler(router, WarmPool([InProcessReplica("w0",
                                                            spare)]),
                         policy=AutoscalePolicy(
                             min_replicas=1, max_replicas=3,
                             up_after=1, down_after=10,
                             cooldown_ticks=0))
        timeline = []
        for tick in range(4):
            if swap and tick == 2:
                for e in engines:
                    e.swap_params(np_tree(params_v1), 1)
            rec = asc.tick()
            timeline.append((tick, rec["action"]))
        return timeline

    assert run(swap=False) == run(swap=True)


# ------------------------------------------------------ trainer hook


def test_trainer_publishes_and_fleet_adopts(tmp_path, params,
                                            template, devices):
    """The closed loop: an LMTrainer publishes every round while a
    hot_swap fleet polls — versions advance mid-session and the final
    served weights are the final trained weights."""
    import distkeras_tpu as dk
    from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh

    root = str(tmp_path)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, 64, (32, 17)).astype(np.int32)
    mesh = make_mesh(MeshSpec(data=2), devices=devices[:2])
    t = dk.LMTrainer(CFG, learning_rate=1e-2, batch_size=16,
                     num_epoch=2, mesh=mesh)
    t.attach_publisher(SnapshotPublisher(root), every=1)

    engines, router = fleet(params)
    ctl = CanaryController(router, SnapshotReader(root), CFG, template)
    versions = []
    done = threading.Event()

    def poll_loop():
        while not done.is_set():
            rec = ctl.poll()
            if rec is not None and rec["action"] == "promote":
                versions.append(rec["version"])
            done.wait(0.01)

    poller = threading.Thread(target=poll_loop)
    poller.start()
    try:
        trained = t.train(dk.Dataset({"tokens": toks}))
    finally:
        done.set()
        poller.join()
    # Drain any publish the poller missed after training finished.
    rec = ctl.poll()
    if rec is not None and rec["action"] == "promote":
        versions.append(rec["version"])
    rounds = len(t.history)
    assert versions and versions[-1] == rounds, (versions, rounds)
    assert all(e.param_version == rounds for e in engines)
    # The fleet serves the trainer's final weights, bit-exactly.
    prompt = np.asarray([1, 2, 3], np.int32)
    rid = router.enqueue(prompt, 5)
    res = router.drain(rid)
    toks_served = res["tokens"] if isinstance(res, dict) else res.tokens
    np.testing.assert_array_equal(np.asarray(toks_served),
                                  solo(trained, prompt, 5))
