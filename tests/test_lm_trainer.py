"""LMTrainer: the transformer under the trainer-family contract, across
mesh configurations, plus streaming prediction."""

import jax
import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.models import transformer as tfm
from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh


CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=32)


def tokens(rng, n=64, s=16):
    return rng.integers(0, 64, (n, s + 1)).astype(np.int32)


def _loss_falls(history):
    assert history[-1] < history[0] * 0.85, history[::max(1, len(history)//5)]


def test_lm_trainer_dp(devices, rng):
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    t = dk.LMTrainer(CFG, learning_rate=1e-2, batch_size=16, num_epoch=8,
                     mesh=mesh)
    params = t.train(dk.Dataset({"tokens": tokens(rng)}))
    assert t.training_time > 0 and len(t.history) == 32
    _loss_falls(t.history)
    assert params["tok_emb"].shape == (64, 32)


def test_lm_trainer_tp_sp(devices, rng):
    mesh = make_mesh(MeshSpec(data=2, model=2, seq=2), devices=devices)
    t = dk.LMTrainer(CFG, learning_rate=1e-2, batch_size=16, num_epoch=8,
                     mesh=mesh)
    t.train(tokens(rng))
    _loss_falls(t.history)


def test_lm_trainer_pp_ep(devices, rng):
    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32,
                                num_experts=2, capacity_factor=2.0)
    mesh = make_mesh(MeshSpec(data=2, pipeline=2, expert=2), devices=devices)
    t = dk.LMTrainer(cfg, learning_rate=1e-2, batch_size=16, num_epoch=8,
                     mesh=mesh)
    t.train(tokens(rng))
    _loss_falls(t.history)


def test_lm_trainer_pp_sp(devices, rng):
    """PP x SP composed: pipelined trunk with the nested ring inside."""
    mesh = make_mesh(MeshSpec(data=2, pipeline=2, seq=2), devices=devices)
    t = dk.LMTrainer(CFG, learning_rate=1e-2, batch_size=16, num_epoch=8,
                     mesh=mesh)
    t.train(tokens(rng))
    _loss_falls(t.history)


def test_lm_trainer_validates_batch(devices, rng):
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    with pytest.raises(ValueError, match="batch_size"):
        dk.LMTrainer(CFG, batch_size=12, mesh=mesh).train(tokens(rng))


def test_lm_trainer_unknown_optimizer(devices):
    with pytest.raises(ValueError, match="unknown optimizer"):
        dk.LMTrainer(CFG, optimizer="lion")


def test_predict_stream(devices, rng):
    import keras

    keras.utils.set_random_seed(0)
    model = keras.Sequential([keras.Input((8,)),
                              keras.layers.Dense(4)])
    pred = dk.ModelPredictor(model, batch_size=16)
    stream = [rng.normal(size=(n, 8)).astype(np.float32) for n in (5, 16, 33)]
    outs = list(pred.predict_stream(iter(stream)))
    assert [len(o) for o in outs] == [5, 16, 33]
    # Matches the batch path.
    ref = pred.predict(dk.Dataset.from_arrays(stream[2]))["prediction"]
    np.testing.assert_allclose(outs[2], ref, atol=1e-6)


def test_lm_trainer_accepts_optax_optimizers(devices, rng):
    import optax

    mesh = make_mesh(MeshSpec(data=2), devices=devices[:2])
    # Prebuilt GradientTransformation.
    t = dk.LMTrainer(CFG, optimizer=optax.lion(1e-3), batch_size=8,
                     num_epoch=1, mesh=mesh)
    t.train(tokens(rng, n=16))
    # Factory callable gets learning_rate applied.
    t2 = dk.LMTrainer(CFG, optimizer=optax.lion, learning_rate=1e-3,
                      batch_size=8, num_epoch=1, mesh=mesh)
    t2.train(tokens(rng, n=16))


def test_lm_trainer_microbatches_requires_pipeline(devices):
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    with pytest.raises(ValueError, match="pipeline"):
        dk.LMTrainer(CFG, mesh=mesh, microbatches=4)


def test_predict_stream_empty_poll(devices, rng):
    import keras

    keras.utils.set_random_seed(0)
    model = keras.Sequential([keras.Input((8,)), keras.layers.Dense(4)])
    pred = dk.ModelPredictor(model, batch_size=16)
    outs = list(pred.predict_stream([np.zeros((0, 8), np.float32),
                                     rng.normal(size=(3, 8)).astype(np.float32)]))
    assert outs[0].shape == (0, 4)
    assert outs[1].shape == (3, 4)


def test_single_trainer_loss_positional_not_shadowed(devices):
    from tests.conftest import make_mlp
    from distkeras_tpu import SingleTrainer

    t = SingleTrainer(make_mlp(), "sparse_categorical_crossentropy",
                      learning_rate=0.1, batch_size=16)
    assert t.steps_per_call == 1


def test_lm_trainer_rejects_mesh_missing_axes(devices):
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(devices).reshape(8), ("batch",))
    with pytest.raises(ValueError, match="missing axes"):
        dk.LMTrainer(CFG, mesh=mesh)


def test_lm_trainer_rejects_indivisible_seq(devices, rng):
    mesh = make_mesh(MeshSpec(data=4, seq=2), devices=devices)
    t = dk.LMTrainer(CFG, batch_size=8, mesh=mesh)
    with pytest.raises(ValueError, match="seq axis"):
        t.train(tokens(rng, s=15))  # 15 positions, seq axis 2


def test_lm_trainer_shuffle_deterministic(devices, rng):
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    toks = tokens(rng, n=64)
    runs = []
    for _ in range(2):
        t = dk.LMTrainer(CFG, learning_rate=1e-2, batch_size=16, num_epoch=2,
                         mesh=mesh, shuffle=True, seed=7)
        runs.append(t.train(toks.copy()))
    for a, b in zip(jax.tree.leaves(runs[0]), jax.tree.leaves(runs[1])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_lm_trainer_resume_matches_straight_run(tmp_path, devices, rng):
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    toks = tokens(rng, n=64)
    common = dict(learning_rate=1e-2, batch_size=16, mesh=mesh,
                  shuffle=True, seed=3)

    straight = dk.LMTrainer(CFG, num_epoch=4, **common)
    ref = straight.train(dk.Dataset({"tokens": toks}))

    d = str(tmp_path / "ckpt")
    first = dk.LMTrainer(CFG, num_epoch=2, checkpoint_dir=d, **common)
    first.train(dk.Dataset({"tokens": toks}))
    resumed = dk.LMTrainer(CFG, num_epoch=4, checkpoint_dir=d, resume=True,
                           **common)
    out = resumed.train(dk.Dataset({"tokens": toks}))

    for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(out)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    assert len(resumed.history) == len(straight.history) - len(first.history)


def test_lm_eval_perplexity(devices, rng):
    """Held-out NLL/perplexity every eval_every steps + at the end; the
    eval loss is pure NLL so exp(loss) is an honest perplexity."""
    import math

    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    # Learnable structure shared by train and eval: cyclic sequences.
    offs = rng.integers(0, 64, 96)
    data = ((offs[:, None] + np.arange(17)) % 64).astype(np.int32)
    t = dk.LMTrainer(CFG, learning_rate=1e-2, batch_size=16, num_epoch=6,
                     mesh=mesh, eval_every=4)
    t.train(data[:64], eval_tokens=data[64:])
    rounds = [r for r, _ in t.eval_history]
    # Final state always evaluated: as -1 unless the last step already
    # hit the eval_every cadence (24 steps / eval_every=4 does).
    assert rounds[0] == 4 and rounds[-1] in (-1, len(t.history))
    assert rounds.count(rounds[-1]) == 1  # no duplicate final eval
    first, last = t.eval_history[0][1], t.eval_history[-1][1]
    assert last["loss"] < first["loss"]
    assert abs(last["perplexity"] - math.exp(last["loss"])) < 1e-9
    # Vocab 64, random tokens: NLL can't beat ln(64) by much but must
    # be finite and positive.
    assert 0 < last["loss"] < 10


def test_lm_eval_moe_excludes_aux(devices, rng):
    """For MoE the eval loss must be below the training loss signal
    that includes the router aux term (same params, same data)."""
    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32,
                                num_experts=2, capacity_factor=2.0)
    mesh = make_mesh(MeshSpec(data=2, expert=2), devices=devices[:4])
    data = tokens(rng, n=48)
    t = dk.LMTrainer(cfg, learning_rate=1e-2, batch_size=16, num_epoch=1,
                     mesh=mesh)
    params = t.train(data[:32], eval_tokens=data[:32])
    # eval on the same rows the last step trained on: nll < nll + aux
    import jax as _jax

    full = float(_jax.jit(lambda p, tk: tfm.lm_loss(p, tk, cfg))(
        params, data[:16].astype(np.int32)))
    nll = float(_jax.jit(lambda p, tk: tfm.lm_nll(p, tk, cfg))(
        params, data[:16].astype(np.int32)))
    assert nll < full  # aux > 0 strictly separates them
    assert t.eval_history and t.eval_history[-1][0] == -1


def test_lm_eval_validation(devices, rng):
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    with pytest.raises(ValueError, match="eval_tokens"):
        dk.LMTrainer(CFG, batch_size=16, mesh=mesh,
                     eval_every=2).train(tokens(rng))
    with pytest.raises(ValueError, match="eval batch"):
        dk.LMTrainer(CFG, batch_size=16, mesh=mesh, eval_every=2).train(
            tokens(rng), eval_tokens=tokens(rng, n=8))


def test_lm_grad_accum_matches_large_batch(devices, rng):
    """With SGD, accumulating 2 microbatches == one 2x batch step."""
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    data = tokens(rng, n=64)

    def run(**kw):
        t = dk.LMTrainer(CFG, optimizer="sgd", learning_rate=1e-2,
                         num_epoch=4, mesh=mesh, **kw)
        t.train(data)
        return t.history

    big = run(batch_size=32)
    accum = run(batch_size=16, grad_accum=2)
    assert len(big) == len(accum)
    # Same updates; the logged loss differs only in reduction order
    # (mean of two microbatch means == full-batch mean for equal sizes).
    np.testing.assert_allclose(accum, big, rtol=2e-5)


def test_lm_grad_clip(devices, rng):
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    data = tokens(rng, n=32)
    free = dk.LMTrainer(CFG, optimizer="sgd", learning_rate=1e-2,
                        batch_size=16, num_epoch=2, mesh=mesh)
    p_free = free.train(data)
    clipped = dk.LMTrainer(CFG, optimizer="sgd", learning_rate=1e-2,
                           batch_size=16, num_epoch=2, mesh=mesh,
                           grad_clip_norm=1e-6)
    p_clip = clipped.train(data)
    init = dk.LMTrainer(CFG, mesh=mesh).init_params()
    # A vanishing clip norm freezes training; no clip moves params.
    move_free = float(np.abs(np.asarray(p_free["tok_emb"])
                             - np.asarray(init["tok_emb"])).max())
    move_clip = float(np.abs(np.asarray(p_clip["tok_emb"])
                             - np.asarray(init["tok_emb"])).max())
    assert move_clip < 1e-6 < move_free


def test_lm_grad_knob_validation(devices):
    with pytest.raises(ValueError, match="grad_accum"):
        dk.LMTrainer(CFG, grad_accum=0)
    with pytest.raises(ValueError, match="grad_clip_norm"):
        dk.LMTrainer(CFG, grad_clip_norm=-1.0)


def test_lm_dropout_trains_and_is_reproducible(devices, rng):
    mesh = make_mesh(MeshSpec(data=4, model=2), devices=devices)
    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32,
                                dropout=0.1)
    data = tokens(rng, n=64)

    def run():
        t = dk.LMTrainer(cfg, learning_rate=1e-2, batch_size=16,
                         num_epoch=4, mesh=mesh, seed=5)
        t.train(data)
        return t.history

    h1, h2 = run(), run()
    np.testing.assert_allclose(h1, h2, rtol=1e-6)  # same dropout stream
    assert h1[-1] < h1[0] * 0.85
    # And it differs from the no-dropout trajectory.
    plain = dk.LMTrainer(tfm.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        max_len=32), learning_rate=1e-2, batch_size=16, num_epoch=4,
        mesh=mesh, seed=5)
    plain.train(data)
    assert not np.allclose(h1, plain.history, rtol=1e-4)


def test_lm_dropout_resume_matches_straight(tmp_path, devices, rng):
    """The dropout stream is keyed on the round, so resume replays it."""
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32,
                                dropout=0.1)
    data = tokens(rng, n=64)
    common = dict(learning_rate=1e-2, batch_size=16, mesh=mesh, seed=3)
    straight = dk.LMTrainer(cfg, num_epoch=4, **common)
    ref = straight.train(data)
    d = str(tmp_path / "ck")
    dk.LMTrainer(cfg, num_epoch=2, checkpoint_dir=d, **common).train(data)
    resumed = dk.LMTrainer(cfg, num_epoch=4, checkpoint_dir=d, resume=True,
                           **common)
    out = resumed.train(data)
    for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(out)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_lm_dropout_rejects_pipeline(devices):
    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32,
                                dropout=0.1)
    mesh = make_mesh(MeshSpec(data=4, pipeline=2), devices=devices)
    with pytest.raises(ValueError, match="dropout.*pipeline"):
        dk.LMTrainer(cfg, mesh=mesh)


def test_lm_weight_decay_masks_norm_scales(devices):
    t = dk.LMTrainer(CFG, optimizer="adamw", learning_rate=1e-2,
                     weight_decay=0.5)
    params = t.init_params()
    zero_g = jax.tree.map(lambda a: np.zeros_like(np.asarray(a)), params)
    upd, _ = t.optimizer.update(zero_g, t.optimizer.init(params), params)
    # Norm scales: no decay -> zero update under zero gradients.
    assert float(np.abs(np.asarray(upd["ln_f_scale"])).max()) == 0.0
    assert float(np.abs(np.asarray(upd["layers"]["ln1_scale"])).max()) == 0.0
    # Weights do decay.
    assert float(np.abs(np.asarray(upd["tok_emb"])).max()) > 0.0
    assert float(np.abs(
        np.asarray(upd["layers"]["attn"]["wq"])).max()) > 0.0
    with pytest.raises(ValueError, match="weight_decay"):
        dk.LMTrainer(CFG, optimizer="sgd", weight_decay=0.1)


def test_lm_profile_dir_writes_trace(tmp_path, devices, rng):
    import glob as _glob

    d = str(tmp_path / "prof")
    mesh = make_mesh(MeshSpec(data=2), devices=devices[:2])
    t = dk.LMTrainer(CFG, learning_rate=1e-2, batch_size=8, num_epoch=2,
                     mesh=mesh, profile_dir=d, profile_steps=2)
    t.train(tokens(rng, n=32))
    traces = _glob.glob(d + "/**/*.trace.json.gz", recursive=True)
    assert traces, f"no trace written under {d}"
    with pytest.raises(ValueError, match="profile_steps"):
        dk.LMTrainer(CFG, profile_steps=0)


def test_ema_decay_matches_manual_shadow():
    """ema_decay: one optimizer step gives shadow == decay*init +
    (1-decay)*params_1 exactly; the EMA tree serves (finite NLL,
    differs from raw params); knob validation."""
    rows = np.random.default_rng(0).integers(
        0, CFG.vocab_size, (8, CFG.max_len)).astype(np.int32)
    decay = 0.7

    tr1 = dk.LMTrainer(CFG, learning_rate=1e-2, batch_size=8,
                       num_epoch=1, seed=3, ema_decay=decay)
    init = tr1.init_params()
    # Snapshot before train(): the jitted step donates its carry, which
    # invalidates the original device buffers.
    init_np = jax.tree.map(lambda a: np.asarray(a, np.float32), init)
    p1 = tr1.train(rows, params=init)
    ema = tr1.ema_params
    expect = jax.tree.map(lambda i, p: decay * i
                          + (1 - decay) * np.asarray(p, np.float32),
                          init_np, p1)
    for a, b in zip(jax.tree.leaves(ema), jax.tree.leaves(expect)):
        np.testing.assert_allclose(np.asarray(a, np.float32), b,
                                   atol=1e-5, rtol=1e-4)

    tr = dk.LMTrainer(CFG, learning_rate=1e-2, batch_size=8,
                      num_epoch=2, seed=3, ema_decay=decay)
    params = tr.train(rows)
    nll_raw = float(tfm.lm_nll(params, rows, CFG))
    nll_ema = float(tfm.lm_nll(tr.ema_params, rows, CFG))
    assert np.isfinite(nll_ema) and nll_ema != nll_raw

    with pytest.raises(ValueError, match="ema_decay"):
        dk.LMTrainer(CFG, ema_decay=1.5)
    with pytest.raises(ValueError, match="ema_decay"):
        dk.LMTrainer(CFG).ema_params


def test_ema_resume_matches_straight_run(tmp_path, devices, rng):
    """The EMA shadow rides the optimizer state, so checkpoint/resume
    reproduces the straight run's EMA tree exactly — the design claim
    behind _with_ema."""
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    toks = tokens(rng, n=64)
    common = dict(learning_rate=1e-2, batch_size=16, mesh=mesh,
                  shuffle=True, seed=3, ema_decay=0.9)

    straight = dk.LMTrainer(CFG, num_epoch=4, **common)
    straight.train(dk.Dataset({"tokens": toks}))

    d = str(tmp_path / "ckpt")
    first = dk.LMTrainer(CFG, num_epoch=2, checkpoint_dir=d, **common)
    first.train(dk.Dataset({"tokens": toks}))
    resumed = dk.LMTrainer(CFG, num_epoch=4, checkpoint_dir=d,
                           resume=True, **common)
    resumed.train(dk.Dataset({"tokens": toks}))

    for a, b in zip(jax.tree.leaves(straight.ema_params),
                    jax.tree.leaves(resumed.ema_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_lora_trainer_rejects_ema(devices):
    base = tfm.init_params(jax.random.key(0), CFG)
    with pytest.raises(ValueError, match="ema_decay is not supported"):
        dk.LoRATrainer(CFG, base, lora_rank=2, ema_decay=0.9)


def test_lm_device_data_matches_streaming(devices, rng):
    """device_data=True reproduces the streaming run's losses exactly:
    the staged stream layout + on-device gather feed the unchanged
    train step the same rows in the same order (dp, TP+grad_accum,
    FSDP, and pipeline meshes)."""
    toks = tokens(rng, n=96)

    def run(spec, **kw):
        t = dk.LMTrainer(CFG, learning_rate=1e-2, batch_size=16,
                         num_epoch=2, mesh=make_mesh(spec, devices=devices),
                         **kw)
        t.train(toks)
        return t.history

    for spec, kw in [(MeshSpec(data=8), {}),
                     (MeshSpec(data=4, model=2), {"grad_accum": 2}),
                     (MeshSpec(data=4, model=2), {"fsdp": True}),
                     (MeshSpec(data=4, pipeline=2), {})]:
        np.testing.assert_allclose(run(spec, device_data=True, **kw),
                                   run(spec, **kw), rtol=1e-6,
                                   err_msg=f"{spec} {kw}")


def test_lm_device_data_packed_segments(devices, rng):
    """device_data gathers the segment rows with the same index block
    as the tokens, so packed training matches streaming exactly."""
    docs = [rng.integers(1, 64, (int(k),)).tolist()
            for k in rng.integers(5, 14, 64)]
    rows, segs = dk.pack_documents(docs, seq_len=16)
    n = (len(rows) // 16) * 16
    mesh = make_mesh(MeshSpec(data=8), devices=None)

    def run(**kw):
        t = dk.LMTrainer(tfm.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
            max_len=17), learning_rate=1e-2, batch_size=16, num_epoch=2,
            mesh=mesh, **kw)
        t.train(rows[:n], segments=segs[:n])
        return t.history

    np.testing.assert_allclose(run(device_data=True), run(), rtol=1e-6)


def test_device_data_staging_guard_raises_with_figure(rng, monkeypatch):
    """Round-6 fix: when the staged token stream cannot fit device
    memory, device_data=True fails fast with the MiB figure and the
    streaming fallback named — not a raw XLA allocation error deep in
    _global_batch.  CPU reports no budget, so the test injects one."""
    from distkeras_tpu.trainers import lm as lm_mod

    monkeypatch.setattr(lm_mod, "_device_bytes_limit", lambda: 256)
    t = dk.LMTrainer(CFG, learning_rate=1e-2, batch_size=16,
                     device_data=True)
    with pytest.raises(ValueError, match=r"MiB.*device_data=False"):
        t.train(tokens(rng))
    # A budget that fits stages normally (guard stays quiet).
    monkeypatch.setattr(lm_mod, "_device_bytes_limit", lambda: 1 << 30)
    t2 = dk.LMTrainer(CFG, learning_rate=1e-2, batch_size=16,
                      device_data=True)
    t2.train(tokens(rng))
    assert len(t2.history) == 4
