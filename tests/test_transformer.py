"""Transformer flagship: numerics, training, and every parallelism axis.

Sharded-vs-unsharded equality is the core contract: TP/EP/SP runs on
the 8-CPU mesh must reproduce the single-device forward bit-for-bit
(up to f32 reduction order).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distkeras_tpu.models import transformer as tfm
from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh
from distkeras_tpu.parallel.ring import make_ring_attention
from distkeras_tpu.parallel.sharding import ShardingPlan
from jax.sharding import NamedSharding, PartitionSpec as P


CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=32)


def toks(rng, b=4, s=16, vocab=64):
    return rng.integers(0, vocab, (b, s)).astype(np.int32)


def test_forward_shape_and_determinism(rng):
    params = tfm.init_params(jax.random.key(0), CFG)
    t = toks(rng)
    out1, aux1 = tfm.apply(params, t, CFG)
    out2, _ = tfm.apply(params, t, CFG)
    assert out1.shape == (4, 16, 64)
    assert float(aux1) == 0.0  # dense model: no aux loss
    np.testing.assert_array_equal(out1, out2)


def test_train_step_learns_copy_task(rng):
    # Predict-previous-token: a transformer with causal attention can
    # solve this exactly; loss must fall fast.
    cfg = CFG
    params = tfm.init_params(jax.random.key(0), cfg)
    opt = optax.adam(1e-2)
    step = jax.jit(tfm.make_train_step(cfg, opt))
    carry = (params, opt.init(params))
    t = jnp.asarray(toks(rng, b=16, s=16))
    first = None
    for i in range(30):
        carry, loss = step(carry, t)
        first = first if first is not None else float(loss)
    assert float(loss) < first * 0.5, (first, float(loss))


def _sharded_apply(params, t, cfg, mesh, rules, attention_fn=None):
    plan = ShardingPlan(rules=rules)
    psh = plan.tree_shardings(mesh, params)
    params_sh = jax.device_put(params, psh)
    tsh = NamedSharding(mesh, P("data", None))
    fn = jax.jit(
        lambda p, t: tfm.apply(p, t, cfg, attention_fn)[0],
        in_shardings=(psh, tsh))
    return fn(params_sh, jnp.asarray(t))


def test_tensor_parallel_matches_single(devices, rng):
    mesh = make_mesh(MeshSpec(data=4, model=2), devices=devices)
    params = tfm.init_params(jax.random.key(0), CFG)
    t = toks(rng)
    ref, _ = tfm.apply(params, jnp.asarray(t), CFG)
    out = _sharded_apply(params, t, CFG, mesh, tfm.tp_rules())
    np.testing.assert_allclose(out, ref, atol=2e-4, rtol=2e-4)


def test_sequence_parallel_ring_matches_single(devices, rng):
    mesh = make_mesh(MeshSpec(data=2, seq=4), devices=devices)
    params = tfm.init_params(jax.random.key(0), CFG)
    t = toks(rng)
    ref, _ = tfm.apply(params, jnp.asarray(t), CFG)
    ring = make_ring_attention(mesh, causal=True)
    out = _sharded_apply(params, t, CFG, mesh, [], attention_fn=ring)
    np.testing.assert_allclose(out, ref, atol=2e-4, rtol=2e-4)


def test_seq_len_over_max_len_raises(rng):
    params = tfm.init_params(jax.random.key(0), CFG)
    with pytest.raises(ValueError, match="max_len"):
        tfm.apply(params, jnp.zeros((2, CFG.max_len + 4), jnp.int32), CFG)


MOE_CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=1, d_ff=64, max_len=32,
                                num_experts=4, capacity_factor=4.0)


def test_moe_dispatch_matches_per_token_reference(rng):
    """Dense-dispatch einsum == a literal per-token expert loop (no drops
    at capacity_factor=4)."""
    params = tfm.init_params(jax.random.key(1), MOE_CFG)
    lp = jax.tree.map(lambda a: a[0], params["layers"])["moe"]
    x = jnp.asarray(rng.normal(size=(2, 8, 32)).astype(np.float32))
    out, aux = tfm._moe_block(lp, x, MOE_CFG)

    flat = np.asarray(x.reshape(-1, 32), np.float32)
    router = flat @ np.asarray(lp["wg"])
    probs = np.exp(router - router.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    ref = np.zeros_like(flat)
    for n in range(flat.shape[0]):
        e = int(probs[n].argmax())
        h = flat[n] @ np.asarray(lp["w1"][e])
        h = np.asarray(jax.nn.gelu(jnp.asarray(h)))
        ref[n] = (h @ np.asarray(lp["w2"][e])) * probs[n].max()
    np.testing.assert_allclose(np.asarray(out).reshape(-1, 32), ref,
                               atol=1e-4, rtol=1e-4)
    assert float(aux) > 0.0


def test_moe_capacity_drops_tokens(rng):
    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=1, d_ff=64, max_len=32,
                                num_experts=4, capacity_factor=0.25)
    params = tfm.init_params(jax.random.key(1), cfg)
    lp = jax.tree.map(lambda a: a[0], params["layers"])["moe"]
    x = jnp.asarray(rng.normal(size=(2, 8, 32)).astype(np.float32))
    out, _ = tfm._moe_block(lp, x, cfg)
    # capacity = 0.25 * 16 / 4 = 1 slot per expert -> at most 4 of 16
    # tokens routed; the rest must be exactly 0 (residual passthrough).
    nonzero = np.abs(np.asarray(out).reshape(16, -1)).sum(-1) > 0
    assert nonzero.sum() <= 4


def test_expert_parallel_matches_single(devices, rng):
    mesh = make_mesh(MeshSpec(data=2, expert=4), devices=devices)
    params = tfm.init_params(jax.random.key(1), MOE_CFG)
    t = toks(rng)
    ref, _ = tfm.apply(params, jnp.asarray(t), MOE_CFG)
    out = _sharded_apply(params, t, MOE_CFG, mesh, tfm.tp_rules())
    np.testing.assert_allclose(out, ref, atol=2e-4, rtol=2e-4)


def test_moe_train_step_learns(rng):
    opt = optax.adam(1e-2)
    params = tfm.init_params(jax.random.key(0), MOE_CFG)
    step = jax.jit(tfm.make_train_step(MOE_CFG, opt))
    carry = (params, opt.init(params))
    t = jnp.asarray(toks(rng, b=16, s=16))
    losses = []
    for _ in range(30):
        carry, loss = step(carry, t)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses[::10]


# --------------------------------------------------------------- top-2 MoE

MOE2_CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                 n_layers=1, d_ff=64, max_len=32,
                                 num_experts=4, moe_top_k=2,
                                 capacity_factor=4.0)


def test_moe_top2_dispatch_matches_per_token_reference(rng):
    """Top-2 capacity dispatch == a literal per-token two-expert loop
    with renormalized gates (no drops at capacity_factor=4)."""
    params = tfm.init_params(jax.random.key(1), MOE2_CFG)
    lp = jax.tree.map(lambda a: a[0], params["layers"])["moe"]
    x = jnp.asarray(rng.normal(size=(2, 8, 32)).astype(np.float32))
    out, aux = tfm._moe_block(lp, x, MOE2_CFG)

    flat = np.asarray(x.reshape(-1, 32), np.float32)
    router = flat @ np.asarray(lp["wg"])
    probs = np.exp(router - router.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    ref = np.zeros_like(flat)
    for n in range(flat.shape[0]):
        top2 = np.argsort(-probs[n])[:2]
        g = probs[n][top2] / probs[n][top2].sum()
        for gi, e in zip(g, top2):
            h = flat[n] @ np.asarray(lp["w1"][e])
            h = np.asarray(jax.nn.gelu(jnp.asarray(h)))
            ref[n] += gi * (h @ np.asarray(lp["w2"][e]))
    np.testing.assert_allclose(np.asarray(out).reshape(-1, 32), ref,
                               atol=1e-4, rtol=1e-4)
    assert float(aux) > 0.0


def test_moe_top2_capacity_equals_dense_routing_when_nothing_drops(rng):
    """At generous capacity the capacity path and the decode-parity
    dense path compute the same function (the top-2 analogue of the
    cached-decode parity contract)."""
    params = tfm.init_params(jax.random.key(2), MOE2_CFG)
    t = jnp.asarray(toks(rng))
    cap_logits, _ = tfm.apply(params, t, MOE2_CFG)
    dense_logits, _ = tfm.apply(params, t, MOE2_CFG,
                                moe_dense_routing=True)
    np.testing.assert_allclose(np.asarray(cap_logits),
                               np.asarray(dense_logits),
                               atol=2e-4, rtol=2e-4)


def test_moe_top2_second_choices_yield_capacity(rng):
    """First choices claim slots before ANY second choice: with one
    slot per expert, every surviving assignment must be a first choice
    wherever first-choice demand covers the expert."""
    import dataclasses

    cfg = dataclasses.replace(MOE2_CFG, capacity_factor=0.125)
    # cap = int(0.125 * 2 * 16 / 4) = 1 slot per expert.
    params = tfm.init_params(jax.random.key(1), cfg)
    lp = jax.tree.map(lambda a: a[0], params["layers"])["moe"]
    x = jnp.asarray(rng.normal(size=(2, 8, 32)).astype(np.float32))
    out, _ = tfm._moe_block(lp, x, cfg)
    # <= 4 slots total; each carries one assignment, so at most 4 of
    # the 16 tokens produce nonzero output.
    nonzero = np.abs(np.asarray(out).reshape(16, -1)).sum(-1) > 0
    assert nonzero.sum() <= 4

    flat = np.asarray(x.reshape(-1, 32), np.float32)
    router = flat @ np.asarray(lp["wg"])
    probs = np.exp(router - router.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    first = probs.argmax(-1)
    # The expert of the FIRST token whose first choice is expert e must
    # have landed (its slot cannot be stolen by any second choice).
    for e in set(first.tolist()):
        n0 = int(np.nonzero(first == e)[0][0])
        assert nonzero[n0], (e, n0)


def test_moe_top2_expert_parallel_matches_single(devices, rng):
    mesh = make_mesh(MeshSpec(data=2, expert=4), devices=devices)
    params = tfm.init_params(jax.random.key(1), MOE2_CFG)
    t = toks(rng)
    ref, _ = tfm.apply(params, jnp.asarray(t), MOE2_CFG)
    out = _sharded_apply(params, t, MOE2_CFG, mesh, tfm.tp_rules())
    np.testing.assert_allclose(out, ref, atol=2e-4, rtol=2e-4)


def test_moe_top_k_range_validated():
    import dataclasses

    import pytest

    for bad in (0, 5):
        cfg = dataclasses.replace(MOE_CFG, moe_top_k=bad)
        with pytest.raises(ValueError, match="moe_top_k"):
            tfm.init_params(jax.random.key(0), cfg)


ROPE_CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                 n_layers=2, d_ff=64, max_len=32, rope=True)


def test_rope_params_have_no_pos_table():
    params = tfm.init_params(jax.random.key(0), ROPE_CFG)
    assert "pos_emb" not in params
    with pytest.raises(ValueError, match="even head_dim"):
        tfm.init_params(jax.random.key(0), tfm.TransformerConfig(
            vocab_size=64, d_model=30, n_heads=2, n_layers=1, d_ff=64,
            max_len=32, rope=True))


def test_rope_forward_and_learning(rng):
    params = tfm.init_params(jax.random.key(0), ROPE_CFG)
    t = toks(rng)
    out, _ = tfm.apply(params, jnp.asarray(t), ROPE_CFG)
    assert out.shape == (4, 16, 64) and np.isfinite(np.asarray(out)).all()

    opt = optax.adam(1e-2)
    step = jax.jit(tfm.make_train_step(ROPE_CFG, opt))
    carry = (params, opt.init(params))
    data = jnp.asarray(toks(rng, b=16, s=16))
    first = None
    for _ in range(30):
        carry, loss = step(carry, data)
        first = first if first is not None else float(loss)
    assert float(loss) < first * 0.5


def test_rope_relative_position_invariance(rng):
    """With RoPE (no absolute table), causal attention over a prefix
    placed at different absolute offsets gives identical logits for the
    same relative context — the property a learned pos_emb cannot have.

    Construct: logits at the last position of sequence [a, b, c]
    must equal logits at the last position of [x, a, b, c] restricted
    to attending only {a, b, c}... which plain causal attention does
    not do; instead verify the cheap exact form: rotating *all*
    positions by a constant offset leaves attention scores unchanged.
    """
    params = tfm.init_params(jax.random.key(0), ROPE_CFG)
    q = jnp.asarray(rng.normal(size=(1, 8, 2, 16)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 8, 2, 16)), jnp.float32)
    base = tfm.rope_angles(jnp.arange(8), 16, 10000.0)[None, :, None, :]
    off = tfm.rope_angles(jnp.arange(8) + 13, 16, 10000.0)[None, :, None, :]

    def scores(ang):
        qr, kr = tfm.rope_rotate(q, ang), tfm.rope_rotate(k, ang)
        return jnp.einsum("bshk,bthk->bsht", qr, kr)

    np.testing.assert_allclose(scores(base), scores(off),
                               atol=1e-4, rtol=1e-4)


def test_rope_ring_matches_single(devices, rng):
    """SP: ring attention with global-position rotary == single-device."""
    mesh = make_mesh(MeshSpec(data=2, seq=4), devices=devices)
    params = tfm.init_params(jax.random.key(0), ROPE_CFG)
    t = toks(rng)
    ref, _ = tfm.apply(params, jnp.asarray(t), ROPE_CFG)
    ring = make_ring_attention(mesh, causal=True)
    out = _sharded_apply(params, t, ROPE_CFG, mesh, [], attention_fn=ring)
    np.testing.assert_allclose(out, ref, atol=2e-4, rtol=2e-4)


def test_rope_pipelined_matches_single(devices, rng):
    """PP and PP x SP: stage-local rotary offsets must reproduce the
    un-pipelined forward exactly."""
    mesh = make_mesh(MeshSpec(data=2, pipeline=2, seq=2), devices=devices)
    params = tfm.init_params(jax.random.key(0), ROPE_CFG)
    t = jnp.asarray(toks(rng, b=4, s=16))
    ref, _ = tfm.apply(params, t, ROPE_CFG)
    out, _ = jax.jit(lambda p, tk: tfm.apply_pipelined(
        p, tk, ROPE_CFG, mesh, microbatches=2, seq_axis="seq"))(params, t)
    np.testing.assert_allclose(out, ref, atol=2e-4, rtol=2e-4)


def test_rope_trains_past_max_len(rng):
    """No position table -> training length is unbounded by max_len
    (which only sizes the decode KV cache)."""
    params = tfm.init_params(jax.random.key(0), ROPE_CFG)
    long = jnp.asarray(toks(rng, b=2, s=ROPE_CFG.max_len * 2))
    out, _ = tfm.apply(params, long, ROPE_CFG)
    assert out.shape == (2, ROPE_CFG.max_len * 2, 64)
    assert np.isfinite(np.asarray(out)).all()


GQA_CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                                n_layers=2, d_ff=64, max_len=32,
                                n_kv_heads=2)


def test_gqa_shapes_and_learning(rng):
    params = tfm.init_params(jax.random.key(0), GQA_CFG)
    assert params["layers"]["attn"]["wk"].shape == (2, 32, 2, 8)
    assert params["layers"]["attn"]["wq"].shape == (2, 32, 4, 8)
    out, _ = tfm.apply(params, jnp.asarray(toks(rng)), GQA_CFG)
    assert out.shape == (4, 16, 64) and np.isfinite(np.asarray(out)).all()

    opt = optax.adam(1e-2)
    step = jax.jit(tfm.make_train_step(GQA_CFG, opt))
    carry = (params, opt.init(params))
    data = jnp.asarray(toks(rng, b=16, s=16))
    first = None
    for _ in range(30):
        carry, loss = step(carry, data)
        first = first if first is not None else float(loss)
    assert float(loss) < first * 0.5


def test_gqa_equals_mha_when_kv_heads_full(rng):
    """n_kv_heads == n_heads must be bit-identical to the default."""
    full = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                 n_layers=2, d_ff=64, max_len=32,
                                 n_kv_heads=2)
    p1 = tfm.init_params(jax.random.key(0), CFG)
    p2 = tfm.init_params(jax.random.key(0), full)
    t = jnp.asarray(toks(rng))
    np.testing.assert_array_equal(tfm.apply(p1, t, CFG)[0],
                                  tfm.apply(p2, t, full)[0])


def test_gqa_validation():
    with pytest.raises(ValueError, match="n_kv_heads"):
        tfm.init_params(jax.random.key(0), tfm.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=1, d_ff=64,
            max_len=32, n_kv_heads=3))


def test_gqa_ring_matches_single(devices, rng):
    mesh = make_mesh(MeshSpec(data=2, seq=4), devices=devices)
    params = tfm.init_params(jax.random.key(0), GQA_CFG)
    t = toks(rng)
    ref, _ = tfm.apply(params, jnp.asarray(t), GQA_CFG)
    ring = make_ring_attention(mesh, causal=True)
    out = _sharded_apply(params, t, GQA_CFG, mesh, [], attention_fn=ring)
    np.testing.assert_allclose(out, ref, atol=2e-4, rtol=2e-4)


DROP_CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                 n_layers=2, d_ff=64, max_len=32,
                                 dropout=0.2)


def test_dropout_deterministic_per_key_and_off_without_rng(rng):
    params = tfm.init_params(jax.random.key(0), DROP_CFG)
    t = jnp.asarray(toks(rng))
    # No rng -> deterministic inference even with cfg.dropout > 0.
    a, _ = tfm.apply(params, t, DROP_CFG)
    b, _ = tfm.apply(params, t, DROP_CFG)
    np.testing.assert_array_equal(a, b)
    # Same key -> same masks; different key -> different activations.
    k1, k2 = jax.random.key(1), jax.random.key(2)
    d1, _ = tfm.apply(params, t, DROP_CFG, dropout_rng=k1)
    d1b, _ = tfm.apply(params, t, DROP_CFG, dropout_rng=k1)
    d2, _ = tfm.apply(params, t, DROP_CFG, dropout_rng=k2)
    np.testing.assert_array_equal(d1, d1b)
    assert not np.array_equal(np.asarray(d1), np.asarray(d2))
    assert not np.array_equal(np.asarray(a), np.asarray(d1))


def test_dropout_training_learns(rng):
    params = tfm.init_params(jax.random.key(0), DROP_CFG)
    opt = optax.adam(1e-2)
    step = jax.jit(tfm.make_train_step(DROP_CFG, opt))
    carry = (params, opt.init(params))
    data = jnp.asarray(toks(rng, b=16, s=16))
    first = None
    for i in range(30):
        carry, loss = step(carry, data, jax.random.key(i))
        first = first if first is not None else float(loss)
    assert float(loss) < first * 0.6


def test_dropout_validation(rng):
    with pytest.raises(ValueError, match="dropout"):
        tfm.init_params(jax.random.key(0), tfm.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64,
            max_len=32, dropout=1.0))
    # A dropout config whose step is driven without an rng must refuse
    # rather than silently train unregularized.
    params = tfm.init_params(jax.random.key(0), DROP_CFG)
    opt = optax.adam(1e-2)
    step = tfm.make_train_step(DROP_CFG, opt)
    with pytest.raises(ValueError, match="dropout_rng"):
        step((params, opt.init(params)), jnp.asarray(toks(rng)))


# ---------------------------------------------------------------- chunked CE

def test_chunked_ce_loss_and_grads_match_full(rng):
    """ce_chunks is a pure optimization: loss AND gradients must equal
    the materialized-logits path (same math, reordered reduction)."""
    import dataclasses

    cfg_c = dataclasses.replace(CFG, ce_chunks=4)
    params = tfm.init_params(jax.random.key(0), CFG)
    t = jnp.asarray(toks(rng))
    l_full, g_full = jax.value_and_grad(tfm.lm_loss)(params, t, CFG)
    l_chunk, g_chunk = jax.value_and_grad(tfm.lm_loss)(params, t, cfg_c)
    np.testing.assert_allclose(float(l_chunk), float(l_full), rtol=1e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        a, b, atol=1e-6, rtol=1e-5), g_full, g_chunk)


def test_chunked_ce_handles_nondivisible_token_count(rng):
    """B*(S-1) not divisible by ce_chunks: padding rows carry target -1
    and must contribute exactly zero."""
    import dataclasses

    cfg_c = dataclasses.replace(CFG, ce_chunks=7)  # 4*15=60 tokens, 7∤60
    params = tfm.init_params(jax.random.key(0), CFG)
    t = jnp.asarray(toks(rng))
    l_full = tfm.lm_loss(params, t, CFG)
    l_chunk = tfm.lm_loss(params, t, cfg_c)
    np.testing.assert_allclose(float(l_chunk), float(l_full), rtol=1e-6)


def test_chunked_ce_eval_nll_matches(rng):
    import dataclasses

    cfg_c = dataclasses.replace(CFG, ce_chunks=4)
    params = tfm.init_params(jax.random.key(0), CFG)
    t = jnp.asarray(toks(rng))
    np.testing.assert_allclose(
        float(tfm.lm_nll(params, t, cfg_c)),
        float(tfm.lm_nll(params, t, CFG)), rtol=1e-6)


def test_chunked_ce_under_tensor_parallel(devices, rng):
    """Chunked head under the Megatron plan: tok_emb is model-sharded,
    the per-chunk contraction psums over the mesh — loss must match the
    single-device full-logits value."""
    import dataclasses

    cfg_c = dataclasses.replace(CFG, ce_chunks=4)
    mesh = make_mesh(MeshSpec(data=4, model=2), devices=devices)
    params = tfm.init_params(jax.random.key(0), CFG)
    t = jnp.asarray(toks(rng))
    ref = float(tfm.lm_loss(params, t, CFG))
    plan = ShardingPlan(rules=tfm.tp_rules())
    psh = plan.tree_shardings(mesh, params)
    params_sh = jax.device_put(params, psh)
    tsh = NamedSharding(mesh, P("data", None))
    loss = jax.jit(lambda p, x: tfm.lm_loss(p, x, cfg_c),
                   in_shardings=(psh, tsh))(params_sh, t)
    np.testing.assert_allclose(float(loss), ref, atol=2e-5, rtol=2e-5)


def test_chunked_ce_trains(rng):
    import dataclasses

    cfg_c = dataclasses.replace(CFG, ce_chunks=4)
    params = tfm.init_params(jax.random.key(0), cfg_c)
    opt = optax.adam(1e-2)
    step = jax.jit(tfm.make_train_step(cfg_c, opt))
    carry = (params, opt.init(params))
    t = jnp.asarray(toks(rng, b=16, s=16))
    first = None
    for _ in range(30):
        carry, loss = step(carry, t)
        first = first if first is not None else float(loss)
    assert float(loss) < first * 0.5, (first, float(loss))


def test_chunked_ce_pipelined_matches_unpipelined(devices, rng):
    """PP trunk + chunked head (hidden_fn route) == single-device full
    logits loss, dense config (MoE capacity differs per microbatch)."""
    import dataclasses

    cfg = dataclasses.replace(CFG, n_layers=2, ce_chunks=4)
    mesh = make_mesh(MeshSpec(data=2, pipeline=2), devices=devices[:4])
    params = tfm.init_params(jax.random.key(0), cfg)
    t = jnp.asarray(toks(rng, b=4, s=16))
    ref = float(tfm.lm_loss(params, t, dataclasses.replace(cfg, ce_chunks=0)))
    hidden_fn = lambda p, x: tfm.apply_pipelined(
        p, x, cfg, mesh, microbatches=2, return_hidden=True)
    with mesh:
        loss = jax.jit(lambda p, x: tfm.lm_loss(p, x, cfg,
                                                hidden_fn=hidden_fn))(params, t)
    np.testing.assert_allclose(float(loss), ref, atol=2e-5, rtol=2e-5)


def test_chunked_ce_pipelined_trains_via_lm_trainer(devices, rng):
    import dataclasses

    import distkeras_tpu as dk
    from distkeras_tpu.parallel.mesh import MeshSpec as MS, make_mesh as mm

    cfg = dataclasses.replace(CFG, n_layers=2, ce_chunks=4)
    mesh = mm(MS(data=2, pipeline=2, seq=2), devices=devices)
    tr = dk.LMTrainer(cfg, learning_rate=1e-2, batch_size=8, num_epoch=4,
                      mesh=mesh, microbatches=2)
    tokens = np.repeat(
        rng.integers(0, CFG.vocab_size, (64, 1)), 17, axis=1).astype(np.int32)
    tr.train(tokens)
    assert tr.history[-1] < tr.history[0] * 0.5, (
        tr.history[0], tr.history[-1])


def test_lm_loss_rejects_both_forward_hooks(rng):
    params = tfm.init_params(jax.random.key(0), CFG)
    t = jnp.asarray(toks(rng))
    dummy = lambda p, x: (None, None)
    with pytest.raises(ValueError, match="not both"):
        tfm.lm_loss(params, t, CFG, apply_fn=dummy, hidden_fn=dummy)
    # Same guard on the eval entry point: silently preferring apply_fn
    # would materialize the logits the caller asked ce_chunks to avoid.
    with pytest.raises(ValueError, match="not both"):
        tfm.lm_nll(params, t, CFG, apply_fn=dummy, hidden_fn=dummy)


# -------------------------------------------------------------------- z-loss

def test_z_loss_chunked_matches_full(rng):
    """z-loss on the chunked head == the materialized head, and both
    strictly exceed the unregularized loss."""
    import dataclasses

    z = dataclasses.replace(CFG, z_loss_coef=1e-3)
    zc = dataclasses.replace(CFG, z_loss_coef=1e-3, ce_chunks=4)
    params = tfm.init_params(jax.random.key(0), CFG)
    t = jnp.asarray(toks(rng))
    base = float(tfm.lm_loss(params, t, CFG))
    l_full, g_full = jax.value_and_grad(tfm.lm_loss)(params, t, z)
    l_chunk, g_chunk = jax.value_and_grad(tfm.lm_loss)(params, t, zc)
    assert float(l_full) > base
    np.testing.assert_allclose(float(l_chunk), float(l_full), rtol=1e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        a, b, atol=1e-6, rtol=1e-5), g_full, g_chunk)


def test_z_loss_excluded_from_eval_nll(rng):
    import dataclasses

    z = dataclasses.replace(CFG, z_loss_coef=1e-2)
    params = tfm.init_params(jax.random.key(0), CFG)
    t = jnp.asarray(toks(rng))
    np.testing.assert_allclose(float(tfm.lm_nll(params, t, z)),
                               float(tfm.lm_nll(params, t, CFG)),
                               rtol=1e-7)


def test_z_loss_trains_and_shrinks_normalizer(rng):
    """With z-loss the trained model's mean logsumexp^2 must come out
    smaller than without (the regularizer does its one job)."""
    import dataclasses

    def train(cfg):
        params = tfm.init_params(jax.random.key(0), cfg)
        opt = optax.adam(1e-2)
        step = jax.jit(tfm.make_train_step(cfg, opt))
        carry = (params, opt.init(params))
        t = jnp.asarray(toks(rng_local, b=16, s=16))
        for _ in range(40):
            carry, loss = step(carry, t)
        logits, _ = tfm.apply(carry[0], t[:, :-1], cfg)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        return float(loss), float(jnp.square(lse).mean())

    rng_local = np.random.default_rng(0)
    loss0, z0 = train(CFG)
    rng_local = np.random.default_rng(0)
    loss1, z1 = train(dataclasses.replace(CFG, z_loss_coef=1e-2))
    assert z1 < z0, (z0, z1)
    assert loss1 < 3.0  # still learns the copy task


# ---------------------------------------------------------- sliding window

def test_attention_window_matches_manual_mask(rng):
    """apply() with attention_window == materialized attention with the
    same banded mask (oracle via naive windowed attention)."""
    import dataclasses

    from distkeras_tpu.ops.attention import naive_attention

    w = 5
    cfg_w = dataclasses.replace(CFG, attention_window=w)
    params = tfm.init_params(jax.random.key(0), CFG)
    t = jnp.asarray(toks(rng))
    ref, _ = tfm.apply(params, t, CFG,
                       attention_fn=lambda q, k, v: naive_attention(
                           q, k, v, causal=True, window=w))
    out, _ = tfm.apply(params, t, cfg_w)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)
    # window >= seq degenerates to full causal
    cfg_big = dataclasses.replace(CFG, attention_window=64)
    full, _ = tfm.apply(params, t, CFG)
    big, _ = tfm.apply(params, t, cfg_big)
    np.testing.assert_allclose(np.asarray(big), np.asarray(full),
                               atol=1e-5, rtol=1e-5)


def test_attention_window_trains(rng):
    import dataclasses

    cfg = dataclasses.replace(CFG, attention_window=4)
    params = tfm.init_params(jax.random.key(0), cfg)
    opt = optax.adam(1e-2)
    step = jax.jit(tfm.make_train_step(cfg, opt))
    carry = (params, opt.init(params))
    t = jnp.asarray(toks(rng, b=16, s=16))
    first = None
    for _ in range(30):
        carry, loss = step(carry, t)
        first = first if first is not None else float(loss)
    assert float(loss) < first * 0.5


def test_attention_window_ring_matches_single(rng, devices):
    """Windowed ring attention (global-position band per hop) == the
    single-device windowed forward, with the windowed cfg flowing
    through apply (the handles_window marker admits the ring fn)."""
    import dataclasses

    w = 5
    cfg = dataclasses.replace(CFG, attention_window=w)
    mesh = make_mesh(MeshSpec(data=2, seq=4), devices=devices)
    params = tfm.init_params(jax.random.key(0), CFG)
    t = toks(rng)
    ref, _ = tfm.apply(params, jnp.asarray(t), cfg)
    ring = make_ring_attention(mesh, causal=True, window=w)
    assert ring.handles_window
    out = _sharded_apply(params, t, cfg, mesh, [], attention_fn=ring)
    np.testing.assert_allclose(out, ref, atol=2e-4, rtol=2e-4)


def test_attention_window_lm_trainer_ring(rng, devices):
    """LMTrainer on a dp x sp mesh with attention_window trains (the
    trainer builds the window-aware ring itself)."""
    import dataclasses

    import distkeras_tpu as dk
    from distkeras_tpu.parallel.mesh import MeshSpec as MS, make_mesh as mm

    cfg = dataclasses.replace(CFG, attention_window=4, max_len=17)
    mesh = mm(MS(data=2, seq=2), devices=devices[:4])
    tr = dk.LMTrainer(cfg, learning_rate=1e-2, batch_size=8, num_epoch=4,
                      mesh=mesh)
    tokens = np.repeat(
        rng.integers(0, CFG.vocab_size, (64, 1)), 17, axis=1
    ).astype(np.int32)
    tr.train(tokens)
    assert tr.history[-1] < tr.history[0] * 0.5


def test_attention_window_rejects_custom_attention_fn(rng):
    import dataclasses

    from distkeras_tpu.ops.attention import naive_attention

    cfg = dataclasses.replace(CFG, attention_window=4)
    params = tfm.init_params(jax.random.key(0), cfg)
    with pytest.raises(ValueError, match="attention_fn"):
        tfm.apply(params, jnp.asarray(toks(rng)), cfg,
                  attention_fn=lambda q, k, v: naive_attention(
                      q, k, v, causal=True))


def test_attention_window_rejects_mismatched_ring(rng, devices):
    """A ring built with a DIFFERENT window than cfg must be refused —
    a mismatched band would silently diverge train from decode."""
    import dataclasses

    cfg = dataclasses.replace(CFG, attention_window=4)
    mesh = make_mesh(MeshSpec(data=2, seq=4), devices=devices)
    params = tfm.init_params(jax.random.key(0), CFG)
    ring8 = make_ring_attention(mesh, causal=True, window=8)
    with pytest.raises(ValueError, match="mismatch"):
        tfm.apply(params, jnp.asarray(toks(rng)), cfg, attention_fn=ring8)
    # The unchecked direction: a windowed fn with a window-less cfg is
    # equally a silent train/decode divergence and must be refused.
    with pytest.raises(ValueError, match="mismatch"):
        tfm.apply(params, jnp.asarray(toks(rng)), CFG, attention_fn=ring8)


def test_attention_window_composes_with_moe(rng):
    """Window + MoE: the band applies at attention level, routing is
    untouched — loss finite and training moves."""
    import dataclasses

    cfg = dataclasses.replace(MOE_CFG, attention_window=4)
    params = tfm.init_params(jax.random.key(0), cfg)
    opt = optax.adam(1e-2)
    step = jax.jit(tfm.make_train_step(cfg, opt))
    carry = (params, opt.init(params))
    t = jnp.asarray(toks(rng, b=8, s=16))
    first = None
    for _ in range(15):
        carry, loss = step(carry, t)
        first = first if first is not None else float(loss)
    assert np.isfinite(float(loss)) and float(loss) < first


def test_attention_window_pipelined_ring_matches_single(devices, rng):
    """PP x SP x window: the pipeline's per-stage ring body carries the
    band (global positions per hop) — must reproduce the un-pipelined
    windowed forward exactly."""
    import dataclasses

    cfg = dataclasses.replace(ROPE_CFG, attention_window=5)
    mesh = make_mesh(MeshSpec(data=2, pipeline=2, seq=2), devices=devices)
    params = tfm.init_params(jax.random.key(0), cfg)
    t = jnp.asarray(toks(rng, b=4, s=16))
    ref, _ = tfm.apply(params, t, cfg)
    out, _ = jax.jit(lambda p, tk: tfm.apply_pipelined(
        p, tk, cfg, mesh, microbatches=2, seq_axis="seq"))(params, t)
    np.testing.assert_allclose(out, ref, atol=2e-4, rtol=2e-4)
