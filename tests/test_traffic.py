"""Trace-replay driver (round 19): the load signal feeding the
autoscaling control plane must be bit-reproducible — same seed =>
identical tick-by-tick schedule across runs and across generation
order (the PR-16 AsyncSchedule contract) — and each trace shape must
actually produce its advertised distribution (ramp monotone, spike
amplitude, locality-shuffle destroying stem reuse, tenant-mix
weights)."""

import numpy as np
import pytest

from distkeras_tpu.serving.traffic import (TRACE_SHAPES, TraceReplay,
                                           TraceRequest)


def _schedule(trace, ticks):
    return [trace.requests_at(t) for t in range(ticks)]


@pytest.mark.parametrize("shape", TRACE_SHAPES)
def test_same_seed_identical_schedule(shape):
    """Two independently constructed traces with the same seed emit
    the IDENTICAL tick-by-tick request schedule — every field of
    every arrival, not just counts (frozen-dataclass equality)."""
    a = _schedule(TraceReplay(shape, seed=7), 64)
    b = _schedule(TraceReplay(shape, seed=7), 64)
    assert a == b
    assert any(len(t) > 0 for t in a), "trace emitted nothing in 64 ticks"


def test_ticks_are_independent_draws():
    """Ticks use independent SeedSequence streams, so generating them
    in any order (or skipping around) reproduces the same schedule —
    a replay can seek."""
    tr = TraceReplay("spike", seed=3)
    fwd = [tr.requests_at(t) for t in range(32)]
    rev = [tr.requests_at(t) for t in reversed(range(32))][::-1]
    assert fwd == rev
    assert tr.requests_at(17) == fwd[17]


def test_different_seed_different_schedule():
    a = _schedule(TraceReplay("diurnal", seed=0), 64)
    b = _schedule(TraceReplay("diurnal", seed=1), 64)
    assert a != b


def test_diurnal_ramp_monotone():
    """The diurnal envelope rises monotonically to the peak at
    period/2 and falls monotonically back — the slow swing the
    scale-up/scale-down hysteresis must track."""
    tr = TraceReplay("diurnal", base_rate=1.0, peak_rate=9.0,
                     period=40)
    rates = [tr.rate(t) for t in range(40)]
    up, down = rates[:21], rates[20:]
    assert all(b >= a for a, b in zip(up, up[1:]))
    assert all(b <= a for a, b in zip(down, down[1:]))
    assert max(rates) == pytest.approx(9.0)
    assert rates[0] == pytest.approx(1.0)


def test_spike_amplitude():
    """Inside the flash window the offered rate is spike_rate and the
    realized arrival mean tracks it; outside it is base_rate."""
    tr = TraceReplay("spike", seed=5, base_rate=2.0, spike_at=10,
                     spike_len=64, spike_rate=16.0)
    assert tr.rate(9) == 2.0 and tr.rate(10 + 64) == 2.0
    assert all(tr.rate(t) == 16.0 for t in range(10, 74))
    in_spike = [len(tr.requests_at(t)) for t in range(10, 74)]
    before = [len(tr.requests_at(t)) for t in range(10)]
    assert np.mean(in_spike) == pytest.approx(16.0, rel=0.25)
    assert np.mean(in_spike) > 3 * max(np.mean(before), 0.5)


def test_shuffle_destroys_stem_locality():
    """The adversarial shape: the steady shapes reuse a small stem
    pool (repeats are what the affinity table keys on); ``shuffle``
    gives every request a UNIQUE stem so no two prompts share a warm
    prefix."""
    steady = TraceReplay("tenant_mix", seed=2, base_rate=4.0, stems=4)
    shuffled = TraceReplay("shuffle", seed=2, base_rate=4.0, stems=4)
    s_reqs = [r for t in range(40) for r in steady.requests_at(t)]
    x_reqs = [r for t in range(40) for r in shuffled.requests_at(t)]
    assert len(s_reqs) > 40 and len(x_reqs) > 40
    assert len({r.stem for r in s_reqs}) <= 4
    assert len({r.stem for r in x_reqs}) == len(x_reqs)
    # Prompt-level check: shared stem => shared stem_len prefix;
    # unique stems => distinct prefixes.
    by_stem = {}
    for r in s_reqs:
        by_stem.setdefault(r.stem, []).append(r)
    grp = next(g for g in by_stem.values() if len(g) >= 2)
    p0 = steady.prompt(grp[0], stem_len=6, tail_len=2)
    p1 = steady.prompt(grp[1], stem_len=6, tail_len=2)
    assert np.array_equal(p0[:6], p1[:6])
    q0 = shuffled.prompt(x_reqs[0], stem_len=6, tail_len=2)
    q1 = shuffled.prompt(x_reqs[1], stem_len=6, tail_len=2)
    assert not np.array_equal(q0[:6], q1[:6])


def test_tails_unique_across_trace():
    tr = TraceReplay("spike", seed=1, spike_rate=20.0, spike_len=16)
    tails = [r.tail for t in range(40) for r in tr.requests_at(t)]
    assert len(tails) == len(set(tails))


def test_tenant_mix_weights():
    tr = TraceReplay("tenant_mix", seed=9, base_rate=8.0,
                     tenants=(("a", 3.0), ("b", 1.0)))
    reqs = [r for t in range(80) for r in tr.requests_at(t)]
    counts = {n: sum(1 for r in reqs if r.tenant == n)
              for n in ("a", "b")}
    assert counts["a"] + counts["b"] == len(reqs)
    assert counts["a"] / max(counts["b"], 1) == pytest.approx(3.0,
                                                             rel=0.3)


def test_max_new_range_and_request_fields():
    tr = TraceReplay("diurnal", seed=4, max_new=(2, 6))
    reqs = [r for t in range(32) for r in tr.requests_at(t)]
    assert all(2 <= r.max_new <= 6 for r in reqs)
    assert all(isinstance(r, TraceRequest) for r in reqs)
    assert all(r.tick < 32 and r.index >= 0 for r in reqs)


def test_prompt_deterministic_and_typed():
    tr = TraceReplay("spike", seed=0)
    r = TraceRequest(tick=3, index=0, tenant="t0", stem=1, tail=99,
                     max_new=4)
    p1 = tr.prompt(r, stem_len=5, tail_len=3, vocab=32)
    p2 = tr.prompt(r, stem_len=5, tail_len=3, vocab=32)
    assert np.array_equal(p1, p2)
    assert p1.dtype == np.int32 and p1.size == 8
    assert (p1 >= 0).all() and (p1 < 32).all()


def test_replay_emits_offered_load_audit_trail():
    """``replay`` is ``requests_at`` plus the audit emissions: the
    per-tick offered gauge and one counter increment per arrival,
    labeled by shape and tenant."""
    from distkeras_tpu import obs

    tr = TraceReplay("spike", seed=5, base_rate=6.0)
    sess = obs.enable()
    try:
        total = sum(len(tr.replay(t)) for t in range(8))
        snap = sess.registry.snapshot()
    finally:
        obs.disable()
    assert total > 0
    counted = sum(s["value"] for s in
                  snap["traffic.requests"]["series"])
    assert int(counted) == total
    assert any(s["labels"].get("shape") == "spike"
               for s in snap["traffic.offered"]["series"])


def test_validation():
    with pytest.raises(ValueError):
        TraceReplay("nope")
    with pytest.raises(ValueError):
        TraceReplay("spike", base_rate=0.0)
    with pytest.raises(ValueError):
        TraceReplay("spike", stems=0)
    with pytest.raises(ValueError):
        TraceReplay("spike", max_new=(0, 4))
    with pytest.raises(ValueError):
        TraceReplay("spike", tenants=())
    with pytest.raises(ValueError):
        TraceReplay("spike", tenants=(("a", -1.0),))
    with pytest.raises(ValueError):
        TraceReplay("spike").requests_at(-1)
