"""Job deployment spec (reference parity: distkeras/job_deployment.py)."""

import shlex
import subprocess
import sys

import numpy as np
import pytest

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.data.transformers import StandardScaleTransformer
from distkeras_tpu.deploy import Job


def test_command_lines_per_host():
    job = Job(script="train.py", num_hosts=4,
              coordinator="10.0.0.1:8476", env={"FOO": "bar", "SEED": 42},
              args=("--epochs", 3))
    cmds = job.command_lines()
    assert len(cmds) == 4
    for h, cmd in enumerate(cmds):
        assert f"DKT_HOST_ID={h}" in cmd
        assert "DKT_NUM_HOSTS=4" in cmd
        assert "DKT_COORDINATOR=10.0.0.1:8476" in cmd
        assert "FOO=bar" in cmd
        assert "SEED=42" in cmd  # non-str env values are coerced
        # Remote commands name a portable interpreter, not this
        # machine's sys.executable.
        assert "python3 train.py --epochs 3" in cmd
        assert sys.executable not in cmd or sys.executable == "python3"
        # Must be valid shell.
        shlex.split(cmd)


def test_env_for_range_checked():
    job = Job(script="t.py", num_hosts=2)
    with pytest.raises(ValueError):
        job.env_for(2)


def test_run_local_executes(tmp_path):
    script = tmp_path / "probe.py"
    script.write_text(
        "import os, sys\n"
        "assert os.environ['DKT_NUM_HOSTS'] == '1'\n"
        "assert os.environ['DKT_HOST_ID'] == '0'\n"
        "sys.exit(0)\n")
    Job(script=str(script)).run_local()


def test_run_local_rejects_multihost():
    with pytest.raises(ValueError):
        Job(script="t.py", num_hosts=2).run_local()


def test_run_local_propagates_nonzero_returncode(tmp_path):
    script = tmp_path / "fail.py"
    script.write_text("import sys\nsys.exit(3)\n")
    with pytest.raises(RuntimeError, match="returncode 3"):
        Job(script=str(script)).run_local()
    # check=False restores the inspect-the-proc escape hatch
    proc = Job(script=str(script)).run_local(check=False)
    assert proc.returncode == 3


def test_run_local_timeout_kills_child(tmp_path):
    script = tmp_path / "hang.py"
    script.write_text("import time\ntime.sleep(600)\n")
    with pytest.raises(TimeoutError, match="did not finish"):
        Job(script=str(script)).run_local(timeout=1.0)


def test_init_from_env_noop_single_host(monkeypatch):
    from distkeras_tpu import deploy

    monkeypatch.delenv("DKT_NUM_HOSTS", raising=False)
    deploy.init_from_env()  # must not raise / touch jax.distributed


def test_standard_scale_transformer():
    rng = np.random.default_rng(0)
    x = rng.normal(3.0, 5.0, (256, 4)).astype(np.float32) * [1, 10, 100, 1000]
    t = StandardScaleTransformer(input_col="features")
    out = t.transform(Dataset({"features": x}))["features"]
    np.testing.assert_allclose(out.mean(axis=0), 0.0, atol=1e-4)
    np.testing.assert_allclose(out.std(axis=0), 1.0, atol=1e-4)
    # Fit-once: a second dataset reuses the first dataset's statistics.
    x2 = x + 100.0
    out2 = t.transform(Dataset({"features": x2}))["features"]
    np.testing.assert_allclose(out2, out + 100.0 / np.maximum(x.std(0), 1e-12),
                               atol=1e-3)


# Shared bootstrapping for every multihost child template: CPU
# platform before any backend init, repo on sys.path, join the
# jax.distributed runtime from the Job env contract.
CHILD_PREAMBLE = """\
import os, sys
os.environ["KERAS_BACKEND"] = "jax"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
sys.path.insert(0, {tests!r})
from distkeras_tpu.deploy import init_from_env
init_from_env()  # joins the multi-process runtime from the Job env vars
"""


MULTIHOST_CHILD = """{preamble}

import numpy as np
import distkeras_tpu as dk
from helpers import make_blobs, make_mlp

assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, jax.devices()
assert jax.local_device_count() == 4

x, y = make_blobs(n=256)
host = int(os.environ["DKT_HOST_ID"])
ds = dk.Dataset.from_arrays(x, y).shard(host, 2)
assert len(ds) == 128

t = dk.ADAG(make_mlp(), loss="sparse_categorical_crossentropy",
            worker_optimizer="sgd", learning_rate=0.05, batch_size=8,
            communication_window=2, num_workers=8, num_epoch=1)
trained = t.train(ds)
assert len(t.history) == 2, t.history
if host == 0:
    np.savez({out!r}, *[np.asarray(w) for w in trained.get_weights()],
             losses=np.asarray(t.history))
print("HOST", host, "OK", flush=True)
"""


def test_two_process_adag_matches_single_process(tmp_path, devices):
    """The multi-host runtime for real: two OS processes join via
    jax.distributed (deploy.Job env contract -> init_from_env), form one
    8-device global mesh, and train ADAG on Dataset.shard-ed data.  The
    strided shard makes every global microbatch the same row *set* as
    the single-process run, and mean-gradients are permutation
    invariant, so the trained weights must match."""
    out = str(tmp_path / "host0.npz")
    _spawn_hosts(MULTIHOST_CHILD, num_hosts=2, devs_per_host=4, out=out)

    # Single-process reference: same data, same global batch math.
    import distkeras_tpu as dk
    from helpers import make_blobs, make_mlp

    x, y = make_blobs(n=256)
    ds = dk.Dataset.from_arrays(x, y)
    t = dk.ADAG(make_mlp(), loss="sparse_categorical_crossentropy",
                worker_optimizer="sgd", learning_rate=0.05, batch_size=8,
                communication_window=2, num_workers=8, num_epoch=1)
    ref = t.train(ds)

    got = np.load(out)
    ref_w = [np.asarray(w) for w in ref.get_weights()]
    got_w = [got[k] for k in got.files if k != "losses"]
    assert len(got_w) == len(ref_w)
    for a, b in zip(got_w, ref_w):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(got["losses"], np.asarray(t.history),
                               rtol=1e-4)


MULTIHOST_ELASTIC_CHILD = """{preamble}

import numpy as np
import distkeras_tpu as dk
from helpers import make_blobs, make_mlp

assert jax.process_count() == 2
host = int(os.environ["DKT_HOST_ID"])

x, y = make_blobs(n=512)
# Exact replica assignment: single-process round r gives replica i the
# rows block[r, i]; host h owns replicas [h*4, h*4+4), so its stream is
# the same blocks restricted to its replica range, in round order.
n, w, B = 8, 2, 8
R = len(x) // (n * w * B)
xb = x[:R*n*w*B].reshape(R, n, w*B, -1)
yb = y[:R*n*w*B].reshape(R, n, w*B)
nl = n // 2
xh = xb[:, host*nl:(host+1)*nl].reshape(-1, x.shape[1])
yh = yb[:, host*nl:(host+1)*nl].reshape(-1)
ds = dk.Dataset.from_arrays(xh, yh)

t = dk.DOWNPOUR(make_mlp(), loss="sparse_categorical_crossentropy",
                worker_optimizer="sgd", learning_rate=0.05, batch_size=B,
                communication_window=w, num_workers=n, num_epoch=1)
trained = t.train(ds)
assert len(t.history) == R, t.history
if host == 0:
    np.savez({out!r}, *[np.asarray(wt) for wt in trained.get_weights()],
             losses=np.asarray(t.history))
print("HOST", host, "OK", flush=True)
"""


def test_two_process_downpour_matches_single_process(tmp_path, devices):
    """The replica-stacked elastic family on the real multi-process
    runtime: per-host local replica slabs assembled into the global
    stacked state, sync collective spanning both hosts.  With the
    replica->host row assignment made explicit, the trained center must
    equal the single-process run's bitwise-ish (same math, same order).
    """
    out = str(tmp_path / "host0.npz")
    _spawn_hosts(MULTIHOST_ELASTIC_CHILD, num_hosts=2, devs_per_host=4,
                 out=out)

    import distkeras_tpu as dk
    from helpers import make_blobs, make_mlp

    x, y = make_blobs(n=512)
    t = dk.DOWNPOUR(make_mlp(), loss="sparse_categorical_crossentropy",
                    worker_optimizer="sgd", learning_rate=0.05, batch_size=8,
                    communication_window=2, num_workers=8, num_epoch=1)
    ref = t.train(dk.Dataset.from_arrays(x, y))

    got = np.load(out)
    ref_w = [np.asarray(w) for w in ref.get_weights()]
    got_w = [got[k] for k in got.files if k != "losses"]
    assert len(ref_w) == len(got_w)
    for a, b in zip(ref_w, got_w):
        np.testing.assert_allclose(a, b, rtol=5e-5, atol=5e-6)
    np.testing.assert_allclose(got["losses"], np.asarray(t.history),
                               rtol=1e-5)


MULTIHOST_LM_CHILD = """{preamble}

import numpy as np
import distkeras_tpu as dk
from distkeras_tpu.models.transformer import TransformerConfig

assert jax.process_count() == 2
host = int(os.environ["DKT_HOST_ID"])

rng = np.random.default_rng(0)
tokens = np.repeat(rng.integers(0, 64, (64, 1)), 17, axis=1).astype(np.int32)
cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=2,
                        d_ff=64, max_len=17)
tr = dk.LMTrainer(cfg, learning_rate=1e-2, batch_size=16, num_epoch=1)
params = tr.train(tokens[host::2])  # strided per-host shard
assert len(tr.history) == 4, tr.history
if host == 0:
    flat = {{"/".join(map(str, p)): np.asarray(v)
            for p, v in jax.tree_util.tree_flatten_with_path(params)[0]}}
    np.savez({out!r}, losses=np.asarray(tr.history), **flat)
print("HOST", host, "OK", flush=True)
"""


def test_two_process_lm_trainer_matches_single_process(tmp_path, devices):
    """The flagship LMTrainer on the real multi-process runtime: each
    host feeds its strided row shard, the global batch is assembled
    from process-local slabs (make_array_from_process_local_data), and
    the optimizer state is built under jit with global shardings.  A
    step's global batch is the same row *set* as the single-process
    run's (strided shard + contiguous blocks), and mean-loss gradients
    are permutation invariant, so losses and trained params must match.
    """
    out = str(tmp_path / "host0.npz")
    _spawn_hosts(MULTIHOST_LM_CHILD, num_hosts=2, devs_per_host=4, out=out)

    # Single-process reference on the full dataset.
    import distkeras_tpu as dk
    from distkeras_tpu.models.transformer import TransformerConfig

    rng = np.random.default_rng(0)
    tokens = np.repeat(rng.integers(0, 64, (64, 1)), 17,
                       axis=1).astype(np.int32)
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=17)
    tr = dk.LMTrainer(cfg, learning_rate=1e-2, batch_size=16, num_epoch=1)
    params = tr.train(tokens)

    import jax as jx

    got = np.load(out)
    np.testing.assert_allclose(got["losses"], np.asarray(tr.history),
                               rtol=1e-4, atol=1e-5)
    ref = {"/".join(map(str, p)): np.asarray(v)
           for p, v in jx.tree_util.tree_flatten_with_path(params)[0]}
    for k, v in ref.items():
        np.testing.assert_allclose(got[k], v, rtol=1e-4, atol=1e-5,
                                   err_msg=k)


# ------------------------------------------------------------------ hard cases
# (round-3: model axis across the process boundary, orbax checkpoint
# save+resume under the multi-process runtime, >2 processes)

def _spawn_hosts(child_src, num_hosts, devs_per_host, timeout=300, **fmt):
    """Run ``child_src`` (a .format template) as ``num_hosts`` OS
    processes joined via a free-port jax.distributed coordinator;
    returns after all exit, raising with the failing hosts' output."""
    import os
    import socket
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tests = os.path.join(repo, "tests")
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    job = Job(script="<inline>", num_hosts=num_hosts,
              coordinator=f"localhost:{port}")
    procs = []
    for h in range(num_hosts):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={devs_per_host}")
        env.update(job.env_for(h))
        # Two-stage format: {preamble} expands to the shared bootstrap,
        # whose own {repo!r}/{tests!r} need their values in the same
        # call — so the preamble is pre-formatted here.
        script = child_src.format(
            repo=repo, tests=tests,
            preamble=CHILD_PREAMBLE.format(repo=repo, tests=tests), **fmt)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    fail = []
    for h, p in enumerate(procs):
        try:
            stdout, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        if p.returncode != 0:
            fail.append(f"host {h} rc={p.returncode}\n"
                        f"{stdout.decode(errors='replace')[-3000:]}")
    assert not fail, "\n---\n".join(fail)


MULTIHOST_TP_CHILD = """{preamble}

import numpy as np
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P
from distkeras_tpu.models import transformer as tfm
from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh, global_batch
from distkeras_tpu.parallel.sharding import ShardingPlan

assert jax.process_count() == 2
host = int(os.environ["DKT_HOST_ID"])

cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=8, n_layers=2,
                            d_ff=64, max_len=17)
host_params = tfm.init_params(jax.random.key(0), cfg)
# model=8 over 2 processes x 4 devices: every Megatron psum crosses the
# process boundary (the ICI/DCN split on a real pod).
mesh = make_mesh(MeshSpec(data=1, model=8))
plan = ShardingPlan(rules=tfm.tp_rules())
psh = plan.tree_shardings(mesh, host_params)
params = jax.tree.map(
    lambda a, sh: jax.make_array_from_callback(
        np.shape(a), sh, lambda idx, a=a: np.asarray(a)[idx]),
    host_params, psh)
opt = optax.adam(1e-2)
opt_state = opt.init(params)
step = jax.jit(tfm.make_train_step(cfg, opt))

rng = np.random.default_rng(0)
tokens = rng.integers(0, 64, (8, 17)).astype(np.int32)
tokens = global_batch(tokens, NamedSharding(mesh, P("data", None)))
losses = []
carry = (params, opt_state)
for _ in range(3):
    carry, loss = step(carry, tokens)
    losses.append(float(loss))
rep = jax.tree.map(
    lambda sh: NamedSharding(mesh, P()), psh)
full = jax.jit(lambda p: p, out_shardings=rep)(carry[0])
if host == 0:
    flat = {{"/".join(map(str, p)): np.asarray(v)
            for p, v in jax.tree_util.tree_flatten_with_path(full)[0]}}
    np.savez({out!r}, losses=np.asarray(losses), **flat)
print("HOST", host, "OK", flush=True)
"""


def test_two_process_model_axis_crosses_boundary(tmp_path, devices):
    """Megatron TP with the ``model`` axis spanning BOTH processes: the
    per-block psum pair runs over the process boundary (on a real pod,
    over DCN), not just the data-axis gradient mean.  Losses and the
    trained params must match the single-process run."""
    import jax as jx
    import optax

    from distkeras_tpu.models import transformer as tfm

    out = str(tmp_path / "host0.npz")
    _spawn_hosts(MULTIHOST_TP_CHILD, num_hosts=2, devs_per_host=4, out=out)

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=8,
                                n_layers=2, d_ff=64, max_len=17)
    params = tfm.init_params(jx.random.key(0), cfg)
    opt = optax.adam(1e-2)
    step = jx.jit(tfm.make_train_step(cfg, opt))
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 64, (8, 17)).astype(np.int32)
    carry = (params, opt.init(params))
    losses = []
    for _ in range(3):
        carry, loss = step(carry, tokens)
        losses.append(float(loss))

    got = np.load(out)
    np.testing.assert_allclose(got["losses"], losses, rtol=2e-4, atol=1e-5)
    ref = {"/".join(map(str, p)): np.asarray(v)
           for p, v in jx.tree_util.tree_flatten_with_path(carry[0])[0]}
    for k, v in ref.items():
        np.testing.assert_allclose(got[k], v, rtol=2e-3, atol=2e-4,
                                   err_msg=k)


MULTIHOST_CKPT_CHILD = """{preamble}

import numpy as np
import distkeras_tpu as dk
from distkeras_tpu.models.transformer import TransformerConfig

assert jax.process_count() == 2
host = int(os.environ["DKT_HOST_ID"])

rng = np.random.default_rng(0)
tokens = rng.integers(0, 64, (64, 17)).astype(np.int32)
cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=2,
                        d_ff=64, max_len=17)
tr = dk.LMTrainer(cfg, learning_rate=1e-2, batch_size=16,
                  num_epoch={num_epoch}, checkpoint_dir={ckdir!r},
                  checkpoint_every=2, resume={resume})
params = tr.train(tokens[host::2])
if host == 0:
    flat = {{"/".join(map(str, p)): np.asarray(v)
            for p, v in jax.tree_util.tree_flatten_with_path(params)[0]}}
    np.savez({out!r}, losses=np.asarray(tr.history), **flat)
print("HOST", host, "OK", flush=True)
"""


def test_two_process_checkpoint_save_and_resume(tmp_path, devices):
    """Orbax checkpointing under the real multi-process runtime: run A
    (2 processes) trains one epoch writing sharded checkpoints; run B
    (2 fresh processes) resumes from them for a second epoch.  The
    resumed params must equal an uninterrupted single-process 2-epoch
    run — checkpoint write AND restore both happen with every array
    global and every host holding only its shards."""
    import jax as jx

    import distkeras_tpu as dk
    from distkeras_tpu.models.transformer import TransformerConfig

    ckdir = str(tmp_path / "ckpt")
    out_a = str(tmp_path / "a.npz")
    out_b = str(tmp_path / "b.npz")
    _spawn_hosts(MULTIHOST_CKPT_CHILD, num_hosts=2, devs_per_host=4,
                 ckdir=ckdir, out=out_a, num_epoch=1, resume=False)
    steps = sorted(int(d) for d in __import__("os").listdir(ckdir)
                   if d.isdigit())
    assert steps == [2, 4], steps  # periodic at 2, final at 4
    _spawn_hosts(MULTIHOST_CKPT_CHILD, num_hosts=2, devs_per_host=4,
                 ckdir=ckdir, out=out_b, num_epoch=2, resume=True)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 64, (64, 17)).astype(np.int32)
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=17)
    tr = dk.LMTrainer(cfg, learning_rate=1e-2, batch_size=16, num_epoch=2)
    params = tr.train(tokens)

    got = np.load(out_b)
    # Run B only executed epoch 2's four rounds.
    assert len(got["losses"]) == 4, got["losses"]
    np.testing.assert_allclose(got["losses"], np.asarray(tr.history)[4:],
                               rtol=1e-4, atol=1e-5)
    ref = {"/".join(map(str, p)): np.asarray(v)
           for p, v in jx.tree_util.tree_flatten_with_path(params)[0]}
    for k, v in ref.items():
        # rtol 1e-3: 8 adam steps amplify multi- vs single-process
        # reduction-order noise slightly past 1e-4 on a few elements;
        # a broken restore is orders of magnitude off.
        np.testing.assert_allclose(got[k], v, rtol=1e-3, atol=1e-4,
                                   err_msg=k)


MULTIHOST_4P_CHILD = """{preamble}

import numpy as np
import distkeras_tpu as dk
from distkeras_tpu.models.transformer import TransformerConfig

assert jax.process_count() == 4, jax.process_count()
assert len(jax.devices()) == 8
assert jax.local_device_count() == 2
host = int(os.environ["DKT_HOST_ID"])

rng = np.random.default_rng(0)
tokens = rng.integers(0, 64, (64, 17)).astype(np.int32)
cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=2,
                        d_ff=64, max_len=17)
tr = dk.LMTrainer(cfg, learning_rate=1e-2, batch_size=16, num_epoch=1)
tr.train(tokens[host::4])
assert len(tr.history) == 4, tr.history
assert all(np.isfinite(tr.history)), tr.history
if host == 0:
    np.savez({out!r}, losses=np.asarray(tr.history))
print("HOST", host, "OK", flush=True)
"""


def test_four_process_smoke(tmp_path, devices):
    """4 processes x 2 devices: the runtime scales past the 2-process
    pair — coordinator join, global mesh assembly, strided per-host data
    feeding, and the loss collective all run with process_count=4.  The
    losses must match the single-process run (same global row sets)."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.transformer import TransformerConfig

    out = str(tmp_path / "host0.npz")
    _spawn_hosts(MULTIHOST_4P_CHILD, num_hosts=4, devs_per_host=2, out=out)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 64, (64, 17)).astype(np.int32)
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=17)
    tr = dk.LMTrainer(cfg, learning_rate=1e-2, batch_size=16, num_epoch=1)
    tr.train(tokens)
    got = np.load(out)
    np.testing.assert_allclose(got["losses"], np.asarray(tr.history),
                               rtol=1e-4, atol=1e-5)


MULTIHOST_PACKED_CHILD = """{preamble}

import numpy as np
import distkeras_tpu as dk
from distkeras_tpu.models.transformer import TransformerConfig

assert jax.process_count() == 2
host = int(os.environ["DKT_HOST_ID"])

rng = np.random.default_rng(3)
docs = [rng.integers(1, 64, (int(n),)).tolist()
        for n in rng.integers(5, 28, 96)]
rows, segs = dk.pack_documents(docs, seq_len=16)
n = (len(rows) // 16) * 16
rows, segs = rows[:n], segs[:n]
cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=2,
                        d_ff=64, max_len=17, rope=True)
tr = dk.LMTrainer(cfg, learning_rate=1e-2, batch_size=16, num_epoch=1)
params = tr.train(rows[host::2], segments=segs[host::2])
if host == 0:
    flat = {{"/".join(map(str, p)): np.asarray(v)
            for p, v in jax.tree_util.tree_flatten_with_path(params)[0]}}
    np.savez({out!r}, losses=np.asarray(tr.history), **flat)
print("HOST", host, "OK", flush=True)
"""


def test_two_process_packed_training_matches_single(tmp_path, devices):
    """Packed-sequence training on the real multi-process runtime: each
    host feeds its strided shard of rows AND segments; losses and the
    trained params must match the single-process run (same global row
    sets, permutation-invariant mean loss)."""
    import jax as jx

    import distkeras_tpu as dk
    from distkeras_tpu.models.transformer import TransformerConfig

    out = str(tmp_path / "host0.npz")
    _spawn_hosts(MULTIHOST_PACKED_CHILD, num_hosts=2, devs_per_host=4,
                 out=out)

    rng = np.random.default_rng(3)
    docs = [rng.integers(1, 64, (int(n),)).tolist()
            for n in rng.integers(5, 28, 96)]
    rows, segs = dk.pack_documents(docs, seq_len=16)
    n = (len(rows) // 16) * 16
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=17, rope=True)
    tr = dk.LMTrainer(cfg, learning_rate=1e-2, batch_size=16, num_epoch=1)
    params = tr.train(rows[:n], segments=segs[:n])

    got = np.load(out)
    np.testing.assert_allclose(got["losses"], np.asarray(tr.history),
                               rtol=1e-4, atol=1e-5)
    ref = {"/".join(map(str, p)): np.asarray(v)
           for p, v in jx.tree_util.tree_flatten_with_path(params)[0]}
    for k, v in ref.items():
        np.testing.assert_allclose(got[k], v, rtol=1e-4, atol=1e-5,
                                   err_msg=k)


MULTIHOST_EVAL_CHILD = """{preamble}

import numpy as np
import distkeras_tpu as dk
from helpers import make_blobs, make_mlp

assert jax.process_count() == 2
host = int(os.environ["DKT_HOST_ID"])

x, y = make_blobs(n=256)
ex, ey = make_blobs(n=128, seed=7)
ds = dk.Dataset.from_arrays(x, y).shard(host, 2)
eval_ds = dk.Dataset.from_arrays(ex, ey).shard(host, 2)

t = dk.ADAG(make_mlp(), loss="sparse_categorical_crossentropy",
            worker_optimizer="sgd", learning_rate=0.05, batch_size=8,
            communication_window=2, num_workers=8, num_epoch=1,
            metrics=("accuracy",), eval_every=1)
t.train(ds, eval_dataset=eval_ds)
assert len(t.eval_history) == 3, t.eval_history  # rounds 1, 2, final

# The replica-stacked family's eval view slices ntv out of the global
# replica stack — an eager a[0] cannot read non-addressable shards, so
# this exercises the jitted replicated slice (code-review regression).
d = dk.DOWNPOUR(make_mlp(), loss="sparse_categorical_crossentropy",
                worker_optimizer="sgd", learning_rate=0.05, batch_size=8,
                communication_window=2, num_workers=8, num_epoch=1,
                metrics=("accuracy",), eval_every=1)
d.train(ds, eval_dataset=eval_ds)
assert len(d.eval_history) == 3, d.eval_history  # rounds 1, 2, final
assert all(np.isfinite(m["loss"]) for _, m in d.eval_history)

# A ragged eval shard (not a multiple of the chunk size) must WARN
# about the dropped tail (advisor round-4) — and still run.
import warnings
rag = dk.Dataset.from_arrays(ex[:68], ey[:68]).shard(host, 2)
w = dk.ADAG(make_mlp(), loss="sparse_categorical_crossentropy",
            worker_optimizer="sgd", learning_rate=0.05, batch_size=8,
            communication_window=2, num_workers=8, num_epoch=1,
            eval_every=1)
with warnings.catch_warnings(record=True) as caught:
    warnings.simplefilter("always")
    w.train(ds, eval_dataset=rag)
assert any("excluded from eval metrics" in str(c.message)
           for c in caught), [str(c.message) for c in caught]

np.savez({out!r} + f".h{{host}}.npz",
         rounds=np.asarray([r for r, _ in t.eval_history]),
         loss=np.asarray([m["loss"] for _, m in t.eval_history]),
         accuracy=np.asarray([m["accuracy"]
                              for _, m in t.eval_history]),
         d_loss=np.asarray([m["loss"] for _, m in d.eval_history]),
         d_acc=np.asarray([m["accuracy"] for _, m in d.eval_history]))
print("HOST", host, "OK", flush=True)
"""


def test_two_process_eval_dataset_matches_single(tmp_path, devices):
    """Mid-training evaluation on the real multi-process runtime
    (round-3 verdict: the eval_dataset ValueError is gone): each host
    stages its eval shard as globally-sharded chunks, the jitted eval
    fn reduces across hosts via the compiled collectives, and the
    recorded history must match the single-process run over the full
    eval set (same rows, permutation-invariant means)."""
    out = str(tmp_path / "evalhist")
    _spawn_hosts(MULTIHOST_EVAL_CHILD, num_hosts=2, devs_per_host=4,
                 out=out)

    import distkeras_tpu as dk
    from helpers import make_blobs, make_mlp

    x, y = make_blobs(n=256)
    ex, ey = make_blobs(n=128, seed=7)
    t = dk.ADAG(make_mlp(), loss="sparse_categorical_crossentropy",
                worker_optimizer="sgd", learning_rate=0.05, batch_size=8,
                communication_window=2, num_workers=8, num_epoch=1,
                metrics=("accuracy",), eval_every=1)
    t.train(dk.Dataset.from_arrays(x, y),
            eval_dataset=dk.Dataset.from_arrays(ex, ey))

    got = np.load(out + ".h0.npz")
    np.testing.assert_array_equal(
        got["rounds"], [r for r, _ in t.eval_history])
    np.testing.assert_allclose(
        got["loss"], [m["loss"] for _, m in t.eval_history],
        rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        got["accuracy"], [m["accuracy"] for _, m in t.eval_history],
        rtol=1e-4, atol=1e-5)
    # Both hosts must record IDENTICAL histories (replicated eval
    # outputs) — for ADAG and for the replica-stacked DOWNPOUR.
    h1 = np.load(out + ".h1.npz")
    for k in ("rounds", "loss", "accuracy", "d_loss", "d_acc"):
        np.testing.assert_array_equal(got[k], h1[k], err_msg=k)


MULTIHOST_DEVICE_DATA_CHILD = """{preamble}

import numpy as np
import distkeras_tpu as dk
from helpers import make_blobs, make_mlp

assert jax.process_count() == 2
host = int(os.environ["DKT_HOST_ID"])

x, y = make_blobs(n=256)
ds = dk.Dataset.from_arrays(x, y).shard(host, 2)

t = dk.ADAG(make_mlp(), loss="sparse_categorical_crossentropy",
            worker_optimizer="sgd", learning_rate=0.05, batch_size=8,
            communication_window=2, num_workers=8, num_epoch=1,
            device_data=True)
trained = t.train(ds)
assert len(t.history) == 2, t.history
if host == 0:
    np.savez({out!r}, *[np.asarray(w) for w in trained.get_weights()],
             losses=np.asarray(t.history))
print("HOST", host, "OK", flush=True)
"""


def test_two_process_device_data_adag_matches_single(tmp_path, devices):
    """The device-resident data plane across hosts (round-3 verdict:
    device_data=True was single-process-only): each host stages its
    shard in replica-stream layout, gathers are replica-local under
    shard_map, and the trained weights must match the single-process
    streaming run (each global microbatch is the same row set; mean
    gradients are permutation invariant)."""
    out = str(tmp_path / "host0.npz")
    _spawn_hosts(MULTIHOST_DEVICE_DATA_CHILD, num_hosts=2,
                 devs_per_host=4, out=out)

    import distkeras_tpu as dk
    from helpers import make_blobs, make_mlp

    x, y = make_blobs(n=256)
    t = dk.ADAG(make_mlp(), loss="sparse_categorical_crossentropy",
                worker_optimizer="sgd", learning_rate=0.05, batch_size=8,
                communication_window=2, num_workers=8, num_epoch=1)
    ref = t.train(dk.Dataset.from_arrays(x, y))

    got = np.load(out)
    ref_w = [np.asarray(w) for w in ref.get_weights()]
    got_w = [got[k] for k in got.files if k != "losses"]
    assert len(got_w) == len(ref_w)
    for a, b in zip(got_w, ref_w):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(got["losses"], np.asarray(t.history),
                               rtol=1e-4)
