"""Job deployment spec (reference parity: distkeras/job_deployment.py)."""

import shlex
import subprocess
import sys

import numpy as np
import pytest

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.data.transformers import StandardScaleTransformer
from distkeras_tpu.deploy import Job


def test_command_lines_per_host():
    job = Job(script="train.py", num_hosts=4,
              coordinator="10.0.0.1:8476", env={"FOO": "bar", "SEED": 42},
              args=("--epochs", 3))
    cmds = job.command_lines()
    assert len(cmds) == 4
    for h, cmd in enumerate(cmds):
        assert f"DKT_HOST_ID={h}" in cmd
        assert "DKT_NUM_HOSTS=4" in cmd
        assert "DKT_COORDINATOR=10.0.0.1:8476" in cmd
        assert "FOO=bar" in cmd
        assert "SEED=42" in cmd  # non-str env values are coerced
        # Remote commands name a portable interpreter, not this
        # machine's sys.executable.
        assert "python3 train.py --epochs 3" in cmd
        assert sys.executable not in cmd or sys.executable == "python3"
        # Must be valid shell.
        shlex.split(cmd)


def test_env_for_range_checked():
    job = Job(script="t.py", num_hosts=2)
    with pytest.raises(ValueError):
        job.env_for(2)


def test_run_local_executes(tmp_path):
    script = tmp_path / "probe.py"
    script.write_text(
        "import os, sys\n"
        "assert os.environ['DKT_NUM_HOSTS'] == '1'\n"
        "assert os.environ['DKT_HOST_ID'] == '0'\n"
        "sys.exit(0)\n")
    Job(script=str(script)).run_local()


def test_run_local_rejects_multihost():
    with pytest.raises(ValueError):
        Job(script="t.py", num_hosts=2).run_local()


def test_init_from_env_noop_single_host(monkeypatch):
    from distkeras_tpu import deploy

    monkeypatch.delenv("DKT_NUM_HOSTS", raising=False)
    deploy.init_from_env()  # must not raise / touch jax.distributed


def test_standard_scale_transformer():
    rng = np.random.default_rng(0)
    x = rng.normal(3.0, 5.0, (256, 4)).astype(np.float32) * [1, 10, 100, 1000]
    t = StandardScaleTransformer(input_col="features")
    out = t.transform(Dataset({"features": x}))["features"]
    np.testing.assert_allclose(out.mean(axis=0), 0.0, atol=1e-4)
    np.testing.assert_allclose(out.std(axis=0), 1.0, atol=1e-4)
    # Fit-once: a second dataset reuses the first dataset's statistics.
    x2 = x + 100.0
    out2 = t.transform(Dataset({"features": x2}))["features"]
    np.testing.assert_allclose(out2, out + 100.0 / np.maximum(x.std(0), 1e-12),
                               atol=1e-3)
