"""Learning-rate schedules through the trainer family: any optax
schedule (step -> lr callable) is accepted by the optimizer-backed
trainers (Single/ADAG/DynSGD/LMTrainer), evaluated on-device inside the
jitted step.  The elastic trainers need a scalar (alpha = rho * lr is
part of their fixed-point math) and say so."""

import numpy as np
import optax
import pytest

import distkeras_tpu as dk
from helpers import make_mlp


def test_schedule_freezes_params_when_lr_hits_zero(blobs):
    """A piecewise schedule dropping to 0 after 2 steps must stop
    parameter movement exactly there — proof the schedule drives the
    update, not just the first step's value."""
    import jax
    from distkeras_tpu.models.adapter import ModelAdapter

    feats, labels = blobs
    sched = optax.piecewise_constant_schedule(0.05, {2: 0.0})
    ad = ModelAdapter(make_mlp(), loss="sparse_categorical_crossentropy",
                      optimizer="sgd", learning_rate=sched)
    state = ad.init_state()
    step = jax.jit(ad.make_train_step(), donate_argnums=0)
    snaps = []
    for i in range(4):
        state, _ = step(state, feats[:32], labels[:32])
        snaps.append(np.asarray(state.tv[0]))
    assert not np.array_equal(snaps[0], snaps[1])  # lr 0.05: moving
    np.testing.assert_array_equal(snaps[2], snaps[3])  # lr 0: frozen


def test_warmup_cosine_through_single_trainer(blobs):
    feats, labels = blobs
    ds = dk.Dataset({"features": feats, "label": labels})
    sched = optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=0.05, warmup_steps=8,
        decay_steps=64, end_value=1e-3)
    t = dk.SingleTrainer(make_mlp(), loss="sparse_categorical_crossentropy",
                         worker_optimizer="sgd", learning_rate=sched,
                         batch_size=16, num_epoch=2)
    t.train(ds)
    assert t.history[-1] < t.history[0] * 0.8


def test_schedule_through_lm_trainer(devices):
    import jax
    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh

    rng = np.random.default_rng(0)
    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=32)
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    sched = optax.warmup_cosine_decay_schedule(0.0, 1e-2, 4, 32)
    t = dk.LMTrainer(cfg, optimizer="adamw", learning_rate=sched,
                     batch_size=16, num_epoch=8, mesh=mesh)
    t.train(rng.integers(0, 64, (64, 17)).astype(np.int32))
    assert t.history[-1] < t.history[0] * 0.85


def test_negative_lr_rejected():
    with pytest.raises(ValueError, match="positive"):
        dk.SingleTrainer(make_mlp(), worker_optimizer="sgd",
                         learning_rate=-0.1)
    from distkeras_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=1, d_ff=64, max_len=32)
    with pytest.raises(ValueError, match="positive"):
        dk.LMTrainer(cfg, learning_rate=-1.0)


def test_elastic_trainers_reject_schedules():
    sched = optax.warmup_cosine_decay_schedule(0.0, 0.05, 4, 32)
    with pytest.raises(ValueError, match="scalar learning_rate"):
        dk.AEASGD(make_mlp(), learning_rate=sched)
    with pytest.raises(ValueError, match="scalar learning_rate"):
        dk.EAMSGD(make_mlp(), learning_rate=sched)
