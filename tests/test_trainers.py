"""Trainer-family integration tests on the 8-device CPU mesh.

The moral equivalent of the reference's workflow.ipynb running every
trainer against one dataset (SURVEY.md §4) — but automated, seeded, and
asserting accuracy, not eyeballing it.
"""

import numpy as np
import pytest

from distkeras_tpu import (
    ADAG,
    AEASGD,
    AveragingTrainer,
    DOWNPOUR,
    DynSGD,
    EAMSGD,
    EnsembleTrainer,
    SingleTrainer,
    AccuracyEvaluator,
    Dataset,
    LabelIndexTransformer,
    ModelPredictor,
)
from tests.conftest import make_blobs, make_mlp


def fit_and_score(trainer_cls, n=1024, accuracy_floor=0.9, **kw):
    x, y = make_blobs(n=n)
    ds = Dataset.from_arrays(x, y)
    model = make_mlp()
    trainer = trainer_cls(model, loss="sparse_categorical_crossentropy",
                          num_epoch=kw.pop("num_epoch", 5), **kw)
    trained = trainer.train(ds)
    assert trainer.training_time > 0
    assert len(trainer.history) > 0
    # losses should drop substantially over training
    assert trainer.history[-1] < trainer.history[0]

    scored = ModelPredictor(trained).predict(ds)
    scored = LabelIndexTransformer()(scored)
    acc = AccuracyEvaluator().evaluate(scored)
    assert acc >= accuracy_floor, f"{trainer_cls.__name__} accuracy {acc}"
    return trainer


def test_single_trainer(devices):
    fit_and_score(SingleTrainer, learning_rate=0.1)


def test_adag(devices):
    fit_and_score(ADAG, learning_rate=0.1, communication_window=2,
                  batch_size=16)


def test_adag_respects_num_workers(devices):
    t = fit_and_score(ADAG, learning_rate=0.1, communication_window=2,
                      batch_size=16, num_workers=4)
    assert t.num_workers == 4


def test_dynsgd(devices):
    fit_and_score(DynSGD, learning_rate=0.1, communication_window=2,
                  batch_size=16)


def test_aeasgd(devices):
    fit_and_score(AEASGD, learning_rate=0.05, rho=1.0,
                  communication_window=4, batch_size=8, num_epoch=10)


def test_eamsgd(devices):
    fit_and_score(EAMSGD, learning_rate=0.02, rho=1.0, momentum=0.9,
                  communication_window=4, batch_size=8, num_epoch=10)


def test_downpour(devices):
    fit_and_score(DOWNPOUR, learning_rate=0.05, communication_window=4,
                  batch_size=8, num_epoch=10)


def test_averaging(devices):
    fit_and_score(AveragingTrainer, learning_rate=0.1, batch_size=8,
                  num_epoch=10)


def test_ensemble(devices):
    x, y = make_blobs(n=1024)
    ds = Dataset.from_arrays(x, y)
    trainer = EnsembleTrainer(make_mlp(), num_models=4,
                              loss="sparse_categorical_crossentropy",
                              worker_optimizer="sgd", learning_rate=0.1,
                              batch_size=8, num_epoch=10)
    models = trainer.train(ds)
    assert len(models) == 4
    # models must be genuinely different (independent training)
    w0 = models[0].get_weights()[0]
    w1 = models[1].get_weights()[0]
    assert not np.allclose(w0, w1)
    # each member should be decent on its own
    for m in models:
        scored = LabelIndexTransformer()(ModelPredictor(m).predict(ds))
        assert AccuracyEvaluator().evaluate(scored) > 0.8


def test_adag_matches_single_semantics(devices):
    """DP + accumulation must equal single-device large-batch SGD."""
    x, y = make_blobs(n=512)
    ds = Dataset.from_arrays(x, y)

    m1 = make_mlp(seed=7)
    t1 = SingleTrainer(m1, loss="sparse_categorical_crossentropy",
                       worker_optimizer="sgd", learning_rate=0.1,
                       batch_size=256, num_epoch=1)
    trained1 = t1.train(ds)

    m2 = make_mlp(seed=7)
    t2 = ADAG(m2, loss="sparse_categorical_crossentropy",
              worker_optimizer="sgd", learning_rate=0.1,
              batch_size=16, communication_window=2, num_workers=8,
              num_epoch=1)
    trained2 = t2.train(ds)

    # batch 256 = 8 workers * 16 rows * window 2 -> identical SGD math
    for a, b in zip(trained1.get_weights(), trained2.get_weights()):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_too_small_dataset_raises(devices):
    x, y = make_blobs(n=64)
    ds = Dataset.from_arrays(x, y)
    t = AEASGD(make_mlp(), loss="sparse_categorical_crossentropy",
               batch_size=32, communication_window=32)
    with pytest.raises(ValueError, match="training step needs"):
        t.train(ds)


def test_ensemble_honors_column_overrides(devices):
    x, y = make_blobs(n=1024)
    ds = Dataset({"f2": x, "y2": y})
    t = EnsembleTrainer(make_mlp(), num_models=2,
                        loss="sparse_categorical_crossentropy",
                        learning_rate=0.1, batch_size=8, num_epoch=2)
    models = t.train(ds, features_col="f2", label_col="y2")
    assert len(models) == 2


def test_ensemble_seed_reproducible(devices):
    x, y = make_blobs(n=512)
    ds = Dataset.from_arrays(x, y)

    def run():
        t = EnsembleTrainer(make_mlp(), num_models=2,
                            loss="sparse_categorical_crossentropy",
                            learning_rate=0.1, batch_size=8, num_epoch=1,
                            seed=3)
        return t.train(ds)

    a, b = run(), run()
    for m1, m2 in zip(a, b):
        for w1, w2 in zip(m1.get_weights(), m2.get_weights()):
            np.testing.assert_array_equal(w1, w2)


def test_single_trainer_steps_per_call_matches_plain(devices):
    """steps_per_call scans the same update sequence: same final weights."""
    x, y = make_blobs(n=512)
    ds = Dataset.from_arrays(x, y)

    def run(spc):
        t = SingleTrainer(make_mlp(), steps_per_call=spc,
                          loss="sparse_categorical_crossentropy",
                          learning_rate=0.1, batch_size=16, num_epoch=2)
        model = t.train(ds)
        return model, t

    m1, t1 = run(1)
    m4, t4 = run(4)
    assert len(t1.history) == len(t4.history)  # per-step losses either way
    np.testing.assert_allclose(t1.history, t4.history, atol=1e-5, rtol=1e-5)
    for w1, w4 in zip(m1.get_weights(), m4.get_weights()):
        np.testing.assert_allclose(w1, w4, atol=1e-5, rtol=1e-5)


def test_single_trainer_steps_per_call_validation(devices):
    with pytest.raises(ValueError, match="steps_per_call"):
        SingleTrainer(make_mlp(), steps_per_call=0)


def test_single_trainer_resume_rejects_spc_mismatch(devices, tmp_path):
    x, y = make_blobs(n=512)
    ds = Dataset.from_arrays(x, y)
    ck = str(tmp_path / "ck")
    t = SingleTrainer(make_mlp(), loss="sparse_categorical_crossentropy",
                      learning_rate=0.1, batch_size=16, num_epoch=1,
                      checkpoint_dir=ck, checkpoint_every=8)
    t.train(ds)
    t2 = SingleTrainer(make_mlp(), steps_per_call=4,
                       loss="sparse_categorical_crossentropy",
                       learning_rate=0.1, batch_size=16, num_epoch=1,
                       checkpoint_dir=ck, resume=True)
    with pytest.raises(ValueError, match="different steps_per_call"):
        t2.train(ds)


def test_aeasgd_warns_on_unstable_alpha(devices):
    # rho*lr*n >= 1 violates the synchronous stability bound; the clamp
    # must be loud, not a silent algorithm substitution.
    with pytest.warns(UserWarning, match="stability bound"):
        t = AEASGD(make_mlp(), loss="sparse_categorical_crossentropy",
                   rho=5.0, learning_rate=0.05, num_workers=8)
    assert t.alpha == pytest.approx(0.9 / 8)


def test_aeasgd_no_warning_inside_bound(devices, recwarn):
    t = AEASGD(make_mlp(), loss="sparse_categorical_crossentropy",
               rho=1.0, learning_rate=0.01, num_workers=8)
    assert t.alpha == pytest.approx(0.01)
    assert not [w for w in recwarn if "stability" in str(w.message)]
