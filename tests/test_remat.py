"""Rematerialization: identical numerics, O(1)-block activation memory."""

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.models import transformer as tfm


def test_transformer_remat_matches_plain(rng):
    base = dict(vocab_size=64, d_model=32, n_heads=2, n_layers=2,
                d_ff=64, max_len=32)
    cfg = tfm.TransformerConfig(**base)
    cfg_r = tfm.TransformerConfig(**base, remat=True)
    params = tfm.init_params(jax.random.key(0), cfg)
    toks = jnp.asarray(rng.integers(0, 64, (4, 17)).astype(np.int32))

    l1, g1 = jax.value_and_grad(tfm.lm_loss)(params, toks, cfg)
    l2, g2 = jax.value_and_grad(tfm.lm_loss)(params, toks, cfg_r)
    np.testing.assert_allclose(l1, l2, atol=1e-6, rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)


def test_transformer_pipelined_remat(devices, rng):
    """remat composes with the pipelined trunk."""
    from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh

    base = dict(vocab_size=64, d_model=32, n_heads=2, n_layers=2,
                d_ff=64, max_len=32)
    cfg = tfm.TransformerConfig(**base)
    cfg_r = tfm.TransformerConfig(**base, remat=True)
    mesh = make_mesh(MeshSpec(data=2, pipeline=2), devices=devices[:4])
    params = tfm.init_params(jax.random.key(0), cfg)
    toks = jnp.asarray(rng.integers(0, 64, (4, 16)).astype(np.int32))
    ref, _ = jax.jit(lambda p, t: tfm.apply_pipelined(p, t, cfg, mesh, 2))(
        params, toks)
    out, _ = jax.jit(lambda p, t: tfm.apply_pipelined(p, t, cfg_r, mesh, 2))(
        params, toks)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_remat_policy_matches_plain_remat(rng):
    """Selective remat changes what the backward saves, never the math:
    loss and grads must match full remat and no remat exactly."""
    import dataclasses

    from distkeras_tpu.models import transformer as tfm

    base = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                 n_layers=2, d_ff=64, max_len=32)
    params = tfm.init_params(jax.random.key(0), base)
    t = jnp.asarray(rng.integers(0, 64, (4, 16)), jnp.int32)
    ref_l, ref_g = jax.value_and_grad(tfm.lm_loss)(params, t, base)
    for kw in ({"remat": True},
               {"remat": True, "remat_policy": "dots"},
               {"remat": True, "remat_policy": "dots_no_batch"}):
        cfg = dataclasses.replace(base, **kw)
        l, g = jax.value_and_grad(tfm.lm_loss)(params, t, cfg)
        np.testing.assert_allclose(float(l), float(ref_l), rtol=1e-6,
                                   err_msg=str(kw))
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            a, b, atol=1e-5, rtol=1e-5), ref_g, g)


def test_remat_policy_validation(rng):
    import dataclasses

    import pytest

    from distkeras_tpu.models import transformer as tfm

    base = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                 n_layers=1, d_ff=64, max_len=16)
    with pytest.raises(ValueError, match="remat_policy"):
        tfm.init_params(jax.random.key(0),
                        dataclasses.replace(base, remat=True,
                                            remat_policy="bogus"))
    # A policy without remat=True would be silently inert; refuse it.
    with pytest.raises(ValueError, match="remat=False"):
        tfm.init_params(jax.random.key(0),
                        dataclasses.replace(base, remat_policy="dots"))


def test_remat_policy_inert_when_remat_disabled_post_init(rng):
    """dataclasses.replace(cfg, remat=False) on a trained config is the
    natural eval move; the leftover policy must be inert, not an error."""
    import dataclasses

    from distkeras_tpu.models import transformer as tfm

    train_cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                      n_layers=1, d_ff=64, max_len=16,
                                      remat=True, remat_policy="dots")
    params = tfm.init_params(jax.random.key(0), train_cfg)
    eval_cfg = dataclasses.replace(train_cfg, remat=False)
    t = jnp.asarray(rng.integers(0, 64, (2, 8)), jnp.int32)
    logits, _ = tfm.apply(params, t, eval_cfg)  # must not raise
    assert logits.shape == (2, 8, 64)
