"""Packed-sequence training: segment-masked attention (all tiers),
loss masking, the packing utility, and LMTrainer integration."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.data.packing import pack_documents, packing_efficiency
from distkeras_tpu.models import transformer as tfm
from distkeras_tpu.ops.attention import (
    blockwise_attention,
    flash_attention,
    naive_attention,
)


# ---------------------------------------------------------------- packing

def test_pack_documents_layout():
    docs = [[1, 2, 3], [4, 5], [6, 7, 8, 9], [10, 11]]
    rows, segs = pack_documents(docs, seq_len=5)
    assert rows.shape == segs.shape and rows.shape[1] == 6
    # Within a row, segment ids are 1..k in order and padding is 0.
    for r, s in zip(rows, segs):
        nz = s[s != 0]
        changes = np.flatnonzero(np.diff(nz)) + 1
        assert (np.diff(nz) >= 0).all()  # non-decreasing
        assert set(np.unique(nz)) == set(range(1, nz.max() + 1))
        del changes
        assert (r[s == 0] == 0).all()
    # Every document's tokens appear, in order, under one segment (or a
    # row-boundary split into consecutive fresh segments).
    flat = [tok for r, s in zip(rows, segs) for tok in r[s != 0]]
    assert flat == [t for d in docs for t in d]


def test_pack_documents_long_doc_spans_rows():
    rows, segs = pack_documents([list(range(1, 15))], seq_len=5)
    assert rows.shape[0] >= 2
    # Continuations restart as fresh segments (context resets at the
    # row boundary) and every row starts with segment 1.
    assert all(s[0] == 1 for s in segs if s[0] != 0)


def test_pack_documents_drops_single_tokens():
    rows, segs = pack_documents([[7], [1, 2, 3]], seq_len=3)
    assert 7 not in rows[segs != 0]


def test_pack_documents_never_emits_single_token_segments():
    """A 1-token chunk is untrainable (boundary-masked target): the
    packer must start the document on a fresh row instead (regression:
    [[1,2,3,4],[5,6,7]] @ seq_len=5 used to strand token 5 alone)."""
    cases = [([[1, 2, 3, 4], [5, 6, 7]], 5),
             ([[1, 2], [3, 4, 5], [6, 7, 8, 9, 10, 11, 12]], 4),
             ([list(range(1, 40))], 6)]
    for docs, sl in cases:
        rows, segs = pack_documents(docs, seq_len=sl)
        for s in segs:
            ids, counts = np.unique(s[s != 0], return_counts=True)
            assert (counts >= 2).all(), (docs, sl, s)


def test_packing_efficiency():
    rows, segs = pack_documents([[1, 2, 3, 4]], seq_len=3)
    assert packing_efficiency(segs) == 1.0


def test_pack_validation():
    with pytest.raises(ValueError, match="seq_len"):
        pack_documents([[1, 2]], seq_len=0)
    with pytest.raises(ValueError, match="2 tokens"):
        pack_documents([[1]], seq_len=4)


# ------------------------------------------------- attention segment masking

def _qkv(rng, b=2, s=64, h=2, d=16):
    mk = lambda: jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
    return mk(), mk(), mk()


def _segs(b, s, splits=(20, 44)):
    seg = np.zeros((b, s), np.int32)
    bounds = (0,) + tuple(splits) + (s,)
    for i in range(len(bounds) - 1):
        seg[:, bounds[i]:bounds[i + 1]] = i + 1
    return jnp.asarray(seg)


def test_blockwise_segments_match_naive(rng):
    q, k, v = _qkv(rng)
    seg = _segs(2, 64)
    ref = naive_attention(q, k, v, causal=True, segment_ids=seg)
    out = blockwise_attention(q, k, v, causal=True, block_k=16,
                              segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_segments_equal_separate_documents(rng):
    """The semantic contract: a packed row attends exactly like its
    documents run alone (per-document slices match)."""
    q, k, v = _qkv(rng, b=1)
    seg = _segs(1, 64)
    packed = naive_attention(q, k, v, causal=True, segment_ids=seg)
    for lo, hi in ((0, 20), (20, 44), (44, 64)):
        alone = naive_attention(q[:, lo:hi], k[:, lo:hi], v[:, lo:hi],
                                causal=True)
        np.testing.assert_allclose(np.asarray(packed[:, lo:hi]),
                                   np.asarray(alone), atol=2e-5, rtol=2e-5)


def test_flash_fallback_segments_grads_match_naive(rng):
    q, k, v = _qkv(rng)
    seg = _segs(2, 64)
    f = lambda fn: jax.grad(
        lambda q, k, v: (fn(q, k, v) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
    got = f(lambda q, k, v: flash_attention(q, k, v, True, segment_ids=seg))
    ref = f(lambda q, k, v: naive_attention(q, k, v, causal=True,
                                            segment_ids=seg))
    for a, b in zip(got, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)


def test_pallas_interpret_segments_fwd_bwd(rng):
    """The Pallas kernels under the TPU-semantics interpreter: segment
    masking in the forward and in both backward kernels, composed with
    the banded (windowed) grid."""
    from distkeras_tpu.ops.attention import _flash_pallas, _flash_pallas_bwd

    b, s, h, d = 1, 256, 1, 128
    q = jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
    seg = _segs(b, s, splits=(100, 180))
    for window in (None, 96):
        ref = naive_attention(q, k, v, causal=True, window=window,
                              segment_ids=seg)
        g = jax.grad(lambda q, k, v: (naive_attention(
            q, k, v, causal=True, window=window, segment_ids=seg) ** 2
        ).sum(), argnums=(0, 1, 2))(q, k, v)
        out, lse = _flash_pallas(q, k, v, True, 1 / np.sqrt(d), 128, 128,
                                 interpret=True, window=window,
                                 segment_ids=seg)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-3, rtol=2e-3)
        dq, dk, dv = _flash_pallas_bwd(
            q, k, v, out, lse, 2 * out, True, 1 / np.sqrt(d), 128, 128,
            interpret=True, window=window, segment_ids=seg)
        for a, b_ in zip((dq, dk, dv), g):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=5e-3, rtol=5e-3)


# --------------------------------------------------------- transformer + loss

CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=64, rope=True)


def test_packed_forward_equals_separate_docs(rng):
    """rope + segments: the packed logits for each document equal the
    document run alone (relative positions survive the shift)."""
    params = tfm.init_params(jax.random.key(0), CFG)
    d1 = rng.integers(1, 64, (1, 11)).astype(np.int32)
    d2 = rng.integers(1, 64, (1, 13)).astype(np.int32)
    row = np.concatenate([d1, d2], axis=1)
    seg = np.concatenate([np.full((1, 11), 1), np.full((1, 13), 2)],
                         axis=1).astype(np.int32)
    packed, _ = tfm.apply(params, jnp.asarray(row), CFG,
                          segment_ids=jnp.asarray(seg))
    for doc, lo, hi in ((d1, 0, 11), (d2, 11, 24)):
        alone, _ = tfm.apply(params, jnp.asarray(doc), CFG)
        np.testing.assert_allclose(np.asarray(packed[:, lo:hi]),
                                   np.asarray(alone), atol=2e-4, rtol=2e-4)


def test_packed_loss_equals_weighted_separate_losses(rng):
    """Masked packed NLL == target-count-weighted mean of per-document
    NLLs (boundary and pad targets excluded)."""
    params = tfm.init_params(jax.random.key(0), CFG)
    d1 = rng.integers(1, 64, (1, 11)).astype(np.int32)
    d2 = rng.integers(1, 64, (1, 9)).astype(np.int32)
    row = np.zeros((1, 25), np.int32)
    row[:, :11], row[:, 11:20] = d1, d2
    seg = np.zeros((1, 25), np.int32)
    seg[:, :11], seg[:, 11:20] = 1, 2
    packed = float(tfm.lm_nll(params, jnp.asarray(row), CFG,
                              segment_ids=jnp.asarray(seg)))
    nll1 = float(tfm.lm_nll(params, jnp.asarray(d1), CFG))
    nll2 = float(tfm.lm_nll(params, jnp.asarray(d2), CFG))
    want = (10 * nll1 + 8 * nll2) / 18
    np.testing.assert_allclose(packed, want, rtol=1e-5)


def test_packed_loss_chunked_ce_matches_full(rng):
    cfg = dataclasses.replace(CFG, ce_chunks=4)
    params = tfm.init_params(jax.random.key(1), CFG)
    row = rng.integers(1, 64, (2, 25)).astype(np.int32)
    seg = np.asarray(_segs(2, 25, splits=(9, 17)))
    full = float(tfm.lm_nll(params, jnp.asarray(row), CFG,
                            segment_ids=jnp.asarray(seg)))
    chunked = float(tfm.lm_nll(params, jnp.asarray(row), cfg,
                               segment_ids=jnp.asarray(seg)))
    np.testing.assert_allclose(chunked, full, rtol=1e-5)


def test_segments_with_custom_attention_fn_rejected(rng):
    params = tfm.init_params(jax.random.key(0), CFG)
    row = rng.integers(1, 64, (1, 8)).astype(np.int32)
    seg = np.ones((1, 8), np.int32)
    with pytest.raises(ValueError, match="custom attention_fn"):
        tfm.apply(params, jnp.asarray(row), CFG,
                  attention_fn=lambda q, k, v: q,
                  segment_ids=jnp.asarray(seg))


# ----------------------------------------------------------- LMTrainer e2e

def test_lm_trainer_packed_end_to_end(rng):
    """pack_documents -> LMTrainer(train with segments) -> eval with
    segments: loss falls and the eval NLL is finite."""
    docs = [rng.integers(1, 64, (int(n),)).tolist()
            for n in rng.integers(3, 30, 40)]
    rows, segs = pack_documents(docs, seq_len=16)
    cfg = dataclasses.replace(CFG, max_len=17)
    n = (len(rows) // 8) * 8
    tr = dk.LMTrainer(cfg, learning_rate=1e-2, batch_size=8, num_epoch=3,
                      eval_every=2)
    tr.train(rows[:n], segments=segs[:n],
             eval_tokens=rows[:8], eval_segments=segs[:8])
    assert tr.history[-1] < tr.history[0]
    assert all(np.isfinite(v["loss"]) for _, v in tr.eval_history)


def test_packed_eval_weighted_by_valid_counts(rng):
    """Eval chunks with unequal valid-target counts must combine into
    the corpus mean (count-weighted), not a mean of chunk means."""
    cfg = dataclasses.replace(CFG, max_len=17)
    rows = rng.integers(1, 64, (16, 17)).astype(np.int32)
    segs = np.ones((16, 17), np.int32)
    # Second chunk: mostly padding -> few valid targets.
    rows[8:, 5:] = 0
    segs[8:, 5:] = 0
    tr = dk.LMTrainer(cfg, learning_rate=1e-2, batch_size=8, num_epoch=1)
    params = tr.train(rows[:8], segments=segs[:8],
                      eval_tokens=rows, eval_segments=segs)
    got = tr.eval_history[-1][1]["loss"]

    n1 = float(tfm.lm_nll(params, jnp.asarray(rows[:8]), cfg,
                          segment_ids=jnp.asarray(segs[:8])))
    n2 = float(tfm.lm_nll(params, jnp.asarray(rows[8:]), cfg,
                          segment_ids=jnp.asarray(segs[8:])))
    w1, w2 = 8 * 16, 8 * 4  # valid targets per chunk
    np.testing.assert_allclose(got, (w1 * n1 + w2 * n2) / (w1 + w2),
                               rtol=1e-6)


def test_ring_attention_segments_match_single(devices, rng):
    """Ring attention with rotating KV-side segment shards equals the
    single-device segmented attention exactly (the packed long-context
    combination)."""
    from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh
    from distkeras_tpu.parallel.ring import make_ring_attention
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh(MeshSpec(data=2, seq=4), devices=devices)
    q, k, v = _qkv(rng, b=2, s=64)
    seg = _segs(2, 64, splits=(13, 37))
    for window in (None, 24):
        ref = naive_attention(q, k, v, causal=True, window=window,
                              segment_ids=seg)
        ring = make_ring_attention(mesh, causal=True, window=window)
        out = jax.jit(lambda q, k, v, s: ring(q, k, v, segment_ids=s),
                      in_shardings=(None, None, None,
                                    NamedSharding(mesh, P("data", "seq"))
                                    ))(q, k, v, seg)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)


def test_packed_forward_ring_mesh_matches_default(devices, rng):
    """apply() with segments on a seq mesh (ring path) == the default
    flash path — one segment semantics across parallelism choices."""
    from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh
    from distkeras_tpu.parallel.ring import make_ring_attention

    mesh = make_mesh(MeshSpec(data=2, seq=4), devices=devices)
    cfg = dataclasses.replace(CFG, max_len=33)
    params = tfm.init_params(jax.random.key(2), cfg)
    row = rng.integers(1, 64, (2, 32)).astype(np.int32)
    seg = np.asarray(_segs(2, 32, splits=(11, 21)))
    ref, _ = tfm.apply(params, jnp.asarray(row), cfg,
                       segment_ids=jnp.asarray(seg))
    ring = make_ring_attention(mesh, causal=True)
    out, _ = tfm.apply(params, jnp.asarray(row), cfg, attention_fn=ring,
                       segment_ids=jnp.asarray(seg))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_lm_trainer_packed_ring_mesh(devices, rng):
    """Packed training runs on a seq (ring) mesh end to end."""
    from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh

    docs = [rng.integers(1, 64, (int(n),)).tolist()
            for n in rng.integers(5, 28, 48)]
    rows, segs = pack_documents(docs, seq_len=16)
    cfg = dataclasses.replace(CFG, max_len=17)
    n = (len(rows) // 8) * 8
    mesh = make_mesh(MeshSpec(data=2, seq=4), devices=devices)
    tr = dk.LMTrainer(cfg, learning_rate=1e-2, batch_size=8, num_epoch=2,
                      mesh=mesh)
    tr.train(rows[:n], segments=segs[:n])
    assert tr.history[-1] < tr.history[0]


@pytest.mark.parametrize("with_seq", [False, True], ids=["pp", "ppxsp"])
def test_packed_forward_pipeline_matches_default(devices, rng, with_seq):
    """apply_pipelined with segments == the default segmented apply —
    per-microbatch segment slices ride the pipeline (and shard over
    seq under PP x SP)."""
    from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh

    spec = (MeshSpec(data=2, pipeline=2, seq=2) if with_seq
            else MeshSpec(data=4, pipeline=2))
    mesh = make_mesh(spec, devices=devices)
    cfg = dataclasses.replace(CFG, max_len=33)
    params = tfm.init_params(jax.random.key(3), cfg)
    rows = rng.integers(1, 64, (4, 32)).astype(np.int32)
    seg = np.asarray(_segs(4, 32, splits=(9, 23)))
    ref, _ = tfm.apply(params, jnp.asarray(rows), cfg,
                       segment_ids=jnp.asarray(seg))
    out, _ = jax.jit(lambda p, t, s: tfm.apply_pipelined(
        p, t, cfg, mesh, microbatches=2,
        seq_axis="seq" if with_seq else None, segment_ids=s))(
        params, jnp.asarray(rows), jnp.asarray(seg))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_lm_trainer_packed_pipeline_mesh(devices, rng):
    """Packed training end to end on a PP x SP mesh."""
    from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh

    docs = [rng.integers(1, 64, (int(n),)).tolist()
            for n in rng.integers(5, 28, 48)]
    rows, segs = pack_documents(docs, seq_len=16)
    cfg = dataclasses.replace(CFG, max_len=17)
    n = (len(rows) // 8) * 8
    mesh = make_mesh(MeshSpec(data=2, pipeline=2, seq=2),
                     devices=devices)
    tr = dk.LMTrainer(cfg, learning_rate=1e-2, batch_size=8, num_epoch=2,
                      mesh=mesh)
    tr.train(rows[:n], segments=segs[:n])
    assert tr.history[-1] < tr.history[0]


def test_remat_composes_with_segments(rng):
    """remat=True with segment_ids: the attention lambda closes over
    the traced segments and still goes through jax.checkpoint's static
    attention_fn slot — loss and grads must match the no-remat run."""
    cfg = dataclasses.replace(CFG, max_len=33, remat=True)
    plain = dataclasses.replace(cfg, remat=False)
    params = tfm.init_params(jax.random.key(4), cfg)
    rows = jnp.asarray(rng.integers(1, 64, (2, 20)), jnp.int32)
    seg = jnp.asarray(np.asarray(_segs(2, 20, splits=(7, 13))))
    ref = float(tfm.lm_nll(params, rows, plain, segment_ids=seg))
    out = float(jax.jit(lambda p, t, s: tfm.lm_nll(p, t, cfg,
                                                   segment_ids=s))(
        params, rows, seg))
    np.testing.assert_allclose(out, ref, rtol=1e-5)
    g = jax.jit(jax.grad(lambda p: tfm.lm_nll(p, rows, cfg,
                                              segment_ids=seg)))(params)
    gr = jax.grad(lambda p: tfm.lm_nll(p, rows, plain,
                                       segment_ids=seg))(params)
    for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(gr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)


def test_lm_trainer_packed_tp_fsdp_mesh(devices, rng):
    """Packed training composes with TP x FSDP sharding."""
    from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh

    docs = [rng.integers(1, 64, (int(n),)).tolist()
            for n in rng.integers(5, 28, 48)]
    rows, segs = pack_documents(docs, seq_len=16)
    cfg = dataclasses.replace(CFG, max_len=17)
    n = (len(rows) // 8) * 8
    mesh = make_mesh(MeshSpec(data=4, model=2), devices=devices)
    tr = dk.LMTrainer(cfg, learning_rate=1e-2, batch_size=8, num_epoch=2,
                      mesh=mesh, fsdp=True)
    tr.train(rows[:n], segments=segs[:n])
    assert tr.history[-1] < tr.history[0]
