"""Round-13 fleet router: cache-aware routing over N engine replicas.

Unit half: FAKE replicas (no jax work) pin the routing core —
membership off heartbeat staleness, affinity hit/miss decisions,
least-loaded fallback, QueueFull spillover, drain-and-reroute
idempotence by request id, and the EngineClosed-vs-enqueue race.
Integration half: two REAL in-process engines prove the routed
tokens keep solo-generate parity across a mid-request drain, the
``--request`` waterfall crosses the router, and the HTTP endpoint
serves the same contract cross-process.
"""

import json
import time
import urllib.request

import numpy as np
import pytest

from distkeras_tpu import obs
from distkeras_tpu.obs.report import request_waterfall
from distkeras_tpu.obs.trace import read_trace
from distkeras_tpu.resilience.admission import RequestResult
from distkeras_tpu.resilience.health import write_beat, beat_age
from distkeras_tpu.serving import (EngineClosed, EngineEndpoint,
                                   HttpReplica, InProcessReplica,
                                   PagedBatcher, QueueFull, Router)
from distkeras_tpu.serving.residency import stem_hexes


# ------------------------------------------------------- fake replicas


class FakeReplica:
    """A replica that admits into a bounded table and finishes
    requests only when the test says so — routing decisions become
    fully deterministic and jax-free."""

    remote = False

    def __init__(self, name, lanes=2, max_queue=2, block=8,
                 resident=(), prefix_ids=(), healthy=True):
        self.name = name
        self.lanes = lanes
        self.max_queue = max_queue
        self.block = block
        self.resident = list(resident)      # hex stem digests
        self.prefix_ids = list(prefix_ids)
        self.is_healthy = healthy
        self.closed = False
        self._next = 0
        self.live = {}                      # rid -> (prompt, max_new)
        self.done = {}                      # rid -> RequestResult
        self.enqueued = []                  # admission order
        self.steps = 0

    def set_rid_base(self, base):
        self._next = max(self._next, base)

    def enqueue(self, prompt, max_new_tokens, **kw):
        if self.closed:
            raise EngineClosed("fake closed")
        if len(self.live) >= self.lanes + self.max_queue:
            raise QueueFull("fake full")
        rid = self._next
        self._next += 1
        self.live[rid] = (np.asarray(prompt, np.int32),
                          int(max_new_tokens))
        self.enqueued.append(rid)
        return rid

    def complete_all(self, status="ok"):
        for rid, (prompt, n) in list(self.live.items()):
            tokens = np.concatenate(
                [prompt, np.zeros(n, np.int32)])
            self.done[rid] = RequestResult(
                request_id=rid, tokens=tokens, status=status,
                prompt_len=prompt.size)
            del self.live[rid]

    def poll(self, rid):
        return self.done.get(rid)

    def step(self):
        self.steps += 1

    def healthy(self):
        return self.is_healthy

    def residency(self):
        return {"stem_hashes": self.resident,
                "prefix_ids": self.prefix_ids, "block": self.block,
                "queue_depth": max(0, len(self.live) - self.lanes),
                "lanes_busy": min(len(self.live), self.lanes),
                "lanes": self.lanes}

    def load(self):
        return (max(0, len(self.live) - self.lanes),
                min(len(self.live), self.lanes), self.lanes)


def _prompt(rng, n=20):
    return rng.integers(0, 64, (n,)).astype(np.int32)


# ------------------------------------------------------------- routing


def test_affinity_hit_routes_to_resident_replica(rng):
    prompt = _prompt(rng)
    r0 = FakeReplica("r0")
    r1 = FakeReplica("r1", resident=stem_hexes(prompt[:-1], 8))
    router = Router([r0, r1])
    router.enqueue(prompt, 4)
    assert len(r1.enqueued) == 1 and not r0.enqueued

    # Miss: an unrelated prompt falls back to least-loaded — r0 (r1
    # now carries the routed request).
    router.enqueue(_prompt(rng), 4)
    assert len(r0.enqueued) == 1


def test_affinity_prefers_longest_resident_prefix(rng):
    prompt = _prompt(rng, 33)
    stems = stem_hexes(prompt[:-1], 8)           # 4 full blocks
    r0 = FakeReplica("r0", resident=stems[:1])
    r1 = FakeReplica("r1", resident=stems)
    router = Router([r0, r1])
    router.enqueue(prompt, 4)
    assert len(r1.enqueued) == 1 and not r0.enqueued


def test_least_loaded_fallback_spreads_by_load(rng):
    r0, r1 = FakeReplica("r0"), FakeReplica("r1")
    router = Router([r0, r1])
    rids = [router.enqueue(_prompt(rng), 4) for _ in range(4)]
    assert len(r0.enqueued) == 2 and len(r1.enqueued) == 2
    for r in (r0, r1):
        r.complete_all()
    assert set(router.pump()) == set(rids)
    assert all(router.take(x).ok for x in rids)


def test_round_robin_policy_alternates(rng):
    r0, r1 = FakeReplica("r0", lanes=8), FakeReplica("r1", lanes=8)
    router = Router([r0, r1], policy="round_robin")
    for _ in range(4):
        router.enqueue(_prompt(rng), 4)
    assert len(r0.enqueued) == 2 and len(r1.enqueued) == 2


def test_queuefull_spills_to_next_candidate_then_caller(rng):
    r0 = FakeReplica("r0", lanes=1, max_queue=0)
    r1 = FakeReplica("r1", lanes=1, max_queue=0)
    router = Router([r0, r1])
    router.enqueue(_prompt(rng), 4)
    router.enqueue(_prompt(rng), 4)      # spillover to the other
    assert len(r0.enqueued) == 1 and len(r1.enqueued) == 1
    # Every live replica saturated: NOW the caller sees QueueFull,
    # and the rejected request leaves no router-side residue.
    with pytest.raises(QueueFull):
        router.enqueue(_prompt(rng), 4)
    assert router.queued == 0
    res = router.shutdown(max_steps=0)
    assert len(res) == 2                 # only the accepted two


def test_prefix_id_routes_to_advertising_replica(rng):
    r0 = FakeReplica("r0")
    r1 = FakeReplica("r1", prefix_ids=[5])
    router = Router([r0, r1])
    router.enqueue(_prompt(rng), 4, prefix_id=5)
    assert len(r1.enqueued) == 1 and not r0.enqueued
    with pytest.raises(ValueError, match="not resident"):
        router.enqueue(_prompt(rng), 4, prefix_id=9)


# ---------------------------------------------------------- membership


def test_membership_via_heartbeat_staleness(rng, tmp_path):
    t = [0.0]
    clock = lambda: t[0]
    hb = str(tmp_path)
    window = 2.0

    def health_of(host):
        def probe():
            aged = beat_age(hb, host, clock=clock)
            return aged is not None and (aged[1]
                                         or aged[0] <= window)
        return probe

    write_beat(hb, 0, 0, 1, clock=clock)
    write_beat(hb, 1, 0, 1, clock=clock)
    r0 = FakeReplica("r0", lanes=8)
    r1 = FakeReplica("r1", lanes=8)
    router = Router([InProcessReplicaLike(r0, health_of(0)),
                     InProcessReplicaLike(r1, health_of(1))],
                    clock=clock, health_interval=0.5)
    rids = [router.enqueue(_prompt(rng), 4) for _ in range(4)]
    assert len(r0.enqueued) == 2 and len(r1.enqueued) == 2
    epoch0 = router.epoch

    # Host 1's beats stop; past the window its replica leaves and its
    # two accepted requests reroute to r0 — none are lost.
    t[0] = 3.0
    write_beat(hb, 0, 0, 2, clock=clock)
    router.pump()
    assert router.replicas_up() == ["r0"]
    assert router.epoch > epoch0
    assert len(r0.enqueued) == 4
    r0.complete_all()
    router.pump()
    assert sorted(router.results()) == sorted(rids)

    # A fresh beat rejoins it under a newer epoch.
    t[0] = 3.6
    write_beat(hb, 0, 0, 3, clock=clock)
    write_beat(hb, 1, 0, 2, clock=clock)
    router.pump()
    assert router.replicas_up() == ["r0", "r1"]


class InProcessReplicaLike:
    """FakeReplica + an injected health probe (the InProcessReplica
    ``health=`` shape, without needing a real engine)."""

    remote = False

    def __init__(self, fake, health):
        self._fake = fake
        self._health = health
        self.name = fake.name

    def healthy(self):
        return bool(self._health())

    def __getattr__(self, item):
        return getattr(self._fake, item)


# ---------------------------------------------------- drain-and-reroute


def test_dead_replica_reroutes_accepted_requests(rng):
    r0 = FakeReplica("r0", lanes=8)
    r1 = FakeReplica("r1", lanes=8)
    router = Router([r0, r1], health_interval=0.0)
    rids = [router.enqueue(_prompt(rng), 4) for _ in range(4)]
    dead = r0 if len(r0.enqueued) else r1
    survivor = r1 if dead is r0 else r0
    dead.is_healthy = False
    router.pump()
    assert router.replicas_up() == [survivor.name]
    assert len(survivor.enqueued) == 4   # every accepted request moved
    survivor.complete_all()
    router.pump()
    results = router.results()
    assert sorted(results) == sorted(rids)
    assert all(results[x].ok for x in rids)


def test_result_before_death_wins_over_reroute(rng):
    """Idempotence ordering: a request its replica finished just
    before dying is RECORDED, not rerouted — one terminal result per
    request id, from the replica that actually served it."""
    r0 = FakeReplica("r0", lanes=8)
    r1 = FakeReplica("r1", lanes=8)
    router = Router([r0, r1], health_interval=0.0)
    rid = router.enqueue(_prompt(rng), 4)
    served = r0 if r0.enqueued else r1
    served.complete_all()
    served.is_healthy = False            # dies WITH the result ready
    router.pump()
    res = router.take(rid)
    assert res.ok and res.request_id == rid
    other = r1 if served is r0 else r0
    assert not other.enqueued            # never rerouted


def test_reroute_parks_when_fleet_saturated_then_recovers(rng):
    r0 = FakeReplica("r0", lanes=1, max_queue=0)
    r1 = FakeReplica("r1", lanes=1, max_queue=0)
    router = Router([r0, r1], health_interval=0.0)
    a = router.enqueue(_prompt(rng), 4)
    b = router.enqueue(_prompt(rng), 4)
    dead = r0 if r0.enqueued else r1
    survivor = r1 if dead is r0 else r0
    dead.is_healthy = False
    router.pump()
    # The survivor is full (it holds its own request): the dead
    # replica's request PARKS instead of surfacing QueueFull to a
    # caller who already holds an id.
    assert router.queued == 1
    survivor.complete_all()
    router.pump()                        # frees a slot; backlog routes
    assert router.queued == 0
    survivor.complete_all()
    router.pump()
    results = router.results()
    assert sorted(results) == sorted([a, b])
    assert all(r.ok for r in results.values())


def test_drain_replica_moves_unfinished_requests(rng):
    r0 = FakeReplica("r0", lanes=8)
    r1 = FakeReplica("r1", lanes=8)
    router = Router([r0, r1], health_interval=1e9)
    rids = [router.enqueue(_prompt(rng), 4) for _ in range(4)]
    target = r0 if r0.enqueued else r1
    other = r1 if target is r0 else r0
    n_target = len(target.enqueued)
    router.drain_replica(target.name)
    assert target.name not in router.replicas_up()
    assert len(other.enqueued) == 4      # 4 - n_target + rerouted
    other.complete_all()
    router.pump()
    assert sorted(router.results()) == sorted(rids), n_target


def test_prefix_request_dies_with_its_replica_as_structured_error(rng):
    """A prefix_id is replica-local: when its only advertising
    replica dies, the reroute cannot serve the request anywhere —
    it must become a structured ``"error"`` result, never an
    exception out of the pump round."""
    r0 = FakeReplica("r0", prefix_ids=[5])
    r1 = FakeReplica("r1")
    router = Router([r0, r1], health_interval=0.0)
    rid = router.enqueue(_prompt(rng), 4, prefix_id=5)
    assert r0.enqueued
    other = router.enqueue(_prompt(rng), 4)   # plain request, reroutable
    r0.is_healthy = False
    router.pump()                             # must not raise
    res = router.take(rid)
    assert res.status == "error" and "prefix_id" in res.error
    r1.complete_all()
    router.pump()
    assert router.take(other).ok


def test_step_thread_failure_flips_healthy():
    """InProcessReplica's driver thread dying on an engine.step()
    exception must flip healthy() so the router reroutes instead of
    hanging that replica's requests forever."""
    class BoomEngine:
        _next_id = 0
        closed = False
        queued = 1

        def running(self):
            return [0]

        def step(self):
            raise RuntimeError("boom")

    rep = InProcessReplica("boomer", BoomEngine())
    rep.start()
    deadline = time.monotonic() + 10.0
    while rep.healthy() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert not rep.healthy()
    rep.stop()


# ----------------------------------------------------------- lifecycle


def test_engineclosed_wins_enqueue_race(rng):
    router = Router([FakeReplica("r0")])
    router.begin_shutdown()
    with pytest.raises(EngineClosed):
        router.enqueue(_prompt(rng), 4)
    assert router.shutdown(max_steps=0) == {}


def test_shutdown_cancels_stragglers(rng):
    r0 = FakeReplica("r0", lanes=8)
    router = Router([r0])
    rid = router.enqueue(_prompt(rng), 4)
    res = router.shutdown(max_steps=2)   # fake never completes
    assert res[rid].status == "cancelled"


def test_replica_scoped_slo_breach_demotes_automatically(rng):
    """Round-14 satellite: ``Router.slo_rules`` stamps one
    ``replica=``-labeled SloRule per attached replica, the breach
    event carries the label, and a single ``breach_demoter()``
    subscriber (no per-replica closure) demotes exactly the replica
    the breaching rule is scoped to."""
    from distkeras_tpu.obs.metrics import MetricsRegistry
    from distkeras_tpu.obs.slo import SloEngine, SloRule

    t = [0.0]
    r0, r1 = FakeReplica("r0"), FakeReplica("r1")
    router = Router([r0, r1], clock=lambda: t[0])
    rules = router.slo_rules(
        SloRule("serving.request_s", percentile=0.5, threshold=1.0,
                window_s=10.0))
    assert [r.replica for r in rules] == ["r0", "r1"]

    events = []
    reg = MetricsRegistry()
    eng = SloEngine(reg, rules, clock=lambda: t[0],
                    emit=lambda name, **f: events.append((name, f)))
    eng.subscribe(router.breach_demoter())
    h = reg.histogram("serving.request_s")
    for _ in range(5):
        h.observe(5.0)
    eng.tick()
    breaches = [f for n, f in events if n == "slo.breach"]
    # Both replicas' rules watch the same aggregated metric here, so
    # both breach — each event labeled with ITS replica.
    assert {b["replica"] for b in breaches} == {"r0", "r1"}
    assert all(m.degraded_until > t[0]
               for m in router._members.values())
    assert reg.counter("slo.breaches").value(
        metric="serving.request_s", q="p50", replica="r0") == 1

    # Degraded replicas sort behind a healthy newcomer until the
    # cooldown passes — the routing effect the label exists for.
    r2 = FakeReplica("r2")
    router.add_replica(r2)
    router.enqueue(_prompt(rng), 4)
    assert len(r2.enqueued) == 1 and not r0.enqueued \
        and not r1.enqueued


def test_expired_on_arrival_never_routes(rng):
    t = [10.0]
    r0 = FakeReplica("r0")
    router = Router([r0], clock=lambda: t[0])
    rid = router.enqueue(_prompt(rng), 4, ttl=0.0)
    assert router.take(rid).timed_out
    assert not r0.enqueued


# ------------------------------------------- integration: real engines

CFG_KW = dict(vocab_size=64, d_model=32, n_heads=2, n_layers=2,
              d_ff=64, max_len=32, rope=True)
BLOCK = 8


@pytest.fixture(scope="module")
def engine_params():
    import jax

    from distkeras_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(**CFG_KW)
    return tfm.init_params(jax.random.key(0), cfg), cfg


def _paged(params, cfg, **kw):
    kw.setdefault("prompt_buckets", (8,))
    kw.setdefault("max_queue", 8)
    return PagedBatcher(params, cfg, lanes=2, block=BLOCK,
                        n_blocks=2 * (cfg.max_len // BLOCK) + 1, **kw)


def test_two_engine_affinity_and_parity(engine_params, rng):
    from distkeras_tpu.models.generate import generate

    params, cfg = engine_params
    engines = [_paged(params, cfg) for _ in range(2)]
    router = Router([InProcessReplica(f"r{i}", e)
                     for i, e in enumerate(engines)])
    stem = rng.integers(0, 64, (8,)).astype(np.int32)
    tails = rng.integers(0, 64, (4, 4)).astype(np.int32)
    prompts = [np.concatenate([stem, t]) for t in tails]
    rids = [router.enqueue(p, 5) for p in prompts]
    while any(router.poll(x) is None for x in rids):
        router.step()
    results = {x: router.take(x) for x in rids}
    # Affinity co-located the shared stem: 3 of 4 admissions hit.
    assert sum(e.stem_hit_blocks for e in engines) >= 3
    for x, p in zip(rids, prompts):
        solo = np.asarray(generate(params, p[None], cfg, 5))[0]
        np.testing.assert_array_equal(results[x].tokens, solo)


def test_drain_midstream_keeps_parity_and_waterfall(engine_params,
                                                    rng, tmp_path):
    from distkeras_tpu.models.generate import generate

    params, cfg = engine_params
    trace = str(tmp_path / "router.jsonl")
    engines = [_paged(params, cfg) for _ in range(2)]
    router = Router([InProcessReplica(f"r{i}", e)
                     for i, e in enumerate(engines)])
    prompt = rng.integers(0, 64, (6,)).astype(np.int32)
    with obs.session(trace_path=trace):
        rid = router.enqueue(prompt, 10)
        router.step()                   # partial decode on hop 0
        src = router._requests[rid].replica
        router.drain_replica(src)       # forces the re-route hop
        res = router.drain(rid)
        assert res.ok
        solo = np.asarray(generate(params, prompt[None], cfg, 10))[0]
        np.testing.assert_array_equal(res.tokens, solo)
    wf = request_waterfall(read_trace(trace), rid)
    assert wf["found"] and wf["status"] == "ok"
    assert wf["reroutes"] == 1
    names = [s["name"] for s in wf["stages"]]
    assert "router.route" in names and "router.reroute" in names
    assert "serving.emit" in names and "serving.finish" in names
    # The final hop's stages carry the serving replica's name.
    replicas = {s.get("replica") for s in wf["stages"]
                if s["name"] == "serving.emit"}
    assert replicas and None not in replicas
    assert wf["tokens"] == 10


def test_residency_digest_and_endpoint(engine_params, rng):
    params, cfg = engine_params
    eng = _paged(params, cfg)
    pid = eng.pin_prefix(rng.integers(0, 64, (8,)).astype(np.int32))
    doc = eng.residency()
    assert doc["block"] == BLOCK and doc["lanes"] == 2
    assert pid in doc["prefix_ids"]
    assert len(doc["stem_hashes"]) == 1        # one pinned full block
    with obs.session(serve_port=0, residency=eng.residency) as sess:
        url = sess.server.url + "/residency"
        got = json.loads(urllib.request.urlopen(url, timeout=5).read())
        assert got["stem_hashes"] == doc["stem_hashes"]
        assert got["block"] == BLOCK
    eng.unpin_prefix(pid)


def test_http_endpoint_serves_router(engine_params, rng):
    params, cfg = engine_params
    eng = _paged(params, cfg)
    ep = EngineEndpoint(eng, host_id=3)
    ep.start(step=True)
    try:
        replica = HttpReplica("h3", ep.addr)
        router = Router([replica], health_interval=0.0)
        prompt = rng.integers(0, 64, (6,)).astype(np.int32)
        rid = router.enqueue(prompt, 5)
        deadline = time.monotonic() + 60.0
        while router.poll(rid) is None:
            router.pump()
            assert time.monotonic() < deadline
            time.sleep(0.01)
        res = router.take(rid)
        assert res.ok and res.prompt_len == 6
        assert len(res.generated) == 5
        # The endpoint's rid base keeps fleet traces collision-free.
        assert res.request_id == rid and rid < 1_000_000
        doc = json.loads(urllib.request.urlopen(
            f"http://{ep.addr}/residency", timeout=5).read())
        assert doc["block"] == BLOCK
        assert replica.healthy()
    finally:
        ep.stop()
    assert not replica.healthy()
    router.pump()                        # health probe flips it down
    assert router.replicas_up() == []
