"""Autoscaling control plane (round 19): the Autoscaler's policy
loop over Router.fleet_snapshot() and the warm pool — zero-compile
health-gated scale-up, lossless drain-and-reroute scale-down,
hysteresis/cooldown/envelope, the pinned-state retire guard, SLO
breach wiring, and the deterministic decision audit trail."""

import numpy as np
import pytest

from distkeras_tpu.obs.metrics import MetricsRegistry
from distkeras_tpu.obs.slo import SloEngine, SloRule
from distkeras_tpu.resilience.admission import (QueueFull,
                                                RequestResult)
from distkeras_tpu.serving.autoscale import (Autoscaler,
                                             AutoscalePolicy, WarmPool)
from distkeras_tpu.serving.router import Router
from distkeras_tpu.serving.traffic import TraceReplay


class FakeReplica:
    """Deterministic jax-free replica: bounded queue, ``step()``
    completes at most ``lanes`` requests per call (so queues build
    under load), controllable health, and a residency doc carrying
    pinned ``prefix_ids`` for the retire-guard tests."""

    remote = False

    def __init__(self, name, lanes=2, max_queue=64, role=None,
                 prefix_ids=(), fail_residency=False):
        self.name = name
        self.lanes = lanes
        self.max_queue = max_queue
        self.role = role
        self.prefix_ids = set(prefix_ids)
        self.fail_residency = fail_residency
        self.alive = True
        self._next = 0
        self._q: dict[int, tuple] = {}
        self._done: dict[int, RequestResult] = {}

    def set_rid_base(self, base):
        self._next = max(self._next, base)

    def enqueue(self, prompt, max_new, **kw):
        if len(self._q) >= self.max_queue:
            raise QueueFull("full")
        rid = self._next
        self._next += 1
        self._q[rid] = (np.asarray(prompt, np.int32), int(max_new))
        return rid

    def step(self):
        for rid in list(self._q)[:self.lanes]:
            p, n = self._q.pop(rid)
            self._done[rid] = RequestResult(
                rid, np.concatenate([p, np.ones(n, np.int32)]), "ok",
                p.size)

    def poll(self, rid):
        return self._done.get(rid)

    def partial(self, rid):
        return self._done.get(rid)

    def healthy(self):
        return self.alive

    def residency(self):
        if self.fail_residency or not self.alive:
            raise RuntimeError("replica is gone")
        return {"queue_depth": len(self._q), "lanes_busy": 0,
                "lanes": self.lanes, "block": None, "stem_hashes": [],
                "prefix_ids": sorted(self.prefix_ids)}

    def load(self):
        return (len(self._q), 0, self.lanes)


def _fleet(*replicas, clock=None):
    return Router(list(replicas),
                  clock=clock if clock is not None else (lambda: 0.0))


# ------------------------------------------------------ fleet_snapshot


def test_fleet_snapshot_one_consistent_read():
    """The snapshot carries per-replica health/queue/role/affinity
    and the fleet epoch/backlog from ONE locked read."""
    t = [0.0]
    r0, r1 = FakeReplica("r0"), FakeReplica("r1", lanes=4)
    router = _fleet(r0, r1, clock=lambda: t[0])
    router.enqueue([1, 2, 3], 2)
    snap = router.fleet_snapshot()
    assert set(snap) == {"epoch", "pending", "closed", "replicas"}
    assert snap["epoch"] == router.epoch and snap["pending"] == 0
    assert set(snap["replicas"]) == {"r0", "r1"}
    one = snap["replicas"]["r0"]
    for key in ("up", "draining", "degraded", "inflight", "role",
                "queue_depth", "lanes_busy", "lanes", "load",
                "prefix_ids", "stems", "block"):
        assert key in one
    assert sum(r["queue_depth"]
               for r in snap["replicas"].values()) == 1
    assert snap["replicas"]["r1"]["lanes"] == 4


def test_fleet_snapshot_degraded_and_draining_flags():
    t = [0.0]
    router = _fleet(FakeReplica("r0"), FakeReplica("r1"),
                    clock=lambda: t[0])
    router.mark_degraded("r0", cooldown=5.0)
    snap = router.fleet_snapshot()
    assert snap["replicas"]["r0"]["degraded"]
    assert not snap["replicas"]["r1"]["degraded"]
    t[0] = 6.0  # cooldown expired
    assert not router.fleet_snapshot()["replicas"]["r0"]["degraded"]
    router.drain_replica("r1")
    snap = router.fleet_snapshot()
    assert snap["replicas"]["r1"]["draining"]
    assert snap["epoch"] == router.epoch


def test_fleet_snapshot_feeds_routing_consistently():
    """The migrated consumers: the route scorer reads degraded/load
    from the same snapshot — a degraded replica loses the tie, so
    routing demotes it exactly as the per-field reads used to."""
    router = _fleet(FakeReplica("r0"), FakeReplica("r1"))
    router.mark_degraded("r0", cooldown=100.0)
    rids = [router.enqueue([1, 2, 3], 1) for _ in range(3)]
    snap = router.fleet_snapshot()
    assert snap["replicas"]["r1"]["queue_depth"] == 3
    assert snap["replicas"]["r0"]["queue_depth"] == 0
    del rids


def test_remove_replica_returns_handle():
    r0, r1 = FakeReplica("r0"), FakeReplica("r1")
    router = _fleet(r0, r1)
    assert router.remove_replica("r1") is r1
    assert router.replicas_up() == ["r0"]


# ------------------------------------------------------------ scale-up


def _scaler(router, pool, **kw):
    defaults = dict(min_replicas=1, max_replicas=4, up_threshold=0.9,
                    down_threshold=0.3, up_after=1, down_after=2,
                    cooldown_ticks=0, breach_ticks=2)
    defaults.update(kw)
    return Autoscaler(router, pool,
                      policy=AutoscalePolicy(**defaults))


def test_scale_up_on_saturation_admits_warm_replica():
    router = _fleet(FakeReplica("r0", lanes=1))
    pool = WarmPool([FakeReplica("w0")])
    asc = _scaler(router, pool)
    for _ in range(4):
        router.enqueue([1, 2], 1)
    rec = asc.tick()
    assert rec["action"] == "up" and rec["replica"] == "w0"
    assert "w0" in router.replicas_up()
    assert len(pool) == 0
    assert rec["epoch"] == router.epoch  # joined under a bumped epoch


def test_join_health_gate_skips_dead_pool_replica():
    """A replica that died IN the pool must never get a route-table
    entry: the join aborts cleanly and the next candidate admits."""
    router = _fleet(FakeReplica("r0", lanes=1))
    dead = FakeReplica("w0")
    dead.alive = False
    pool = WarmPool([dead, FakeReplica("w1")])
    asc = _scaler(router, pool)
    for _ in range(4):
        router.enqueue([1, 2], 1)
    rec = asc.tick()
    assert rec["action"] == "up" and rec["replica"] == "w1"
    assert "w0" not in router.replicas_up()
    snap = router.fleet_snapshot()
    assert "w0" not in snap["replicas"]
    aborts = [d for d in asc.decisions if d["action"] == "abort"]
    del aborts  # aborts surface via obs events; decisions holds ticks


def test_join_aborts_when_death_races_the_gate():
    """Died BETWEEN the health gate and the join (the mid-join
    SIGKILL shape): ``add_replica`` sees it dead-on-arrival, and the
    autoscaler drops the membership entry rather than leaving a dead
    replica in the table."""
    router = _fleet(FakeReplica("r0", lanes=1))
    racy = FakeReplica("w0", fail_residency=True)  # gate ok, join dead

    def health_flip():
        # healthy() passes the gate once, then the process is gone.
        racy.alive = False
        return True

    racy.healthy = health_flip
    pool = WarmPool([racy, FakeReplica("w1")])
    asc = _scaler(router, pool)
    for _ in range(4):
        router.enqueue([1, 2], 1)
    rec = asc.tick()
    assert rec["action"] == "up" and rec["replica"] == "w1"
    assert "w0" not in router.fleet_snapshot()["replicas"]


def test_pool_exhausted_recorded_not_fatal():
    router = _fleet(FakeReplica("r0", lanes=1))
    asc = _scaler(router, WarmPool())
    for _ in range(4):
        router.enqueue([1, 2], 1)
    rec = asc.tick()
    assert rec["action"] == "exhausted"
    assert router.replicas_up() == ["r0"]


def test_max_envelope_respected():
    router = _fleet(FakeReplica("r0", lanes=1))
    pool = WarmPool([FakeReplica("w0"), FakeReplica("w1")])
    asc = _scaler(router, pool, max_replicas=2)
    for _ in range(8):
        router.enqueue([1, 2], 1)
    asc.tick()
    asc.tick()
    asc.tick()
    assert len(router.replicas_up()) == 2
    assert len(pool) == 1  # second warm replica never admitted


# ---------------------------------------------------------- scale-down


def test_scale_down_is_lossless_and_pools_the_handle():
    """Retire = the existing drain-and-reroute: unfinished requests
    re-admit elsewhere and complete; the retired handle returns to
    the warm pool still warm."""
    r0, r1 = FakeReplica("r0"), FakeReplica("r1")
    router = _fleet(r0, r1)
    pool = WarmPool()
    asc = _scaler(router, pool, down_after=1)
    rids = [router.enqueue([1, 2, 3], 2) for _ in range(2)]
    epoch0 = router.epoch
    rec = asc.tick()  # util = 4 queued+0 busy over 4 lanes? -> hold
    # Drain to idle then let the low-streak trigger a retire.
    for _ in range(4):
        router.step()
    rec = asc.tick()
    assert rec["action"] == "down"
    assert len(router.replicas_up()) == 1
    assert router.epoch > epoch0
    assert pool.names() == (rec["replica"],)
    for rid in rids:
        res = router.drain(rid)
        assert res.status == "ok"


def test_min_envelope_respected():
    router = _fleet(FakeReplica("r0"))
    asc = _scaler(router, WarmPool(), down_after=1)
    for _ in range(5):
        rec = asc.tick()
    assert rec["action"] == "hold"
    assert router.replicas_up() == ["r0"]


def test_retire_refused_for_last_pinned_holder():
    """Satellite regression: the ONLY replica advertising a pinned
    prefix_id is never retired — the scale-down defers until the pin
    is released, then proceeds."""
    pinned = FakeReplica("r0", prefix_ids={7})
    free = FakeReplica("r1")
    router = _fleet(pinned, free)
    router.refresh_residency()
    pool = WarmPool()
    asc = _scaler(router, pool, down_after=1)
    rec = asc.tick()
    # Idle fleet of two: r1 (unpinned) must be the victim even though
    # r0 sorts first by name at equal load.
    assert rec["action"] == "down" and rec["replica"] == "r1"
    # Now r0 is the last member holding pin 7 AND the only retire
    # candidate above... min=1 stops further downs; rebuild with
    # min=1 and two pinned replicas to hit the defer path.
    a = FakeReplica("a", prefix_ids={1})
    b = FakeReplica("b", prefix_ids={2})
    router2 = _fleet(a, b)
    router2.refresh_residency()
    asc2 = _scaler(router2, WarmPool(), down_after=1)
    rec2 = asc2.tick()
    assert rec2["action"] == "defer"
    assert rec2["reason"] == "pinned-last-holder"
    assert len(router2.replicas_up()) == 2
    # Unpin b: the deferred retire proceeds on the next tick.
    b.prefix_ids.clear()
    router2.refresh_residency()
    rec3 = asc2.tick()
    assert rec3["action"] == "down" and rec3["replica"] == "b"


def test_retire_allowed_when_pin_resident_elsewhere():
    """A pin advertised by MORE than one replica does not block the
    retire (nothing is lost while another holder serves it)."""
    a = FakeReplica("a", prefix_ids={5})
    b = FakeReplica("b", prefix_ids={5})
    router = _fleet(a, b)
    router.refresh_residency()
    asc = _scaler(router, WarmPool(), down_after=1)
    rec = asc.tick()
    assert rec["action"] == "down"


# --------------------------------------------------------- hysteresis


def test_hysteresis_damps_flapping_load():
    """Alternating hot/cold ticks with down_after=3 and a cooldown
    must not thrash membership: at most the initial scale-up
    happens."""
    r0 = FakeReplica("r0", lanes=1)
    router = _fleet(r0)
    pool = WarmPool([FakeReplica("w0"), FakeReplica("w1")])
    asc = _scaler(router, pool, down_after=3, cooldown_ticks=2)
    changes = 0
    for i in range(12):
        if i % 2 == 0:
            rids = [router.enqueue([1, 2], 1) for _ in range(4)]
            del rids
        for _ in range(6):
            router.step()
        rec = asc.tick()
        changes += rec["action"] in ("up", "down")
    assert changes <= 2, \
        f"membership thrashed: {changes} changes in 12 flapping ticks"


def test_cooldown_blocks_back_to_back_changes():
    router = _fleet(FakeReplica("r0", lanes=1))
    pool = WarmPool([FakeReplica("w0"), FakeReplica("w1"),
                     FakeReplica("w2")])
    asc = _scaler(router, pool, cooldown_ticks=3)
    for _ in range(12):
        router.enqueue([1, 2], 1)
    first = asc.tick()
    assert first["action"] == "up"
    held = [asc.tick() for _ in range(2)]
    assert all(r["action"] == "hold" and r["reason"] == "cooldown"
               for r in held)
    assert len(router.replicas_up()) == 2


# ---------------------------------------------------------- SLO wiring


def test_slo_breach_votes_scale_up():
    """``on_breach`` is a SloEngine.subscribe target: a breach votes
    scale-up for breach_ticks ticks even while utilization is calm —
    the latency-led half of the policy."""
    router = _fleet(FakeReplica("r0"))
    pool = WarmPool([FakeReplica("w0")])
    asc = _scaler(router, pool, breach_ticks=2)
    t = [0.0]
    reg = MetricsRegistry()
    eng = SloEngine(
        reg, rules=(SloRule("serving.ttft_s", percentile=0.5,
                            threshold=0.01, window_s=5.0),),
        clock=lambda: t[0])
    eng.subscribe(asc.on_breach)
    h = reg.histogram("serving.ttft_s", "ttft")
    eng.tick()
    for _ in range(8):
        h.observe(0.5)
    t[0] = 1.0
    eng.tick()  # ok -> breach edge fires the subscriber
    rec = asc.tick()
    assert rec["action"] == "up" and rec["reason"] == "breach"


def test_breach_vote_expires():
    router = _fleet(FakeReplica("r0"))
    pool = WarmPool([FakeReplica("w0")])
    asc = _scaler(router, pool, breach_ticks=1, max_replicas=1)
    asc.on_breach(None, 1.0)
    rec = asc.tick()   # breach vote active but fleet at max: hold
    assert rec["action"] == "hold"
    for _ in range(4):
        rec = asc.tick()
    assert rec["action"] == "hold"


# ------------------------------------------------- determinism harness


def _replay_run(seed):
    """A miniature bench harness: fixed trace + fake fleet + scaler,
    everything stepped synchronously — the decision timeline must be
    a pure function of the seed."""
    trace = TraceReplay("spike", seed=seed, base_rate=1.0,
                        spike_at=4, spike_len=6, spike_rate=10.0)
    r0 = FakeReplica("r0", lanes=2)
    warm = [FakeReplica(f"w{i}", lanes=2) for i in range(3)]
    router = _fleet(r0)
    asc = _scaler(router, WarmPool(warm), down_after=2,
                  cooldown_ticks=1)
    for t in range(24):
        for req in trace.requests_at(t):
            try:
                router.enqueue(
                    trace.prompt(req, stem_len=4, tail_len=2,
                                 vocab=16), req.max_new)
            except QueueFull:
                pass
        for _ in range(2):
            router.step()
        asc.tick()
    return [(d["tick"], d["action"], d["replica"], d["reason"],
             d["replicas"], d["epoch"]) for d in asc.decisions]


def test_decision_timeline_deterministic_same_seed():
    a = _replay_run(11)
    b = _replay_run(11)
    assert a == b
    assert any(action == "up" for _, action, _r, _re, _n, _e in a), \
        "spike never triggered a scale-up"


def test_decision_timeline_varies_with_seed():
    assert _replay_run(1) != _replay_run(2) or True  # non-binding
    # (different seeds usually differ; the binding claim is same-seed
    # identity above)


# ---------------------------------------------------------- validation


def test_policy_validation():
    with pytest.raises(ValueError):
        AutoscalePolicy(min_replicas=0)
    with pytest.raises(ValueError):
        AutoscalePolicy(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError):
        AutoscalePolicy(up_threshold=0.2, down_threshold=0.5)
    with pytest.raises(ValueError):
        AutoscalePolicy(up_after=0)
    with pytest.raises(ValueError):
        AutoscalePolicy(cooldown_ticks=-1)


def test_warm_pool_fifo():
    a, b = FakeReplica("a"), FakeReplica("b")
    pool = WarmPool([a])
    pool.put(b)
    assert len(pool) == 2 and pool.names() == ("a", "b")
    assert pool.take() is a and pool.take() is b
    assert pool.take() is None
