import numpy as np
import pytest

from distkeras_tpu import (
    Dataset,
    OneHotTransformer,
    LabelIndexTransformer,
    MinMaxTransformer,
    ReshapeTransformer,
    DenseTransformer,
    AccuracyEvaluator,
)


def make_ds(n=100):
    return Dataset.from_arrays(
        np.arange(n * 4, dtype=np.float32).reshape(n, 4),
        np.arange(n, dtype=np.int64) % 3)


def test_basics():
    ds = make_ds()
    assert len(ds) == 100
    assert set(ds.columns) == {"features", "label"}
    ds2 = ds.with_column("z", np.zeros(100))
    assert "z" in ds2.columns and "z" not in ds.columns
    with pytest.raises(ValueError):
        Dataset({"a": np.zeros(3), "b": np.zeros(4)})


def test_shuffle_is_permutation():
    ds = make_ds().shuffle(seed=1)
    assert sorted(ds["label"].tolist()) == sorted(make_ds()["label"].tolist())
    assert not np.array_equal(ds["label"], make_ds()["label"])


def test_shard_partitions_everything():
    ds = make_ds(100)
    parts = [ds.shard(i, 4) for i in range(4)]
    assert sum(len(p) for p in parts) == 100
    all_rows = np.concatenate([p["features"] for p in parts])
    assert sorted(all_rows[:, 0].tolist()) == sorted(ds["features"][:, 0].tolist())


def test_batches_shapes():
    ds = make_ds(100)
    batches = list(ds.batches(32))
    assert len(batches) == 3
    x, y = batches[0]
    assert x.shape == (32, 4) and y.shape == (32,)


def test_windowed_batches():
    ds = make_ds(128)
    batches = list(ds.batches(16, window=4))
    assert len(batches) == 2
    x, y = batches[0]
    assert x.shape == (4, 16, 4) and y.shape == (4, 16)


def test_one_hot_transformer():
    ds = OneHotTransformer(3)(make_ds())
    assert ds["label_onehot"].shape == (100, 3)
    np.testing.assert_array_equal(ds["label_onehot"].argmax(-1), ds["label"])


def test_label_index_transformer():
    ds = make_ds().with_column("prediction",
                               np.eye(3, dtype=np.float32)[make_ds()["label"]])
    out = LabelIndexTransformer()(ds)
    np.testing.assert_array_equal(out["prediction_index"], ds["label"])


def test_min_max_transformer():
    ds = MinMaxTransformer(input_col="features")(make_ds())
    assert ds["features"].min() >= 0.0 and ds["features"].max() <= 1.0


def test_reshape_transformer():
    ds = ReshapeTransformer("features", "image", (2, 2, 1))(make_ds())
    assert ds["image"].shape == (100, 2, 2, 1)


def test_dense_transformer_sparse():
    idx = np.empty(2, dtype=object)
    val = np.empty(2, dtype=object)
    idx[0], val[0] = np.array([0, 2]), np.array([1.0, 2.0])
    idx[1], val[1] = np.array([1]), np.array([3.0])
    ds = Dataset({"i": idx, "v": val})
    out = DenseTransformer(indices_col="i", values_col="v", size=4,
                           output_col="features")(ds)
    np.testing.assert_array_equal(out["features"],
                                  [[1, 0, 2, 0], [0, 3, 0, 0]])


def test_accuracy_evaluator():
    ds = make_ds().with_column("prediction_index", make_ds()["label"])
    assert AccuracyEvaluator().evaluate(ds) == 1.0
    wrong = (make_ds()["label"] + 1) % 3
    ds2 = make_ds().with_column("prediction_index", wrong)
    assert AccuracyEvaluator().evaluate(ds2) == 0.0


def test_csv_round_trip(tmp_path):
    p = tmp_path / "data.csv"
    p.write_text("a,b,y\n1.0,2.0,0\n3.0,4.0,1\n")
    ds = Dataset.from_csv(str(p), label_col="y")
    assert ds["features"].shape == (2, 2)
    np.testing.assert_array_equal(ds["y"], [0, 1])


def test_window_requires_drop_remainder():
    ds = make_ds(10)
    with pytest.raises(ValueError, match="drop_remainder"):
        list(ds.batches(2, window=3, drop_remainder=False))


def test_csv_multiline_header(tmp_path):
    p = tmp_path / "d.csv"
    p.write_text("# comment line\na,b,y\n1.0,2.0,0\n3.0,4.0,1\n")
    ds = Dataset.from_csv(str(p), label_col="y", skip_header=2)
    assert ds["features"].shape == (2, 2)
    np.testing.assert_array_equal(ds["y"], [0, 1])


def test_csv_headerless():
    import tempfile, os
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "h.csv")
        with open(p, "w") as f:
            f.write("1.0,2.0,0\n3.0,4.0,1\n")
        ds = Dataset.from_csv(p, label_col=2, skip_header=0)
        assert ds["features"].shape == (2, 2)
        np.testing.assert_array_equal(ds["label"], [0, 1])
        ds2 = Dataset.from_csv(p, skip_header=0)
        assert ds2["features"].shape == (2, 3)


def test_csv_headerless_single_column(tmp_path):
    # One column: must parse as [n, 1] samples, not one [1, n] row.
    p = tmp_path / "one.csv"
    p.write_text("1.0\n2.0\n3.0\n")
    ds = Dataset.from_csv(str(p), skip_header=0)
    assert ds["features"].shape == (3, 1)


def test_split_deterministic_and_disjoint():
    import distkeras_tpu as dk

    rng = np.random.default_rng(0)
    ds = dk.Dataset({"features": rng.normal(size=(100, 4)).astype(np.float32),
                     "label": np.arange(100)})
    a, b = ds.split(0.8, seed=3)
    a2, b2 = ds.split(0.8, seed=3)
    assert len(a) == 80 and len(b) == 20
    np.testing.assert_array_equal(a["label"], a2["label"])
    assert set(a["label"]) | set(b["label"]) == set(range(100))
    assert not set(a["label"]) & set(b["label"])
    import pytest

    with pytest.raises(ValueError, match="frac"):
        ds.split(1.5)
    with pytest.raises(ValueError, match="empty"):
        dk.Dataset({"x": np.arange(3)}).split(0.1)


def test_dense_transformer_sparse_edge_cases():
    # Empty rows mixed with populated ones, out-of-range rejection, and
    # scale (the scatter is one flattened fancy-index, not a row loop).
    idx = np.empty(3, dtype=object)
    val = np.empty(3, dtype=object)
    idx[0], val[0] = np.array([3]), np.array([7.0])
    idx[1], val[1] = np.array([], dtype=np.int64), np.array([])
    idx[2], val[2] = np.array([0, 1]), np.array([1.0, 2.0])
    ds = Dataset({"i": idx, "v": val})
    out = DenseTransformer(indices_col="i", values_col="v", size=4,
                           output_col="features")(ds)
    np.testing.assert_array_equal(out["features"],
                                  [[0, 0, 0, 7], [0, 0, 0, 0], [1, 2, 0, 0]])

    bad = np.empty(1, dtype=object)
    badv = np.empty(1, dtype=object)
    bad[0], badv[0] = np.array([5]), np.array([1.0])
    import pytest

    with pytest.raises(ValueError, match="out of range"):
        DenseTransformer(indices_col="i", values_col="v", size=4)(
            Dataset({"i": bad, "v": badv}))

    rng = np.random.default_rng(0)
    n, size, nnz = 20000, 256, 8
    big_i = np.empty(n, dtype=object)
    big_v = np.empty(n, dtype=object)
    for r in range(n):
        big_i[r] = rng.choice(size, nnz, replace=False)
        big_v[r] = rng.normal(size=nnz)
    dense = DenseTransformer(indices_col="i", values_col="v", size=size,
                             output_col="features")(
        Dataset({"i": big_i, "v": big_v}))["features"]
    r = 1234
    ref = np.zeros(size, np.float32)
    ref[big_i[r]] = big_v[r]
    np.testing.assert_allclose(dense[r], ref, rtol=1e-6)


def test_dense_transformer_rejects_row_length_mismatch():
    # Equal totals, unequal rows: must raise, never shift values.
    idx = np.empty(2, dtype=object)
    val = np.empty(2, dtype=object)
    idx[0], val[0] = np.array([0, 1]), np.array([1.0])
    idx[1], val[1] = np.array([2]), np.array([2.0, 3.0])
    import pytest

    with pytest.raises(ValueError, match="mismatch at row 0"):
        DenseTransformer(indices_col="i", values_col="v", size=4)(
            Dataset({"i": idx, "v": val}))
