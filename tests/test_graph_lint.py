"""Graph lint: every AST and IR rule, positive + negative, plus the
``# dkt: ignore`` suppression syntax and the census parser.

Heavier checks against the REAL trainer/serving programs (comm budget,
ZeRO-1 parity, compile counts) live in tests/test_budget_guards.py.
"""

import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.analysis.findings import (Finding, apply_suppressions,
                                              suppressed_rules)
from distkeras_tpu.analysis.ir_lint import (CollectiveOp, TraceSpec,
                                             check_budget,
                                             census_to_budget,
                                             check_zero1_parity,
                                             comm_census, lint_trace)
from distkeras_tpu.analysis.source_lint import lint_source


def rules_of(findings, only_gating=False):
    return {f.rule for f in findings if f.gating or not only_gating}


def lint(src, path="distkeras_tpu/models/foo.py"):
    return lint_source(textwrap.dedent(src), path=path)


# ------------------------------------------------------------- AST rules


def test_jit_wallclock_positive_and_negative():
    pos = lint("""
        import time, jax

        @jax.jit
        def step(x):
            t = time.time()
            return x * t
    """)
    assert "jit-wallclock" in rules_of(pos)
    neg = lint("""
        import time, jax

        def host_logger(x):
            return time.time()
    """)
    assert "jit-wallclock" not in rules_of(neg)


def test_jit_np_random_positive_and_negative():
    pos = lint("""
        import jax
        import numpy as np

        def step(x):
            return x + np.random.rand()

        f = jax.jit(step)
    """)
    assert "jit-np-random" in rules_of(pos)
    neg = lint("""
        import numpy as np

        def make_batch(n):
            return np.random.rand(n)
    """)
    assert "jit-np-random" not in rules_of(neg)


def test_traced_detection_reaches_nested_defs():
    pos = lint("""
        import time, jax

        @jax.jit
        def outer(x):
            def inner(y):
                return y + time.time()
            return inner(x)
    """)
    assert "jit-wallclock" in rules_of(pos)


def test_hot_sync_positive_and_negative():
    src = """
        import jax

        def run(losses):
            for l in losses:
                jax.device_get(l)
    """
    assert "hot-sync" in rules_of(
        lint(src, path="distkeras_tpu/trainers/foo.py"))
    # Same code off the hot paths: no finding.
    assert "hot-sync" not in rules_of(
        lint(src, path="distkeras_tpu/data/foo.py"))
    # Hot path but not in a loop: no finding.
    assert "hot-sync" not in rules_of(lint("""
        import jax

        def run(loss):
            jax.device_get(loss)
    """, path="distkeras_tpu/trainers/foo.py"))


def test_import_time_jnp_positive_and_negative():
    pos = lint("""
        import jax.numpy as jnp

        TABLE = jnp.arange(1024)
    """)
    assert "import-time-jnp" in rules_of(pos)
    neg = lint("""
        import jax.numpy as jnp

        def table():
            return jnp.arange(1024)
    """)
    assert "import-time-jnp" not in rules_of(neg)


def test_mutable_default_positive_and_negative():
    pos = lint("""
        def submit(prompt, hooks=[]):
            return hooks
    """)
    assert "mutable-default" in rules_of(pos)
    neg = lint("""
        def submit(prompt, hooks=None):
            return hooks or []

        def _private(prompt, hooks=[]):
            return hooks
    """)
    assert "mutable-default" not in rules_of(neg)


def test_jit_no_donate_positive_and_negative():
    pos = lint("""
        import jax

        def make(train_step):
            return jax.jit(train_step)
    """)
    assert "jit-no-donate" in rules_of(pos)
    neg = lint("""
        import jax

        def make(train_step, loss_fn):
            a = jax.jit(train_step, donate_argnums=0)
            b = jax.jit(loss_fn)
            return a, b
    """)
    assert "jit-no-donate" not in rules_of(neg)


def test_axis_name_positive_and_negative():
    pos = lint("""
        from jax.sharding import PartitionSpec as P

        SPEC = P("dta", None)
    """)
    assert "axis-name" in rules_of(pos)
    neg = lint("""
        from jax.sharding import PartitionSpec as P

        SPEC = P("data", ("model", "seq"))
    """)
    assert "axis-name" not in rules_of(neg)


def test_loop_jit_positive_and_negative():
    pos = lint("""
        import jax

        def compile_all(fns):
            out = []
            for f in fns:
                out.append(jax.jit(f))
            return out
    """)
    assert "loop-jit" in rules_of(pos)
    neg = lint("""
        import jax

        def compile_one(f):
            return jax.jit(f, donate_argnums=0)
    """)
    assert "loop-jit" not in rules_of(neg)


# ---------------------------------------------------- thread-safety rules


def tlint(src, path="distkeras_tpu/serving/foo.py"):
    from distkeras_tpu.analysis.thread_lint import lint_source_threads

    return lint_source_threads(textwrap.dedent(src), path=path)


def test_raw_lock_positive_and_negative():
    src = """
        import threading

        L = threading.Lock()
    """
    assert "raw-lock" in rules_of(tlint(src))
    assert "raw-lock" in rules_of(tlint(
        "import threading\nR = threading.RLock()",
        path="distkeras_tpu/obs/foo.py"))
    # Outside the threaded scope: no finding.
    assert "raw-lock" not in rules_of(
        tlint(src, path="distkeras_tpu/models/foo.py"))
    # The instrumented wrappers are the fix, not a finding.
    assert "raw-lock" not in rules_of(tlint("""
        from distkeras_tpu.utils.locks import TracedLock

        L = TracedLock("x")
    """))
    # ... and their own module is the one allowlisted raw-lock home.
    assert "raw-lock" not in rules_of(tlint(
        "import threading\nL = threading.Lock()",
        path="distkeras_tpu/utils/locks.py"))
    # Every import spelling is caught, not just the literal one.
    assert "raw-lock" in rules_of(tlint("""
        from threading import Lock

        L = Lock()
    """))
    assert "raw-lock" in rules_of(tlint("""
        from threading import RLock as R

        L = R()
    """))
    assert "raw-lock" in rules_of(tlint("""
        import threading as t

        L = t.Condition()
    """))
    # A non-threading Lock name does not fire.
    assert "raw-lock" not in rules_of(tlint("""
        from multiprocessing import Lock

        L = Lock()
    """))


def test_lock_callback_positive_and_negative():
    # The exact PR-8 deadlock shape: subscribers fired under the lock.
    pos = tlint("""
        class T:
            def tick(self):
                with self._lock:
                    for fn in list(self._subscribers):
                        fn(1)
    """)
    assert "lock-callback" in rules_of(pos)
    # Direct call of a callback-named attribute under a lock.
    assert "lock-callback" in rules_of(tlint("""
        class T:
            def fire(self):
                with self._lock:
                    self.on_breach_callback(1)
    """))
    # The fixed shape: collect under the lock, fire after release.
    assert "lock-callback" not in rules_of(tlint("""
        class T:
            def tick(self):
                with self._lock:
                    fired = list(self._subscribers)
                for fn in fired:
                    fn(1)
    """))
    # A def nested under the with runs LATER, not under the lock.
    assert "lock-callback" not in rules_of(tlint("""
        class T:
            def tick(self):
                with self._lock:
                    def later():
                        for fn in list(self._subscribers):
                            fn(1)
                    self.pending = later
    """))


def test_lock_blocking_positive_and_negative():
    assert "lock-blocking" in rules_of(tlint("""
        import time

        def f(lock):
            with lock:
                time.sleep(1.0)
    """))
    assert "lock-blocking" in rules_of(tlint("""
        import subprocess

        def f(lock):
            with lock:
                subprocess.run(["g++"])
    """))
    assert "lock-blocking" in rules_of(tlint("""
        class T:
            def stop(self):
                with self._lock:
                    self._thread.join(timeout=5.0)
    """))
    assert "lock-blocking" in rules_of(tlint("""
        from urllib.request import urlopen

        def f(lock):
            with lock:
                return urlopen("http://peer/metrics").read()
    """))
    # The same calls OFF the lock: no finding.
    assert "lock-blocking" not in rules_of(tlint("""
        import time

        def f(lock):
            with lock:
                n = 1
            time.sleep(1.0)
    """))
    # A string join under a lock is not a thread join.
    assert "lock-blocking" not in rules_of(tlint("""
        def f(lock, parts):
            with lock:
                return ",".join(parts)
    """))


def test_lock_double_acquire_positive_and_negative():
    pos = tlint("""
        from distkeras_tpu.utils.locks import TracedLock

        class T:
            def __init__(self):
                self._lock = TracedLock("t")

            def f(self):
                with self._lock:
                    with self._lock:
                        pass
    """)
    assert "lock-double-acquire" in rules_of(pos)
    # The same nesting on a REENTRANT lock is legal.
    assert "lock-double-acquire" not in rules_of(tlint("""
        from distkeras_tpu.utils.locks import TracedRLock

        class T:
            def __init__(self):
                self._lock = TracedRLock("t")

            def f(self):
                with self._lock:
                    with self._lock:
                        pass
    """))
    # Two DIFFERENT locks nesting: legal.
    assert "lock-double-acquire" not in rules_of(tlint("""
        from distkeras_tpu.utils.locks import TracedLock

        class T:
            def __init__(self):
                self._a = TracedLock("a")
                self._b_lock = TracedLock("b")

            def f(self):
                with self._a:
                    with self._b_lock:
                        pass
    """))
    # An attr name bound reentrant in ONE class and non-reentrant in
    # another is ambiguous, not proof: the reentrant class's legal
    # nesting must not fire.
    assert "lock-double-acquire" not in rules_of(tlint("""
        from distkeras_tpu.utils.locks import TracedLock, TracedRLock

        class A:
            def __init__(self):
                self._lock = TracedRLock("a")

            def f(self):
                with self._lock:
                    with self._lock:
                        pass

        class B:
            def __init__(self):
                self._lock = TracedLock("b")
    """))


def test_thread_lint_suppression_and_severity():
    findings = tlint("""
        import time

        def f(lock):
            with lock:
                time.sleep(0.1)  # dkt: ignore[lock-blocking]
    """)
    hits = [f for f in findings if f.rule == "lock-blocking"]
    assert hits and all(f.suppressed for f in hits)
    assert not [f for f in findings if f.gating]
    # raw-lock / lock-callback / lock-double-acquire are errors
    # (never baselineable); lock-blocking is a warn (ratchets).
    sev = {f.rule: f.severity for f in tlint("""
        import threading, time

        L = threading.Lock()

        def f(lock):
            with lock:
                time.sleep(0.1)
    """)}
    assert sev == {"raw-lock": "error", "lock-blocking": "warn"}


def test_thread_lint_clean_on_repo():
    """The shipped threaded core lints clean — the migration to
    TracedLock is complete and nothing fires callbacks or blocks
    under a lock (zero suppressions; satellite acceptance)."""
    import os

    from distkeras_tpu.analysis.thread_lint import lint_paths_threads

    root = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "distkeras_tpu")
    findings = lint_paths_threads([root])
    gating = [f.format() for f in findings if f.gating]
    assert not gating, gating
    assert not [f for f in findings if f.suppressed], (
        "the concurrency gate ships with zero suppressions")


# ----------------------------------------------------------- suppression


def test_jax_free_positive_and_negative():
    """Round-11 satellite: modules on the jit-free ledger (the live
    telemetry plane, the offline obs modules) must never import jax —
    not even lazily inside a function."""
    src = """
        def handler():
            import jax

            return jax.devices()
    """
    pos = lint(src, path="distkeras_tpu/obs/live.py")
    assert "jax-free" in rules_of(pos, only_gating=True)
    pos = lint("from jax import numpy as jnp",
               path="distkeras_tpu/obs/slo.py")
    assert "jax-free" in rules_of(pos, only_gating=True)
    # Same import outside the ledger: no finding.
    neg = lint(src, path="distkeras_tpu/serving/lanes.py")
    assert "jax-free" not in rules_of(neg)
    # Ledger module importing non-jax things: no finding.
    neg = lint("import json\nimport threading\n",
               path="distkeras_tpu/obs/live.py")
    assert "jax-free" not in rules_of(neg)


def test_jax_free_ledger_covers_live_plane_on_disk():
    """The shipped live-plane modules really are jax-free (the rule
    would gate a regression; this pins the ledger covers them)."""
    import os

    from distkeras_tpu.analysis.source_lint import (_JAX_FREE_FILES,
                                                    lint_paths)

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = [os.path.join(root, f) for f in _JAX_FREE_FILES]
    assert all(os.path.exists(p) for p in paths), paths
    assert {os.path.basename(p) for p in paths} >= {"live.py", "slo.py"}
    findings = lint_paths(paths)
    assert not [f.format() for f in findings if f.rule == "jax-free"]


def test_suppression_comment_parsing():
    assert suppressed_rules("x = 1") is None
    assert suppressed_rules("x = 1  # dkt: ignore") == frozenset()
    assert suppressed_rules("x = 1  # dkt: ignore[a-b, c]") == {"a-b", "c"}


def test_suppression_matching_rule():
    f = Finding(rule="hot-sync", severity="warn", path="p", line=1,
                message="m")
    assert apply_suppressions(f, "foo()  # dkt: ignore[hot-sync]").suppressed
    assert apply_suppressions(f, "foo()  # dkt: ignore").suppressed
    assert not apply_suppressions(f, "foo()  # dkt: ignore[other]").suppressed
    assert not apply_suppressions(f, "foo()").suppressed


def test_source_suppression_end_to_end():
    src = """
        import time, jax

        @jax.jit
        def step(x):
            return x * time.time()  # dkt: ignore[jit-wallclock]
    """
    findings = lint(src)
    assert [f for f in findings if f.rule == "jit-wallclock"]
    assert not [f for f in findings if f.gating]


def test_ir_suppression_via_spec():
    def f(x):
        a = jax.random.normal(x, (4,))
        b = jax.random.normal(x, (4,))
        return a + b

    spec = TraceSpec(name="t", fn=jax.jit(f),
                     args=(jax.random.key(0),),
                     suppress=("prng-reuse",))
    findings, _ = lint_trace(spec, compile_census=False)
    hits = [f for f in findings if f.rule == "prng-reuse"]
    assert hits and all(f.suppressed for f in hits)


# -------------------------------------------------------------- IR rules


def _ir(fn, *args, donate=(), **jit_kw):
    spec = TraceSpec(name="t",
                     fn=jax.jit(fn, donate_argnums=donate, **jit_kw),
                     args=args, donate_argnums=donate)
    findings, _ = lint_trace(spec, compile_census=False)
    return findings


def test_dtype_f64_positive_and_negative():
    with jax.experimental.enable_x64():
        pos = _ir(lambda x: jnp.asarray(x, jnp.float64) * 2.0,
                  jax.ShapeDtypeStruct((4,), jnp.float32))
    assert "dtype-f64" in rules_of(pos)
    neg = _ir(lambda x: x * 2.0, jax.ShapeDtypeStruct((4,), jnp.float32))
    assert "dtype-f64" not in rules_of(neg)


def test_dtype_upcast_positive_and_negative():
    # Upcast escaping into elementwise math: silent, flagged.
    pos = _ir(lambda x: x.astype(jnp.float32) * 2.0,
              jax.ShapeDtypeStruct((4,), jnp.bfloat16))
    assert "dtype-upcast" in rules_of(pos)
    # f32 ACCUMULATION of a bf16 value (sum's internal promotion) is
    # the standard intentional upcast — exempt.
    neg = _ir(lambda x: x.astype(jnp.bfloat16).sum(),
              jax.ShapeDtypeStruct((4,), jnp.float32))
    assert "dtype-upcast" not in rules_of(neg)


def test_host_callback_positive_and_negative():
    def pos_fn(x):
        jax.debug.print("x={x}", x=x)
        return x + 1

    assert "host-callback" in rules_of(
        _ir(pos_fn, jax.ShapeDtypeStruct((), jnp.float32)))
    assert "host-callback" not in rules_of(
        _ir(lambda x: x + 1, jax.ShapeDtypeStruct((), jnp.float32)))


def test_prng_reuse_positive_and_negative():
    def pos_fn(key):
        a = jax.random.normal(key, (4,))
        b = jax.random.categorical(key, jnp.zeros((8,)))
        return a.sum() + b

    assert "prng-reuse" in rules_of(_ir(pos_fn, jax.random.key(0)))

    def neg_fn(key):
        k1, k2 = jax.random.split(key)
        return (jax.random.normal(k1, (4,)).sum()
                + jax.random.categorical(k2, jnp.zeros((8,))))

    assert "prng-reuse" not in rules_of(_ir(neg_fn, jax.random.key(0)))


def test_prng_loop_invariant_reuse():
    def pos_fn(key, xs):
        def body(c, x):
            return c + jax.random.categorical(key, x), None

        out, _ = jax.lax.scan(body, 0.0, xs)
        return out

    xs = jax.ShapeDtypeStruct((3, 8), jnp.float32)
    assert "prng-reuse" in rules_of(_ir(pos_fn, jax.random.key(0), xs))

    def neg_fn(key, xs):
        def body(c, ix):
            i, x = ix
            k = jax.random.fold_in(key, i)
            return c + jax.random.categorical(k, x), None

        out, _ = jax.lax.scan(body, 0.0, (jnp.arange(3), xs))
        return out

    assert "prng-reuse" not in rules_of(
        _ir(neg_fn, jax.random.key(0), xs))

    def neg_presplit(key, xs):
        # The textbook pattern: scan OVER pre-split keys — each
        # iteration's key varies, nothing is loop-invariant.
        ks = jax.random.split(key, 3)

        def body(c, kx):
            k, x = kx
            return c + jax.random.categorical(k, x), None

        out, _ = jax.lax.scan(body, 0.0, (ks, xs))
        return out

    assert "prng-reuse" not in rules_of(
        _ir(neg_presplit, jax.random.key(0), xs))


def test_prng_cond_branches_are_exclusive():
    def neg_fn(pred, key):
        # Only one branch runs: consuming the key once in EACH branch
        # is exactly one consumption at runtime.
        return jax.lax.cond(
            pred,
            lambda k: jax.random.normal(k, (4,)),
            lambda k: jax.random.uniform(k, (4,)),
            key)

    assert "prng-reuse" not in rules_of(
        _ir(neg_fn, jax.ShapeDtypeStruct((), jnp.bool_),
            jax.random.key(0)))

    def pos_fn(pred, key):
        # Consumed before the cond AND inside a branch: real reuse.
        a = jax.random.normal(key, (4,))
        b = jax.lax.cond(
            pred,
            lambda k: jax.random.normal(k, (4,)),
            lambda k: jnp.zeros((4,)),
            key)
        return a + b

    assert "prng-reuse" in rules_of(
        _ir(pos_fn, jax.ShapeDtypeStruct((), jnp.bool_),
            jax.random.key(0)))


def test_donation_unused_positive_and_negative():
    pos = _ir(lambda x: (x * 2.0).sum(),
              jax.ShapeDtypeStruct((8,), jnp.float32), donate=(0,))
    assert "donation-unused" in rules_of(pos)
    neg = _ir(lambda x: x * 2.0,
              jax.ShapeDtypeStruct((8,), jnp.float32), donate=(0,))
    assert "donation-unused" not in rules_of(neg)


def test_donation_read_positive_and_negative():
    pos = _ir(lambda x: (x, x + 1.0),
              jax.ShapeDtypeStruct((8,), jnp.float32), donate=(0,))
    assert "donation-read" in rules_of(pos)
    neg = _ir(lambda x: (x + 1.0, x.sum()),
              jax.ShapeDtypeStruct((8,), jnp.float32), donate=(0,))
    assert "donation-read" not in rules_of(neg)


# ------------------------------------------------------- census + budget


_SYNTH_HLO = """\
HloModule synth

%add (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %s = f32[] add(f32[] %a, f32[] %b)
}

%fused_computation.1 (p0: f32[128], p1: s32[]) -> f32[16] {
  %p0 = f32[128]{0} parameter(0)
  %p1 = s32[] parameter(1)
  ROOT %ds = f32[16]{0} dynamic-slice(f32[128]{0} %p0, s32[] %p1), dynamic_slice_sizes={16}
}

ENTRY %main.1 (g: f32[128], x: f32[1,16], y: f32[128], l: f32[]) -> f32[16] {
  %g = f32[128]{0} parameter(0)
  %x = f32[1,16]{1,0} parameter(1)
  %y = f32[128]{0} parameter(2)
  %l = f32[] parameter(3)
  %all-reduce = f32[128]{0} all-reduce(f32[128]{0} %g), channel_id=1, replica_groups=[1,8]<=[8], to_apply=%add
  %pid = s32[] partition-id()
  %use = f32[16]{0} fusion(f32[128]{0} %all-reduce, s32[] %pid), kind=kLoop, calls=%fused_computation.1
  %all-reduce.1 = f32[] all-reduce(f32[] %l), channel_id=2, replica_groups=[1,8]<=[8], to_apply=%add
  %b = f32[16]{0} broadcast(f32[] %all-reduce.1), dimensions={}
  %all-gather = f32[8,16]{1,0} all-gather(f32[1,16]{1,0} %x), channel_id=3, replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}
  %reduce-scatter = f32[16]{0} reduce-scatter(f32[128]{0} %y), channel_id=4, replica_groups=[1,8]<=[8], dimensions={0}, to_apply=%add
  ROOT %out = f32[16]{0} add(f32[16]{0} %use, f32[16]{0} %b)
}
"""


def test_comm_census_parses_and_canonicalizes():
    census = {(c.op, c.canonical): c
              for c in comm_census(_SYNTH_HLO, default_group=8)}
    # The gradient AR's only consumer slices 1/8 of it -> canonical RS.
    ar_rs = census[("all-reduce", "reduce-scatter")]
    assert ar_rs.payload_bytes == 512 and ar_rs.wire_bytes == 448.0
    # The loss AR's consumer broadcasts (no slice) -> stays AR.
    ar = census[("all-reduce", "all-reduce")]
    assert ar.payload_bytes == 4
    ag = census[("all-gather", "all-gather")]
    assert ag.payload_bytes == 512 and ag.wire_bytes == 448.0
    rs = census[("reduce-scatter", "reduce-scatter")]
    # Payload = the full pre-scatter operand, not the 1/8 result.
    assert rs.payload_bytes == 512 and rs.wire_bytes == 448.0


def test_budget_check_positive_and_negative():
    census = comm_census(_SYNTH_HLO, default_group=8)
    good = {"t": census_to_budget(census)}
    assert check_budget("t", census, good) == []
    drifted = {"t": {"collectives": [], "wire_total": 0}}
    bad = check_budget("t", census, drifted)
    assert [f for f in bad if f.rule == "comm-budget" and f.gating]
    missing = check_budget("other", census, good)
    assert [f for f in missing if f.rule == "comm-budget"]


def test_zero1_parity_needs_reference_bytes():
    spec = TraceSpec(name="z", fn=jax.jit(lambda x: x), args=(1.0,))
    findings = check_zero1_parity(spec, [])
    assert "zero1-parity" in rules_of(findings)


def test_zero1_parity_detects_missing_exchange():
    # A step with NO declared zero1 exchange must fail parity loudly.
    spec = TraceSpec(name="z", fn=jax.jit(lambda x: x * 2.0),
                     args=(jnp.ones((8,)),), params_bytes=32)
    dp_census = [CollectiveOp(op="all-reduce", canonical="all-reduce",
                              payload_bytes=32, group_size=8)]
    findings = check_zero1_parity(spec, dp_census)
    assert "zero1-parity" in rules_of(findings)


# ------------------------------------------------------ warn baselines


def _warn(rule, path, line=1):
    return Finding(rule=rule, severity="warn", path=path, line=line,
                   message="m")


def test_baseline_ratchet_covers_and_gates():
    """Per-finding baselines (round-10 satellite): warn findings
    covered by the ledger stop gating, the EXCESS beyond a key's
    recorded count still gates, unrecorded keys gate, and errors are
    never baselineable."""
    from distkeras_tpu.analysis.findings import (apply_baseline,
                                                 baseline_key,
                                                 warn_counts)

    fs = [_warn("hot-sync", "a.py"), _warn("hot-sync", "a.py", 9),
          _warn("loop-jit", "b.py"),
          Finding(rule="jit-wallclock", severity="error", path="a.py",
                  line=2, message="m")]
    ledger = {baseline_key(fs[0]): 1, baseline_key(fs[2]): 1}
    out = apply_baseline(fs, ledger)
    # One of the two hot-sync findings is covered; the second gates.
    hot = [f for f in out if f.rule == "hot-sync"]
    assert sorted(f.baselined for f in hot) == [False, True]
    assert [f for f in out if f.rule == "hot-sync" and f.gating]
    assert not next(f for f in out if f.rule == "loop-jit").gating
    # The error is untouched and still gates.
    err = next(f for f in out if f.severity == "error")
    assert err.gating and not err.baselined
    assert "(baselined)" in next(f for f in out if f.baselined).format()
    # An empty ledger is the pre-baseline behavior: every warn gates.
    assert all(f.gating for f in apply_baseline(fs, {})
               if f.severity == "warn")
    # Census counts only unsuppressed warns (what --update records).
    counts = warn_counts(fs + [dataclasses_replace_suppressed(fs[0])])
    assert counts[baseline_key(fs[0])] == 2
    assert baseline_key(err) not in counts


def dataclasses_replace_suppressed(f):
    import dataclasses

    return dataclasses.replace(f, suppressed=True)


def test_baseline_roundtrip_and_missing_file(tmp_path):
    from distkeras_tpu.analysis.findings import (load_baseline,
                                                 save_baseline)

    path = str(tmp_path / "lint_baseline.json")
    assert load_baseline(path) == {}       # missing = empty ledger
    fs = [_warn("hot-sync", "a.py"), _warn("hot-sync", "a.py", 7)]
    counts = save_baseline(path, fs)
    assert counts == {"hot-sync:a.py": 2}
    assert load_baseline(path) == counts


def test_graph_lint_cli_update_baseline(tmp_path):
    """scripts/graph_lint.py --update-baseline writes the ledger (the
    repo is warn-clean, so it records an empty census) and the normal
    run reads it."""
    import json
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ledger = os.path.join(root, "scripts", "lint_baseline.json")
    assert os.path.exists(ledger), "ship the (possibly empty) ledger"
    with open(ledger) as fh:
        data = json.load(fh)
    assert "warn_counts" in data
    # The checked-in ledger must already be the ratchet floor: a full
    # --source-only run against it is clean (subprocess keeps this
    # hermetic; the IR half is covered by test_budget_guards).
    r = subprocess.run(
        [sys.executable, os.path.join(root, "scripts", "graph_lint.py"),
         "--source-only"], capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    # Re-recording from a half-census would drop the other layer's
    # keys: the CLI refuses the combination.
    r = subprocess.run(
        [sys.executable, os.path.join(root, "scripts", "graph_lint.py"),
         "--source-only", "--update-baseline"],
        capture_output=True, text=True, timeout=300)
    assert r.returncode != 0 and "full run" in r.stderr


# ----------------------------------------------------- repo runs clean


def test_source_lint_clean_on_repo():
    import os

    from distkeras_tpu.analysis.source_lint import lint_paths

    root = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "distkeras_tpu")
    findings = lint_paths([root])
    gating = [f.format() for f in findings if f.gating]
    assert not gating, gating
