"""Model zoo builders + driver entry points."""

import numpy as np
import pytest

from distkeras_tpu.models import zoo
from distkeras_tpu.models.adapter import ModelAdapter


def test_mnist_mlp_forward():
    m = zoo.mnist_mlp(seed=0)
    out = m(np.zeros((2, 784), np.float32))
    assert out.shape == (2, 10)


def test_cifar_cnn_forward():
    m = zoo.cifar_cnn(seed=0)
    out = m(np.zeros((2, 32, 32, 3), np.float32))
    assert out.shape == (2, 10)


def test_higgs_mlp_forward():
    m = zoo.higgs_mlp(seed=0)
    out = m(np.zeros((2, 28), np.float32))
    assert out.shape == (2, 2)


def test_imdb_lstm_forward():
    m = zoo.imdb_lstm(vocab_size=100, embed_dim=8, lstm_units=8, maxlen=16,
                      seed=0)
    out = m(np.zeros((2, 16), np.int32))
    assert out.shape == (2, 1)


def test_graft_entry_single(devices):
    import importlib.util, pathlib

    spec = importlib.util.spec_from_file_location(
        "graft_entry", pathlib.Path(__file__).parent.parent / "__graft_entry__.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    import jax

    fn, args = mod.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (4, 128, 128)  # [B, S, vocab] transformer logits


def test_graft_entry_multichip(devices):
    """The driver's 8-device dryrun, in a FRESH subprocess.

    In-process, this is the suite's single heaviest XLA-CPU compile; a
    40-minute full-suite run once segfaulted inside backend_compile at
    ~86% with exactly this test on the stack (docs/round3_notes.md)
    while the test passes standalone — accumulated backend state, not
    the program, is the trigger.  A subprocess gives the compile a
    clean backend every time (the same isolation test_deploy.py uses
    for the multi-host runtime) and makes the full suite one-command
    green."""
    import os
    import pathlib
    import subprocess
    import sys

    repo = pathlib.Path(__file__).parent.parent
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = str(repo) + os.pathsep + env.get("PYTHONPATH", "")
    child = (
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import importlib.util\n"
        f"spec = importlib.util.spec_from_file_location("
        f"'graft_entry', {str(repo / '__graft_entry__.py')!r})\n"
        "mod = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(mod)\n"
        "mod.dryrun_multichip(8)\n"
        "print('DRYRUN OK', flush=True)\n")
    out = subprocess.run([sys.executable, "-c", child], env=env,
                         capture_output=True, text=True, timeout=900)
    assert out.returncode == 0, out.stdout[-3000:] + out.stderr[-3000:]
    assert "DRYRUN OK" in out.stdout
