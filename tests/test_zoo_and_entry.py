"""Model zoo builders + driver entry points."""

import numpy as np
import pytest

from distkeras_tpu.models import zoo
from distkeras_tpu.models.adapter import ModelAdapter


def test_mnist_mlp_forward():
    m = zoo.mnist_mlp(seed=0)
    out = m(np.zeros((2, 784), np.float32))
    assert out.shape == (2, 10)


def test_cifar_cnn_forward():
    m = zoo.cifar_cnn(seed=0)
    out = m(np.zeros((2, 32, 32, 3), np.float32))
    assert out.shape == (2, 10)


def test_higgs_mlp_forward():
    m = zoo.higgs_mlp(seed=0)
    out = m(np.zeros((2, 28), np.float32))
    assert out.shape == (2, 2)


def test_imdb_lstm_forward():
    m = zoo.imdb_lstm(vocab_size=100, embed_dim=8, lstm_units=8, maxlen=16,
                      seed=0)
    out = m(np.zeros((2, 16), np.int32))
    assert out.shape == (2, 1)


def test_graft_entry_single(devices):
    import importlib.util, pathlib

    spec = importlib.util.spec_from_file_location(
        "graft_entry", pathlib.Path(__file__).parent.parent / "__graft_entry__.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    import jax

    fn, args = mod.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (4, 128, 128)  # [B, S, vocab] transformer logits


def test_graft_entry_multichip(devices):
    import importlib.util, pathlib

    spec = importlib.util.spec_from_file_location(
        "graft_entry", pathlib.Path(__file__).parent.parent / "__graft_entry__.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(8)
