import numpy as np

from distkeras_tpu import serialize_keras_model, deserialize_keras_model
from distkeras_tpu.utils.misc import to_dense_vector, uniform_weights


def test_round_trip(mlp):
    blob = serialize_keras_model(mlp)
    assert isinstance(blob["model"], str)
    m2 = deserialize_keras_model(blob)
    for a, b in zip(mlp.get_weights(), m2.get_weights()):
        np.testing.assert_array_equal(a, b)


def test_blob_is_picklable(mlp):
    import pickle

    blob = serialize_keras_model(mlp)
    m2 = deserialize_keras_model(pickle.loads(pickle.dumps(blob)))
    for a, b in zip(mlp.get_weights(), m2.get_weights()):
        np.testing.assert_array_equal(a, b)


def test_to_dense_vector():
    out = to_dense_vector(2, 4)
    np.testing.assert_array_equal(out, [0, 0, 1, 0])
    out = to_dense_vector([0, 3], 4)
    assert out.shape == (2, 4)
    assert out[1, 3] == 1.0


def test_uniform_weights(mlp):
    uniform_weights(mlp, bounds=(-0.1, 0.1), seed=0)
    for w in mlp.get_weights():
        assert w.min() >= -0.1 and w.max() <= 0.1
