import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu import serialize_keras_model, deserialize_keras_model
from distkeras_tpu.utils.misc import to_dense_vector, uniform_weights


def test_round_trip(mlp):
    blob = serialize_keras_model(mlp)
    assert isinstance(blob["model"], str)
    m2 = deserialize_keras_model(blob)
    for a, b in zip(mlp.get_weights(), m2.get_weights()):
        np.testing.assert_array_equal(a, b)


def test_blob_is_picklable(mlp):
    import pickle

    blob = serialize_keras_model(mlp)
    m2 = deserialize_keras_model(pickle.loads(pickle.dumps(blob)))
    for a, b in zip(mlp.get_weights(), m2.get_weights()):
        np.testing.assert_array_equal(a, b)


def test_to_dense_vector():
    out = to_dense_vector(2, 4)
    np.testing.assert_array_equal(out, [0, 0, 1, 0])
    out = to_dense_vector([0, 3], 4)
    assert out.shape == (2, 4)
    assert out[1, 3] == 1.0


def test_uniform_weights(mlp):
    uniform_weights(mlp, bounds=(-0.1, 0.1), seed=0)
    for w in mlp.get_weights():
        assert w.min() >= -0.1 and w.max() <= 0.1


def test_save_load_lm_round_trip(tmp_path, rng):
    import distkeras_tpu as dk
    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.models.generate import generate

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=24,
                                rope=True, n_kv_heads=1, remat=True,
                                remat_policy="dots", ce_chunks=2)
    params = tfm.init_params(jax.random.key(0), cfg)
    path = str(tmp_path / "lm.npz")
    dk.save_lm(path, params, cfg)
    loaded, cfg2 = dk.load_lm(path)
    assert cfg2 == cfg
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), params, loaded)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 5)), jnp.int32)
    # loaded leaves are host numpy by contract: hand them to a jitted
    # generate (jit places arguments), as the load_lm docstring says.
    gen = jax.jit(lambda p, pr: generate(p, pr, cfg2, 6))
    np.testing.assert_array_equal(
        np.asarray(gen(loaded, prompt)),
        np.asarray(generate(params, prompt, cfg, 6)))


def test_save_lm_rejects_quantized(tmp_path):
    import pytest

    import distkeras_tpu as dk
    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.models.quant import quantize_params

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=1, d_ff=64, max_len=16)
    qp = quantize_params(tfm.init_params(jax.random.key(0), cfg))
    with pytest.raises(ValueError, match="full-precision"):
        dk.save_lm(str(tmp_path / "q.npz"), qp, cfg)


def test_load_lm_decodes_eagerly_without_jit(tmp_path, rng):
    """load_lm's host-numpy tree must decode WITHOUT an explicit outer
    jit: generate's scan closes over the params, and a raw numpy leaf
    cannot be fancy-indexed by traced tokens (regression — the decode
    entries coerce the tree with _device_tree)."""
    import distkeras_tpu as dk
    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.models.generate import beam_search, generate

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=1, d_ff=64, max_len=24)
    params = tfm.init_params(jax.random.key(1), cfg)
    path = str(tmp_path / "lm.npz")
    dk.save_lm(path, params, cfg)
    loaded, cfg2 = dk.load_lm(path)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 4)), jnp.int32)
    want = np.asarray(generate(params, prompt, cfg, 5))
    np.testing.assert_array_equal(
        np.asarray(generate(loaded, prompt, cfg2, 5)), want)
    seqs, _ = beam_search(loaded, prompt, cfg2, 4, beam_width=2)
    assert np.asarray(seqs).shape == (2, 2, 8)
