"""Contract lint (analysis/contract_lint.py): the telemetry-schema
census, the wire-protocol cross-check, and the resource-pairing
control-flow analysis — every rule exercised positive AND negative on
toy sources, the schema round-trip, the repo-clean pin (zero findings,
zero suppressions, empty baseline), the autoscaler input-signal
contract (satellite of the same round), and the CLI mode-flag
rejections, PR-9/PR-15 parity.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from distkeras_tpu.analysis import contract_lint as cl

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCHEMA_PATH = os.path.join(ROOT, "scripts", "obs_schema.json")


def _rules(findings, only_gating=False):
    return [f.rule for f in findings if f.gating or not only_gating]


# ============================================================ telemetry census


def test_census_emits_covers_facade_registry_and_slo_emit():
    src = textwrap.dedent("""
        def tick(self):
            obs.count("serving.requests", route="enqueue")
            obs.gauge("serving.queue_depth", depth)
            obs.observe("serving.ttft_s", dt, value=dt)
            self.registry.counter("slo.breaches", "h").inc(
                metric=name, q=q, **labels)
            g = self.registry.gauge("slo.windowed", "h")
            g.set(v, metric=name, q=q)
            self._emit("slo.breach", metric=name, q=q, **labels)
    """)
    sites = {s.name: s for s in cl.census_emits(src)}
    assert sites["serving.requests"].kind == "counter"
    assert sites["serving.requests"].labels == frozenset({"route"})
    assert sites["serving.queue_depth"].kind == "gauge"
    # ``value`` is a histogram call parameter, not a label.
    assert sites["serving.ttft_s"].labels == frozenset()
    assert sites["slo.breaches"].kind == "counter"
    assert sites["slo.breaches"].labels == {"metric", "q", "*"}
    assert sites["slo.windowed"].kind == "gauge"
    assert sites["slo.breach"].kind == "event"
    assert sites["slo.breach"].labels == {"metric", "q", "*"}


def test_census_skips_dynamic_name_sites():
    src = textwrap.dedent("""
        def probe(self):
            obs.gauge(f"train.{k}", v)
            obs.observe(metric, value, lock=self.name)
    """)
    assert cl.census_emits(src) == []
    # ...which is exactly why the allowlist exists and is pinned.
    assert "train.step_s" in cl.DYNAMIC_METRICS
    assert "lock.wait_s" in cl.DYNAMIC_METRICS


def test_metric_collision_positive_and_negative():
    bad = textwrap.dedent("""
        def a(self):
            obs.count("serving.degraded")
            obs.event("serving.degraded", error=err)
    """)
    _census, findings = cl.merge_census(cl.census_emits(bad))
    assert _rules(findings) == ["metric-collision"]
    good = bad.replace('obs.event("serving.degraded"',
                       'obs.event("serving.degrade"')
    census, findings = cl.merge_census(cl.census_emits(good))
    assert findings == [] and len(census) == 2


def _schema(metrics):
    return {"metrics": metrics, "dynamic_metrics": [],
            "scenario_events": []}


def test_metric_drift_positive_and_negative():
    pinned = _schema({"serving.requests": {"kind": "counter",
                                           "labels": ["route"]}})
    # Unpinned emission, vanished producer, kind change — each drifts.
    added = _schema({**pinned["metrics"],
                     "serving.extra": {"kind": "gauge", "labels": []}})
    assert _rules(cl.check_obs_schema(added, pinned)) == ["metric-drift"]
    gone = _schema({})
    assert _rules(cl.check_obs_schema(gone, pinned)) == ["metric-drift"]
    rekind = _schema({"serving.requests": {"kind": "event",
                                           "labels": ["route"]}})
    assert _rules(cl.check_obs_schema(rekind, pinned)) == ["metric-drift"]
    # No schema recorded at all is itself a drift (bootstrap error).
    assert _rules(cl.check_obs_schema(pinned, None)) == ["metric-drift"]
    assert cl.check_obs_schema(pinned, pinned) == []


def test_label_drift_positive_and_negative():
    pinned = _schema({"router.replica_load": {"kind": "gauge",
                                              "labels": ["replica"]}})
    drifted = _schema({"router.replica_load": {"kind": "gauge",
                                               "labels": ["shard"]}})
    assert _rules(cl.check_obs_schema(drifted, pinned)) == ["label-drift"]
    assert cl.check_obs_schema(pinned, pinned) == []


def test_dynamic_and_scenario_sections_drift():
    pinned = _schema({})
    drifted = dict(pinned, dynamic_metrics=["train.step_s"])
    assert _rules(cl.check_obs_schema(drifted, pinned)) == ["metric-drift"]


def test_schema_round_trip(tmp_path):
    schema = cl.build_obs_schema(ROOT)
    p = str(tmp_path / "obs_schema.json")
    cl.save_obs_schema(p, schema)
    loaded = cl.load_obs_schema(p)
    assert loaded == schema  # comment stripped, sets already sorted
    assert cl.check_obs_schema(schema, loaded) == []
    # The on-disk form carries the provenance comment.
    assert "comment" in json.load(open(p))


# ------------------------------------------------------- consumer references


def test_consumer_refs_positive_and_noise_filtered():
    src = textwrap.dedent("""
        def report(events):
            for e in events:
                if e["name"] == "serving.nope":
                    yield e
                if e.get("name").startswith("router."):
                    yield e
            rule = SloRule("serving.ttft_s", q=0.99)
            keys = ("serving.requests", "serving.queue_depth")
            plan = [("cluster.push", 5, "fail")]    # fault site, not a ref
            path = "runs/serving.jsonl"             # filename, not a ref
    """)
    refs = cl.consumer_refs(src, "toy.py", vocab={"serving", "router",
                                                  "cluster"})
    names = {(n, m) for n, _ln, m in refs}
    assert ("serving.nope", "exact") in names
    assert ("router.", "prefix") in names
    assert ("serving.ttft_s", "exact") in names
    assert ("serving.requests", "exact") in names
    # Mixed tuples (chaos fault plans) and filenames stay out.
    assert not any(n == "cluster.push" for n, _m in names)
    assert not any(n.endswith(".jsonl") for n, _m in names)


def test_documented_names_strips_label_suffixes():
    doc = "| serving | `serving.requests{route}`, `slo.breach` | - |"
    names = cl.documented_names(doc)
    assert {"serving.requests", "slo.breach"} <= names


# ================================================================ wire census


SERVER_SRC = textwrap.dedent("""
    class Handler:
        def do_GET(self):
            url = urlparse(self.path)
            if url.path == "/healthz":
                self._send(200 if self.up else 503, body)
            elif url.path == "/poll":
                q = parse_qs(url.query)
                rid = q.get("id")
                if rid is None:
                    self._send(404, err)
                else:
                    self._send(200, out)

        def do_POST(self):
            routes = {"/enqueue": self._post_enqueue}

        def _post_enqueue(self):
            if full:
                self._send(429, err)
            self._send(200, out)
""")

CLIENT_SRC = textwrap.dedent("""
    class Replica:
        def health(self):
            body, code = self._get("/healthz")
            return code == 200

        def poll(self, rid):
            body, code = self._get(f"/poll?id={rid}")
            if code == 404:
                return None
            return body

        def submit(self, payload):
            body, code = self._post("/enqueue", payload)
            if code == 429:
                raise Busy()
            return body
""")


def _toy_wire(client_src=CLIENT_SRC):
    servers = {"engine": cl.server_routes(SERVER_SRC, "srv.py")}
    clients = {"engine": {}}
    for c in cl.client_calls(client_src, "cli.py"):
        ent = clients["engine"].setdefault(
            c["route"], {"params": set(), "expects": set(), "sites": []})
        ent["params"] |= c["params"]
        ent["expects"] |= c["expects"]
        ent["sites"].append(("cli.py", c["line"]))
    return servers, clients


def test_wire_census_extracts_routes_params_statuses():
    servers, clients = _toy_wire()
    srv = servers["engine"]
    assert srv["GET /healthz"]["status"] == {200, 503}
    assert srv["GET /poll"] == {"params": {"id"}, "status": {200, 404}}
    assert srv["POST /enqueue"]["status"] == {200, 429}
    assert clients["engine"]["GET /poll"]["params"] == {"id"}
    assert clients["engine"]["GET /poll"]["expects"] == {404}
    assert clients["engine"]["POST /enqueue"]["expects"] == {429}


def test_route_drift_positive_and_negative():
    servers, clients = _toy_wire()
    pinned = cl._wire_doc(servers, clients)
    assert cl.check_wire(servers, clients, pinned, "s.json") == []
    # Orphan client route: nothing serves /nope.
    orphan = CLIENT_SRC + textwrap.dedent("""
        def probe(self):
            body, code = self._get("/nope")
    """)
    servers, clients = _toy_wire(orphan)
    fs = cl.check_wire(servers, clients,
                       cl._wire_doc(servers, clients), "s.json")
    assert _rules(fs) == ["route-drift"]
    assert "/nope" in fs[0].message


def test_route_param_drift_positive():
    noisy = CLIENT_SRC.replace("/poll?id={rid}",
                               "/poll?verbose=1&id={rid}")
    servers, clients = _toy_wire(noisy)
    fs = cl.check_wire(servers, clients,
                       cl._wire_doc(servers, clients), "s.json")
    assert _rules(fs) == ["route-drift"]
    assert "'verbose'" in fs[0].message


def test_status_drift_positive_and_negative():
    dead = CLIENT_SRC.replace("if code == 429:", "if code == 418:")
    servers, clients = _toy_wire(dead)
    fs = cl.check_wire(servers, clients,
                       cl._wire_doc(servers, clients), "s.json")
    assert _rules(fs) == ["status-drift"]
    assert fs[0].severity == "warn" and "418" in fs[0].message


def test_served_route_without_client_or_operator_flag():
    # Drop the /enqueue client: the POST route is now served-but-dead.
    lone = CLIENT_SRC.replace('self._post("/enqueue", payload)',
                              'self._post("/other", payload)')
    servers, clients = _toy_wire(lone)
    fs = cl.check_wire(servers, clients,
                       cl._wire_doc(servers, clients), "s.json")
    assert "route-drift" in _rules(fs)
    assert any("/enqueue" in f.message and "no in-repo client"
               in f.message for f in fs)


def test_pinned_schema_wire_drift():
    servers, clients = _toy_wire()
    pinned = cl._wire_doc(servers, clients)
    stale = json.loads(json.dumps(pinned))
    stale["engine"]["GET /poll"]["status"] = [200]
    fs = cl.check_wire(servers, clients, stale, "s.json")
    assert _rules(fs) == ["route-drift"]
    assert "pinned" in fs[0].message


# ============================================================ resource pairing


def _leaks(src):
    return [f for f in cl.lint_resource_source(textwrap.dedent(src))
            if not f.suppressed]


def test_unbalanced_resource_exception_edge_positive_and_negative():
    leaky = """
        def grow(self):
            bid = self._alloc.alloc()
            self.cache = self._copy_block(self.cache, bid)
            self._alloc.free(bid)
    """
    fs = _leaks(leaky)
    assert _rules(fs) == ["unbalanced-resource"]
    assert "_copy_block" in fs[0].message
    fixed = """
        def grow(self):
            bid = self._alloc.alloc()
            try:
                self.cache = self._copy_block(self.cache, bid)
            except Exception:
                self._alloc.free(bid)
                raise
            self.slots.append(bid)
    """
    assert _leaks(fixed) == []


def test_unbalanced_resource_try_finally_discharges():
    src = """
        def export(self):
            h = self.pool.acquire()
            try:
                self._ship(h)
                if short:
                    return None
            finally:
                self.pool.release(h)
    """
    assert _leaks(src) == []


def test_unbalanced_resource_handler_rollback_still_needs_normal_release():
    src = """
        def grow(self):
            bid = self._alloc.alloc()
            try:
                self.cache = self._copy_block(self.cache, bid)
            except Exception:
                self._alloc.free(bid)
                raise
    """
    fs = _leaks(src)
    assert _rules(fs) == ["unbalanced-resource"]
    assert "never released" in fs[0].message


def test_unbalanced_resource_discarded_acquire():
    fs = _leaks("""
        def warm(self):
            self._alloc.alloc()
    """)
    assert _rules(fs) == ["unbalanced-resource"]
    assert "discarded" in fs[0].message


def test_unbalanced_resource_vacuous_none_branch():
    src = """
        def take(self):
            bid = self._alloc.alloc()
            if bid is None:
                return None
            self.blocks.append(bid)
    """
    assert _leaks(src) == []
    # ...but falling off the function still holding is a leak.
    assert _rules(_leaks(src.replace("self.blocks.append(bid)",
                                     "pass"))) == ["unbalanced-resource"]


def test_unbalanced_resource_ownership_transfer_forms():
    src = """
        def lease(self):
            h = self.pool.acquire()
            return h

        def stage(self):
            bid = self._alloc.alloc()
            self._staged[rid] = bid

        def reply(self, endpoint):
            pid = self.engine.pin_prefix(tokens)
            self._send(200, pid)
    """
    assert _leaks(src) == []


def test_unbalanced_resource_overwrite_before_release():
    fs = _leaks("""
        def twice(self):
            bid = self._alloc.alloc()
            bid = self._alloc.alloc()
            self._alloc.free(bid)
    """)
    assert _rules(fs) == ["unbalanced-resource"]
    assert "overwritten" in fs[0].message


def test_unbalanced_resource_suppression_comment_honoured():
    src = """
        def warm(self):
            bid = self._alloc.alloc()  # dkt: ignore[unbalanced-resource]
    """
    fs = cl.lint_resource_source(textwrap.dedent(src))
    assert len(fs) == 1 and fs[0].suppressed and not fs[0].gating


# =============================================================== repo-level pin


def test_contract_lint_clean_on_repo():
    """The gate the PR ships green: the WHOLE repo's contracts are
    clean against the pinned schema with zero findings — not zero
    gating findings, zero findings: no suppressions, nothing
    baselined."""
    findings = cl.lint_repo_contracts(ROOT, schema_path=SCHEMA_PATH)
    assert findings == [], [f.format() for f in findings]
    # The undocumented-metric baseline is EMPTY: every censused name
    # is documented, so the warn ledger carries no contract debt.
    ledger = json.load(open(
        os.path.join(ROOT, "scripts", "lint_baseline.json")))
    contract_rules = ("metric-", "label-", "dangling-", "undocumented-",
                      "route-", "status-", "unbalanced-")
    debt = [k for k in ledger.get("warn_counts", {})
            if k.startswith(contract_rules)]
    assert debt == [], debt


def test_consumer_files_and_wire_files_exist():
    """The configured census surfaces are real files — a moved consumer
    or server module must update contract_lint's config, not silently
    shrink the census."""
    for rel in (list(cl.CONSUMER_FILES) + list(cl.WIRE_SERVER_FILES)
                + list(cl.WIRE_CLIENT_FILES) + [cl.DOC_FILE]):
        assert os.path.exists(os.path.join(ROOT, rel)), rel


# ============================================== autoscaler input contract


def test_autoscaler_input_signals_pinned():
    """The producer<->consumer agreement the upcoming autoscaler closes
    its loop on, pinned via the schema: the SLO breach event shape, the
    queue/load gauges, and every default SLO metric resolving to a live
    producer."""
    schema = cl.load_obs_schema(SCHEMA_PATH)
    m = schema["metrics"]
    assert m["slo.breach"] == {
        "kind": "event",
        "labels": ["*", "metric", "q", "threshold", "value", "window_s"]}
    assert m["slo.breaches"] == {"kind": "counter",
                                 "labels": ["*", "metric", "q"]}
    assert m["slo.windowed"] == {"kind": "gauge",
                                 "labels": ["metric", "q"]}
    assert m["serving.queue_depth"]["kind"] == "gauge"
    assert m["router.replica_load"] == {"kind": "gauge",
                                        "labels": ["replica"]}
    assert m["serving.kv_blocks_free"]["kind"] == "gauge"
    from distkeras_tpu.obs.slo import DEFAULT_SLO_METRICS
    for name in DEFAULT_SLO_METRICS:
        assert name in m or name in schema["dynamic_metrics"], name


def test_residency_digest_fields_match_router_reader():
    """The residency digest the cache-aware router builds its affinity
    table from: PagedBatcher.residency() publishes the fields, and the
    router reads them under the SAME keys — checked statically so a
    renamed field fails here, not in a fleet."""
    import ast

    src = open(os.path.join(
        ROOT, "distkeras_tpu", "serving", "paged.py")).read()
    keys = set()
    for node in ast.walk(ast.parse(src)):
        if (isinstance(node, ast.FunctionDef)
                and node.name == "residency"):
            for n in ast.walk(node):
                if (isinstance(n, ast.Assign) and len(n.targets) == 1
                        and isinstance(n.targets[0], ast.Subscript)
                        and isinstance(n.targets[0].slice, ast.Constant)):
                    keys.add(n.targets[0].slice.value)
    assert {"block", "stem_hashes", "prefix_ids",
            "kv_blocks_free"} <= keys, keys
    router = open(os.path.join(
        ROOT, "distkeras_tpu", "serving", "router.py")).read()
    reads = set()
    for node in ast.walk(ast.parse(router)):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get" and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            reads.add(node.args[0].value)
    assert {"stem_hashes", "prefix_ids"} <= reads
    # ...and the engine wire family serves the digest route.
    schema = cl.load_obs_schema(SCHEMA_PATH)
    assert "GET /residency" in schema["wire"]["engine"]


# ================================================================ CLI parity


@pytest.mark.parametrize("argv,needle", [
    (["--contracts", "--source-only"], "cannot combine"),
    (["--contracts", "--ir-only"], "cannot combine"),
    (["--contracts", "--threads"], "cannot combine"),
    (["--contracts", "--shardings"], "cannot combine"),
    (["--contracts", "--update-baseline"], "full run"),
])
def test_graph_lint_cli_rejects_contracts_combos(argv, needle):
    """PR-9/PR-15 parity: conflicting mode combos exit at argparse,
    before the heavy jax import is paid."""
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "graph_lint.py")]
        + argv, capture_output=True, text=True, timeout=120, cwd=ROOT)
    assert r.returncode != 0 and needle in r.stderr, r.stderr


def test_graph_lint_cli_contracts_runs_clean():
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "graph_lint.py"),
         "--contracts"],
        capture_output=True, text=True, timeout=120, cwd=ROOT)
    assert r.returncode == 0, r.stdout + "\n" + r.stderr
    assert "0 finding(s)" in r.stdout
