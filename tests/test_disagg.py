"""Round-17 disaggregated prefill/decode: block shipping + streaming.

Codec half (jax-free): the :mod:`serving.disagg` wire format round-
trips bit-exactly (int8 leaves included) and refuses anything torn.
Engine half: ``export_blocks``/``import_blocks`` adopt by page-table
splice with allocator refcounts — warm blocks hash-hit with zero
device writes, backpressure rolls back every reference.  Fleet half:
a role-split Router serves BIT-EXACT tokens vs solo (greedy AND
seeded, chunked-prefill and kv_int8 variants), never decodes on the
prefill replica, skips transfers for warm stems, falls back on hop
failure without a caller-visible error, streams the first token long
before the terminal result, and renders the cross-replica hop in the
``--request`` waterfall.
"""

import time

import numpy as np
import pytest

from distkeras_tpu import obs
from distkeras_tpu.obs.report import request_waterfall
from distkeras_tpu.obs.trace import read_trace
from distkeras_tpu.serving import (EngineEndpoint, HttpReplica,
                                   InProcessReplica, PagedBatcher,
                                   Router)
from distkeras_tpu.serving.disagg import (BlockShipment,
                                          decode_shipment,
                                          encode_shipment)

CFG_KW = dict(vocab_size=64, d_model=32, n_heads=2, n_layers=2,
              d_ff=64, max_len=32, rope=True)
BLOCK = 8


@pytest.fixture(scope="module")
def engine_params():
    import jax

    from distkeras_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(**CFG_KW)
    return tfm.init_params(jax.random.key(0), cfg), cfg


def _paged(params, cfg, **kw):
    kw.setdefault("prompt_buckets", (8,))
    kw.setdefault("max_queue", 8)
    kw.setdefault("n_blocks", 16)
    return PagedBatcher(params, cfg, lanes=2, block=BLOCK, **kw)


def _cold(rng, blocks=2, tail=1):
    return rng.integers(0, 64, (blocks * BLOCK + tail,)) \
        .astype(np.int32)


def _run(router, rids):
    deadline = time.monotonic() + 120.0
    while any(router.poll(r) is None for r in rids):
        router.step()
        assert time.monotonic() < deadline
    return [router.take(r) for r in rids]


def _count(sess, name):
    doc = sess.registry.snapshot().get(name)
    if not doc:
        return 0.0
    return sum(s["value"] for s in doc["series"])


# ----------------------------------------------------------- the codec


def _toy_shipment(n=2):
    rng = np.random.default_rng(7)
    blocks, hashes = [], []
    for k in range(n):
        blocks.append((
            rng.normal(size=(2, 1, BLOCK, 2, 4)).astype(np.float32),
            rng.integers(-127, 128, (2, 1, BLOCK, 2, 4))
               .astype(np.int8),
            rng.normal(size=(2, 1, BLOCK, 2, 1)).astype(np.float32)))
        hashes.append(bytes([k]) * 16)
    return BlockShipment(block=BLOCK, hashes=tuple(hashes),
                         blocks=tuple(blocks))


def test_codec_roundtrip_bit_exact_including_int8():
    ship = _toy_shipment()
    back = decode_shipment(encode_shipment(ship))
    assert back.block == ship.block
    assert back.hashes == ship.hashes
    assert back.span == 2 * BLOCK and len(back) == 2
    assert back.nbytes == ship.nbytes
    for got, want in zip(back.blocks, ship.blocks):
        for g, w in zip(got, want):
            assert g.dtype == w.dtype
            np.testing.assert_array_equal(g, w)


def test_codec_rejects_malformed():
    ship = _toy_shipment()
    data = encode_shipment(ship)
    with pytest.raises(ValueError, match="truncated"):
        decode_shipment(data[:3])
    with pytest.raises(ValueError, match="truncated"):
        decode_shipment(data[:40])
    with pytest.raises(ValueError, match="magic"):
        decode_shipment(data.replace(b"dkt-blocks", b"dkt-bogus!"))
    with pytest.raises(ValueError, match="payload"):
        decode_shipment(data[:-8])
    with pytest.raises(ValueError, match="empty"):
        encode_shipment(BlockShipment(block=BLOCK, hashes=(),
                                      blocks=()))
    with pytest.raises(ValueError, match="digests"):
        BlockShipment(block=BLOCK, hashes=(b"x",), blocks=())
    ragged = BlockShipment(
        block=BLOCK, hashes=ship.hashes,
        blocks=(ship.blocks[0], ship.blocks[1][:2]))
    with pytest.raises(ValueError, match="ragged"):
        encode_shipment(ragged)


# ------------------------------------------------- export/import/adopt


def test_export_import_refcounts_and_admission_hit(engine_params,
                                                   rng):
    from distkeras_tpu.models.generate import generate

    params, cfg = engine_params
    src, dst = _paged(params, cfg), _paged(params, cfg)
    prompt = _cold(rng, blocks=2, tail=1)
    ship = src.export_blocks(prompt)
    assert len(ship) == 2 and ship.block == BLOCK
    assert ship.span == 16 and ship.nbytes > 0
    # The wire format carries the engine's real leaves bit-exactly.
    back = decode_shipment(encode_shipment(ship))
    for got, want in zip(back.blocks, ship.blocks):
        for g, w in zip(got, want):
            np.testing.assert_array_equal(g, w)

    base_used = dst.allocator.stats()["used"]
    imported = dst.import_blocks(ship)
    assert imported["blocks"] == 2 and imported["hits"] == 0
    assert dst.allocator.stats()["used"] == base_used + 2
    assert set(ship.hexes()) <= set(dst.residency()["stem_hashes"])
    # Re-import is pure refcounting: content already resident.
    again = dst.import_blocks(ship)
    assert again["hits"] == 2
    assert dst.allocator.stats()["used"] == base_used + 2

    # Admission hash-hits the adopted run: zero re-prefill, tokens
    # bit-exact vs solo.
    rid = dst.enqueue(prompt, 5)
    while dst.poll(rid) is None:
        dst.step()
    res = dst.take(rid)
    assert dst.stem_hit_blocks >= 2
    solo = np.asarray(generate(params, prompt[None], cfg, 5))[0]
    np.testing.assert_array_equal(res.tokens, solo)

    dst.unpin_prefix(imported["prefix_id"])
    dst.unpin_prefix(again["prefix_id"])
    assert dst.allocator.stats()["used"] == base_used


def test_import_backpressure_rolls_back_every_reference(
        engine_params, rng):
    params, cfg = engine_params
    src = _paged(params, cfg)
    small = _paged(params, cfg, n_blocks=9)      # capacity 8
    for _ in range(2):                           # pin 6 of 8 blocks
        small.pin_prefix(rng.integers(0, 64, (24,)).astype(np.int32))
    used = small.allocator.stats()["used"]
    assert used == 6
    ship = src.export_blocks(_cold(rng, blocks=3, tail=0))
    assert small.import_blocks(ship) is None     # 3 > 2 free
    assert small.allocator.stats()["used"] == used  # nothing leaked

    with pytest.raises(ValueError, match="paged at"):
        small.import_blocks(BlockShipment(
            block=4, hashes=ship.hashes, blocks=ship.blocks))
    with pytest.raises(ValueError, match="empty"):
        small.import_blocks(BlockShipment(block=BLOCK, hashes=(),
                                          blocks=()))


# --------------------------------------------------- the 2-stage fleet


class _NoDecode(InProcessReplica):
    """A prefill replica that fails the test if the router ever
    routes a DECODE request to it — role exclusivity."""

    def enqueue(self, *a, **kw):
        raise AssertionError(
            "decode request admitted on the prefill replica")


def _fleet(params, cfg, prefill_cls=InProcessReplica, **kw):
    pre, dec = _paged(params, cfg, **kw), _paged(params, cfg, **kw)
    router = Router([prefill_cls("pre", pre, role="prefill"),
                     InProcessReplica("dec", dec, role="decode")])
    router.refresh_residency()    # the planner reads `block` off it
    return router, pre, dec


def test_disagg_parity_greedy_and_role_exclusivity(engine_params,
                                                   rng):
    from distkeras_tpu.models.generate import generate

    params, cfg = engine_params
    router, pre, dec = _fleet(params, cfg, prefill_cls=_NoDecode)
    prompts = [_cold(rng, blocks=2, tail=t) for t in (1, 2, 3)]
    with obs.session() as sess:
        rids = [router.enqueue(p, 5) for p in prompts]
        results = _run(router, rids)
        assert _count(sess, "router.disagg_requests") == 3
        assert _count(sess, "router.disagg_fallbacks") == 0
    for res, p in zip(results, prompts):
        solo = np.asarray(generate(params, p[None], cfg, 5))[0]
        np.testing.assert_array_equal(res.tokens, solo)
    # Each adopted run hash-hit at admission on the decode side.
    assert dec.stem_hit_blocks >= 6
    # Import pins were handed back at terminal: the decode slab
    # drains to empty (no pins, no lanes).
    router.pump()
    assert dec.residency()["prefix_ids"] == []
    assert dec.allocator.stats()["used"] == 0


def test_disagg_parity_seeded_sampling(engine_params, rng):
    import jax

    from distkeras_tpu.models.generate import generate

    params, cfg = engine_params
    kw = dict(temperature=0.7, top_k=16)
    router, _pre, _dec = _fleet(params, cfg, **kw)
    prompts = [_cold(rng, blocks=2, tail=t) for t in (1, 2)]
    keys = [jax.random.key(11), jax.random.key(12)]
    rids = [router.enqueue(p, 5, key=k)
            for p, k in zip(prompts, keys)]
    for res, p, k in zip(_run(router, rids), prompts, keys):
        solo = np.asarray(
            generate(params, p[None], cfg, 5, key=k, **kw))[0]
        np.testing.assert_array_equal(res.tokens, solo)


def test_disagg_parity_chunked_prefill(engine_params, rng):
    from distkeras_tpu.models.generate import generate

    params, cfg = engine_params
    router, _pre, _dec = _fleet(params, cfg, prefill_chunk=8)
    prompt = _cold(rng, blocks=2, tail=2)
    with obs.session() as sess:
        (res,) = _run(router, [router.enqueue(prompt, 5)])
        assert _count(sess, "router.disagg_requests") == 1
    solo = np.asarray(generate(params, prompt[None], cfg, 5))[0]
    np.testing.assert_array_equal(res.tokens, solo)


def test_disagg_parity_kv_int8(engine_params, rng):
    """int8 blocks ride the wire as-is: a disaggregated kv_int8
    request matches the SAME-config solo engine bit-exactly (int8
    decode is its own numeric contract, so the reference is the solo
    engine, not f32 generate)."""
    params, cfg = engine_params
    prompt = _cold(rng, blocks=2, tail=1)
    solo_eng = _paged(params, cfg, kv_int8=True)
    lane = solo_eng.enqueue(prompt, 5)
    while solo_eng.poll(lane) is None:
        solo_eng.step()
    ref = solo_eng.take(lane).tokens

    router, _pre, _dec = _fleet(params, cfg, kv_int8=True)
    with obs.session() as sess:
        (res,) = _run(router, [router.enqueue(prompt, 5)])
        assert _count(sess, "router.disagg_requests") == 1
    np.testing.assert_array_equal(res.tokens, ref)


def test_warm_stems_skip_the_transfer(engine_params, rng):
    from distkeras_tpu.models.generate import generate

    params, cfg = engine_params
    router, _pre, _dec = _fleet(params, cfg)
    head = _cold(rng, blocks=2, tail=0)
    with obs.session() as sess:
        (r1,) = _run(router, [router.enqueue(
            np.concatenate([head, head[:1]]), 5)])
        assert _count(sess, "router.disagg_requests") == 1
        # Same full blocks, different tail: every stem is now
        # resident on the decode replica — the hop is pure waste.
        p2 = np.concatenate([head, head[1:2]])
        (r2,) = _run(router, [router.enqueue(p2, 5)])
        assert _count(sess, "router.disagg_requests") == 1
        assert _count(sess, "router.disagg_warm_skips") >= 1
    solo = np.asarray(generate(params, p2[None], cfg, 5))[0]
    np.testing.assert_array_equal(r2.tokens, solo)


def test_prefill_failure_falls_back_never_errors(engine_params, rng,
                                                 monkeypatch):
    from distkeras_tpu.models.generate import generate

    params, cfg = engine_params
    router, pre, _dec = _fleet(params, cfg)

    def boom(tokens):
        raise RuntimeError("prefill replica died mid-build")

    monkeypatch.setattr(pre, "export_blocks", boom)
    prompt = _cold(rng, blocks=2, tail=1)
    with obs.session() as sess:
        (res,) = _run(router, [router.enqueue(prompt, 5)])
        assert _count(sess, "router.disagg_fallbacks") == 1
        assert _count(sess, "router.disagg_requests") == 0
    assert res.ok
    solo = np.asarray(generate(params, prompt[None], cfg, 5))[0]
    np.testing.assert_array_equal(res.tokens, solo)


# ------------------------------------------------------------ streaming


def test_stream_first_token_before_terminal(engine_params, rng):
    from distkeras_tpu.models.generate import generate

    params, cfg = engine_params
    eng = _paged(params, cfg)
    router = Router([InProcessReplica("r0", eng)])
    prompt = rng.integers(0, 64, (6,)).astype(np.int32)
    rid = router.enqueue(prompt, 8)
    gen = router.stream(rid)
    first = next(gen)
    # The whole point: a token in hand while the request decodes.
    assert router.poll(rid) is None
    tokens = [first] + list(gen)
    res = router.take(rid)
    assert res.ok and tokens == list(res.generated)
    solo = np.asarray(generate(params, prompt[None], cfg, 8))[0]
    np.testing.assert_array_equal(res.tokens, solo)


def test_stream_across_the_disagg_hop(engine_params, rng):
    from distkeras_tpu.models.generate import generate

    params, cfg = engine_params
    router, _pre, _dec = _fleet(params, cfg)
    prompt = _cold(rng, blocks=2, tail=1)
    with obs.session() as sess:
        rid = router.enqueue(prompt, 6)
        assert _count(sess, "router.disagg_requests") == 1
        tokens = list(router.stream(rid))
    solo = np.asarray(generate(params, prompt[None], cfg, 6))[0]
    assert tokens == list(solo[prompt.size:])
    assert router.take(rid).ok


def test_waterfall_renders_the_block_transfer_hop(engine_params, rng,
                                                  tmp_path):
    params, cfg = engine_params
    trace = str(tmp_path / "disagg.jsonl")
    router, _pre, _dec = _fleet(params, cfg)
    prompt = _cold(rng, blocks=2, tail=1)
    with obs.session(trace_path=trace):
        rid = router.enqueue(prompt, 5)
        res = router.drain(rid)
        assert res.ok
    wf = request_waterfall(read_trace(trace), rid)
    assert wf["found"] and wf["status"] == "ok"
    names = [s["name"] for s in wf["stages"]]
    assert "router.prefill" in names
    assert "router.block_transfer" in names
    assert "serving.finish" in names
    hop = next(s for s in wf["stages"]
               if s["name"] == "router.block_transfer")
    assert hop["src"] == "pre" and hop["dst"] == "dec"
    assert hop["blocks"] == 2 and hop["bytes"] > 0


# ------------------------------------------------------- the endpoints


def test_endpoint_disagg_routes_and_discovery(engine_params, rng,
                                              tmp_path):
    params, cfg = engine_params
    pre_eng, dec_eng = _paged(params, cfg), _paged(params, cfg)
    pre_ep = EngineEndpoint(pre_eng, host_id=0, role="prefill",
                            coord_dir=str(tmp_path))
    dec_ep = EngineEndpoint(dec_eng, host_id=1, role="decode",
                            coord_dir=str(tmp_path))
    pre_ep.start(step=True)
    dec_ep.start(step=True)
    try:
        from distkeras_tpu.serving import discover_replicas

        found = {r.name: r for r in discover_replicas(str(tmp_path))}
        assert found["host0"].role == "prefill"
        assert found["host1"].role == "decode"

        pre = HttpReplica("pre", pre_ep.addr, role="prefill")
        dec = HttpReplica("dec", dec_ep.addr, role="decode")
        prompt = _cold(rng, blocks=2, tail=1)
        # The raw transfer surface: POST /prefill -> shipment,
        # POST /blocks -> adoption dict, POST /unpin releases.
        ship = pre.prefill_blocks(prompt)
        assert len(ship) == 2 and ship.block == BLOCK
        imported = dec.import_blocks(ship)
        assert imported["blocks"] == 2 and imported["hits"] == 0
        dec.unpin(int(imported["prefix_id"]))
        # GET /stream: 404 for unknown ids maps to None.
        assert dec.partial(123456789) is None

        router = Router([pre, dec], health_interval=0.0)
        router.refresh_residency()
        rid = router.enqueue(prompt, 5)
        deadline = time.monotonic() + 60.0
        while router.poll(rid) is None:
            router.pump()
            assert time.monotonic() < deadline
            time.sleep(0.01)
        res = router.take(rid)
        assert res.ok and len(res.generated) == 5
        # The hop landed the decode on the decode endpoint, warm.
        assert dec_eng.stem_hit_blocks >= 2
        assert pre_eng.stem_hit_blocks == 0 or not pre_eng.running()
    finally:
        pre_ep.stop()
        dec_ep.stop()
