"""FusedLSTM: weight-compatible TPU restructuring of keras.layers.LSTM.

Contract: identical parameterization and numerics to the stock layer
(set_weights interchange, f32 tolerance match), so the zoo's IMDB
config can swap it in without changing the model.
"""

import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.models.rnn import FusedLSTM


def _pair(units=12, seq=16, feat=8, return_sequences=False, rng=None):
    import keras

    x = (rng or np.random.default_rng(0)).normal(
        size=(4, seq, feat)).astype(np.float32)
    ref = keras.layers.LSTM(units, return_sequences=return_sequences)
    fused = FusedLSTM(units, return_sequences=return_sequences)
    r = ref(x)
    f = fused(x)  # builds
    fused.set_weights(ref.get_weights())
    return ref, fused, x, np.asarray(r)


def test_matches_keras_last_state(rng):
    _, fused, x, ref_out = _pair(rng=rng)
    np.testing.assert_allclose(np.asarray(fused(x)), ref_out,
                               atol=1e-5, rtol=1e-5)


def test_matches_keras_sequences(rng):
    _, fused, x, ref_out = _pair(return_sequences=True, rng=rng)
    out = np.asarray(fused(x))
    assert out.shape == ref_out.shape == (4, 16, 12)
    np.testing.assert_allclose(out, ref_out, atol=1e-5, rtol=1e-5)


def test_weights_interchange_both_ways(rng):
    import keras

    ref, fused, x, _ = _pair(rng=rng)
    # fused -> stock: the layout really is identical, not just same-shaped.
    ref.set_weights(fused.get_weights())
    np.testing.assert_allclose(np.asarray(ref(x)), np.asarray(fused(x)),
                               atol=1e-5, rtol=1e-5)


def test_serialization_round_trip(rng):
    from distkeras_tpu.models.zoo import imdb_lstm

    model = imdb_lstm(vocab_size=64, embed_dim=8, lstm_units=8, maxlen=12,
                      seed=0)
    blob = dk.serialize_keras_model(model)
    clone = dk.deserialize_keras_model(blob)
    x = rng.integers(0, 64, (4, 12)).astype(np.int32)
    np.testing.assert_allclose(np.asarray(model(x)), np.asarray(clone(x)),
                               atol=1e-6)


def test_trains_under_single_trainer(rng):
    from distkeras_tpu.models.zoo import imdb_lstm

    # Learnable toy rule: label = (first token < vocab/2).
    vocab = 64
    x = rng.integers(0, vocab, (256, 12)).astype(np.int32)
    y = (x[:, 0] < vocab // 2).astype(np.int64)
    model = imdb_lstm(vocab_size=vocab, embed_dim=16, lstm_units=16,
                      maxlen=12, seed=0)
    tr = dk.SingleTrainer(model, loss="binary_crossentropy",
                          worker_optimizer="adam", learning_rate=1e-2,
                          batch_size=32, num_epoch=8)
    tr.train(dk.Dataset.from_arrays(x, y))
    assert tr.history[-1] < tr.history[0] * 0.5, tr.history[::16]


def test_validation():
    with pytest.raises(ValueError, match="units"):
        FusedLSTM(0)
    with pytest.raises(ValueError, match="batch, time, features"):
        FusedLSTM(4)(np.zeros((2, 8), np.float32))
