"""KV-cached decoding: must reproduce the training-path forward exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.models import transformer as tfm
from distkeras_tpu.models.generate import _decode_step, generate, init_cache


CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=16)
MOE_CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=1, d_ff=64, max_len=16,
                                num_experts=4, capacity_factor=1.25)
MOE2_CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                 n_layers=1, d_ff=64, max_len=16,
                                 num_experts=4, moe_top_k=2,
                                 capacity_factor=1.25)
ROPE_CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                 n_layers=2, d_ff=64, max_len=16, rope=True)


@pytest.mark.parametrize("cfg", [CFG, MOE_CFG, MOE2_CFG, ROPE_CFG],
                         ids=["dense", "moe", "moe2", "rope"])
def test_cached_decode_matches_full_forward(rng, cfg):
    """Teacher-forcing through the cache == apply() at every position.

    The MoE case pins parity at a REALISTIC capacity factor (1.25):
    the batched forward scores with ``moe_dense_routing=True`` — the
    decode semantics — so nothing depends on capacity being large
    enough to never drop (a no-op flag for the dense/rope configs).
    """
    params = tfm.init_params(jax.random.key(0), cfg)
    toks = jnp.asarray(rng.integers(0, 64, (2, 12)).astype(np.int32))
    full_logits, _ = tfm.apply(params, toks, cfg,
                               moe_dense_routing=bool(cfg.num_experts))

    cache = init_cache(cfg, 2)
    for pos in range(12):
        logits, cache = _decode_step(params, cache, toks[:, pos], pos, cfg)
        np.testing.assert_allclose(logits, full_logits[:, pos], atol=2e-4,
                                   rtol=2e-4)


def test_moe_capacity_vs_dense_divergence_bounded(rng):
    """Quantified train/serve routing contract on a TRAINED MoE.

    Trains briefly at capacity_factor=1.25 (tokens really drop), then
    measures the capacity-routing vs dense-routing eval NLL gap.  The
    served model (decode == dense routing by the parity test above)
    must track the training-time forward within a modest bound — this
    is the measured form of the divergence caveat in ``generate``'s
    docstring, asserted so a regression in either routing path shows
    up as a blown bound rather than silent quality drift.
    """
    import optax

    cfg = MOE_CFG
    params = tfm.init_params(jax.random.key(3), cfg)
    opt = optax.adam(3e-3)
    step = jax.jit(tfm.make_train_step(cfg, opt))
    carry = (params, opt.init(params))
    toks = jnp.asarray(rng.integers(0, 64, (8, 13)).astype(np.int32))
    for _ in range(30):
        carry, _ = step(carry, toks)
    trained = carry[0]

    nll_cap = float(tfm.lm_nll(trained, toks, cfg))
    nll_dense = float(tfm.lm_nll(trained, toks, cfg,
                                 moe_dense_routing=True))
    # Routing genuinely differs at this capacity (the contract is a
    # bound, not equality)...
    assert nll_cap != nll_dense
    # ...but serving quality must track training quality: |gap| within
    # 5% relative.  Observed gap on this config is well under 1%; 5%
    # leaves headroom across seeds without letting real drift pass.
    assert abs(nll_dense - nll_cap) <= 0.05 * nll_cap, (nll_cap, nll_dense)


def test_generate_greedy_matches_argmax_rollout(rng):
    params = tfm.init_params(jax.random.key(0), CFG)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 4)).astype(np.int32))
    out = generate(params, prompt, CFG, max_new_tokens=6)
    assert out.shape == (2, 10)
    np.testing.assert_array_equal(out[:, :4], prompt)

    # Reference rollout: full forward, argmax, append.
    seq = np.asarray(prompt)
    for _ in range(6):
        logits, _ = tfm.apply(params, jnp.asarray(seq), CFG)
        nxt = np.asarray(logits[:, -1].argmax(-1), np.int32)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, seq)


def test_generate_deterministic_and_jittable(rng):
    params = tfm.init_params(jax.random.key(0), CFG)
    prompt = jnp.asarray(rng.integers(0, 64, (1, 3)).astype(np.int32))
    g = jax.jit(lambda p, t: generate(p, t, CFG, max_new_tokens=5))
    np.testing.assert_array_equal(g(params, prompt), g(params, prompt))


def test_generate_temperature_needs_key(rng):
    params = tfm.init_params(jax.random.key(0), CFG)
    prompt = jnp.zeros((1, 3), jnp.int32)
    with pytest.raises(ValueError, match="PRNG key"):
        generate(params, prompt, CFG, 4, temperature=0.8)
    out = generate(params, prompt, CFG, 4, temperature=0.8,
                   key=jax.random.key(1))
    assert out.shape == (1, 7)


def test_generate_bfloat16_cache(rng):
    """bf16 compute config: cache updates must not dtype-clash."""
    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=1, d_ff=64, max_len=16,
                                dtype="bfloat16")
    params = tfm.init_params(jax.random.key(0), cfg)
    out = generate(params, jnp.zeros((1, 2), jnp.int32), cfg, 4)
    assert out.shape == (1, 6)


def test_generate_length_guard(rng):
    params = tfm.init_params(jax.random.key(0), CFG)
    with pytest.raises(ValueError, match="max_len"):
        generate(params, jnp.zeros((1, 10), jnp.int32), CFG, 10)
    with pytest.raises(ValueError, match="at least one token"):
        generate(params, jnp.zeros((1, 0), jnp.int32), CFG, 4)


def test_top_k_mask_keeps_exactly_k():
    from distkeras_tpu.models.generate import top_k_mask

    logits = jnp.asarray([[1.0, 5.0, 3.0, 2.0, 4.0]])
    out = np.asarray(top_k_mask(logits, 2))
    assert np.isfinite(out).sum() == 2
    assert np.isfinite(out[0, [1, 4]]).all()  # the two largest survive


def test_top_p_mask_nucleus():
    from distkeras_tpu.models.generate import top_p_mask

    # probs ~ [0.643, 0.236, 0.087, 0.032, 0.002]
    logits = jnp.log(jnp.asarray([[0.643, 0.236, 0.087, 0.032, 0.002]]))
    out = np.asarray(top_p_mask(logits, 0.8))
    # exclusive mass: 0, .643, .879 -> first two kept, rest dropped
    assert np.isfinite(out[0, :2]).all() and not np.isfinite(out[0, 2:]).any()
    # top token always survives even with tiny p
    out = np.asarray(top_p_mask(logits, 1e-6))
    assert np.isfinite(out[0, 0]) and not np.isfinite(out[0, 1:]).any()


def test_generate_topk1_equals_greedy(rng):
    params = tfm.init_params(jax.random.key(0), CFG)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 4)).astype(np.int32))
    greedy = generate(params, prompt, CFG, max_new_tokens=6)
    k1 = generate(params, prompt, CFG, max_new_tokens=6, temperature=0.7,
                  top_k=1, key=jax.random.key(7))
    np.testing.assert_array_equal(greedy, k1)


def test_generate_tiny_top_p_equals_greedy(rng):
    params = tfm.init_params(jax.random.key(0), CFG)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 4)).astype(np.int32))
    greedy = generate(params, prompt, CFG, max_new_tokens=6)
    p0 = generate(params, prompt, CFG, max_new_tokens=6, temperature=1.3,
                  top_p=1e-9, key=jax.random.key(11))
    np.testing.assert_array_equal(greedy, p0)


def test_generate_sampling_deterministic_per_key(rng):
    params = tfm.init_params(jax.random.key(0), CFG)
    prompt = jnp.asarray(rng.integers(0, 64, (1, 3)).astype(np.int32))

    def g(seed):
        return generate(params, prompt, CFG, 5, temperature=1.0,
                        top_k=8, top_p=0.9, key=jax.random.key(seed))

    np.testing.assert_array_equal(g(3), g(3))
    assert not np.array_equal(np.asarray(g(3)), np.asarray(g(4)))


def test_generate_sampling_validation(rng):
    params = tfm.init_params(jax.random.key(0), CFG)
    prompt = jnp.zeros((1, 3), jnp.int32)
    with pytest.raises(ValueError, match="temperature > 0"):
        generate(params, prompt, CFG, 4, top_k=5)
    with pytest.raises(ValueError, match="top_k"):
        generate(params, prompt, CFG, 4, temperature=1.0, top_k=0,
                 key=jax.random.key(0))
    with pytest.raises(ValueError, match="top_p"):
        generate(params, prompt, CFG, 4, temperature=1.0, top_p=1.5,
                 key=jax.random.key(0))


def test_generate_rope_greedy_matches_rollout(rng):
    cfg = ROPE_CFG
    params = tfm.init_params(jax.random.key(0), cfg)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 4)).astype(np.int32))
    out = generate(params, prompt, cfg, max_new_tokens=6)
    seq = np.asarray(prompt)
    for _ in range(6):
        logits, _ = tfm.apply(params, jnp.asarray(seq), cfg)
        nxt = np.asarray(logits[:, -1].argmax(-1), np.int32)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, seq)


@pytest.mark.parametrize("kv", [1, 2])
def test_gqa_cache_is_smaller_and_decode_matches(rng, kv):
    """kv=2 exercises the group->kv-head mapping proper (kv=1/MQA is
    grouping-invariant and would mask a reshape-order regression)."""
    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                                n_layers=2, d_ff=64, max_len=16,
                                n_kv_heads=kv, rope=True)
    cache = init_cache(cfg, batch=2)
    assert cache["k"].shape == (2, 2, 16, kv, 8)
    params = tfm.init_params(jax.random.key(0), cfg)
    toks_ = jnp.asarray(rng.integers(0, 64, (2, 10)).astype(np.int32))
    full_logits, _ = tfm.apply(params, toks_, cfg)
    for pos in range(10):
        logits, cache = _decode_step(params, cache, toks_[:, pos], pos, cfg)
        np.testing.assert_allclose(logits, full_logits[:, pos],
                                   atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("cfg", [CFG, ROPE_CFG], ids=["table", "rope"])
def test_generate_ragged_batch_matches_individual(rng, cfg):
    """Right-padded prompts + prompt_lengths: every row decodes exactly
    as it would alone (left-pad alignment, masked pad, per-row position
    ids)."""
    params = tfm.init_params(jax.random.key(0), cfg)
    p1 = rng.integers(1, 64, (5,)).astype(np.int32)   # length 5
    p2 = rng.integers(1, 64, (2,)).astype(np.int32)   # length 2
    padded = np.zeros((2, 5), np.int32)
    padded[0] = p1
    padded[1, :2] = p2
    out = generate(params, jnp.asarray(padded), cfg, max_new_tokens=6,
                   prompt_lengths=np.array([5, 2]))
    solo1 = generate(params, jnp.asarray(p1[None]), cfg, max_new_tokens=6)
    solo2 = generate(params, jnp.asarray(p2[None]), cfg, max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(out)[0, :11],
                                  np.asarray(solo1)[0])
    np.testing.assert_array_equal(np.asarray(out)[1, :8],
                                  np.asarray(solo2)[0])
    # Tail padding preserved in the input layout.
    np.testing.assert_array_equal(np.asarray(out)[1, 8:], 0)


def test_generate_ragged_validation(rng):
    params = tfm.init_params(jax.random.key(0), CFG)
    prompt = jnp.zeros((2, 4), jnp.int32)
    with pytest.raises(ValueError, match="prompt_lengths"):
        generate(params, prompt, CFG, 4, prompt_lengths=np.array([4]))


def test_generate_ragged_length_range_checked(rng):
    params = tfm.init_params(jax.random.key(0), CFG)
    prompt = jnp.zeros((2, 4), jnp.int32)
    with pytest.raises(ValueError, match=r"\[1, 4\]"):
        generate(params, prompt, CFG, 4, prompt_lengths=np.array([4, 7]))
    with pytest.raises(ValueError, match=r"\[1, 4\]"):
        generate(params, prompt, CFG, 4, prompt_lengths=np.array([0, 4]))


def test_generate_eos_sticky(rng):
    params = tfm.init_params(jax.random.key(0), CFG)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 4)).astype(np.int32))
    free = np.asarray(generate(params, prompt, CFG, max_new_tokens=8))
    eos = int(free[0, 4])  # row 0's first generated token
    out = np.asarray(generate(params, prompt, CFG, max_new_tokens=8,
                              eos_token=eos))
    # Row 0 finished at its first generated slot: the rest is eos fill.
    assert (out[0, 4:] == eos).all()
    # A row that never emits eos matches the unconstrained run.
    if eos not in free[1, 4:]:
        np.testing.assert_array_equal(out[1], free[1])
    with pytest.raises(ValueError, match="eos_token"):
        generate(params, prompt, CFG, 4, eos_token=64)


# ------------------------------------------------------------------ prefill

@pytest.mark.parametrize("cfg", [CFG, ROPE_CFG])
def test_prefill_matches_sequential_generate(rng, cfg):
    """The prefill/decode split is a pure optimization: outputs must
    equal teacher-forcing every prompt position through the cached
    step (same einsums, same dtype path)."""
    params = tfm.init_params(jax.random.key(0), cfg)
    prompt = jnp.asarray(rng.integers(0, 64, (3, 7)), jnp.int32)
    seq = generate(params, prompt, cfg, max_new_tokens=8,
                   use_prefill=False)
    pre = generate(params, prompt, cfg, max_new_tokens=8,
                   use_prefill=True)
    np.testing.assert_array_equal(np.asarray(pre), np.asarray(seq))


def test_prefill_matches_sequential_gqa(rng):
    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                                n_layers=2, d_ff=64, max_len=32,
                                n_kv_heads=2, rope=True)
    params = tfm.init_params(jax.random.key(1), cfg)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 11)), jnp.int32)
    seq = generate(params, prompt, cfg, max_new_tokens=6,
                   use_prefill=False)
    pre = generate(params, prompt, cfg, max_new_tokens=6,
                   use_prefill=True)
    np.testing.assert_array_equal(np.asarray(pre), np.asarray(seq))


def test_prefill_sampling_matches_sequential(rng):
    params = tfm.init_params(jax.random.key(0), CFG)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 7)), jnp.int32)
    kw = dict(temperature=0.8, key=jax.random.key(5), top_k=8)
    seq = generate(params, prompt, CFG, 6, use_prefill=False, **kw)
    pre = generate(params, prompt, CFG, 6, use_prefill=True, **kw)
    np.testing.assert_array_equal(np.asarray(pre), np.asarray(seq))


def test_prefill_eos_matches_sequential(rng):
    params = tfm.init_params(jax.random.key(0), CFG)
    prompt = jnp.asarray(rng.integers(0, 64, (4, 5)), jnp.int32)
    seq = generate(params, prompt, CFG, 10, eos_token=3,
                   use_prefill=False)
    pre = generate(params, prompt, CFG, 10, eos_token=3,
                   use_prefill=True)
    np.testing.assert_array_equal(np.asarray(pre), np.asarray(seq))


def test_prefill_rejections(rng):
    prompt = jnp.asarray(rng.integers(0, 64, (2, 5)), jnp.int32)
    # Ragged prompts keep the sequential path.
    params_d = tfm.init_params(jax.random.key(0), CFG)
    with pytest.raises(ValueError, match="use_prefill"):
        generate(params_d, prompt, CFG, 4, use_prefill=True,
                 prompt_lengths=np.array([3, 5]))


@pytest.mark.parametrize("cfg", [MOE_CFG, MOE2_CFG], ids=["top1", "top2"])
def test_prefill_moe_matches_sequential(rng, cfg):
    """MoE prompts prefill with decode-parity dense routing: outputs
    equal the all-sequential path exactly (same per-token math)."""
    params = tfm.init_params(jax.random.key(1), cfg)
    prompt = jnp.asarray(rng.integers(0, 64, (3, 7)), jnp.int32)
    seq = generate(params, prompt, cfg, 6, use_prefill=False)
    pre = generate(params, prompt, cfg, 6, use_prefill=True)
    np.testing.assert_array_equal(np.asarray(pre), np.asarray(seq))
    auto = generate(params, prompt, cfg, 6)  # auto now prefills
    np.testing.assert_array_equal(np.asarray(auto), np.asarray(seq))


def test_prefill_rejects_overlong_prompt(rng):
    from distkeras_tpu.models.generate import prefill

    params = tfm.init_params(jax.random.key(0), CFG)
    prompt = jnp.asarray(rng.integers(0, 64, (2, CFG.max_len + 2)), jnp.int32)
    with pytest.raises(ValueError, match="max_len"):
        prefill(params, prompt, CFG)


# ---------------------------------------------------------------- int8 decode

def test_quantize_roundtrip_error_bound(rng):
    from distkeras_tpu.models.quant import quantize_params

    params = tfm.init_params(jax.random.key(0), CFG)
    qp = quantize_params(params)
    w = np.asarray(params["layers"]["attn"]["wq"])
    dq = np.asarray(qp["layers"]["attn"]["wq"].dequant())
    # Symmetric absmax int8: per-channel error <= scale/2 = amax/254.
    amax = np.abs(w).max(axis=1, keepdims=True)
    assert np.all(np.abs(dq - w) <= amax / 254 + 1e-7)


def test_quantized_decode_matches_f32_greedy(rng):
    """On a trained model the int8 decode must reproduce the f32 greedy
    tokens (easy task -> logit margins dwarf the ~0.4% rounding)."""
    import optax

    from distkeras_tpu.models.quant import quantize_params

    params = tfm.init_params(jax.random.key(0), CFG)
    opt = optax.adam(1e-2)
    step = jax.jit(tfm.make_train_step(CFG, opt))
    carry = (params, opt.init(params))
    data = jnp.asarray(np.repeat(rng.integers(0, 64, (32, 1)), 16, axis=1),
                       jnp.int32)
    for _ in range(30):
        carry, loss = step(carry, data)
    trained = carry[0]

    prompt = data[:4, :4]
    ref = generate(trained, prompt, CFG, 8, use_prefill=False)
    qp = quantize_params(trained)
    out = generate(qp, prompt, CFG, 8)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_quantized_params_memory_and_guards(rng):
    from distkeras_tpu.models.quant import QTensor, quantize_params

    params = tfm.init_params(jax.random.key(0), CFG)
    qp = quantize_params(params)
    emb = qp["tok_emb"]
    assert isinstance(emb, QTensor) and emb.q.dtype == jnp.int8
    # int8 + per-row scales ~ 1/3.9 of the f32 bytes on the big mats.
    f32_bytes = np.asarray(params["tok_emb"]).nbytes
    q_bytes = (np.asarray(emb.q).nbytes + np.asarray(emb.s).nbytes)
    assert q_bytes < f32_bytes / 3.5
    # prefill wants full-precision weights.
    prompt = jnp.asarray(rng.integers(0, 64, (2, 6)), jnp.int32)
    with pytest.raises(ValueError, match="use_prefill"):
        generate(qp, prompt, CFG, 4, use_prefill=True)
    # MoE rejected.
    moe_params = tfm.init_params(jax.random.key(1), MOE_CFG)
    with pytest.raises(ValueError, match="dense-FFN"):
        quantize_params(moe_params)


def test_quantized_decode_rope_gqa(rng):
    from distkeras_tpu.models.quant import quantize_params

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                                n_layers=2, d_ff=64, max_len=32,
                                n_kv_heads=2, rope=True)
    params = tfm.init_params(jax.random.key(1), cfg)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 5)), jnp.int32)
    out = generate(quantize_params(params), prompt, cfg, 6)
    assert out.shape == (2, 11)
    assert int(np.asarray(out).min()) >= 0


# ------------------------------------------------------------- beam search

def _seq_logprob(params, cfg, seq, start):
    """Sum of per-token log-probs of seq[start:] under the model."""
    from distkeras_tpu.models import transformer as tfm

    logits, _ = tfm.apply(params, jnp.asarray(seq[None, :-1]), cfg)
    logp = jax.nn.log_softmax(logits, axis=-1)[0]
    tgt = np.asarray(seq[1:])
    per = np.asarray(jnp.take_along_axis(
        logp, jnp.asarray(tgt)[:, None], axis=-1))[:, 0]
    return float(per[start - 1:].sum())


def test_beam_width_1_equals_greedy(rng):
    from distkeras_tpu.models.generate import beam_search

    params = tfm.init_params(jax.random.key(0), CFG)
    prompt = jnp.asarray(rng.integers(0, 64, (3, 5)), jnp.int32)
    greedy = generate(params, prompt, CFG, 8)
    seqs, scores = beam_search(params, prompt, CFG, 8, beam_width=1)
    np.testing.assert_array_equal(np.asarray(seqs[:, 0]),
                                  np.asarray(greedy))


def test_beam_scores_match_rescoring_and_beat_greedy(rng):
    from distkeras_tpu.models.generate import beam_search

    params = tfm.init_params(jax.random.key(1), CFG)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 4)), jnp.int32)
    n_new = 6
    seqs, scores = beam_search(params, prompt, CFG, n_new, beam_width=4)
    greedy = generate(params, prompt, CFG, n_new)
    for row in range(2):
        # Internal score bookkeeping == re-scoring with the training
        # forward (same math up to f32 reduction order).
        best = np.asarray(seqs[row, 0])
        np.testing.assert_allclose(
            float(scores[row, 0]), _seq_logprob(params, CFG, best, 4),
            atol=1e-3, rtol=1e-4)
        # Seeded regression property, not a theorem: vanilla beam
        # search can in principle prune the greedy path, but with this
        # pinned seed/width/config the best beam matches or beats the
        # greedy rollout's total log-prob (deterministic on CPU f32).
        g = _seq_logprob(params, CFG, np.asarray(greedy[row]), 4)
        assert float(scores[row, 0]) >= g - 1e-4, (float(scores[row, 0]), g)
        # Beams come back best-first.
        assert np.all(np.diff(np.asarray(scores[row])) <= 1e-6)


def test_beam_eos_freezes_score(rng):
    from distkeras_tpu.models.generate import beam_search

    params = tfm.init_params(jax.random.key(2), CFG)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 3)), jnp.int32)
    seqs, scores = beam_search(params, prompt, CFG, 8, beam_width=3,
                               eos_token=5)
    s = np.asarray(seqs)
    # After a generated eos, every later slot is eos (frozen beam).
    gen = s[:, :, 3:]
    for row in gen.reshape(-1, gen.shape[-1]):
        hits = np.nonzero(row == 5)[0]
        if hits.size:
            assert np.all(row[hits[0]:] == 5), row


def test_beam_validation_and_quantized(rng):
    from distkeras_tpu.models.generate import beam_search
    from distkeras_tpu.models.quant import quantize_params

    params = tfm.init_params(jax.random.key(0), CFG)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 4)), jnp.int32)
    with pytest.raises(ValueError, match="beam_width"):
        beam_search(params, prompt, CFG, 4, beam_width=0)
    with pytest.raises(ValueError, match="max_len"):
        beam_search(params, prompt, CFG, 64, beam_width=2)
    with pytest.raises(ValueError, match="use_prefill"):
        beam_search(quantize_params(params), prompt, CFG, 4,
                    beam_width=2, use_prefill=True)
    # Quantized tree works on the auto (sequential) path.
    seqs, _ = beam_search(quantize_params(params), prompt, CFG, 4,
                          beam_width=2)
    assert seqs.shape == (2, 2, 8)


def test_beam_prefill_matches_sequential(rng):
    from distkeras_tpu.models.generate import beam_search

    params = tfm.init_params(jax.random.key(3), CFG)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 6)), jnp.int32)
    s1, sc1 = beam_search(params, prompt, CFG, 5, beam_width=3,
                          use_prefill=True)
    s2, sc2 = beam_search(params, prompt, CFG, 5, beam_width=3,
                          use_prefill=False)
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
    np.testing.assert_allclose(np.asarray(sc1), np.asarray(sc2),
                               rtol=1e-5, atol=1e-5)


def test_beam_frozen_score_is_length_invariant(rng):
    """A beam that emits eos freezes: its score must not change as the
    scan keeps running (regression guard: frozen continuation adds 0,
    not logp(eos), each step)."""
    import optax

    from distkeras_tpu.models.generate import beam_search

    # Constant-row training: the model emits token c forever; with
    # eos_token=c the best beam finishes at the first generated slot.
    c = 9
    params = tfm.init_params(jax.random.key(0), CFG)
    opt = optax.adam(1e-2)
    step = jax.jit(tfm.make_train_step(CFG, opt))
    carry = (params, opt.init(params))
    data = jnp.full((16, 16), c, jnp.int32)
    for _ in range(25):
        carry, _ = step(carry, data)
    trained = carry[0]
    prompt = jnp.full((2, 3), c, jnp.int32)
    _, s_short = beam_search(trained, prompt, CFG, 2, beam_width=2,
                             eos_token=c)
    _, s_long = beam_search(trained, prompt, CFG, 10, beam_width=2,
                            eos_token=c)
    np.testing.assert_allclose(np.asarray(s_long[:, 0]),
                               np.asarray(s_short[:, 0]),
                               rtol=1e-5, atol=1e-6)


def test_windowed_decode_matches_training_forward(rng):
    """KV-cached decode with attention_window reproduces the training
    forward's logits position by position (same banded mask)."""
    import dataclasses

    cfg = dataclasses.replace(ROPE_CFG, attention_window=4)
    params = tfm.init_params(jax.random.key(0), cfg)
    t = jnp.asarray(rng.integers(0, 64, (2, 10)), jnp.int32)
    full_logits, _ = tfm.apply(params, t, cfg)
    cache = init_cache(cfg, 2)
    for pos in range(10):
        step_logits, cache = _decode_step(params, cache, t[:, pos], pos,
                                          cfg)
        np.testing.assert_allclose(
            np.asarray(step_logits), np.asarray(full_logits[:, pos]),
            atol=2e-4, rtol=2e-4)


def test_windowed_generate_prefill_matches_sequential(rng):
    import dataclasses

    cfg = dataclasses.replace(CFG, attention_window=3)
    params = tfm.init_params(jax.random.key(1), cfg)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 7)), jnp.int32)
    pre = generate(params, prompt, cfg, 6, use_prefill=True)
    seq = generate(params, prompt, cfg, 6, use_prefill=False)
    np.testing.assert_array_equal(np.asarray(pre), np.asarray(seq))


def test_beam_length_penalty(rng):
    """alpha=0 is the raw ordering; alpha>0 re-ranks by the GNMT
    normalization and returns the normalized scores, consistent with
    each beam's generated length."""
    from distkeras_tpu.models.generate import beam_search

    params = tfm.init_params(jax.random.key(4), CFG)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 4)), jnp.int32)
    s0, sc0 = beam_search(params, prompt, CFG, 6, beam_width=4)
    s1, sc1 = beam_search(params, prompt, CFG, 6, beam_width=4,
                          length_penalty=0.0)
    np.testing.assert_array_equal(np.asarray(s0), np.asarray(s1))
    np.testing.assert_allclose(np.asarray(sc0), np.asarray(sc1))

    # No eos: every beam generates exactly 6 tokens, so the alpha>0
    # ordering matches raw and scores divide by the same factor.
    s2, sc2 = beam_search(params, prompt, CFG, 6, beam_width=4,
                          length_penalty=1.0)
    np.testing.assert_array_equal(np.asarray(s2), np.asarray(s0))
    np.testing.assert_allclose(np.asarray(sc2),
                               np.asarray(sc0) / ((5.0 + 6.0) / 6.0),
                               rtol=1e-5)
    # Scores come back sorted under the normalization too.
    assert np.all(np.diff(np.asarray(sc2), axis=1) <= 1e-6)

    with pytest.raises(ValueError, match="length_penalty"):
        beam_search(params, prompt, CFG, 4, beam_width=2,
                    length_penalty=-1.0)


def test_beam_length_penalty_frozen_lengths(rng):
    """Frozen (eos) beams stop accumulating length: with a model that
    emits eos immediately, the best beam's normalized score uses n=1."""
    import optax

    from distkeras_tpu.models.generate import beam_search

    c = 9
    params = tfm.init_params(jax.random.key(0), CFG)
    opt = optax.adam(1e-2)
    step = jax.jit(tfm.make_train_step(CFG, opt))
    carry = (params, opt.init(params))
    data = jnp.full((16, 16), c, jnp.int32)
    for _ in range(25):
        carry, _ = step(carry, data)
    trained = carry[0]
    prompt = jnp.full((1, 3), c, jnp.int32)
    _, raw = beam_search(trained, prompt, CFG, 8, beam_width=2,
                         eos_token=c)
    _, norm = beam_search(trained, prompt, CFG, 8, beam_width=2,
                          eos_token=c, length_penalty=1.0)
    np.testing.assert_allclose(float(norm[0, 0]),
                               float(raw[0, 0]) / 1.0, rtol=1e-5)


# ----------------------------------------------------------- rolling decode

def test_rolling_decode_matches_large_cache(rng):
    """Generation past max_len on the ring-buffer cache must reproduce
    a non-wrapping run of the same windowed model with a big cache —
    the window makes everything beyond the last W positions irrelevant,
    so wrap-around must be invisible."""
    import dataclasses

    base = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                 n_layers=2, d_ff=64, rope=True,
                                 attention_window=6, max_len=64)
    small = dataclasses.replace(base, max_len=16)  # will wrap
    params = tfm.init_params(jax.random.key(0), base)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 5)), jnp.int32)
    n_new = 35  # 5 + 35 = 40 > 16: several full wraps
    big = generate(params, prompt, base, n_new)
    rolled = generate(params, prompt, small, n_new)
    np.testing.assert_array_equal(np.asarray(rolled), np.asarray(big))


def test_rolling_decode_sampling_and_eos(rng):
    import dataclasses

    base = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                 n_layers=2, d_ff=64, rope=True,
                                 attention_window=4, max_len=48,
                                 n_kv_heads=1)
    small = dataclasses.replace(base, max_len=12)
    params = tfm.init_params(jax.random.key(1), base)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 4)), jnp.int32)
    kw = dict(temperature=0.8, key=jax.random.key(7), top_k=8, eos_token=3)
    big = generate(params, prompt, base, 25, **kw)
    rolled = generate(params, prompt, small, 25, **kw)
    np.testing.assert_array_equal(np.asarray(rolled), np.asarray(big))


def test_rolling_beam_matches_large_cache(rng):
    """Beam search past max_len on the ring-buffer cache (round-4)
    reproduces a non-wrapping run of the same windowed model with a
    big cache — on BOTH the ancestry path (slot-indexed ancestor map;
    stale entries retired as slots are rewritten) and the physical
    parent-gather, with eos and GQA in the mix."""
    import dataclasses

    from distkeras_tpu.models.generate import beam_search

    base = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                                 n_kv_heads=2, n_layers=2, d_ff=64,
                                 rope=True, attention_window=6,
                                 max_len=64)
    small = dataclasses.replace(base, max_len=16)  # will wrap
    params = tfm.init_params(jax.random.key(2), base)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 5)), jnp.int32)
    n_new = 30  # 5 + 30 = 35 > 16: several full wraps
    for kw in [dict(), dict(eos_token=7),
               dict(beam_impl="physical")]:
        big_s, big_sc = beam_search(params, prompt, base, n_new,
                                    beam_width=3, **kw)
        roll_s, roll_sc = beam_search(params, prompt, small, n_new,
                                      beam_width=3, **kw)
        np.testing.assert_array_equal(np.asarray(roll_s),
                                      np.asarray(big_s), err_msg=str(kw))
        np.testing.assert_allclose(np.asarray(roll_sc),
                                   np.asarray(big_sc),
                                   atol=1e-5, rtol=1e-5)


def test_rolling_decode_requires_rope_and_window(rng):
    """Past-max_len decoding without the rolling prerequisites must
    still raise, including for ragged prompts."""
    import dataclasses

    params = tfm.init_params(jax.random.key(0), CFG)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 4)), jnp.int32)
    with pytest.raises(ValueError, match="max_len"):
        generate(params, prompt, CFG, 20)  # no rope, no window
    win = dataclasses.replace(CFG, attention_window=4)  # window, no rope
    pw = tfm.init_params(jax.random.key(0), win)
    with pytest.raises(ValueError, match="max_len"):
        generate(pw, prompt, win, 20)
    roll = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                 n_layers=1, d_ff=64, rope=True,
                                 attention_window=4, max_len=12)
    pr = tfm.init_params(jax.random.key(0), roll)
    with pytest.raises(ValueError, match="max_len"):  # ragged: no rolling
        generate(pr, prompt, roll, 20, prompt_lengths=np.array([2, 4]))
    out = generate(pr, prompt, roll, 20)  # eligible: runs past max_len
    assert out.shape == (2, 24)


def test_rolling_decode_long_prompt_sequential_fallback(rng):
    """A prompt longer than max_len is rolling-eligible: auto path must
    fall back to sequential teacher-forcing (prefill cannot hold it)
    and still match the big-cache run."""
    import dataclasses

    base = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                 n_layers=2, d_ff=64, rope=True,
                                 attention_window=4, max_len=48)
    small = dataclasses.replace(base, max_len=12)
    params = tfm.init_params(jax.random.key(2), base)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 20)), jnp.int32)  # > 12
    big = generate(params, prompt, base, 10)
    rolled = generate(params, prompt, small, 10)
    np.testing.assert_array_equal(np.asarray(rolled), np.asarray(big))
    with pytest.raises(ValueError, match="fits the cache"):
        generate(params, prompt, small, 10, use_prefill=True)


def test_beam_search_windowed_cfg(rng):
    """Beam search composes with attention_window (the banded decode
    mask drives every beam's cache reads); width 1 == windowed greedy."""
    import dataclasses

    cfg = dataclasses.replace(ROPE_CFG, attention_window=4)
    params = tfm.init_params(jax.random.key(3), cfg)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 4)), jnp.int32)
    from distkeras_tpu.models.generate import beam_search

    greedy = generate(params, prompt, cfg, 6)
    seqs, _ = beam_search(params, prompt, cfg, 6, beam_width=1)
    np.testing.assert_array_equal(np.asarray(seqs[:, 0]),
                                  np.asarray(greedy))


def test_rolling_decode_quantized(rng):
    """int8 weights x rolling window cache: sequential decode past
    max_len with a quantized tree matches the quantized big-cache run."""
    import dataclasses

    from distkeras_tpu.models.quant import quantize_params

    base = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                 n_layers=2, d_ff=64, rope=True,
                                 attention_window=4, max_len=40)
    small = dataclasses.replace(base, max_len=10)
    qp = quantize_params(tfm.init_params(jax.random.key(5), base))
    prompt = jnp.asarray(rng.integers(0, 64, (2, 4)), jnp.int32)
    big = generate(qp, prompt, base, 20)
    rolled = generate(qp, prompt, small, 20)
    np.testing.assert_array_equal(np.asarray(rolled), np.asarray(big))


def test_min_p_mask_semantics():
    from distkeras_tpu.models.generate import min_p_mask

    logits = jnp.asarray([[0.0, -1.0, -10.0]])
    out = np.asarray(min_p_mask(logits, 0.5))
    # p1/pmax = e^-1 ~ 0.37 < 0.5 -> dropped; p2/pmax tiny -> dropped.
    assert np.isfinite(out[0, 0])
    assert np.isneginf(out[0, 1]) and np.isneginf(out[0, 2])
    out2 = np.asarray(min_p_mask(logits, 0.3))
    assert np.isfinite(out2[0, 1])  # 0.37 >= 0.3 survives
    with pytest.raises(ValueError, match="min_p"):
        min_p_mask(logits, 0.0)


def test_generate_min_p_sampling(rng):
    params = tfm.init_params(jax.random.key(0), CFG)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 5)), jnp.int32)
    out = generate(params, prompt, CFG, 6, temperature=0.9, min_p=0.1,
                   key=jax.random.key(1))
    assert out.shape == (2, 11)
    # min_p=1.0 keeps only the argmax -> equals greedy.
    strict = generate(params, prompt, CFG, 6, temperature=0.9, min_p=1.0,
                      key=jax.random.key(1))
    greedy = generate(params, prompt, CFG, 6)
    np.testing.assert_array_equal(np.asarray(strict), np.asarray(greedy))
    with pytest.raises(ValueError, match="temperature"):
        generate(params, prompt, CFG, 6, min_p=0.1)


def test_beam_ancestry_equals_physical_reorder(rng):
    """The ancestry-attention beam path (cache never reordered; history
    resolved through the one-hot ancestor map) returns the same
    hypotheses and scores as the physical parent-gather it replaced —
    including under GQA grouping and an eos freeze."""
    import dataclasses

    from distkeras_tpu.models.generate import beam_search

    gqa_cfg = dataclasses.replace(CFG, n_heads=4, n_kv_heads=2, rope=True)
    params = tfm.init_params(jax.random.key(3), gqa_cfg)
    prompt = jnp.asarray(rng.integers(0, 64, (3, 5)), jnp.int32)
    for kw in [dict(), dict(eos_token=7), dict(length_penalty=0.8)]:
        seqs_a, sc_a = beam_search(params, prompt, gqa_cfg, 10,
                                   beam_width=3, **kw)
        seqs_p, sc_p = beam_search(params, prompt, gqa_cfg, 10,
                                   beam_width=3, beam_impl="physical",
                                   **kw)
        np.testing.assert_array_equal(np.asarray(seqs_a),
                                      np.asarray(seqs_p))
        np.testing.assert_allclose(np.asarray(sc_a), np.asarray(sc_p),
                                   atol=1e-5, rtol=1e-5)


def test_beam_impl_knob_and_ancestry_size_guard(rng, monkeypatch):
    """The public beam_impl knob: 'physical' matches 'ancestry' (both
    explicit), 'auto' falls back with a warning when the ancestry score
    intermediate would exceed the limit, explicit 'ancestry' raises at
    that size, and bad values are rejected.  (Windowed configs take
    ancestry too — test_beam_windowed_ancestry_equals_physical.)"""
    from distkeras_tpu.models import generate as gen
    from distkeras_tpu.models.generate import beam_search

    params = tfm.init_params(jax.random.key(5), CFG)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 4)).astype(np.int32))
    sa, sca = beam_search(params, prompt, CFG, 5, beam_width=3,
                          beam_impl="ancestry")
    sp, scp = beam_search(params, prompt, CFG, 5, beam_width=3,
                          beam_impl="physical")
    np.testing.assert_array_equal(np.asarray(sa), np.asarray(sp))
    np.testing.assert_allclose(np.asarray(sca), np.asarray(scp),
                               atol=1e-5, rtol=1e-5)

    # Shrink the limit below this config's estimate to exercise the
    # guard without allocating GBs.
    est = gen._ancestry_score_bytes(2, 3, CFG)
    monkeypatch.setattr(gen, "ANCESTRY_SCORE_LIMIT_BYTES", est // 2)
    with pytest.warns(UserWarning, match="falling back to the physical"):
        sf, scf = gen.beam_search(params, prompt, CFG, 5, beam_width=3)
    np.testing.assert_array_equal(np.asarray(sf), np.asarray(sp))
    with pytest.raises(ValueError, match="over the"):
        gen.beam_search(params, prompt, CFG, 5, beam_width=3,
                        beam_impl="ancestry")
    monkeypatch.undo()

    with pytest.raises(ValueError, match="beam_impl must be"):
        beam_search(params, prompt, CFG, 5, beam_width=3,
                    beam_impl="fast")


def test_beam_windowed_ancestry_equals_physical(rng):
    """Windowed (ring-buffer) beam search on the ancestry path matches
    the physical parent-gather exactly — beam search never decodes past
    max_len, so slots never wrap and the ancestor map indexes them
    directly; only the band mask differs from the full-cache path
    (round-4 extension; windowed beam previously always paid the
    per-step cache gather).  Covers rope + GQA + eos under a window
    shorter than the sequence."""
    import dataclasses

    from distkeras_tpu.models.generate import beam_search

    cfg = dataclasses.replace(CFG, n_heads=4, n_kv_heads=2, rope=True,
                              attention_window=6)
    params = tfm.init_params(jax.random.key(7), cfg)
    prompt = jnp.asarray(rng.integers(0, 64, (3, 5)), jnp.int32)
    for kw in [dict(), dict(eos_token=7), dict(length_penalty=0.6)]:
        sa, sca = beam_search(params, prompt, cfg, 10, beam_width=3,
                              beam_impl="ancestry", **kw)
        sp, scp = beam_search(params, prompt, cfg, 10, beam_width=3,
                              beam_impl="physical", **kw)
        np.testing.assert_array_equal(np.asarray(sa), np.asarray(sp))
        np.testing.assert_allclose(np.asarray(sca), np.asarray(scp),
                                   atol=1e-5, rtol=1e-5)


def test_top_k_mask_approx_path():
    """The approximate-threshold top-k (vocab large enough to engage
    approx_max_k) keeps ~k entries around the true threshold, and
    exact=True reproduces the pre-round-3 exact mask bit-for-bit."""
    from distkeras_tpu.models.generate import top_k_mask

    rng_l = np.random.default_rng(0)
    logits = jnp.asarray(rng_l.normal(size=(4, 4096)).astype(np.float32))
    k = 50
    approx = np.asarray(top_k_mask(logits, k))
    exact = np.asarray(top_k_mask(logits, k, exact=True))
    kept_a = np.isfinite(approx).sum(axis=-1)
    kept_e = np.isfinite(exact).sum(axis=-1)
    np.testing.assert_array_equal(kept_e, k)
    # NOTE: on CPU (this suite) approx_max_k lowers to an exact top-k,
    # so kept_a == k trivially and the band assertions below only
    # genuinely bite on TPU — they pin the CONTRACT the approx path is
    # allowed to exploit, not the TPU kernel's recall itself.
    # Approximate support sits in a small band around k, and every kept
    # logit is a genuinely large one (>= the exact threshold minus a
    # small slack).
    assert (np.abs(kept_a - k) <= max(5, k // 5)).all(), kept_a
    thresh = np.sort(np.asarray(logits), axis=-1)[:, -k]
    assert (approx[np.isfinite(approx)].min()
            >= thresh.min() - 0.5)
    # Small vocab (k > V/2) silently takes the exact path.
    small = jnp.asarray(rng_l.normal(size=(2, 64)).astype(np.float32))
    np.testing.assert_array_equal(
        np.asarray(top_k_mask(small, 40)),
        np.asarray(top_k_mask(small, 40, exact=True)))


# ---------------------------------------------------------- int8 KV cache

def test_kv_int8_decode_close_to_fp(rng):
    """int8 KV cache: teacher-forced logits track the full-precision
    decode within quantization noise, and greedy generation on a
    near-deterministic model is unchanged."""
    from distkeras_tpu.models.generate import _decode_step

    cfg = ROPE_CFG
    params = tfm.init_params(jax.random.key(0), cfg)
    toks = jnp.asarray(rng.integers(0, 64, (2, 12)).astype(np.int32))
    full_logits, _ = tfm.apply(params, toks, cfg)

    cache = init_cache(cfg, 2, kv_int8=True)
    for pos in range(12):
        logits, cache = _decode_step(params, cache, toks[:, pos], pos, cfg)
        base = np.abs(np.asarray(full_logits[:, pos])).max()
        np.testing.assert_allclose(logits, full_logits[:, pos],
                                   atol=0.05 * base, rtol=0.1)


def test_kv_int8_generate_prefill_close_to_sequential(rng):
    """Prefill and sequential prompt paths under kv_int8 agree to
    quantization noise — NOT bit-exactly: prefill computes the prompt's
    attention in full precision and quantizes the K/V it writes, while
    the sequential path attends the already-quantized cache, so from
    layer 2 on the residual streams (and hence cached K/V) differ by
    int8 rounding.  The contract is closeness on logits (advisor
    round-3: token equality only held because greedy argmax absorbed
    the drift on a tiny model — fragile across seeds/backends)."""
    from distkeras_tpu.models.generate import (_decode_step, init_cache,
                                               prefill)

    params = tfm.init_params(jax.random.key(1), CFG)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 6)).astype(np.int32))
    _, last_p = prefill(params, prompt, CFG, last_logits=True,
                        kv_int8=True)
    cache_s = init_cache(CFG, 2, kv_int8=True)
    for pos in range(6):
        last_s, cache_s = _decode_step(params, cache_s, prompt[:, pos],
                                       pos, CFG)
    base = np.abs(np.asarray(last_p)).max()
    np.testing.assert_allclose(np.asarray(last_s), np.asarray(last_p),
                               atol=0.05 * base, rtol=0.1)


def test_kv_int8_beam_ancestry_equals_physical(rng):
    """Beam search runs on the int8 cache through BOTH the ancestry and
    physical paths with identical results."""
    from distkeras_tpu.models.generate import beam_search

    params = tfm.init_params(jax.random.key(2), CFG)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 4)).astype(np.int32))
    sa, sca = beam_search(params, prompt, CFG, 6, beam_width=3,
                          kv_int8=True)
    sp, scp = beam_search(params, prompt, CFG, 6, beam_width=3,
                          kv_int8=True, beam_impl="physical")
    np.testing.assert_array_equal(np.asarray(sa), np.asarray(sp))
    np.testing.assert_allclose(np.asarray(sca), np.asarray(scp),
                               atol=1e-5, rtol=1e-5)


def test_kv_int8_rolling_decode_matches_large_cache(rng):
    """kv_int8 on the ring-buffer cache (round-5: the scale slabs ride
    the same slot updates as the K/V): generation past max_len must
    EXACTLY reproduce a non-wrapping kv_int8 run with a big cache —
    quantization is per-token and slot-independent, so the wrap must
    stay invisible, int8 or not."""
    import dataclasses

    base = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                 n_layers=2, d_ff=64, rope=True,
                                 attention_window=6, max_len=64)
    small = dataclasses.replace(base, max_len=16)  # will wrap
    params = tfm.init_params(jax.random.key(0), base)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 5)), jnp.int32)
    big = generate(params, prompt, base, 35, kv_int8=True,
                   use_prefill=False)
    rolled = generate(params, prompt, small, 35, kv_int8=True,
                      use_prefill=False)
    np.testing.assert_array_equal(np.asarray(rolled), np.asarray(big))


def test_kv_int8_rolling_beam_matches_large_cache(rng):
    """Rolling beam search on the int8 ring cache, both impls, vs a
    non-wrapping int8 big-cache run."""
    import dataclasses

    from distkeras_tpu.models.generate import beam_search

    base = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                                 n_kv_heads=2, n_layers=2, d_ff=64,
                                 rope=True, attention_window=6,
                                 max_len=64)
    small = dataclasses.replace(base, max_len=16)  # will wrap
    params = tfm.init_params(jax.random.key(2), base)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 5)), jnp.int32)
    kw = dict(beam_width=3, kv_int8=True, use_prefill=False)
    bs, bsc = beam_search(params, prompt, base, 20, **kw)
    for impl in ("ancestry", "physical"):
        rs, rsc = beam_search(params, prompt, small, 20, beam_impl=impl,
                              **kw)
        np.testing.assert_array_equal(np.asarray(rs), np.asarray(bs))
        np.testing.assert_allclose(np.asarray(rsc), np.asarray(bsc),
                                   atol=1e-4, rtol=1e-4)


def test_kv_int8_ragged_rows_match_solo(rng):
    """Ragged prompts x kv_int8: each row decodes exactly as it would
    alone on the int8 cache (left-pad slots never attend; position ids
    count from the row's true start; per-token quantization makes the
    comparison exact, not just close)."""
    params = tfm.init_params(jax.random.key(3), ROPE_CFG)
    p = 6
    rows = jnp.asarray(rng.integers(0, 64, (2, p)), jnp.int32)
    lens = [3, 6]
    out = generate(params, rows, ROPE_CFG, 5, kv_int8=True,
                   prompt_lengths=lens)
    for i, ln in enumerate(lens):
        alone = generate(params, rows[i:i + 1, :ln], ROPE_CFG, 5,
                         kv_int8=True, use_prefill=False)
        np.testing.assert_array_equal(np.asarray(out[i, :ln + 5]),
                                      np.asarray(alone[0]))


# ------------------------------------------------------- prompt/prefix cache

def test_prompt_cache_matches_full_prompt(rng):
    """Reusing a prefilled prefix cache (system-prompt pattern) emits
    EXACTLY the tokens of running the concatenated prompt from scratch
    — greedy and sampled (the position-keyed PRNG stream makes the
    sampled comparison exact), batch-matched and batch-1-broadcast."""
    from distkeras_tpu.models.generate import prefill

    params = tfm.init_params(jax.random.key(0), ROPE_CFG)
    prefix = jnp.asarray(rng.integers(0, 64, (2, 5)).astype(np.int32))
    tail = jnp.asarray(rng.integers(0, 64, (2, 3)).astype(np.int32))
    full = jnp.concatenate([prefix, tail], axis=1)

    ref = generate(params, full, ROPE_CFG, 6)
    cache, _ = prefill(params, prefix, ROPE_CFG, last_logits=False)
    out = generate(params, tail, ROPE_CFG, 6, prompt_cache=(cache, 5))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref[:, 5:]))

    k = jax.random.key(9)
    ref_s = generate(params, full, ROPE_CFG, 6, temperature=0.9, top_k=8,
                     key=k)
    out_s = generate(params, tail, ROPE_CFG, 6, temperature=0.9, top_k=8,
                     key=k, prompt_cache=(cache, 5))
    np.testing.assert_array_equal(np.asarray(out_s),
                                  np.asarray(ref_s[:, 5:]))

    # Batch-1 shared prefix fans out to the request batch.
    cache1, _ = prefill(params, prefix[:1], ROPE_CFG, last_logits=False)
    prefix_b = jnp.broadcast_to(prefix[:1], prefix.shape)
    ref_b = generate(params, jnp.concatenate([prefix_b, tail], axis=1),
                     ROPE_CFG, 6)
    out_b = generate(params, tail, ROPE_CFG, 6, prompt_cache=(cache1, 5))
    np.testing.assert_array_equal(np.asarray(out_b),
                                  np.asarray(ref_b[:, 5:]))


def test_prompt_cache_kv_int8_and_validation(rng):
    from distkeras_tpu.models.generate import prefill

    params = tfm.init_params(jax.random.key(1), CFG)
    prefix = jnp.asarray(rng.integers(0, 64, (2, 4)).astype(np.int32))
    tail = jnp.asarray(rng.integers(0, 64, (2, 2)).astype(np.int32))
    qcache, _ = prefill(params, prefix, CFG, last_logits=False,
                        kv_int8=True)
    full = jnp.concatenate([prefix, tail], axis=1)
    ref = generate(params, full, CFG, 4, kv_int8=True)
    out = generate(params, tail, CFG, 4, kv_int8=True,
                   prompt_cache=(qcache, 4))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref[:, 4:]))

    with pytest.raises(ValueError, match="quantization must match"):
        generate(params, tail, CFG, 4, prompt_cache=(qcache, 4))
    with pytest.raises(ValueError, match="fit max_len"):
        generate(params, tail, CFG, 12, prompt_cache=(qcache, 4))
    bad = jax.tree.map(lambda a: jnp.repeat(a, 3, axis=1), qcache)
    with pytest.raises(ValueError, match="batch"):
        generate(params, tail, CFG, 4, kv_int8=True,
                 prompt_cache=(bad, 4))


def test_prompt_cache_single_token_tail_and_quantized(rng):
    """Code-review regressions: a 1-token tail and a quantized tree both
    work with prompt_cache (no _resolve_prefill interference), and the
    error messages distinguish empty prefixes from budget overflow."""
    from distkeras_tpu.models.generate import prefill
    from distkeras_tpu.models.quant import quantize_params

    params = tfm.init_params(jax.random.key(1), CFG)
    prefix = jnp.asarray(rng.integers(0, 64, (2, 4)).astype(np.int32))
    tail = jnp.asarray(rng.integers(0, 64, (2, 1)).astype(np.int32))
    cache, _ = prefill(params, prefix, CFG, last_logits=False)
    full = jnp.concatenate([prefix, tail], axis=1)
    ref = generate(params, full, CFG, 4)
    out = generate(params, tail, CFG, 4, prompt_cache=(cache, 4))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref[:, 4:]))

    # Quantized tree + prompt_cache: the regression is that
    # _resolve_prefill's full-precision precondition no longer blocks
    # the call.  (The cache here holds full-precision prefix K/V while
    # the tail decodes through int8 weights — a legitimate mixed
    # deployment, but not bit-comparable to any single-precision
    # reference, so this is a smoke + shape check, not an equality.)
    qp = quantize_params(params)
    qout = generate(qp, tail, CFG, 4, prompt_cache=(cache, 4))
    assert qout.shape == (2, 5)
    np.testing.assert_array_equal(np.asarray(qout[:, :1]),
                                  np.asarray(tail))

    with pytest.raises(ValueError, match=">= 1"):
        generate(params, tail, CFG, 4, prompt_cache=(cache, 0))
    with pytest.raises(ValueError, match="no effect with prompt_cache"):
        generate(params, tail, CFG, 4, prompt_cache=(cache, 4),
                 use_prefill=True)


def test_beam_prompt_cache_matches_full_prompt(rng):
    """Beam search over a reused prefix cache returns exactly the
    hypotheses and scores of beaming the concatenated prompt — on both
    the ancestry and physical paths, and under kv_int8."""
    from distkeras_tpu.models.generate import beam_search, prefill

    params = tfm.init_params(jax.random.key(0), ROPE_CFG)
    prefix = jnp.asarray(rng.integers(0, 64, (2, 4)).astype(np.int32))
    tail = jnp.asarray(rng.integers(0, 64, (2, 3)).astype(np.int32))
    full = jnp.concatenate([prefix, tail], axis=1)
    for kw in [dict(), dict(kv_int8=True),
               dict(_force_physical=True), dict(eos_token=5)]:
        cache, _ = prefill(params, prefix, ROPE_CFG, last_logits=False,
                           kv_int8=kw.get("kv_int8", False))
        ref_s, ref_sc = beam_search(params, full, ROPE_CFG, 6,
                                    beam_width=3, **kw)
        out_s, out_sc = beam_search(params, tail, ROPE_CFG, 6,
                                    beam_width=3,
                                    prompt_cache=(cache, 4), **kw)
        np.testing.assert_array_equal(np.asarray(out_s),
                                      np.asarray(ref_s[:, :, 4:]))
        # Scores are sums of token log-probs; the two prompt passes
        # (full prefill vs prefix-prefill + suffix chunk) reduce
        # attention in different orders, so logits differ ~1e-4/pos in
        # f32 — the HYPOTHESES must match exactly, the score sums to a
        # few 1e-3.
        np.testing.assert_allclose(np.asarray(out_sc),
                                   np.asarray(ref_sc), atol=1e-2,
                                   rtol=1e-4)
    with pytest.raises(ValueError, match="no effect with prompt_cache"):
        beam_search(params, tail, ROPE_CFG, 4, beam_width=2,
                    prompt_cache=(cache, 4), use_prefill=True)


def test_kv_int8_gqa_decode_close_to_fp(rng):
    """int8 KV scales are per-kv-head: the GQA cache (fewer kv heads
    than query heads) quantizes and dequantizes consistently."""
    import dataclasses

    from distkeras_tpu.models.generate import _decode_step

    cfg = dataclasses.replace(ROPE_CFG, n_heads=4, n_kv_heads=2)
    params = tfm.init_params(jax.random.key(2), cfg)
    toks = jnp.asarray(rng.integers(0, 64, (2, 10)).astype(np.int32))
    full_logits, _ = tfm.apply(params, toks, cfg)
    cache = init_cache(cfg, 2, kv_int8=True)
    for pos in range(10):
        logits, cache = _decode_step(params, cache, toks[:, pos], pos,
                                     cfg)
        base = np.abs(np.asarray(full_logits[:, pos])).max()
        np.testing.assert_allclose(logits, full_logits[:, pos],
                                   atol=0.05 * base, rtol=0.1)


def test_mask_validation_rejects_concrete_arrays_out_of_range():
    """Round-6 fix: _validate_unit_interval used to skip ALL non-scalar
    values, so a direct mask caller with a bad concrete array got
    silent NaN masking; now concrete arrays are range-checked (min_p
    arrays may carry 0.0, the serving engines' explicit no-op slot)."""
    from distkeras_tpu.models.generate import min_p_mask, top_p_mask

    logits = jnp.zeros((2, 4))
    with pytest.raises(ValueError, match="min_p"):
        min_p_mask(logits, np.asarray([[-0.2], [0.5]]))
    with pytest.raises(ValueError, match="top_p"):
        top_p_mask(logits, np.asarray([[0.0], [0.5]]))
    with pytest.raises(ValueError, match="top_p"):
        top_p_mask(logits, np.asarray([[1.5], [0.5]]))
    # The engines' no-op slot values stay legal in arrays...
    out = np.asarray(min_p_mask(logits, np.asarray([[0.0], [0.5]])))
    assert np.isfinite(out[0]).all()
    np.asarray(top_p_mask(logits, np.asarray([[1.0], [0.5]])))
    # ...and traced values still pass through to the caller's checks.
    jax.jit(lambda l, p: top_p_mask(l, p))(
        logits, jnp.asarray([[0.9], [0.5]]))


def test_generate_top_p_one_equals_no_filter(rng):
    """Round-6 parity fix: top_p=1.0 bypasses the nucleus mask exactly
    like top_p=None (the serving engines' contract), so a request
    copying its solo call's top_p=1.0 cannot diverge in the float
    corner where the sorted cumsum overshoots 1.0."""
    params = tfm.init_params(jax.random.key(0), CFG)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 5)), jnp.int32)
    k = jax.random.key(7)
    one = generate(params, prompt, CFG, 6, temperature=0.9, top_p=1.0,
                   key=k)
    none = generate(params, prompt, CFG, 6, temperature=0.9, key=k)
    np.testing.assert_array_equal(np.asarray(one), np.asarray(none))
    # min_p=0.0 is the matching explicit no-op, and both no-op values
    # are legal on greedy calls too (submit() accepts them there).
    zero = generate(params, prompt, CFG, 6, temperature=0.9,
                    min_p=0.0, key=k)
    np.testing.assert_array_equal(np.asarray(zero), np.asarray(none))
    greedy = generate(params, prompt, CFG, 6)
    noop = generate(params, prompt, CFG, 6, top_p=1.0, min_p=0.0)
    np.testing.assert_array_equal(np.asarray(noop), np.asarray(greedy))
    with pytest.raises(ValueError, match="temperature"):
        generate(params, prompt, CFG, 6, top_p=0.9)  # real filter
    with pytest.raises(ValueError, match="min_p"):
        generate(params, prompt, CFG, 6, temperature=0.9, min_p=-0.1,
                 key=k)
