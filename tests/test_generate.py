"""KV-cached decoding: must reproduce the training-path forward exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.models import transformer as tfm
from distkeras_tpu.models.generate import _decode_step, generate, init_cache


CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=16)
MOE_CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=1, d_ff=64, max_len=16,
                                num_experts=4, capacity_factor=8.0)


@pytest.mark.parametrize("cfg", [CFG, MOE_CFG], ids=["dense", "moe"])
def test_cached_decode_matches_full_forward(rng, cfg):
    """Teacher-forcing through the cache == apply() at every position."""
    params = tfm.init_params(jax.random.key(0), cfg)
    toks = jnp.asarray(rng.integers(0, 64, (2, 12)).astype(np.int32))
    full_logits, _ = tfm.apply(params, toks, cfg)

    cache = init_cache(cfg, 2)
    for pos in range(12):
        logits, cache = _decode_step(params, cache, toks[:, pos], pos, cfg)
        np.testing.assert_allclose(logits, full_logits[:, pos], atol=2e-4,
                                   rtol=2e-4)


def test_generate_greedy_matches_argmax_rollout(rng):
    params = tfm.init_params(jax.random.key(0), CFG)
    prompt = jnp.asarray(rng.integers(0, 64, (2, 4)).astype(np.int32))
    out = generate(params, prompt, CFG, max_new_tokens=6)
    assert out.shape == (2, 10)
    np.testing.assert_array_equal(out[:, :4], prompt)

    # Reference rollout: full forward, argmax, append.
    seq = np.asarray(prompt)
    for _ in range(6):
        logits, _ = tfm.apply(params, jnp.asarray(seq), CFG)
        nxt = np.asarray(logits[:, -1].argmax(-1), np.int32)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, seq)


def test_generate_deterministic_and_jittable(rng):
    params = tfm.init_params(jax.random.key(0), CFG)
    prompt = jnp.asarray(rng.integers(0, 64, (1, 3)).astype(np.int32))
    g = jax.jit(lambda p, t: generate(p, t, CFG, max_new_tokens=5))
    np.testing.assert_array_equal(g(params, prompt), g(params, prompt))


def test_generate_temperature_needs_key(rng):
    params = tfm.init_params(jax.random.key(0), CFG)
    prompt = jnp.zeros((1, 3), jnp.int32)
    with pytest.raises(ValueError, match="PRNG key"):
        generate(params, prompt, CFG, 4, temperature=0.8)
    out = generate(params, prompt, CFG, 4, temperature=0.8,
                   key=jax.random.key(1))
    assert out.shape == (1, 7)


def test_generate_bfloat16_cache(rng):
    """bf16 compute config: cache updates must not dtype-clash."""
    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=1, d_ff=64, max_len=16,
                                dtype="bfloat16")
    params = tfm.init_params(jax.random.key(0), cfg)
    out = generate(params, jnp.zeros((1, 2), jnp.int32), cfg, 4)
    assert out.shape == (1, 6)


def test_generate_length_guard(rng):
    params = tfm.init_params(jax.random.key(0), CFG)
    with pytest.raises(ValueError, match="max_len"):
        generate(params, jnp.zeros((1, 10), jnp.int32), CFG, 10)
    with pytest.raises(ValueError, match="at least one token"):
        generate(params, jnp.zeros((1, 0), jnp.int32), CFG, 4)
