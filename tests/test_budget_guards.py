"""Budget guards in tier-1: the IR lint over the REAL trainer/serving
step programs, the collective census vs scripts/comm_budget.json, the
ZeRO-1 parity proof, the shard lint's compiled-placement census vs
scripts/shard_budget.json (+ the no-unattributed-resharding
invariant), the contract census vs scripts/obs_schema.json, and the
compile-count guard — so a budget regression fails the fast gate, not
a reviewer's eyeball.
"""

import os
import subprocess
import sys

import pytest

from distkeras_tpu.analysis import ir_lint, shard_lint
from distkeras_tpu.analysis.targets import (ZERO1_PARITY_PAIRS,
                                             ZERO_PARITY_TARGETS,
                                             default_targets)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def linted():
    """(spec, findings, census, placements) per standard target —
    traced, lowered and compiled ONCE for the whole module; the IR
    findings, the collective census, the shard lint's placement census
    and the resharding findings all read the same artifacts."""
    from distkeras_tpu.analysis.findings import (apply_baseline,
                                                 load_baseline)

    ledger = load_baseline(
        os.path.join(ROOT, "scripts", "lint_baseline.json"))
    out = {}
    for spec in default_targets():
        art = ir_lint.trace_target(spec)
        findings, census = ir_lint.lint_trace(spec, artifacts=art)
        findings += shard_lint.reshard_findings(spec, art.hlo)
        # The checked-in warn ledger applies exactly as CI applies it
        # (keys are rule:path, so per-target application is exact).
        findings = apply_baseline(findings, ledger)
        placements = shard_lint.placement_census(spec, art)
        out[spec.name] = (spec, findings, census, placements)
    return out


def test_standard_targets_cover_every_family(linted):
    names = set(linted)
    for required in ("adag_dp/accum_step", "adag_zero1/accum_step",
                     "adag_zero2/accum_step", "adag_zero3/accum_step",
                     "adag_adasum/accum_step",
                     "adag_localsgd4/accum_step",
                     "lmtrainer_dp/train_step",
                     "lmtrainer_zero1/train_step",
                     "lmtrainer_zero2/train_step",
                     "lmtrainer_zero3/train_step",
                     "lmtrainer_fsdp/train_step",
                     "lmtrainer_int8ef/train_step",
                     "lmtrainer_rulesef/train_step",
                     "lmtrainer_zero1_int8ef/train_step",
                     "continuousbatcher_per_request/decode_step",
                     "speculativebatcher_sampled/step"):
        assert required in names, names


def test_ir_lint_clean_on_real_programs(linted):
    gating = [f.format() for (_, fs, _, _) in linted.values()
              for f in fs if f.gating]
    assert not gating, gating


def test_comm_budget_matches_recorded(linted):
    budgets = ir_lint.load_budgets(
        os.path.join(ROOT, "scripts", "comm_budget.json"))
    drift = []
    for name, (_, _, census, _) in linted.items():
        drift += [f.format()
                  for f in ir_lint.check_budget(name, census, budgets)]
    assert not drift, drift


def test_shard_budget_matches_recorded(linted):
    """The placement census — every tensor's compiled sharding and the
    per-device byte ledger — matches scripts/shard_budget.json exactly
    for every standard target (re-record intentional changes with
    graph_lint.py --update-budgets; the JSON diff IS the placement
    review)."""
    budgets = shard_lint.load_shard_budgets(
        os.path.join(ROOT, "scripts", "shard_budget.json"))
    drift = []
    for name, (_, _, _, placements) in linted.items():
        drift += [f.format() for f in shard_lint.check_shard_budget(
            name, placements, budgets)]
    assert not drift, drift
    # ... and the budget has no stale targets the suite stopped tracing.
    assert set(budgets) == set(linted)


def test_no_unattributed_resharding_beyond_ledger(linted):
    """The resharding invariant: every compiled all-gather /
    collective-permute / all-to-all is either attributable to a
    declared scope or covered by the explicitly-justified
    lint_baseline.json ledger (the CPU partitioner's hierarchical
    AR+permute spelling and the fsdp/zero3 gather-on-use
    materializations — docs/graph_lint.md); anything NEW gates."""
    for name, (_, fs, _, placements) in linted.items():
        reshard = [f for f in fs if f.rule == "resharding-collective"]
        gating = [f.format() for f in reshard if f.gating]
        assert not gating, (name, gating)
        # The census pins the attribution counts too: baselined debt
        # and census must agree.
        assert placements["resharding"]["unattributed"] == len(reshard)
    # The pod-sharded serve path is fully attributed: its per-token
    # collectives are the declared psums, nothing GSPMD snuck in.
    tp2 = linted["continuousbatcher_greedy_tp2/decode_step"][3]
    assert tp2["resharding"]["unattributed"] == 0


def test_placement_census_cross_checks_live_memory_footprint(linted):
    """The per-device byte ledger is not self-referential: for the
    pod-sharded serving engine the census's per-device bytes for the
    closed-over parameters and the KV cache equal what
    engine.memory_footprint() reads off LIVE addressable shards — the
    same accounting the ~n×-per-device-bytes serving claim is asserted
    from (tests/test_serving_sharded.py), now with a static witness."""
    import jax

    import distkeras_tpu as dk
    from distkeras_tpu.analysis.targets import _lm_cfg
    from distkeras_tpu.models import transformer as tfm
    from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh
    from distkeras_tpu.parallel.sharding import serving_plan

    cfg = _lm_cfg()
    params = tfm.init_params(jax.random.key(0), cfg)
    mesh = make_mesh(MeshSpec(data=4, model=2))
    eng = dk.ContinuousBatcher(params, cfg, lanes=2, prompt_buckets=(8,),
                               plan=serving_plan(), mesh=mesh)
    fp = eng.memory_footprint()
    census = linted["continuousbatcher_greedy_tp2/decode_step"][3]
    t = census["tensors"]
    const_dev = sum(v[2] for k, v in t.items() if k.startswith("const/"))
    cache_dev = sum(v[2] for k, v in t.items() if k.startswith("args/0/"))
    assert const_dev == fp["param_bytes_per_device"]
    assert cache_dev == fp["kv_bytes_per_device"]
    # The n× claim's static spelling: sharded per-device bytes strictly
    # below the replicated total.
    assert census["bytes_per_device"] < census["bytes_global"]


def test_zero_placement_ledger_static_witness(linted):
    """The ZeRO per-device-state claims, witnessed statically from the
    placement census: the zero3 step's persistent state (args) holds
    ~1/8 of the dp step's bytes per device (params + moments all
    scattered P('data', None)), zero1 sits between (moments only),
    and the batch args are identical — so the ledger, not a live-run
    measurement, pins the 8× direction."""
    def state_dev(name):
        t = linted[name][3]["tensors"]
        return sum(v[2] for k, v in t.items()
                   if k.startswith("args/0/"))

    dp = state_dev("adag_dp/accum_step")
    z1 = state_dev("adag_zero1/accum_step")
    z3 = state_dev("adag_zero3/accum_step")
    assert z3 < z1 < dp
    # All three hold the same global bytes; only placement differs.
    assert (linted["adag_zero3/accum_step"][3]["bytes_global"]
            == linted["adag_dp/accum_step"][3]["bytes_global"])
    # zero3 scatters params AND moments: > 2/3 of dp's per-device
    # state is gone (the exact figure is pinned byte-for-byte in
    # shard_budget.json; this is the direction-proof).
    assert z3 < dp / 3
    # Placement spelling: every zero3 tv leaf is P('data', None).
    t3 = linted["adag_zero3/accum_step"][3]["tensors"]
    tvs = [v for k, v in t3.items() if k.startswith("args/0/tv/")]
    assert tvs and all(v[1] == "P('data', None)" for v in tvs)


def test_adag_zero1_compiled_wire_equals_dp(linted):
    """On the MLP flagship the parity holds at the COMPILED level
    outright: total per-device wire bytes of the zero1 step (RS-
    canonicalized AR + explicit AG) == the replicated-DP step's
    all-reduces, to the byte."""
    dp = ir_lint.census_wire_total(linted["adag_dp/accum_step"][2])
    z1 = ir_lint.census_wire_total(linted["adag_zero1/accum_step"][2])
    assert dp == z1 > 0


def test_zero_parity_proof_every_stage_both_families(linted):
    """The acceptance check, extended to stages 2/3: for ADAG and
    LMTrainer at every ZeRO stage, the step's DECLARED exchange is
    pad-free (scatter == gather == parameter bytes per program
    occurrence: stage 1's RS+AG, stage 2's in-scan accumulator RS +
    update AG, stage 3's gather-on-use AG + backward grad RS), hence
    by the ring identity the per-round wire never exceeds the gradient
    all-reduce it replaces — asserted against each DP partner's
    compiled census."""
    for z_name, dp_name, _stage in ZERO_PARITY_TARGETS:
        spec = linted[z_name][0]
        findings = ir_lint.check_zero1_parity(spec, linted[dp_name][2])
        gating = [f.format() for f in findings if f.gating]
        assert not gating, (z_name, gating)


def test_declared_exchange_measures_param_bytes(linted):
    for z_name, _dp, stage in ZERO_PARITY_TARGETS:
        spec = linted[z_name][0]
        assert spec.zero_stage == stage
        decl = ir_lint.declared_zero_exchange(spec)
        assert decl["rs_bytes"] == decl["ag_bytes"] == spec.params_bytes
    # Stage-1 pairs keep their historical spelling too.
    assert ZERO1_PARITY_PAIRS == (
        ("adag_zero1/accum_step", "adag_dp/accum_step"),
        ("lmtrainer_zero1/train_step", "lmtrainer_dp/train_step"))


def test_lm_dp_tied_embedding_grads_summed_before_exchange(linted):
    """PR 3's parity machinery discovered replicated-DP LM all-reduced
    the tied embedding's two gradient contributions separately (8 KiB
    per step redundant); PR 4 sums them locally before ONE pmean per
    leaf (LMTrainer._dp_local_value_and_grad).  Pinned: the DP census
    carries exactly parameter-bytes of gradient all-reduce and the
    `comm-redundant-ar` rule — now promoted to warn, so a regression
    gates — stays silent."""
    spec = linted["lmtrainer_zero1/train_step"][0]
    findings = ir_lint.check_zero1_parity(
        spec, linted["lmtrainer_dp/train_step"][2])
    assert not any(f.rule == "comm-redundant-ar" for f in findings)
    assert not [f.format() for f in findings if f.gating]


def test_int8ef_cuts_gradient_wire_to_quarter(linted):
    """The lowcomm acceptance claim, from the COMPILED census: the
    int8-EF step's GRADIENT payload crosses the wire as s8 at exactly
    <= 1/4 the f32 baseline's gradient wire bytes (a codec that
    decompressed before the collective would show f32 payloads at full
    size — the per-dtype census field exists to catch that), and the
    f32 remnant — the per-bucket quantization scales — is declared and
    o(1): under 1% of the compressed payload, leaving the whole step
    within 1% of the quarter."""
    ef_census = linted["lmtrainer_int8ef/train_step"][2]
    dp_census = linted["lmtrainer_dp/train_step"][2]

    def grad_wire(census):  # everything but the scalar loss pmean
        return sum(c.wire_bytes for c in census if c.payload_bytes > 4)

    dp_grad = grad_wire(dp_census)
    s8 = sum(c.wire_bytes for c in ef_census if "s8" in c.dtype)
    assert 0 < s8 <= dp_grad / 4, (s8, dp_grad)
    f32_scales = grad_wire(ef_census) - s8
    assert 0 <= f32_scales <= 0.01 * s8, (f32_scales, s8)
    ef = ir_lint.census_wire_total(ef_census)
    dp = ir_lint.census_wire_total(dp_census)
    assert ef <= 1.01 * dp / 4, (ef, dp)
    # zero1 x int8 compresses the reduce-scatter leg: its s8 payload
    # must appear in the compiled program too.
    z1ef = linted["lmtrainer_zero1_int8ef/train_step"][2]
    assert any("s8" in c.dtype for c in z1ef)


def test_codec_rules_census_pins_per_bucket_wire_dtypes(linted):
    """The per-bucket codec rules claim, from the COMPILED census: the
    (emb -> topk, .* -> int8) LM exchange moves an s8 payload for the
    int8 buckets AND the top-k (values, indices) legs for the
    embedding bucket — both wire dtypes visible in one program, which
    a uniform codec can never produce."""
    census = linted["lmtrainer_rulesef/train_step"][2]
    dtypes = {c.dtype for c in census}
    assert any("s8" in d for d in dtypes), dtypes      # int8 buckets
    assert any("s32" in d for d in dtypes), dtypes     # top-k indices
    # The s8 payload must be the dominant gradient wire (dense
    # leaves), the top-k legs the small remainder.
    s8 = sum(c.wire_bytes for c in census if "s8" in c.dtype)
    assert s8 > 0


def test_zero3_census_has_no_update_gather(linted):
    """Stage 3's structural claim from the compiled census: the
    gather-on-use program all-gathers the PARAMETERS (per fusion
    bucket, gradient-sized payloads) but has no update all-gather leg
    beyond them — params stay scattered across steps — while stage 1's
    program gathers the packed update as one fused ``[n, P/n]``
    payload.  Pinned: zero3's largest all-gather payload is a bucket,
    not the whole packed update."""
    z1 = linted["adag_zero1/accum_step"][2]
    z3 = linted["adag_zero3/accum_step"][2]
    z1_ag = max(c.payload_bytes for c in z1 if c.op == "all-gather")
    z3_ag = max(c.payload_bytes for c in z3 if c.op == "all-gather")
    P = linted["adag_zero3/accum_step"][0].params_bytes
    assert z1_ag == P          # stage 1: one packed update gather
    assert z3_ag < P, (z3_ag, P)  # stage 3: bucket-granular param AGs
    ag_total = sum(c.payload_bytes * c.count for c in z3
                   if c.op == "all-gather")
    assert ag_total == P       # ...that together cover the params once


def test_localsgd_quarters_per_step_collective_count(linted):
    """The other lowcomm acceptance claim: the sync_every=4 ADAG round
    program covers FOUR optimizer steps with ONE merge's collectives,
    so the per-optimizer-step collective count is exactly its census
    count / 4 — pinned at <= 1/4 of the synchronous step's count (the
    merge itself is bucket-fused, so it is no chattier than one
    synchronous exchange)."""
    dp_count = sum(c.count
                   for c in linted["adag_dp/accum_step"][2])
    ls_count = sum(c.count
                   for c in linted["adag_localsgd4/accum_step"][2])
    # For H=4 this IS the acceptance bound (ls_count/H <= dp_count/4
    # rearranges to ls_count <= dp_count), asserted strictly: the
    # whole 4-optimizer-step round must run FEWER collectives than one
    # synchronous step (recorded: 3 vs 5).
    assert ls_count < dp_count, (ls_count, dp_count)


def test_serving_steps_have_no_collectives(linted):
    """The unsharded decode steps must stay collective-free — a
    collective appearing here means the engine started resharding
    per token."""
    for name in ("continuousbatcher_per_request/decode_step",
                 "speculativebatcher_sampled/step"):
        assert linted[name][2] == []


def test_compile_count_guard_passes():
    """The recompile guard (scripts/check_compile_counts.py) over
    every recorded session — zero1/device_data/exchange-variant
    trainers and the serving engines included — as a subprocess with
    its own deterministic mesh."""
    r = subprocess.run(
        [sys.executable,
         os.path.join(ROOT, "scripts", "check_compile_counts.py")],
        capture_output=True, text=True, timeout=540,
        cwd=ROOT)
    assert r.returncode == 0, r.stdout + "\n" + r.stderr


def test_obs_schema_matches_recorded():
    """The contract census — every emission site's name/kind/labels,
    the dynamic-name allowlist, the scenario-event sweep, and the wire
    route census — matches scripts/obs_schema.json exactly (re-record
    intentional changes with graph_lint.py --update-budgets; the JSON
    diff IS the contract review)."""
    from distkeras_tpu.analysis import contract_lint

    built = contract_lint.build_obs_schema(ROOT)
    pinned = contract_lint.load_obs_schema(
        os.path.join(ROOT, "scripts", "obs_schema.json"))
    assert pinned is not None, (
        "scripts/obs_schema.json missing — run graph_lint.py "
        "--contracts --update-budgets")
    assert built == pinned


def test_graph_lint_cli_source_only_runs_clean():
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "graph_lint.py"),
         "--source-only"],
        capture_output=True, text=True, timeout=300, cwd=ROOT)
    assert r.returncode == 0, r.stdout + "\n" + r.stderr


def test_adag_device_data_hook_covers_indexed_step():
    """device_data trainers hand the lint their REAL indexed-step
    program (single-process form), not the streaming one."""
    from distkeras_tpu.analysis.targets import (_mlp_dataset,
                                                 _mlp_trainer)

    t = _mlp_trainer(zero1=False)
    t.device_data = True  # _supports_device_data on ADAG
    spec = t.traced_for_analysis(_mlp_dataset())[0]
    assert spec.name == "adag_dp_device_data/accum_step"
    # Four args: state, staged X, staged Y, index block.
    assert len(spec.args) == 4
    findings, _ = ir_lint.lint_trace(spec, compile_census=False)
    assert not [f.format() for f in findings if f.gating]
