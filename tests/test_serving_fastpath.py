"""Round-10 decode fast path: the serving/ package split (import
compatibility), chunked prefill interleaved with decode, and the
multi-prefix KV PrefixPool on both engines.

The exact-parity contract is the same as tests/test_serving.py's:
every request matches its solo generate()/prompt_cache run bit for
bit; the new machinery (chunk scheduling, pool gathers) must be
invisible in the emitted tokens.
"""

import importlib
import os

import jax
import numpy as np
import pytest

from distkeras_tpu import obs
from distkeras_tpu.models import transformer as tfm
from distkeras_tpu.models.generate import generate, prefill
from distkeras_tpu.serving import (ContinuousBatcher, PrefixPool,
                                   SpeculativeBatcher)

CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=64, rope=True)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(jax.random.key(0), CFG)


def run_to_done(eng, lane):
    while lane in eng.running():
        eng.step()
    return eng.drain(lane)


def solo(params, prompt, n, **kw):
    return np.asarray(generate(params, np.asarray(prompt)[None], CFG,
                               n, **kw))[0]


# ------------------------------------------------------- package split


def test_package_split_import_compat():
    """serving.py is gone; the serving/ package re-exports the exact
    public API at the old import path, and each split module imports
    on its own."""
    import distkeras_tpu
    import distkeras_tpu.serving as serving

    root = os.path.dirname(distkeras_tpu.__file__)
    assert not os.path.exists(os.path.join(root, "serving.py"))
    assert os.path.isdir(os.path.join(root, "serving"))
    for name in ("ContinuousBatcher", "SpeculativeBatcher",
                 "RequestResult", "QueueFull", "EngineClosed",
                 "PrefixPool"):
        assert name in serving.__all__, name
        assert getattr(serving, name) is not None
    for mod in ("engine", "lanes", "admission", "speculative",
                "elastic", "prefix"):
        m = importlib.import_module(f"distkeras_tpu.serving.{mod}")
        assert m is not None
    # The resilience-owned types are the SAME objects on every path.
    from distkeras_tpu.resilience.admission import QueueFull as RQ
    assert serving.QueueFull is RQ is distkeras_tpu.QueueFull
    assert distkeras_tpu.ContinuousBatcher is serving.ContinuousBatcher
    assert distkeras_tpu.PrefixPool is serving.PrefixPool


# ------------------------------------------------------ chunked prefill


def test_chunked_prefill_parity_and_interleave(params, rng):
    """A prompt longer than prefill_chunk admits in chunks between
    decode steps: the OTHER lane keeps emitting one token on EVERY
    step while the long prompt admits (the inter-token gap is bounded
    by one chunk), and both outputs match their solo runs exactly."""
    eng = ContinuousBatcher(params, CFG, lanes=2, prefill_chunk=8,
                            prompt_buckets=(8, 16))
    ps = rng.integers(0, 64, (4,)).astype(np.int32)
    pl = rng.integers(0, 64, (30,)).astype(np.int32)  # warm 29: 3+tail
    ls = eng.submit(ps, 24)
    for _ in range(2):
        eng.step()
    ll = eng.submit(pl, 8)               # parked, admits over steps
    assert ll in eng.running()           # running() covers admitting
    with pytest.raises(ValueError, match="still decoding"):
        eng.drain(ll)
    short_emissions = []
    while ll in eng.running():
        out = eng.step()
        short_emissions.append(len(out.get(ls, [])))
    # The short lane emitted on every step of the long admission.
    assert short_emissions and all(n == 1 for n in short_emissions)
    np.testing.assert_array_equal(eng.drain(ll), solo(params, pl, 8))
    np.testing.assert_array_equal(run_to_done(eng, ls),
                                  solo(params, ps, 24))


def test_chunked_prefill_1k_prompt_bounded_gap(rng):
    """The acceptance shape: a >= 1k-token prompt admitted mid-flight
    never blocks the other lane for more than one chunk step — the
    decoding lane emits exactly one token per step() through the whole
    8-chunk admission, and the long request's output still matches its
    solo run."""
    big = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_len=1056,
                                rope=True)
    bparams = tfm.init_params(jax.random.key(2), big)
    eng = ContinuousBatcher(bparams, big, lanes=2, prefill_chunk=128,
                            prompt_buckets=(8, 128))
    ps = rng.integers(0, 64, (4,)).astype(np.int32)
    pl = rng.integers(0, 64, (1025,)).astype(np.int32)  # warm = 1024
    ls = eng.submit(ps, 24)
    eng.step()
    ll = eng.submit(pl, 4)          # chunk 0 at submit, 7 interleaved
    assert len(eng._lane_state[ll].chunks) == 7
    gaps = []
    while ll in eng.running():
        out = eng.step()
        gaps.append(len(out.get(ls, [])))
    assert all(n == 1 for n in gaps[:7])   # one token per chunk step
    out_l = eng.drain(ll)
    np.testing.assert_array_equal(
        out_l, np.asarray(generate(bparams, pl[None], big, 4))[0])
    np.testing.assert_array_equal(
        run_to_done(eng, ls),
        np.asarray(generate(bparams, ps[None], big, 24))[0])


@pytest.mark.parametrize("chunk", [8, 16])
def test_chunked_prefill_sampled_and_tail_overlap(params, rng, chunk):
    """Chunked admission writes exactly the monolithic K/V: sampled
    requests replay their solo streams through awkward tail sizes
    (warm % chunk != 0 exercises the backed-up overlap tail)."""
    eng = ContinuousBatcher(params, CFG, lanes=1, prefill_chunk=chunk,
                            temperature=0.8, top_k=8,
                            prompt_buckets=(8,))
    for plen in (chunk + 2, 3 * chunk - 1):
        p = rng.integers(0, 64, (plen,)).astype(np.int32)
        k = jax.random.key(plen)
        lane = eng.submit(p, 6, key=k)
        np.testing.assert_array_equal(
            run_to_done(eng, lane),
            solo(params, p, 6, temperature=0.8, top_k=8, key=k))


def test_chunked_prefill_validation(params):
    with pytest.raises(ValueError, match="full-cache"):
        roll = tfm.TransformerConfig(vocab_size=64, d_model=32,
                                     n_heads=2, n_layers=2, d_ff=64,
                                     max_len=12, rope=True,
                                     attention_window=5)
        ContinuousBatcher(tfm.init_params(jax.random.key(1), roll),
                          roll, lanes=1, prefill_chunk=4)
    with pytest.raises(ValueError, match="prefill_chunk"):
        ContinuousBatcher(params, CFG, lanes=1, prefill_chunk=0)
    with pytest.raises(ValueError, match="prefill_chunk"):
        ContinuousBatcher(params, CFG, lanes=1, prefill_chunk=100)


def test_chunked_lane_evicted_mid_admission(params, rng):
    """A deadline that expires while a lane is still admitting evicts
    it cleanly: structured timeout, chunk queue drained, and the lane
    is immediately reusable with exact parity."""
    t = {"now": 0.0}
    eng = ContinuousBatcher(params, CFG, lanes=1, prefill_chunk=8,
                            prompt_buckets=(8,),
                            clock=lambda: t["now"])
    pl = rng.integers(0, 64, (30,)).astype(np.int32)
    lane = eng.submit(pl, 8, ttl=5.0)
    rid = eng.last_request_id
    assert eng._admitting            # parked mid-admission
    t["now"] = 10.0
    eng.step()                       # reap evicts the parked lane
    res = eng.take(rid)
    assert res.timed_out and not eng._admitting
    p2 = rng.integers(0, 64, (5,)).astype(np.int32)
    lane2 = eng.submit(p2, 6)
    np.testing.assert_array_equal(run_to_done(eng, lane2),
                                  solo(params, p2, 6))


# ---------------------------------------------------------- PrefixPool


def test_prefix_pool_refcount_lru_and_errors(params, rng):
    pool = PrefixPool(CFG, slots=2)
    segs = {}
    for name, n in (("a", 4), ("b", 6), ("c", 5)):
        pref = rng.integers(0, 64, (n,)).astype(np.int32)
        cache, _ = prefill(params, pref[None], CFG, last_logits=False)
        segs[name] = (pref, cache)
    ida = pool.put(segs["a"][1], 4)
    idb = pool.put(segs["b"][1], 6)
    assert len(pool) == 2 and pool.length_of(ida) == 4
    # LRU: touch a, insert c -> b (least recent, unreferenced) evicted.
    pool.acquire(ida)
    pool.release(ida)
    idc = pool.put(segs["c"][1], 5)
    assert idb not in pool and ida in pool and idc in pool
    with pytest.raises(KeyError, match="prefix_id"):
        pool.length_of(idb)          # stale id fails loudly
    # Pinned entries are never evicted: pin both, put must raise.
    pool.acquire(ida)
    pool.acquire(idc)
    with pytest.raises(RuntimeError, match="referenced"):
        pool.put(segs["b"][1], 6)
    pool.release(ida)
    pool.put(segs["b"][1], 6)        # unpinned LRU slot frees up
    assert ida not in pool and idc in pool
    # Validation: segment shape/quantization must match the pool spec.
    with pytest.raises(ValueError, match="spec"):
        qcache, _ = prefill(params, segs["a"][0][None], CFG,
                            last_logits=False, kv_int8=True)
        pool.put(qcache, 4)
    with pytest.raises(ValueError, match="length"):
        pool.put(segs["a"][1], 0)


def test_prefix_pool_engine_parity_and_zero_prefix_work(params, rng,
                                                        tmp_path):
    """Two distinct pooled prefixes on one engine: each request
    matches generate(tail, prompt_cache=(segment, P)) exactly, a
    plain request still works, and the admission span proves the
    prefix tokens ran NO prefill work (the admitted bucket covers only
    the tail, not prefix + tail)."""
    from distkeras_tpu.obs.trace import read_trace

    pool = PrefixPool(CFG, slots=2)
    pref_a = rng.integers(0, 64, (20,)).astype(np.int32)
    pref_b = rng.integers(0, 64, (6,)).astype(np.int32)
    ca, _ = prefill(params, pref_a[None], CFG, last_logits=False)
    cb, _ = prefill(params, pref_b[None], CFG, last_logits=False)
    ida, idb = pool.put(ca, 20), pool.put(cb, 6)
    eng = ContinuousBatcher(params, CFG, lanes=2, prefix_pool=pool,
                            prompt_buckets=(8,))
    tail = rng.integers(0, 64, (4,)).astype(np.int32)
    path = str(tmp_path / "admit.jsonl")
    with obs.session(trace_path=path):
        la = eng.submit(tail, 6, prefix_id=ida)
        lb = eng.submit(tail, 6, prefix_id=idb)
        assert pool.refs_of(ida) == pool.refs_of(idb) == 1
        oa, ob = run_to_done(eng, la), run_to_done(eng, lb)
    np.testing.assert_array_equal(
        oa, np.asarray(generate(params, tail[None], CFG, 6,
                                prompt_cache=(ca, 20)))[0])
    np.testing.assert_array_equal(
        ob, np.asarray(generate(params, tail[None], CFG, 6,
                                prompt_cache=(cb, 6)))[0])
    assert pool.refs_of(ida) == 0    # drain released the pin
    # Step accounting for "no prefill work for the prefix": the
    # 20-token prefix + 3 warm tokens admitted through the 8-wide
    # bucket.  Re-prefilling prefix+tail would need a >= 23-wide
    # program (the 64 bucket); bucket == 8 proves only the tail ran.
    admits = [r for r in read_trace(path)
              if r.get("name") == "serving.admit"]
    assert len(admits) == 2
    assert all(r["fields"]["bucket"] == 8 for r in admits)
    # Plain request on the pooled engine (slot -1 = zero seed).
    lp = eng.submit(tail, 6)
    np.testing.assert_array_equal(run_to_done(eng, lp),
                                  solo(params, tail, 6))
    # Stale prefix id at submit fails loudly.
    with pytest.raises(ValueError, match="needs"):
        ContinuousBatcher(params, CFG, lanes=1).submit(
            tail, 4, prefix_id=ida)


def test_prefix_pool_sampled_kv_int8_and_lane_reuse(params, rng):
    """kv_int8 engines pool kv_int8 segments (quantization-matched
    gather, scale slabs included): greedy AND sampled pooled requests
    match generate(prompt_cache=..., kv_int8=True), through lane
    reuse and the 1-token-prompt reseed path."""
    pool = PrefixPool(CFG, slots=2, kv_int8=True)
    pref = rng.integers(0, 64, (6,)).astype(np.int32)
    cache, _ = prefill(params, pref[None], CFG, last_logits=False,
                       kv_int8=True)
    pid = pool.put(cache, 6)
    with pytest.warns(RuntimeWarning, match="kv_int8"):
        eng = ContinuousBatcher(params, CFG, lanes=1, kv_int8=True,
                                prefix_pool=pool, prompt_buckets=(8,),
                                temperature=0.8,
                                per_request_sampling=True)
    for tail_len in (3, 1):          # 1: the pooled reseed path
        tail = rng.integers(0, 64, (tail_len,)).astype(np.int32)
        lane = eng.submit(tail, 5, temperature=0.0, prefix_id=pid)
        out = run_to_done(eng, lane)
        np.testing.assert_array_equal(
            out, np.asarray(generate(params, tail[None], CFG, 5,
                                     prompt_cache=(cache, 6),
                                     kv_int8=True))[0])
    tail = rng.integers(0, 64, (3,)).astype(np.int32)
    k = jax.random.key(17)
    lane = eng.submit(tail, 5, key=k, prefix_id=pid)
    np.testing.assert_array_equal(
        run_to_done(eng, lane),
        np.asarray(generate(params, tail[None], CFG, 5,
                            prompt_cache=(cache, 6), kv_int8=True,
                            temperature=0.8, key=k))[0])
    # Quantization mismatch between pool and engine rejects (before
    # the small-lane advisory is even reached).
    with pytest.raises(ValueError, match="kv_int8"):
        ContinuousBatcher(params, CFG, lanes=1, kv_int8=True,
                          prefix_pool=PrefixPool(CFG, slots=1))


def test_prefix_pin_taken_first_and_released_on_decline(params, rng):
    """The eviction race is closed by pinning BEFORE any slab access:
    while a pooled request occupies a lane its entry cannot be evicted
    by put() (pinned entries are never victims), and every declined
    or failed submit releases the pin it took."""
    pool = PrefixPool(CFG, slots=1)
    pref = rng.integers(0, 64, (6,)).astype(np.int32)
    cache, _ = prefill(params, pref[None], CFG, last_logits=False)
    pid = pool.put(cache, 6)
    eng = ContinuousBatcher(params, CFG, lanes=1, prefix_pool=pool,
                            prompt_buckets=(8,))
    tail = rng.integers(0, 64, (4,)).astype(np.int32)
    # Validation failure AFTER the pin releases it.
    with pytest.raises(ValueError, match="exceeds"):
        eng.submit(tail, 100, prefix_id=pid)
    assert pool.refs_of(pid) == 0
    lane = eng.submit(tail, 5, prefix_id=pid)
    assert pool.refs_of(pid) == 1
    # Engine-full decline releases its own pin, not the lane's.
    assert eng.submit(tail, 5, prefix_id=pid) is None
    assert pool.refs_of(pid) == 1
    # While the lane decodes, the pinned entry can NEVER be evicted.
    with pytest.raises(RuntimeError, match="referenced"):
        pool.put(cache, 6)
    run_to_done(eng, lane)
    assert pool.refs_of(pid) == 0
    pool.put(cache, 6)               # now evictable again


def test_prefix_pool_chunked_compose(params, rng):
    """prefill_chunk and prefix_pool compose: a long tail past a
    pooled prefix admits in chunks and still matches
    generate(prompt_cache=...)."""
    pool = PrefixPool(CFG, slots=1)
    pref = rng.integers(0, 64, (6,)).astype(np.int32)
    cache, _ = prefill(params, pref[None], CFG, last_logits=False)
    pid = pool.put(cache, 6)
    eng = ContinuousBatcher(params, CFG, lanes=1, prefix_pool=pool,
                            prefill_chunk=8, prompt_buckets=(8,))
    tail = rng.integers(0, 64, (25,)).astype(np.int32)  # warm 24: 3 ch
    lane = eng.submit(tail, 6, prefix_id=pid)
    np.testing.assert_array_equal(
        run_to_done(eng, lane),
        np.asarray(generate(params, tail[None], CFG, 6,
                            prompt_cache=(cache, 6)))[0])


# ------------------------------------------- SpeculativeBatcher prefix


def test_speculative_prefix_pool_greedy_parity(params, rng):
    """The v1 'no shared prefix' exclusion is lifted: pooled
    (target, draft) prefix pairs serve speculative lanes with exact
    greedy parity vs generate(prompt_cache=...) — including the
    1-token-prompt reseed (which needs the recorded last_token) — and
    refcounts release at drain."""
    draft_cfg = tfm.TransformerConfig(vocab_size=64, d_model=16,
                                      n_heads=2, n_layers=1, d_ff=32,
                                      max_len=64, rope=True)
    draft = tfm.init_params(jax.random.key(9), draft_cfg)
    pref = rng.integers(0, 64, (10,)).astype(np.int32)
    tca, _ = prefill(params, pref[None], CFG, last_logits=False)
    dca, _ = prefill(draft, pref[None], draft_cfg, last_logits=False)
    pool = PrefixPool(CFG, slots=2, draft_cfg=draft_cfg)
    pid = pool.put((tca, dca), 10, last_token=int(pref[-1]))
    pid_bare = pool.put((tca, dca), 10)      # no last_token recorded
    eng = SpeculativeBatcher(params, draft, CFG, draft_cfg, lanes=2,
                             n_draft=3, prefix_pool=pool,
                             prompt_buckets=(8,))
    tail = rng.integers(0, 64, (4,)).astype(np.int32)
    one = np.asarray([5], np.int32)
    la = eng.submit(tail, 6, prefix_id=pid)
    lb = eng.submit(one, 5, prefix_id=pid)
    assert pool.refs_of(pid) == 2
    oa, ob = run_to_done(eng, la), run_to_done(eng, lb)
    np.testing.assert_array_equal(
        oa, np.asarray(generate(params, tail[None], CFG, 6,
                                prompt_cache=(tca, 10)))[0])
    np.testing.assert_array_equal(
        ob, np.asarray(generate(params, one[None], CFG, 5,
                                prompt_cache=(tca, 10)))[0])
    assert pool.refs_of(pid) == 0
    # Budget counts the prefix: 10 + 4 + 50 - 1 > cap(60) rejects.
    with pytest.raises(ValueError, match="prefix"):
        eng.submit(tail, 50, prefix_id=pid)
    # 1-token prompt without a recorded last_token fails loudly.
    with pytest.raises(ValueError, match="last token"):
        eng.submit(one, 5, prefix_id=pid_bare)
    # A plain (no-prefix) request on the pooled engine still matches.
    lc = eng.submit(tail, 6)
    np.testing.assert_array_equal(run_to_done(eng, lc),
                                  solo(params, tail, 6))


def test_speculative_pool_validation(params, rng):
    draft_cfg = tfm.TransformerConfig(vocab_size=64, d_model=16,
                                      n_heads=2, n_layers=1, d_ff=32,
                                      max_len=64, rope=True)
    draft = tfm.init_params(jax.random.key(9), draft_cfg)
    with pytest.raises(ValueError, match="speculative pool"):
        SpeculativeBatcher(params, draft, CFG, draft_cfg,
                           prefix_pool=PrefixPool(CFG, slots=1))
    with pytest.raises(ValueError, match="plain PrefixPool"):
        ContinuousBatcher(params, CFG, prefix_pool=PrefixPool(
            CFG, slots=1, draft_cfg=draft_cfg))
    with pytest.raises(ValueError, match="full-cache"):
        PrefixPool(tfm.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
            max_len=12, rope=True, attention_window=5), slots=1)


# ------------------------------------------------------ kv_int8 advice


def test_kv_int8_small_lane_advisory(params, tmp_path):
    """Construction-time advisory: kv_int8 below the documented
    cache-bound regime (−15% at b8, serving_guide byte-lever table)
    warns and records an obs event; at/above the threshold it is
    silent."""
    from distkeras_tpu.obs.trace import read_trace
    from distkeras_tpu.serving.lanes import KV_INT8_LANE_ADVISORY

    path = str(tmp_path / "adv.jsonl")
    with obs.session(trace_path=path):
        with pytest.warns(RuntimeWarning, match="kv_int8"):
            ContinuousBatcher(params, CFG, lanes=2, kv_int8=True)
    evs = [r for r in read_trace(path)
           if r.get("name") == "serving.advisory"]
    assert len(evs) == 1
    assert evs[0]["fields"]["kind"] == "kv_int8_small_lanes"
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("error")
        ContinuousBatcher(params, CFG, lanes=KV_INT8_LANE_ADVISORY,
                          kv_int8=True, prompt_buckets=(8,))


# --------------------------------------------------- elastic composure


def test_elastic_chunked_pool_enqueue(params, rng):
    """Elastic tiers compose with chunked prefill + the pool: a long
    pooled request enqueued under load admits in chunks across a tier
    step-up and finishes with exact parity."""
    pool = PrefixPool(CFG, slots=1)
    pref = rng.integers(0, 64, (6,)).astype(np.int32)
    cache, _ = prefill(params, pref[None], CFG, last_logits=False)
    pid = pool.put(cache, 6)
    eng = ContinuousBatcher(params, CFG, lane_tiers=(1, 2),
                            max_queue=1, scale_up_after=1,
                            scale_down_after=4, prompt_buckets=(8,),
                            prefill_chunk=8, prefix_pool=pool)
    long_tail = rng.integers(0, 64, (20,)).astype(np.int32)
    short = rng.integers(0, 64, (3,)).astype(np.int32)
    rids = [eng.enqueue(long_tail, 5, prefix_id=pid),
            eng.enqueue(short, 5),
            eng.enqueue(short, 5)]
    while any(eng.poll(r) is None for r in rids):
        eng.step()
    res = [eng.take(r) for r in rids]
    assert all(r.ok for r in res)
    np.testing.assert_array_equal(
        res[0].tokens,
        np.asarray(generate(params, long_tail[None], CFG, 5,
                            prompt_cache=(cache, 6)))[0])
    np.testing.assert_array_equal(res[1].tokens,
                                  solo(params, short, 5))
