"""BatchNorm under data parallelism: statistics are globally exact.

The reference cannot sync BN across workers at all (each Spark worker's
model normalizes over its local minibatch; SURVEY.md §7.3 flags BN as
the ResNet-50 hard part).  Under this framework's pjit DP the batch
axis is sharded but the program is global — jnp.mean over the batch IS
the global mean, with XLA inserting the collectives.  This test pins
that: training a BN model on the 8-device mesh must produce the same
weights and running statistics as the same global batch on one device.
"""

import numpy as np

import distkeras_tpu as dk
from tests.conftest import make_blobs


def bn_mlp(dim=16, classes=4, seed=0):
    import keras

    keras.utils.set_random_seed(seed)
    return keras.Sequential([
        keras.Input((dim,)),
        keras.layers.Dense(32),
        keras.layers.BatchNormalization(),
        keras.layers.ReLU(),
        keras.layers.Dense(classes),
    ])


def _train(num_workers, devices):
    x, y = make_blobs(n=512)
    ds = dk.Dataset.from_arrays(x, y)
    t = dk.ADAG(bn_mlp(), loss="sparse_categorical_crossentropy",
                worker_optimizer="sgd", learning_rate=0.05,
                batch_size=64 // num_workers, communication_window=2,
                num_epoch=2, num_workers=num_workers)
    model = t.train(ds)
    return model, t


def test_batchnorm_dp_matches_single_device(devices):
    m1, t1 = _train(1, devices)
    m8, t8 = _train(8, devices)
    # Same global batch (64) either way -> identical training incl. the
    # BN running mean/var (non-trainable state).
    np.testing.assert_allclose(t1.history, t8.history, atol=1e-4, rtol=1e-4)
    for w1, w8 in zip(m1.get_weights(), m8.get_weights()):
        np.testing.assert_allclose(w1, w8, atol=1e-4, rtol=1e-4)
