"""bench.py's one-line JSON contract, including the last-green record
that carries evidence through accelerator-tunnel outages (round-3
verdict: the driver's BENCH artifact was null two rounds running while
green same-day measurements existed only in prose)."""

import json
import os
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
for p in (ROOT, os.path.join(ROOT, "scripts")):
    if p not in sys.path:
        sys.path.insert(0, p)


def test_last_green_roundtrip(tmp_path):
    from bench_suite import read_last_green, update_last_green

    p = str(tmp_path / "lg.json")
    assert read_last_green(path=p) is None
    update_last_green({"metric": "a", "value": 1.5, "unit": "u"},
                      path=p, device="TPU v5e")
    update_last_green({"metric": "b", "value": 2.0}, path=p)
    update_last_green({"metric": "a", "value": 3.0}, path=p)  # overwrite
    rec = read_last_green(path=p)
    assert sorted(rec["entries"]) == ["a", "b"]
    a = read_last_green("a", path=p)
    assert a["value"] == 3.0 and "measured_utc" in a
    assert read_last_green("missing", path=p) is None
    # Corrupt file: helpers degrade to None / fresh record, never raise.
    (tmp_path / "lg.json").write_text("{not json")
    assert read_last_green(path=p) is None
    update_last_green({"metric": "c", "value": 1.0}, path=p)
    assert read_last_green("c", path=p)["value"] == 1.0


def test_repo_seed_record_is_readable():
    """The committed BENCH_LAST_GREEN.json (seeded from the round-3
    measured green window) parses and names the headline metric."""
    from bench_suite import read_last_green

    entry = read_last_green("cifar_cnn_train_throughput")
    assert entry is not None
    assert entry["value"] and entry["unit"] == "samples/sec/chip"
    assert "measured_utc" in entry


def test_bench_probe_failure_skips_with_last_green(monkeypatch, capsys):
    """When the device probe fails/hangs, bench.py emits a structured
    ``status: skipped`` record and exits 0 — an environment outage must
    not read as a repo regression (BENCH_r05: rc=1 poisoned the run) —
    while keeping the null-value contract AND the prior green
    measurement, clearly labeled."""
    import bench
    import bench_suite

    monkeypatch.setattr(bench, "_probe_with_retries",
                        lambda *a, **k: "tunnel down (test)")
    prior = {"metric": "cifar_cnn_train_throughput", "value": 42.0,
             "measured_utc": "2026-01-01T00:00:00Z"}
    monkeypatch.setattr(bench_suite, "read_last_green",
                        lambda *a, **k: dict(prior))
    with pytest.raises(SystemExit) as e:
        bench.main()
    assert e.value.code == 0
    line = json.loads(capsys.readouterr().out.strip())
    assert line["status"] == "skipped"
    assert line["value"] is None and line["vs_baseline"] is None
    assert line["error"] == "tunnel down (test)"
    assert line["last_green"]["value"] == 42.0
    assert "NOT this run" in line["last_green"]["note"]


def test_bench_skip_line_without_record(monkeypatch, capsys):
    """No last-green record: the skip line is exactly the documented
    key set (no fabricated evidence), still rc=0."""
    import bench
    import bench_suite

    monkeypatch.setattr(bench, "_probe_with_retries",
                        lambda *a, **k: "tunnel down (test)")
    monkeypatch.setattr(bench_suite, "read_last_green",
                        lambda *a, **k: None)
    with pytest.raises(SystemExit) as e:
        bench.main()
    assert e.value.code == 0
    line = json.loads(capsys.readouterr().out.strip())
    assert "last_green" not in line
    assert line["value"] is None
    assert line["status"] == "skipped"


def test_engine_load_fields_mean_what_they_say(monkeypatch):
    """Round-4 verdict: bench_engine_load returned per-request makespan
    as the tuple element main() prints under "ms_per_token".  Contract
    now: that element is aggregate per-token wall time (1/value), the
    per-request figure lives under its own ``ms_per_request`` key, and
    the window quantization of the latency percentiles is announced as
    ``ttft_granularity_ms`` (window x median TPOT)."""
    import bench_serving as bs
    from distkeras_tpu.models import transformer as tfm

    tiny = tfm.TransformerConfig(
        vocab_size=64, d_model=16, n_heads=2, n_layers=1, d_ff=32,
        max_len=33, dtype="float32", rope=True)
    monkeypatch.setattr(bs, "_cfg", lambda window=None: tiny)

    run = bs.bench_engine_load(lanes=2, offered_rps=200.0)
    rate, step_s, _, extras = run(n_req=3, p_len=8, new=6, window=2)

    assert rate > 0
    # ms_per_token really is per token: the tuple element inverts the
    # achieved aggregate token rate.
    assert abs(rate * step_s - 1.0) < 1e-9
    assert extras["ms_per_request"] > 0
    # Makespan/request covers a whole 6-token request plus queueing —
    # it must dominate the per-token figure it used to masquerade as.
    assert extras["ms_per_request"] > step_s * 1e3
    assert extras["ttft_granularity_ms"] == pytest.approx(
        extras["tpot_p50_ms"] * 2, rel=0.02, abs=0.2)
    for key in ("ttft_p50_ms", "ttft_p99_ms", "tpot_p50_ms",
                "tpot_p99_ms", "achieved_rps"):
        assert key in extras


def _tiny_serving_cfg():
    from distkeras_tpu.models import transformer as tfm

    return tfm.TransformerConfig(
        vocab_size=64, d_model=16, n_heads=2, n_layers=1, d_ff=32,
        max_len=48, dtype="float32", rope=True)


def test_bench_longprompt_rows_report_step_gap(monkeypatch):
    """Round-10 rows: engine_longprompt_{monolithic,chunked} report
    the decoding lanes' step-gap percentiles and self-scale the chunk
    width to the config (the flagship's 128 would not even construct
    on a small cache)."""
    import bench_serving as bs

    monkeypatch.setattr(bs, "_cfg", lambda window=None:
                        _tiny_serving_cfg())
    for chunk in (None, 128):
        run = bs.bench_longprompt(chunk)
        rate, step_s, _, extras = run(p_short=6, p_long=30, new=12,
                                      long_new=4)
        assert rate > 0 and abs(rate * step_s - 1.0) < 1e-9
        for key in ("step_gap_p50_ms", "step_gap_p99_ms",
                    "step_gap_max_ms", "prefill_chunk"):
            assert key in extras
        if chunk is not None:
            # Self-scaled: 48 // 8 = 6, never the flagship 128.
            assert extras["prefill_chunk"] == 6


def test_bench_prefix_reuse_reports_speedup(monkeypatch):
    import bench_serving as bs

    monkeypatch.setattr(bs, "_cfg", lambda window=None:
                        _tiny_serving_cfg())
    run = bs.bench_prefix_reuse(2)
    rate, step_s, _, extras = run(prefix_len=8, tail_len=4, n_req=6,
                                  new=4)
    assert rate > 0
    assert extras["n_prefixes"] == 2
    assert extras["noreuse_tok_s"] > 0
    assert extras["reuse_speedup"] > 0


def test_bench_load_elastic_and_spec_rows(monkeypatch):
    """The PR-5 load-sweep follow-ups: the elastic row drives the
    enqueue/poll flow (QueueFull retried, tier trajectory reported),
    the speculative row reports TTFT/TPOT percentiles."""
    import bench_serving as bs

    monkeypatch.setattr(bs, "_cfg", lambda window=None:
                        _tiny_serving_cfg())
    rate, _, _, extras = bs.bench_engine_load_elastic(
        (1, 2), 400.0)(n_req=4, p_len=6, new=5, window=1)
    assert rate > 0 and extras["ok"] == 4
    assert extras["final_lanes"] in (1, 2)
    for key in ("request_p50_ms", "request_p99_ms", "tier_epoch"):
        assert key in extras
    rate, _, _, extras = bs.bench_engine_load_spec(
        2, 400.0)(n_req=3, p_len=6, new=5, n_draft=2)
    assert rate > 0 and not extras["degraded"]
    for key in ("ttft_p99_ms", "tpot_p50_ms", "n_draft"):
        assert key in extras


def test_bench_router_scale_row(monkeypatch):
    """Round-13 fleet row: router_scale_N drives the enqueue/poll
    load flow over N in-process replicas on per-replica step threads
    and reports achieved rps plus TTFT/TPOT percentiles off the obs
    histograms (needs the active session main() provides)."""
    import bench_serving as bs
    from distkeras_tpu import obs

    monkeypatch.setattr(bs, "_cfg", lambda window=None:
                        _tiny_serving_cfg())
    sess = obs.enable()
    try:
        rate, step_s, _, extras = bs.bench_router_scale(2)(
            n_req=4, p_len=6, new=5, lanes=1, per_replica_rps=200.0)
    finally:
        obs.disable()
    assert rate > 0 and abs(rate * step_s - 1.0) < 1e-9
    assert extras["replicas"] == 2 and extras["ok"] == 4
    for key in ("achieved_rps", "lanes_per_replica", "offered_rps",
                "ttft_p50_ms", "ttft_p99_ms", "tpot_p50_ms",
                "tpot_p99_ms"):
        assert key in extras


def test_bench_router_affinity_row(monkeypatch):
    """The affinity policy must beat (or tie, never lose to)
    round-robin on stem_hit_blocks over the SAME shuffled trace — the
    re-prefill work the cache-aware policy exists to avoid."""
    import bench_serving as bs

    monkeypatch.setattr(bs, "_cfg", lambda window=None:
                        _tiny_serving_cfg())
    rate, _, _, extras = bs.bench_router_affinity()(
        n_stems=2, reqs_per_stem=3, tail_len=4, new=4, lanes=2)
    assert rate > 0
    assert extras["affinity_hit_blocks"] > 0
    assert (extras["affinity_hit_blocks"]
            >= extras["round_robin_hit_blocks"])
    assert extras["round_robin_tok_s"] > 0
    assert _tiny_serving_cfg().max_len % extras["block"] == 0


def test_bench_router_disagg_row(monkeypatch):
    """Round-17 disaggregated-fleet row: role-split fleet vs the
    co-resident baseline on one trace — victims stream through
    Router.stream() under a storm, and the row must surface both
    fleets' streaming-TPOT percentiles plus the transfer-bytes /
    adoption-hit counters (obs session required, as main() provides),
    with the storm actually taking the ship->adopt hop."""
    import bench_serving as bs
    from distkeras_tpu import obs

    monkeypatch.setattr(bs, "_cfg", lambda window=None:
                        _tiny_serving_cfg())
    obs.enable()
    try:
        ratio, p99_s, _, extras = bs.bench_router_disagg()(
            n_storm=6, n_victims=2, storm_new=2, victim_new=6,
            lanes=2, n_stems=2, window=3)
    finally:
        obs.disable()
    assert ratio > 0 and p99_s > 0
    assert extras["storm_ok"] == 6 and extras["baseline_storm_ok"] == 6
    for key in ("tpot_p50_ms", "tpot_p99_ms", "baseline_tpot_p50_ms",
                "baseline_tpot_p99_ms", "ttft_p50_ms",
                "baseline_ttft_p50_ms", "storm_rps",
                "adoption_hit_rate", "transfer_mb", "warm_skips",
                "fallbacks"):
        assert key in extras
    # The storm must ride the disaggregated hop, not fall back: the
    # unique second block defeats the warm-skip gate on every request.
    assert extras["disagg_requests"] > 0
    assert extras["blocks_shipped"] > 0
    assert extras["transfer_mb"] > 0
    assert 0.0 <= extras["adoption_hit_rate"] <= 1.0


def test_bench_serving_probe_failure_skips_all_rows(monkeypatch,
                                                    capsys):
    """Round-14 small fix: bench_serving.py under a dead accelerator
    tunnel emits one ``status: skipped`` line per requested row (null
    value, last_green when a prior record exists) and exits 0 — the
    same poisoned-run hazard PR 2 fixed for the training bench."""
    import bench_serving as bs
    import bench_suite

    monkeypatch.setattr(bs, "_probe_with_retries",
                        lambda *a, **k: "tunnel down (test)")
    monkeypatch.setattr(
        bench_suite, "read_last_green",
        lambda name=None, **k: ({"metric": name, "value": 7.0}
                                if name == "engine_throughput"
                                else None))
    with pytest.raises(SystemExit) as e:
        bs.main(["engine_throughput", "engine_sharded_tp2"])
    assert e.value.code == 0
    lines = [json.loads(x) for x in
             capsys.readouterr().out.strip().splitlines()]
    assert [x["metric"] for x in lines] == ["engine_throughput",
                                           "engine_sharded_tp2"]
    for x in lines:
        assert x["status"] == "skipped"
        assert x["value"] is None and x["ms_per_token"] is None
        assert x["error"] == "tunnel down (test)"
    assert lines[0]["last_green"]["value"] == 7.0
    assert "NOT this run" in lines[0]["last_green"]["note"]
    assert "last_green" not in lines[1]


def test_bench_engine_sharded_row(monkeypatch):
    """Round-14 pod-sharded row: engine_sharded_tpN serves a real
    tiny-cfg workload on the 8-CPU mesh and reports per-device
    param+KV bytes (sharded AND solo — the ~tp× reduction must be
    visible in the row, not asserted in prose) plus TTFT/TPOT for
    both engines."""
    import bench_serving as bs

    monkeypatch.setattr(bs, "_cfg", lambda window=None:
                        _tiny_serving_cfg())
    rate, step_s, _, extras = bs.bench_engine_sharded(2)(
        n_req=4, p_len=6, new=5, lanes=2)
    assert rate > 0 and abs(rate * step_s - 1.0) < 1e-9
    assert extras["tp"] == 2
    # KV shards exactly 2x; params nearly (norm scales replicate).
    assert extras["solo_kv_mb_per_device"] == pytest.approx(
        extras["kv_mb_per_device"] * 2, rel=0.01)
    assert extras["bytes_reduction"] > 1.5
    for key in ("param_mb_per_device", "solo_param_mb_per_device",
                "ttft_p50_ms", "tpot_p50_ms", "solo_ttft_p50_ms",
                "solo_tpot_p50_ms", "solo_tok_s"):
        assert key in extras


def test_bench_engine_sharded_tp4_runs_when_heads_allow(monkeypatch):
    """tp4 needs n_heads % 4 == 0: a 4-head tiny cfg runs the real
    row on the 8-CPU mesh (data=2, model=4)."""
    import bench_serving as bs
    from distkeras_tpu.models import transformer as tfm

    cfg4 = tfm.TransformerConfig(
        vocab_size=64, d_model=16, n_heads=4, n_layers=1, d_ff=32,
        max_len=48, dtype="float32", rope=True)
    monkeypatch.setattr(bs, "_cfg", lambda window=None: cfg4)
    rate, _, _, extras = bs.bench_engine_sharded(4)(
        n_req=2, p_len=6, new=4, lanes=2)
    assert rate > 0 and extras["tp"] == 4
    assert extras["solo_kv_mb_per_device"] == pytest.approx(
        extras["kv_mb_per_device"] * 4, rel=0.01)


def test_bench_paged_rows(monkeypatch):
    """Round-12 paged-KV rows: the lanes-at-fixed-HBM row reports a
    >= 2x lane multiple at identical slab block counts, the shared-
    stem row reports refcounted block savings, and the CoW row
    reports fork vs whole-row-copy latency — all self-scaled to the
    config (block must divide max_len)."""
    import bench_serving as bs

    monkeypatch.setattr(bs, "_cfg", lambda window=None:
                        _tiny_serving_cfg())
    rate, step_s, _, extras = bs.bench_paged_lanes(4)(
        mono_lanes=2, p_len=6, new=4)
    assert rate > 0 and abs(rate * step_s - 1.0) < 1e-9
    assert extras["paged_lanes"] == extras["mono_lanes"] * 4
    # lanes_ratio is MEASURED peak concurrency, not the configured
    # constant — the >=2x acceptance claim must be falsifiable.
    assert extras["peak_lanes_paged"] <= extras["paged_lanes"]
    assert extras["peak_lanes_mono"] <= extras["mono_lanes"]
    assert extras["lanes_ratio"] >= 2.0
    assert extras["mono_tok_s"] > 0 and extras["slab_blocks"] > 0
    assert _tiny_serving_cfg().max_len % extras["block"] == 0

    rate, _, _, extras = bs.bench_paged_shared_stem(4)(
        stem_len=12, tail_len=4, new=4, lanes=2)
    assert rate > 0
    assert extras["blocks_saved"] > 0
    assert extras["noshare_tok_s"] > 0 and extras["share_speedup"] > 0

    ratio, fork_s, _, extras = bs.bench_paged_cow_fork()(
        p_len=8, warm_steps=2, iters=3)
    assert ratio > 0 and fork_s > 0
    assert extras["fork_ms"] > 0 and extras["cache_copy_ms"] > 0
    assert extras["bytes_ratio"] > 1


def test_bench_autoscale_row(monkeypatch):
    """Round-19 policy-vs-policy row: the SAME deterministic spike
    trace over static-min, static-max, and autoscaled fleets under
    the virtual clock.  The autoscaled leg must beat static-min on
    hot-window p99 TTFT while burning fewer replica-ticks than
    static-max, lose NOTHING, and reproduce its scaling-decision
    timeline on a repeat run (the `autoscale.decision` audit trail)."""
    import bench_serving as bs
    from distkeras_tpu import obs

    monkeypatch.setattr(bs, "_cfg", lambda window=None:
                        _tiny_serving_cfg())
    sess = obs.enable()
    try:
        value, p99_auto, _, extras = bs.bench_autoscale("spike")(
            ticks=16, min_replicas=1, max_replicas=2, lanes=2,
            steps_per_tick=3, spike_at=4, spike_len=5,
            spike_rate=7.0, base_rate=0.5)
    finally:
        obs.disable()
    assert value > 1.0, (
        f"autoscaled p99 TTFT did not beat static-min: {extras}")
    assert (extras["autoscaled_replica_ticks"]
            < extras["static_max_replica_ticks"]), (
        "elasticity burned as many replica-ticks as the static "
        f"maximum fleet: {extras}")
    assert extras["deterministic_timeline"], (
        "two same-seed runs produced different scaling decisions")
    assert extras["autoscaled_lost"] == 0
    assert extras["static_max_lost"] == 0
    assert extras["scale_ups"] >= 1
    assert extras["offered_requests"] > 0
    for key in ("static_min_ttft_p99_ticks", "scaling_changes",
                "autoscaled_ttft_p99_ticks", "shape"):
        assert key in extras
    assert p99_auto == extras["autoscaled_ttft_p99_ticks"]


def test_bench_canary_rollout_row(monkeypatch):
    """Round-20 live-push row: a canary promote lands mid-stream over
    in-flight requests.  Both legs must be per-version token-
    deterministic, the push must actually change the served tokens,
    and the victim TPOT ratio / rollout wall-clock must be finite."""
    import bench_serving as bs
    from distkeras_tpu import obs

    monkeypatch.setattr(bs, "_cfg", lambda window=None:
                        _tiny_serving_cfg())
    sess = obs.enable()
    try:
        ratio, rollout_s, _, extras = bs.bench_canary_rollout()(
            n_req=3, max_new=6, push_after=2, lanes=2)
    finally:
        obs.disable()
    assert extras["tokens_deterministic_per_version"], (
        "same-seed legs produced different token streams")
    assert extras["tokens_changed_at_push"], (
        "the mid-stream push left every token stream unchanged — the "
        "swap never landed")
    assert extras["rollout_wallclock_ms"] > 0
    # extras round to 3 decimals of a millisecond; compare in seconds
    # with the matching absolute slack.
    assert rollout_s == pytest.approx(
        extras["rollout_wallclock_ms"] / 1e3, abs=1e-6)
    assert ratio == pytest.approx(extras["tpot_p99_push_ms"]
                                  / extras["tpot_p99_baseline_ms"],
                                  rel=0.05)
    for key in ("tpot_p99_push_ms", "tpot_p99_baseline_ms", "n_req",
                "push_after_steps"):
        assert key in extras
