"""Attention tier equivalence: naive == blockwise == pallas(interpret) == ring.

The contract: every implementation computes identical math, so the
Pallas kernel and the ring-parallel version are validated against the
materialized-logits oracle (SURVEY.md §4 test strategy: numerics vs a
hand-rolled reference).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.ops.attention import (
    blockwise_attention,
    flash_attention,
    naive_attention,
    _flash_pallas,
)
from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh
from distkeras_tpu.parallel.ring import make_ring_attention, \
    sequence_sharding


def qkv(rng, b=2, l=32, h=2, d=8, lk=None):
    shape_q = (b, l, h, d)
    shape_k = (b, lk or l, h, d)
    return (rng.normal(size=shape_q).astype(np.float32),
            rng.normal(size=shape_k).astype(np.float32),
            rng.normal(size=shape_k).astype(np.float32))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block_k", [8, 16, 32])
def test_blockwise_matches_naive(rng, causal, block_k):
    q, k, v = qkv(rng)
    ref = naive_attention(q, k, v, causal=causal)
    out = blockwise_attention(q, k, v, causal=causal, block_k=block_k)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_blockwise_cross_attention(rng):
    q, k, v = qkv(rng, l=16, lk=48)
    ref = naive_attention(q, k, v)
    out = blockwise_attention(q, k, v, block_k=16)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_flash_fallback_any_length(rng):
    """Non-divisible KV lengths must clamp block_k, not raise."""
    q, k, v = qkv(rng, l=24, lk=40)  # gcd(512, 40) -> block_k 40... etc.
    ref = naive_attention(q, k, v)
    out = flash_attention(q, k, v)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)
    out = blockwise_attention(q, k, v, block_k=512)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_grads_match_naive(rng, causal):
    q, k, v = qkv(rng, b=1, l=16, h=1, d=4)

    def loss_ref(q, k, v):
        return naive_attention(q, k, v, causal=causal).sum()

    def loss_blk(q, k, v):
        return blockwise_attention(q, k, v, causal=causal, block_k=8).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_blk = jax.grad(loss_blk, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_blk):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_fallback_and_vjp(rng, causal):
    """On CPU flash_attention routes to blockwise; VJP must still work."""
    q, k, v = qkv(rng, l=16)
    ref = naive_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    g = jax.grad(lambda q: flash_attention(q, k, v, causal).sum())(q)
    g_ref = jax.grad(lambda q: naive_attention(q, k, v, causal=causal).sum())(q)
    np.testing.assert_allclose(g, g_ref, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_kernel_interpret(rng, causal):
    """The TPU kernel's logic, run via the Pallas interpreter on CPU."""
    q, k, v = qkv(rng, b=1, l=16, h=1, d=128)
    ref = naive_attention(q, k, v, causal=causal)
    out, lse = _flash_pallas(q, k, v, causal, 1.0 / np.sqrt(128), block_q=8,
                             block_k=8, interpret=True)
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)
    # lse residual: matches the materialized logits' logsumexp.
    logits = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(128)
    if causal:
        mask = np.tril(np.ones((16, 16), bool))
        logits = np.where(mask[None, None], logits, -1e30)
    ref_lse = np.log(np.exp(logits).sum(-1)).reshape(1, 16)
    np.testing.assert_allclose(lse, ref_lse, atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_backward_kernels_interpret(rng, causal):
    """FA2 dQ/dK/dV kernels vs autodiff of the naive oracle (interpreter)."""
    from distkeras_tpu.ops.attention import _flash_pallas_bwd, _scale_for

    q, k, v = qkv(rng, b=1, l=16, h=2, d=128)
    scale = _scale_for(q, None)
    out, lse = _flash_pallas(q, k, v, causal, scale, block_q=8, block_k=8,
                             interpret=True)
    g = rng.normal(size=out.shape).astype(np.float32)
    dq, dk, dv = _flash_pallas_bwd(q, k, v, np.asarray(out), lse, g, causal,
                                   scale, block_q=8, block_k=8,
                                   interpret=True)
    _, vjp = jax.vjp(
        lambda q, k, v: naive_attention(q, k, v, causal=causal), q, k, v)
    dq_ref, dk_ref, dv_ref = vjp(jnp.asarray(g))
    np.testing.assert_allclose(dq, dq_ref, atol=2e-3, rtol=2e-3)
    np.testing.assert_allclose(dk, dk_ref, atol=2e-3, rtol=2e-3)
    np.testing.assert_allclose(dv, dv_ref, atol=2e-3, rtol=2e-3)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("mesh_shape", [(1, 8), (2, 4)])
def test_ring_attention_matches_full(devices, rng, causal, mesh_shape):
    data, seq = mesh_shape
    mesh = make_mesh(MeshSpec(data=data, seq=seq), devices=devices)
    q, k, v = qkv(rng, b=2, l=32, h=2, d=8)
    ref = naive_attention(q, k, v, causal=causal)
    # Pre-placing with sequence_sharding must match the ring's in_specs
    # (pins the helper's [B, L, ...] contract).
    sh = sequence_sharding(mesh)
    q, k, v = (jax.device_put(a, sh) for a in (q, k, v))
    ring = jax.jit(make_ring_attention(mesh, causal=causal))
    out = ring(q, k, v)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_ring_attention_grads(devices, rng):
    mesh = make_mesh(MeshSpec(data=1, seq=4), devices=devices[:4])
    q, k, v = qkv(rng, b=1, l=16, h=1, d=4)
    ring = make_ring_attention(mesh, causal=True)
    g = jax.jit(jax.grad(lambda q, k, v: ring(q, k, v).sum(),
                         argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: naive_attention(q, k, v, causal=True).sum(),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)


# ------------------------------------------------------- sliding window

@pytest.mark.parametrize("window", [1, 3, 7, 16, 64])
def test_blockwise_window_matches_naive(rng, window):
    q, k, v = qkv(rng, b=2, l=16, h=2, d=8)
    ref = naive_attention(q, k, v, causal=True, window=window)
    out = blockwise_attention(q, k, v, causal=True, block_k=4,
                              window=window)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)
    if window >= 16:  # window >= L degenerates to plain causal
        full = naive_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, full, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("window", [2, 5, 8])
def test_pallas_window_interpret(rng, window):
    """Windowed flash kernel (incl. dead-block skipping) == naive, via
    the TPU-semantics interpreter on CPU."""
    q, k, v = qkv(rng, b=1, l=16, h=1, d=128)
    ref = naive_attention(q, k, v, causal=True, window=window)
    out, _ = _flash_pallas(q, k, v, True, 1.0 / np.sqrt(128), block_q=8,
                           block_k=8, interpret=True, window=window)
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("window", [3, 8])
def test_pallas_window_backward_interpret(rng, window):
    """Windowed FA2 backward kernels == autodiff of the blockwise
    windowed reference."""
    from distkeras_tpu.ops.attention import _flash_pallas_bwd

    q, k, v = qkv(rng, b=1, l=16, h=1, d=128)
    scale = 1.0 / np.sqrt(128)
    out, lse = _flash_pallas(q, k, v, True, scale, block_q=8, block_k=8,
                             interpret=True, window=window)
    g = np.asarray(jax.random.normal(jax.random.key(0), out.shape),
                   np.float32)
    dq, dk, dv = _flash_pallas_bwd(q, k, v, out, lse, g, True, scale,
                                   8, 8, interpret=True, window=window)
    ref, vjp = jax.vjp(
        lambda q, k, v: blockwise_attention(q, k, v, causal=True,
                                            scale=scale, block_k=4,
                                            window=window), q, k, v)
    rdq, rdk, rdv = vjp(g)
    np.testing.assert_allclose(dq, rdq, atol=2e-3, rtol=2e-3)
    np.testing.assert_allclose(dk, rdk, atol=2e-3, rtol=2e-3)
    np.testing.assert_allclose(dv, rdv, atol=2e-3, rtol=2e-3)


def test_window_validation(rng):
    q, k, v = qkv(rng, b=1, l=8, h=1, d=8)
    with pytest.raises(ValueError, match="causal"):
        naive_attention(q, k, v, causal=False, window=4)
    with pytest.raises(ValueError, match="window"):
        blockwise_attention(q, k, v, causal=True, window=0)
    from distkeras_tpu.ops.attention import flash_attention

    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, k, v, False, None, 8, 8, 4)


def test_flash_attention_window_grads_fallback(rng):
    """flash_attention with a window on the non-TPU fallback: value and
    grads match the naive windowed oracle."""
    from distkeras_tpu.ops.attention import flash_attention

    q, k, v = qkv(rng, b=2, l=12, h=2, d=8)

    def f_flash(q, k, v):
        return flash_attention(q, k, v, True, None, 8, 4, 5).sum()

    def f_naive(q, k, v):
        return naive_attention(q, k, v, causal=True, window=5).sum()

    np.testing.assert_allclose(float(f_flash(q, k, v)),
                               float(f_naive(q, k, v)), rtol=1e-5)
    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("bq,bk", [(8, 16), (16, 8), (8, 8)])
@pytest.mark.parametrize("window", [3, 9, 20])
def test_pallas_window_banded_grid_asymmetric_blocks(rng, bq, bk, window):
    """The banded index maps must stay exact for block_q != block_k and
    windows spanning multiple blocks (fwd + both backward kernels)."""
    from distkeras_tpu.ops.attention import _flash_pallas_bwd

    q, k, v = qkv(rng, b=1, l=32, h=1, d=128)
    scale = 1.0 / np.sqrt(128)
    ref = naive_attention(q, k, v, causal=True, window=window)
    out, lse = _flash_pallas(q, k, v, True, scale, block_q=bq, block_k=bk,
                             interpret=True, window=window)
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)

    g = np.asarray(jax.random.normal(jax.random.key(1), out.shape),
                   np.float32)
    dq, dk, dv = _flash_pallas_bwd(q, k, v, out, lse, g, True, scale,
                                   bq, bk, interpret=True, window=window)
    _, vjp = jax.vjp(
        lambda q, k, v: blockwise_attention(q, k, v, causal=True,
                                            scale=scale, block_k=8,
                                            window=window), q, k, v)
    rdq, rdk, rdv = vjp(g)
    np.testing.assert_allclose(dq, rdq, atol=2e-3, rtol=2e-3)
    np.testing.assert_allclose(dk, rdk, atol=2e-3, rtol=2e-3)
    np.testing.assert_allclose(dv, rdv, atol=2e-3, rtol=2e-3)


def test_fit_block_divisor_logic():
    """Oversized defaults fit down to the largest lane-aligned divisor
    instead of pushing the length off the Pallas path (code-review
    regression: (1024, 1024) defaults must not exile seq 1536)."""
    from distkeras_tpu.ops.attention import _fit_block

    assert _fit_block(1024, 4096) == 1024     # divides exactly
    assert _fit_block(1024, 1536) == 768      # largest x128 divisor
    assert _fit_block(1024, 1280) == 640
    assert _fit_block(1024, 512) == 512       # short row: one block
    assert _fit_block(1024, 200) == 200       # short unaligned row
    assert _fit_block(8, 16) == 8             # explicit test blocks keep
    assert _fit_block(1024, 1288) is None     # nothing lane-aligned tiles

    # strict (explicitly requested blocks): honored exactly or None —
    # never a substituted divisor (advisor round-3: a sweep asking for
    # block 512 at length 768 must not silently time a 384 block).
    assert _fit_block(512, 768, strict=True) is None
    assert _fit_block(1024, 1536, strict=True) is None
    assert _fit_block(512, 1024, strict=True) == 512   # divides: kept
    assert _fit_block(1024, 512, strict=True) == 512   # whole-row clamp


def test_pallas_fitted_blocks_interpret(rng):
    """A length the tuned defaults don't divide (1536) still runs the
    kernel — with fitted 768-blocks — and matches the naive reference."""
    q, k, v = qkv(rng, b=1, l=1536, h=1, d=128)
    ref = naive_attention(q, k, v, causal=True)
    out, _ = _flash_pallas(q, k, v, True, 1.0 / np.sqrt(128),
                           block_q=1024, block_k=1024, interpret=True)
    np.testing.assert_allclose(out, ref, atol=2e-4, rtol=2e-4)


def test_explicit_small_block_k_honored_and_unfittable_raises(rng):
    """Explicit small blocks reach the kernel (the sweep must be able to
    time any grid point); unfittable direct launches raise instead of
    silently leaving tail rows unwritten (code-review regressions).
    ``_pallas_blocks`` is the backend-independent decision, so this
    runs fully on the CPU suite."""
    from distkeras_tpu.ops.attention import _pallas_blocks, _require_fit

    # Explicit block_k=128 tiles lk=4096 — accepted when the caller
    # asked for it (gate off), rejected on the defaulted path (gate on)
    # unless block_q fitted to >=1024 (sweep: (1024, 128) alone beats
    # the fallback).
    assert _pallas_blocks(4096, 4096, 128, 512, 128) == (512, 128)
    assert _pallas_blocks(4096, 4096, 128, 512, 128,
                          gate_small_bk=True) is None
    assert _pallas_blocks(4096, 4096, 128, 1024, 128,
                          gate_small_bk=True) == (1024, 128)
    # Defaulted seq 2176 = 17x128: both blocks fit only to 128 -> the
    # (128, 128)-class kernel is pathological, fallback wins.
    assert _pallas_blocks(2176, 2176, 128, 1024, 1024,
                          gate_small_bk=True) is None
    # Unaligned head_dim or sub-8 rows never tile.
    assert _pallas_blocks(4096, 4096, 64, 1024, 1024) is None
    assert _require_fit(8, 16) == 8
    with pytest.raises(ValueError, match="tiles sequence length"):
        _require_fit(1024, 1288)
    # Explicit (strict) blocks that don't divide take the fallback
    # instead of a refitted grid; defaults at the same shape refit.
    assert _pallas_blocks(768, 768, 128, 512, 512,
                          strict_q=True, strict_k=True) is None
    assert _pallas_blocks(768, 768, 128, 512, 512) == (384, 384)
    assert _pallas_blocks(1024, 768, 128, 512, 512,
                          strict_q=True, strict_k=False) == (512, 384)
