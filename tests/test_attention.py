"""Attention tier equivalence: naive == blockwise == pallas(interpret) == ring.

The contract: every implementation computes identical math, so the
Pallas kernel and the ring-parallel version are validated against the
materialized-logits oracle (SURVEY.md §4 test strategy: numerics vs a
hand-rolled reference).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.ops.attention import (
    blockwise_attention,
    flash_attention,
    naive_attention,
    _flash_pallas,
)
from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh
from distkeras_tpu.parallel.ring import make_ring_attention, \
    sequence_sharding


def qkv(rng, b=2, l=32, h=2, d=8, lk=None):
    shape_q = (b, l, h, d)
    shape_k = (b, lk or l, h, d)
    return (rng.normal(size=shape_q).astype(np.float32),
            rng.normal(size=shape_k).astype(np.float32),
            rng.normal(size=shape_k).astype(np.float32))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block_k", [8, 16, 32])
def test_blockwise_matches_naive(rng, causal, block_k):
    q, k, v = qkv(rng)
    ref = naive_attention(q, k, v, causal=causal)
    out = blockwise_attention(q, k, v, causal=causal, block_k=block_k)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_blockwise_cross_attention(rng):
    q, k, v = qkv(rng, l=16, lk=48)
    ref = naive_attention(q, k, v)
    out = blockwise_attention(q, k, v, block_k=16)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_flash_fallback_any_length(rng):
    """Non-divisible KV lengths must clamp block_k, not raise."""
    q, k, v = qkv(rng, l=24, lk=40)  # gcd(512, 40) -> block_k 40... etc.
    ref = naive_attention(q, k, v)
    out = flash_attention(q, k, v)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)
    out = blockwise_attention(q, k, v, block_k=512)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_grads_match_naive(rng, causal):
    q, k, v = qkv(rng, b=1, l=16, h=1, d=4)

    def loss_ref(q, k, v):
        return naive_attention(q, k, v, causal=causal).sum()

    def loss_blk(q, k, v):
        return blockwise_attention(q, k, v, causal=causal, block_k=8).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_blk = jax.grad(loss_blk, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_blk):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_fallback_and_vjp(rng, causal):
    """On CPU flash_attention routes to blockwise; VJP must still work."""
    q, k, v = qkv(rng, l=16)
    ref = naive_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    g = jax.grad(lambda q: flash_attention(q, k, v, causal).sum())(q)
    g_ref = jax.grad(lambda q: naive_attention(q, k, v, causal=causal).sum())(q)
    np.testing.assert_allclose(g, g_ref, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_kernel_interpret(rng, causal):
    """The TPU kernel's logic, run via the Pallas interpreter on CPU."""
    q, k, v = qkv(rng, b=1, l=16, h=1, d=128)
    ref = naive_attention(q, k, v, causal=causal)
    out, lse = _flash_pallas(q, k, v, causal, 1.0 / np.sqrt(128), block_q=8,
                             block_k=8, interpret=True)
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)
    # lse residual: matches the materialized logits' logsumexp.
    logits = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(128)
    if causal:
        mask = np.tril(np.ones((16, 16), bool))
        logits = np.where(mask[None, None], logits, -1e30)
    ref_lse = np.log(np.exp(logits).sum(-1)).reshape(1, 16)
    np.testing.assert_allclose(lse, ref_lse, atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_backward_kernels_interpret(rng, causal):
    """FA2 dQ/dK/dV kernels vs autodiff of the naive oracle (interpreter)."""
    from distkeras_tpu.ops.attention import _flash_pallas_bwd, _scale_for

    q, k, v = qkv(rng, b=1, l=16, h=2, d=128)
    scale = _scale_for(q, None)
    out, lse = _flash_pallas(q, k, v, causal, scale, block_q=8, block_k=8,
                             interpret=True)
    g = rng.normal(size=out.shape).astype(np.float32)
    dq, dk, dv = _flash_pallas_bwd(q, k, v, np.asarray(out), lse, g, causal,
                                   scale, block_q=8, block_k=8,
                                   interpret=True)
    _, vjp = jax.vjp(
        lambda q, k, v: naive_attention(q, k, v, causal=causal), q, k, v)
    dq_ref, dk_ref, dv_ref = vjp(jnp.asarray(g))
    np.testing.assert_allclose(dq, dq_ref, atol=2e-3, rtol=2e-3)
    np.testing.assert_allclose(dk, dk_ref, atol=2e-3, rtol=2e-3)
    np.testing.assert_allclose(dv, dv_ref, atol=2e-3, rtol=2e-3)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("mesh_shape", [(1, 8), (2, 4)])
def test_ring_attention_matches_full(devices, rng, causal, mesh_shape):
    data, seq = mesh_shape
    mesh = make_mesh(MeshSpec(data=data, seq=seq), devices=devices)
    q, k, v = qkv(rng, b=2, l=32, h=2, d=8)
    ref = naive_attention(q, k, v, causal=causal)
    # Pre-placing with sequence_sharding must match the ring's in_specs
    # (pins the helper's [B, L, ...] contract).
    sh = sequence_sharding(mesh)
    q, k, v = (jax.device_put(a, sh) for a in (q, k, v))
    ring = jax.jit(make_ring_attention(mesh, causal=causal))
    out = ring(q, k, v)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_ring_attention_grads(devices, rng):
    mesh = make_mesh(MeshSpec(data=1, seq=4), devices=devices[:4])
    q, k, v = qkv(rng, b=1, l=16, h=1, d=4)
    ring = make_ring_attention(mesh, causal=True)
    g = jax.jit(jax.grad(lambda q, k, v: ring(q, k, v).sum(),
                         argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: naive_attention(q, k, v, causal=True).sum(),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)
