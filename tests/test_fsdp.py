"""FSDP (ZeRO-3 via GSPMD sharding): identical math to plain DP, with
parameters and optimizer state scattered over the data axis.

The reference cannot shard parameter memory at all — every worker and
the parameter server hold full weight copies (reference:
distkeras/parameter_servers.py center variable); FSDP is pure rebuild
surface, tested the same way the trainer family is: exactness against
the replicated path on the 8-CPU mesh.
"""

import jax
import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.models import transformer as tfm
from distkeras_tpu.parallel.mesh import MeshSpec, make_mesh
from distkeras_tpu.parallel.sharding import _augment_fsdp
from jax.sharding import PartitionSpec as P


CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=32)


def tokens(rng, n=64, s=16):
    return rng.integers(0, 64, (n, s + 1)).astype(np.int32)


# ------------------------------------------------------------ spec rule


def test_augment_fsdp_picks_largest_free_dim():
    assert _augment_fsdp(P(), (64, 128), 8, "data") == P(None, "data")
    assert _augment_fsdp(P(), (128, 64), 8, "data") == P("data")
    # TP already owns the big dim -> FSDP takes the other one.
    assert _augment_fsdp(P(None, "model"), (64, 128), 8, "data") == \
        P("data", "model")
    # Nothing divisible -> stays as-is (small params replicate).
    assert _augment_fsdp(P(), (5, 3), 8, "data") == P()
    # Axis already present (user rule) -> untouched.
    assert _augment_fsdp(P("data"), (64, 64), 8, "data") == P("data")
    # Trivial axis -> no-op.
    assert _augment_fsdp(P(), (64, 64), 1, "data") == P()


# ------------------------------------------------------------ LMTrainer


def _lm_losses(mesh, rng, **kw):
    t = dk.LMTrainer(CFG, learning_rate=1e-2, batch_size=16, num_epoch=4,
                     mesh=mesh, **kw)
    t.train(tokens(rng))
    return t


def test_lm_fsdp_matches_dp(devices):
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    base = _lm_losses(mesh, np.random.default_rng(0))
    fsdp = _lm_losses(mesh, np.random.default_rng(0), fsdp=True)
    np.testing.assert_allclose(fsdp.history, base.history, rtol=2e-4)


def test_lm_fsdp_shards_param_memory(devices, rng):
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    t = dk.LMTrainer(CFG, learning_rate=1e-2, batch_size=16, mesh=mesh,
                     fsdp=True)
    params = t.train(tokens(rng))
    emb = params["tok_emb"]  # [64, 32]: vocab dim shards 8-way
    assert "data" in tuple(emb.sharding.spec)
    shard = emb.addressable_shards[0].data
    assert shard.size == emb.size // 8


def test_lm_fsdp_composes_with_tp(devices):
    mesh = make_mesh(MeshSpec(data=4, model=2), devices=devices)
    base = _lm_losses(mesh, np.random.default_rng(0))
    fsdp = _lm_losses(mesh, np.random.default_rng(0), fsdp=True)
    np.testing.assert_allclose(fsdp.history, base.history, rtol=2e-4)


def test_lm_fsdp_rejects_pipeline(devices):
    mesh = make_mesh(MeshSpec(data=2, pipeline=2, seq=2), devices=devices)
    with pytest.raises(ValueError, match="fsdp.*pipeline"):
        dk.LMTrainer(CFG, mesh=mesh, fsdp=True)


def test_lm_fsdp_checkpoint_resume(devices, tmp_path):
    """FSDP state round-trips through orbax with its scattered layout."""
    d = str(tmp_path / "ck")
    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    rng = np.random.default_rng(0)
    data = tokens(rng)
    full = dk.LMTrainer(CFG, learning_rate=1e-2, batch_size=16, num_epoch=2,
                        mesh=mesh, fsdp=True)
    full.train(data)

    first = dk.LMTrainer(CFG, learning_rate=1e-2, batch_size=16, num_epoch=1,
                         mesh=mesh, fsdp=True, checkpoint_dir=d,
                         checkpoint_every=1)
    first.train(data)
    resumed = dk.LMTrainer(CFG, learning_rate=1e-2, batch_size=16,
                           num_epoch=2, mesh=mesh, fsdp=True,
                           checkpoint_dir=d, checkpoint_every=1, resume=True)
    p2 = resumed.train(data)
    np.testing.assert_allclose(
        resumed.history, full.history[len(first.history):], rtol=1e-5)
    jax.block_until_ready(jax.tree.leaves(p2)[0])


# ------------------------------------------------------------ Keras side


def test_adag_fsdp_matches_dp(devices, blobs):
    from helpers import make_mlp

    feats, labels = blobs
    ds = dk.Dataset({"features": feats, "label": labels})

    def run(**kw):
        t = dk.ADAG(make_mlp(), loss="sparse_categorical_crossentropy",
                    worker_optimizer="sgd", learning_rate=0.05,
                    batch_size=8, num_epoch=2, communication_window=4, **kw)
        t.train(ds)
        return t.history

    np.testing.assert_allclose(run(fsdp=True), run(), rtol=2e-4)


def test_fsdp_plan_and_plan_conflict(blobs):
    from helpers import make_mlp

    with pytest.raises(ValueError, match="plan.*fsdp|fsdp.*plan"):
        dk.ADAG(make_mlp(), plan=dk.dp_plan(), fsdp=True)


def test_replica_trainers_reject_fsdp():
    from helpers import make_mlp

    with pytest.raises(ValueError, match="FSDP"):
        dk.AEASGD(make_mlp(), fsdp=True)
    # The explicit-plan spelling of the same forbidden configuration.
    with pytest.raises(ValueError, match="FSDP"):
        dk.AEASGD(make_mlp(), plan=dk.fsdp_plan())
